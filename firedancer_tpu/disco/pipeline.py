"""The minimum end-to-end verify slice (SURVEY.md §7.4): txn bytes in,
per-txn verdicts out.

Mirrors the verify tile's processing contract
(src/app/fdctl/run/tiles/fd_verify.c after_frag -> fd_txn_verify,
fd_verify.h:43-88): parse -> tcache pre-dedup on the first 64 sig bits ->
batched ed25519 verify -> per-txn accept iff every signature passes.

The TPU twist vs the reference's synchronous in-tile loop: signatures from
many txns are coalesced into fixed-shape device batches (wiredancer's
async-offload insertion point, SURVEY.md §3.2), so per-batch latency is
device round-trip + coalescing window, amortized over thousands of lanes.

Message-length buckets: XLA graphs are fixed-shape, so the pipeline keeps
several compiled (batch, msg_maxlen) buckets and routes each txn to the
smallest bucket that fits its message — small transfers fill the wide
fast bucket while full-MTU txns (wire MTU 1232, ref
src/ballet/txn/fd_txn.h:92-103) go to a narrower full-width bucket instead
of being dropped.  This is the same compile-time-batch-specialization game
the reference plays with SIMD widths (fd_sha512.h:266-361).
"""

from collections import deque
import ctypes
from dataclasses import dataclass, field
import os
import time

import numpy as np

from .. import native as native_mod
from ..ballet import txn as txn_lib
from ..tango.tcache import NativeTCache, TCache
from ..utils import log
from ..utils.hist import Histf
from . import trace as trace_mod


def _is_ready(dev) -> bool:
    """Non-blocking completion poll on a dispatched device array (jax
    arrays grew .is_ready() long ago; anything without it is host data
    and trivially ready)."""
    fn = getattr(dev, "is_ready", None)
    return True if fn is None else bool(fn())

# default bucket ladder: (lanes, msg_maxlen); covers through the wire MTU
DEFAULT_BUCKETS = ((2048, 256), (256, 768), (64, 1232))

# priority admission (round 9): ingest links thread a per-frag
# latency-class bit through the tango frag meta `sig` field — the same
# meta-field threading round 8 used for packed row counts in `meta.sz`.
# Producers that participate in priority tagging keep their app sigs
# below bit 63 (the source tile draws tags in [1, 2^63)); untagged wire
# ingest (quic) masks the bit off so random signature bytes can never
# alias a txn into the low-latency lane.
LAT_PRIO_BIT = 1 << 63

# default low-latency lane shape ladder (lanes per pre-warmed shape)
DEFAULT_LAT_SHAPES = (16, 64, 256)


class _GuardedVerdict:
    """Verdict future with a harvest-side deadline (GuardedVerifier's
    async half).  Implements exactly the surface the pipeline touches on
    a dispatched verdict: is_ready() polls, np.asarray materializes,
    copy_to_host_async passes through.  A future that is still not ready
    past the deadline — or whose materialization raises — counts as a
    device failure and the verdict is recomputed on the host from the
    still-pinned inputs (the pipeline pins packed blobs/row views until
    _finish, so the bytes are guaranteed live here)."""

    __slots__ = ("_g", "_dev", "_host_call", "_t0")

    def __init__(self, g, dev, host_call, t0):
        self._g = g
        self._dev = dev
        self._host_call = host_call
        self._t0 = t0

    def is_ready(self) -> bool:
        if _is_ready(self._dev):
            return True
        if self._g.deadline_s <= 0:     # deadline disabled: poll only
            return False
        # a hung dispatch becomes "ready" at the deadline so harvest()
        # reaches __array__ and the host fallback fires
        return self._g._clock() - self._t0 > self._g.deadline_s

    def copy_to_host_async(self):
        fn = getattr(self._dev, "copy_to_host_async", None)
        if fn is not None:
            fn()

    def __array__(self, dtype=None, copy=None):
        g = self._g
        if (_is_ready(self._dev) or g.deadline_s <= 0
                or g._clock() - self._t0 <= g.deadline_s):
            try:
                ok = np.asarray(self._dev)
                g._consec = 0
                return ok if dtype is None else ok.astype(dtype)
            except Exception as e:  # noqa: BLE001 — any materialization
                log.warning("device verdict fetch failed: %s", str(e))
        else:
            log.warning("device verdict hung past %.1fs deadline",
                        g.deadline_s)
        ok = g._device_failed(self._host_call)
        return ok if dtype is None else ok.astype(dtype)


class GuardedVerifier:
    """Self-healing wrapper around a device verifier (the graceful-
    degradation half of the supervision tentpole).

    Wraps the two dispatch surfaces the pipeline uses — __call__ over
    (msgs, lens, sigs, pubs) and, when the wrapped fn has one,
    dispatch_blob over packed rows — preserving the duck-typing
    VerifyPipeline autodetects on (dispatch_blob presence, .mode,
    .n_shards pass through).  Behavior:

      * every device dispatch gets `retries` bounded retries; a dispatch
        that still raises falls back to the host ed25519 backend for THAT
        batch (verdicts keep flowing, `device_fail_cnt` counts)
      * a dispatched verdict that never materializes within `deadline_s`
        is also a failure (caught at harvest via _GuardedVerdict) and is
        recomputed on the host from the still-pinned inputs; set
        deadline_s <= 0 to disable the hang watchdog (benchmarks on a
        contended 1-core CPU host legitimately outlast any sane deadline)
      * `fail_threshold` CONSECUTIVE failures flip `degraded` on: all
        dispatches go straight to the host backend, and every `reprobe_s`
        seconds one live batch probes the device — a probe that
        materializes in time clears degraded and restores the device path

    Host verdicts are bit-identical to device verdicts: both paths
    implement the same acceptance rules, conformance-tested against
    ops.ed25519.verify_one_host."""

    def __init__(self, fn, fail_threshold: int = 3, retries: int = 1,
                 deadline_s: float = 30.0, reprobe_s: float = 5.0,
                 fault=None, clock=time.monotonic,
                 host_blob=None, host_arrays=None):
        self.fn = fn
        self.fail_threshold = max(1, int(fail_threshold))
        self.retries = max(0, int(retries))
        self.deadline_s = float(deadline_s)
        self.reprobe_s = float(reprobe_s)
        self.fault = fault          # FaultInjector or None
        self._clock = clock
        self._host_blob = host_blob
        self._host_arrays = host_arrays
        self.degraded = False
        self.device_fail_cnt = 0
        self.fallback_lanes = 0
        self.reprobe_cnt = 0
        self._consec = 0
        self._next_probe = 0.0
        self._fb_t0 = None          # fallback-rate window origin
        self._fb_lanes0 = 0
        # expose dispatch_blob ONLY if the wrapped fn has it — pipeline
        # packed autodetect is hasattr-based, so a phantom method here
        # would flip a 4-array verifier into packed mode
        if hasattr(fn, "dispatch_blob"):
            self.dispatch_blob = self._guarded_dispatch_blob

    def __getattr__(self, name):
        # .mode / .n_shards / anything else the pipeline introspects
        return getattr(self.__dict__["fn"], name)

    # -- dispatch surfaces -------------------------------------------------
    def __call__(self, msgs, lens, sigs, pubs):
        return self._dispatch(
            lambda: self.fn(msgs, lens, sigs, pubs),
            lambda: self._host_4(msgs, lens, sigs, pubs))

    def _guarded_dispatch_blob(self, blob, maxlen=None):
        return self._dispatch(
            lambda: self.fn.dispatch_blob(blob, maxlen=maxlen),
            lambda: self._host_b(blob, maxlen))

    # -- host backend ------------------------------------------------------
    # The default backends follow the wrapped verifier's mode: an
    # antipa-mode device graph degrades to the antipa host verify
    # (torsion laxity included), so fallback verdicts stay bit-identical
    # to what the device would have produced.  Injected host_blob /
    # host_arrays (tests, custom backends) are used as given.
    def _fn_mode(self) -> str:
        return getattr(self.__dict__["fn"], "mode", "strict")

    def _host_4(self, msgs, lens, sigs, pubs):
        if self._host_arrays is None:
            from functools import partial

            from ..models.verifier import host_verify_arrays
            self._host_arrays = partial(host_verify_arrays,
                                        mode=self._fn_mode())
        return self._host_arrays(msgs, lens, sigs, pubs)

    def _host_b(self, blob, maxlen):
        if self._host_blob is None:
            from functools import partial

            from ..models.verifier import host_verify_blob
            self._host_blob = partial(host_verify_blob,
                                      mode=self._fn_mode())
        return self._host_blob(blob, maxlen=maxlen)

    def _host(self, host_call):
        ok = np.asarray(host_call()).astype(bool)
        self.fallback_lanes += len(ok)
        return ok

    def fallback_vps(self) -> int:
        """CPU-fallback verify rate (lanes/s) over the current degraded
        window; 0 when healthy."""
        if self._fb_t0 is None:
            return 0
        dt = self._clock() - self._fb_t0
        if dt <= 0:
            return 0
        return int((self.fallback_lanes - self._fb_lanes0) / dt)

    # -- state machine -----------------------------------------------------
    def _enter_degraded(self):
        self.degraded = True
        self._next_probe = self._clock() + self.reprobe_s
        self._fb_t0 = self._clock()
        self._fb_lanes0 = self.fallback_lanes
        log.warning("verify device path degraded after %d consecutive "
                    "failures: serving off the CPU ed25519 fallback "
                    "(reprobe every %.1fs)", self._consec, self.reprobe_s)

    def _recover(self):
        self.degraded = False
        self._consec = 0
        self._fb_t0 = None
        log.warning("verify device path recovered; leaving degraded mode")

    def _device_failed(self, host_call):
        """Shared failure accounting (dispatch raise or harvest timeout)
        + host fallback for the affected batch."""
        self.device_fail_cnt += 1
        self._consec += 1
        if self.degraded:
            self._next_probe = self._clock() + self.reprobe_s
        elif self._consec >= self.fail_threshold:
            self._enter_degraded()
        return self._host(host_call)

    def _try_materialize(self, dev):
        """Degraded-mode probe: block (bounded by deadline_s) on a live
        dispatch; returns the verdict array or None on hang/raise."""
        deadline = self._clock() + self.deadline_s
        while not _is_ready(dev):
            if self._clock() > deadline:
                return None
            time.sleep(0.001)
        try:
            return np.asarray(dev)
        except Exception as e:  # noqa: BLE001
            log.warning("device probe materialization failed: %s", e)
            return None

    def _dispatch(self, dev_call, host_call):
        now = self._clock()
        if self.degraded and now < self._next_probe:
            return self._host(host_call)
        probing = self.degraded
        if probing:
            self.reprobe_cnt += 1
        last = None
        for _ in range(self.retries + 1):
            try:
                if self.fault is not None:
                    self.fault.dispatch()
                dev = dev_call()
            except Exception as e:  # noqa: BLE001 — a dispatch-time raise
                last = str(e)       # of ANY kind means the device path is
                continue            # not producing verdicts right now
                # (stringified: keeping the exception would pin the whole
                # frag-loop stack through its traceback if a log handler
                # retains the record)
            if probing:
                # degraded-mode probe: this live batch decides recovery,
                # so (unlike the healthy path) we block on it
                ok = self._try_materialize(dev)
                if ok is None:
                    break
                self._recover()
                return ok.astype(bool)
            # NOTE: _consec is NOT reset here — only a verdict that
            # actually materializes clears it (_GuardedVerdict.__array__);
            # a device that accepts dispatches but never completes them
            # must still cross the threshold
            return _GuardedVerdict(self, dev, host_call, now)
        if last is not None:
            log.warning("device dispatch failed (consec=%d): %s",
                        self._consec + 1, last)
        return self._device_failed(host_call)


@dataclass
class VerifyMetrics:
    """Counter block, the shape of the reference's per-tile metrics region
    (src/disco/metrics/metrics.xml verify tile)."""

    txns_in: int = 0
    parse_fail: int = 0
    dedup_drop: int = 0
    too_long_drop: int = 0
    sig_overflow_drop: int = 0
    verify_fail: int = 0
    verify_pass: int = 0
    batches: int = 0
    # zero-copy packed-wire path: frags whose seqlock re-check failed
    # AFTER the device dispatch (producer lapped the dcache mid-upload);
    # the whole frag is dropped rather than risking torn verdicts
    torn_drop: int = 0
    # rows riding those torn frags.  Counted SEPARATELY from txns_in so
    # pass/fail rates derived from txns_in (fdtpuctl top) exclude rows
    # that never reached harvest — a torn frag bumps neither txns_in nor
    # dedup_drop
    torn_txns: int = 0
    # TPU hooks (fdtrace): first-dispatch-per-shape events (the XLA
    # trace+compile cost a cold (batch, maxlen) bucket pays) and lane
    # occupancy (filled vs dispatched — padding waste per age-flush)
    compile_cnt: int = 0
    compile_ns: int = 0
    lanes_filled: int = 0
    lanes_dispatched: int = 0
    last_fill_pct: int = 0
    # dual-lane dispatch (round 9): low-latency lane accounting.
    # lat_spill counts lat-class txns shed to the throughput lane
    # (inflight budget / queue age / capacity) — shed txns are still
    # verified, never dropped, so spill is a latency signal not a loss.
    lat_txns: int = 0
    lat_spill: int = 0
    lat_batches: int = 0
    lat_deadline_closes: int = 0
    batch_ns: Histf = field(default_factory=lambda: Histf(1_000, 60_000_000_000))
    # batch-latency decomposition (round 4): coalesce = first submit ->
    # dispatch (the batching window's cost), batch_ns = dispatch ->
    # verdict harvested (device + queue + tunnel RTT)
    coalesce_ns: Histf = field(
        default_factory=lambda: Histf(1_000, 60_000_000_000))
    # end-to-end arrival->verdict per lane (round 9): e2e_ns samples the
    # throughput lane (oldest txn of each bucket batch), lat_e2e_ns the
    # low-latency lane — the per-lane p99s the dual-lane bench reports,
    # measured with the SAME ruler on both sides
    e2e_ns: Histf = field(
        default_factory=lambda: Histf(1_000, 60_000_000_000))
    lat_e2e_ns: Histf = field(
        default_factory=lambda: Histf(1_000, 60_000_000_000))

    def snapshot(self) -> dict:
        d = {k: getattr(self, k) for k in (
            "txns_in", "parse_fail", "dedup_drop", "too_long_drop",
            "sig_overflow_drop", "verify_fail", "verify_pass", "batches",
            "torn_drop", "torn_txns", "compile_cnt", "compile_ns",
            "lanes_filled",
            "lanes_dispatched", "last_fill_pct", "lat_txns", "lat_spill",
            "lat_batches", "lat_deadline_closes")}
        d["batch_ns_p50"] = self.batch_ns.percentile(0.50)
        d["batch_ns_p99"] = self.batch_ns.percentile(0.99)
        d["coalesce_ns_p50"] = self.coalesce_ns.percentile(0.50)
        d["coalesce_ns_p99"] = self.coalesce_ns.percentile(0.99)
        d["e2e_ns_p50"] = self.e2e_ns.percentile(0.50)
        d["e2e_ns_p99"] = self.e2e_ns.percentile(0.99)
        d["lat_e2e_ns_p50"] = self.lat_e2e_ns.percentile(0.50)
        d["lat_e2e_ns_p99"] = self.lat_e2e_ns.percentile(0.99)
        return d


@dataclass
class _Pending:
    payload: bytes
    parsed: txn_lib.Txn
    lanes: list[int]  # indices into the bucket's open batch
    tag: int  # dedup tag (low 64 bits of first sig), computed once in submit()


@dataclass
class _BurstPending:
    """A whole accepted burst as one pending record (submit_burst): per-txn
    bookkeeping stays in numpy so harvest is vectorized too.  Lanes of the
    burst's txns are CONTIGUOUS in the bucket (the native parser allocates
    sequentially).  Payload bytes live as ONE copied region (the rx
    scratch buffer is reused next poll) with per-txn (start, len) into it;
    per-txn bytes objects are materialized only for PASSING txns at
    harvest."""

    buf: bytes              # copy of this round's payload region
    start: object           # (k,) int64 payload start per accepted txn
    plen: object            # (k,) int32 payload length per accepted txn
    lane0: object           # (k,) int32 first lane per txn
    nsig: object            # (k,) int32 sig lanes per txn
    tag: object             # (k,) uint64 dedup tags


@dataclass
class _RowsPending:
    """A packed-wire frag verified ZERO-copy (submit_packed_rows): the rows
    are a live view over the shm dcache, pinned by a held consumer credit
    until the verdict materializes — the producer cannot overwrite the
    region, so passing payloads can be reconstructed from the view at
    harvest.  release_cb returns the credit once the frag retires."""

    rows: object            # (batch, ml+100) uint8 shm view
    tag: object             # (n,) uint64 dedup tags (row[ml:ml+8])
    dup: object             # (n,) bool pre-dedup verdicts (query-only)
    n: int                  # true row count; rows beyond are zero padding
    ml: int
    release_cb: object = None


@dataclass
class PackedVerdicts:
    """One harvested frag's passing txns as a packed wire arena (round 11
    egress form): wire j = arena[offs[j]:offs[j+1]] = 0x01 | sig[64] |
    msg — the same bytes the legacy per-txn list would carry, back to
    back.  The arena is OWNED (copied out of the harvest scratch), so a
    PackedVerdicts outlives the pipeline's next finish; the verify tile
    burst-stamps it downstream as ONE frag instead of k."""

    arena: object           # (nbytes,) uint8, owned
    offs: object            # (k+1,) int64 wire boundaries, offs[0] = 0
    tags: object            # (k,) uint64 dedup tags of the survivors
    k: int                  # survivor count

    def wires(self) -> list[bytes]:
        """Materialize per-txn wire bytes (legacy egress / parity).  One
        arena tobytes + bytes slicing — ~2x cheaper per txn than slicing
        the ndarray per wire (no per-txn view objects)."""
        buf = self.arena.tobytes() if isinstance(
            self.arena, np.ndarray) else bytes(self.arena)
        ol = np.asarray(self.offs).tolist()
        return [buf[a:b] for a, b in zip(ol, ol[1:])]


@dataclass
class _Inflight:
    """A dispatched-but-unharvested device batch (wiredancer's in-flight
    request set, src/wiredancer/c/wd_f1.h:85-113: results come back
    asynchronously and are matched to requests on completion)."""

    ok_dev: object            # jax array future of per-lane pass bits
    pending: list             # the _Pending txns of that batch
    t0: int                   # dispatch timestamp (ns)
    buf: object = None        # packed blob pinned under this dispatch
    owner: object = None      # the _Bucket whose pool gets buf back
    lane: int = 0             # 0 = throughput lane, 1 = low-latency lane
    t_first: int = 0          # arrival ns of the batch's oldest txn


class _Bucket:
    """One compiled (batch, msg_maxlen) shape with its open batch.

    packed=True lays the bucket out as ONE row-interleaved uint8 array
    (msgs | sigs | pubs | lens-le32 per row): the native burst parser
    fills it in place and the device dispatch uploads it as a single
    blob (wiredancer's DMA push shape; ~3-4 fewer transfer RPCs per
    batch through a tunneled device).  msgs/sigs/pubs remain live numpy
    VIEWS into the array, so the scalar submit() path and test fakes
    work unchanged.

    Packed buckets rotate over a small pool of `n_buffers` blobs
    (upload/compute double buffering, VERDICT r5 Next #4): a flushed
    blob stays pinned under its _Inflight dispatch and returns to the
    pool only after its verdict materializes in _finish() — it is never
    repacked while the device may still read it — while reset() swaps
    in a free (zeroed) blob so the next batch packs during the previous
    batch's upload + verify."""

    def __init__(self, batch: int, maxlen: int, packed: bool = False,
                 n_buffers: int = 2, bidx: int = 0, lane: int = 0):
        self.batch = batch
        self.maxlen = maxlen
        self.packed = packed
        self.n_buffers = max(1, n_buffers)
        # position in the pipeline's ladder, stamped at creation — the
        # dispatch trace span's iidx (a list.index() per flush before)
        self.bidx = bidx
        self.lane = lane            # 0 = throughput, 1 = low-latency
        self._pool: deque = deque()
        self.reset()

    # packed row tail width; must equal ops.ed25519.PACKED_EXTRA (the
    # layout's single definition — cross-checked in tests) without
    # importing jax at pipeline-module import time
    PACKED_EXTRA = 100

    def release(self, arr) -> None:
        """Return a no-longer-inflight packed blob to the rotation."""
        if self.packed and len(self._pool) < self.n_buffers:
            self._pool.append(arr)

    def reset(self):
        if self.packed:
            ml = self.maxlen
            if self._pool:
                self.arr = self._pool.popleft()
                # zero the reused blob: the verify contract wants
                # zero-padded message columns, and partial (age-flush)
                # fills would otherwise see the previous batch's bytes
                self.arr.fill(0)
            else:
                self.arr = np.zeros((self.batch, ml + self.PACKED_EXTRA),
                                    dtype=np.uint8)
            self.msgs = self.arr[:, :ml]
            self.sigs = self.arr[:, ml:ml + 64]
            self.pubs = self.arr[:, ml + 64:ml + 96]
        else:
            self.arr = None
            self.msgs = np.zeros((self.batch, self.maxlen), dtype=np.uint8)
            self.sigs = np.zeros((self.batch, 64), dtype=np.uint8)
            self.pubs = np.zeros((self.batch, 32), dtype=np.uint8)
        self.lens = np.zeros((self.batch,), dtype=np.int32)
        self.used = 0
        self.t_first = 0  # ns stamp of the first txn in the open batch
        self.pending: list[_Pending] = []

    def set_len(self, lane: int, n: int):
        self.lens[lane] = n
        if self.packed:
            self.arr[lane, self.maxlen + 96:self.maxlen + 100] = (
                np.int32(n).tobytes())


class VerifyPipeline:
    """Fixed-shape batching verify pipeline.

    Single-bucket form (tests, latency tiers):
        VerifyPipeline(fn, batch=B, msg_maxlen=L)
    Multi-bucket form (production: full-MTU coverage):
        VerifyPipeline(fn, buckets=[(2048, 256), (256, 768), (64, 1232)])

    verify_fn must be shape-polymorphic (a jitted ed.verify_batch / a
    SigVerifier recompiles per bucket shape on first use).
    tcache_depth: dedup window in distinct signatures (fd_dedup tile default
    is ~2M; tests use small windows).
    """

    def __init__(self, verify_fn, batch: int | None = None,
                 msg_maxlen: int | None = None, tcache_depth: int = 1 << 16,
                 buckets=None, max_inflight: int = 0,
                 packed_rows: bool | None = None, tracer=None,
                 n_buffers: int = 2, dp_shards: int = 1,
                 heartbeat_cb=None, lat_shapes=None, deadline_us: int = 2000,
                 lat_max_inflight: int = 2, lat_maxlen: int | None = None,
                 lat_spill_age_factor: float = 4.0,
                 native_hostpath: bool | None = None,
                 egress_packed: bool = False):
        if buckets is None:
            if batch is None or msg_maxlen is None:
                raise ValueError("need either (batch, msg_maxlen) or buckets")
            buckets = ((batch, msg_maxlen),)
        self.verify_fn = verify_fn
        # dp_shards: the data-parallel mesh width the verifier dispatches
        # over (round 7).  Bucket shapes must split the mesh evenly so the
        # hot path never pads (a padded dispatch compiles a second masked
        # graph per bucket); the verifier's own shard count must agree or
        # its dispatch would silently run a different SPMD program than
        # the topology declares.
        self.dp_shards = max(1, int(dp_shards))
        if self.dp_shards > 1:
            vshards = getattr(verify_fn, "n_shards", self.dp_shards)
            if vshards != self.dp_shards:
                raise ValueError(
                    f"dp_shards={self.dp_shards} but verify_fn shards "
                    f"{vshards} ways")
            for b, _m in buckets:
                if b % self.dp_shards:
                    raise ValueError(
                        f"bucket batch {b} not divisible by "
                        f"dp_shards {self.dp_shards}")
        # packed row-interleaved buckets + single-blob dispatch when the
        # verifier supports it (SigVerifier.dispatch_blob, per-sig modes
        # — the packed graph is the configured strict/antipa graph; rlc
        # has no packed form); explicit packed_rows overrides the
        # autodetect
        if packed_rows is None:
            packed_rows = (hasattr(verify_fn, "dispatch_blob")
                           and getattr(verify_fn, "mode", "strict")
                           in ("strict", "antipa"))
        self.packed_rows = packed_rows
        # n_buffers: packed-blob rotation depth per bucket (double
        # buffering by default; raise alongside max_inflight to keep a
        # free blob available at higher dispatch-ahead depths)
        self.n_buffers = n_buffers
        self.buckets = [
            _Bucket(b, m, packed=packed_rows, n_buffers=n_buffers, bidx=i)
            for i, (b, m) in enumerate(sorted(buckets, key=lambda t: t[1]))
        ]
        # legacy single-bucket attributes (tests introspect these)
        self.batch = self.buckets[0].batch
        self.msg_maxlen = self.buckets[-1].maxlen
        # native tcache preferred: the burst parse path queries it inline
        # from C (one call per burst instead of one dict op per txn)
        try:
            self.tcache = NativeTCache(tcache_depth)
        except Exception:
            self.tcache = TCache(tcache_depth)
        # one-pass native host path (round 11): submit-side tag gather +
        # dedup query and harvest-side verdict/insert/wire-build each run
        # as a single C call per frag (native/hostpath.cpp).  Requires the
        # native tcache (the C kernel queries/inserts it in-library); any
        # build/load failure falls back to the NumPy path, bit-identical.
        if native_hostpath is None:
            native_hostpath = os.environ.get(
                "FDTPU_INGEST_NATIVE_HOSTPATH", "1") != "0"
        self._hp = None
        if native_hostpath and isinstance(self.tcache, NativeTCache):
            try:
                self._hp = native_mod.lib()
            except Exception:
                self._hp = None
        # harvest scratch for the native finish, grown to the worst case
        # n*(65+ml) once per shape — steady state allocates nothing
        self._hp_arena = np.empty(0, np.uint8)
        self._hp_offs = np.empty(1, np.int64)
        self._hp_tags = np.empty(0, np.uint64)
        self._hp_cnt = np.zeros(3, np.int64)
        # packed verdict egress: _finish_rows returns ONE PackedVerdicts
        # per frag instead of k (bytes, txn) tuples; the verify tile
        # stamps it downstream as a single arena frag
        self.egress_packed = bool(egress_packed)
        self.metrics = VerifyMetrics()
        # max_inflight > 0 enables the ASYNC data plane (wiredancer's
        # contract): a filled batch is dispatched without waiting, up to
        # max_inflight batches ride the device queue, and completed
        # batches are harvested in order by harvest() / submit().  0 =
        # synchronous (verdicts returned by the submit that fills a
        # batch — the simple form tests use).
        self.max_inflight = max_inflight
        # bulk batches retired per NON-blocking harvest poll (see
        # harvest()); the deadline lane is never quota'd.  2 measures
        # best on the modeled-latency smoke: 1 stretches the backlog
        # window (the grind runs longer), unbounded head-of-line-blocks
        # the deadline lane for tens of ms
        self.harvest_quota = 2
        self.inflight: deque[_Inflight] = deque()
        # fdtrace: optional span sink (a disco.trace.TraceRing — or any
        # object with its .record signature); coalesce/device/compile
        # spans are recorded alongside the mux's frag/burst spans so the
        # whole chain reconstructs in one timeline
        self.tracer = tracer
        self._seen_shapes: set[tuple[int, int]] = set()
        # called while blocked on a device verdict (TileCtx.heartbeat in
        # the verify tile): a long device wait must not read as a dead
        # tile to the supervisor, and must still honor HALT
        self.heartbeat_cb = heartbeat_cb
        # ---- low-latency lane (round 9) --------------------------------
        # A ladder of small pre-warmed shapes beside the throughput
        # buckets.  Admitted txns accumulate in ONE bucket shaped as the
        # LARGEST lat shape; at close — fill, or deadline_us on the
        # oldest admitted txn — the batch ships as the SMALLEST ladder
        # shape that holds the filled lanes (closest fit), so a
        # deadline close at 1% fill does not pay the full accumulator's
        # device time.  lat batches retire through their OWN inflight
        # queue: a 16-lane verdict must never wait behind a 2048-lane
        # throughput batch in the ordered harvest.
        self.lat_shapes = tuple(sorted(int(s) for s in (lat_shapes or ())))
        self.deadline_us = int(deadline_us)
        self.lat_max_inflight = max(1, int(lat_max_inflight))
        self.lat_spill_age_ns = int(
            float(lat_spill_age_factor) * self.deadline_us * 1_000)
        self.lat_inflight: deque[_Inflight] = deque()
        if self.lat_shapes:
            for s in self.lat_shapes:
                if self.dp_shards > 1 and s % self.dp_shards:
                    raise ValueError(
                        f"lat shape {s} not divisible by "
                        f"dp_shards {self.dp_shards}")
            ml = (min(m for _, m in buckets) if lat_maxlen is None
                  else int(lat_maxlen))
            self.lat_bucket = _Bucket(
                self.lat_shapes[-1], ml, packed=packed_rows,
                n_buffers=n_buffers, bidx=len(self.buckets), lane=1)
        else:
            self.lat_bucket = None

    @property
    def has_pending(self) -> bool:
        return (any(bk.pending for bk in self.buckets)
                or bool(self.lat_bucket and self.lat_bucket.pending)
                or bool(self.inflight) or bool(self.lat_inflight))

    @property
    def has_open(self) -> bool:
        """True iff some bucket holds UNDISPATCHED txns — the age-flush
        predicate (in-flight batches need no flushing, only harvesting;
        gating the flush on has_pending made the tile re-fire a no-op
        dispatch_open every after_credit while batches were in flight)."""
        return (any(bk.pending for bk in self.buckets)
                or bool(self.lat_bucket and self.lat_bucket.pending))

    def _bucket_for(self, msg_len: int) -> _Bucket | None:
        for bk in self.buckets:  # sorted by maxlen: smallest fitting bucket
            if msg_len <= bk.maxlen:
                return bk
        return None

    # ---- low-latency lane ----------------------------------------------
    def mark_warm(self, shapes) -> None:
        """Record (batch, maxlen) shapes as already compiled (the tile
        warms every bucket + lat ladder shape through the verifier BEFORE
        this pipeline exists): their first dispatch here then does not
        count as a compile, so a nonzero compile_cnt in steady state
        means a genuinely cold shape reached the hot path — the
        no-compile-storm signal the latency smoke gates on."""
        for b, ml in shapes:
            self._seen_shapes.add((int(b), int(ml)))

    def _lat_overloaded(self) -> bool:
        """Overload-shed predicate: the lane's dispatch-ahead depth is at
        budget, or its open queue has aged far past the deadline (device
        underwater) — either way new admissions spill to the throughput
        lane instead of queuing behind a lane that can't keep its
        promise."""
        if len(self.lat_inflight) >= self.lat_max_inflight:
            return True
        bk = self.lat_bucket
        return bool(
            bk.t_first and self.lat_spill_age_ns
            and time.perf_counter_ns() - bk.t_first > self.lat_spill_age_ns)

    def _fit_rows(self, used: int) -> int:
        """Closest-fit ladder shape: the smallest pre-warmed lat shape
        holding `used` filled lanes."""
        for s in self.lat_shapes:
            if s >= used:
                return s
        return self.lat_shapes[-1]

    def _flush_lat(self, deadline: bool = False) -> list:
        bk = self.lat_bucket
        if bk is None or not bk.pending:
            return []
        if deadline:
            self.metrics.lat_deadline_closes += 1
        return self._flush_bucket(bk, rows=self._fit_rows(bk.used))

    def lat_due(self, now_ns: int | None = None) -> bool:
        """True iff the open low-latency batch's OLDEST txn has aged past
        deadline_us — the batch-close-on-deadline predicate, cheap enough
        for every after_credit iteration."""
        bk = self.lat_bucket
        if bk is None or not bk.pending or self.deadline_us <= 0:
            return False
        now = time.perf_counter_ns() if now_ns is None else now_ns
        return now - bk.t_first >= self.deadline_us * 1_000

    def dispatch_due(self) -> list:
        """Deadline dispatch: close the open lat batch the moment its
        oldest txn hits deadline_us, even at 1% fill (closest-fit shape).
        Non-blocking in async mode; completed batches from either lane
        are returned."""
        out = self._flush_lat(deadline=True) if self.lat_due() else []
        if self.max_inflight > 0:
            out += self.harvest()
        return out

    def submit(self, payload: bytes,
               lat: bool = False) -> list[tuple[bytes, txn_lib.Txn]]:
        """Feed one serialized txn.  Returns verified txns flushed by this
        submit (empty unless an open batch filled and was dispatched).

        lat=True admits the txn to the low-latency lane (priority
        admission).  When the lane is overloaded — inflight depth at
        budget, or the open queue aged far past the deadline — or the
        txn doesn't fit the lane's shape, it SPILLS to the throughput
        lane (lat_spill counts it) rather than blowing the deadline
        silently or dropping."""
        self.metrics.txns_in += 1
        try:
            parsed = txn_lib.parse(payload)
        except txn_lib.TxnParseError:
            self.metrics.parse_fail += 1
            return []

        msg = parsed.message(payload)
        sigs = parsed.signatures(payload)
        bk = None
        if lat and self.lat_bucket is not None:
            lb = self.lat_bucket
            if (len(msg) <= lb.maxlen and len(sigs) <= lb.batch
                    and not self._lat_overloaded()):
                bk = lb
            else:
                self.metrics.lat_spill += 1
        if bk is None:
            bk = self._bucket_for(len(msg))
            if bk is None:
                self.metrics.too_long_drop += 1
                return []

        if len(sigs) > bk.batch:
            # a txn's sig lanes must fit one device batch; batch >= 12
            # (FD_TXN_ACTUAL_SIG_MAX) covers every wire-valid txn
            self.metrics.sig_overflow_drop += 1
            return []
        # pre-dedup on the low 64 bits of the first signature
        # (fd_verify.h:64-71; the full-sig dedup tile runs downstream).
        # Query-only here; the tag is inserted only after verify PASSES in
        # flush() — inserting pre-verify would let an attacker poison the
        # window with a mangled copy and block the valid retransmission.
        tag = int.from_bytes(sigs[0][:8], "little")
        if self.tcache.query(tag):
            self.metrics.dedup_drop += 1
            return []

        out = []
        if bk.used + len(sigs) > bk.batch:
            out = (self._flush_lat() if bk.lane
                   else self._flush_bucket(bk))
        pubs = parsed.signer_pubkeys(payload)
        lanes = []
        for s, p in zip(sigs, pubs):
            lane = bk.used
            bk.msgs[lane, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
            bk.set_len(lane, len(msg))
            bk.sigs[lane] = np.frombuffer(s, dtype=np.uint8)
            bk.pubs[lane] = np.frombuffer(p, dtype=np.uint8)
            lanes.append(lane)
            bk.used += 1
        if not bk.t_first:
            bk.t_first = time.perf_counter_ns()
        bk.pending.append(_Pending(payload, parsed, lanes, tag))
        if bk.lane:
            self.metrics.lat_txns += 1
        if bk.used == bk.batch:
            out += self._flush_lat() if bk.lane else self._flush_bucket(bk)
        return out

    def submit_burst(self, payloads=None, packed=None) -> list:
        """Feed many serialized txns with ONE native parse+dedup call per
        bucket fill (native/txnparse.cpp — the verify tile's burst data
        plane; the scalar submit() path cost ~110 us/txn of Python,
        3.6x the reference's whole per-core verify budget).

        Input: either payloads (list[bytes]) or packed=(buf, offs) — a
        flat buffer + int64 offsets (n+1), e.g. the ring rx scratch from
        fd_ring_rx_burst, consumed zero-copy.

        Returns verified txns flushed by this call as (payload, None)
        tuples: burst mode skips Txn descriptor construction (the verify
        tile forwards payload+tag only; downstream tiles re-parse).
        Callers that need the parsed descriptor use submit().

        Bursts fill the PRIMARY (widest-lane) bucket; txns whose message
        exceeds it reroute through the scalar path's bucket ladder."""
        from ..ballet import txn_native as tn

        if packed is None:
            handle = getattr(self.tcache, "handle", None)
            if handle is None:
                # no native tcache (lib unavailable): degrade to scalar
                out = []
                for p in payloads:
                    out += self.submit(p)
                return out
            packed = tn.pack_payloads(payloads)
        else:
            handle = getattr(self.tcache, "handle", None)
            if handle is None:
                out = []
                buf0, offs0 = packed
                for i in range(len(offs0) - 1):
                    out += self.submit(bytes(buf0[offs0[i]:offs0[i + 1]]))
                return out
        buf, offs = packed

        out = []
        bk = self.buckets[0]
        idx = 0
        n = len(offs) - 1
        while idx < n:
            if bk.packed:
                r = tn.parse_packed_bucket(buf, offs[idx:], bk.arr,
                                           bk.maxlen, bk.lens, bk.used,
                                           handle)
            else:
                r = tn.parse_packed(buf, offs[idx:], bk.msgs, bk.lens,
                                    bk.sigs, bk.pubs, bk.used, handle)
            errs = r.err
            too_long = np.nonzero(errs == tn.ERR_TOO_LONG)[0]
            reroute = len(self.buckets) > 1
            self.metrics.txns_in += r.consumed - (
                len(too_long) if reroute else 0)
            self.metrics.parse_fail += int((errs == tn.ERR_PARSE).sum())
            self.metrics.dedup_drop += int((errs == tn.ERR_DUP).sum())
            self.metrics.sig_overflow_drop += int(
                (errs == tn.ERR_SIG_CAP).sum())
            if reroute:
                for i in too_long:
                    j = idx + int(i)
                    out += self.submit(bytes(buf[offs[j]:offs[j + 1]]))
            else:
                self.metrics.too_long_drop += len(too_long)
            acc = np.nonzero(errs == tn.OK)[0]
            if len(acc):
                # one copy of this round's region; accepted txns address
                # into it by (start, len) — materialized per txn only on
                # verify pass at harvest
                base = int(offs[idx])
                region = bytes(
                    memoryview(buf)[base:int(offs[idx + r.consumed])])
                starts = (offs[idx:][acc] - base).astype(np.int64)
                plens = (offs[idx:][acc + 1] - offs[idx:][acc]).astype(
                    np.int32)
                if not bk.t_first:
                    bk.t_first = time.perf_counter_ns()
                bk.pending.append(_BurstPending(
                    region, starts, plens,
                    r.lane0[acc], r.nsig[acc], r.tag[acc]))
                bk.used += r.lanes_used
            pre_used = bk.used
            idx += r.consumed
            if idx >= n:
                break
            # reaching here means the parser stopped early: the next txn
            # needs more lanes than remain — flush and retry it against
            # the empty bucket
            out += self._flush_bucket(bk)
            if r.consumed == 0 and pre_used == 0:
                # even an empty bucket can't hold it (defensive;
                # kErrSigCap already rejects txns wider than capacity)
                self.metrics.txns_in += 1
                self.metrics.sig_overflow_drop += 1
                idx += 1
        if bk.used == bk.batch:
            out += self._flush_bucket(bk)
        return out

    def submit_packed_rows(self, rows, n: int | None = None, guard=None,
                           release_cb=None, lat: bool = False) -> list:
        """Zero-copy packed-wire submit (round 8): `rows` is a (batch,
        ml+100) uint8 VIEW over the shm dcache, already laid out in the
        device-blob row format (msg | sig | pub | len-le32) by the
        producer.  The view goes straight to verify_fn.dispatch_blob —
        ZERO payload copies between ring rx and device dispatch.

        n: true row count (rows beyond are the producer's zero padding;
        their tag is 0 and they are excluded from dedup and counts).
        guard=(mcache, seq): the frag's seqlock is re-checked AFTER the
        dispatch call returns; a torn frag (producer lapped the dcache
        mid-upload) is dropped whole (torn_drop) — never verified.
        release_cb: fired exactly once when the frag retires (verdict
        materialized or torn-drop) — the tile returns the held consumer
        credit there, which is what pins the view until then.
        lat=True routes the frag through the low-latency lane: the
        dispatch slices the view to the closest-fit ladder shape >= n
        (still zero-copy — a leading row slice is contiguous) and the
        verdict retires via the lat inflight queue; an overloaded lane
        spills the whole frag to the throughput path (lat_spill += n).
        """
        if not hasattr(self.verify_fn, "dispatch_blob"):
            raise ValueError("submit_packed_rows needs a packed verifier "
                             "(dispatch_blob)")
        nrows = rows.shape[0]
        ml = rows.shape[1] - _Bucket.PACKED_EXTRA
        n = nrows if n is None else min(int(n), nrows)
        # dedup tags = low 64 bits of the signature (row[ml:ml+8]); the
        # 8B/row gather is metadata, not a payload copy.  Query-only here
        # — tags insert at harvest iff verify passes (fd_verify.h:64-71).
        # Native path (round 11): strided gather + batched query as ONE C
        # call straight off the dcache view, no ascontiguousarray staging.
        if (self._hp is not None and rows.dtype == np.uint8
                and rows.strides[1] == 1):
            tag = np.empty(n, np.uint64)
            dup8 = np.empty(n, np.uint8)
            ndup = self._hp.fd_hostpath_submit_rows(
                ctypes.c_void_p(rows.ctypes.data),
                int(rows.strides[0]), n, ml,
                ctypes.c_void_p(self.tcache.handle),
                ctypes.c_void_p(tag.ctypes.data),
                ctypes.c_void_p(dup8.ctypes.data))
            dup = dup8.view(bool)
            ndup = int(ndup)
        else:
            tag = np.ascontiguousarray(rows[:n, ml:ml + 8]).view(
                np.uint64).ravel()
            if hasattr(self.tcache, "query_batch"):
                dup = self.tcache.query_batch(tag)
            else:
                dup = np.array([self.tcache.query(int(t)) for t in tag],
                               dtype=bool)
            ndup = int(dup.sum())

        lane = 0
        nd = nrows                       # dispatched row count
        if lat and self.lat_shapes:
            if self._lat_overloaded():
                self.metrics.lat_spill += n
            else:
                lane = 1
                self.metrics.lat_txns += n
                fit = next((s for s in self.lat_shapes if s >= n), None)
                if fit is not None and fit < nrows:
                    nd = fit
        t0 = time.perf_counter_ns()
        shape = (nd, ml)
        first_dispatch = shape not in self._seen_shapes
        blob = rows if nd == nrows else rows[:nd]
        ok_dev = self.verify_fn.dispatch_blob(blob, maxlen=ml)
        if first_dispatch:
            self._seen_shapes.add(shape)
            dt = time.perf_counter_ns() - t0
            self.metrics.compile_cnt += 1
            self.metrics.compile_ns += dt
            trace_mod.record_compile(("verify",) + shape, dt)
            if self.tracer is not None:
                self.tracer.record(
                    trace_mod.KIND_COMPILE, t0, dt,
                    iidx=trace_mod.LANE_LAT if lane else 0)
        if guard is not None:
            # no-torn-buffer invariant, view edition: the payload was
            # never copied under the seqlock, so the overrun check moves
            # to AFTER the device got its read of the region underway.
            # Any overrun between rx and here means the rows may be torn.
            mcache, seq = guard
            rc, _ = mcache.query(seq)
            if rc != 0:
                # torn rows never reach harvest: count them in their OWN
                # counter and leave txns_in/dedup_drop untouched so
                # pass/fail rates derived from txns_in stay honest
                self.metrics.torn_drop += 1
                self.metrics.torn_txns += n
                if release_cb is not None:
                    release_cb()
                return []
        self.metrics.txns_in += n
        self.metrics.dedup_drop += ndup
        start_async = getattr(ok_dev, "copy_to_host_async", None)
        if start_async is not None:
            start_async()
        self.metrics.lanes_filled += n
        self.metrics.lanes_dispatched += nd
        self.metrics.last_fill_pct = 100 * n // nd
        fl = _Inflight(ok_dev,
                       [_RowsPending(rows, tag, dup, n, ml, release_cb)],
                       t0, lane=lane, t_first=t0)
        if self.max_inflight <= 0:
            return self._finish(fl)
        q = self.lat_inflight if lane else self.inflight
        q.append(fl)
        out = []
        while len(q) > self.max_inflight:
            out += self._finish(q.popleft())
        return out + self.harvest()

    def flush(self) -> list[tuple[bytes, txn_lib.Txn]]:
        """Dispatch every bucket with pending txns and harvest EVERYTHING
        (blocking); returns passing txns."""
        out = self._flush_lat()
        for bk in self.buckets:
            out += self._flush_bucket(bk)
        out += self.harvest(block=True)
        return out

    def dispatch_open(self) -> list[tuple[bytes, txn_lib.Txn]]:
        """Age-flush for the async tile: dispatch partially-filled buckets
        WITHOUT waiting for their results (they surface via harvest());
        any already-completed batches are returned."""
        out = self._flush_lat()
        for bk in self.buckets:
            out += self._flush_bucket(bk)
        return out

    def harvest(self, block: bool = False) -> list[tuple[bytes, txn_lib.Txn]]:
        """Collect verdicts of completed in-flight batches, in dispatch
        order per lane.  block=False stops at the first still-running
        batch (the tile's after_credit poll); block=True drains both
        queues.  The low-latency queue drains FIRST — its verdicts are
        the deadline-bound ones, and its batches never wait behind a
        still-running throughput batch.

        A throughput batch's host-side finish (verdict fetch + passing-txn
        materialization) runs MILLISECONDS at 2048 lanes, and several bulk
        batches routinely become ready inside one poll window — an
        unbounded drain here head-of-line-blocks the deadline lane behind
        tens of ms of bulk bookkeeping.  Non-blocking harvest therefore
        retires at most `harvest_quota` bulk batches per call (work is
        conserved — the rest retire on subsequent polls) and re-services
        the lat lane between bulk finishes."""
        out = self._drain_lat(block)
        n_bulk = 0
        while self.inflight:
            if not block:
                if n_bulk >= self.harvest_quota:
                    break
                if not _is_ready(self.inflight[0].ok_dev):
                    break
            out += self._finish(self.inflight.popleft())
            n_bulk += 1
            # a bulk finish is ms of host work: close + drain the
            # deadline lane between finishes so it never queues behind
            if self.lat_due():
                out += self._flush_lat(deadline=True)
            out += self._drain_lat(block=False)
        return out

    def _drain_lat(self, block: bool = False) -> list:
        out = []
        while self.lat_inflight:
            if not block and not _is_ready(self.lat_inflight[0].ok_dev):
                break
            out += self._finish(self.lat_inflight.popleft())
        return out

    def _flush_bucket(self, bk: _Bucket,
                      rows: int | None = None) -> list:
        """Dispatch a bucket's open batch.  rows (low-latency lane only)
        dispatches just the first `rows` lanes — the closest-fit ladder
        shape — instead of the full accumulator width."""
        if not bk.pending:
            return []
        t0 = time.perf_counter_ns()
        tr_idx = bk.bidx | (trace_mod.LANE_LAT if bk.lane else 0)
        if bk.t_first:
            self.metrics.coalesce_ns.sample(t0 - bk.t_first)
            if self.tracer is not None:
                self.tracer.record(trace_mod.KIND_COALESCE, bk.t_first,
                                   t0 - bk.t_first, iidx=tr_idx,
                                   cnt=len(bk.pending))
        nrows = bk.batch if rows is None else min(int(rows), bk.batch)
        # bucket occupancy: filled sig lanes vs the full dispatched shape
        # (the padding delta is the age-flush's device-waste signal)
        self.metrics.lanes_filled += bk.used
        self.metrics.lanes_dispatched += nrows
        self.metrics.last_fill_pct = 100 * bk.used // nrows
        # jax dispatch is asynchronous: this returns a device future
        # without waiting for the TPU.  The numpy bucket arrays pass
        # straight through — a jitted verify_fn device_puts them itself,
        # and reset() below allocates FRESH arrays, so the callee can
        # consume these asynchronously without a torn read.  Packed
        # buckets upload as ONE blob via the verifier's dispatch_blob.
        # A closest-fit slice is row-major-contiguous, so the sliced
        # blob/arrays are exactly the smaller shape's layout.
        shape = (nrows, bk.maxlen)
        first_dispatch = shape not in self._seen_shapes
        if bk.packed and hasattr(self.verify_fn, "dispatch_blob"):
            blob = bk.arr if nrows == bk.batch else bk.arr[:nrows]
            ok_dev = self.verify_fn.dispatch_blob(blob, maxlen=bk.maxlen)
        else:
            ok_dev = self.verify_fn(bk.msgs[:nrows], bk.lens[:nrows],
                                    bk.sigs[:nrows], bk.pubs[:nrows])
        if first_dispatch:
            # first dispatch of this (batch, maxlen) shape: the wall time
            # above includes the jit trace+compile (or AOT load) — the
            # compile-storm signal bench.py and /metrics report
            self._seen_shapes.add(shape)
            dt = time.perf_counter_ns() - t0
            self.metrics.compile_cnt += 1
            self.metrics.compile_ns += dt
            trace_mod.record_compile(("verify",) + shape, dt)
            if self.tracer is not None:
                self.tracer.record(trace_mod.KIND_COMPILE, t0, dt,
                                   iidx=tr_idx)
        # kick the device->host verdict copy off NOW: on a tunneled/remote
        # device each later np.asarray pays a full RTT (~100 ms here);
        # with the async copy started at dispatch, harvest's fetch finds
        # the bits already (or nearly) resident
        start_async = getattr(ok_dev, "copy_to_host_async", None)
        if start_async is not None:
            start_async()
        # the packed blob stays pinned under this dispatch; reset() below
        # rotates a FREE pool blob in, so the next batch packs while this
        # one uploads/verifies (double-buffered ingest)
        fl = _Inflight(ok_dev, bk.pending, t0,
                       buf=bk.arr if bk.packed else None, owner=bk,
                       lane=bk.lane, t_first=bk.t_first)
        bk.reset()
        if self.max_inflight <= 0:
            if self.tracer is not None:
                self.tracer.record(trace_mod.KIND_DISPATCH, t0,
                                   time.perf_counter_ns() - t0,
                                   iidx=tr_idx, cnt=len(fl.pending))
            return self._finish(fl)          # synchronous mode
        q = self.lat_inflight if bk.lane else self.inflight
        q.append(fl)
        out = []
        while len(q) > self.max_inflight:
            # bounded queue: retire the oldest before accepting more
            out += self._finish(q.popleft())
        if self.tracer is not None:
            # dispatch call + over-budget drain: a full inflight queue
            # blocks in the loop above, so this span IS the
            # dispatch-queue pressure stage of the SLO budget
            self.tracer.record(trace_mod.KIND_DISPATCH, t0,
                               time.perf_counter_ns() - t0, iidx=tr_idx,
                               cnt=len(fl.pending))
        return out + self.harvest()

    def _finish(self, fl: _Inflight) -> list[tuple[bytes, txn_lib.Txn]]:
        if self.heartbeat_cb is not None:
            # heartbeat through the device wait instead of blocking cold
            # in np.asarray: the supervisor's staleness check keeps seeing
            # a live tile, and HALT still lands.  (A _GuardedVerdict's
            # is_ready turns True at its deadline, so a hung device cannot
            # wedge this loop either.)  Adaptive backoff: the low-latency
            # lane's verdicts are often <1 ms out, and a fixed 500 us poll
            # ate up to half of that per harvest — start at 50 us and
            # decay toward the old cap for long throughput-batch waits.
            wait = 50e-6
            while not _is_ready(fl.ok_dev):
                self.heartbeat_cb()
                time.sleep(wait)
                wait = min(wait * 2, 500e-6)
        ok = np.asarray(fl.ok_dev)           # blocks only if still running
        if fl.buf is not None:
            # verdict materialized => the in-order device queue finished
            # both the blob's upload and the verify that read it; only
            # now may the blob re-enter the pack rotation
            fl.owner.release(fl.buf)
            fl.buf = None
        now = time.perf_counter_ns()
        self.metrics.batches += 1
        self.metrics.batch_ns.sample(now - fl.t0)
        if fl.lane:
            self.metrics.lat_batches += 1
            if fl.t_first:
                self.metrics.lat_e2e_ns.sample(now - fl.t_first)
        elif fl.t_first:
            self.metrics.e2e_ns.sample(now - fl.t_first)
        tr_idx = ((fl.owner.bidx if fl.owner is not None else 0)
                  | (trace_mod.LANE_LAT if fl.lane else 0))
        if self.tracer is not None:
            self.tracer.record(trace_mod.KIND_DEVICE, fl.t0, now - fl.t0,
                               iidx=tr_idx, cnt=len(fl.pending))
        out = []
        for p in fl.pending:
            if isinstance(p, _RowsPending):
                out += self._finish_rows(p, ok)
            elif isinstance(p, _BurstPending):
                out += self._finish_burst(p, ok)
            elif all(ok[lane] for lane in p.lanes):
                if self.tcache.insert(p.tag):
                    # same tag verified twice inside one open batch window
                    self.metrics.dedup_drop += 1
                    continue
                self.metrics.verify_pass += 1
                out.append((p.payload, p.parsed))
            else:
                self.metrics.verify_fail += 1
        if self.tracer is not None:
            # harvest stage: verdict materialized -> passing txns rebuilt
            self.tracer.record(trace_mod.KIND_HARVEST, now,
                               time.perf_counter_ns() - now, iidx=tr_idx,
                               cnt=len(out))
        return out

    def _finish_rows(self, rp: _RowsPending, ok) -> list:
        """Harvest one zero-copy packed-wire frag: verdicts are per-row
        (one sig per row on this path), passing payloads reconstruct the
        single-sig wire form (0x01 | sig | msg) from the still-pinned shm
        view, then the held credit is released.

        Native path (round 11): verdict masking + conditional tag insert
        + wire build run as ONE C call (fd_hostpath_finish_rows) writing
        every passing wire into a persistent arena with an offsets table.
        The NumPy fallback is bit-identical.  Egress is either the legacy
        per-txn [(bytes, None)] list or — egress_packed — a single
        PackedVerdicts carrying the arena."""
        try:
            okv = np.asarray(ok[:rp.n])
            rows = rp.rows
            if (self._hp is not None and rows.dtype == np.uint8
                    and rows.strides[1] == 1):
                pv = self._hp_finish(rp, okv)
            else:
                pv = self._np_finish(rp, okv)
            if pv is None or pv.k == 0:
                return []
            if self.egress_packed:
                return [pv]
            return [(w, None) for w in pv.wires()]
        finally:
            if rp.release_cb is not None:
                rp.release_cb()

    def _hp_finish(self, rp: _RowsPending, okv) -> "PackedVerdicts | None":
        """One-pass C finish: masks, inserts, and memcpy-builds the wires
        of one frag into the grow-only scratch arena (worst case
        n*(65+ml) bytes, allocated once per shape)."""
        n, ml = rp.n, rp.ml
        ok8 = okv.view(np.uint8) if okv.dtype == np.bool_ else okv.astype(
            np.uint8)
        ok8 = np.ascontiguousarray(ok8)
        dup8 = (rp.dup.view(np.uint8) if rp.dup.dtype == np.bool_
                else np.ascontiguousarray(rp.dup, dtype=np.uint8))
        cap = n * (65 + ml)
        if self._hp_arena.nbytes < cap:
            self._hp_arena = np.empty(cap, np.uint8)
        if len(self._hp_offs) < n + 1:
            self._hp_offs = np.empty(n + 1, np.int64)
            self._hp_tags = np.empty(n, np.uint64)
        while True:
            rc = self._hp.fd_hostpath_finish_rows(
                ctypes.c_void_p(rp.rows.ctypes.data),
                int(rp.rows.strides[0]), n, ml,
                ctypes.c_void_p(ok8.ctypes.data),
                ctypes.c_void_p(rp.tag.ctypes.data),
                ctypes.c_void_p(dup8.ctypes.data),
                ctypes.c_void_p(self.tcache.handle),
                ctypes.c_void_p(self._hp_arena.ctypes.data),
                int(self._hp_arena.nbytes),
                ctypes.c_void_p(self._hp_offs.ctypes.data),
                ctypes.c_void_p(self._hp_tags.ctypes.data),
                ctypes.c_void_p(self._hp_cnt.ctypes.data))
            if rc >= 0:
                break
            # arena too small (cannot happen with the worst-case sizing
            # above, kept for safety): the C call touched NOTHING — grow
            # and retry with identical semantics
            self._hp_arena = np.empty(-int(rc), np.uint8)
        k = int(rc)
        self.metrics.verify_fail += int(self._hp_cnt[0])
        self.metrics.dedup_drop += int(self._hp_cnt[1])
        self.metrics.verify_pass += k
        if k == 0:
            return None
        nb = int(self._hp_offs[k])
        # copy out of the scratch: a PackedVerdicts must survive the next
        # frag's finish (harvest retires several per poll)
        return PackedVerdicts(self._hp_arena[:nb].copy(),
                              self._hp_offs[:k + 1].copy(),
                              self._hp_tags[:k].copy(), k)

    # fallback ragged-build pad cap: the masked column copy stages at most
    # this many payload bytes (plus the same-shape bool mask) at once, so
    # one long-tail row no longer inflates the harvest footprint to
    # k*Lmax (~2x the payload) — chunking trades one masked copy for a
    # few, identical bytes out
    _NP_PAD_CAP = 1 << 18

    def _np_finish(self, rp: _RowsPending, okv) -> "PackedVerdicts | None":
        """NumPy finish (no .so / non-native tcache / exotic row strides):
        same verdict masking, insert semantics, and arena layout as the C
        path, built with vectorized column copies."""
        ml = rp.ml
        okv = okv.astype(bool)
        live = rp.tag != 0
        passing = okv & ~rp.dup & live
        self.metrics.verify_fail += int((live & ~rp.dup & ~okv).sum())
        pass_idx = np.nonzero(passing)[0]
        if len(pass_idx) == 0:
            return None
        # insert tags only now (verify passed) — exact FD_TCACHE_INSERT
        # dup semantics across frags and within this one
        if hasattr(self.tcache, "insert_batch_dedup"):
            dup2 = self.tcache.insert_batch_dedup(rp.tag[pass_idx])
        else:
            dup2 = np.array([self.tcache.insert(int(t))
                             for t in rp.tag[pass_idx]], dtype=bool)
        self.metrics.dedup_drop += int(dup2.sum())
        self.metrics.verify_pass += int((~dup2).sum())
        rows = rp.rows
        lens = np.ascontiguousarray(
            rows[:rp.n, ml + 96:ml + 100]).view(np.int32).ravel()
        keep = pass_idx[~dup2]
        if len(keep) == 0:
            return None
        klens = np.clip(lens[keep], 0, ml)
        k = len(keep)
        offs = np.empty(k + 1, np.int64)
        offs[0] = 0
        np.cumsum(65 + klens, out=offs[1:])
        arena = np.empty(int(offs[k]), np.uint8)
        if int(klens.min()) == int(klens.max()):
            # equal-length rows (template-stamped bursts): the arena IS a
            # (k, 65+L) matrix — three vectorized column copies, no pad
            L = int(klens[0])
            wires = arena.reshape(k, 65 + L)
            wires[:, 0] = 1
            wires[:, 1:65] = rows[keep, ml:ml + 64]
            wires[:, 65:] = rows[keep, :L]
        else:
            # ragged lengths: vectorized wire build over a padded
            # (c, 65+Lmax) staging block, chunked so pad + mask stay
            # under _NP_PAD_CAP regardless of the length tail, then
            # per-row sliced copies into the exact-size arena
            Lmax = int(klens.max())
            step = max(1, self._NP_PAD_CAP // (65 + Lmax))
            for c0 in range(0, k, step):
                c1 = min(c0 + step, k)
                kc, lc = keep[c0:c1], klens[c0:c1]
                Lm = int(lc.max())
                wires = np.empty((c1 - c0, 65 + Lm), np.uint8)
                wires[:, 0] = 1
                wires[:, 1:65] = rows[kc, ml:ml + 64]
                body = wires[:, 65:]
                msk = np.arange(Lm)[None, :] < lc[:, None]
                body[msk] = rows[kc, :Lm][msk]
                for j in range(c1 - c0):
                    o = int(offs[c0 + j])
                    arena[o:o + 65 + int(lc[j])] = wires[j, :65 + int(lc[j])]
        return PackedVerdicts(arena, offs, rp.tag[keep].copy(), k)

    def _finish_burst(self, bp: _BurstPending, ok) -> list:
        """Vectorized harvest of one burst record: per-txn verdict via
        segmented minimum over its (contiguous) lanes, then one batched
        tcache insert with exact FD_TCACHE_INSERT dup semantics."""
        k = len(bp.lane0)
        if k == 0:
            return []
        start = int(bp.lane0[0])
        end = int(bp.lane0[-1] + bp.nsig[-1])
        seg = np.asarray(ok[start:end], dtype=np.uint8)
        acc = np.minimum.reduceat(seg, bp.lane0 - start).astype(bool)
        pass_idx = np.nonzero(acc)[0]
        self.metrics.verify_fail += k - len(pass_idx)
        if len(pass_idx) == 0:
            return []
        if hasattr(self.tcache, "insert_batch_dedup"):
            dup = self.tcache.insert_batch_dedup(bp.tag[pass_idx])
        else:
            dup = np.array([self.tcache.insert(int(t))
                            for t in bp.tag[pass_idx]], dtype=bool)
        self.metrics.dedup_drop += int(dup.sum())
        self.metrics.verify_pass += int((~dup).sum())
        buf = bp.buf
        return [(buf[int(bp.start[i]):int(bp.start[i]) + int(bp.plen[i])],
                 None)
                for i, d in zip(pass_idx, dup) if not d]
