"""Tile framework & production pipeline (the reference's disco layer,
src/disco/): the verify pipeline, batch coalescing, metrics."""
