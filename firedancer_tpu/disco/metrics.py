"""Shared-memory metrics regions (ref: src/disco/metrics/fd_metrics.h:16-60,
declarative schema metrics.xml + gen_metrics.py codegen).

Each tile owns a fixed block of 64-bit slots in the workspace laid out by
static offset from a declarative schema.  Writers are single-threaded per
block (one tile = one writer, the reference's contract) and use aligned
8-byte stores (atomic on every platform we run on); the metric tile / monitor
snapshots blocks without coordination.

Instead of XML + codegen, the schema is a plain dict (kind -> slot defs)
that both writer and reader import — same static-layout idea, Python-native.
A slot def is either a bare name (COUNTER) or a (name, kind) tuple; the
reference's metrics.xml declares the same counter/gauge/histogram kinds
and fd_metric.c renders the matching Prometheus TYPE lines.

Histograms: each block also carries up to MAX_HISTS fixed 32-bucket
geomspace histograms (HIST defs below — the shm mirror of utils.hist.Histf)
rendered as native Prometheus `le`-bucket histograms with _sum/_count.
"""

import numpy as np

COUNTER = "counter"
GAUGE = "gauge"

# Slots common to every tile, written by the mux run loop itself
# (the reference's FD_METRICS_ALL* in generated/fd_metrics_all.h).
MUX_SLOTS = [
    "in_frag_cnt",       # frags consumed over all in links
    "in_sz",             # payload bytes consumed
    "in_filt_cnt",       # frags dropped by before_frag filter
    "in_ovrn_cnt",       # overruns detected (producer lapped us)
    "out_frag_cnt",      # frags published
    "out_sz",            # payload bytes published
    "backp_cnt",         # backpressure events (no downstream credit)
    "housekeep_cnt",     # housekeeping iterations
    "loop_cnt",          # run-loop iterations
    # run-loop regime accounting (ns counters): where this tile's wall
    # time goes — callback work, credit-stall waits, housekeeping, idle
    # sleeps.  The monitor (`fdtpuctl top`) renders the deltas as
    # busy%/backpressure%/housekeep% per tile (ref monitor.c's tile
    # in_backp/in_housekeeping regime columns).
    "busy_ns",           # time inside tile callbacks (frag/burst/credit)
    "backp_ns",          # time stalled in _wait_credit (no downstream credit)
    "house_ns",          # time inside the housekeeping block
    "idle_ns",           # time in the nothing-inbound yield sleep
    "knob_apply_cnt",    # autotune knob-pod generations applied via
                         # apply_knobs (disco/autotune.py)
    # drain protocol (graceful quiesce): every tile kind can be drained,
    # so the slots live in the mux section.  drain_flush_ns is the last
    # drain's DRAIN->dry wall time (the BENCH drain_flush_ms source).
    "drain_cnt",
    ("drain_flush_ns", GAUGE),
    # per-in-link hop latency gauges (ns), consume-time minus the
    # producer's tspub stamp — the monitor's per-hop latency source
    # (ref monitor.c renders the same from tsorig/tspub frag metas).
    # Up to 4 in links; set by the mux during housekeeping over a
    # fresh window each interval (CURRENT latency, hence gauges).
    ("in0_hop_p50_ns", GAUGE), ("in0_hop_p99_ns", GAUGE),
    ("in1_hop_p50_ns", GAUGE), ("in1_hop_p99_ns", GAUGE),
    ("in2_hop_p50_ns", GAUGE), ("in2_hop_p99_ns", GAUGE),
    ("in3_hop_p50_ns", GAUGE), ("in3_hop_p99_ns", GAUGE),
]

# per-out-link attribution gauges (up to 4 out links, mirroring the
# in*_hop pattern): sampled by the mux housekeeping loop over a fresh
# window each interval.  lag = producer seq minus the slowest reliable
# consumer's fseq (how far downstream has fallen behind); occ_hwm = ring
# occupancy high-watermark over the window (depth - cr_avail low-water);
# cr_lwm = the credit low-watermark itself; frag/byte rates are the
# window's publish throughput.  disco/attrib.py re-exports these with
# producer->consumer link labels (fdtpu_link_*).
for _j in range(4):
    MUX_SLOTS += [
        (f"out{_j}_lag", GAUGE), (f"out{_j}_occ_hwm", GAUGE),
        (f"out{_j}_cr_lwm", GAUGE), (f"out{_j}_frag_rate", GAUGE),
        (f"out{_j}_byte_rate", GAUGE),
    ]
del _j

# Per-kind app slots, appended after MUX_SLOTS (metrics.xml tile sections).
TILE_SLOTS: dict[str, list] = {
    "source": ["txn_gen_cnt", "blockhash_refresh_cnt",
               "adopt_pub_cnt"],          # fleet failover: txns re-published
                                          # from an adopted (dead) host's
                                          # stream
    "net": ["rx_pkt_cnt", "rx_drop_cnt", "tx_pkt_cnt",
            ("bound_port", GAUGE),
            "rate_drop_cnt",              # per-source pps token-bucket sheds
            ("shedding", GAUGE)],         # 1 = shed within the last ~5 s
    "quic": [("conn_cnt", GAUGE), "reasm_pub_cnt", "reasm_drop_cnt",
             "reasm_evict_cnt"],          # reasm slots lost to FIFO/budget
    "quic_server": [
        ("bound_port", GAUGE), "reasm_pub_cnt", "pkt_rx_cnt", "pkt_tx_cnt",
        "conn_created_cnt", "conn_closed_cnt", "streams_rx_cnt",
        "retrans_cnt", "pkt_undecryptable_cnt",
        # DoS front-door shed counters (every shed is counted somewhere):
        "pkt_malformed_cnt",              # unparseable datagrams
        "conn_reject_cnt",                # conn/peer caps refused admission
        "retry_sent_cnt",                 # stateless Retries (flood defense)
        "rate_drop_cnt",                  # per-conn txn token-bucket sheds
        "reasm_evict_cnt",                # partial streams evicted (budgets)
        "reasm_drop_cnt",                 # completed txns dropped pre-publish
        ("conn_cnt", GAUGE),              # live conn table size
        ("half_open_cnt", GAUGE),         # conns mid-handshake
        ("shedding", GAUGE),              # 1 = shed within the last ~5 s
        # burst packet-protection backend attribution + key-cache bound
        "crypto_native_cnt",              # packets through the C engine
        "crypto_fallback_cnt",            # packets through Python/NumPy
        "initial_keys_evict_cnt",         # Initial key-schedule LRU evictions
    ],
    "verify": [
        "txn_in_cnt", "parse_fail_cnt", "dedup_drop_cnt", "too_long_cnt",
        "verify_fail_cnt", "verify_pass_cnt", "batch_cnt",
        # TPU hooks (fdtrace): XLA compile storms, bucket occupancy, and
        # device-queue depth — the decomposition the bench optimizes by
        "compile_cnt",                    # (batch, maxlen) first-dispatches
        "compile_ns",                     # wall ns spent in those dispatches
        "lanes_filled_cnt",               # sig lanes occupied at dispatch
        "lanes_dispatched_cnt",           # sig lanes shipped (filled + pad)
        ("bucket_fill_pct", GAUGE),       # last dispatch's occupancy %
        ("inflight_depth", GAUGE),        # device batches in flight
        "torn_drop_cnt",                  # packed-wire frags dropped on a
                                          # post-dispatch seq re-check miss
        "torn_txn_cnt",                   # rows riding those frags (kept out
                                          # of txn_in_cnt so pass/fail rates
                                          # only count harvested rows)
        # self-healing (GuardedVerifier): device dispatch health + the
        # CPU ed25519 fallback that keeps verdicts flowing when the
        # device path is sick
        "device_fail_cnt",                # device dispatches failed/timed out
        "fallback_lane_cnt",              # sig lanes verdicted on the CPU path
        "reprobe_cnt",                    # degraded-mode device probes
        ("degraded_mode", GAUGE),         # 1 = serving off the CPU fallback
        ("fallback_vps", GAUGE),          # CPU-fallback verify rate (lanes/s)
        # dual-lane dispatch (round 9): low-latency lane accounting
        "lat_txn_cnt",                    # txns admitted to the lat lane
        "lat_spill_cnt",                  # lat txns shed to the bulk lane
        "lat_batch_cnt",                  # lat-lane device batches
        "lat_deadline_close_cnt",         # batches closed by deadline_us
    ],
    "dedup": ["dup_drop_cnt", "uniq_cnt",
              "torn_drop_cnt",             # packed-egress frags dropped on a
                                           # seq re-check miss mid-unpack
              "preload_cnt",               # tags preloaded at boot from the
                                           # fleet digest/ledger reject set
              ("shard_foreign_cnt", GAUGE)],  # mis-steered tags (fleet
                                              # sharded tcache)
    "pack": ["txn_insert_cnt", "microblock_cnt", "cu_consumed"],
    "leader_pack": [
        "txn_in_cnt", "parse_fail_cnt", "txn_insert_cnt", "vote_insert_cnt",
        "sched_txn_cnt", "microblock_cnt", "cu_consumed",
        "oversize_drop_cnt",               # txn cost > block budget at insert
        "heap_full_drop_cnt",              # max_pending shed (votes bypass)
        "conflict_delay_cnt",              # account conflict deferrals
        "torn_drop_cnt",                   # packed-egress seq re-check miss
        "drain_drop_cnt",                  # unschedulable heap remainder
                                           # shed by the drain protocol
        "shard_steer_cnt",                 # txns owned by this fee-payer
                                           # shard (sharded topology)
        ("pending", GAUGE),                # heap occupancy
    ],
    "leader_merge": [
        "mb_rx_cnt",                       # shard microblocks received
        "mb_merge_cnt",                    # microblocks admitted downstream
        "parse_fail_cnt",                  # malformed merge-wire frags
        "merge_budget_defer_cnt",          # admissions deferred by the
                                           # GLOBAL block/vote/data/account
                                           # budgets
        "merge_stall_cnt",                 # full passes with queued work
                                           # but zero admissions
        "drain_drop_cnt",                  # queued microblocks shed by the
                                           # drain protocol after repeated
                                           # stalls
        ("merge_q", GAUGE),                # queued microblocks across shards
    ],
    "bank": ["txn_exec_cnt", "txn_fail_cnt", "slot_cnt",
             ("rpc_port", GAUGE)],
    "poh": ["hash_cnt", "mixin_cnt"],
    "poh_dev": [
        "hash_cnt", "mixin_cnt", "entry_cnt", "tick_cnt",
        "mb_rx_cnt", "parse_fail_cnt",
        "spec_hit_cnt",                    # speculative span became the tick
        "spec_miss_cnt",                   # mixins landed: span re-dispatched
        "rehash_cnt",                      # hashes re-run on spec misses
        "recheck_ok_cnt", "recheck_fail_cnt",  # emitted-entry re-verify lanes
        "mb_deferred_cnt",                 # microblocks pushed past a full tick
        "dispatch_cnt",                    # window (K-tick) span dispatches
        "splice_dispatch_cnt",             # mixin-splice dispatches (re-hash
                                           # from the saved insertion point)
        ("spec_depth", GAUGE),             # speculated ticks still unconsumed
        ("inflight_depth", GAUGE),
        ("mb_queue", GAUGE),
    ],
    "shred": ["fec_set_cnt", "shred_tx_cnt", "shred_rx_cnt",
              "shred_parse_fail_cnt", "shred_sig_fail_cnt",
              "turbine_tx_cnt", ("turbine_port", GAUGE),
              # batched leader-sig admission (round 13)
              "sig_batch_cnt", "sig_deadline_flush_cnt"],
    "shred_recover": ["shred_rx_cnt", "shred_parse_fail_cnt",
                      "fec_complete_cnt", "fec_recovered_cnt",
                      "fec_dispatch_cnt", "fec_fail_cnt",
                      "fec_host_fallback_cnt",
                      ("recover_pending", GAUGE)],
    "store": ["shred_store_cnt", "parse_fail_cnt",
              ("complete_slot", GAUGE)],
    "sign": ["sign_cnt", "refuse_cnt"],
    "gossip": ["rx_pkt_cnt", ("peer_cnt", GAUGE), ("bound_port", GAUGE)],
    "repair": ["req_cnt", "served_cnt", ("bound_port", GAUGE), "req_tx_cnt",
               "repaired_cnt", "resp_sig_fail_cnt"],
    "replay": [("replay_slot", GAUGE), "txn_replay_cnt", "dead_slot_cnt",
               ("ghost_head", GAUGE), ("root_slot", GAUGE), "vote_cnt"],
    "metric": [],
    "sink": ["frag_cnt"],
}

BLOCK_SLOTS = 128  # fixed slot area per tile, room to grow every kind

# -- shm histograms ---------------------------------------------------------
# (name, min_val, max_val) per def; layout per hist: 32 u64 bucket counts
# (bucket 31 = overflow, matching utils.hist.Histf) + 1 u64 running sum.
HIST_BUCKETS = 32
MAX_HISTS = 4

# one hop-latency histogram every tile feeds (cumulative; the windowed
# in*_hop gauges stay the liveness view, this is the scrape-friendly
# full-distribution view)
MUX_HISTS = [("in_hop_ns", 100.0, 10e9)]

# ranges MUST match the Histf the writer samples into (pipeline.py's
# VerifyMetrics); hist_store() asserts the edges agree.
TILE_HISTS: dict[str, list] = {
    "verify": [("batch_ns", 1_000.0, 60e9), ("coalesce_ns", 1_000.0, 60e9),
               # lat lane arrival->verdict e2e (round 9) — the deadline
               # SLO distribution the dual-lane bench gates on
               ("lat_e2e_ns", 1_000.0, 60e9)],
}


def slot_defs(kind: str) -> list[tuple[str, str]]:
    out = []
    for s in MUX_SLOTS + TILE_SLOTS.get(kind, []):
        out.append((s, COUNTER) if isinstance(s, str) else tuple(s))
    return out


def slot_names(kind: str) -> list[str]:
    return [n for n, _ in slot_defs(kind)]


def hist_defs(kind: str) -> list[tuple[str, float, float]]:
    return MUX_HISTS + TILE_HISTS.get(kind, [])


def footprint() -> int:
    # slots then hist area; uniform across kinds so the layout replay in
    # every process stays identical regardless of tile kind
    return (BLOCK_SLOTS + MAX_HISTS * (HIST_BUCKETS + 1)) * 8


def lint_schema() -> None:
    """CI gate over the declarative schema (the reference validates
    metrics.xml at codegen time): slot names unique post-prefixing, the
    block fits BLOCK_SLOTS, kinds valid, hist defs fit MAX_HISTS with
    sane ranges."""
    kinds = set(TILE_SLOTS) | set(TILE_HISTS)
    for kind in kinds:
        defs = slot_defs(kind)
        names = [n for n, _ in defs]
        if len(set(names)) != len(names):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"{kind}: duplicate slot names {dupes}")
        if len(defs) > BLOCK_SLOTS:
            raise ValueError(
                f"{kind}: {len(defs)} slots exceed BLOCK_SLOTS={BLOCK_SLOTS}")
        for n, k in defs:
            if k not in (COUNTER, GAUGE):
                raise ValueError(f"{kind}.{n}: invalid metric kind {k!r}")
            if not n.isidentifier():
                raise ValueError(f"{kind}.{n}: not a valid metric name")
        hds = hist_defs(kind)
        if len(hds) > MAX_HISTS:
            raise ValueError(
                f"{kind}: {len(hds)} hists exceed MAX_HISTS={MAX_HISTS}")
        hnames = [h[0] for h in hds]
        if len(set(hnames)) != len(hnames):
            raise ValueError(f"{kind}: duplicate hist names")
        for n, lo, hi in hds:
            if not (0 < lo < hi):
                raise ValueError(f"{kind}.{n}: bad hist range [{lo}, {hi}]")
            if n in names:
                raise ValueError(f"{kind}.{n}: hist name collides with slot")


class MetricsBlock:
    """Writer/reader view of one tile's metrics block."""

    def __init__(self, buf: memoryview, off: int, kind: str):
        self._arr = np.frombuffer(buf, dtype=np.uint64, count=BLOCK_SLOTS,
                                  offset=off)
        self._idx = {n: i for i, n in enumerate(slot_names(kind))}
        self._kinds = dict(slot_defs(kind))
        self.kind = kind
        # hist views: per def, (edges, counts view, sum view)
        self._hists = {}
        hoff = off + BLOCK_SLOTS * 8
        for hi, (name, lo, hi_v) in enumerate(hist_defs(kind)):
            base = hoff + hi * (HIST_BUCKETS + 1) * 8
            counts = np.frombuffer(buf, dtype=np.uint64,
                                   count=HIST_BUCKETS, offset=base)
            hsum = np.frombuffer(buf, dtype=np.uint64, count=1,
                                 offset=base + HIST_BUCKETS * 8)
            edges = np.geomspace(lo, hi_v, HIST_BUCKETS - 1)
            self._hists[name] = (edges, counts, hsum)

    def add(self, name: str, delta: int = 1):
        i = self._idx[name]
        # single writer per block: read-modify-write is safe; the 8B store
        # is what readers observe atomically
        self._arr[i] += np.uint64(delta)

    def set(self, name: str, val: int):
        self._arr[self._idx[name]] = np.uint64(val)

    def get(self, name: str) -> int:
        return int(self._arr[self._idx[name]])

    def has(self, name: str) -> bool:
        """Schema probe — health checks ask kinds they don't own (e.g.
        "does this tile export degraded_mode?") without try/except."""
        return name in self._idx

    def snapshot(self) -> dict[str, int]:
        return {n: int(self._arr[i]) for n, i in self._idx.items()}

    # -- histograms --------------------------------------------------------
    def hist_sample(self, name: str, v: float):
        edges, counts, hsum = self._hists[name]
        counts[np.searchsorted(edges, v)] += 1
        hsum[0] += np.uint64(max(int(v), 0))

    def hist_store(self, name: str, histf):
        """Bulk-mirror a utils.hist.Histf into the shm hist (the verify
        tile syncs its pipeline Histf this way).  The writer's edges must
        match the schema's — drift would mislabel every exported bucket."""
        edges, counts, hsum = self._hists[name]
        if len(histf.counts) != HIST_BUCKETS or not np.allclose(
                histf.edges, edges):
            raise ValueError(f"hist {name}: writer edges do not match schema")
        counts[:] = histf.counts
        hsum[0] = np.uint64(max(int(histf.sum), 0))

    def hist_snapshot(self, name: str):
        edges, counts, hsum = self._hists[name]
        return edges, counts.copy(), int(hsum[0])

    def hist_names(self) -> list[str]:
        return list(self._hists)


def _esc(v: str) -> str:
    """Escape a label VALUE per the Prometheus text exposition format
    (backslash, double-quote, newline — in that order)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(d: dict) -> str:
    return ",".join(f'{k}="{_esc(v)}"' for k, v in d.items())


def prometheus_render(tiles: dict[str, "MetricsBlock"], extra=None) -> str:
    """Render all tile blocks as Prometheus text exposition
    (ref: src/app/fdctl/run/tiles/fd_metric.c:232-263 prometheus_print):
    counters and gauges per the schema kind, shm histograms as native
    `le`-bucket histograms with _sum/_count.

    Conformant grouping: ALL samples of a family are emitted contiguously
    under exactly one `# HELP`/`# TYPE` pair (strict parsers reject a
    family split across the page), and label values are escaped.

    `extra` is an optional iterable of (name, kind, help, labels_dict,
    value) samples — disco/attrib.py feeds the producer->consumer link
    families through it so the HTTP server stays one render call.
    """
    # family name -> (kind, help, [sample lines])
    fams: dict[str, tuple[str, str, list[str]]] = {}

    def fam(metric, kind, help_txt):
        if metric in fams:
            return fams[metric][2]
        lines: list[str] = []
        fams[metric] = (kind, help_txt, lines)
        return lines

    for tname, blk in tiles.items():
        kind = blk.kind
        base = {"tile": tname, "kind": kind}
        for slot, val in blk.snapshot().items():
            metric = f"fdtpu_{slot}"
            fam(metric, blk._kinds[slot], f"{slot} per tile").append(
                f"{metric}{{{_labels(base)}}} {val}")
        for hname in blk.hist_names():
            metric = f"fdtpu_{hname}"
            lines = fam(metric, "histogram", f"{hname} distribution per tile")
            edges, counts, hsum = blk.hist_snapshot(hname)
            labels = _labels(base)
            cum = 0
            for i, e in enumerate(edges):
                cum += int(counts[i])
                lines.append(
                    f'{metric}_bucket{{{labels},le="{e:.6g}"}} {cum}')
            cum += int(counts[-1])  # overflow bucket
            lines.append(f'{metric}_bucket{{{labels},le="+Inf"}} {cum}')
            lines.append(f"{metric}_sum{{{labels}}} {hsum}")
            lines.append(f"{metric}_count{{{labels}}} {cum}")
    for name, kind, help_txt, labels, value in (extra or ()):
        fam(name, kind, help_txt).append(
            f"{name}{{{_labels(labels)}}} {value}")

    out = []
    for metric, (kind, help_txt, lines) in fams.items():
        out.append(f"# HELP {metric} {help_txt}")
        out.append(f"# TYPE {metric} {kind}")
        out.extend(lines)
    return "\n".join(out) + "\n"
