"""Shared-memory metrics regions (ref: src/disco/metrics/fd_metrics.h:16-60,
declarative schema metrics.xml + gen_metrics.py codegen).

Each tile owns a fixed block of 64-bit slots in the workspace laid out by
static offset from a declarative schema.  Writers are single-threaded per
block (one tile = one writer, the reference's contract) and use aligned
8-byte stores (atomic on every platform we run on); the metric tile / monitor
snapshots blocks without coordination.

Instead of XML + codegen, the schema is a plain dict (kind -> slot names)
that both writer and reader import — same static-layout idea, Python-native.
"""

import numpy as np

# Slots common to every tile, written by the mux run loop itself
# (the reference's FD_METRICS_ALL* in generated/fd_metrics_all.h).
MUX_SLOTS = [
    "in_frag_cnt",       # frags consumed over all in links
    "in_sz",             # payload bytes consumed
    "in_filt_cnt",       # frags dropped by before_frag filter
    "in_ovrn_cnt",       # overruns detected (producer lapped us)
    "out_frag_cnt",      # frags published
    "out_sz",            # payload bytes published
    "backp_cnt",         # backpressure events (no downstream credit)
    "housekeep_cnt",     # housekeeping iterations
    "loop_cnt",          # run-loop iterations
    # per-in-link hop latency gauges (ns), consume-time minus the
    # producer's tspub stamp — the monitor's per-hop latency source
    # (ref monitor.c renders the same from tsorig/tspub frag metas).
    # Up to 4 in links; set by the mux during housekeeping.
    "in0_hop_p50_ns", "in0_hop_p99_ns",
    "in1_hop_p50_ns", "in1_hop_p99_ns",
    "in2_hop_p50_ns", "in2_hop_p99_ns",
    "in3_hop_p50_ns", "in3_hop_p99_ns",
]

# Per-kind app slots, appended after MUX_SLOTS (metrics.xml tile sections).
TILE_SLOTS: dict[str, list[str]] = {
    "source": ["txn_gen_cnt", "blockhash_refresh_cnt"],
    "net": ["rx_pkt_cnt", "rx_drop_cnt", "tx_pkt_cnt", "bound_port"],
    "quic": ["conn_cnt", "reasm_pub_cnt", "reasm_drop_cnt"],
    "quic_server": [
        "bound_port", "reasm_pub_cnt", "pkt_rx_cnt", "pkt_tx_cnt",
        "conn_created_cnt", "conn_closed_cnt", "streams_rx_cnt",
        "retrans_cnt", "pkt_undecryptable_cnt",
    ],
    "verify": [
        "txn_in_cnt", "parse_fail_cnt", "dedup_drop_cnt", "too_long_cnt",
        "verify_fail_cnt", "verify_pass_cnt", "batch_cnt",
    ],
    "dedup": ["dup_drop_cnt", "uniq_cnt"],
    "pack": ["txn_insert_cnt", "microblock_cnt", "cu_consumed"],
    "bank": ["txn_exec_cnt", "txn_fail_cnt", "slot_cnt", "rpc_port"],
    "poh": ["hash_cnt", "mixin_cnt"],
    "shred": ["fec_set_cnt", "shred_tx_cnt", "shred_rx_cnt",
              "shred_parse_fail_cnt", "shred_sig_fail_cnt",
              "turbine_tx_cnt", "turbine_port"],
    "store": ["shred_store_cnt", "parse_fail_cnt", "complete_slot"],
    "sign": ["sign_cnt", "refuse_cnt"],
    "gossip": ["rx_pkt_cnt", "peer_cnt", "bound_port"],
    "repair": ["req_cnt", "served_cnt", "bound_port", "req_tx_cnt",
               "repaired_cnt", "resp_sig_fail_cnt"],
    "replay": ["replay_slot", "txn_replay_cnt", "dead_slot_cnt",
               "ghost_head", "root_slot", "vote_cnt"],
    "metric": [],
    "sink": ["frag_cnt"],
}

BLOCK_SLOTS = 64  # fixed block size per tile, room to grow every kind


def slot_names(kind: str) -> list[str]:
    return MUX_SLOTS + TILE_SLOTS.get(kind, [])


def footprint() -> int:
    return BLOCK_SLOTS * 8


class MetricsBlock:
    """Writer/reader view of one tile's metrics block."""

    def __init__(self, buf: memoryview, off: int, kind: str):
        self._arr = np.frombuffer(buf, dtype=np.uint64, count=BLOCK_SLOTS,
                                  offset=off)
        self._idx = {n: i for i, n in enumerate(slot_names(kind))}
        self.kind = kind

    def add(self, name: str, delta: int = 1):
        i = self._idx[name]
        # single writer per block: read-modify-write is safe; the 8B store
        # is what readers observe atomically
        self._arr[i] += np.uint64(delta)

    def set(self, name: str, val: int):
        self._arr[self._idx[name]] = np.uint64(val)

    def get(self, name: str) -> int:
        return int(self._arr[self._idx[name]])

    def snapshot(self) -> dict[str, int]:
        return {n: int(self._arr[i]) for n, i in self._idx.items()}


def prometheus_render(tiles: dict[str, "MetricsBlock"]) -> str:
    """Render all tile blocks as Prometheus text exposition
    (ref: src/app/fdctl/run/tiles/fd_metric.c:232-263 prometheus_print)."""
    out = []
    seen = set()
    for tname, blk in tiles.items():
        kind = blk.kind
        for slot, val in blk.snapshot().items():
            metric = f"fdtpu_{slot}"
            if metric not in seen:
                out.append(f"# TYPE {metric} counter")
                seen.add(metric)
            out.append(f'{metric}{{tile="{tname}",kind="{kind}"}} {val}')
    return "\n".join(out) + "\n"
