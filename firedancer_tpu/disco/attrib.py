"""Per-link bottleneck attribution (ref: the reference monitor's
fctl/fseq diag rendering, src/app/fdctl/monitor/monitor.c:49-160 — which
link is backpressured, which consumer is slow, and one verdict line).

Pure reader over a joined topology: consumer-side state comes from each
(tile, in-link) fseq (seq + slow/ovrn diag, charged by the producer's
credit-stall loop in disco/mux.py), producer-side state from the mux's
out{j}_* housekeeping gauges (ring occupancy high-watermark, credit
low-watermark, publish rates).  Three consumers:

  * `fdtpuctl top`      — live terminal view (render_top)
  * /metrics            — producer->consumer labeled families
                          (link_families, via prometheus_render's extra=)
  * flight recorder     — link state at time of death (link_sample +
                          snapshot_verdict in the postmortem bundle)
"""

import time

from ..tango.ring import FSeq

# verdict thresholds: a consumer charged slow faster than this is THE
# bottleneck; else a ring whose occupancy high-watermark crossed this
# fraction of depth is close to stalling its producer
SLOW_RATE_HZ = 0.5
OCC_FRAC = 0.75

_REGIMES = ("busy_ns", "backp_ns", "house_ns", "idle_ns")

# leader-lane counters surfaced in `fdtpuctl top` (sharded pack steering,
# merge-point budget pressure, PoH speculation depth/hit rate)
_LEADER_KEYS = ("shard_steer_cnt", "pending",
                "merge_budget_defer_cnt", "merge_stall_cnt", "merge_q",
                "spec_hit_cnt", "spec_miss_cnt", "splice_dispatch_cnt",
                "spec_depth")


def producers_of(spec) -> dict[str, str]:
    """link name -> producing tile name."""
    out = {}
    for t in spec.tiles:
        for ln in t.out_links:
            out[ln] = t.name
    return out


def link_sample(jt) -> dict:
    """One attribution snapshot: per (link, consumer) the fseq-side
    state, per tile the regime counters + per-out-link gauges."""
    spec = jt.spec
    prod_of = producers_of(spec)
    s = {"t": time.monotonic_ns(), "links": {}, "tiles": {}}
    for t in spec.tiles:
        for il in t.in_links:
            fs = jt.fseq[(t.name, il.link)]
            jl = jt.links[il.link]
            s["links"][(il.link, t.name)] = {
                "producer": prod_of.get(il.link, "?"),
                "seq": fs.query(),
                "prod": jl.mcache.seq_query(),
                "depth": jl.spec.depth,
                "slow": fs.diag(FSeq.DIAG_SLOW_CNT),
                "ovrnp": fs.diag(FSeq.DIAG_OVRNP_CNT),
                "pub_cnt": fs.diag(FSeq.DIAG_PUB_CNT),
                "pub_sz": fs.diag(FSeq.DIAG_PUB_SZ),
            }
        m = jt.metrics[t.name].snapshot()
        tv = {k: m.get(k, 0) for k in
              _REGIMES + ("backp_cnt", "loop_cnt", "housekeep_cnt")}
        # leader-lane counters (sharded pack + PoH speculation), shown in
        # the `top` LEADER section when the topology runs those tiles
        for k in _LEADER_KEYS:
            if k in m:
                tv.setdefault("kv", {})[k] = m[k]
        tv["out"] = {}
        for oi, ln in enumerate(t.out_links[:4]):
            tv["out"][ln] = {
                "lag": m.get(f"out{oi}_lag", 0),
                "occ_hwm": m.get(f"out{oi}_occ_hwm", 0),
                "cr_lwm": m.get(f"out{oi}_cr_lwm", 0),
                "frag_rate": m.get(f"out{oi}_frag_rate", 0),
                "byte_rate": m.get(f"out{oi}_byte_rate", 0),
            }
        s["tiles"][t.name] = tv
    return s


def link_families(jt):
    """(name, kind, help, labels, value) samples for prometheus_render's
    `extra` hook: the per-link families, producer->consumer labeled."""
    s = link_sample(jt)
    out = []
    for (link, consumer), lv in s["links"].items():
        lab = {"link": link, "producer": lv["producer"],
               "consumer": consumer}
        out += [
            ("fdtpu_link_lag", "gauge",
             "frags the consumer trails the producer by", lab,
             max(lv["prod"] - lv["seq"], 0)),
            ("fdtpu_link_slow_cnt", "counter",
             "producer credit stalls attributed to this consumer", lab,
             lv["slow"]),
            ("fdtpu_link_ovrnp_cnt", "counter",
             "frags lost to producer overrun on this link", lab,
             lv["ovrnp"]),
            ("fdtpu_link_frag_cnt", "counter",
             "frags this consumer processed off the link", lab,
             lv["pub_cnt"]),
            ("fdtpu_link_sz", "counter",
             "payload bytes this consumer processed off the link", lab,
             lv["pub_sz"]),
        ]
    for tile, tv in s["tiles"].items():
        for link, ov in tv["out"].items():
            lab = {"link": link, "producer": tile}
            out += [
                ("fdtpu_link_occ_hwm", "gauge",
                 "ring occupancy high-watermark over the last window",
                 lab, ov["occ_hwm"]),
                ("fdtpu_link_cr_lwm", "gauge",
                 "producer credit low-watermark over the last window",
                 lab, ov["cr_lwm"]),
                ("fdtpu_link_frag_rate", "gauge",
                 "frags/s published over the last window", lab,
                 ov["frag_rate"]),
                ("fdtpu_link_byte_rate", "gauge",
                 "bytes/s published over the last window", lab,
                 ov["byte_rate"]),
            ]
    return out


def bottleneck(prev: dict, cur: dict) -> tuple[str, str]:
    """One-line verdict from two samples: ("<link>", "<reason>") — the
    link whose consumer is charging slow diag fastest, else the ring
    closest to full past the occupancy threshold, else the busiest tile
    (cpu-bound, no link pressure), else none."""
    dt = max((cur["t"] - prev["t"]) / 1e9, 1e-9)
    best = None  # (score, link_label, reason)
    for key, lv in cur["links"].items():
        link, consumer = key
        pv = prev["links"].get(key, lv)
        slow_rate = (lv["slow"] - pv["slow"]) / dt
        lag = max(lv["prod"] - lv["seq"], 0)
        occ = lag / max(lv["depth"], 1)
        label = f"{lv['producer']}->{consumer} ({link})"
        if slow_rate > SLOW_RATE_HZ:
            cand = (2e9 + slow_rate, label,
                    f"slow consumer {consumer} "
                    f"({slow_rate:.1f} stalls/s, lag {lag}/{lv['depth']})")
        elif occ >= OCC_FRAC:
            cand = (1e9 + occ, label,
                    f"ring {occ:.0%} full (lag {lag}/{lv['depth']})")
        else:
            continue
        if best is None or cand[0] > best[0]:
            best = cand
    if best is not None:
        return best[1], best[2]
    # no link pressure: name the busiest tile so "what would I scale
    # next" still has an answer
    busiest = None
    for tile, tv in cur["tiles"].items():
        pv = prev["tiles"].get(tile, tv)
        busy = tv["busy_ns"] - pv["busy_ns"]
        total = sum(tv[r] - pv[r] for r in _REGIMES)
        if total <= 0:
            continue
        frac = busy / total
        if busiest is None or frac > busiest[0]:
            busiest = (frac, tile)
    if busiest is not None and busiest[0] > 0.5:
        return "none", (f"no link pressure; busiest tile "
                        f"{busiest[1]} ({busiest[0]:.0%} busy)")
    return "none", "no backpressure observed"


def snapshot_verdict(sample: dict) -> tuple[str, str]:
    """bottleneck() without a prior sample (postmortem bundles): grades
    cumulative slow counts + instantaneous occupancy."""
    best = None
    for key, lv in sample["links"].items():
        link, consumer = key
        lag = max(lv["prod"] - lv["seq"], 0)
        occ = lag / max(lv["depth"], 1)
        label = f"{lv['producer']}->{consumer} ({link})"
        if lv["slow"] > 0:
            cand = (2e9 + lv["slow"], label,
                    f"slow consumer {consumer} ({lv['slow']} stalls "
                    f"total, lag {lag}/{lv['depth']})")
        elif occ >= OCC_FRAC:
            cand = (1e9 + occ, label,
                    f"ring {occ:.0%} full (lag {lag}/{lv['depth']})")
        else:
            continue
        if best is None or cand[0] > best[0]:
            best = cand
    if best is not None:
        return best[1], best[2]
    return "none", "no backpressure observed"


def render_top(spec, prev: dict, cur: dict) -> list[str]:
    """The `fdtpuctl top` frame: per-tile regime split, per-link lag and
    stall attribution, one bottleneck verdict line."""
    dt = max((cur["t"] - prev["t"]) / 1e9, 1e-9)
    lines = [f"fdtpu top — {spec.app}  (interval {dt:.2f}s, "
             "ctrl-c to exit)", ""]
    lines.append(f"{'TILE':<14}{'busy%':>7}{'backp%':>7}{'house%':>7}"
                 f"{'idle%':>7}{'backp/s':>9}")
    for tile, tv in cur["tiles"].items():
        pv = prev["tiles"].get(tile, tv)
        d = {r: tv[r] - pv[r] for r in _REGIMES}
        total = sum(d.values())

        def _pct(r):
            return f"{100 * d[r] / total:.0f}" if total > 0 else "-"

        backp_rate = (tv["backp_cnt"] - pv["backp_cnt"]) / dt
        lines.append(f"{tile:<14}{_pct('busy_ns'):>7}{_pct('backp_ns'):>7}"
                     f"{_pct('house_ns'):>7}{_pct('idle_ns'):>7}"
                     f"{backp_rate:>9,.0f}")
    lines.append("")
    lines.append(f"{'LINK':<34}{'rate/s':>10}{'lag':>8}{'occ%':>6}"
                 f"{'slow/s':>8}{'ovrn/s':>8}")
    for key, lv in cur["links"].items():
        link, consumer = key
        pv = prev["links"].get(key, lv)
        lag = max(lv["prod"] - lv["seq"], 0)
        occ = 100 * lag // max(lv["depth"], 1)
        lines.append(
            f"{lv['producer'] + '->' + consumer + ' (' + link + ')':<34}"
            f"{(lv['seq'] - pv['seq']) / dt:>10,.0f}"
            f"{lag:>8,}{occ:>6}"
            f"{(lv['slow'] - pv['slow']) / dt:>8,.1f}"
            f"{(lv['ovrnp'] - pv['ovrnp']) / dt:>8,.1f}")
    rows = [(t, tv["kv"]) for t, tv in cur["tiles"].items()
            if tv.get("kv")]
    if rows:
        lines.append("")
        lines.append("LEADER")
        for tile, kv in rows:
            pkv = prev["tiles"].get(tile, {}).get("kv", kv)
            parts = []
            for k, v in kv.items():
                if k.endswith("_cnt"):
                    parts.append(
                        f"{k[:-4]}/s {(v - pkv.get(k, v)) / dt:,.0f}")
                else:
                    parts.append(f"{k} {v:,}")
            lines.append(f"  {tile:<14}" + "  ".join(parts))
    lines.append("")
    link, reason = bottleneck(prev, cur)
    lines.append(f"bottleneck: {link} ({reason})")
    return lines
