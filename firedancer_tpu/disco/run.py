"""Supervised topology boot (ref: src/app/fdctl/run/run.c — clone per tile,
src/disco/topo/fd_topo_run.c:50-130 — join wksps -> init -> run loop; the
pidns parent waits on children and tears the whole validator down if any
tile dies, run.c:279).

TPU-native shape: one OS process per tile (multiprocessing 'spawn' so each
child gets a fresh JAX runtime), shared-memory topology joined by replaying
the deterministic layout, supervision by (a) child exit -> teardown and
(b) cnc heartbeat staleness -> teardown.  Halt is cooperative: the
supervisor raises HALT on every cnc and joins.
"""

import multiprocessing as mp
import os
import time

from ..tango.ring import Cnc
from ..utils import log
from . import topo as topo_mod
from .mux import Mux
from .topo import TopoSpec


def _tile_main(spec: TopoSpec, tile_name: str):
    """Child entry: join workspace, build the vtable, run the mux loop.

    With FDTPU_PROFILE_DIR set, the whole tile loop runs under cProfile
    and dumps <dir>/<tile>.pstats at exit — the `fdtpudev flame`
    per-tile profiling hook (ref: src/app/fddev/flame.c wraps perf
    record per tile; cProfile is the in-language equivalent)."""
    # tiles that touch jax must run on CPU unless told otherwise; the
    # verify tile picks its own device via cfg
    from .tiles import TILES
    # tiles READ the persistent XLA cache but never write it (this
    # jaxlib's cache-write serialization segfaults sporadically on large
    # CPU executables — a dead tile mid-boot is the worse failure mode)
    os.environ.setdefault("FDTPU_XLA_CACHE_READONLY", "1")
    # debug-attach hook (the fddbg role, src/app/fddbg/main.c — there a
    # gdb-capability wrapper; here the Python-process analogue): SIGUSR1
    # dumps every thread's stack to stderr WITHOUT stopping the tile, so
    # `fdtpudbg stack` can inspect a live or wedged topology
    import faulthandler
    import signal as _signal
    faulthandler.register(_signal.SIGUSR1, all_threads=True, chain=False)
    prof_dir = os.environ.get("FDTPU_PROFILE_DIR")
    prof = None
    if prof_dir:
        import cProfile
        import signal
        import sys
        prof = cProfile.Profile()
        prof.enable()
        # a stuck tile is terminate()d by the supervisor (halt() escalation);
        # default SIGTERM exits without unwinding and the profile — of
        # exactly the tile worth profiling — would vanish.  Convert to a
        # normal exit so the finally-dump below runs.
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    jt = topo_mod.join(spec)
    try:
        ts = jt.tile_spec(tile_name)
        # per-tile CPU pinning (ref: fd_topo_run_tile's fd_tile_exec cpu
        # assignment + the [layout] affinity knob): cfg cpu_idx is threaded
        # in by topo.assign_affinity; modulo cpu_count so a layout written
        # for a bigger host still boots on a smaller one
        cpu = ts.cfg.get("cpu_idx")
        if cpu is not None and hasattr(os, "sched_setaffinity"):
            try:
                os.sched_setaffinity(0, {int(cpu) % os.cpu_count()})
            except OSError:
                log.warning("tile %s: cpu pin %s failed", tile_name, cpu)
        vt = TILES[ts.kind]()
        Mux(jt, tile_name, vt).run()
    finally:
        # drop tile-held dcache views (packed-wire tiles pin row views)
        # before the workspace unmaps, else SharedMemory.__del__ whines
        # "exported pointers exist" at interpreter exit
        vt = None
        jt.close()
        if prof is not None:
            prof.disable()
            os.makedirs(prof_dir, exist_ok=True)
            prof.dump_stats(os.path.join(prof_dir, f"{tile_name}.pstats"))


class MetricsHttpServer:
    """In-process Prometheus scrape target over a joined topology.

    GET /metrics — text exposition of every tile's shm metrics block
    (counters, gauges, and le-bucketed histograms).  GET /healthz — 200
    iff every tile's cnc is in RUN with a fresh heartbeat, else 503 with
    the offending tiles listed (ref: fd_metric.c's http listener plus
    the fdctl status probe, folded into one endpoint).  Runs on a
    daemon thread: readers only touch shm, never the tile loops.
    """

    def __init__(self, jt, host: str = "127.0.0.1", port: int = 0,
                 stale_ns: int = 60_000_000_000):
        import http.server
        import threading
        from . import metrics as metrics_mod

        def health() -> tuple[int, bytes]:
            bad = []
            for name, cnc in jt.cnc.items():
                sig = cnc.signal_query()
                if sig != Cnc.SIGNAL_RUN:
                    bad.append(f"{name}: signal={sig}")
                    continue
                hb = cnc.heartbeat_query()
                if hb and time.monotonic_ns() - hb > stale_ns:
                    bad.append(f"{name}: stale heartbeat")
            if bad:
                return 503, ("unhealthy\n" + "\n".join(bad) + "\n").encode()
            return 200, b"ok\n"

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                ctype = "text/plain"
                if path == "/healthz":
                    code, body = health()
                elif path in ("/", "/metrics"):
                    code = 200
                    body = metrics_mod.prometheus_render(jt.metrics).encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    code, body = 404, b"not found\n"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes arrive every few seconds
                pass

        self.httpd = http.server.ThreadingHTTPServer((host, port), H)
        self.port = self.httpd.server_address[1]  # resolved when port=0
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, name="fdtpu:metrics-http",
            daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TopoRun:
    """Handle to a running topology (the supervisor side)."""

    HEARTBEAT_STALE_NS = 60_000_000_000  # 60s (uncached device dispatches
    # can stall a Python tile loop for seconds; compiles happen pre-RUN)

    def __init__(self, spec: TopoSpec, start: bool = True,
                 metrics_port: int | None = None):
        self.spec = spec.validate()
        self.jt = topo_mod.create(spec)
        self.procs: dict[str, mp.process.BaseProcess] = {}
        self._mpctx = mp.get_context("spawn")
        # metrics_port: None = no http endpoint, 0 = ephemeral (resolved
        # port on self.metrics_port), N = fixed
        self.http: MetricsHttpServer | None = None
        if metrics_port is not None:
            self.http = MetricsHttpServer(
                self.jt, port=metrics_port,
                stale_ns=self.HEARTBEAT_STALE_NS)
        if start:
            self.start()

    @property
    def metrics_port(self) -> int | None:
        return self.http.port if self.http is not None else None

    def start(self):
        for t in self.spec.tiles:
            p = self._mpctx.Process(
                target=_tile_main, args=(self.spec, t.name),
                name=f"fdtpu:{t.name}", daemon=True)
            p.start()
            self.procs[t.name] = p

    # -- supervision ------------------------------------------------------
    def wait_ready(self, timeout: float = 120.0):
        """Block until every tile signals RUN (ref fd_cnc wait in topo boot)."""
        deadline = time.monotonic() + timeout
        for name, cnc in self.jt.cnc.items():
            while cnc.signal_query() != Cnc.SIGNAL_RUN:
                if not self.procs[name].is_alive():
                    raise RuntimeError(f"tile {name} died during boot")
                if time.monotonic() > deadline:
                    raise TimeoutError(f"tile {name} failed to boot")
                time.sleep(0.01)

    def poll(self) -> str | None:
        """One supervision scan; returns the name of a failed tile or None."""
        now = time.monotonic_ns()
        for name, p in self.procs.items():
            if not p.is_alive():
                return name
            hb = self.jt.cnc[name].heartbeat_query()
            if hb and now - hb > self.HEARTBEAT_STALE_NS:
                return name
        return None

    def supervise(self, poll_s: float = 0.1):
        """Run until a tile fails, then tear everything down (fail-fast,
        ref run.c:279)."""
        try:
            while True:
                bad = self.poll()
                if bad is not None:
                    log.warning("tile %s failed; tearing down topology", bad)
                    return bad
                time.sleep(poll_s)
        finally:
            self.halt()

    def metrics(self, tile: str) -> dict:
        return self.jt.metrics[tile].snapshot()

    # -- shutdown ---------------------------------------------------------
    def halt(self, timeout: float = 10.0):
        for cnc in self.jt.cnc.values():
            cnc.signal(Cnc.SIGNAL_HALT)
        deadline = time.monotonic() + timeout
        for name, p in self.procs.items():
            p.join(max(0.0, deadline - time.monotonic()))
            if p.is_alive():
                p.terminate()
                p.join(1.0)
                if p.is_alive():
                    p.kill()
                    p.join(1.0)

    def close(self):
        self.halt()
        if self.http is not None:
            self.http.close()
            self.http = None
        self.jt.close()
        self.jt.unlink()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
