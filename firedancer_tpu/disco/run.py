"""Supervised topology boot (ref: src/app/fdctl/run/run.c — clone per tile,
src/disco/topo/fd_topo_run.c:50-130 — join wksps -> init -> run loop; the
pidns parent waits on children and tears the whole validator down if any
tile dies, run.c:279).

TPU-native shape: one OS process per tile (multiprocessing 'spawn' so each
child gets a fresh JAX runtime), shared-memory topology joined by replaying
the deterministic layout, supervision by (a) child exit and (b) cnc
heartbeat staleness.  The response is policy-driven (SupervisionPolicy,
the [supervision] config section): `fail_fast` keeps the reference's
tear-everything-down behavior; `respawn` restarts the failed tile into
the SAME workspace with exponential backoff + jitter under a per-tile
restart budget, evicting the corpse's fseq credits while it is down so
producers don't stall.  Halt is cooperative: the supervisor raises HALT
on every cnc and joins.
"""

import json
import multiprocessing as mp
import os
import time
import zlib
from dataclasses import dataclass, field

from ..tango.fctl import Fctl
from ..tango.ring import Cnc
from ..utils import log
from . import topo as topo_mod
from .mux import Mux
from .topo import TopoSpec


@dataclass
class SupervisionPolicy:
    """Per-topology supervision knobs ([supervision] in config.py).

    Pickles into tile children (it rides in TopoRun's spawn args closure
    only on the supervisor side), so keep it plain data."""

    restart_policy: str = "fail_fast"   # fail_fast (ref run.c:279) | respawn
    max_restarts: int = 5               # per-tile budget under respawn
    backoff_initial_s: float = 0.25     # exponential: initial, cap, jitter
    backoff_max_s: float = 8.0
    backoff_jitter: float = 0.2         # +/- fraction of the delay
    boot_grace_s: float = 300.0         # no staleness checks while booting
    heartbeat_stale_s: float = 60.0     # default staleness -> failed
    heartbeat_stale_by_kind: dict = field(default_factory=dict)
    # graceful degradation (consumed by the verify tile's GuardedVerifier)
    device_fail_threshold: int = 3
    device_retry: int = 1
    device_deadline_s: float = 30.0
    device_reprobe_s: float = 5.0
    # drain protocol: per-tile graceful-quiesce budget for rolling
    # restarts and SIGTERM/SIGINT topology drains.  0 (the default)
    # keeps drain fully disarmed — bit-identical behavior to a world
    # without it (crash-respawn and abrupt halt only).
    drain_timeout_s: float = 0.0
    drain_manifest_dir: str = ""

    @classmethod
    def from_cfg(cls, cfg: dict) -> "SupervisionPolicy":
        sup = dict(cfg.get("supervision") or {})
        by_kind = {k: float(v)
                   for k, v in (sup.get("heartbeat_stale") or {}).items()}
        return cls(
            restart_policy=str(sup.get("restart_policy", "fail_fast")),
            max_restarts=int(sup.get("max_restarts", 5)),
            backoff_initial_s=float(sup.get("backoff_initial_s", 0.25)),
            backoff_max_s=float(sup.get("backoff_max_s", 8.0)),
            backoff_jitter=float(sup.get("backoff_jitter", 0.2)),
            boot_grace_s=float(sup.get("boot_grace_s", 300.0)),
            heartbeat_stale_s=float(sup.get("heartbeat_stale_s", 60.0)),
            heartbeat_stale_by_kind=by_kind,
            device_fail_threshold=int(sup.get("device_fail_threshold", 3)),
            device_retry=int(sup.get("device_retry", 1)),
            device_deadline_s=float(sup.get("device_deadline_s", 30.0)),
            device_reprobe_s=float(sup.get("device_reprobe_s", 5.0)),
            drain_timeout_s=float(sup.get("drain_timeout_s", 0.0)),
            drain_manifest_dir=str(sup.get("drain_manifest_dir", "")))

    def stale_ns(self, kind: str | None = None) -> int:
        """Heartbeat staleness threshold for a tile kind (verify tiles
        doing uncached device dispatches legitimately stall longer than
        net/sink tiles, so [supervision.heartbeat_stale] overrides the
        default per kind)."""
        s = self.heartbeat_stale_by_kind.get(kind, self.heartbeat_stale_s)
        return int(s * 1e9)

    def backoff_s(self, attempt: int, tile_name: str = "") -> float:
        """Exponential backoff with deterministic per-(tile, attempt)
        jitter — reproducible chaos runs need a reproducible supervisor,
        so the jitter is a hash, not an rng draw."""
        base = min(self.backoff_initial_s * (2 ** max(0, attempt - 1)),
                   self.backoff_max_s)
        if not self.backoff_jitter:
            return base
        h = zlib.crc32(f"{tile_name}#{attempt}".encode()) / 0xFFFFFFFF
        return base * (1.0 + self.backoff_jitter * (2.0 * h - 1.0))


def dependency_order(spec: TopoSpec) -> list[str]:
    """Tiles in producer->consumer topological order (source first):
    draining in this order parks each tile's upstream before the tile
    itself, so its DRAIN admission snapshot covers everything ever
    published to it and the quiesce runs genuinely dry."""
    prod = {}
    for t in spec.tiles:
        for ln in t.out_links:
            prod[ln] = t.name
    deps = {t.name: {prod[il.link] for il in t.in_links
                     if il.link in prod and prod[il.link] != t.name}
            for t in spec.tiles}
    order: list[str] = []
    done: set[str] = set()
    while len(order) < len(deps):
        ready = [t.name for t in spec.tiles
                 if t.name not in done and deps[t.name] <= done]
        if not ready:  # cycle: fall back to spec order
            ready = [t.name for t in spec.tiles if t.name not in done]
        order += ready
        done.update(ready)
    return order


def _tile_main(spec: TopoSpec, tile_name: str, restart_cnt: int = 0):
    """Child entry: join workspace, build the vtable, run the mux loop.

    With FDTPU_PROFILE_DIR set, the whole tile loop runs under cProfile
    and dumps <dir>/<tile>.pstats at exit — the `fdtpudev flame`
    per-tile profiling hook (ref: src/app/fddev/flame.c wraps perf
    record per tile; cProfile is the in-language equivalent)."""
    # tiles that touch jax must run on CPU unless told otherwise; the
    # verify tile picks its own device via cfg
    from .tiles import TILES
    # log attribution: every record from this process carries tile name +
    # restart generation, so a respawned child's lines are separable from
    # its corpse's in an interleaved supervisor log
    log.set_context(tile_name, restart_cnt)
    # tiles READ the persistent XLA cache but never write it (this
    # jaxlib's cache-write serialization segfaults sporadically on large
    # CPU executables — a dead tile mid-boot is the worse failure mode)
    os.environ.setdefault("FDTPU_XLA_CACHE_READONLY", "1")
    # debug-attach hook (the fddbg role, src/app/fddbg/main.c — there a
    # gdb-capability wrapper; here the Python-process analogue): SIGUSR1
    # dumps every thread's stack to stderr WITHOUT stopping the tile, so
    # `fdtpudbg stack` can inspect a live or wedged topology
    import faulthandler
    import signal as _signal
    faulthandler.register(_signal.SIGUSR1, all_threads=True, chain=False)
    prof_dir = os.environ.get("FDTPU_PROFILE_DIR")
    prof = None
    if prof_dir:
        import cProfile
        import signal
        import sys
        prof = cProfile.Profile()
        prof.enable()
        # a stuck tile is terminate()d by the supervisor (halt() escalation);
        # default SIGTERM exits without unwinding and the profile — of
        # exactly the tile worth profiling — would vanish.  Convert to a
        # normal exit so the finally-dump below runs.
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    jt = topo_mod.join(spec)
    try:
        ts = jt.tile_spec(tile_name)
        # per-tile CPU pinning (ref: fd_topo_run_tile's fd_tile_exec cpu
        # assignment + the [layout] affinity knob): cfg cpu_idx is threaded
        # in by topo.assign_affinity; modulo cpu_count so a layout written
        # for a bigger host still boots on a smaller one
        cpu = ts.cfg.get("cpu_idx")
        if cpu is not None and hasattr(os, "sched_setaffinity"):
            try:
                os.sched_setaffinity(0, {int(cpu) % os.cpu_count()})
            except OSError:
                log.warning("tile %s: cpu pin %s failed", tile_name, cpu)
        vt = TILES[ts.kind]()
        Mux(jt, tile_name, vt, restart_cnt=restart_cnt).run()
    finally:
        # drop tile-held dcache views (packed-wire tiles pin row views)
        # before the workspace unmaps, else SharedMemory.__del__ whines
        # "exported pointers exist" at interpreter exit
        vt = None
        jt.close()
        if prof is not None:
            prof.disable()
            os.makedirs(prof_dir, exist_ok=True)
            prof.dump_stats(os.path.join(prof_dir, f"{tile_name}.pstats"))


class MetricsHttpServer:
    """In-process Prometheus scrape target over a joined topology.

    GET /metrics — text exposition of every tile's shm metrics block
    (counters, gauges, and le-bucketed histograms).  GET /healthz — three
    states (ref: fd_metric.c's http listener plus the fdctl status probe,
    folded into one endpoint):

        503 "unhealthy\\n<tiles>"  a tile is not in RUN or its heartbeat
                                  is stale (per-kind threshold when a
                                  SupervisionPolicy is supplied)
        200 "degraded\\n<tiles>"  every tile is live but a verify tile is
                                  serving verdicts off the CPU fallback
                                  (degraded_mode gauge set) — the load
                                  balancer should keep routing, the
                                  operator should look
        200 "shedding\\n<tiles>"  every tile is live and verifying, but a
                                  front-door tile (net/quic) is actively
                                  shedding load (conn caps, rate limits,
                                  reasm budgets) — capacity alarm, not an
                                  outage
        200 "ok\\n"               fully healthy

    Runs on a daemon thread: readers only touch shm, never the tile loops.
    """

    def __init__(self, jt, host: str = "127.0.0.1", port: int = 0,
                 stale_ns: int = 60_000_000_000,
                 policy: "SupervisionPolicy | None" = None,
                 slo_target_ms: float = 2.0):
        import http.server
        import threading
        from . import attrib
        from . import metrics as metrics_mod
        from . import slo as slo_mod

        kinds = {t.name: t.kind for t in jt.spec.tiles}

        def _slo_line() -> bytes:
            # degraded latency visible without a trace dump; guarded —
            # a scrape must never take the health endpoint down
            try:
                return (slo_mod.healthz_field(jt, slo_target_ms)
                        + "\n").encode()
            except Exception:
                return b"slo unavailable\n"

        def _stale(name: str) -> int:
            if policy is not None:
                return policy.stale_ns(kinds.get(name))
            return stale_ns

        def health() -> tuple[int, bytes]:
            bad, degraded, shedding, draining = [], [], [], []
            for name, cnc in jt.cnc.items():
                sig = cnc.signal_query()
                if sig in (Cnc.SIGNAL_DRAIN, Cnc.SIGNAL_DRAINED):
                    # mid-drain (rolling restart / graceful shutdown):
                    # live by construction while heartbeating — an
                    # operational event, not an outage
                    hb = cnc.heartbeat_query()
                    if hb and time.monotonic_ns() - hb > _stale(name):
                        bad.append(f"{name}: stale heartbeat (draining)")
                    else:
                        draining.append(name)
                    continue
                if sig != Cnc.SIGNAL_RUN:
                    bad.append(f"{name}: signal={sig}")
                    continue
                hb = cnc.heartbeat_query()
                if hb and time.monotonic_ns() - hb > _stale(name):
                    bad.append(f"{name}: stale heartbeat")
                    continue
                blk = jt.metrics.get(name)
                if blk is None:
                    continue
                if blk.has("degraded_mode") and blk.get("degraded_mode"):
                    degraded.append(name)
                if blk.has("shedding") and blk.get("shedding"):
                    shedding.append(name)
            if bad:
                return 503, ("unhealthy\n" + "\n".join(bad)
                             + "\n").encode() + _slo_line()
            if degraded:
                return 200, ("degraded\n" + "\n".join(degraded)
                             + "\n").encode() + _slo_line()
            if shedding:
                # front-door overload shed (conn caps / rate limits /
                # reasm budgets active): still serving — capacity signal
                return 200, ("shedding\n" + "\n".join(shedding)
                             + "\n").encode() + _slo_line()
            if draining:
                return 200, ("draining\n" + "\n".join(draining)
                             + "\n").encode() + _slo_line()
            return 200, b"ok\n" + _slo_line()

        # supervisor-side extra metric families (autotune decision
        # counters + knob gauges, flightrec evictions): installed after
        # construction via `self.extra_fn = callable -> iterable of
        # prometheus_render extra tuples`
        self.extra_fn = None
        srv = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                ctype = "text/plain"
                if path == "/healthz":
                    code, body = health()
                elif path in ("/", "/metrics"):
                    code = 200
                    try:
                        extra = list(attrib.link_families(jt))
                        if srv.extra_fn is not None:
                            extra += list(srv.extra_fn())
                    except Exception:
                        extra = None
                    body = metrics_mod.prometheus_render(
                        jt.metrics, extra=extra).encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    code, body = 404, b"not found\n"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes arrive every few seconds
                pass

        self.httpd = http.server.ThreadingHTTPServer((host, port), H)
        self.port = self.httpd.server_address[1]  # resolved when port=0
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, name="fdtpu:metrics-http",
            daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TopoRun:
    """Handle to a running topology (the supervisor side)."""

    HEARTBEAT_STALE_NS = 60_000_000_000  # 60s (uncached device dispatches
    # can stall a Python tile loop for seconds; compiles happen pre-RUN)

    def __init__(self, spec: TopoSpec, start: bool = True,
                 metrics_port: int | None = None,
                 policy: SupervisionPolicy | None = None,
                 flight_dir: str = "", slo_target_ms: float = 2.0,
                 config: dict | None = None):
        self.spec = spec.validate()
        self.jt = topo_mod.create(spec)
        self.procs: dict[str, mp.process.BaseProcess] = {}
        self._mpctx = mp.get_context("spawn")
        self.policy = policy or SupervisionPolicy(
            heartbeat_stale_s=self.HEARTBEAT_STALE_NS / 1e9)
        self._kind = {t.name: t.kind for t in self.spec.tiles}
        self.restarts: dict[str, int] = {}      # respawns done per tile
        self._boot_deadline: dict[str, float] = {}
        self._evicting: set[str] = set()        # respawned, not yet RUN
        self._draining: set[str] = set()        # mid rolling-restart
        self._drain_req = False                 # SIGTERM/SIGINT -> drain
        self._halting = False
        # flight recorder ([observability] flight_dir): postmortem
        # bundles on crash/degrade/respawn/SIGUSR2; "" disables
        self.flight_dir = flight_dir
        self.slo_target_ms = slo_target_ms
        self.config = config
        self.events: list[str] = []             # supervisor event log
        self._dump_req = False                  # SIGUSR2 -> dump next scan
        self._degraded: set[str] = set()        # tiles seen in degraded
        obs = (config or {}).get("observability") or {}
        self.flight_max_bundles = int(obs.get("flight_max_bundles", 16))
        self._flight_evicts = 0                 # bundles rotated away
        self.manifest_corrupt_cnt = 0           # torn drain receipts seen
        if flight_dir:
            self._install_dump_signal()
        if self.policy.drain_timeout_s > 0:
            self._install_term_signals()
        # metrics_port: None = no http endpoint, 0 = ephemeral (resolved
        # port on self.metrics_port), N = fixed
        self.http: MetricsHttpServer | None = None
        if metrics_port is not None:
            self.http = MetricsHttpServer(
                self.jt, port=metrics_port,
                stale_ns=self.HEARTBEAT_STALE_NS, policy=self.policy,
                slo_target_ms=slo_target_ms)
        # closed-loop autotuner ([autotune] enabled = 1): default-off —
        # unarmed, nothing here runs and no knob pod is ever written
        self.autotuner = None
        acfg = (config or {}).get("autotune") or {}
        if int(acfg.get("enabled", 0) or 0):
            from .autotune import Autotuner
            self.autotuner = Autotuner(self, acfg,
                                       target_ms=slo_target_ms,
                                       log_dir=flight_dir)
        if self.http is not None:
            self.http.extra_fn = self._extra_families
        if start:
            self.start()

    def _extra_families(self):
        """Supervisor-side metric families for the /metrics endpoint."""
        out = [("fdtpu_flightrec_evict_cnt", "counter",
                "flight bundles rotated away (flight_max_bundles)", {},
                self._flight_evicts),
               ("fdtpu_manifest_corrupt_cnt", "counter",
                "drain manifests rejected as torn/corrupt (crash-eviction "
                "fallback taken)", {}, self.manifest_corrupt_cnt)]
        if self.autotuner is not None:
            out += self.autotuner.families()
        return out

    def _load_drain_manifest(self, name: str):
        """Load + validate `name`'s drain-cursor manifest (written by the
        mux at DRAINED — disco/mux.py _write_drain_manifest).

        Returns the manifest dict, None if no manifest dir is configured
        or the file simply doesn't exist, or raises ValueError if the
        file is present but torn/corrupt — truncated JSON, wrong tile,
        non-integer cursors.  The caller treats corrupt as a failed
        drain receipt: bounded-loss crash-eviction respawn instead of
        trusting cursors that may describe a different (or partial)
        quiesce point; duplicates stay impossible because the crash path
        never rewinds consumer fseqs."""
        d = self.policy.drain_manifest_dir or os.environ.get(
            "FDTPU_DRAIN_DIR", "")
        if not d:
            return None
        path = os.path.join(d, name.replace(":", "_") + ".manifest.json")
        try:
            with open(path) as f:
                raw = f.read()
        except OSError:
            return None
        try:
            m = json.loads(raw)
        except ValueError as e:
            raise ValueError(f"torn JSON: {e}") from None
        if not isinstance(m, dict) or m.get("tile") != name:
            raise ValueError("manifest tile mismatch")
        for sect in ("cursors", "outs"):
            c = m.get(sect)
            if not isinstance(c, dict) or not all(
                    isinstance(v, int) and v >= 0 for v in c.values()):
                raise ValueError(f"bad {sect} table")
        return m

    def _install_dump_signal(self):
        """SIGUSR2 -> write a bundle at the next supervision scan (an
        operator snapshot of a LIVE topology; signals only bind in the
        main thread, and a test-thread supervisor just won't have the
        hook)."""
        import signal
        import threading
        if threading.current_thread() is not threading.main_thread():
            return
        signal.signal(signal.SIGUSR2,
                      lambda *_: setattr(self, "_dump_req", True))

    def _install_term_signals(self):
        """SIGTERM/SIGINT -> graceful topology drain at the next
        supervision scan, instead of the abrupt child kill the default
        handlers produce.  Only armed when [supervision] drain_timeout_s
        is set (drain configured), and only in the main thread — same
        constraint as the SIGUSR2 hook.  SIGUSR2 keeps working mid-drain:
        the dump request is checked every scan, including the drain
        pass."""
        import signal
        import threading
        if threading.current_thread() is not threading.main_thread():
            return

        def _req(signum, _frame):
            # second signal = operator insisting: restore the default
            # and let it through (abrupt teardown escape hatch)
            self._drain_req = True
            signal.signal(signum, signal.SIG_DFL)

        signal.signal(signal.SIGTERM, _req)
        signal.signal(signal.SIGINT, _req)

    def _log_event(self, msg: str):
        self.events.append(
            f"{time.strftime('%H:%M:%S', time.gmtime())} {msg}")
        del self.events[:-500]  # bounded: the bundle tails it anyway

    def flight_dump(self, reason: str, tile: str = "") -> str | None:
        """Write a postmortem bundle (no-op without flight_dir); never
        raises — the flight recorder must not take the supervisor down
        with it."""
        if not self.flight_dir:
            return None
        try:
            from . import flightrec
            path = flightrec.write_bundle(
                self.flight_dir, self.jt, reason=reason, tile=tile,
                restarts=self.restarts, config=self.config,
                events=self.events,
                autotune=(self.autotuner.decisions
                          if self.autotuner is not None else None))
            self._flight_evicts += flightrec.rotate(
                self.flight_dir, self.flight_max_bundles)
            self._log_event(f"flight bundle {reason} -> {path}")
            log.warning("flight recorder: %s bundle -> %s", reason, path)
            return path
        except Exception as e:  # pragma: no cover - defensive
            log.warning("flight recorder failed (%s): %s", reason, e)
            return None

    @property
    def metrics_port(self) -> int | None:
        return self.http.port if self.http is not None else None

    def start(self):
        for t in self.spec.tiles:
            self._spawn(t.name)

    def _spawn(self, name: str, restart_cnt: int = 0):
        cnc = self.jt.cnc[name]
        if restart_cnt:
            # the corpse may have died in RUN with a stale heartbeat; a
            # respawn must present as BOOTING (health checks and poll()
            # apply boot-grace, not staleness, until it signals RUN)
            cnc.signal(Cnc.SIGNAL_BOOT)
            cnc.heartbeat(time.monotonic_ns())
        p = self._mpctx.Process(
            target=_tile_main, args=(self.spec, name, restart_cnt),
            name=f"fdtpu:{name}", daemon=True)
        p.start()
        self.procs[name] = p
        self._log_event(f"spawn {name} gen={restart_cnt} pid={p.pid}")
        self._boot_deadline[name] = time.monotonic() + self.policy.boot_grace_s

    # -- supervision ------------------------------------------------------
    def wait_ready(self, timeout: float = 120.0):
        """Block until every tile signals RUN (ref fd_cnc wait in topo boot)."""
        if not self.procs:
            raise RuntimeError(
                "topology not started (constructed with start=False; "
                "call start() first)")
        deadline = time.monotonic() + timeout
        for name, cnc in self.jt.cnc.items():
            while cnc.signal_query() != Cnc.SIGNAL_RUN:
                if not self.procs[name].is_alive():
                    raise RuntimeError(f"tile {name} died during boot")
                if time.monotonic() > deadline:
                    raise TimeoutError(f"tile {name} failed to boot")
                time.sleep(0.01)

    def poll(self) -> str | None:
        """One supervision scan; returns the name of a failed tile or None.

        Failure = dead process, heartbeat older than the per-kind
        staleness threshold (policy.stale_ns), or a tile wedged in BOOT
        past its boot-grace window.  A booting tile is exempt from
        heartbeat staleness — compiles happen pre-RUN."""
        now_ns = time.monotonic_ns()
        now = time.monotonic()
        for name, p in list(self.procs.items()):
            if name in self._draining:
                # mid rolling-restart: the tile is intentionally parked
                # (or reaped, between HALT and respawn) — the drain path
                # owns its lifecycle and bounds it with drain_timeout_s
                continue
            if not p.is_alive():
                return name
            cnc = self.jt.cnc[name]
            sig = cnc.signal_query()
            if sig in (Cnc.SIGNAL_DRAIN, Cnc.SIGNAL_DRAINED):
                # draining outside the supervisor's own bookkeeping
                # (operator signal): live while heartbeating
                hb = cnc.heartbeat_query()
                if hb and now_ns - hb > self.policy.stale_ns(
                        self._kind.get(name)):
                    return name
                continue
            if sig != Cnc.SIGNAL_RUN:
                bd = self._boot_deadline.get(name)
                if bd is not None and now > bd:
                    return name
                continue
            hb = cnc.heartbeat_query()
            if hb and now_ns - hb > self.policy.stale_ns(self._kind.get(name)):
                return name
        return None

    def supervise(self, poll_s: float = 0.1):
        """Run the supervision loop.

        fail_fast (default, ref run.c:279): return the first failed tile
        and tear everything down.  respawn: restart the failed tile with
        exponential backoff + jitter until its restart budget is spent,
        evicting its consumer fseqs while it is down so producers don't
        stall on the corpse's frozen credits; over-budget failures fall
        back to fail_fast.  Returns the tile that exhausted the policy,
        or None if halted externally."""
        try:
            while True:
                if self._halting:
                    return None
                if self._dump_req:
                    self._dump_req = False
                    self.flight_dump("sigusr2")
                if self._drain_req:
                    # SIGTERM/SIGINT with drain configured: quiesce the
                    # whole topology in dependency order, then halt
                    self._drain_req = False
                    self._log_event("signal-initiated topology drain")
                    self.drain()
                    return None
                self._scan_degraded()
                if self.autotuner is not None:
                    self.autotuner.maybe_step()
                # a freshly respawned tile consumes nothing until it is
                # RUN: keep acking its in-links on its behalf (its mux
                # resumes from the fseq cursor we advance, so nothing is
                # double-processed)
                for name in list(self._evicting):
                    if self.jt.cnc[name].signal_query() == Cnc.SIGNAL_RUN:
                        self._evicting.discard(name)
                    else:
                        self.evict_consumer(name)
                bad = self.poll()
                if bad is None:
                    time.sleep(poll_s)
                    continue
                n = self.restarts.get(bad, 0)
                if (self.policy.restart_policy != "respawn"
                        or n >= self.policy.max_restarts):
                    log.warning("tile %s failed (restarts=%d); tearing "
                                "down topology", bad, n)
                    self._log_event(f"tile {bad} failed (restarts={n}); "
                                    "fail-fast teardown")
                    # evidence BEFORE teardown: halt() wipes the cnc
                    # states and the respawned world never comes
                    self.flight_dump("crash", bad)
                    return bad
                self.respawn(bad)
        finally:
            self.halt()

    def _scan_degraded(self):
        """Dump a bundle once per 0->1 degraded_mode transition (a verify
        tile fell back to CPU serving: the device-loss evidence is the
        trace/metrics state at the moment it happened)."""
        for name, blk in self.jt.metrics.items():
            if not blk.has("degraded_mode"):
                continue
            if blk.get("degraded_mode"):
                if name not in self._degraded:
                    self._degraded.add(name)
                    self._log_event(f"tile {name} degraded (CPU fallback)")
                    self.flight_dump("degrade", name)
            else:
                self._degraded.discard(name)

    def respawn(self, name: str):
        """Kill + restart one tile into the live workspace: reap the
        corpse, wait out the backoff window (evicting the dead consumer's
        fseqs the whole time), then respawn.  The child re-joins by
        deterministic layout replay and resumes its in-links from the
        persisted fseq cursors — frags published during the outage were
        acked by eviction and are lost to this tile (the reference's
        unreliable-consumer overrun semantics for the outage window); no
        frag is ever processed twice."""
        n = self.restarts.get(name, 0) + 1
        self.restarts[name] = n
        # snapshot BEFORE the respawn: the child re-joins the same trace
        # ring and will overwrite the corpse's final spans
        self._log_event(f"tile {name} died; respawn {n}"
                        f"/{self.policy.max_restarts}")
        self.flight_dump("respawn", name)
        p = self.procs.get(name)
        if p is not None and p.is_alive():
            # stale-heartbeat (wedged) failure: the process is live but
            # catatonic — take it down hard before replacing it
            p.terminate()
            p.join(2.0)
            if p.is_alive():
                p.kill()
                p.join(1.0)
        delay = self.policy.backoff_s(n, name)
        log.warning("tile %s died; respawn %d/%d in %.2fs", name, n,
                    self.policy.max_restarts, delay)
        deadline = time.monotonic() + delay
        self.evict_consumer(name)
        while time.monotonic() < deadline and not self._halting:
            time.sleep(0.02)
            self.evict_consumer(name)
        if self._halting:
            return
        self._spawn(name, restart_cnt=n)
        self._evicting.add(name)

    def evict_consumer(self, name: str):
        """Fast-forward a dead consumer's reliable fseqs to the producer
        cursors so upstream credits refill (tango-layer eviction)."""
        for il, fseq, mcache in self.jt.consumer_edges(name):
            if il.reliable:
                Fctl.evict_dead_consumer(fseq, mcache)

    # -- drain protocol (graceful quiesce + rolling restart) --------------
    def drain_tile(self, name: str, timeout_s: float) -> bool:
        """Raise SIGNAL_DRAIN on one tile and wait (bounded) for its
        DRAINED ack.  Returns False on timeout or if the tile died
        mid-drain — the caller decides the fallback (crash-respawn
        semantics); this never hangs."""
        cnc = self.jt.cnc[name]
        cnc.signal(Cnc.SIGNAL_DRAIN)
        self._log_event(f"drain {name} (budget {timeout_s:.1f}s)")
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            sig = cnc.signal_query()
            if sig == Cnc.SIGNAL_DRAINED:
                return True
            if sig == Cnc.SIGNAL_RUN:
                # a tile that was mid-boot when we raised DRAIN stamps
                # RUN over it on loop entry; only boot writes RUN, so
                # seeing it here means the request was lost — re-assert
                cnc.signal(Cnc.SIGNAL_DRAIN)
            p = self.procs.get(name)
            if p is not None and not p.is_alive():
                return False
            if self._dump_req:   # SIGUSR2 still works mid-drain
                self._dump_req = False
                self.flight_dump("sigusr2")
            time.sleep(0.005)
        return cnc.signal_query() == Cnc.SIGNAL_DRAINED

    def _retile(self, name: str, new_cfg: dict):
        """Swap restart-required cfg keys into a tile's spec.  The
        workspace layout derives only from links and tile/in-link counts
        — never tile cfg — so a successor spawned from the new spec
        re-joins identical shm offsets with different private objects
        (n_buffers, max_inflight, cpu_idx, latency shapes, buckets)."""
        tiles = []
        for t in self.spec.tiles:
            if t.name == name:
                cfg = dict(t.cfg)
                cfg.update(new_cfg)
                t = topo_mod.TileSpec(t.name, t.kind, t.in_links,
                                      t.out_links, cfg)
            tiles.append(t)
        self.spec = TopoSpec(self.spec.app, self.spec.links, tuple(tiles),
                             self.spec.wksp_mb).validate()
        # supervisor-side lookups (tile_spec, consumer_edges) follow the
        # new spec; the joined rings themselves are untouched
        self.jt.spec = self.spec

    def rolling_restart(self, name: str, new_cfg: dict | None = None,
                        drain_timeout_s: float | None = None) -> bool:
        """Zero-loss tile restart: drain, reap, re-layout the tile's
        private objects with changed immutable knobs, respawn from the
        cursor manifest.

        The tile is drained (bounded by drain_timeout_s, default the
        policy's), HALTed out of its DRAINED park and joined; restart-
        required cfg keys are swapped via _retile; the successor then
        resumes every in-link from the drained fseq cursor — no frag is
        lost or re-verdicted, and upstream credits were parked (never
        evicted), so producers stall at most drain + respawn-boot.

        On drain timeout (or death mid-drain) the tile gets a flight
        bundle and falls back to today's crash-respawn semantics —
        terminate, evict-while-down, backoff respawn; frags published
        during the outage are acked on its behalf and lost to it,
        exactly as a crash.  Returns True on the graceful path."""
        t = (self.policy.drain_timeout_s if drain_timeout_s is None
             else float(drain_timeout_s))
        self._draining.add(name)
        try:
            ok = self.drain_tile(name, t)
            if ok:
                # validate the drain receipt: a torn/corrupt cursor
                # manifest means the quiesce point on disk can't be
                # trusted — fall back to the crash-eviction respawn path
                # (bounded loss; never duplicate verdicts) instead of
                # raising in the supervisor
                try:
                    self._load_drain_manifest(name)
                except ValueError as e:
                    self.manifest_corrupt_cnt += 1
                    self._log_event(
                        f"tile {name} drain manifest corrupt ({e}); "
                        f"crash-eviction fallback")
                    log.warning("tile %s drain manifest corrupt (%s); "
                                "falling back to crash respawn", name, e)
                    ok = False
            if new_cfg:
                self._retile(name, new_cfg)
            if not ok:
                self._log_event(f"tile {name} drain timeout "
                                f"({t:.1f}s); falling back to respawn")
                log.warning("tile %s drain timed out after %.1fs; "
                            "crash-respawn fallback", name, t)
                self.flight_dump("drain-timeout", name)
                self.respawn(name)
                return False
            n = self.restarts.get(name, 0) + 1
            self.restarts[name] = n
            self._log_event(f"tile {name} drained; rolling restart "
                            f"gen={n}")
            cnc = self.jt.cnc[name]
            cnc.signal(Cnc.SIGNAL_HALT)
            p = self.procs.get(name)
            if p is not None:
                p.join(5.0)
                if p.is_alive():
                    p.terminate()
                    p.join(2.0)
                    if p.is_alive():
                        p.kill()
                        p.join(1.0)
            self._spawn(name, restart_cnt=n)
            return True
        finally:
            self._draining.discard(name)

    def _dependency_order(self) -> list[str]:
        return dependency_order(self.spec)

    def drain(self, timeout_s: float | None = None) -> bool:
        """Graceful whole-topology shutdown: quiesce source->net->quic->
        verify->dedup in dependency order so every accepted txn is
        verdicted before exit, then halt.  Per-tile budget timeout_s
        (default the policy's drain_timeout_s); a tile that cannot run
        dry inside its budget gets a flight bundle and the remainder of
        the topology degrades to the plain cooperative halt — bounded,
        never a hang.  Returns True iff every tile drained."""
        t = (self.policy.drain_timeout_s if timeout_s is None
             else float(timeout_s))
        ok = True
        if t > 0:
            for name in self._dependency_order():
                p = self.procs.get(name)
                if p is None or not p.is_alive():
                    continue
                self._draining.add(name)
                if self.drain_tile(name, t):
                    self._log_event(f"tile {name} drained")
                else:
                    self._log_event(f"drain timeout: {name}; degrading "
                                    "to cooperative halt")
                    self.flight_dump("drain-timeout", name)
                    ok = False
                    break
        try:
            self.halt()
        finally:
            self._draining.clear()
        return ok

    def metrics(self, tile: str) -> dict:
        return self.jt.metrics[tile].snapshot()

    # -- shutdown ---------------------------------------------------------
    def halt(self, timeout: float = 10.0):
        self._halting = True
        for cnc in self.jt.cnc.values():
            cnc.signal(Cnc.SIGNAL_HALT)
        deadline = time.monotonic() + timeout
        for name, p in self.procs.items():
            p.join(max(0.0, deadline - time.monotonic()))
            if p.is_alive():
                p.terminate()
                p.join(1.0)
                if p.is_alive():
                    p.kill()
                    p.join(1.0)

    def close(self):
        self.halt()
        if self.http is not None:
            self.http.close()
            self.http = None
        self.jt.close()
        self.jt.unlink()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
