"""The tile run loop (ref: src/disco/mux/fd_mux.c — credit-based flow
control fd_mux.c:233-310, randomized housekeeping fd_mux.c:349-395, frag
poll -> before_frag/during_frag/after_frag dispatch, overrun detection).

One Mux drives one tile process: it polls every in-link mcache by sequence
number, copies payloads out of dcaches with seqlock re-validation, invokes
the tile's callbacks, and publishes to the tile's out links gated on credits
from reliable downstream consumers.

Callbacks (a tile implements any subset — the fd_topo_run_tile_t vtable,
src/disco/tiles.h):
    init(ctx)                      after joining the topology, before the loop
    before_frag(ctx, iidx, seq, sig) -> bool   True = skip (filter w/o payload)
    on_frag(ctx, iidx, meta, payload)          process one frag
    after_credit(ctx)              once per loop when credits are available
    house(ctx)                     during housekeeping (low rate)
    fini(ctx)                      on halt
"""

import os
import time
from dataclasses import dataclass

from ..tango import ring
from ..tango.ring import FSeq, Cnc
from ..utils.hist import Histf
from . import faultinject
from . import trace as trace_mod
from .topo import JoinedTopology, TileSpec

# fseq diag indices (mirrors FD_FSEQ_DIAG_*)
_D_PUB_CNT, _D_PUB_SZ = FSeq.DIAG_PUB_CNT, FSeq.DIAG_PUB_SZ
_D_FILT_CNT = FSeq.DIAG_FILT_CNT
_D_OVRNP_CNT = FSeq.DIAG_OVRNP_CNT
_D_SLOW_CNT = FSeq.DIAG_SLOW_CNT


@dataclass
class _InState:
    name: str
    mcache: object
    dcache: object
    fseq: FSeq
    seq: int = 0


@dataclass
class _OutState:
    name: str
    mcache: object
    dcache: object
    consumers: list          # reliable consumer fseqs
    depth: int = 0
    seq: int = 0
    chunk: int = 0
    cr_avail: int = 0
    mtu: int = 0
    # per-housekeeping-window attribution state (out{j}_* gauges):
    # credit low-watermark since the last housekeeping sample, plus the
    # publish seq/bytes marks the window rates are measured against
    cr_lwm: int = 0
    sz_total: int = 0
    seq_w0: int = 0
    sz_w0: int = 0


class TileCtx:
    """What a tile's callbacks see: its config, metrics block, and publish
    surface over the out links."""

    def __init__(self, topo: JoinedTopology, tile: TileSpec, mux: "Mux"):
        self.topo = topo
        self.tile = tile
        self.cfg = tile.cfg
        self.metrics = topo.metrics[tile.name]
        self.trace = topo.trace.get(tile.name)  # fdtrace span ring writer
        self._mux = mux
        self.halted = False

    def out_index(self, link_name: str) -> int:
        for i, o in enumerate(self._mux.outs):
            if o.name == link_name:
                return i
        raise KeyError(link_name)

    def publish(self, payload: bytes = b"", sig: int = 0, out: int = 0,
                ctl_: int | None = None) -> int:
        """Publish one frag on out link `out`, blocking on downstream credits
        (the reference instead polls credits in housekeeping and the tile
        yields; a bounded spin keeps the Python loop simple and still
        surfaces the stall in backp_cnt)."""
        return self._mux.publish(out, payload, sig, ctl_)

    def publish_burst(self, buf, starts, lens, sigs, out: int = 0) -> int:
        """Publish many frags in one native call (tango.cpp
        fd_ring_tx_burst): payload i = buf[starts[i]:starts[i]+lens[i]]
        with app sig sigs[i].  Same credit semantics as publish()."""
        return self._mux.publish_burst(out, buf, starts, lens, sigs)

    def out_reserve(self, nbytes: int, out: int = 0):
        """Reserve dcache space for one frag: blocks on a downstream
        credit, then returns (chunk, writable uint8 view of nbytes over
        the shm) for readinto-style stamping — no staging bytes object.
        Returns (None, None) on halt-while-backpressured.  Must be paired
        with out_commit()."""
        return self._mux.out_reserve(out, nbytes)

    def out_commit(self, chunk: int, nbytes: int, sig: int = 0,
                   sz: int | None = None, out: int = 0) -> int:
        """Publish the frag reserved at `chunk`.  `sz` is the value stored
        in the 16-bit meta.sz field (defaults to nbytes; packed-wire frags
        store the ROW COUNT there since byte sizes overflow u16)."""
        return self._mux.out_commit(out, chunk, nbytes, sig,
                                    nbytes if sz is None else sz)

    def in_mcache(self, iidx: int):
        """The in-link's mcache — zero-copy consumers (on_burst_view)
        re-check frag seqlocks against it after reading shm views."""
        return self._mux.ins[iidx].mcache

    def halt(self):
        """Ask the loop to exit after this callback returns."""
        self.halted = True

    def heartbeat(self):
        """Stamp this tile's cnc heartbeat and honor HALT — for callbacks
        that block longer than a housekeeping interval (a tile waiting on
        an in-flight device batch must not be declared stale, and must
        still come down when the supervisor raises HALT).  Rate-limited
        internally, so calling it from a tight wait loop is fine."""
        self._mux.heartbeat_poke()


class Mux:
    HOUSE_NS = 20_000_000   # ~20ms default housekeeping interval
    BURST = 64              # frags drained per mcache poll

    def __init__(self, topo: JoinedTopology, tile_name: str, vtable,
                 restart_cnt: int = 0):
        self.topo = topo
        self.tile = topo.tile_spec(tile_name)
        self.vt = vtable
        self.metrics = topo.metrics[tile_name]
        self.cnc: Cnc = topo.cnc[tile_name]
        # armed fault plan or None (the common case; every hot-path site
        # below guards on `is not None` so disabled injection costs one
        # identity compare per burst)
        self.fault = faultinject.for_tile(tile_name, self.tile.cfg,
                                          restart_cnt=restart_cnt)
        self.restart_cnt = restart_cnt
        self._next_poke = 0
        # fdtrace: this tile's span ring (disco/trace.py) + the span-chain
        # origin stamp of the frag currently being processed — publishes
        # during a callback carry it forward as tsorig so downstream hops
        # can measure whole-chain age (the reference's tsorig contract,
        # fd_tango_base.h:140-170)
        self.tracer = topo.trace.get(tile_name)
        self._cur_tsorig = 0
        # autotune knob mailbox: generation-checked once per housekeeping
        # (one int compare unarmed — the faultinject zero-overhead rule).
        # gen-seen starts at 0, so a respawned tile re-applies whatever
        # knob set the supervisor accumulated before it died.
        self._knob_pod = topo.knobs.get(tile_name)
        self._knob_gen = 0

        self.ins: list[_InState] = []
        for il in self.tile.in_links:
            jl = topo.links[il.link]
            fs = topo.fseq[(self.tile.name, il.link)]
            # start at the link's seq0, NOT the live producer cursor: a
            # producer that booted first may already have published, and a
            # reliable consumer must see every frag from the beginning (the
            # credit system guarantees none were overwritten: the producer
            # is gated on our fseq, which also starts at seq0).
            # EXCEPT on respawn: a tile restarted into a live workspace
            # resumes from its own persisted fseq cursor — every frag below
            # it was already acked (by the previous incarnation, or by the
            # supervisor's dead-consumer eviction while we were down), so
            # re-processing would emit duplicate verdicts downstream.
            seq = jl.mcache.seq0()
            if restart_cnt > 0:
                seq = max(seq, fs.query())
            self.ins.append(_InState(il.link, jl.mcache, jl.dcache, fs,
                                     seq=seq))
        self.outs: list[_OutState] = []
        for ln in self.tile.out_links:
            jl = topo.links[ln]
            self.outs.append(_OutState(
                ln, jl.mcache, jl.dcache, topo.reliable_consumers(ln),
                depth=jl.spec.depth, seq=jl.mcache.seq_query(),
                chunk=0))
            self.outs[-1].mtu = jl.spec.mtu
        self.ctx = TileCtx(topo, self.tile, self)

    # -- credits (fd_mux.c:233-310) ---------------------------------------
    def _refresh_credits(self):
        for o in self.outs:
            if not o.consumers:
                o.cr_avail = o.depth
                continue
            lo = min(fs.query() for fs in o.consumers)
            o.cr_avail = o.depth - (o.seq - lo)

    def _wait_credit(self, o: _OutState) -> bool:
        """Block (in slices) until one credit is available on `o`.  Returns
        False if the topology HALTed while backpressured (frag dropped)."""
        backp = False
        next_hb = 0
        t_enter = 0
        while o.cr_avail <= 0:
            if not backp:
                backp = True
                t_enter = time.monotonic_ns()
            self._refresh_credits()
            if o.cr_avail <= 0:
                # stay responsive while backpressured: heartbeat and honor
                # HALT so a dead downstream can't wedge shutdown or make the
                # supervisor flag us as stalled
                now = time.monotonic_ns()
                if now >= next_hb:
                    # charge the limiting consumer's slow diag (next_hb=0:
                    # the first pass charges immediately) — how the monitor
                    # attributes this producer's stall to a specific rx
                    # (fd_fctl.h receiver diag)
                    if o.consumers:
                        min(o.consumers,
                            key=lambda fs: fs.query()).diag_add(_D_SLOW_CNT)
                    next_hb = now + 10_000_000
                    self.cnc.heartbeat(now)
                    if self.cnc.signal_query() == Cnc.SIGNAL_HALT:
                        self.ctx.halted = True
                        self.metrics.add(
                            "backp_ns", time.monotonic_ns() - t_enter)
                        return False
                time.sleep(50e-6)
        if backp:
            self.metrics.add("backp_cnt")
            self.metrics.add("backp_ns", time.monotonic_ns() - t_enter)
        return True

    def heartbeat_poke(self):
        """Out-of-band heartbeat + HALT check for callbacks that block
        past a housekeeping interval (device verdict waits).  Rate-limited
        to the same 10ms cadence as the backpressure loop so hammering it
        from a poll loop stays cheap."""
        now = time.monotonic_ns()
        if now < self._next_poke:
            return
        self._next_poke = now + 10_000_000
        self.cnc.heartbeat(now)
        if self.cnc.signal_query() == Cnc.SIGNAL_HALT:
            self.ctx.halted = True

    # -- drain protocol (graceful quiesce) --------------------------------
    def _drain_park(self, ctx, vt, m, cb_held, t0):
        """SIGNAL_DRAIN terminal phase, entered from housekeeping once
        the catch-up phase has consumed every frag published before the
        DRAIN admission snapshot:

          1. stop admitting frags — the in-link fseqs freeze and live
             upstream producers park on withheld credits via the normal
             fctl math (a credit park, not a dead consumer: no eviction,
             no loss);
          2. run the tile dry: the vtable's optional `drain(ctx) -> bool`
             hook is polled until it reports True (the verify tile
             dispatches every open bucket + lat accumulator and harvests
             every in-flight device batch, publishing all verdicts);
             tiles without the hook are dry by definition;
          3. persist a cursor manifest (per-in-link fseq position, knob
             generation) — the zero-loss audit artifact;
          4. signal DRAINED and park, heartbeating, until HALT.

        The park keeps DRAINED visible for as long as the supervisor
        needs it (the loop-exit finally would otherwise overwrite it with
        the BOOT halted-ack immediately).  A tile that cannot run dry
        stays in DRAIN heartbeating — the supervisor's drain_timeout_s
        bounds that by falling back to crash-respawn semantics (HALT or
        terminate), so peers never hang on a wedged drain."""
        cb_drain = getattr(vt, "drain", None)
        while not ctx.halted:
            done = cb_drain(ctx) if cb_drain is not None else True
            now = time.monotonic_ns()
            self.cnc.heartbeat(now)
            # verdicts landing during the dry-run release pinned credits:
            # keep publishing fseq minus held so the manifest cursor (and
            # the producer's credit view) converges to fully-acked
            for hidx, i in enumerate(self.ins):
                held = cb_held(hidx) if cb_held is not None else 0
                i.fseq.update(i.seq - held)
            if self.cnc.signal_query() == Cnc.SIGNAL_HALT:
                return  # supervisor gave up (drain_timeout_s): plain halt
            if done:
                break
            time.sleep(200e-6)
        if ctx.halted:
            return
        m.set("drain_flush_ns", time.monotonic_ns() - t0)
        self._write_drain_manifest()
        self.cnc.signal(Cnc.SIGNAL_DRAINED)
        while self.cnc.signal_query() != Cnc.SIGNAL_HALT:
            self.cnc.heartbeat(time.monotonic_ns())
            time.sleep(1e-3)

    def _write_drain_manifest(self):
        """Cursor manifest for a completed drain.  The respawn itself
        resumes from the fseq lines in shm (restart_cnt > 0 path); the
        manifest is what an operator or chaos harness inspects to prove
        zero-loss — per-in-link fseq cursor, out-link publish cursor, and
        the knob-pod generation this incarnation had applied.  Written to
        [supervision] drain_manifest_dir (threaded into tile cfg) or
        $FDTPU_DRAIN_DIR; skipped when neither is set — a drain must
        never fail on a read-only filesystem."""
        sup = (self.tile.cfg.get("supervision") or {})
        d = (sup.get("drain_manifest_dir")
             or os.environ.get("FDTPU_DRAIN_DIR"))
        if not d:
            return
        try:
            import json
            os.makedirs(d, exist_ok=True)
            man = {
                "tile": self.tile.name,
                "kind": self.tile.kind,
                "restart_cnt": self.restart_cnt,
                "knob_gen": self._knob_gen,
                "cursors": {i.name: int(i.fseq.query()) for i in self.ins},
                "outs": {o.name: int(o.seq) for o in self.outs},
            }
            path = os.path.join(
                d, self.tile.name.replace(":", "_") + ".manifest.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(man, f, indent=1, sort_keys=True)
            os.replace(tmp, path)  # atomic: readers never see a torn file
        except OSError:
            pass

    def publish(self, out_idx: int, payload: bytes, sig: int,
                ctl_: int | None) -> int:
        o = self.outs[out_idx]
        if len(payload) > o.mtu:
            # covers metadata-only links too (mtu=0): publishing payload
            # bytes there would silently arrive as b"" downstream
            raise ValueError(
                f"payload {len(payload)}B exceeds link {o.name} mtu {o.mtu}")
        if not self._wait_credit(o):
            return -1  # frag dropped; topology is going down
        chunk, sz = 0, len(payload)
        if o.dcache is not None and sz:
            chunk = o.chunk
            o.chunk = o.dcache.write(chunk, payload)
        tspub = time.monotonic_ns() & 0xFFFFFFFF
        # span-chain origin: forward the consumed frag's tsorig; a frag
        # published outside frag processing (after_credit/house) STARTS a
        # chain, so its origin is its own publish time
        seq = o.mcache.publish(
            sig, chunk, sz,
            ring.ctl() if ctl_ is None else ctl_,
            self._cur_tsorig or tspub, tspub)
        o.seq = seq + 1
        o.cr_avail -= 1
        if o.cr_avail < o.cr_lwm:
            o.cr_lwm = o.cr_avail
        o.sz_total += sz
        self.metrics.add("out_frag_cnt")
        self.metrics.add("out_sz", sz)
        return seq

    def publish_burst(self, out_idx: int, buf, starts, lens, sigs) -> int:
        """Credit-gated burst publish: waits (in slices) until the whole
        burst's credits are available, then one fd_ring_tx_burst call.
        Returns the last seq published, or -1 on halt-while-backpressured."""
        import numpy as np
        o = self.outs[out_idx]
        n = len(starts)
        if n == 0:
            return o.seq - 1
        if int(np.max(lens)) > o.mtu:
            raise ValueError(
                f"payload exceeds link {o.name} mtu {o.mtu}")
        if o.dcache is None:
            raise ValueError(f"link {o.name} has no dcache (burst needs one)")
        done = 0
        while done < n:
            if not self._wait_credit(o):
                return -1
            take = min(n - done, o.cr_avail)
            tspub = time.monotonic_ns() & 0xFFFFFFFF
            seq, o.chunk = ring.tx_burst(
                o.mcache, o.dcache, o.chunk, buf,
                starts[done : done + take], lens[done : done + take],
                sigs[done : done + take],
                tsorig=self._cur_tsorig or tspub, tspub=tspub)
            o.seq = seq + 1
            o.cr_avail -= take
            if o.cr_avail < o.cr_lwm:
                o.cr_lwm = o.cr_avail
            done += take
        sz_total = int(np.sum(lens))
        o.sz_total += sz_total
        self.metrics.add("out_frag_cnt", n)
        self.metrics.add("out_sz", sz_total)
        return o.seq - 1

    # -- zero-copy producer surface (packed-wire path) ---------------------
    def out_reserve(self, out_idx: int, nbytes: int):
        """Reserve one frag's dcache space: wait for one credit, return
        (chunk, writable view).  The producer stamps the payload directly
        into shm (readinto-style) and then out_commit()s — the frag never
        exists as an intermediate bytes object."""
        o = self.outs[out_idx]
        if nbytes > o.mtu:
            raise ValueError(
                f"reserve {nbytes}B exceeds link {o.name} mtu {o.mtu}")
        if o.dcache is None:
            raise ValueError(f"link {o.name} has no dcache")
        if not self._wait_credit(o):
            return None, None
        return o.chunk, o.dcache.write_view(o.chunk, nbytes)

    def out_commit(self, out_idx: int, chunk: int, nbytes: int, sig: int,
                   sz: int) -> int:
        """Publish the frag reserved at `chunk` (nbytes written through the
        reserved view; `sz` goes into the u16 meta.sz field — for packed
        frags that is the row count, not the byte size)."""
        o = self.outs[out_idx]
        o.chunk = o.dcache.advance(chunk, nbytes)
        tspub = time.monotonic_ns() & 0xFFFFFFFF
        seq = o.mcache.publish(
            sig, chunk, sz, ring.ctl(),
            self._cur_tsorig or tspub, tspub)
        o.seq = seq + 1
        o.cr_avail -= 1
        if o.cr_avail < o.cr_lwm:
            o.cr_lwm = o.cr_avail
        o.sz_total += nbytes
        self.metrics.add("out_frag_cnt")
        self.metrics.add("out_sz", nbytes)
        return seq

    # -- main loop ---------------------------------------------------------
    def run(self):
        import numpy as np
        vt, ctx, m = self.vt, self.ctx, self.metrics
        # bind the vtable once: per-frag hasattr probes cost in the hot loop
        cb_before = getattr(vt, "before_frag", None)
        cb_frag = getattr(vt, "on_frag", None)
        cb_credit = getattr(vt, "after_credit", None)
        cb_house = getattr(vt, "house", None)
        cb_knobs = getattr(vt, "apply_knobs", None)
        if hasattr(vt, "init"):
            vt.init(ctx)
        # burst rx (round 4): a tile exposing on_burst(ctx, iidx, metas,
        # buf, offs, kept) gets frags drained via ONE native call per poll
        # (consume + seqlock payload copy + optional round-robin filter at
        # the ring, fd_ring_rx_burst) — the per-frag Python dispatch below
        # caps a tile near ~10^5 frags/s; the burst path doesn't.  The
        # tile's init may set .burst_rr = (cnt, idx) for ring-level RR
        # (ref fd_verify.c:36-47); before_frag is NOT called on this path.
        cb_burst = getattr(vt, "on_burst", None)
        # zero-copy burst rx (round 8): a tile exposing on_burst_view(ctx,
        # iidx, metas, dcache) consumes metas only — payloads stay in the
        # shm dcache and the tile builds views over them (dcache.rows).
        # Because the payload is NOT copied out under the seqlock, the tile
        # must re-check the mcache seq AFTER it is done reading (or after
        # the device upload completes) and drop torn frags itself.  A tile
        # may hold credits for frags whose views are still pinned by
        # exposing credits_held(iidx); fseq updates subtract it so the
        # producer cannot overwrite a pinned region.
        cb_view = getattr(vt, "on_burst_view", None)
        cb_held = getattr(vt, "credits_held", None)
        rr_cnt, rr_idx = getattr(vt, "burst_rr", (1, 0))
        if cb_burst is not None:
            BURST_RX = 1024
            rx_buf = [np.zeros(
                BURST_RX * max(self.topo.links[il.name].spec.mtu, 64),
                np.uint8) for il in self.ins]
            rx_metas = [np.zeros(BURST_RX, dtype=ring.FRAG_META_DTYPE)
                        for _ in self.ins]
            rx_offs = [np.zeros(BURST_RX + 1, np.int64) for _ in self.ins]
        self.cnc.signal(Cnc.SIGNAL_RUN)
        self._refresh_credits()
        for o in self.outs:
            o.cr_lwm = o.cr_avail
            o.seq_w0 = o.seq
        next_house = 0
        drain_stop = None  # per-in-link admission cursors once DRAINing
        drain_t0 = 0
        win_t0 = 0         # start of the current attribution window
        busy_acc = 0       # ns inside tile callbacks since last flush
        idle_acc = 0       # ns in the nothing-inbound yield sleep
        # per-in-link hop latency: consume time minus producer tspub (both
        # monotonic_ns low 32 bits, same machine clock) — the data the
        # reference monitor renders per link (monitor.c:49-160)
        hop_hists = [Histf(100, 10_000_000_000) for _ in self.ins[:4]]
        try:
            while not ctx.halted:
                now = time.monotonic_ns()
                m.add("loop_cnt")
                if now >= next_house:
                    next_house = now + self.HOUSE_NS
                    m.add("housekeep_cnt")
                    self.cnc.heartbeat(now)
                    sig = self.cnc.signal_query()
                    if sig == Cnc.SIGNAL_HALT:
                        break
                    if sig == Cnc.SIGNAL_DRAIN:
                        # graceful quiesce: rides the signal compare the
                        # loop already pays — zero cost until raised
                        if drain_stop is None:
                            m.add("drain_cnt")
                            drain_t0 = now
                            # admission snapshot: the catch-up phase
                            # consumes every frag published before this
                            # point and nothing after it (a dependency-
                            # ordered topology drain parks producers
                            # first, so the snapshot covers everything;
                            # a rolling restart leaves the tail for the
                            # successor's cursor — zero loss either way)
                            drain_stop = [x.mcache.seq_query()
                                          for x in self.ins]
                        if all(x.seq >= s for x, s
                               in zip(self.ins, drain_stop)):
                            self._drain_park(ctx, vt, m, cb_held,
                                             drain_t0)
                            break
                    for hidx, i in enumerate(self.ins):
                        held = cb_held(hidx) if cb_held is not None else 0
                        i.fseq.update(i.seq - held)
                    self._refresh_credits()
                    for hi, h in enumerate(hop_hists):
                        if h.count():
                            m.set(f"in{hi}_hop_p50_ns",
                                  int(h.percentile(0.50)))
                            m.set(f"in{hi}_hop_p99_ns",
                                  int(h.percentile(0.99)))
                            # fresh window per housekeeping interval: the
                            # gauges must track CURRENT latency, not a
                            # lifetime-cumulative distribution that hides
                            # a live stall behind old samples
                            hop_hists[hi] = Histf(100, 10_000_000_000)
                    # per-out-link attribution (out{j}_* gauges): seq lag
                    # behind the slowest reliable consumer, ring-occupancy
                    # high-watermark (depth - credit low-water), and the
                    # window's publish rates — the inputs to the monitor's
                    # bottleneck verdict (disco/attrib.py)
                    dt = now - win_t0 if win_t0 else 0
                    for oi, o in enumerate(self.outs[:4]):
                        lag = 0
                        if o.consumers:
                            lo = min(fs.query() for fs in o.consumers)
                            lag = max(o.seq - lo, 0)
                        m.set(f"out{oi}_lag", lag)
                        occ = o.depth - o.cr_lwm
                        m.set(f"out{oi}_occ_hwm",
                              max(0, min(occ, o.depth)))
                        m.set(f"out{oi}_cr_lwm", max(o.cr_lwm, 0))
                        if dt > 0:
                            m.set(f"out{oi}_frag_rate",
                                  (o.seq - o.seq_w0) * 1_000_000_000 // dt)
                            m.set(f"out{oi}_byte_rate",
                                  (o.sz_total - o.sz_w0)
                                  * 1_000_000_000 // dt)
                        o.cr_lwm = o.cr_avail
                        o.seq_w0 = o.seq
                        o.sz_w0 = o.sz_total
                    win_t0 = now
                    # regime flush: where the loop's wall time went since
                    # the last housekeeping (backp_ns lands straight from
                    # _wait_credit; housekeeping charges itself below)
                    if busy_acc:
                        m.add("busy_ns", busy_acc)
                        busy_acc = 0
                    if idle_acc:
                        m.add("idle_ns", idle_acc)
                        idle_acc = 0
                    if self.fault is not None:
                        self.fault.house()
                    if self._knob_pod is not None and cb_knobs is not None:
                        g = self._knob_pod.gen
                        if g != self._knob_gen:
                            self._knob_gen = g
                            vals = self._knob_pod.read_set()
                            if vals:
                                cb_knobs(ctx, vals)
                                m.add("knob_apply_cnt", 1)
                    if cb_house is not None:
                        cb_house(ctx)
                    m.add("house_ns", time.monotonic_ns() - now)

                did = 0
                for iidx, i in enumerate(self.ins):
                    if drain_stop is None:
                        room = 1 << 30   # effectively unbounded
                    else:
                        # drain catch-up: admit only frags published
                        # before the DRAIN snapshot; everything after it
                        # belongs to the successor's resume cursor
                        room = drain_stop[iidx] - i.seq
                        if room <= 0:
                            continue
                    if cb_view is not None and i.dcache is not None:
                        metas, rc = i.mcache.consume_burst(
                            i.seq, min(self.BURST, room))
                        cons = len(metas)
                        if cons:
                            # ring-level round-robin on the frag seq (the
                            # native rx_burst filter, in Python: packed
                            # frags are few and large)
                            mine = (metas[(metas["seq"] % rr_cnt) == rr_idx]
                                    if rr_cnt > 1 else metas)
                            if self.fault is not None and len(mine):
                                mine, _nd = self.fault.frags_view(
                                    mine, i.dcache)
                            filt = cons - len(mine)
                            m0 = metas[0]
                            hop = (int(now) - int(m0["tspub"])) & 0xFFFFFFFF
                            if hop >= 1 << 31:
                                hop = 0
                            elif iidx < 4:
                                hop_hists[iidx].sample(hop)
                                m.hist_sample("in_hop_ns", hop)
                            tsorig = int(m0["tsorig"])
                            age = ((int(now) - tsorig) & 0xFFFFFFFF
                                   if tsorig else hop)
                            self._cur_tsorig = tsorig or int(m0["tspub"])
                            t0 = time.monotonic_ns()
                            if len(mine):
                                cb_view(ctx, iidx, mine, i.dcache)
                            t1 = time.monotonic_ns()
                            busy_acc += t1 - t0
                            if self.tracer is not None:
                                self.tracer.record(
                                    trace_mod.KIND_BURST, t0,
                                    t1 - t0, iidx=iidx,
                                    hop_ns=hop,
                                    age_ns=age if age < 1 << 31 else 0,
                                    cnt=cons, seq=int(m0["seq"]))
                            self._cur_tsorig = 0
                            i.seq += cons
                            held = (cb_held(iidx)
                                    if cb_held is not None else 0)
                            i.fseq.update(i.seq - held)
                            i.fseq.diag_add(_D_PUB_CNT, len(mine))
                            if filt:
                                i.fseq.diag_add(_D_FILT_CNT, filt)
                                m.add("in_filt_cnt", filt)
                            m.add("in_frag_cnt", len(mine))
                            did += cons
                        elif cb_held is not None:
                            # release-driven credit return: harvests in
                            # after_credit may have retired pinned frags
                            # since the last poll even with nothing new
                            # inbound — one atomic store per poll
                            i.fseq.update(i.seq - cb_held(iidx))
                        if rc == 1:
                            cur = i.mcache.seq_query()
                            i.fseq.diag_add(_D_OVRNP_CNT, cur - i.seq)
                            m.add("in_ovrn_cnt", cur - i.seq)
                            i.seq = cur
                        if ctx.halted:
                            break
                        continue
                    if cb_burst is not None and i.dcache is not None:
                        rc, cons, kept, filt = ring.rx_burst(
                            i.mcache, i.dcache, i.seq,
                            min(BURST_RX, room),
                            rx_buf[iidx], rx_metas[iidx], rx_offs[iidx],
                            rr_cnt, rr_idx)
                        if kept and self.fault is not None:
                            # a kill threshold inside the burst trims it:
                            # the prefix is processed + span-recorded, the
                            # tail is acked-but-lost (outage semantics)
                            kept = self.fault.burst(kept, rx_buf[iidx],
                                                    rx_offs[iidx])
                        if kept:
                            m0 = rx_metas[iidx][0]
                            # one hop sample per burst keeps the
                            # monitor's in*_hop gauges alive on this
                            # path (per-frag sampling would be pure
                            # overhead at burst rates)
                            hop = (int(now) - int(m0["tspub"])) & 0xFFFFFFFF
                            if hop >= 1 << 31:
                                hop = 0  # stale/wrapped stamp
                            elif iidx < 4:
                                hop_hists[iidx].sample(hop)
                                m.hist_sample("in_hop_ns", hop)
                            tsorig = int(m0["tsorig"])
                            age = ((int(now) - tsorig) & 0xFFFFFFFF
                                   if tsorig else hop)
                            self._cur_tsorig = tsorig or int(m0["tspub"])
                            t0 = time.monotonic_ns()
                            cb_burst(ctx, iidx, rx_metas[iidx][:kept],
                                     rx_buf[iidx], rx_offs[iidx], kept)
                            t1 = time.monotonic_ns()
                            busy_acc += t1 - t0
                            if self.tracer is not None:
                                self.tracer.record(
                                    trace_mod.KIND_BURST, t0,
                                    t1 - t0, iidx=iidx,
                                    hop_ns=hop,
                                    age_ns=age if age < 1 << 31 else 0,
                                    cnt=kept, seq=int(m0["seq"]))
                            self._cur_tsorig = 0
                        if cons:
                            i.seq += cons
                            i.fseq.update(i.seq)
                            i.fseq.diag_add(_D_PUB_CNT, kept)
                            if filt:
                                i.fseq.diag_add(_D_FILT_CNT, filt)
                                m.add("in_filt_cnt", filt)
                            sz_total = int(rx_offs[iidx][kept])
                            i.fseq.diag_add(_D_PUB_SZ, sz_total)
                            m.add("in_frag_cnt", kept)
                            m.add("in_sz", sz_total)
                            did += cons
                        if rc == 1:
                            cur = i.mcache.seq_query()
                            i.fseq.diag_add(_D_OVRNP_CNT, cur - i.seq)
                            m.add("in_ovrn_cnt", cur - i.seq)
                            i.seq = cur
                        if ctx.halted:
                            break
                        continue
                    seq_before = i.seq
                    metas, rc = i.mcache.consume_burst(
                        i.seq, min(self.BURST, room))
                    if rc == 1 and len(metas) == 0:
                        # producer lapped us: resync and count the loss
                        cur = i.mcache.seq_query()
                        i.fseq.diag_add(_D_OVRNP_CNT, cur - i.seq)
                        m.add("in_ovrn_cnt", cur - i.seq)
                        i.seq = cur
                        continue
                    for meta in metas:
                        seq = int(meta["seq"])
                        if (cb_before is not None
                                and cb_before(ctx, iidx, seq,
                                              int(meta["sig"]))):
                            i.fseq.diag_add(_D_FILT_CNT)
                            m.add("in_filt_cnt")
                            i.seq = seq + 1
                            continue
                        payload = b""
                        sz = int(meta["sz"])
                        if i.dcache is not None and sz:
                            payload = i.dcache.read(int(meta["chunk"]), sz)
                            # seqlock re-validation: if the producer moved
                            # past this line while we copied, the payload may
                            # be torn (fd_mux.c overrun-during-frag check)
                            rc2, _ = i.mcache.query(seq)
                            if rc2 != 0:
                                i.fseq.diag_add(_D_OVRNP_CNT)
                                m.add("in_ovrn_cnt")
                                i.seq = i.mcache.seq_query()
                                break
                        if self.fault is not None:
                            payload, _drop = self.fault.frag(payload)
                            if _drop:
                                i.fseq.diag_add(_D_FILT_CNT)
                                m.add("in_filt_cnt")
                                i.seq = seq + 1
                                continue
                        hop = (int(now) - int(meta["tspub"])) & 0xFFFFFFFF
                        if hop >= 1 << 31:  # guard against stale stamps
                            hop = 0
                        elif iidx < 4:
                            hop_hists[iidx].sample(hop)
                            m.hist_sample("in_hop_ns", hop)
                        if cb_frag is not None:
                            tsorig = int(meta["tsorig"])
                            age = ((int(now) - tsorig) & 0xFFFFFFFF
                                   if tsorig else hop)
                            self._cur_tsorig = tsorig or int(meta["tspub"])
                            t0 = time.monotonic_ns()
                            cb_frag(ctx, iidx, meta, payload)
                            t1 = time.monotonic_ns()
                            busy_acc += t1 - t0
                            if self.tracer is not None:
                                self.tracer.record(
                                    trace_mod.KIND_FRAG, t0,
                                    t1 - t0, iidx=iidx,
                                    hop_ns=hop,
                                    age_ns=age if age < 1 << 31 else 0,
                                    seq=seq)
                            self._cur_tsorig = 0
                        i.fseq.diag_add(_D_PUB_CNT)
                        i.fseq.diag_add(_D_PUB_SZ, sz)
                        m.add("in_frag_cnt")
                        m.add("in_sz", sz)
                        i.seq = seq + 1
                        did += 1
                        if ctx.halted:
                            break
                    # eager credit return: publish our position as soon as we
                    # advance, not just in housekeeping — otherwise producer
                    # throughput caps at depth frags per HOUSE_NS (the
                    # reference's mux returns credits at a depth-scaled lazy
                    # rate for the same reason, fd_mux.c:233-310)
                    if i.seq != seq_before:
                        i.fseq.update(i.seq)
                    if ctx.halted:
                        break

                if cb_credit is not None:
                    t0 = time.monotonic_ns()
                    cb_credit(ctx)
                    busy_acc += time.monotonic_ns() - t0
                if not did:
                    # nothing inbound: brief yield keeps one spinning Python
                    # loop from starving siblings on shared cores (the
                    # reference spins with FD_SPIN_PAUSE on dedicated cores)
                    t0 = time.monotonic_ns()
                    time.sleep(20e-6)
                    idle_acc += time.monotonic_ns() - t0
        finally:
            if hasattr(vt, "fini"):
                vt.fini(ctx)
            for i in self.ins:
                i.fseq.update(i.seq)
            self.cnc.signal(Cnc.SIGNAL_BOOT)  # BOOT == halted-ack at exit
