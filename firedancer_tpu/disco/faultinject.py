"""Deterministic fault injection for chaos testing (disco layer).

The reference hardens tiles with fuzz targets and out-of-band chaos runs;
the in-tree equivalent here is a seeded fault plan threaded through the
mux rx paths, the tile housekeeping, and the verifier dispatch.  Faults
are OFF unless the FDTPU_FAULTS env var (or a tile cfg `faults` entry)
names the tile, in which case `for_tile()` returns a FaultInjector; every
hot-path call site guards with `if fault is not None`, so the disabled
cost is a single identity compare per burst.

Plan grammar (env FDTPU_FAULTS, or a tile cfg `faults` string; a cfg
`faults` dict applies to that one tile directly):

    tile=knob:value,knob:value[;tile2=...]

    FDTPU_FAULTS="verify:0=kill_after_frags:128,boot:0;source=delay_frag_us:50"

A tile term matches by exact instance name ("verify:0") or by kind prefix
("verify" matches every verify:* instance).  When both match, the exact
entry wins knob-by-knob.

Knobs (all deterministic given `seed` — identical plans replay identical
failure sequences):

    kill_after_frags:N   hard-exit (os._exit, no unwinding — SIGKILL-grade)
                         the tile process right BEFORE it processes its Nth
                         received frag: the doomed frag is neither processed
                         nor fseq-acked, so a respawn resumes at it cleanly
    delay_frag_us:U      sleep U microseconds per received frag
    drop_frag_p:P        silently drop each received frag with probability P
                         (frag-granular on the scalar and zero-copy view
                         paths; the native rx_burst path does not support it)
    corrupt_payload_p:P  flip one payload bit per frag with probability P
                         (on the zero-copy view path the flip lands in the
                         first 64 payload bytes — inside the packed row 0
                         message region)
    fail_dispatch_p:P    device dispatch raises InjectedDispatchError with
                         probability P (consumed by pipeline.GuardedVerifier)
    fail_dispatch_n:N    fail the first N device dispatches, then heal —
                         scripts the "device sick, then recovers" arc
    stall_heartbeat_s:S  one-shot: housekeeping sleeps S seconds without
                         heartbeating (stale-detection drill)
    seed:K               rng seed for the probabilistic knobs (default 0;
                         folded with the tile name so instances diverge)
    boot:G               plan applies only to boot generation G (0 = first
                         spawn; a tile respawned by the supervisor runs
                         generation 1, 2, ...) — lets a chaos script kill
                         the first incarnation and let the respawn live
"""

import os
import time
import zlib

import numpy as np

KILL_EXIT_CODE = 86  # distinguishes an injected kill from a real crash


class InjectedDispatchError(RuntimeError):
    """Raised by FaultInjector.dispatch() in place of a real device error."""


def _coerce(v: str):
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


def parse_plan(text: str) -> dict:
    """'tile=k:v,k:v;tile2=...' -> {tile: {k: v}} with numeric coercion."""
    plans = {}
    for term in text.split(";"):
        term = term.strip()
        if not term:
            continue
        tile, _, body = term.partition("=")
        knobs = {}
        for kv in body.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition(":")
            knobs[k.strip()] = _coerce(v.strip())
        plans[tile.strip()] = knobs
    return plans


def plan_for(tile_name: str, plans: dict) -> dict | None:
    """Kind-prefix entry overlaid by an exact-name entry (exact wins)."""
    kind = tile_name.split(":", 1)[0]
    knobs = {}
    if kind in plans and kind != tile_name:
        knobs.update(plans[kind])
    if tile_name in plans:
        knobs.update(plans[tile_name])
    return knobs or None


def for_tile(tile_name: str, cfg: dict | None = None, restart_cnt: int = 0,
             environ=os.environ) -> "FaultInjector | None":
    """The single entry point: None (the common case — zero overhead
    downstream) unless a fault plan names this tile AND its boot-generation
    gate matches."""
    knobs = {}
    env_text = environ.get("FDTPU_FAULTS", "")
    if env_text:
        knobs.update(plan_for(tile_name, parse_plan(env_text)) or {})
    f = (cfg or {}).get("faults")
    if isinstance(f, str) and f:
        knobs.update(plan_for(tile_name, parse_plan(f)) or {})
    elif isinstance(f, dict):
        knobs.update(f)
    if not knobs:
        return None
    gen = knobs.get("boot")
    if gen is not None and int(gen) != int(restart_cnt):
        return None
    return FaultInjector(tile_name, knobs)


class FaultInjector:
    """One tile's armed fault plan.  Mux calls frag()/frags_view()/burst()
    on the rx paths and house() in housekeeping; GuardedVerifier calls
    dispatch().  All decisions are driven by one seeded Generator, so a
    fixed (plan, traffic) pair replays the exact same failure sequence."""

    def __init__(self, tile_name: str, knobs: dict):
        self.tile = tile_name
        self.knobs = dict(knobs)
        seed = int(knobs.get("seed", 0))
        # fold the tile name in so verify:0 and verify:1 diverge under the
        # same plan seed
        self._rng = np.random.default_rng(
            (seed << 16) ^ zlib.crc32(tile_name.encode()))
        self.frag_cnt = 0
        self.dispatch_cnt = 0
        self._kill_after = int(knobs.get("kill_after_frags", 0))
        self._delay_s = float(knobs.get("delay_frag_us", 0)) * 1e-6
        self._drop_p = float(knobs.get("drop_frag_p", 0.0))
        self._corrupt_p = float(knobs.get("corrupt_payload_p", 0.0))
        self._fail_p = float(knobs.get("fail_dispatch_p", 0.0))
        self._fail_n = int(knobs.get("fail_dispatch_n", 0))
        self._stall_s = float(knobs.get("stall_heartbeat_s", 0.0))
        self._stalled = False
        self._kill_pending = False

    # -- shared per-frag machinery ----------------------------------------
    def _maybe_kill(self):
        if self._kill_pending:
            os._exit(KILL_EXIT_CODE)

    def _tick(self):
        """Count one received frag; kill/delay per plan.  The kill fires
        BEFORE the frag is processed or acked (at-least-once handoff to
        the respawned incarnation, never a duplicate verdict)."""
        self._maybe_kill()
        self.frag_cnt += 1
        if self._kill_after and self.frag_cnt >= self._kill_after:
            os._exit(KILL_EXIT_CODE)
        if self._delay_s:
            time.sleep(self._delay_s)

    def _tick_batch(self, n: int) -> int:
        """Count n received frags at once; returns how many leading frags
        may still be processed.  When the kill threshold lands inside the
        batch, the kill is DEFERRED to the next fault-point entry (the
        frag boundary) rather than fired mid-batch: the allowed prefix is
        processed, span-recorded and acked exactly like the scalar path,
        where every frag before the threshold completes.  The trailing
        frags of the killing batch are acked-but-unprocessed — the same
        outage-loss semantics dead-consumer eviction applies for the rest
        of the downtime."""
        self._maybe_kill()
        take = n
        if self._kill_after:
            allowed = self._kill_after - 1 - self.frag_cnt
            if allowed < n:
                take = max(0, allowed)
                self._kill_pending = True
        self.frag_cnt += n
        if self._delay_s and take:
            time.sleep(self._delay_s * take)
        return take

    def _flip(self, buf, lo: int, hi: int):
        """Deterministically flip one bit of buf[lo:hi] (uint8 view)."""
        if hi <= lo:
            return
        i = lo + int(self._rng.integers(hi - lo))
        buf[i] ^= np.uint8(1 << int(self._rng.integers(8)))

    # -- mux rx fault points ----------------------------------------------
    def frag(self, payload):
        """Scalar rx path: returns (payload, drop)."""
        self._tick()
        if self._drop_p and self._rng.random() < self._drop_p:
            return payload, True
        if self._corrupt_p and payload and self._rng.random() < self._corrupt_p:
            b = bytearray(payload)
            arr = np.frombuffer(b, np.uint8)
            self._flip(arr, 0, len(arr))
            payload = bytes(b)
        return payload, False

    def frags_view(self, metas, dcache):
        """Zero-copy view rx path: metas stay in place, payload bytes live
        in the shm dcache.  Returns (metas', n_dropped); corruption mutates
        the dcache in place (the consumer reads the flipped bytes, exactly
        like wire corruption that beat the producer's checksum)."""
        take = self._tick_batch(len(metas))
        if take < len(metas):
            metas = metas[:take]
        keep = None
        for j in range(len(metas)):
            if self._drop_p and self._rng.random() < self._drop_p:
                if keep is None:
                    keep = np.ones(len(metas), bool)
                keep[j] = False
                continue
            if self._corrupt_p and self._rng.random() < self._corrupt_p:
                view = dcache.view(int(metas[j]["chunk"]), 64)
                self._flip(view, 0, 64)
        if keep is None:
            return metas, 0
        return metas[keep], int((~keep).sum())

    def burst(self, kept: int, buf, offs) -> int:
        """Native rx_burst path: frags were already copied out; supports
        kill/delay/corrupt (no drop — the burst is committed at the ring).
        Returns the number of leading frags the mux may hand to the tile
        (kept, unless the kill threshold lands inside this burst)."""
        take = self._tick_batch(kept)
        for j in range(take):
            if self._corrupt_p and self._rng.random() < self._corrupt_p:
                self._flip(buf, int(offs[j]), int(offs[j + 1]))
        return take

    # -- verifier dispatch fault point ------------------------------------
    def dispatch(self):
        self.dispatch_cnt += 1
        if self._fail_n and self.dispatch_cnt <= self._fail_n:
            raise InjectedDispatchError(
                f"{self.tile}: injected dispatch failure "
                f"#{self.dispatch_cnt}/{self._fail_n}")
        if self._fail_p and self._rng.random() < self._fail_p:
            raise InjectedDispatchError(
                f"{self.tile}: injected dispatch failure (p={self._fail_p})")

    # -- housekeeping fault point -----------------------------------------
    def house(self):
        # a batch-deferred kill must fire even with nothing inbound: the
        # housekeeping cadence (~20ms) bounds how long the corpse lingers
        self._maybe_kill()
        if self._stall_s and not self._stalled:
            self._stalled = True
            time.sleep(self._stall_s)


def fleet_faults(environ=os.environ, cfg: dict | None = None,
                 boot_gen: int = 0) -> "FleetFaultPlan | None":
    """Fleet-grade fault plan for the fleet supervisor (disco/fleet.py).

    Rides the same FDTPU_FAULTS grammar under the reserved tile name
    `fleet` (or a `[fleet]` cfg `faults` string), seeded and
    boot-generation-gated exactly like the per-tile knobs:

        FDTPU_FAULTS="fleet=host_kill:1,after_capture:40,boot:0"
        FDTPU_FAULTS="fleet=partition:0-2,seed:7"

    Knobs:

        host_kill:I       SIGKILL host supervisor I's whole process group
                          (tiles included — the host-loss chaos drill)
        after_capture:N   arm the kill only once the doomed host has
                          exported >= N verdicts (default 1: the kill
                          always lands mid-load, never on an idle host)
        kill_jitter_s:S   add rng.uniform(0, S) seconds after arming
                          before the kill fires (seeded -> replayable)
        partition:A-B     drop control-ring gossip both ways between
                          hosts A and B (repeatable: "0-1", "0-2" via
                          multiple FDTPU_FAULTS terms or a+semicolons)
        seed:K            rng seed (folded with 'fleet')
        boot:G            plan applies only to fleet boot generation G
                          (a host respawned by the fleet runs gen 1, 2…)
    """
    knobs = {}
    env_text = environ.get("FDTPU_FAULTS", "")
    if env_text:
        knobs.update(plan_for("fleet", parse_plan(env_text)) or {})
    f = (cfg or {}).get("faults")
    if isinstance(f, str) and f:
        knobs.update(plan_for("fleet", parse_plan(f)) or {})
    elif isinstance(f, dict):
        knobs.update(f)
    if not knobs:
        return None
    gen = knobs.get("boot")
    if gen is not None and int(gen) != int(boot_gen):
        return None
    return FleetFaultPlan(knobs)


class FleetFaultPlan:
    """Armed fleet fault plan (host_kill / partition).  The fleet
    supervisor polls should_kill() with each host's exported-verdict
    count; partitioned() gates the control-ring packet pump."""

    def __init__(self, knobs: dict):
        self.knobs = dict(knobs)
        seed = int(knobs.get("seed", 0))
        self._rng = np.random.default_rng(
            (seed << 16) ^ zlib.crc32(b"fleet"))
        hk = knobs.get("host_kill")
        self.host_kill = None if hk is None else int(hk)
        self.after_capture = int(knobs.get("after_capture", 1))
        jitter = float(knobs.get("kill_jitter_s", 0.0))
        self._kill_delay_s = float(self._rng.uniform(0.0, jitter)) \
            if jitter > 0 else 0.0
        self._armed_at = None
        self.fired = False
        self.partitions: set[frozenset] = set()
        p = knobs.get("partition")
        for term in (str(p).split("+") if p is not None else ()):
            a, _, b = term.partition("-")
            try:
                self.partitions.add(frozenset((int(a), int(b))))
            except ValueError:
                continue

    def should_kill(self, host_idx: int, captured_cnt: int) -> bool:
        """True exactly once, when the doomed host crosses the
        after_capture threshold (+ seeded jitter)."""
        if self.fired or self.host_kill is None \
                or int(host_idx) != self.host_kill:
            return False
        if captured_cnt < self.after_capture:
            return False
        now = time.monotonic()
        if self._armed_at is None:
            self._armed_at = now
        if now - self._armed_at < self._kill_delay_s:
            return False
        self.fired = True
        return True

    def partitioned(self, a: int, b: int) -> bool:
        return frozenset((int(a), int(b))) in self.partitions

    def partition_peers(self, host_idx: int) -> set[int]:
        """Hosts this host must drop gossip from (both directions)."""
        out = set()
        for pair in self.partitions:
            if int(host_idx) in pair:
                out |= {p for p in pair if p != int(host_idx)}
        return out


class WireFaultGen:
    """Seeded generator of hostile QUIC wire traffic for front-door chaos
    (the out-of-band half of the reference's quic fuzz targets: we attack
    the real socket, not the parser in isolation).

    Everything is plain bytes: callers sendto() the datagrams from
    whatever spoofed/secondary source address the scenario needs.  Forged
    Initials are AEAD-valid under the dcid-derived v1 Initial keys, so
    they pass the server's admission probe and cost it real conn state —
    exactly the handshake-flood shape the Retry threshold exists for.
    `malformed()` emits the cheap attacks that must die in the header
    parser / AEAD probe without touching conn state.
    """

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)

    def _rand(self, n: int) -> bytes:
        return self._rng.integers(0, 256, n, dtype=np.uint8).tobytes()

    def forged_initial(self, dcid: bytes | None = None,
                       scid: bytes | None = None, token: bytes = b"",
                       payload: bytes | None = None) -> tuple:
        """One AEAD-valid client Initial datagram (PING + PADDING payload
        by default).  Returns (datagram, dcid, scid); a fresh random
        dcid/scid pair per call makes each datagram a new-conn attempt."""
        from ..waltz import quic as q
        if dcid is None:
            dcid = self._rand(q.CID_SZ)
        if scid is None:
            scid = self._rand(q.CID_SZ)
        if payload is None:
            payload = b"\x01" + b"\x00" * 47  # PING + PADDING
        _, tx = q.initial_keys(dcid, is_server=False)  # client tx keys
        pn = 0
        hdr = (bytes([0xC0 | 0x03])  # long hdr, Initial, pn_len=4
               + q.QUIC_VERSION.to_bytes(4, "big")
               + bytes([len(dcid)]) + dcid
               + bytes([len(scid)]) + scid
               + q.enc_varint(len(token)) + token
               + q.enc_varint(4 + len(payload) + 16))
        header = hdr + pn.to_bytes(4, "big")
        ct = tx.aead.encrypt(tx.nonce(pn), payload, header)
        pkt = bytearray(header + ct)
        pn_off = len(hdr)
        sample = bytes(pkt[pn_off + 4 : pn_off + 20])
        mask = q.aes_encrypt_block(tx.hp_rk, sample)
        pkt[0] ^= mask[0] & 0x0F
        for i in range(4):
            pkt[pn_off + i] ^= mask[1 + i]
        return bytes(pkt), dcid, scid

    def conn_flood(self, n: int) -> list:
        """n half-open handshake attempts: AEAD-valid Initials, each a
        distinct conn, none of which will ever complete the handshake."""
        return [self.forged_initial()[0] for _ in range(n)]

    @staticmethod
    def redeem_retry(datagram: bytes) -> tuple | None:
        """Parse a server Retry datagram -> (retry_scid, token), or None.
        Lets a flood scenario prove the token round-trip still admits a
        validated client while the threshold is tripped."""
        if not datagram or (datagram[0] & 0xF0) != 0xF0 or len(datagram) < 23:
            return None
        p = 5
        p += 1 + datagram[p]                 # dcid (our scid echo)
        scid_len = datagram[p]
        retry_scid = bytes(datagram[p + 1 : p + 1 + scid_len])
        p += 1 + scid_len
        token = bytes(datagram[p : len(datagram) - 16])
        return retry_scid, token

    def malformed(self, n: int, template: bytes | None = None) -> list:
        """n deterministic malformed datagrams cycling four mutation
        modes: pure garbage, truncation, single-bit flips, and bogus CID
        lengths.  All must be shed in the parser/AEAD probe — zero conn
        state, zero crashes."""
        if template is None:
            template = self.forged_initial()[0]
        out = []
        for i in range(n):
            mode = i % 4
            if mode == 0:    # garbage with a long-header-looking first byte
                g = bytearray(self._rand(1 + int(self._rng.integers(8, 96))))
                g[0] |= 0x80
                out.append(bytes(g))
            elif mode == 1:  # truncated real packet
                cut = 1 + int(self._rng.integers(len(template) - 1))
                out.append(template[:cut])
            elif mode == 2:  # bit-flipped real packet (breaks HP/AEAD)
                b = bytearray(template)
                j = int(self._rng.integers(len(b)))
                b[j] ^= 1 << int(self._rng.integers(8))
                out.append(bytes(b))
            else:            # bogus CID length byte -> parser walks off
                b = bytearray(template)
                b[5] = 0xFF
                out.append(bytes(b))
        return out

    def oversize_stream_payload(self, size: int) -> bytes:
        """A txn-shaped blob far past TXN_MTU / the reasm budget."""
        return self._rand(size)

    @staticmethod
    def partial_stream_frame(sid: int, off: int, data: bytes) -> bytes:
        """A STREAM frame with OFF|LEN set but NO FIN (type 0x0E): the
        slowloris building block — the server must buffer it in reasm
        and may never see the end."""
        from ..waltz.quic import enc_varint
        return (bytes([0x08 | 0x04 | 0x02]) + enc_varint(sid)
                + enc_varint(off) + enc_varint(len(data)) + data)
