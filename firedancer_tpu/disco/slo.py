"""Stage-budget SLO engine: fold fdtrace spans into the named stage
pipeline wire -> reasm -> ring-wait -> coalesce -> dispatch-queue ->
device -> harvest -> publish and grade each stage against its share of
the end-to-end latency target (ROADMAP: 2 ms p99 packet->verdict).

Every stage maps to a span source that already exists in the live trace
rings (disco/trace.py) — the engine is a pure reader:

    wire            quic_server KIND_STAGE spans (socket drain + QUIC rx)
    reasm           ingress-tile KIND_FRAG/BURST callback durations
    ring-wait       verify-tile KIND_FRAG/BURST hop_ns (producer tspub ->
                    consume: time spent queued in the tango ring)
    coalesce        KIND_COALESCE (first txn in bucket -> dispatch)
    dispatch-queue  KIND_DISPATCH (dispatch call + over-budget drain)
    device          KIND_DEVICE (dispatch -> verdict materialized)
    harvest         KIND_HARVEST (verdict -> passing txns rebuilt)
    publish         KIND_PUBLISH (txns -> downstream ring publish)

The budget split is a fixed fraction per stage of the e2e target (the
device leg dominates by design; the host stages exist to stay small).
Burn rate is measured from the terminal tiles' whole-chain age stamps
(frag-meta tsorig -> consume): the fraction of chain completions in the
window whose age exceeded the target, with a first-half/second-half
trend so a worsening burn is visible before the window saturates.
"""

import numpy as np

from ..utils.hist import Histf
from . import trace as trace_mod

DEFAULT_TARGET_MS = 2.0

STAGES = ["wire", "reasm", "ring-wait", "coalesce", "dispatch-queue",
          "device", "harvest", "publish"]

# fraction of the e2e target each stage may burn at p99 (sums to 1.0)
BUDGET_FRAC = {
    "wire": 0.05, "reasm": 0.05, "ring-wait": 0.10, "coalesce": 0.20,
    "dispatch-queue": 0.10, "device": 0.35, "harvest": 0.10,
    "publish": 0.05,
}

# tile kinds whose frag callbacks ARE the reassembly/parse stage
_INGRESS_KINDS = {"source", "net", "quic", "quic_server"}
# tile kinds that run the verify pipeline (ring-wait measured here)
_VERIFY_KINDS = {"verify"}
# tile kinds downstream of verify: their age_ns is the whole-chain
# latency the SLO grades (first match wins as the burn source)
_TERMINAL_KINDS = {"dedup", "sink", "pack", "bank", "store"}

_RX_KINDS = (trace_mod.KIND_FRAG, trace_mod.KIND_BURST)


def collect(jt, since: int = 0):
    """Snapshot every tile's trace ring -> (spans_by_tile, kind_of)."""
    spans, kind_of = {}, {}
    for tname, ring in jt.trace.items():
        _, recs = ring.snapshot(since)
        spans[tname] = recs
        kind_of[tname] = jt.tile_spec(tname).kind
    return spans, kind_of


def _rx_mask(recs):
    return (recs["kind"] == _RX_KINDS[0]) | (recs["kind"] == _RX_KINDS[1])


def stage_samples(spans_by_tile, kind_of) -> dict[str, np.ndarray]:
    """Per stage, the ns samples (one per span) feeding its p50/p99."""
    out = {s: [] for s in STAGES}
    for tile, recs in spans_by_tile.items():
        if not len(recs):
            continue
        kind = kind_of.get(tile, "")
        if kind in _INGRESS_KINDS:
            rx = recs[_rx_mask(recs)]
            if len(rx):
                out["reasm"].append(rx["dur"].astype(np.int64))
            st = recs[recs["kind"] == trace_mod.KIND_STAGE]
            if len(st):
                out["wire"].append(st["dur"].astype(np.int64))
        if kind in _VERIFY_KINDS:
            rx = recs[_rx_mask(recs)]
            hops = rx["hop_ns"][rx["hop_ns"] > 0]
            if len(hops):
                out["ring-wait"].append(hops.astype(np.int64))
        for stage, k in (("coalesce", trace_mod.KIND_COALESCE),
                         ("dispatch-queue", trace_mod.KIND_DISPATCH),
                         ("device", trace_mod.KIND_DEVICE),
                         ("harvest", trace_mod.KIND_HARVEST),
                         ("publish", trace_mod.KIND_PUBLISH)):
            sel = recs[recs["kind"] == k]
            if len(sel):
                out[stage].append(sel["dur"].astype(np.int64))
    return {s: (np.concatenate(v) if v else np.zeros(0, np.int64))
            for s, v in out.items()}


def _pctl(samples: np.ndarray, q: float) -> float:
    if not len(samples):
        return 0.0
    # vectorized Histf fill (healthz calls this per scrape): same edges,
    # same first-bucket-reaching-ceil(q*total) percentile
    h = Histf(100, 60e9)
    idx = np.searchsorted(h.edges, np.maximum(samples, 1))
    np.add.at(h.counts, idx, 1)
    return h.percentile(q)


def stage_stats(spans_by_tile, kind_of,
                target_ms: float = DEFAULT_TARGET_MS) -> list[dict]:
    """One row per stage: sample count, p50/p99 ns, budget ns, pass."""
    target_ns = target_ms * 1e6
    rows = []
    samples_all = stage_samples(spans_by_tile, kind_of)
    for stage in STAGES:
        s = samples_all[stage]
        budget = BUDGET_FRAC[stage] * target_ns
        p50 = _pctl(s, 0.50)
        p99 = _pctl(s, 0.99)
        rows.append({
            "stage": stage, "n": int(len(s)), "p50_ns": p50, "p99_ns": p99,
            "budget_ns": budget, "ok": (len(s) == 0) or (p99 <= budget),
        })
    return rows


def burn(spans_by_tile, kind_of,
         target_ms: float = DEFAULT_TARGET_MS) -> dict:
    """Window burn rate from whole-chain age stamps: fraction of chain
    completions whose age exceeded the e2e target, with a first/second
    half split (by span ts) for trend."""
    target_ns = target_ms * 1e6
    ages, ts = [], []
    # terminal tiles first; any tile with age stamps as the fallback so
    # a verify-terminated topology still grades (verify's own age = the
    # chain up to dispatch admission)
    for pick_terminal in (True, False):
        for tile, recs in spans_by_tile.items():
            is_term = kind_of.get(tile, "") in _TERMINAL_KINDS
            if pick_terminal != is_term or not len(recs):
                continue
            rx = recs[_rx_mask(recs)]
            rx = rx[rx["age_ns"] > 0]
            if len(rx):
                ages.append(rx["age_ns"].astype(np.int64))
                ts.append(rx["ts"].astype(np.int64))
        if ages:
            break
    if not ages:
        return {"n": 0, "rate": 0.0, "rate_first": 0.0,
                "rate_second": 0.0, "trend": "flat"}
    age = np.concatenate(ages)
    t = np.concatenate(ts)
    viol = age > target_ns
    mid = np.median(t)
    first, second = viol[t <= mid], viol[t > mid]
    rf = float(first.mean()) if len(first) else 0.0
    rs = float(second.mean()) if len(second) else 0.0
    trend = "up" if rs > rf + 0.01 else ("down" if rf > rs + 0.01
                                         else "flat")
    return {"n": int(len(age)), "rate": float(viol.mean()),
            "rate_first": rf, "rate_second": rs, "trend": trend}


def render_table(stats: list[dict], burn_info: dict,
                 target_ms: float = DEFAULT_TARGET_MS) -> str:
    """Terminal stage-budget table (`fdtpuctl slo`)."""
    lines = [f"stage budget vs {target_ms:g} ms p99 e2e target",
             f"{'STAGE':<16}{'SPANS':>7}{'p50':>10}{'p99':>10}"
             f"{'BUDGET':>10}  VERDICT"]

    def _ms(v):
        return f"{v / 1e6:.3f}ms" if v else "-"

    for r in stats:
        verdict = "-" if r["n"] == 0 else ("ok" if r["ok"] else "OVER")
        lines.append(
            f"{r['stage']:<16}{r['n']:>7}{_ms(r['p50_ns']):>10}"
            f"{_ms(r['p99_ns']):>10}{_ms(r['budget_ns']):>10}  {verdict}")
    b = burn_info
    lines.append(
        f"burn rate: {b['rate']:.1%} of {b['n']} chain completions over "
        f"target (first half {b['rate_first']:.1%}, second "
        f"{b['rate_second']:.1%}, trend {b['trend']})")
    return "\n".join(lines)


def healthz_field(jt, target_ms: float = DEFAULT_TARGET_MS) -> str:
    """One-line slo summary for /healthz: worst over-budget stage (or
    ok) + burn rate — degraded latency visible without a trace dump."""
    spans, kind_of = collect(jt)
    stats = stage_stats(spans, kind_of, target_ms)
    b = burn(spans, kind_of, target_ms)
    over = [r for r in stats if r["n"] and not r["ok"]]
    if over:
        worst = max(over, key=lambda r: r["p99_ns"] / max(r["budget_ns"], 1))
        state = (f"over:{worst['stage']} "
                 f"p99={worst['p99_ns'] / 1e6:.3f}ms")
    else:
        state = "ok"
    return f"slo {state} burn={b['rate']:.3f} n={b['n']}"
