"""Flight recorder: crash postmortem bundles (ref: the reference leaves
its tango workspaces behind after a tile crash so fd_monitor can inspect
the corpse; our supervisor respawns tiles into the SAME workspace, which
heals the topology but overwrites the evidence — so the supervisor
snapshots it first).

A bundle is one directory under `[observability] flight_dir`:

    manifest.json   app, reason, dead tile, creation time, per-tile kind
                    + restart count + cnc state, span counts
    spans.npz       last-N trace spans per tile (TRACE_REC_DTYPE)
    metrics.json    per-tile metrics slots + shm histograms
    links.json      per-link fctl/fseq state (seq, lag, diag) + the
                    producer-side out{j}_* gauges (disco/attrib.py)
    config.json     the resolved config the topology ran with
    events.log      the supervisor's event log (spawn/respawn/degrade...)

`fdtpuctl postmortem <bundle>` renders it: hop table + stage budgets +
bottleneck verdict at time of death + the dead tile's final spans.
"""

import json
import os
import time

import numpy as np

from ..tango.ring import Cnc
from . import attrib
from . import slo
from . import trace as trace_mod

SPANS_PER_TILE = 2048   # last-N spans kept per tile in a bundle

_SIG_NAMES = {Cnc.SIGNAL_RUN: "run", Cnc.SIGNAL_BOOT: "boot",
              Cnc.SIGNAL_FAIL: "FAIL", Cnc.SIGNAL_HALT: "halt",
              Cnc.SIGNAL_DRAIN: "drain", Cnc.SIGNAL_DRAINED: "drained"}


def write_bundle(flight_dir: str, jt, *, reason: str, tile: str = "",
                 restarts: dict | None = None, config: dict | None = None,
                 events: list | None = None,
                 autotune: list | None = None) -> str:
    """Snapshot the joined topology into a new bundle directory; returns
    its path.  Read-only over the workspace — safe to call while tiles
    run (the snapshot contract every reader in this repo follows)."""
    spec = jt.spec
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    name = f"{spec.app}-{reason}-{stamp}-{os.getpid()}"
    path = os.path.join(flight_dir, name)
    n = 0
    while os.path.exists(path):  # same second, same pid: disambiguate
        n += 1
        path = os.path.join(flight_dir, f"{name}.{n}")
    os.makedirs(path)

    spans = {}
    span_cnt = {}
    for tname, ring in jt.trace.items():
        _, recs = ring.snapshot(0)
        spans[tname] = recs[-SPANS_PER_TILE:]
        span_cnt[tname] = int(len(spans[tname]))
    np.savez(os.path.join(path, "spans.npz"), **spans)

    metrics = {}
    for tname, blk in jt.metrics.items():
        hists = {}
        for hname in blk.hist_names():
            edges, counts, hsum = blk.hist_snapshot(hname)
            hists[hname] = {"edges": [float(e) for e in edges],
                            "counts": [int(c) for c in counts],
                            "sum": hsum}
        metrics[tname] = {"slots": blk.snapshot(), "hists": hists}
    with open(os.path.join(path, "metrics.json"), "w") as f:
        json.dump(metrics, f)

    sample = attrib.link_sample(jt)
    links = {"t": sample["t"],
             "links": {f"{ln}|{cons}": lv
                       for (ln, cons), lv in sample["links"].items()},
             "tiles": sample["tiles"]}
    with open(os.path.join(path, "links.json"), "w") as f:
        json.dump(links, f)

    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(config or {}, f, default=str)

    with open(os.path.join(path, "events.log"), "w") as f:
        f.write("\n".join(events or []) + ("\n" if events else ""))

    if autotune is not None:
        # the autotuner's decision ring (disco/autotune.py): every knob
        # move that led here, rendered by `fdtpuctl postmortem`
        with open(os.path.join(path, "autotune.json"), "w") as f:
            json.dump(list(autotune), f)

    manifest = {
        "app": spec.app, "reason": reason, "tile": tile,
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "tiles": {t.name: {
            "kind": t.kind,
            "restarts": int((restarts or {}).get(t.name, 0)),
            "cnc": _SIG_NAMES.get(jt.cnc[t.name].signal_query(), "?"),
            "spans": span_cnt.get(t.name, 0),
        } for t in spec.tiles},
    }
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    return path


def rotate(flight_dir: str, max_bundles: int) -> int:
    """Oldest-bundle rotation ([observability] flight_max_bundles): keep
    the newest `max_bundles` bundle dirs, delete the rest.  Returns the
    number evicted (fdtpu_flightrec_evict_cnt) — a crash loop under
    autotune experimentation must never fill the disk."""
    if max_bundles <= 0:
        return 0
    try:
        entries = [os.path.join(flight_dir, d)
                   for d in os.listdir(flight_dir)]
    except OSError:
        return 0
    bundles = [p for p in entries
               if os.path.isdir(p)
               and os.path.exists(os.path.join(p, "manifest.json"))]
    if len(bundles) <= max_bundles:
        return 0
    import shutil
    bundles.sort(key=os.path.getmtime)
    evicted = 0
    for p in bundles[:len(bundles) - max_bundles]:
        try:
            shutil.rmtree(p)
            evicted += 1
        except OSError:
            pass
    return evicted


def load_bundle(path: str) -> dict:
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    with np.load(os.path.join(path, "spans.npz")) as z:
        spans = {k: np.asarray(z[k], dtype=trace_mod.TRACE_REC_DTYPE)
                 for k in z.files}
    with open(os.path.join(path, "metrics.json")) as f:
        metrics = json.load(f)
    with open(os.path.join(path, "links.json")) as f:
        links = json.load(f)
    with open(os.path.join(path, "config.json")) as f:
        config = json.load(f)
    events = []
    ev_path = os.path.join(path, "events.log")
    if os.path.exists(ev_path):
        with open(ev_path) as f:
            events = [ln for ln in f.read().splitlines() if ln]
    autotune = []
    at_path = os.path.join(path, "autotune.json")
    if os.path.exists(at_path):
        with open(at_path) as f:
            autotune = json.load(f)
    return {"path": path, "manifest": manifest, "spans": spans,
            "metrics": metrics, "links": links, "config": config,
            "events": events, "autotune": autotune}


def render_bundle(path: str, target_ms: float | None = None) -> str:
    """Terminal postmortem: what the topology looked like when it died
    (`fdtpuctl postmortem <bundle>`)."""
    b = load_bundle(path)
    man = b["manifest"]
    if target_ms is None:
        target_ms = float(
            b["config"].get("observability", {}).get(
                "slo_target_ms", slo.DEFAULT_TARGET_MS))
    lines = [f"flight recorder bundle: {b['path']}",
             f"app {man['app']}  reason {man['reason']}"
             + (f"  tile {man['tile']}" if man.get("tile") else "")
             + f"  created {man['created']}", ""]
    lines.append(f"{'TILE':<14}{'KIND':<12}{'CNC':<6}{'RESTARTS':>9}"
                 f"{'SPANS':>7}")
    for tname, tv in man["tiles"].items():
        lines.append(f"{tname:<14}{tv['kind']:<12}{tv['cnc']:<6}"
                     f"{tv['restarts']:>9}{tv['spans']:>7}")

    kind_of = {t: tv["kind"] for t, tv in man["tiles"].items()}
    lines += ["", trace_mod.hop_table(b["spans"]), ""]
    stats = slo.stage_stats(b["spans"], kind_of, target_ms)
    burn = slo.burn(b["spans"], kind_of, target_ms)
    lines += [slo.render_table(stats, burn, target_ms), ""]

    # bottleneck at time of death, from the bundled link snapshot
    sample = {"t": b["links"]["t"],
              "links": {tuple(k.split("|", 1)): v
                        for k, v in b["links"]["links"].items()},
              "tiles": b["links"]["tiles"]}
    link, why = attrib.snapshot_verdict(sample)
    lines.append(f"bottleneck at death: {link} ({why})")

    dead = man.get("tile")
    if dead and dead in b["spans"] and len(b["spans"][dead]):
        lines += ["", f"final spans of {dead}:"]
        for r in b["spans"][dead][-10:]:
            kname = trace_mod.KIND_NAMES.get(int(r["kind"]),
                                             str(int(r["kind"])))
            lines.append(
                f"  ts={int(r['ts'])} {kname:<9} dur={int(r['dur'])}ns"
                f" cnt={int(r['cnt'])} seq={int(r['seq'])}")
    if b["events"]:
        lines += ["", "supervisor events (tail):"]
        lines += [f"  {ln}" for ln in b["events"][-15:]]
    if b.get("autotune"):
        from . import autotune as autotune_mod
        lines += ["", "autotune decision history:",
                  autotune_mod.render_decisions(b["autotune"])]
    return "\n".join(lines)
