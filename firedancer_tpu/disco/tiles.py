"""Production tile implementations + registry (ref: the fd_topo_run_tile_t
vtables in src/app/fdctl/run/tiles/ and the TILES[] registry in
src/app/fdctl/main.c:33-48).

A tile is a class with any subset of the mux callbacks (disco/mux.py).  The
registry maps kind -> class; fd_topo_run looks tiles up by TileSpec.kind.

The data plane mirrors the reference's frankendancer flow (SURVEY.md §1):

    source/net -> verify -> dedup -> pack -> bank -> sink

with the TPU twist in the verify tile: txn signatures from many frags are
coalesced into one fixed-shape device batch, flushed on size or age
(wiredancer's async insertion point, SURVEY.md §3.2), instead of the
reference's synchronous per-frag batch-of-<=16 verify.
"""

import os
import struct
import time
from collections import OrderedDict

import numpy as np

from ..ballet import txn as txn_lib
from ..tango.tcache import TCache
from ..utils import log
from . import trace as trace_mod
from .pipeline import (DEFAULT_LAT_SHAPES, LAT_PRIO_BIT, PackedVerdicts,
                       VerifyPipeline)


def source_txn_stream(seed: int, keys: int = 4, count: int = 0,
                      start: int = 0):
    """Regenerate the (tag, wire) stream a standalone non-burst
    SourceTile with cfg {seed, keys, count} publishes, without a
    topology: same rng recipe (key pool, blockhash, program id all
    drawn from default_rng(seed) in init order), same per-txn build.
    The tag is the wire's sig[0:8] LE — exactly the sig the verify
    tile stamps on the frag and the sink capture records.

    This is the fleet layer's replay surface: a failover host adopts a
    dead host's stream by re-running this generator (SourceTile
    `adopt_streams`), and the chaos harness derives the injected-txn
    universe from it for the exactly-once assertion."""
    from ..ops import ed25519 as ed
    rng = np.random.default_rng(int(seed))
    seeds = [rng.bytes(32) for _ in range(int(keys))]
    blockhash = rng.bytes(32)
    pool = [(s, ed.keypair_from_seed(s)[0]) for s in seeds]
    program = rng.bytes(32)
    i = int(start)
    while count == 0 or i < int(count):
        seed_i, pub = pool[i % len(pool)]
        msg = txn_lib.build_unsigned(
            [pub], blockhash, [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[program])
        sig = ed.sign(seed_i, msg)
        yield (int.from_bytes(sig[:8], "little"),
               txn_lib.assemble([sig], msg))
        i += 1


class SourceTile:
    """Synthetic signed-txn generator (the fddev benchg analogue,
    src/app/fddev/tiles/fd_benchg.c): publishes `count` distinct valid
    txns then idles (count=0 -> unbounded).

    Two modes: standalone (default) signs with fresh keys against a random
    blockhash — enough for the verify path; executable=True generates REAL
    system transfers from cfg `seeds` (hex, funded in genesis) against
    cfg `blockhash`, so a downstream bank tile can execute them."""

    def init(self, ctx):
        from ..ops import ed25519 as ed
        cfg = ctx.cfg
        self.count = cfg.get("count", 0)
        self.executable = cfg.get("executable", False)
        self.pool = []
        # blockhash feedback (fddev benchg refreshes its blockhash the
        # same way over RPC): any in-link named *blockhash carries the
        # bank's latest root hash; txns sign against it from then on
        self._bh_ins = {
            i for i, il in enumerate(ctx.tile.in_links)
            if il.link.endswith("blockhash")}
        rng = np.random.default_rng(cfg.get("seed", 42))
        if self.executable:
            from ..flamenco.system_program import ix_transfer
            from ..flamenco.types import SYSTEM_PROGRAM_ID
            self._ix_transfer = ix_transfer
            self._system_id = SYSTEM_PROGRAM_ID
            seeds = [bytes.fromhex(s) for s in cfg["seeds"]]
            self.blockhash = bytes.fromhex(cfg["blockhash"])
        else:
            seeds = [rng.bytes(32) for _ in range(cfg.get("keys", 4))]
            self.blockhash = rng.bytes(32)
        for seed in seeds:
            pub, _, _ = ed.keypair_from_seed(seed)
            self.pool.append((seed, pub))
        self.program = rng.bytes(32)
        self.sent = 0
        self._ed = ed
        self._rng = rng
        # optional pacing (benchg's tps knob): min ns between txns, so
        # feedback topologies exercise refresh cycles instead of racing
        # the whole count out against the boot blockhash
        self.rate_ns = cfg.get("rate_ns", 0)
        self._last_gen_ns = 0
        # with a feedback link, hold generation until the bank's first
        # blockhash heartbeat arrives: txns pre-signed against the boot
        # hash while downstream tiles compile would all age out
        # (benchg's RPC-blockhash-first behaviour)
        self._bh_seen = not (cfg.get("wait_blockhash", True)
                             and self._bh_ins)
        # burst firehose mode (round 4): burst_n > 0 pre-builds one signed
        # template and stamps out `burst_n` txns per loop in numpy — unique
        # signature tag + unique instr data per txn, one native burst
        # publish.  Host signing (1 ms/python-int sign) would cap a source
        # at ~1 K/s; the verify DEVICE cost is identical for the stamped
        # copies because the verify graph is fixed-shape and
        # data-independent, so this is the honest firehose for throughput
        # work (the same trick bench.py's latency section documents).
        # NOTE: every stamped txn fails sigverify (the tag overwrite
        # invalidates each row's signature), so nothing flows PAST the
        # verify tile — burst_n measures ingest->verify throughput at the
        # verify tiles' own counters; topologies needing executable flow
        # downstream use executable=True without burst_n.
        self._burst_n = int(cfg.get("burst_n", 0))
        # latency-class tagging (round 9): every `lat_every`-th txn is
        # published with LAT_PRIO_BIT set on its frag meta sig, marking
        # it for the verify tile's low-latency lane — the mixed
        # bulk+latency load the dual-lane bench and CI smoke drive.  0
        # (default) = no tagging.  The bit rides the META only; payload
        # sig bytes (the dedup tag) stay the clean value.  Packed-wire
        # mode stays bulk-only: one frag is one whole device blob, so a
        # per-txn class bit has no sub-frag routing to do there.
        self._lat_every = max(0, int(cfg.get("lat_every", 0)))
        # fleet failover adoption (round 17): `adopt_streams` is a list of
        # {"seed", "keys", "count"} stream specs from dead hosts; their
        # txns are regenerated (source_txn_stream) and published FIRST —
        # the in-flight work a failover host takes over.  Already-verified
        # sigs among them are rejected downstream (dedup preload /
        # verify tcache), so adoption never double-verdicts.
        self._adopt = []
        for st in (cfg.get("adopt_streams") or []):
            self._adopt.append(source_txn_stream(
                int(st["seed"]), int(st.get("keys", 4)),
                int(st.get("count", 0))))
        if self._burst_n:
            tpl = np.frombuffer(self._make_txn(0), np.uint8).copy()
            self._tpl = tpl
            self._tpl_len = len(tpl)
        # packed-wire firehose (round 8): the source writes frags ALREADY
        # in device-blob row layout (msg | sig64 | pub32 | len-le32, row
        # stride chunk-aligned via packed_row_ml) straight into the dcache
        # through ctx.out_reserve — one frag = one packed burst of
        # `packed_rows` rows, meta.sz carries the row count.  Downstream
        # the verify tile dispatches the dcache region as the device blob
        # with ZERO payload copies in between.  Same honesty note as
        # burst_n: tag stamping invalidates each row's signature.
        self._packed_rows = int(cfg.get("packed_rows", 0))
        if self._packed_rows:
            from ..tango.ring import PACKED_ROW_EXTRA, packed_row_ml
            ml = int(cfg.get("packed_ml") or packed_row_ml(256))
            stride = ml + PACKED_ROW_EXTRA
            wire = self._make_txn(0)
            msg, sig = wire[65:], wire[1:65]
            if len(msg) > ml:
                raise ValueError(
                    f"template msg {len(msg)}B exceeds packed ml {ml}")
            row = np.zeros(stride, np.uint8)
            row[:len(msg)] = np.frombuffer(msg, np.uint8)
            row[ml:ml + 64] = np.frombuffer(sig, np.uint8)
            row[ml + 64:ml + 96] = np.frombuffer(self.pool[0][1], np.uint8)
            row[ml + 96:ml + 100] = np.frombuffer(
                len(msg).to_bytes(4, "little"), np.uint8)
            self._row_tpl = row
            self._packed_ml = ml
            self._row_stride = stride
            self._msg_len = len(msg)
            # round-robin burst splitter: emit `burst_splits` frags per
            # loop so consecutive seqs deal rows across rr verify tiles
            # instead of one tile swallowing a whole mega-burst
            self._splits = max(1, int(cfg.get("burst_splits", 1)))

    def apply_knobs(self, ctx, vals):
        """Autotune pod application (disco/autotune.py KNOBS['source'])."""
        if "burst_splits" in vals and self._packed_rows:
            self._splits = max(1, int(vals["burst_splits"]))

    def _make_txn(self, i: int) -> bytes:
        seed, pub = self.pool[i % len(self.pool)]
        if self.executable:
            # nonzero prefix: dest must never collide with the all-zeros
            # system program id (duplicate account addresses in one txn)
            dest = b"\xd5" + bytes(15) + i.to_bytes(16, "little")
            msg = txn_lib.build_unsigned(
                [pub], self.blockhash,
                [(2, bytes([0, 1]), self._ix_transfer(1000 + i))],
                extra_accounts=[dest, self._system_id],
                readonly_unsigned_cnt=1)
        else:
            data = i.to_bytes(8, "little")  # distinct payload per i
            msg = txn_lib.build_unsigned(
                [pub], self.blockhash,
                [(1, bytes([0]), data)], extra_accounts=[self.program])
        sig = self._ed.sign(seed, msg)
        return txn_lib.assemble([sig], msg)

    def on_frag(self, ctx, iidx, meta, payload):
        if iidx in self._bh_ins and len(payload) >= 32:
            self.blockhash = bytes(payload[:32])
            self._bh_seen = True
            ctx.metrics.add("blockhash_refresh_cnt")

    def after_credit(self, ctx):
        if self._adopt:
            # adopted (failover) streams drain before our own resumes:
            # the dead host's in-flight work is the urgent half
            if self.rate_ns:
                now = time.monotonic_ns()
                if now - self._last_gen_ns < self.rate_ns:
                    return
                self._last_gen_ns = now
            try:
                tag, wire = next(self._adopt[0])
            except StopIteration:
                self._adopt.pop(0)
                return
            ctx.publish(wire, sig=tag & (LAT_PRIO_BIT - 1))
            ctx.metrics.add("adopt_pub_cnt")
            return
        if not self._bh_seen or (self.count and self.sent >= self.count):
            return
        if self.rate_ns:
            now = time.monotonic_ns()
            if now - self._last_gen_ns < self.rate_ns:
                return
            self._last_gen_ns = now
        if self._packed_rows:
            self._gen_packed(ctx)
            return
        if self._burst_n:
            n = self._burst_n
            if self.count:
                n = min(n, self.count - self.sent)
            L = self._tpl_len
            arr = np.tile(self._tpl, (n, 1))
            # unique tag (first 8 sig bytes) + unique instr data (last 8
            # payload bytes) per txn; the tag doubles as the app sig
            tags = self._rng.integers(1, 1 << 63, size=n, dtype=np.uint64)
            arr[:, 1:9] = tags.view(np.uint8).reshape(n, 8)
            arr[:, L - 8:] = np.arange(
                self.sent, self.sent + n, dtype=np.uint64
            ).view(np.uint8).reshape(n, 8)
            starts = np.arange(n, dtype=np.int64) * L
            lens = np.full(n, L, dtype=np.int32)
            mtags = tags
            if self._lat_every:
                mtags = tags.copy()
                mtags[::self._lat_every] |= np.uint64(LAT_PRIO_BIT)
            ctx.publish_burst(arr, starts, lens, mtags)
            self.sent += n
            ctx.metrics.add("txn_gen_cnt", n)
            return
        payload = self._make_txn(self.sent)
        # mask bit 63 — raw signature bytes are uniform, and a random
        # high bit must never read as a latency-class tag downstream
        sig64 = (int.from_bytes(payload[1:9], "little")
                 & (LAT_PRIO_BIT - 1))
        if self._lat_every and self.sent % self._lat_every == 0:
            sig64 |= LAT_PRIO_BIT
        ctx.publish(payload, sig=sig64)
        self.sent += 1
        ctx.metrics.add("txn_gen_cnt")

    def _gen_packed(self, ctx):
        """Stamp packed-blob frags in place in the out dcache: reserve the
        region, np.tile the template row into the shm view, overwrite tag
        + instr-data lanes, zero-pad a short tail, commit.  No staging
        buffer — the dcache bytes ARE the device blob."""
        rows, ml, stride = self._packed_rows, self._packed_ml, \
            self._row_stride
        L = stride
        for _ in range(self._splits):
            n = rows
            if self.count:
                n = min(n, self.count - self.sent)
            if n <= 0:
                return
            chunk, blk = ctx.out_reserve(rows * stride)
            if blk is None:        # halted mid-backpressure
                return
            blk = blk.reshape(rows, stride)
            np.copyto(blk[:n], self._row_tpl)
            tags = self._rng.integers(1, 1 << 63, size=n, dtype=np.uint64)
            blk[:n, ml:ml + 8] = tags.view(np.uint8).reshape(n, 8)
            blk[:n, L - 8:] = np.arange(
                self.sent, self.sent + n, dtype=np.uint64
            ).view(np.uint8).reshape(n, 8)
            if n < rows:
                blk[n:] = 0        # zero sig -> tag 0 -> dead lane
            ctx.out_commit(chunk, rows * stride, sig=int(tags[0]), sz=n)
            self.sent += n
            ctx.metrics.add("txn_gen_cnt", n)


class VerifyTile:
    """The verify tile (ref: src/app/fdctl/run/tiles/fd_verify.c).

    Round-robin data parallel: instance r of n keeps frags with
    seq % n == r (fd_verify.c:36-47).  Parse -> tcache pre-dedup ->
    fixed-shape device batch verify -> publish passing txns downstream with
    sig = low 64 bits of the first signature (the dedup tile's key).
    """

    def init(self, ctx):
        from ..ops import ed25519 as ed
        from ..utils import xla_cache
        import jax
        import jax.numpy as jnp
        xla_cache.enable()
        cfg = ctx.cfg
        self.rr_cnt = cfg.get("round_robin_cnt", 1)
        self.rr_idx = cfg.get("round_robin_idx", 0)
        batch = cfg.get("batch", 64)
        maxlen = cfg.get("msg_maxlen", 256)
        # multi-bucket ladder (full-MTU coverage): cfg buckets = [[b, l],...]
        buckets = cfg.get("buckets") or [[batch, maxlen]]
        self.flush_age_ns = cfg.get("flush_age_ns", 2_000_000)
        # dp-mesh serving path (round 7): dp_shards > 1 swaps the whole
        # verifier for a mesh-mode SigVerifier — each bucket's batch axis
        # shards P("dp", None) over the device mesh and dispatches the
        # donated shard_map step (parallel.mesh.shard_verify_blob).  The
        # AOT store holds single-chip executables only, so the sharded
        # tile boots from jit + the persistent XLA cache instead.
        self.dp_shards = int(cfg.get("dp_shards", 1))
        # dual-lane dispatch (round 9): [latency] enables a deadline-
        # driven low-latency lane of small pre-warmed shapes beside the
        # throughput buckets; latency-class frags carry LAT_PRIO_BIT in
        # the frag meta sig (priority admission)
        latc = cfg.get("latency") or {}
        self._lat_enabled = bool(int(latc.get("enabled", 0)))
        if self._lat_enabled and self.dp_shards > 1:
            # each ladder shape would need its own sharded program; keep
            # the dp-mesh path bulk-only until that lands
            log.warning("[latency] disabled: dp_shards=%d mesh verifier "
                        "is bulk-only", self.dp_shards)
            self._lat_enabled = False
        self._latc = latc
        lat_shapes = (tuple(int(s) for s in
                            (latc.get("shapes") or DEFAULT_LAT_SHAPES))
                      if self._lat_enabled else ())
        lat_ml = min(int(m) for _, m in buckets)
        lat_warm = [(s, lat_ml) for s in sorted(lat_shapes)]
        # [verify] mode (round 9): strict | antipa, env FDTPU_VERIFY_MODE.
        # The knob swaps the whole device graph — the mesh path, the AOT
        # store (verify[-packed]-antipa keys), warmup and the
        # GuardedVerifier CPU fallback all follow it.
        self.verify_mode = str(
            os.environ.get("FDTPU_VERIFY_MODE") or cfg.get("mode", "strict"))
        if self.verify_mode not in ("strict", "antipa"):
            raise ValueError(
                f"[verify] mode must be strict|antipa, "
                f"got {self.verify_mode!r}")
        if self.dp_shards > 1:
            from ..models.verifier import SigVerifier, VerifierConfig
            from ..parallel import mesh as pm
            b0, ml0 = buckets[0]
            fn = SigVerifier(VerifierConfig(batch=b0, msg_maxlen=ml0),
                             mode=self.verify_mode,
                             mesh=pm.make_mesh(self.dp_shards))
        else:
            fn = self._make_single_chip_fn(cfg, buckets, lat_warm)
        self._init_pipeline(ctx, cfg, fn, buckets, lat_warm)

    def _make_single_chip_fn(self, cfg, buckets, lat_warm=()):
        from ..ops import ed25519 as ed
        import jax
        # AOT-first boot (VERDICT r4 #2): per-bucket serialized executables
        # load in ~1 s where trace+lower+compile takes minutes on a
        # contended core.  aot_require makes a miss FATAL — a spawn-context
        # tile silently cold-compiling is exactly the boot-timeout failure
        # the bench must never reproduce.
        from ..utils import aot
        aot_dir = cfg.get("aot_dir") or os.environ.get("FDTPU_AOT_DIR")
        mode = getattr(self, "verify_mode", "strict")
        # mode-namespaced AOT keys: verify[-packed] for strict,
        # verify[-packed]-antipa for the halved chain — a mode flip can
        # never load the other graph's executable
        k_packed = "verify-packed" + ("-antipa" if mode == "antipa" else "")
        k_plain = "verify" + ("-antipa" if mode == "antipa" else "")
        batch_fn = (ed.verify_batch_antipa if mode == "antipa"
                    else ed.verify_batch)
        blob_base = (ed.verify_blob_antipa if mode == "antipa"
                     else ed.verify_blob)
        compiled = {}          # (b, ml) -> 4-array executable
        packed = {}            # (b, ml) -> packed-blob executable
        if aot_dir:
            for b, ml in buckets:
                fp = aot.load(aot_dir, aot.key(k_packed, b, ml))
                if fp is not None:
                    packed[(b, ml)] = fp
        # packed dispatch is all-or-nothing: the pipeline lays EVERY
        # bucket out row-interleaved once dispatch_blob exists, so a
        # partial packed set must fall back wholesale (a mixed state
        # previously left jit_fn None for packed-only buckets)
        if len(packed) != len(buckets):
            packed = {}
            if aot_dir:
                for b, ml in buckets:
                    f = aot.load(aot_dir, aot.key(k_plain, b, ml))
                    if f is not None:
                        compiled[(b, ml)] = f
        elif aot_dir:
            # opportunistic AOT for the low-latency ladder's small shapes;
            # misses fall back to the jit path below (warmed at boot, so
            # still no hot-path compile)
            for b, ml in lat_warm:
                f = aot.load(aot_dir, aot.key(k_packed, b, ml))
                if f is not None:
                    packed[(b, ml)] = f
        missing = [] if packed else [
            tuple(b) for b in buckets if tuple(b) not in compiled]
        if missing and cfg.get("aot_require"):
            raise RuntimeError(
                f"verify tile refusing to cold-compile {missing}: no AOT "
                f"executable in {aot_dir!r} (run utils.aot.ensure_verify "
                f"before boot or drop aot_require)")
        # the lat ladder dispatches shapes outside the bucket set, so a
        # shape-polymorphic fallback must exist even when every bucket
        # is AOT-covered
        jit_fn = (jax.jit(batch_fn)
                  if missing or (lat_warm and not packed) else None)

        class _Fn:
            """Pipeline-facing verifier: packed single-blob dispatch when
            every bucket has a packed AOT executable (the pipeline then
            lays its buckets out row-interleaved and uploads one blob),
            4-array dispatch otherwise.  Shapes outside the AOT set (the
            low-latency ladder) jit-compile once per shape — at boot
            warmup, never on the hot path."""

            _blob_jit = {}

            def __call__(self, msgs, lens, sigs, pubs):
                f = compiled.get((msgs.shape[0], msgs.shape[1]))
                return f(msgs, lens, sigs, pubs) if f is not None \
                    else jit_fn(msgs, lens, sigs, pubs)

            if packed:
                def dispatch_blob(self, blob, maxlen=None):
                    if maxlen is None:
                        maxlen = blob.shape[1] - ed.PACKED_EXTRA
                    f = packed.get((blob.shape[0], maxlen))
                    if f is not None:
                        return f(blob)
                    key = (blob.shape[0], maxlen)
                    jf = self._blob_jit.get(key)
                    if jf is None:
                        from functools import partial
                        jf = jax.jit(partial(blob_base,
                                             maxlen=maxlen, ml=maxlen))
                        self._blob_jit[key] = jf
                    return jf(np.asarray(blob))

        f = _Fn()
        # the pipeline's packed autodetect and the GuardedVerifier host
        # fallback both introspect .mode
        f.mode = mode
        return f

    def _init_pipeline(self, ctx, cfg, fn, buckets, lat_warm=()):
        from ..ops import ed25519 as ed
        import jax
        import jax.numpy as jnp

        # packed-wire mode (round 8): frag payloads arrive ALREADY in
        # device-blob row layout in the dcache; dispatch needs a blob
        # entry point even when no packed AOT executable is on disk
        self._packed_wire = bool(cfg.get("packed_wire", 0))
        if self._packed_wire and not hasattr(fn, "dispatch_blob"):
            fn = _jit_blob_fn(fn, mode=getattr(fn, "mode", "strict"))
        latc = getattr(self, "_latc", None) or cfg.get("latency") or {}
        self._lat_enabled = getattr(self, "_lat_enabled", False)

        # warmup before signaling RUN: compiles any non-AOT bucket (the
        # graph can take minutes to build cold, and the run loop must never
        # stall that long — the supervisor would flag a stale heartbeat)
        # and primes the transfer path for AOT ones.  The low-latency
        # ladder's shapes warm here too: deadline closes dispatch
        # pre-warmed shapes only, so no compile storm can land on the
        # hot path (the no-compile contract the latency smoke gates on).
        warm_shapes = [(int(b), int(ml)) for b, ml in buckets]
        warm_shapes += [(int(b), int(ml)) for b, ml in lat_warm]
        # poke the cnc heartbeat between ladder rungs: a large shape
        # ladder compiling cold can exceed heartbeat_timeout_s, and a
        # supervisor killing a tile MID-COMPILE restarts the compile from
        # scratch — a livelock, not a recovery (same contract as
        # utils/aot._poke on the pre-spawn ensure paths)
        hb = getattr(ctx, "heartbeat", None)
        for b, ml in warm_shapes:
            if hb is not None:
                hb()
            if hasattr(fn, "dispatch_blob"):
                fn.dispatch_blob(np.zeros(
                    (b, ml + ed.PACKED_EXTRA),
                    np.uint8)).block_until_ready()
            else:
                fn(jnp.zeros((b, ml), jnp.uint8),
                   jnp.zeros((b,), jnp.int32),
                   jnp.zeros((b, 64), jnp.uint8),
                   jnp.zeros((b, 32), jnp.uint8)).block_until_ready()
        if hb is not None:
            hb()
        # self-healing dispatch (AFTER warmup: warmup failures must stay
        # fatal boot failures, not silently degrade a fresh tile): bounded
        # retries, verdict deadline, CPU ed25519 fallback after N
        # consecutive device failures, periodic re-probe.  The wrapper
        # preserves the duck-typed surface (dispatch_blob presence, .mode)
        # the pipeline autodetects packed layout from.
        from .pipeline import GuardedVerifier
        sup = cfg.get("supervision") or {}
        # the mux already armed this tile's FaultInjector (or None); share
        # it so the whole tile runs ONE deterministic fault stream
        mux = getattr(ctx, "_mux", None)
        self.guard = GuardedVerifier(
            fn,
            fail_threshold=int(sup.get("device_fail_threshold", 3)),
            retries=int(sup.get("device_retry", 1)),
            deadline_s=float(sup.get("device_deadline_s", 30.0)),
            reprobe_s=float(sup.get("device_reprobe_s", 5.0)),
            fault=getattr(mux, "fault", None))
        fn = self.guard
        self.pipe = VerifyPipeline(
            fn, buckets=[tuple(b) for b in buckets],
            tcache_depth=cfg.get("tcache_depth", 1 << 16),
            dp_shards=self.dp_shards,
            # async data plane by default (wiredancer's contract): filled
            # buckets dispatch without blocking the mux loop; verdicts are
            # harvested in after_credit once the device completes them
            max_inflight=cfg.get("max_inflight", 8),
            # packed-blob rotation depth (upload/compute double buffering):
            # a flushed blob stays pinned until its verdict lands while the
            # next batch packs into a pool blob
            n_buffers=cfg.get("n_buffers", 3),
            # fdtrace: coalesce/device/compile spans land in this tile's
            # shm trace ring next to the mux's frag/burst spans
            tracer=ctx.trace,
            # heartbeat through blocking device waits (flush/_finish):
            # a long in-flight batch must not read as a dead tile, and
            # HALT must still land mid-wait
            heartbeat_cb=getattr(ctx, "heartbeat", None),
            # low-latency lane (round 9): deadline-driven small-shape
            # dispatch beside the throughput buckets
            lat_shapes=[b for b, _ in lat_warm] or None,
            deadline_us=int(latc.get("deadline_us", 2000)),
            lat_max_inflight=int(latc.get("max_inflight", 2)),
            lat_spill_age_factor=float(latc.get("spill_age_factor", 4.0)),
            # round 11: one-pass C submit/harvest ([ingest] native_hostpath;
            # None defers to the FDTPU_INGEST_NATIVE_HOSTPATH env default)
            # and packed verdict egress (one arena frag per harvest instead
            # of per-txn frags; needs the dedup tile's packed_egress mode)
            native_hostpath=(None if cfg.get("native_hostpath") is None
                             else bool(cfg.get("native_hostpath"))),
            egress_packed=bool(cfg.get("egress_packed", 0)))
        # every shape above went through the verifier before the pipeline
        # existed — their first pipeline dispatch is not a compile
        self.pipe.mark_warm(warm_shapes)
        self._last_submit_ns = 0
        self._synced_batches = -1
        # optional XLA-level capture: FDTPU_JAX_TRACE_DIR=<dir> wraps the
        # tile's whole run in a jax.profiler trace (TensorBoard-loadable);
        # off by default — it is NOT free like the shm span rings
        self._jax_trace_dir = cfg.get("jax_trace_dir") or os.environ.get(
            "FDTPU_JAX_TRACE_DIR")
        if self._jax_trace_dir:
            jax.profiler.start_trace(self._jax_trace_dir)
        trace_mod.install_jax_compile_listener()
        # burst data plane (round 4): frags drain from the ring via one
        # native call (mux on_burst path) with the round-robin filter
        # applied AT the ring, and passing txns publish via one burst
        # publish — the scalar per-frag path remains for cfg burst=False
        # (tests of the before_frag contract).
        self._burst = cfg.get("burst", True)
        if self._packed_wire:
            # zero-copy rx: the mux's on_burst_view path hands this tile
            # metas + the raw dcache; hide on_burst so the mux does NOT
            # allocate its BURST_RX*mtu rx scratch (a packed link's mtu is
            # batch*stride — hundreds of KB — and the scratch would be
            # BURST_RX times that)
            self.on_burst = None
            self.burst_rr = (self.rr_cnt, self.rr_idx)
            b0, ml0 = buckets[0]
            self._pw_batch = int(b0)
            self._pw_ml = int(ml0)
            self._pw_stride = int(ml0) + ed.PACKED_EXTRA
            self._held = {}        # iidx -> frags pinned awaiting verdict
        elif self._burst:
            self.on_burst_view = None
            self.burst_rr = (self.rr_cnt, self.rr_idx)
        else:
            # hide both vtable hooks from the mux
            self.on_burst = None
            self.on_burst_view = None

    def before_frag(self, ctx, iidx, seq, sig) -> bool:
        return (seq % self.rr_cnt) != self.rr_idx

    def apply_knobs(self, ctx, vals):
        """Autotune pod application (disco/autotune.py KNOBS['verify']).
        Every target here is re-read on its hot path each call, so the
        new value is live from the next batch onward — no respawn."""
        if "flush_age_ns" in vals:
            self.flush_age_ns = max(1, int(vals["flush_age_ns"]))
        pipe = getattr(self, "pipe", None)
        if pipe is None:
            return
        if "max_inflight" in vals:
            pipe.max_inflight = max(1, int(vals["max_inflight"]))
        if "lat_max_inflight" in vals:
            pipe.lat_max_inflight = max(1, int(vals["lat_max_inflight"]))
        if "deadline_us" in vals:
            new = max(1, int(vals["deadline_us"]))
            old = max(1, int(pipe.deadline_us))
            # the spill age was derived as factor * deadline at init;
            # preserve the implied factor across deadline moves
            factor = pipe.lat_spill_age_ns / (old * 1000)
            pipe.deadline_us = new
            pipe.lat_spill_age_ns = int(factor * new * 1000)

    def _forward(self, ctx, passed):
        if self._burst:
            return self._forward_burst(ctx, passed)
        if not passed:
            return
        t0 = time.monotonic_ns()
        for payload, parsed in passed:
            # first sig's low 64 bits: signature_off is 1 for every
            # wire-valid txn (1-byte sig count prefix)
            tag = int.from_bytes(payload[1:9], "little")
            ctx.publish(payload, sig=tag)
        if ctx.trace is not None:
            ctx.trace.record(trace_mod.KIND_PUBLISH, t0,
                             time.monotonic_ns() - t0, cnt=len(passed))

    def _forward_burst(self, ctx, passed):
        """One native burst publish for all passing txns.  Packed verdict
        egress (round 11): a PackedVerdicts entry ships as ONE arena frag
        instead of k per-txn frags."""
        if not passed:
            return
        if any(isinstance(p, PackedVerdicts) for p in passed):
            for pv in passed:
                if isinstance(pv, PackedVerdicts):
                    self._publish_packed_verdicts(ctx, pv)
            passed = [p for p in passed
                      if not isinstance(p, PackedVerdicts)]
            if not passed:
                return
        import numpy as np
        t0 = time.monotonic_ns()
        bufs = [p for p, _ in passed]
        joined = b"".join(bufs)
        lens = np.array([len(b) for b in bufs], np.int32)
        starts = np.zeros(len(bufs), np.int64)
        np.cumsum(lens[:-1], out=starts[1:])
        sigs = np.array([int.from_bytes(b[1:9], "little") for b in bufs],
                        np.uint64)
        ctx.publish_burst(joined, starts, lens, sigs)
        if ctx.trace is not None:
            ctx.trace.record(trace_mod.KIND_PUBLISH, t0,
                             time.monotonic_ns() - t0, cnt=len(passed))

    def _publish_packed_verdicts(self, ctx, pv):
        """Stamp one harvest's passing wires downstream as a single packed
        frag: u32 offsets table (k+1 entries) then the wires back to back,
        written straight into the out dcache via out_reserve (the round-8
        ingest stamping idiom).  meta.sz = survivor count k (byte sizes
        overflow the u16 field); meta.sig = first survivor's tag, bit 63
        masked so arena frags never alias latency-class admission."""
        t0 = time.monotonic_ns()
        hdr = 4 * (pv.k + 1)
        nb = hdr + int(pv.offs[pv.k])
        chunk, blk = ctx.out_reserve(nb)
        if blk is None:
            return  # halted while backpressured
        blk[:hdr].view(np.uint32)[:] = pv.offs
        blk[hdr:nb] = pv.arena
        sig0 = int(pv.tags[0]) & (LAT_PRIO_BIT - 1)
        ctx.out_commit(chunk, nb, sig=sig0, sz=pv.k)
        if ctx.trace is not None:
            ctx.trace.record(trace_mod.KIND_PUBLISH, t0,
                             time.monotonic_ns() - t0, cnt=pv.k)

    def on_frag(self, ctx, iidx, meta, payload):
        # priority admission: the producer's latency-class bit rides the
        # frag meta sig (meta-field threading, round 8 precedent: meta.sz)
        lat = bool(self._lat_enabled and (int(meta["sig"]) & LAT_PRIO_BIT))
        passed = self.pipe.submit(payload, lat=lat)
        self._last_submit_ns = time.monotonic_ns()
        self._forward(ctx, passed)
        self._sync_metrics(ctx)

    def on_burst(self, ctx, iidx, metas, buf, offs, kept):
        # zero-copy handoff: the ring rx scratch (buf, offs) feeds the
        # native parser directly; the pipeline copies the region once
        if self._lat_enabled and kept:
            prio = (metas["sig"][:kept].astype(np.uint64)
                    & np.uint64(LAT_PRIO_BIT)) != 0
            if prio.any():
                passed = self._submit_burst_split(buf, offs, kept, prio)
                self._last_submit_ns = time.monotonic_ns()
                self._forward_burst(ctx, passed)
                self._sync_metrics(ctx)
                return
        passed = self.pipe.submit_burst(packed=(buf, offs[:kept + 1]))
        self._last_submit_ns = time.monotonic_ns()
        self._forward_burst(ctx, passed)
        self._sync_metrics(ctx)

    def _submit_burst_split(self, buf, offs, kept, prio):
        """Mixed-class burst: latency-class txns (LAT_PRIO_BIT set in the
        frag meta sig) go scalar into the low-latency lane; the bulk runs
        between them keep the native packed-window path (submit_burst
        accepts any contiguous offs subrange).  Latency traffic is sparse
        by design, so the scalar hops are rare."""
        passed = []
        i = 0
        while i < kept:
            if prio[i]:
                passed += self.pipe.submit(
                    bytes(buf[offs[i]:offs[i + 1]]), lat=True)
                i += 1
            else:
                j = i
                while j < kept and not prio[j]:
                    j += 1
                passed += self.pipe.submit_burst(
                    packed=(buf, offs[i:j + 1]))
                i = j
        return passed

    def credits_held(self, iidx: int) -> int:
        """Frags this tile has consumed but still pins in the dcache
        (device reads the shm view until the verdict lands) — the mux
        subtracts this from the fseq so the producer can't overwrite."""
        held = getattr(self, "_held", None)
        return held.get(iidx, 0) if held else 0

    def on_burst_view(self, ctx, iidx, metas, dcache):
        """Packed-wire rx: each meta is one packed frag of meta.sz rows
        already laid out as device-blob rows in the dcache.  Dispatch the
        shm view with zero payload copies; the frag's flow credit stays
        held (credits_held) until its verdict materializes, and the mcache
        seq is re-checked after dispatch so a torn read can never produce
        a verdict (no-torn-buffer invariant)."""
        b, stride = self._pw_batch, self._pw_stride
        mc = ctx.in_mcache(iidx)
        held = self._held
        for meta in metas:
            rows = dcache.rows(int(meta["chunk"]), b, stride)
            # pin BEFORE submit: sync mode may retire (and release) inside
            held[iidx] = held.get(iidx, 0) + 1

            def _release(iidx=iidx):
                held[iidx] -= 1

            lat = bool(self._lat_enabled
                       and (int(meta["sig"]) & LAT_PRIO_BIT))
            passed = self.pipe.submit_packed_rows(
                rows, n=int(meta["sz"]),
                guard=(mc, int(meta["seq"])), release_cb=_release, lat=lat)
            if passed:
                self._forward_burst(ctx, passed)
        self._last_submit_ns = time.monotonic_ns()
        self._sync_metrics(ctx)

    def after_credit(self, ctx):
        # batch-close-on-deadline (round 9): the low-latency lane's own
        # fine-grained age check runs every loop — independent of the
        # coarse flush_age_ns below, which bounds the bulk lane — so the
        # open lat batch ships the moment its oldest txn ages out
        if self._lat_enabled and self.pipe.lat_due():
            self._forward(ctx, self.pipe.dispatch_due())
        # harvest completed device batches first — never blocks
        passed = self.pipe.harvest()
        if passed:
            self._forward(ctx, passed)
        # sync on every completed batch, not only on passing ones: an
        # all-fail batch (e.g. the burst firehose's stamped sigs) must
        # still surface its verify_fail_cnt
        if self.pipe.metrics.batches != self._synced_batches:
            self._synced_batches = self.pipe.metrics.batches
            self._sync_metrics(ctx)
        # age-based flush: bound batch latency when inflow stalls
        # (BASELINE p99 < 2ms requires closing partial batches).  Async
        # mode only DISPATCHES the partial bucket; results surface on a
        # later harvest, so the mux loop still never waits on the device.
        # Gate on has_open (undispatched txns), not has_pending: inflight
        # batches only need harvesting, and re-firing dispatch_open while
        # they drain is a no-op busy loop (ADVICE r3).
        if (self.pipe.has_open
                and time.monotonic_ns() - self._last_submit_ns
                > self.flush_age_ns):
            if self.pipe.max_inflight:
                self._forward(ctx, self.pipe.dispatch_open())
            else:
                self._forward(ctx, self.pipe.flush())
            self._last_submit_ns = time.monotonic_ns()
            self._sync_metrics(ctx)

    def _sync_metrics(self, ctx):
        s = self.pipe.metrics
        ctx.metrics.set("txn_in_cnt", s.txns_in)
        ctx.metrics.set("parse_fail_cnt", s.parse_fail)
        ctx.metrics.set("dedup_drop_cnt", s.dedup_drop)
        ctx.metrics.set("too_long_cnt", s.too_long_drop)
        ctx.metrics.set("verify_fail_cnt", s.verify_fail)
        ctx.metrics.set("verify_pass_cnt", s.verify_pass)
        ctx.metrics.set("torn_drop_cnt", s.torn_drop)
        ctx.metrics.set("torn_txn_cnt", s.torn_txns)
        ctx.metrics.set("batch_cnt", s.batches)
        ctx.metrics.set("compile_cnt", s.compile_cnt)
        ctx.metrics.set("compile_ns", s.compile_ns)
        ctx.metrics.set("lanes_filled_cnt", s.lanes_filled)
        ctx.metrics.set("lanes_dispatched_cnt", s.lanes_dispatched)
        ctx.metrics.set("bucket_fill_pct", s.last_fill_pct)
        ctx.metrics.set("inflight_depth",
                        len(self.pipe.inflight) + len(self.pipe.lat_inflight))
        # dual-lane dispatch (round 9)
        ctx.metrics.set("lat_txn_cnt", s.lat_txns)
        ctx.metrics.set("lat_spill_cnt", s.lat_spill)
        ctx.metrics.set("lat_batch_cnt", s.lat_batches)
        ctx.metrics.set("lat_deadline_close_cnt", s.lat_deadline_closes)
        # self-healing dispatch health (GuardedVerifier): the degraded
        # gauge is what flips /healthz from "ok" to "degraded"
        g = self.guard
        ctx.metrics.set("degraded_mode", 1 if g.degraded else 0)
        ctx.metrics.set("device_fail_cnt", g.device_fail_cnt)
        ctx.metrics.set("fallback_lane_cnt", g.fallback_lanes)
        ctx.metrics.set("reprobe_cnt", g.reprobe_cnt)
        ctx.metrics.set("fallback_vps", g.fallback_vps())
        # shm histograms: full decomposition distributions, not just the
        # derived scalars — /metrics exports them as native Prometheus
        # le-bucketed histograms
        ctx.metrics.hist_store("batch_ns", s.batch_ns)
        ctx.metrics.hist_store("coalesce_ns", s.coalesce_ns)
        ctx.metrics.hist_store("lat_e2e_ns", s.lat_e2e_ns)

    def drain(self, ctx) -> bool:
        """Drain-protocol hook (mux SIGNAL_DRAIN): run the pipeline dry.
        Each poll dispatches every open bucket + the lat accumulator
        (dispatch_open covers both lanes) and harvests completed device
        batches non-blocking, publishing their verdicts downstream; the
        mux keeps heartbeating between polls so a multi-batch backlog
        can't read as a stale tile.  Returns True once nothing is open
        and nothing is in flight — every accepted txn verdicted."""
        pipe = getattr(self, "pipe", None)
        if pipe is None:
            return True
        if pipe.has_open:
            self._forward(ctx, pipe.dispatch_open())
        passed = pipe.harvest()
        if passed:
            self._forward(ctx, passed)
        if pipe.has_pending:
            return False
        self._sync_metrics(ctx)
        return True

    def fini(self, ctx):
        try:
            self._forward(ctx, self.pipe.flush())
            self._sync_metrics(ctx)
        except Exception:
            pass
        if self._jax_trace_dir:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass


def _jit_blob_fn(base, mode: str = "strict"):
    """Wrap a 4-array verifier with a jit packed-blob entry point: the
    packed-wire tile dispatches dcache rows as one device blob, which
    needs dispatch_blob even when no packed AOT executable is on disk
    (first call per shape compiles; the persistent XLA cache and the
    warmup in _init_pipeline keep that off the hot loop).  `mode` keeps
    the blob graph consistent with the wrapped 4-array graph."""
    from functools import partial
    import jax
    from ..ops import ed25519 as ed

    blob_base = (ed.verify_blob_antipa if mode == "antipa"
                 else ed.verify_blob)

    class _BlobFn:
        _cache = {}

        def __call__(self, *a):
            return base(*a)

        def dispatch_blob(self, blob, maxlen=None):
            ml = (blob.shape[1] - ed.PACKED_EXTRA
                  if maxlen is None else maxlen)
            key = (blob.shape[0], ml)
            f = self._cache.get(key)
            if f is None:
                f = jax.jit(partial(blob_base, maxlen=ml, ml=ml))
                self._cache[key] = f
            return f(np.asarray(blob))

    bf = _BlobFn()
    bf.mode = mode
    bf._cache = {}   # per-instance: two modes must never share blob jits
    return bf


def _sock_backend(cfg):
    """Socket backend selection (ref: the xdp-vs-udpsock choice in
    fd_topo config): "native" = C++ recvmmsg/sendmmsg burst engine
    (waltz.pkteng), default = python sockets (waltz.udpsock)."""
    if cfg.get("backend") == "native":
        from ..waltz.pkteng import NativeUdpSock
        return NativeUdpSock
    from ..waltz.udpsock import UdpSock
    return UdpSock


def _wire_row(wire: bytes, ml: int):
    """Locate the three packed-row fields of one wire txn: (message,
    sig64, signer pub32) or None.  Validation is txn_lib.parse — the SAME
    gate the legacy per-txn path applies inside the verify tile — so a
    txn dropped here would not have produced a verdict on the legacy path
    either (parse_fail / too_long), keeping the two publish modes'
    verdict streams bit-identical.  Packed rows carry one sig lane, the
    Solana TPU single-signer profile."""
    try:
        t = txn_lib.parse(wire)
    except txn_lib.TxnParseError:
        return None
    if t.signature_cnt != 1:
        return None
    msg = t.message(wire)
    if len(msg) > ml:
        return None
    return (msg, wire[t.signature_off:t.signature_off + 64],
            wire[t.acct_addr_off:t.acct_addr_off + 32])


class _PackedWirePublisher:
    """Accumulate reassembled wire txns into round-8 packed dcache rows
    (msg | sig64 | pub32 | len-le32 at packed_row_ml stride), stamped
    straight into the out dcache via ctx.out_reserve like SourceTile's
    _gen_packed — meta.sz carries the row count, zeroed tail rows read as
    dead lanes (sig tag 0).  The quic tiles' packed-publish mode: the
    wire->device path stays zero-copy end to end (one stamp here, shm
    views from there on).

    The open reservation holds one downstream credit between loop
    iterations; flush-on-fill plus the tile's age-based flush bound how
    long a partial frag can sit."""

    def __init__(self, ctx, rows: int, ml: int,
                 flush_age_ns: int = 2_000_000):
        self.ctx = ctx
        self.rows = int(rows)
        self.ml = int(ml)
        from ..tango.ring import PACKED_ROW_EXTRA
        self.stride = self.ml + PACKED_ROW_EXTRA
        self.flush_age_ns = int(flush_age_ns)
        self._chunk = None
        self._blk = None
        self._n = 0
        self._sig0 = 0
        self._opened_ns = 0

    def add(self, wire: bytes) -> bool:
        """Stamp one wire txn into the open packed frag.  False = dropped
        (would not have verdict'd on the legacy path either, see
        _wire_row)."""
        row = _wire_row(wire, self.ml)
        if row is None:
            return False
        msg, sig, pub = row
        if self._blk is None:
            chunk, blk = self.ctx.out_reserve(self.rows * self.stride)
            if blk is None:
                return False  # halted while backpressured
            self._chunk = chunk
            self._blk = blk.reshape(self.rows, self.stride)
            self._blk[:] = 0  # unfilled tail rows must read as dead lanes
            self._n = 0
            self._opened_ns = time.monotonic_ns()
        r = self._blk[self._n]
        ml = self.ml
        r[:len(msg)] = np.frombuffer(msg, np.uint8)
        r[ml:ml + 64] = np.frombuffer(sig, np.uint8)
        r[ml + 64:ml + 96] = np.frombuffer(pub, np.uint8)
        r[ml + 96:ml + 100] = np.frombuffer(
            len(msg).to_bytes(4, "little"), np.uint8)
        if self._n == 0:
            # same bit-63 mask as the per-txn publish: untagged wire
            # ingest must never alias into latency-class admission
            self._sig0 = (int.from_bytes(sig[:8], "little")
                          & (LAT_PRIO_BIT - 1))
        self._n += 1
        if self._n >= self.rows:
            self.flush()
        return True

    def due(self) -> bool:
        return (self._n > 0
                and time.monotonic_ns() - self._opened_ns
                > self.flush_age_ns)

    def flush(self) -> None:
        if self._blk is None or self._n == 0:
            return
        self.ctx.out_commit(self._chunk, self.rows * self.stride,
                            sig=self._sig0, sz=self._n)
        self._chunk = self._blk = None
        self._n = 0


class NetTile:
    """Packet ingress (ref: src/app/fdctl/run/tiles/fd_net.c): drains UDP
    socket bursts and steers by destination port to out links.

    cfg ports: {port: out_link_name}; port 0 = ephemeral, with the kernel's
    chosen port for the FIRST socket exported in the `bound_port` metrics
    slot once the tile is RUN (how tests discover where to send).

    DoS knob: pps_per_source > 0 arms a per-source-IP packet token bucket
    (rate_drop_cnt counts sheds; the `shedding` gauge feeds /healthz) over
    a bounded LRU source map — one flooding source is clamped before its
    packets cost the quic tile anything."""

    _SRC_MAP_CAP = 4096  # bounded per-source bucket table (LRU)

    def init(self, ctx):
        self._xdp_fds = ()
        self.socks = []
        self._pps = float(ctx.cfg.get("pps_per_source", 0) or 0)
        self._pps_burst = float(
            ctx.cfg.get("pps_burst", 0) or 2 * self._pps or 64)
        self._src_buckets: OrderedDict = OrderedDict()
        self._last_shed = -1e9
        if ctx.cfg.get("backend") == "xsk":
            # kernel-bypass tier (VERDICT r4 #6): XSK rings on a NIC
            # queue, fed by the in-kernel redirect program steering this
            # tile's (ip, port) flows into the XSKMAP — NIC -> XSK ->
            # quic with zero per-packet syscalls.  Ports must be
            # explicit (the redirect keys on them).
            from ..waltz.ebpf import KernelXdp
            from ..waltz.xsk import XskSock
            xcfg = ctx.cfg.get("xsk", {})
            ifname = xcfg.get("ifname", "lo")
            ip = xcfg.get("ip", "127.0.0.1")
            xs = XskSock(ifname, queue=int(xcfg.get("queue", 0)))
            kx = KernelXdp()
            flows = [(ip, int(port)) for port in ctx.cfg["ports"]]
            self._xdp_fds = kx.install_redirect(
                ifname, flows, {int(xcfg.get("queue", 0)): xs.fileno()})
            # one XSK serves every port; steer per-dst-port at publish
            self._xsk_outs = {int(port): ctx.out_index(link)
                              for port, link in ctx.cfg["ports"].items()}
            self.socks = [(xs, next(iter(self._xsk_outs.values())))]
            ctx.metrics.set("bound_port", sorted(self._xsk_outs)[0])
            return
        sock_cls = _sock_backend(ctx.cfg)
        for port, link in sorted(ctx.cfg["ports"].items()):
            s = sock_cls(bind_port=port)
            self.socks.append((s, ctx.out_index(link)))
        ctx.metrics.set("bound_port", self.socks[0][0].port)

    def apply_knobs(self, ctx, vals):
        """Autotune pod application (disco/autotune.py KNOBS['net']).
        Only retunes an ALREADY-armed bucket: pps == 0 means the operator
        chose no rate limiting, and autotune must not arm one."""
        if self._pps <= 0:
            return
        if "pps_per_source" in vals:
            self._pps = max(1.0, float(vals["pps_per_source"]))
        if "pps_burst" in vals:
            self._pps_burst = max(1.0, float(vals["pps_burst"]))

    def _admit(self, ctx, src, now: float) -> bool:
        """Per-source pps token bucket: True = forward, False = shed."""
        bk = self._src_buckets.get(src)
        if bk is None:
            if len(self._src_buckets) >= self._SRC_MAP_CAP:
                self._src_buckets.popitem(last=False)
            self._src_buckets[src] = bk = [self._pps_burst, now]
        else:
            self._src_buckets.move_to_end(src)
            bk[0] = min(self._pps_burst,
                        bk[0] + (now - bk[1]) * self._pps)
            bk[1] = now
        if bk[0] < 1.0:
            ctx.metrics.add("rate_drop_cnt")
            self._last_shed = now
            return False
        bk[0] -= 1.0
        return True

    def after_credit(self, ctx):
        pps = self._pps
        now = time.monotonic() if pps else 0.0
        if getattr(self, "_xsk_outs", None):
            xs = self.socks[0][0]
            default_out = self.socks[0][1]
            for pkt, dport in xs.recv_burst_dst():
                src = getattr(pkt, "addr", None)
                if pps and src and not self._admit(ctx, src[0], now):
                    continue
                ctx.publish(pkt.payload, sig=0,
                            out=self._xsk_outs.get(dport, default_out))
                ctx.metrics.add("rx_pkt_cnt")
        else:
            for s, out in self.socks:
                for pkt in s.recv_burst():
                    src = getattr(pkt, "addr", None)
                    if pps and src and not self._admit(ctx, src[0], now):
                        continue
                    ctx.publish(pkt.payload, sig=0, out=out)
                    ctx.metrics.add("rx_pkt_cnt")
        if pps:
            # overload-shedding signal for /healthz: holds ~5 s past the
            # last shed so scrapes can't miss a short burst
            ctx.metrics.set(
                "shedding", 1 if now - self._last_shed < 5.0 else 0)

    def fini(self, ctx):
        # teardown ordering: detach the XDP redirect FIRST (close the bpf
        # link/prog/map fds) so no in-flight packet is steered into a dead
        # XSKMAP entry, THEN close the sockets.  State is cleared before
        # closing, so a re-entrant fini (supervisor + atexit paths) is a
        # no-op.
        fds, self._xdp_fds = getattr(self, "_xdp_fds", ()), ()
        for fd in fds:
            try:
                os.close(fd)
            except OSError:
                pass
        socks, self.socks = getattr(self, "socks", []), []
        for s, _ in socks:
            try:
                s.close()
            except OSError:
                pass


class QuicTile:
    """TPU ingest tile (ref: src/app/fdctl/run/tiles/fd_quic.c).  Consumes
    net frags and publishes whole txns into the verify link via TpuReasm.
    UDP legacy mode today (one datagram = one txn, fd_quic.c:155-165); the
    QUIC stream path plugs into the same reasm."""

    def init(self, ctx):
        from .tpu_reasm import TpuReasm

        self._packed = _mk_packed_publisher(ctx)

        def _pub(txn_bytes: bytes):
            if self._packed is not None:
                if self._packed.add(txn_bytes):
                    ctx.metrics.add("reasm_pub_cnt")
                else:
                    ctx.metrics.add("reasm_drop_cnt")
                return
            # mask bit 63: signature bytes are uniform, and untagged wire
            # ingest must never alias a random high bit into the verify
            # tile's latency-class admission (LAT_PRIO_BIT)
            sig64 = ((int.from_bytes(txn_bytes[1:9], "little")
                      if len(txn_bytes) >= 9 else 0) & (LAT_PRIO_BIT - 1))
            ctx.publish(txn_bytes, sig=sig64)
            ctx.metrics.add("reasm_pub_cnt")

        self.reasm = TpuReasm(
            ctx.cfg.get("reasm_depth", 64), _pub,
            conn_budget=int(ctx.cfg.get("reasm_conn_budget", 0)))

    def on_frag(self, ctx, iidx, meta, payload):
        if not self.reasm.publish_datagram(payload):
            ctx.metrics.add("reasm_drop_cnt")

    def on_burst(self, ctx, iidx, metas, buf, offs, kept):
        """Burst rx: one native drain of the net link per loop; each
        datagram still walks the reasm (legacy one-datagram-one-txn mode
        publishes straight through)."""
        for i in range(kept):
            if not self.reasm.publish_datagram(
                    bytes(buf[offs[i]:offs[i + 1]])):
                ctx.metrics.add("reasm_drop_cnt")

    def after_credit(self, ctx):
        p = self._packed
        if p is not None and p.due():
            p.flush()
        ctx.metrics.set("reasm_evict_cnt", self.reasm.metrics["evict_cnt"])

    def fini(self, ctx):
        if self._packed is not None:
            self._packed.flush()


def _mk_packed_publisher(ctx):
    """cfg packed_publish=1 -> a _PackedWirePublisher on out link 0 (the
    quic tiles' zero-copy mode); None keeps the legacy per-txn publish."""
    if not int(ctx.cfg.get("packed_publish", 0)):
        return None
    from ..tango.ring import packed_row_ml
    return _PackedWirePublisher(
        ctx,
        rows=int(ctx.cfg.get("packed_rows", 64)),
        ml=int(ctx.cfg.get("packed_ml", 0) or packed_row_ml(256)),
        flush_age_ns=int(ctx.cfg.get("packed_flush_age_ns", 2_000_000)))


class QuicServerTile:
    """Full QUIC TPU ingest (ref: src/app/fdctl/run/tiles/fd_quic.c QUIC
    path, fd_quic.c:399-466): terminates QUIC conns on a dedicated UDP
    socket (the reference's dedicated XDP queue analogue), reassembles
    one-txn-per-uni-stream payloads, and publishes whole txns to the
    verify link.

    cfg: port (0 = ephemeral; bound port exported in metrics),
         identity_seed (hex; fresh random if absent),
         require_client_cert (default False for open TPU ingest),
         DoS knobs threaded to QuicConfig (max_conns, max_conns_per_peer,
         retry, retry_half_open_threshold, conn_txn_rate/burst,
         conn_reasm_budget, lru_evict_idle, idle_timeout), reasm_conn_budget
         (TpuReasm-level per-conn bytes), packed_publish (+packed_rows/
         packed_ml/packed_flush_age_ns) for zero-copy row stamping.
    """

    def init(self, ctx):
        import os as _os

        from ..waltz.quic import QuicConfig, QuicEndpoint
        from .tpu_reasm import TpuReasm

        cfg = ctx.cfg
        self._packed = _mk_packed_publisher(ctx)

        def _pub(txn_bytes: bytes):
            if self._packed is not None:
                if self._packed.add(txn_bytes):
                    ctx.metrics.add("reasm_pub_cnt")
                # parse-dropped rows land in reasm_drop_cnt via _sync
                return
            # same bit-63 mask as QuicTile: no random latency-class tags
            sig64 = ((int.from_bytes(txn_bytes[1:9], "little")
                      if len(txn_bytes) >= 9 else 0) & (LAT_PRIO_BIT - 1))
            ctx.publish(txn_bytes, sig=sig64)
            ctx.metrics.add("reasm_pub_cnt")

        self.reasm = TpuReasm(
            cfg.get("reasm_depth", 256), _pub,
            conn_budget=int(cfg.get("reasm_conn_budget", 0)))
        self.sock = _sock_backend(cfg)(
            bind_port=cfg.get("port", 0), burst=256, mutable=True)
        seed_hex = cfg.get("identity_seed")
        seed = bytes.fromhex(seed_hex) if seed_hex else _os.urandom(32)
        qc = QuicConfig(
            identity_seed=seed,
            is_server=True,
            require_client_cert=cfg.get("require_client_cert", False),
            idle_timeout=float(cfg.get("idle_timeout", 10.0)),
            max_conns=int(cfg.get("max_conns", 4096)),
            max_conns_per_peer=int(cfg.get("max_conns_per_peer", 0)),
            retry=bool(cfg.get("retry", False)),
            retry_half_open_threshold=int(
                cfg.get("retry_half_open_threshold", 0)),
            lru_evict_idle=float(cfg.get("lru_evict_idle", 1.0)),
            conn_txn_rate=float(cfg.get("conn_txn_rate", 0.0)),
            conn_txn_burst=int(cfg.get("conn_txn_burst", 32)),
            # same -1/0/1 idiom as native_pack: -1 auto (C if it builds),
            # 0 force the Python fallback, 1 require the C burst engine
            crypto_native={0: False, 1: True}.get(
                int(cfg.get("crypto_native", -1))),
            initial_key_cache=int(cfg.get("initial_key_cache", 1024)),
        )
        if "conn_reasm_budget" in cfg:
            qc.conn_reasm_budget = int(cfg["conn_reasm_budget"])
        self.ep = QuicEndpoint(qc, self.sock.aio())
        # completed streams arrive as memoryviews into the decrypted rx
        # burst buffer; publish_datagram stamps them downstream (packed
        # dcache rows / mcache write) before the view can go stale — the
        # wire->row path pays zero payload copies
        self.ep.stream_views = True

        def _on_stream(conn, sid, data):
            self.reasm.publish_datagram(data)

        self.ep.on_stream = _on_stream
        self._last_msync = 0.0
        self._shed_total = 0
        self._shed_ts = -1e9
        ctx.metrics.set("bound_port", self.sock.port)

    def apply_knobs(self, ctx, vals):
        """Autotune pod application (KNOBS['quic_server']): per-conn txn
        token-bucket rates, read live by _txn_admit via ep.cfg.  Same
        already-armed rule as NetTile — rate 0 stays off."""
        ep = getattr(self, "ep", None)
        if ep is None or ep.cfg.conn_txn_rate <= 0:
            return
        ep.set_rate_knobs(
            conn_txn_rate=vals.get("conn_txn_rate"),
            conn_txn_burst=vals.get("conn_txn_burst"))

    def after_credit(self, ctx):
        now = time.monotonic()
        pkts = self.sock.recv_burst()
        if pkts:
            if ctx.trace is not None:
                # wire stage of the SLO budget: datagrams off the socket
                # through QUIC rx (decrypt + stream delivery + reassembly
                # publishes ride inside ep.rx via on_stream)
                t0 = time.monotonic_ns()
                self.ep.rx(pkts, now)
                ctx.trace.record(trace_mod.KIND_STAGE, t0,
                                 time.monotonic_ns() - t0, cnt=len(pkts))
            else:
                self.ep.rx(pkts, now)
        # deadline-driven service (not a fixed cadence): the endpoint
        # reports its earliest timer (PTO retransmit / idle reap) and we
        # run service exactly when it falls due — retransmits under load
        # are no longer quantized to a polling interval
        if now >= self.ep.next_timeout():
            self.ep.service(now)
        p = self._packed
        if p is not None and p.due():
            p.flush()
        if pkts or now - self._last_msync > 0.01:
            self._last_msync = now
            self._sync_metrics(ctx, now)

    def _sync_metrics(self, ctx, now: float) -> None:
        m = self.ep.metrics
        for k in ("pkt_rx", "pkt_tx", "conn_created", "conn_closed",
                  "streams_rx", "retrans", "pkt_undecryptable",
                  "pkt_malformed", "conn_reject", "rate_drop",
                  "crypto_native", "crypto_fallback",
                  "initial_keys_evict"):
            ctx.metrics.set(k + "_cnt", m[k])
        ctx.metrics.set("retry_sent_cnt", m["retry_tx"])
        r = self.reasm.metrics
        # every shed partial-stream, wire-level (endpoint recv_streams
        # budget/FIFO) or reasm-slot-level (TpuReasm conn budget/FIFO)
        ctx.metrics.set("reasm_evict_cnt",
                        m["reasm_evict"] + r["evict_cnt"])
        # completed txns dropped before publish (oversize/dup/empty/
        # packed-parse): reasm pub_cnt + this accounts every stream the
        # endpoint delivered
        ctx.metrics.set("reasm_drop_cnt",
                        r["oversz_cnt"] + r["dup_cnt"] + r["empty_cnt"]
                        + r["pub_cnt"] - ctx.metrics.get("reasm_pub_cnt"))
        ctx.metrics.set("conn_cnt", len(self.ep.conns))
        ctx.metrics.set("half_open_cnt", self.ep.half_open)
        # overload-shedding signal for /healthz: any shed counter moving
        # within the last ~5 s flips the gauge (held so scrapes can't
        # miss a short burst)
        shed = (m["conn_reject"] + m["conn_evict"] + m["rate_drop"]
                + m["retry_tx"] + m["reasm_evict"]
                + r["evict_cnt"] + r["oversz_cnt"])
        if shed > self._shed_total:
            self._shed_total = shed
            self._shed_ts = now
        ctx.metrics.set("shedding", 1 if now - self._shed_ts < 5.0 else 0)

    def fini(self, ctx):
        if self._packed is not None:
            self._packed.flush()
        self.sock.close()


class DedupTile:
    """Cross-verify-tile dedup on the signature tag
    (ref: src/app/fdctl/run/tiles/fd_dedup.c, tango tcache)."""

    def init(self, ctx):
        from ..tango.tcache import NativeTCache, ShardedTCache
        depth = ctx.cfg.get("tcache_depth", 1 << 20)
        # fleet mode (round 17): shard the tcache by sig prefix, with
        # ownership following the steering ring (cfg shard_own lists this
        # host's shards); foreign-shard tags still dedup — fail-safe — but
        # are surfaced as a gauge so fleet top can see mis-steering
        self._sharded = int(ctx.cfg.get("shard_bits", 0))
        if self._sharded:
            self.tcache = ShardedTCache(
                depth, self._sharded,
                owned=ctx.cfg.get("shard_own"))
        else:
            try:
                self.tcache = NativeTCache(depth)
            except Exception:
                self.tcache = TCache(depth)
        # failover/restart preload: tags already verdicted fleet-wide
        # (a dead host's capture ledger + gossiped sig digests, or our own
        # ledger across a host rolling restart) — rejecting them here is
        # what keeps the fleet verdict set exactly-once.  One u64 hex tag
        # per line; torn/partial lines are skipped (the writer may have
        # died mid-append).
        path = ctx.cfg.get("preload_tags_path") or ""
        if path:
            n = 0
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            tag = int(line, 16)
                        except ValueError:
                            continue
                        if 0 < tag < (1 << 64):
                            self.tcache.insert(tag)
                            n += 1
            except OSError:
                pass
            if n:
                ctx.metrics.add("preload_cnt", n)
        # packed verdict egress consumer (round 11): the upstream verify
        # tile ships ONE arena frag per harvest; on_burst_view unpacks it.
        # Hidden unless configured so ordinary per-txn links keep the
        # rx-scratch burst path; when configured, on_burst hides instead so
        # the mux skips its BURST_RX*mtu scratch (a packed link's mtu is a
        # whole arena — hundreds of KB).
        if ctx.cfg.get("packed_egress", 0):
            self.on_burst = None
        else:
            self.on_burst_view = None

    def on_frag(self, ctx, iidx, meta, payload):
        tag = int(meta["sig"])
        if self.tcache.insert(tag):
            ctx.metrics.add("dup_drop_cnt")
            return
        ctx.metrics.add("uniq_cnt")
        ctx.publish(payload, sig=tag)

    def on_burst(self, ctx, iidx, metas, buf, offs, kept):
        """Burst path: one batched tcache insert decides all verdicts,
        survivors forward in one burst publish."""
        tags = metas["sig"].astype(np.uint64)
        if hasattr(self.tcache, "insert_batch_dedup"):
            dup = self.tcache.insert_batch_dedup(tags)
        else:
            dup = np.array([self.tcache.insert(int(t)) for t in tags], bool)
        ndup = int(dup.sum())
        if ndup:
            ctx.metrics.add("dup_drop_cnt", ndup)
        keep = np.nonzero(~dup)[0]
        if not len(keep):
            return
        ctx.metrics.add("uniq_cnt", len(keep))
        starts = offs[:kept][keep]
        lens = (offs[1 : kept + 1] - offs[:kept])[keep].astype(np.int32)
        ctx.publish_burst(buf, starts, lens, tags[keep])

    def on_burst_view(self, ctx, iidx, metas, dcache):
        """Packed verdict egress rx: each frag is meta.sz wires behind a
        u32 offsets table (see VerifyTile._publish_packed_verdicts).  The
        frag is copied out of the shm view ONCE, then the mcache seq is
        re-checked — a producer lap mid-copy drops the frag whole
        (torn_drop_cnt) before anything derived from it is published.
        Tags re-derive from each wire's sig bytes (wire[1:9] LE), the
        same low-64 tag the per-txn path carries in meta.sig."""
        mc = ctx.in_mcache(iidx)
        for meta in metas:
            k = int(meta["sz"])
            if k <= 0:
                continue
            chunk, seq = int(meta["chunk"]), int(meta["seq"])
            hdr = 4 * (k + 1)
            # copy the offsets table out, then re-check the seq BEFORE
            # trusting it to size the payload copy (a torn table could
            # point anywhere); re-check again after the payload copy so
            # nothing derived from a lapped frag is ever published
            offs = dcache.view(chunk, hdr).view(np.uint32).astype(np.int64)
            rc, _ = mc.query(seq)
            if rc != 0:
                ctx.metrics.add("torn_drop_cnt")
                continue
            frag = dcache.view(chunk, hdr + int(offs[k]))[hdr:].copy()
            rc, _ = mc.query(seq)
            if rc != 0:
                ctx.metrics.add("torn_drop_cnt")
                continue
            starts = offs[:k]
            lens = (offs[1:] - offs[:k]).astype(np.int32)
            idx = starts[:, None] + np.arange(1, 9)
            tags = np.ascontiguousarray(frag[idx]).view(np.uint64).ravel()
            if hasattr(self.tcache, "insert_batch_dedup"):
                dup = self.tcache.insert_batch_dedup(tags)
            else:
                dup = np.array([self.tcache.insert(int(t)) for t in tags],
                               bool)
            ndup = int(dup.sum())
            if ndup:
                ctx.metrics.add("dup_drop_cnt", ndup)
            keep = np.nonzero(~dup)[0]
            if not len(keep):
                continue
            ctx.metrics.add("uniq_cnt", len(keep))
            ctx.publish_burst(frag, starts[keep], lens[keep], tags[keep])


    def house(self, ctx):
        if self._sharded:
            ctx.metrics.set("shard_foreign_cnt",
                            int(self.tcache.foreign_cnt))


class PackTile:
    """Block-packing scheduler tile (ref: src/app/fdctl/run/tiles/fd_pack.c
    over src/ballet/pack/fd_pack.c): inserts verified txns into the
    fee-priority scheduler and emits conflict-free microblocks round-robin
    to bank out-links (out link i = bank lane i)."""

    def init(self, ctx):
        from ..ballet.pack import Pack
        nbank = max(1, len(ctx.tile.out_links))
        self.pack = Pack(bank_tile_cnt=nbank,
                         max_txn_per_microblock=ctx.cfg.get("max_txn", 31))

    def on_frag(self, ctx, iidx, meta, payload):
        try:
            parsed = txn_lib.parse(payload)
        except txn_lib.TxnParseError:
            return
        if self.pack.insert(payload, parsed):
            ctx.metrics.add("txn_insert_cnt")
        self._drain(ctx)

    def after_credit(self, ctx):
        self._drain(ctx)

    def _drain(self, ctx):
        progressed = True
        while progressed and self.pack.pending:
            progressed = False
            for bank in range(self.pack.bank_cnt):
                mb = self.pack.schedule(bank)
                if mb is None:
                    continue
                for payload in mb.payloads:
                    ctx.publish(payload, sig=mb.bank, out=bank)
                ctx.metrics.add("microblock_cnt")
                # bank tiles are synchronous sinks for now: release at once
                self.pack.done(bank)
                progressed = True


class BankTile:
    """Executing bank tile (ref: src/app/fdctl/run/tiles/fd_bank.c — there a
    thin FFI shim into the Agave runtime; here the real thing: the flamenco
    Runtime executes microblock txns against a funk fork, freezes the slot
    after `slot_txn_max` txns or `slot_ns`, and rolls to the next slot).

    cfg: genesis_path (required), slot_txn_max, slot_ns."""

    def init(self, ctx):
        import hashlib
        from ..flamenco.genesis import Genesis
        from ..flamenco.runtime import Runtime
        self.rt = Runtime(Genesis.read(ctx.cfg["genesis_path"]))
        # blockhash feedback: an out link named *blockhash carries the
        # root hash to sources after every slot roll (real recency
        # semantics end-to-end).  pin_genesis_blockhash remains for
        # topologies without the link (sources can't refresh there).
        self._bh_out = next(
            (i for i, ln in enumerate(ctx.tile.out_links)
             if ln.endswith("blockhash")), None)
        # executed txns flow to PoH on the non-blockhash out link(s);
        # publishing them on the tiny-MTU blockhash link would wedge
        self._poh_outs = [i for i, ln in enumerate(ctx.tile.out_links)
                          if not ln.endswith("blockhash")]
        if ctx.cfg.get("pin_genesis_blockhash", self._bh_out is None):
            self.rt.blockhash_queue.pin(self.rt.root_hash)
        if ctx.cfg.get("blockhash_max_age"):
            self.rt.blockhash_queue.max_age = ctx.cfg["blockhash_max_age"]
        self.slot_txn_max = ctx.cfg.get("slot_txn_max", 1024)
        self.slot_ns = ctx.cfg.get("slot_ns", 400_000_000)
        self._hashlib = hashlib
        self._slot = 1
        self._bank = self.rt.new_bank(1)
        self._slot_t0 = time.monotonic_ns()
        self._last_bh_ns = 0
        self._poh = self.rt.root_hash
        self._txns_executed = 0
        self.rpc = None
        if ctx.cfg.get("rpc_port") is not None:
            # dev RPC served from the bank process (the reference's full-FD
            # path serves RPC from the validator; Frankendancer delegates
            # to Agave's) — submitted txns drain into the bank in house()
            from ..flamenco.rpc import RpcServer
            tile = self

            class _Provider:
                def slot(self):
                    return tile._slot

                def blockhash(self):
                    return tile.rt.root_hash

                def balance(self, pk: bytes) -> int:
                    # the bank xid can be published by a slot roll between
                    # reading it and the funk lookup (HTTP thread vs tile
                    # loop); retry, then fall back to the root view
                    for _ in range(3):
                        xid = tile._bank.xid
                        try:
                            acct = tile.rt.accdb.load(xid, pk)
                            break
                        except Exception:
                            continue
                    else:
                        acct = tile.rt.accdb.load(None, pk)
                    return 0 if acct is None else acct.lamports

                def txn_count(self):
                    return tile._txns_executed

            self.rpc = RpcServer(_Provider(), port=ctx.cfg["rpc_port"])
            ctx.metrics.set("rpc_port", self.rpc.port)

    def on_frag(self, ctx, iidx, meta, payload):
        self._exec(ctx, payload)

    def _exec(self, ctx, payload):
        res = self._bank.execute_txn(payload)
        if res.ok:
            self._txns_executed += 1
            ctx.metrics.add("txn_exec_cnt")
            for out in self._poh_outs:  # bank_poh: executed txns -> PoH
                ctx.publish(payload, sig=self._slot, out=out)
        else:
            ctx.metrics.add("txn_fail_cnt")
        if self._bank.txn_cnt >= self.slot_txn_max:
            self._roll(ctx)

    def house(self, ctx):
        if self.rpc is not None:
            for raw in self.rpc.drain():
                # RPC submissions bypass the verify tile, so the bank must
                # check signatures itself before execution (the executor's
                # contract is "already signature-verified" txns)
                if self._rpc_sigs_ok(raw):
                    self._exec(ctx, raw)
                else:
                    ctx.metrics.add("txn_fail_cnt")
        if (self._bank.txn_cnt
                and time.monotonic_ns() - self._slot_t0 > self.slot_ns):
            self._roll(ctx)
        elif (self._bh_out is not None
              and time.monotonic_ns() - self._last_bh_ns
              > min(self.slot_ns, 200_000_000)):
            # heartbeat the current blockhash even with no traffic, so
            # feedback-gated sources can begin producing
            self._last_bh_ns = time.monotonic_ns()
            ctx.publish(self.rt.root_hash, sig=self._slot, out=self._bh_out)

    @staticmethod
    def _rpc_sigs_ok(raw: bytes) -> bool:
        from ..ops.ed25519 import verify_one_host
        try:
            parsed = txn_lib.parse(raw)
        except txn_lib.TxnParseError:
            return False
        msg = parsed.message(raw)
        sigs = parsed.signatures(raw)
        pubs = parsed.signer_pubkeys(raw)
        return all(verify_one_host(s, msg, p) for s, p in zip(sigs, pubs))

    def _roll(self, ctx):
        """Freeze + root the slot, open the next (single-fork leader mode;
        fork choice arrives with the choreo layer)."""
        self._poh = self._hashlib.sha256(self._poh).digest()
        self._bank.freeze(self._poh)
        self.rt.publish(self._slot)
        self._slot += 1
        self._bank = self.rt.new_bank(self._slot)
        self._slot_t0 = time.monotonic_ns()
        ctx.metrics.add("slot_cnt")
        if self._bh_out is not None:
            self._last_bh_ns = time.monotonic_ns()
            ctx.publish(self.rt.root_hash, sig=self._slot, out=self._bh_out)

    def fini(self, ctx):
        if self._bank.txn_cnt:
            self._roll(ctx)
        if self.rpc is not None:
            self.rpc.close()


class SignTile:
    """Key-isolation signer (ref: src/app/fdctl/run/tiles/fd_sign.c).  The
    only tile whose process reads the private key; serves role-typed signing
    requests arriving on in-links and replies on the SAME-INDEX out link
    (in_links[i] requests -> out_links[i] responses).  Requests whose
    payload shape is illegal for the role are refused with an empty frag.

    cfg: key_path (JSON keypair file)."""

    def init(self, ctx):
        from ..ops import ed25519 as ed
        from . import keyguard
        self._kg = keyguard
        self._ed = ed
        self.seed, self.pub = keyguard.keypair_read(ctx.cfg["key_path"])

    def on_frag(self, ctx, iidx, meta, payload):
        role = payload[0] if payload else 0
        msg = bytes(payload[1:])
        if not self._kg.role_payload_ok(role, msg):
            ctx.metrics.add("refuse_cnt")
            ctx.publish(b"", sig=role, out=iidx)
            return
        sig = self._ed.sign(self.seed, msg)
        ctx.metrics.add("sign_cnt")
        ctx.publish(sig, sig=role, out=iidx)


class PohTile:
    """Proof-of-history tile (ref: src/app/fdctl/run/tiles/fd_poh.c /
    src/disco/poh/fd_poh_tile.c): continuously advances the sha256 hash
    chain, mixes in executed microblocks from the bank as txn entries, and
    emits serialized entries (sig = slot) to the shred link.  Ticks are
    emitted from housekeeping; after ticks_per_slot ticks the slot advances
    and the final entry is flagged slot-complete (ctl ERR bit repurposed is
    NOT used — the shred tile watches sig slot changes and the tick count
    embedded in the frag's ctl field stays standard; slot completion rides
    the `sig` high bit).

    cfg: seed_hash (hex, default zeros), hashes_per_tick, ticks_per_slot,
    start_slot."""

    SLOT_DONE_BIT = 1 << 63

    def init(self, ctx):
        from ..ballet import entry as entry_lib
        self._el = entry_lib
        cfg = ctx.cfg
        self.hash = bytes.fromhex(cfg["seed_hash"]) if "seed_hash" in cfg \
            else bytes(32)
        self.hashes_per_tick = cfg.get("hashes_per_tick", 16)
        self.ticks_per_slot = cfg.get("ticks_per_slot", 8)
        self.slot = cfg.get("start_slot", 1)
        self.tick = 0
        # With a bank in-link the BANK's slot (carried in each frag's sig)
        # is authoritative for slot boundaries, so PoH/shred slots contain
        # exactly the txns the bank executed in that slot — otherwise a
        # follower replaying slot N would execute a different txn set than
        # the leader's slot-N bank and fail the bank-hash check.  Ticks
        # advance slots only in standalone (no-bank) topologies.
        self.bank_driven = bool(ctx.tile.in_links)

    def _emit(self, ctx, e, slot_done: bool):
        sig = self.slot | (self.SLOT_DONE_BIT if slot_done else 0)
        ctx.publish(e.serialize(), sig=sig)

    def on_frag(self, ctx, iidx, meta, payload):
        """A bank frag: one executed txn payload to absorb (sig = slot the
        bank executed it in; entries group per frag burst for simplicity —
        one txn per entry is legal)."""
        bslot = int(meta["sig"]) & ~self.SLOT_DONE_BIT
        if self.bank_driven and bslot > self.slot:
            # bank rolled: close our current slot before absorbing the
            # first txn of the new one
            self._emit(ctx, self._el.Entry(0, self.hash, []), True)
            self.slot = bslot
            self.tick = 0
        mix = self._el.txn_mixin([payload])
        self.hash = self._el.next_hash(self.hash, 1, mix)
        self._emit(ctx, self._el.Entry(1, self.hash, [payload]), False)
        ctx.metrics.add("mixin_cnt")
        ctx.metrics.add("hash_cnt")

    def house(self, ctx):
        self.hash = self._el.next_hash(self.hash, self.hashes_per_tick, None)
        ctx.metrics.add("hash_cnt", self.hashes_per_tick)
        self.tick += 1
        done = (not self.bank_driven) and self.tick >= self.ticks_per_slot
        self._emit(ctx, self._el.Entry(self.hashes_per_tick, self.hash, []),
                   done)
        if done:
            self.tick = 0
            self.slot += 1

    def fini(self, ctx):
        # close the slot so downstream sees a complete block
        if self.tick:
            self.hash = self._el.next_hash(self.hash, self.hashes_per_tick,
                                           None)
            self._emit(ctx, self._el.Entry(
                self.hashes_per_tick, self.hash, []), True)


class LeaderPackTile:
    """Leader-lane pack scheduler (round 14; ref: fd_pack.c between dedup
    and the banks, here between verify and the device PoH tile): consumes
    verify's verdict egress — per-txn frags or the PR-11 packed arena
    format — runs ballet.pack's fee-priority heap + account-conflict
    scheduling host-side, and emits each conflict-free microblock as ONE
    frag in entry.serialize_txn_batch wire (sig = monotonic microblock
    seq, bit 63 clear so it can never read as a slot-done entry sig).

    Vote-vs-regular admission rides the cost model: simple votes bypass
    the max_pending heap cap (the reserved vote lane), so a fee-paying
    flood can't crowd consensus traffic out of the block.

    Sharding (round 15): with shard_cnt > 1 every shard consumes ALL
    verify links and keeps only the txns whose fee payer hashes to it
    (acct_key(fee_payer) % shard_cnt — deterministic, so a respawned
    shard steers identically).  The fee payer is always writable, so a
    fee payer's whole conflict neighborhood lands on one shard and
    cross-shard write conflicts are the rare multi-payer-hot-account
    case — serialized by microblock ordering at the merge, same as the
    single-packer's done(0)-immediately semantics.  Sharded microblocks
    egress in a merge wire (budget header + serialized batch) to
    LeaderMergeTile, which owns the GLOBAL block budgets.

    cfg: max_txn (per microblock, default 31), max_pending (heap cap, 0 =
    unbounded), block_us (end_block cadence, default 400_000),
    packed_egress (consume arena frags), shard_cnt/shard_idx (fee-payer
    sharding; shard_cnt > 1 switches egress to the merge wire),
    native_pack (-1 auto, 0 force the Python fallback, 1 require the C
    hot loop)."""

    # merge wire: n_acct u32 | cost u64 | vote_cost u64 | data u32 |
    # n_acct * (acct_key u64 | write_cost u64) | serialize_txn_batch
    MERGE_HDR = struct.Struct("<IQQI")
    MERGE_ITEM = struct.Struct("<QQ")

    # pack.Pack.metrics -> tile metric slots (synced by delta so a
    # respawned tile's fresh Pack never rewinds shm counters)
    _PACK_METRICS = (
        ("inserted", "txn_insert_cnt"),
        ("vote_inserted", "vote_insert_cnt"),
        ("scheduled", "sched_txn_cnt"),
        ("microblocks", "microblock_cnt"),
        ("dropped_oversize", "oversize_drop_cnt"),
        ("dropped_heap_full", "heap_full_drop_cnt"),
        ("delayed_conflict", "conflict_delay_cnt"),
    )

    def init(self, ctx):
        from ..ballet import entry as entry_lib
        from ..ballet import pack as pack_lib
        self._el = entry_lib
        self._pl = pack_lib
        native = {0: False, 1: True}.get(ctx.cfg.get("native_pack", -1))
        self.pack = pack_lib.Pack(
            bank_tile_cnt=1,
            max_txn_per_microblock=ctx.cfg.get("max_txn", 31),
            max_pending=ctx.cfg.get("max_pending", 0),
            native=native)
        self.shard_cnt = ctx.cfg.get("shard_cnt", 1)
        self.shard_idx = ctx.cfg.get("shard_idx", 0)
        self.block_us = ctx.cfg.get("block_us", 400_000)
        self._block_t0 = time.monotonic_ns()
        self._mb_seq = 0
        self._last_pm = {k: 0 for k, _ in self._PACK_METRICS}
        self._drain_stall = 0
        if not ctx.cfg.get("packed_egress", 0):
            self.on_burst_view = None

    def _sync_pack(self, ctx):
        pm = self.pack.metrics
        for key, slot in self._PACK_METRICS:
            d = pm[key] - self._last_pm[key]
            if d:
                ctx.metrics.add(slot, d)
                self._last_pm[key] = pm[key]
        ctx.metrics.set("pending", self.pack.pending)

    def _insert(self, ctx, payload: bytes):
        if self.shard_cnt > 1:
            # deterministic fee-payer steering: a broken header steers to
            # shard 0, whose full parse rejects it with the real error
            fp = txn_lib.fee_payer(payload)
            shard = (self._pl.acct_key(fp) % self.shard_cnt
                     if fp is not None else 0)
            if shard != self.shard_idx:
                return
            ctx.metrics.add("shard_steer_cnt")
        ctx.metrics.add("txn_in_cnt")
        try:
            parsed = txn_lib.parse(payload)
        except txn_lib.TxnParseError:
            ctx.metrics.add("parse_fail_cnt")
            return
        self.pack.insert(bytes(payload), parsed)

    def on_frag(self, ctx, iidx, meta, payload):
        self._insert(ctx, payload)
        self._emit(ctx)
        self._sync_pack(ctx)

    def on_burst_view(self, ctx, iidx, metas, dcache):
        """Packed verdict egress rx (the DedupTile unpack): copy the frag
        out of the shm view once, re-checking the mcache seq before the
        offsets table is trusted and again after the payload copy, so
        nothing derived from a producer-lapped frag is ever inserted."""
        mc = ctx.in_mcache(iidx)
        for meta in metas:
            k = int(meta["sz"])
            if k <= 0:
                continue
            chunk, seq = int(meta["chunk"]), int(meta["seq"])
            hdr = 4 * (k + 1)
            offs = dcache.view(chunk, hdr).view(np.uint32).astype(np.int64)
            rc, _ = mc.query(seq)
            if rc != 0:
                ctx.metrics.add("torn_drop_cnt")
                continue
            frag = dcache.view(chunk, hdr + int(offs[k]))[hdr:].copy()
            rc, _ = mc.query(seq)
            if rc != 0:
                ctx.metrics.add("torn_drop_cnt")
                continue
            for w in range(k):
                self._insert(ctx, bytes(frag[offs[w]:offs[w + 1]]))
        self._emit(ctx)
        self._sync_pack(ctx)

    def _emit(self, ctx) -> bool:
        """Schedule + publish until the heap can't progress.  One bank
        lane whose locks release immediately (the PoH tile is a
        synchronous consumer), so within a microblock conflicts are
        excluded and across microblocks ordering does the serializing."""
        progressed = False
        while True:
            mb = self.pack.schedule(0)
            if mb is None:
                break
            payload = self._el.serialize_txn_batch(mb.payloads)
            if self.shard_cnt > 1:
                payload = self._merge_wire(mb) + payload
            ctx.publish(payload, sig=self._mb_seq)
            self._mb_seq += 1
            ctx.metrics.add("cu_consumed",
                            sum(h.cost.total for h in mb.txns))
            self.pack.done(0)
            progressed = True
        return progressed

    def _merge_wire(self, mb) -> bytes:
        """Budget header for LeaderMergeTile's global accounting: total /
        vote cost, data bytes, and per-account write costs (u64 keys —
        the merge never re-parses).  Accounts are unique across the
        microblock's txns by construction (write-write conflicts are
        excluded within one microblock)."""
        total = vote = data = 0
        items: dict = {}
        for h in mb.txns:
            total += h.cost.total
            if h.cost.is_simple_vote:
                vote += h.cost.total
            data += len(h.payload)
            for k, c in self._pl.writable_key_costs(h).items():
                items[k] = items.get(k, 0) + c
        return self.MERGE_HDR.pack(len(items), total, vote, data) + \
            b"".join(self.MERGE_ITEM.pack(k, c) for k, c in items.items())

    def after_credit(self, ctx):
        if self.pack.pending:
            self._emit(ctx)
            self._sync_pack(ctx)

    def house(self, ctx):
        if (time.monotonic_ns() - self._block_t0) // 1000 >= self.block_us:
            self.pack.end_block()
            self._block_t0 = time.monotonic_ns()
        self._sync_pack(ctx)

    def drain(self, ctx) -> bool:
        """Drain-protocol hook: flush the heap so a rolling restart loses
        nothing.  Block limits reset (end_block) so leftover txns aren't
        stuck behind this block's budget; a heap that still can't
        progress after two budget resets is dropped with a counter —
        never a silent hang of the drain protocol."""
        progressed = self._emit(ctx)
        if not self.pack.pending:
            self._sync_pack(ctx)
            return True
        if progressed:
            self._drain_stall = 0
            return False
        self._drain_stall += 1
        self.pack.end_block()
        self._block_t0 = time.monotonic_ns()
        if self._drain_stall >= 3:
            ctx.metrics.add("drain_drop_cnt", self.pack.clear_pending())
            self._sync_pack(ctx)
            return True
        return False

    def fini(self, ctx):
        try:
            self._emit(ctx)
            self._sync_pack(ctx)
        except Exception:
            pass  # downstream rings may already be gone


class LeaderMergeTile:
    """Shard-merge stage of the sharded leader lane (round 15): consumes
    the merge-wire microblock frags from every leader_pack shard and
    interleaves them round-robin into ONE tick-stream, enforcing the
    GLOBAL block/vote/data and per-account write budgets here — each
    shard's Pack only pre-filters against its local copy, so this tile
    is the consensus-critical accounting authority.

    Admission: one pass over the shards per round starting at a rotating
    cursor, admitting at most one head microblock per shard per pass
    (the round-robin interleave).  A head that would overflow a budget
    stays queued (merge_budget_defer_cnt) until the block rolls; a full
    pass with queued work but zero admissions counts merge_stall_cnt.
    Admitted frags re-publish the inner serialize_txn_batch payload
    (merge header stripped) with this tile's own monotonic microblock
    seq, so PohDevTile sees exactly the single-packer wire.

    Drain convergence: any single shard microblock fits a fresh budget
    (see pack.MergeBudget), so resetting the block always unblocks."""

    def init(self, ctx):
        from collections import deque
        from ..ballet import pack as pack_lib
        self._deque = deque
        self.budget = pack_lib.MergeBudget()
        self.block_us = ctx.cfg.get("block_us", 400_000)
        self._block_t0 = time.monotonic_ns()
        self._qs: dict = {}  # iidx -> deque of (cost, vote, data, items, inner)
        self._rr = 0
        self._mb_seq = 0
        self._drain_stall = 0

    def on_frag(self, ctx, iidx, meta, payload):
        b = bytes(payload)
        try:
            n_items, cost, vote, data = \
                LeaderPackTile.MERGE_HDR.unpack_from(b, 0)
            off = LeaderPackTile.MERGE_HDR.size
            items = [LeaderPackTile.MERGE_ITEM.unpack_from(b, off + 16 * i)
                     for i in range(n_items)]
            inner = b[off + 16 * n_items:]
        except struct.error:
            ctx.metrics.add("parse_fail_cnt")
            return
        self._qs.setdefault(iidx, self._deque()).append(
            (cost, vote, data, items, inner))
        ctx.metrics.add("mb_rx_cnt")
        self._admit(ctx)

    def _admit(self, ctx) -> bool:
        keys = sorted(self._qs)
        if not keys:
            return False
        admitted_any = False
        while True:
            progressed = False
            deferred = False
            for off in range(len(keys)):
                q = self._qs[keys[(self._rr + off) % len(keys)]]
                if not q:
                    continue
                cost, vote, data, items, inner = q[0]
                if not self.budget.try_admit(cost, vote, data, items):
                    ctx.metrics.add("merge_budget_defer_cnt")
                    deferred = True
                    continue
                q.popleft()
                ctx.publish(inner, sig=self._mb_seq)
                self._mb_seq += 1
                ctx.metrics.add("mb_merge_cnt")
                progressed = True
            self._rr = (self._rr + 1) % len(keys)
            if not progressed:
                if deferred:
                    ctx.metrics.add("merge_stall_cnt")
                break
            admitted_any = True
        ctx.metrics.set("merge_q", sum(len(q) for q in self._qs.values()))
        return admitted_any

    def house(self, ctx):
        if (time.monotonic_ns() - self._block_t0) // 1000 >= self.block_us:
            self.budget.end_block()
            self._block_t0 = time.monotonic_ns()
        self._admit(ctx)

    def after_credit(self, ctx):
        if any(self._qs.values()):
            self._admit(ctx)

    def drain(self, ctx) -> bool:
        """Drain-protocol hook: flush every queued microblock.  Budget
        resets force progress (any one microblock fits a fresh block);
        the drop path is an unreachable safety net, never silent."""
        self._admit(ctx)
        if not any(self._qs.values()):
            return True
        self.budget.end_block()
        self._block_t0 = time.monotonic_ns()
        if self._admit(ctx):
            self._drain_stall = 0
            return not any(self._qs.values())
        self._drain_stall += 1
        if self._drain_stall >= 3:
            n = sum(len(q) for q in self._qs.values())
            ctx.metrics.add("drain_drop_cnt", n)
            for q in self._qs.values():
                q.clear()
            return True
        return False

    def fini(self, ctx):
        try:
            self._admit(ctx)
            if any(self._qs.values()):
                self.budget.end_block()
                self._admit(ctx)
        except Exception:
            pass  # downstream rings may already be gone


class PohDevTile:
    """Device-batched PoH tile (round 14; ref: fd_poh_tile.c's hashing
    core over ballet.poh_engine.PohEngine): extends the slot hash chain
    through (lanes, 32) span dispatches on the shared packed rotation
    engine instead of host hashlib.  Lane 0 is the chain; the remaining
    lanes re-verify previously emitted entries (the embarrassingly-
    parallel verify_entries re-check, riding the same dispatch).

    Speculation (round 15, K ticks deep): mixins sit at the END of each
    tick — P = hashes_per_tick - mb_per_tick - 1 plain hashes, then up
    to mb_per_tick single-hash mixin entries, then a tail.  One window
    dispatch pre-hashes K whole ticks from the current head as 2K
    chained steps ((P, None), (tail, None) per tick), so every tick
    boundary AND every mixin insertion point (state @ P) comes back as a
    step plane.  A tick that closes empty consumes one speculated tick
    (spec_hit) with zero extra hashing; a tick that closes with j
    microblocks SPLICES: a second small engine re-hashes only from the
    saved state @ P — steps (1, m_1)..(1, m_j), inactive padding,
    (tail - j, None), per-step hash caps (1,..,1,tail) — so the re-hash
    costs tail - j wasted hashes (rehash_cnt) instead of the whole tick,
    and the later speculated ticks are invalidated (their chain
    assumption broke).  Mixins are device-batched via
    entry.txn_mixins_device; emitted-entry re-checks ride spare window
    lanes.

    In: microblock frags from leader_pack (entry.serialize_txn_batch
    wire).  Out: serialized entries, sig = slot | SLOT_DONE_BIT — the
    same contract as PohTile, so shred/store consume either.

    cfg: seed_hash (hex), hashes_per_tick, ticks_per_slot, start_slot,
    spec_ticks (K, speculation depth in ticks), spec_spans (total window
    engine lanes: 1 chain + N-1 recheck), mb_per_tick (mixin entries per
    tick; capped at hashes_per_tick - 1), mixin_txn_max (pad width for
    the mixin tree shape), nbuf, depth, unroll."""

    SLOT_DONE_BIT = 1 << 63

    def init(self, ctx):
        from collections import deque

        from ..ballet import entry as entry_lib
        from ..ballet.poh_engine import PohEngine
        self._el = entry_lib
        cfg = ctx.cfg
        self.hash = bytes.fromhex(cfg["seed_hash"]) if "seed_hash" in cfg \
            else bytes(32)
        self.hashes_per_tick = cfg.get("hashes_per_tick", 16)
        self.ticks_per_slot = cfg.get("ticks_per_slot", 8)
        self.slot = cfg.get("start_slot", 1)
        self.tick = 0
        # spec_spans = total concurrent span lanes: 1 chain lane + the
        # emitted-entry re-check lanes
        self.recheck_lanes = max(0, cfg.get("spec_spans", 3) - 1)
        self.mb_cap = min(cfg.get("mb_per_tick", 8),
                          self.hashes_per_tick - 1)
        if self.mb_cap < 1:
            raise ValueError("hashes_per_tick must be >= 2 for mixins")
        self.mixin_txn_max = cfg.get("mixin_txn_max", 32)
        self.K = max(1, cfg.get("spec_ticks", 4))
        # tick anatomy: P plain hashes, then the mixin region + tail
        self.P = self.hashes_per_tick - self.mb_cap - 1
        tail = self.mb_cap + 1
        # window engine: K ticks of (P, tail) step pairs.  Step 0's cap
        # is the full hashes_per_tick so recheck lanes (entry n up to a
        # whole tick) fit in the shared first step.
        caps = [self.hashes_per_tick, tail] \
            + [max(self.P, 1), tail] * (self.K - 1)
        self.eng = PohEngine(
            lanes=1 + self.recheck_lanes,
            steps=2 * self.K,
            max_hashes=self.hashes_per_tick,
            step_caps=caps,
            nbuf=cfg.get("nbuf", 2), depth=cfg.get("depth"),
            unroll=cfg.get("unroll", 8))
        # splice engine: re-hash from the saved mixin insertion point —
        # j mixin steps (1 hash each) + the plain tail, never a full tick
        self.seng = PohEngine(
            lanes=1,
            steps=tail,
            max_hashes=tail,
            step_caps=(1,) * self.mb_cap + (tail,),
            nbuf=2, unroll=cfg.get("unroll", 8))
        # compile BEFORE signaling RUN: both span graphs and the
        # mixin-tree shape the hot path will use
        self.eng.warm()
        self.seng.warm()
        entry_lib.txn_mixins_device(
            [[b"\x00" * 65]], pad_batch=self.mb_cap,
            pad_width=self.mixin_txn_max)
        self._mb_q = deque()          # parsed microblocks awaiting a tick
        self._recheck_q = deque(maxlen=256)   # (start, n, mixin|None, end)
        self._pending_disp = deque()  # window-dispatch FIFO
        self._win = None              # current speculation window record
        self._win_pos = 0             # speculated ticks already consumed

    # -------------------------------------------------------------- ingest
    def on_frag(self, ctx, iidx, meta, payload):
        try:
            txns, _ = self._el.deserialize_txn_batch(bytes(payload))
        except ValueError:
            ctx.metrics.add("parse_fail_cnt")
            return
        if not txns or len(txns) > self.mixin_txn_max:
            ctx.metrics.add("parse_fail_cnt")
            return
        self._mb_q.append(txns)
        ctx.metrics.add("mb_rx_cnt")

    # ------------------------------------------------------------- harvest
    def _emit(self, ctx, e, slot_done: bool, slot: int):
        ctx.publish(e.serialize(), sig=slot
                    | (self.SLOT_DONE_BIT if slot_done else 0))
        ctx.metrics.add("entry_cnt")

    def _process(self, ctx, verdicts):
        for v in verdicts:
            planes = self.eng.split_verdict(v)
            rec = self._pending_disp.popleft()
            for lane, exp in rec["rechecks"]:
                if bytes(planes[lane, 0]) == exp:
                    ctx.metrics.add("recheck_ok_cnt")
                else:
                    ctx.metrics.add("recheck_fail_cnt")
            # harvest the window: per speculated tick, the state at the
            # mixin insertion point (plane 2t) and the tick end (2t+1)
            rec["mid"] = [bytes(planes[0, 2 * t]) for t in range(self.K)]
            rec["end"] = [bytes(planes[0, 2 * t + 1]) for t in range(self.K)]
            rec["heads"] = [rec["head"]] + rec["end"][:-1]
            rec["ready"] = True

    # ---------------------------------------------------------- tick cycle
    def _open_window(self, ctx):
        rec = {"head": self.hash, "rechecks": [], "heads": None,
               "mid": None, "end": None, "ready": False}
        steps = []
        for _ in range(self.K):
            steps.append((self.P, None))
            steps.append((self.mb_cap + 1, None))
        lanes = [(self.hash, steps)]
        for lane in range(1, 1 + self.recheck_lanes):
            if not self._recheck_q:
                break
            start, n, mix, end = self._recheck_q.popleft()
            lanes.append((start, [(n, mix)]))
            rec["rechecks"].append((lane, end))
        self._pending_disp.append(rec)
        self._win = rec
        self._win_pos = 0
        ctx.metrics.add("dispatch_cnt")
        self._process(ctx, self.eng.submit_lanes(lanes))

    def _close_tick(self, ctx, final: bool = False):
        j = min(len(self._mb_q), self.mb_cap)
        mbs = [self._mb_q.popleft() for _ in range(j)]
        if self._mb_q:
            ctx.metrics.add("mb_deferred_cnt", len(self._mb_q))
        done = final or (self.tick + 1 >= self.ticks_per_slot)
        win = self._win
        if not win["ready"]:
            self._process(ctx, self.eng.drain())
        t = self._win_pos
        if j == 0:
            # speculation lands: the pre-hashed tick IS the tick, and
            # the window stays live for the next one
            ctx.metrics.add("spec_hit_cnt")
            end = win["end"][t]
            self._emit(ctx, self._el.Entry(self.hashes_per_tick, end, []),
                       done, self.slot)
            self._recheck_q.append(
                (win["heads"][t], self.hashes_per_tick, None, end))
            self.hash = end
            self._win_pos += 1
            if self._win_pos >= self.K:
                self._win = None
        else:
            # mixins landed: splice from the saved state @ P — only the
            # mixin region re-hashes; the later speculated ticks assumed
            # a plain chain and are invalidated
            ctx.metrics.add("spec_miss_cnt")
            ctx.metrics.add("rehash_cnt", self.mb_cap + 1 - j)
            mix_arr = self._el.txn_mixins_device(
                mbs, pad_batch=self.mb_cap, pad_width=self.mixin_txn_max)
            mixins = [bytes(mix_arr[i]) for i in range(j)]
            steps = [(1, m) for m in mixins]
            steps += [(0, None)] * (self.mb_cap - j)
            steps.append((self.mb_cap + 1 - j, None))
            ctx.metrics.add("splice_dispatch_cnt")
            # entry ordering is consensus-critical: the splice retires
            # synchronously before the next tick opens on its end state
            verdicts = self.seng.submit_lanes([(win["mid"][t], steps)])
            verdicts += self.seng.drain()
            planes = self.seng.split_verdict(verdicts[-1])
            h = win["heads"][t]
            end = bytes(planes[0, 0])
            self._emit(ctx, self._el.Entry(self.P + 1, end, mbs[0]),
                       False, self.slot)
            self._recheck_q.append((h, self.P + 1, mixins[0], end))
            ctx.metrics.add("mixin_cnt")
            h = end
            for si in range(1, j):
                end = bytes(planes[0, si])
                self._emit(ctx, self._el.Entry(1, end, mbs[si]),
                           False, self.slot)
                self._recheck_q.append((h, 1, mixins[si], end))
                ctx.metrics.add("mixin_cnt")
                h = end
            n_rem = self.mb_cap + 1 - j
            end = bytes(planes[0, self.mb_cap])
            self._emit(ctx, self._el.Entry(n_rem, end, []), done, self.slot)
            self._recheck_q.append((h, n_rem, None, end))
            self.hash = end
            self._win = None
        ctx.metrics.add("hash_cnt", self.hashes_per_tick)
        ctx.metrics.add("tick_cnt")
        if done:
            self.tick = 0
            self.slot += 1
        else:
            self.tick += 1

    def house(self, ctx):
        if self._win is None:
            self._open_window(ctx)
        else:
            self._close_tick(ctx)
            if self._win is None:
                self._open_window(ctx)
        ctx.metrics.set("mb_queue", len(self._mb_q))
        ctx.metrics.set("spec_depth",
                        (self.K - self._win_pos) if self._win else 0)

    def after_credit(self, ctx):
        verdicts = self.eng.poll()
        if verdicts:
            self._process(ctx, verdicts)
        ctx.metrics.set("inflight_depth",
                        self.eng.inflight_depth + self.seng.inflight_depth)

    def drain(self, ctx) -> bool:
        """Drain-protocol hook: absorb every queued microblock into
        closed ticks, then run the engine dry."""
        if self._win is not None:
            self._close_tick(ctx)
            if self._mb_q:
                if self._win is None:
                    self._open_window(ctx)
                return False
        elif self._mb_q:
            self._open_window(ctx)
            return False
        self._process(ctx, self.eng.drain())
        self.seng.drain()
        return True

    def fini(self, ctx):
        try:
            # close the slot so downstream sees a complete block
            if self._win is None:
                self._open_window(ctx)
            while self._mb_q:
                self._close_tick(ctx)
                if self._win is None and self._mb_q:
                    self._open_window(ctx)
            if self._win is None:
                self._open_window(ctx)
            self._close_tick(ctx, final=True)
            self._process(ctx, self.eng.drain())
        except Exception:
            pass  # downstream rings may already be gone


class _ShredSigBatcher:
    """Batched leader-signature admission for turbine ingress (round 13).

    The old path paid one device graph dispatch PER SHRED (host merkle
    walk + ops.ed25519.verify_one): admission cost scaled with packet
    rate.  Queued shreds now clear as a burst — every merkle root walks
    in ONE batched sha256 graph (ballet.bmtree.batch_walk_roots) and the
    64-byte root signatures verify through the SAME batched SigVerifier
    packed admission the txn lane uses.  Forwarding is deferred until
    the burst verdict; the caller re-checks dedup at verdict time before
    inserting, so the insert-only-after-signed discipline (forge-then-
    censor resistance) is unchanged.

    backend="device" is the batched path; "host" keeps per-shred
    python-int verification (control-plane rates, no device graphs)."""

    # padded batch geometry: leaf data spans at most the wire MTU minus
    # the signature; the proof-length nibble caps the walk depth at 15
    LEAF_MAXLEN = 1228 - 64
    PROOF_DEPTH = 15

    def __init__(self, batch: int = 32, backend: str = "device",
                 flush_age_us: int = 2000):
        if backend not in ("device", "host"):
            raise ValueError(f"unknown sig backend {backend!r}")
        self.batch = max(1, int(batch))
        self.backend = backend
        self.flush_age_us = flush_age_us
        self._q: list = []            # (shred, raw, tag, leader)
        self._t0 = None               # monotonic_ns of oldest queued shred
        if backend == "device":
            from ..ballet import bmtree
            from ..models.verifier import SigVerifier, VerifierConfig
            self._bm = bmtree
            self._roots_fn = bmtree.batch_walk_roots_jit()
            self._sv = SigVerifier(VerifierConfig(batch=self.batch,
                                                  msg_maxlen=32))

    def __len__(self) -> int:
        return len(self._q)

    @property
    def full(self) -> bool:
        return len(self._q) >= self.batch

    def due(self) -> bool:
        """Age deadline: a partial batch must not hold shreds hostage
        when the ingress rate drops (same flush-on-size-or-age shape as
        the verify tile's coalescer)."""
        return (self._t0 is not None
                and time.monotonic_ns() - self._t0
                >= self.flush_age_us * 1000)

    def add(self, s, raw: bytes, tag: int, leader) -> None:
        if self._t0 is None:
            self._t0 = time.monotonic_ns()
        self._q.append((s, raw, tag, leader))

    def warm(self) -> None:
        """Pre-RUN compile of the batched admission graphs (same
        discipline as VerifyTile's warmup: the first live burst must not
        stall the mux loop through a cold compile)."""
        if self.backend != "device":
            return
        b = self.batch
        np.asarray(self._roots_fn(
            np.zeros((b, self.LEAF_MAXLEN), np.uint8),
            np.zeros((b,), np.int32), np.zeros((b,), np.int32),
            np.zeros((b, self.PROOF_DEPTH, self._bm.MERKLE_NODE_SZ),
                     np.uint8),
            np.zeros((b,), np.int32)))
        np.asarray(self._sv.packed_dispatch(
            np.zeros((b, 32), np.uint8), np.full((b,), 32, np.int32),
            np.zeros((b, 64), np.uint8), np.zeros((b, 32), np.uint8)))

    def flush(self) -> list:
        """Verify everything queued: [(shred, raw, tag, ok)], FIFO."""
        q, self._q, self._t0 = self._q, [], None
        if not q:
            return []
        if self.backend == "host":
            out = []
            for s, raw, tag, leader in q:
                root = s.merkle_root()
                ok = (root is not None and leader is not None
                      and _ed25519_verify_host(s.signature, root, leader))
                out.append((s, raw, tag, ok))
            return out
        out = []
        for i in range(0, len(q), self.batch):
            out.extend(self._verify_chunk(q[i:i + self.batch]))
        return out

    def _verify_chunk(self, chunk: list) -> list:
        from ..ballet.shred import TYPE_LEGACY_CODE, TYPE_LEGACY_DATA
        b = self.batch
        leaf = np.zeros((b, self.LEAF_MAXLEN), np.uint8)
        lens = np.zeros((b,), np.int32)
        idxs = np.zeros((b,), np.int32)
        proofs = np.zeros((b, self.PROOF_DEPTH, self._bm.MERKLE_NODE_SZ),
                          np.uint8)
        depths = np.zeros((b,), np.int32)
        sigs = np.zeros((b, 64), np.uint8)
        pubs = np.zeros((b, 32), np.uint8)
        elig = np.zeros((b,), bool)
        for j, (s, _raw, _tag, leader) in enumerate(chunk):
            # legacy (non-merkle) shreds have no signable root; unknown
            # leaders are unverifiable — both fail without a dispatch lane
            if leader is None or s.type in (TYPE_LEGACY_DATA,
                                            TYPE_LEGACY_CODE):
                continue
            ld = s.merkle_leaf_data()
            leaf[j, :len(ld)] = np.frombuffer(ld, np.uint8)
            lens[j] = len(ld)
            idxs[j] = s.tree_index()
            for d, node in enumerate(s.proof_nodes()):
                proofs[j, d] = np.frombuffer(node, np.uint8)
            depths[j] = s.merkle_proof_len
            sigs[j] = np.frombuffer(s.signature, np.uint8)
            pubs[j] = np.frombuffer(leader, np.uint8)
            elig[j] = True
        roots = np.asarray(self._roots_fn(leaf, lens, idxs, proofs, depths))
        ok = np.asarray(self._sv.packed_dispatch(
            roots, np.full((b,), 32, np.int32), sigs, pubs))
        ok = ok.astype(bool) & elig
        return [(s, raw, tag, bool(ok[j]))
                for j, (s, raw, tag, _leader) in enumerate(chunk)]


class ShredTile:
    """Shredder tile (ref: src/app/fdctl/run/tiles/fd_shred.c over
    src/disco/shred/fd_shredder.c + fd_shred_dest.c): accumulates a slot's
    entries, cuts merkle FEC sets (signing each root through the keyguard),
    fans the shreds out to every out link except the sign request link, and
    — when turbine is configured — sends each shred over UDP to its
    computed Turbine destination (leader: the tree root per shred;
    non-leader: retransmits received shreds to its children).

    In-links: entries from poh (sig = slot | done-bit) and, for the
    retransmit role, raw shreds from net links named in cfg `net_ins`.
    Out links: optional keyguard request link `shred_sign` plus shred
    fan-out links.
    cfg: shred_version, fec_data_cnt (default 32), turbine:
      {identity: hexpub, fanout, port, slots_per_epoch,
       stakes: {hexpub: [stake, ip, port]}}; batched-admission knobs
    sig_batch (default 32), sig_flush_age_us (default 2000),
    sig_backend ("device" | "host").

    INTEROP (round 5, closes VERDICT r4 #7): the turbine tree shuffle
    (disco/shred_dest.py) now rides the reference's MODE_SHIFT
    bounded-rand, fixture-verified against the compiled reference
    algorithm (tests/test_wsample_ref_conformance.py) — trees match
    reference/Agave nodes tree-for-tree, so mixed deployments compute
    identical retransmit children."""

    def init(self, ctx):
        from ..ballet import entry as entry_lib, shred as shred_lib
        from . import keyguard
        self._el, self._sl, self._kg = entry_lib, shred_lib, keyguard
        self.kgc = (keyguard.KeyguardClient(ctx, "shred_sign", "sign_shred")
                    if "shred_sign" in ctx.tile.out_links else None)
        self.version = ctx.cfg.get("shred_version", 1)
        self.data_cnt = ctx.cfg.get("fec_data_cnt", 32)
        self._fanout = [i for i, ln in enumerate(ctx.tile.out_links)
                        if ln != "shred_sign"]
        self.batch_max = ctx.cfg.get("batch_max", 16 << 10)
        self.net_ins = set(ctx.cfg.get("net_ins", ()))
        # fail at wiring time, not on the first FEC cut: a topology that
        # feeds this tile entries (any non-net in-link) but gives it no
        # shred_sign out-link could never sign a merkle root (ADVICE r3 —
        # previously died with AttributeError deep in _cut)
        entry_ins = [il.link for il in ctx.tile.in_links
                     if il.link not in self.net_ins]
        if entry_ins and self.kgc is None:
            raise ValueError(
                f"shred tile receives entries on {entry_ins} but has no "
                "'shred_sign' out link to the keyguard; wire one or make "
                "this a net-ins-only retransmit tile")
        self.slot = None
        self.entries = []
        self._size = 0
        self.fec_idx = 0
        self._init_turbine(ctx)

    def _init_turbine(self, ctx):
        self.turbine = None
        tb = ctx.cfg.get("turbine")
        if not tb:
            return
        from ..flamenco.leaders import leader_schedule
        from ..tango.tcache import TCache
        from ..waltz.udpsock import UdpSock
        from . import shred_dest as sd_mod
        self._sd = sd_mod
        self.identity = bytes.fromhex(tb["identity"])
        self.tree_fanout = tb.get("fanout", 200)
        spe = tb.get("slots_per_epoch", 432_000)
        self._stake_map = {}
        ci = sd_mod.StakeCI(self.identity, spe)
        for pkhex, (stake, ip, port) in tb["stakes"].items():
            pk = bytes.fromhex(pkhex)
            self._stake_map[pk] = stake
            if ip:
                ci.set_contact(pk, ip, port)
        self.stake_ci = ci
        sched = {}

        def leaders(slot):
            ep = slot // spe
            if ep not in sched:
                sched[ep] = leader_schedule(
                    ep, {pk: st for pk, st in self._stake_map.items()
                         if st > 0}, spe)
            return sched[ep][slot % spe]

        self._leaders = leaders
        self.tsock = UdpSock(bind_port=tb.get("port", 0))
        self._retx_seen = TCache(1 << 14)
        self.turbine = tb
        # batched leader-signature admission (round 13): merkle walks and
        # signature checks amortize across a burst instead of paying one
        # device dispatch per shred; warm BEFORE signaling RUN (the first
        # burst must not stall the mux loop through a cold compile)
        self._sigb = _ShredSigBatcher(
            batch=ctx.cfg.get("sig_batch", 32),
            backend=ctx.cfg.get("sig_backend", "device"),
            flush_age_us=ctx.cfg.get("sig_flush_age_us", 2000))
        self._sigb.warm()
        ctx.metrics.set("turbine_port", self.tsock.port)

    def _sdest(self, slot):
        ep = self.stake_ci.epoch_of(slot)
        if ep not in self.stake_ci.stakes:
            # static config stakes apply to every epoch until a stake
            # feed (replay epoch boundary) overrides them
            self.stake_ci.set_stakes(ep, self._stake_map)
        return self.stake_ci.sdest_for(slot, self._leaders)

    def _turbine_send(self, ctx, shreds, raws, first: bool):
        """Leader (first=True): root dest per shred.  Retransmitter:
        children per shred."""
        if self.turbine is None or not shreds:
            return
        from ..waltz.aio import Pkt
        sd = self._sdest(shreds[0].slot)
        if sd is None:
            return
        pkts = []
        if first:
            for s, raw in zip(shreds, raws):
                d = sd.idx_to_dest(sd.compute_first([s])[0])
                if d is not None and d.ip and d.pubkey != self.identity:
                    pkts.append(Pkt(raw, d.addr))
        else:
            for s, raw in zip(shreds, raws):
                for idx in sd.compute_children([s], self.tree_fanout)[0]:
                    d = sd.idx_to_dest(idx)
                    if d is not None and d.ip and d.pubkey != self.identity:
                        pkts.append(Pkt(raw, d.addr))
        if pkts:
            self.tsock.send_burst(pkts)
            ctx.metrics.add("turbine_tx_cnt", len(pkts))

    def _cut(self, ctx, slot_complete: bool):
        if not self.entries and not slot_complete:
            return
        batch = self._el.serialize_batch(self.entries)
        self.entries = []
        self._size = 0
        fs = self._sl.make_fec_set(
            batch, self.slot, parent_off=1 if self.slot else 0,
            version=self.version, fec_set_idx=self.fec_idx,
            sign_fn=lambda root: self.kgc.sign(self._kg.ROLE_LEADER, root),
            data_cnt=self.data_cnt, code_cnt=self.data_cnt,
            slot_complete=slot_complete)
        self.fec_idx += self.data_cnt
        ctx.metrics.add("fec_set_cnt")
        raws = fs.data_shreds + fs.code_shreds
        for raw in raws:
            for out in self._fanout:
                ctx.publish(raw, sig=self.slot, out=out)
                ctx.metrics.add("shred_tx_cnt")
        if self.turbine is not None:
            self._turbine_send(
                ctx, [self._sl.parse(r) for r in raws], raws, first=True)

    def _on_net_shred(self, ctx, payload):
        """Turbine ingress (non-leader): verify leader signature, dedup,
        store-forward + retransmit to my children exactly once per shred
        (fd_shred.c's retransmit path).  Admission is BATCHED (round 13):
        the shred queues into _ShredSigBatcher and forwards only when the
        burst verdict lands (size or age triggered) — one merkle-walk and
        one signature dispatch per burst instead of per shred."""
        try:
            s = self._sl.parse(payload)
        except self._sl.ShredParseError:
            ctx.metrics.add("shred_parse_fail_cnt")
            return
        if self.turbine is None:
            # no signature gate: publish the dcache view as-is — the out
            # ring copies it, so no per-shred bytes() materialization
            for out in self._fanout:
                ctx.publish(payload, sig=s.slot, out=out)
            ctx.metrics.add("shred_rx_cnt")
            return
        tag = (s.slot << 17) | (s.idx << 1) | (1 if s.is_data else 0)
        # query-only dedup BEFORE the signature check; the tag is
        # inserted only after the shred proves leader-signed, so a
        # forged copy cannot poison the cache and censor the real one
        # (same discipline as pipeline.py's pre-dedup)
        if self._retx_seen.query(tag):
            return                              # duplicate: drop entirely
        try:
            leader = self._leaders(s.slot)
        except Exception:
            leader = None
        # ONE copy per shred: payload is an in-ring dcache view the mux
        # will reuse, but the verdict is deferred — the same buffer then
        # serves every fan-out publish AND the retransmit send
        self._sigb.add(s, bytes(payload), tag, leader)
        if self._sigb.full:
            self._admit(ctx, self._sigb.flush())

    def _admit(self, ctx, verdicts):
        """Apply a batched admission verdict (FIFO): re-check dedup (a
        duplicate may have queued in the SAME burst window), insert,
        fan out, retransmit."""
        if not verdicts:
            return
        ctx.metrics.add("sig_batch_cnt")
        for s, raw, tag, ok in verdicts:
            if not ok:
                ctx.metrics.add("shred_sig_fail_cnt")
                continue
            if self._retx_seen.query(tag):
                continue                # dup admitted earlier in the burst
            self._retx_seen.insert(tag)
            for out in self._fanout:
                ctx.publish(raw, sig=s.slot, out=out)
            ctx.metrics.add("shred_rx_cnt")
            if self._leaders(s.slot) != self.identity:
                self._turbine_send(ctx, [s], [raw], first=False)

    def after_credit(self, ctx):
        if self.turbine is not None and self._sigb.due():
            ctx.metrics.add("sig_deadline_flush_cnt")
            self._admit(ctx, self._sigb.flush())

    def on_frag(self, ctx, iidx, meta, payload):
        if ctx.tile.in_links[iidx].link in self.net_ins:
            self._on_net_shred(ctx, payload)
            return
        sig = int(meta["sig"])
        slot = sig & ~PohTile.SLOT_DONE_BIT
        done = bool(sig & PohTile.SLOT_DONE_BIT)
        if self.slot is None:
            self.slot = slot
        if slot != self.slot:  # missed the done marker: close anyway
            self._cut(ctx, True)
            self.slot, self.fec_idx = slot, 0
        e, _ = self._el.Entry.deserialize(payload)
        self.entries.append(e)
        self._size += len(payload)
        if done:
            self._cut(ctx, True)
            self.slot, self.fec_idx = slot + 1, 0
        elif self._size >= self.batch_max:
            self._cut(ctx, False)  # mid-slot set: bound FEC batch size

    def fini(self, ctx):
        if self.entries and self.slot is not None:
            try:
                self._cut(ctx, True)
            except Exception:
                pass  # keyguard may already be down
        if self.turbine is not None:
            try:
                self._admit(ctx, self._sigb.flush())  # drain the tail
            except Exception:
                pass  # downstream rings may already be gone
            self.tsock.close()


class StoreTile:
    """Shred sink into the blockstore (ref: src/app/fdctl/run/tiles/
    fd_store.c): inserts incoming shreds, tracks FEC recovery and complete
    slots.  cfg: max_slots; the `complete_slot` metrics slot exports the
    highest fully-assembled slot (how tests observe block completion)."""

    def init(self, ctx):
        from ..ballet.shred import ShredParseError
        from ..flamenco.blockstore import Blockstore, SlotArchive
        self._perr = ShredParseError
        # optional disk archive (fd_blockstore's RocksDB role): completed
        # slots persist past the in-memory retention window
        arch_path = ctx.cfg.get("archive_path")
        self.store = Blockstore(
            ctx.cfg.get("max_slots", 1024),
            archive=SlotArchive(arch_path) if arch_path else None)
        self.complete = 0

    def on_frag(self, ctx, iidx, meta, payload):
        try:
            self.store.insert_shred(payload)
        except self._perr:
            ctx.metrics.add("parse_fail_cnt")
            return
        ctx.metrics.add("shred_store_cnt")
        slot = int(meta["sig"]) & ~PohTile.SLOT_DONE_BIT
        if slot > self.complete and self.store.slot_complete(slot):
            self.complete = slot
            ctx.metrics.set("complete_slot", slot)


class ShredRecoverIngest:
    """Batched RS-recover workload over the packed rotation core (round
    13): one FEC set per row in ballet.reedsol's recover_blob layout
    (surv | ref | have), the per-set reconstruction bit-matrices riding
    in a SIBLING array stamped alongside each rotating buffer.  The
    dispatch/harvest/backpressure machinery is models.verifier's
    PackedDispatchEngine — the same engine sigverify ingest rotates —
    via a shred-recover WorkloadDesc (composed, not subclassed: the
    engine import pulls jax, which must stay out of tiles.py module
    import for net-only processes)."""

    def __init__(self, k_max: int = 32, n_max: int = 64, sz: int = 1019,
                 batch: int = 8, nbuf: int = 2, depth: int | None = None):
        import functools

        import jax

        from ..ballet import reedsol as rs
        from ..models.verifier import PackedDispatchEngine, WorkloadDesc
        self._rs = rs
        self._jax = jax
        self.k_max, self.n_max, self.sz = k_max, n_max, sz
        self.batch = batch
        self._fn = jax.jit(functools.partial(
            rs.recover_blob, k_max=k_max, n_max=n_max, sz=sz))
        self._eng = PackedDispatchEngine(
            WorkloadDesc(
                name="shred-recover",
                rows=batch,
                row_bytes=rs.recover_blob_row_bytes(k_max, n_max, sz),
                true_rows=batch,
                dispatch=self._dispatch),
            nbuf=nbuf, depth=depth)
        # sibling bit-matrix per rotating buffer, paired by buffer id
        self._bitmats = [
            np.zeros((batch, 8 * n_max, 8 * k_max), np.int8)
            for _ in range(nbuf)]
        self._bidx = {id(b): i for i, b in enumerate(self._eng._bufs)}

    # engine passthroughs (observability + harvest surface)
    @property
    def dispatches(self):
        return self._eng.dispatches

    @property
    def inflight_depth(self):
        return self._eng.inflight_depth

    def poll(self):
        return self._eng.poll()

    def drain(self):
        return self._eng.drain()

    def _dispatch(self, buf):
        bm = self._bitmats[self._bidx[id(buf)]]
        return self._fn(self._jax.device_put(buf),
                        self._jax.device_put(bm))

    def warm(self) -> None:
        """Pre-RUN compile: run one zero-filled dispatch to completion
        (padding rows are self-consistent, so the verdict is all-ok)."""
        self._eng.submit_packed(lambda buf: None, 0)
        self._eng.drain()

    def submit_sets(self, sets: list):
        """Stamp up to `batch` recover_args triples — every set must be
        at this engine's fixed sz and within (k_max, n_max) — into one
        rotating row blob + sibling bit-matrix and dispatch.  Returns
        verdicts retired by the inflight window this call (each a
        (batch, n_max*sz + 1) u8 array; pair rows to sets FIFO)."""
        if len(sets) > self.batch:
            raise ValueError(f"{len(sets)} sets > engine batch {self.batch}")
        return self._eng.submit_packed(
            lambda buf: self._stamp(buf, sets), len(sets))

    def _stamp(self, buf, sets) -> None:
        rs = self._rs
        k_max, n_max, sz = self.k_max, self.n_max, self.sz
        ks, ns = k_max * sz, n_max * sz
        buf[:] = 0
        bm = self._bitmats[self._bidx[id(buf)]]
        bm[:] = 0
        for r, (shreds, k, set_sz) in enumerate(sets):
            n = len(shreds)
            if set_sz != sz or k > k_max or n > n_max:
                raise ValueError(
                    f"set geometry (k={k}, n={n}, sz={set_sz}) outside "
                    f"engine ({k_max}, {n_max}, {sz})")
            have = [i for i, s in enumerate(shreds) if s is not None]
            if len(have) < k:
                raise ValueError(
                    f"unrecoverable: only {len(have)} of {k} needed shreds")
            use = tuple(have[:k])
            row = buf[r]
            for c, i in enumerate(use):
                row[c * sz:(c + 1) * sz] = np.frombuffer(
                    shreds[i], np.uint8, count=sz)
            for i in have:
                row[ks + i * sz:ks + (i + 1) * sz] = np.frombuffer(
                    shreds[i], np.uint8, count=sz)
                row[ks + ns + i] = 1
            bm[r, :8 * n, :8 * k] = rs._recover_bitmat(k, n, use)

    def split_verdict(self, v: np.ndarray):
        """(full (batch, n_max, sz) u8, ok (batch,) bool) off one verdict
        row blob."""
        ns = self.n_max * self.sz
        full = v[:, :ns].reshape(len(v), self.n_max, self.sz)
        return full, v[:, ns].astype(bool)


class ShredRecoverTile:
    """FEC recovery tile (round 13; ref: fd_fec_resolver.c feeding
    fd_store): accumulates verified shreds into per-(slot, fec_set_idx)
    resolvers and, when a set becomes recoverable, stamps its survivors
    into a packed recover row dispatched through the SAME double-buffer
    engine shape as sigverify ingest — the reconstruction matmul runs
    once per BURST of sets, not once per set.  All-data completions
    (repair serves data only) publish immediately with no device work.

    In: shred links (the shred tile's verified fan-out).  Out: one
    reassembled entry-batch payload per recovered FEC set (sig = slot).
    cfg: fec_data_cnt (k_max, default 32), fec_code_cnt (default =
    fec_data_cnt), shred_sz (default derived from the geometry's proof
    depth), batch_sets (rows per dispatch, default 8), nbuf, depth,
    flush_age_us (partial-batch deadline, default 5000).
    metrics: shred_rx_cnt, shred_parse_fail_cnt, fec_complete_cnt,
    fec_recovered_cnt, fec_dispatch_cnt, fec_fail_cnt, recover_pending
    (gauge)."""

    def init(self, ctx):
        from ..ballet import shred as shred_lib
        self._sl = shred_lib
        self.k_max = ctx.cfg.get("fec_data_cnt", 32)
        self.c_max = ctx.cfg.get("fec_code_cnt", self.k_max)
        self.n_max = self.k_max + self.c_max
        sz = ctx.cfg.get("shred_sz")
        if sz is None:
            # protected span = 1139 - 20 * proof_len for this geometry
            sz = 1139 - 20 * max(1, (self.n_max - 1).bit_length())
        self.sz = sz
        self.batch_sets = ctx.cfg.get("batch_sets", 8)
        self.flush_age_us = ctx.cfg.get("flush_age_us", 5000)
        self.ingest = ShredRecoverIngest(
            k_max=self.k_max, n_max=self.n_max, sz=sz,
            batch=self.batch_sets, nbuf=ctx.cfg.get("nbuf", 2),
            depth=ctx.cfg.get("depth"))
        from collections import deque
        self.ingest.warm()       # compile BEFORE signaling RUN
        # bounded working state: open resolvers and the recovered-set
        # dedup both evict oldest-first (a slot's worth of sets is tiny
        # next to these bounds; unbounded growth would leak across epochs)
        self.max_open = ctx.cfg.get("max_open_sets", 1 << 12)
        self._sets = OrderedDict()        # (slot, fec_set_idx) -> resolver
        self._queue: list = []   # (key, resolver, recover_args triple)
        self._queued = OrderedDict()      # recovered-set dedup (as a set)
        self._q_t0 = None
        self._pending = deque()  # dispatch FIFO: [(key, resolver), ...]

    def _publish(self, ctx, key, regions):
        payload = self._sl.FecResolver.assemble_payload(regions)
        ctx.publish(payload, sig=key[0])
        ctx.metrics.add("fec_complete_cnt")

    def _dispatch(self, ctx):
        sets, self._queue = self._queue, []
        self._q_t0 = None
        if not sets:
            return
        args = [a for (_k, _r, a) in sets]
        self._pending.append([(k, r) for (k, r, _a) in sets])
        ctx.metrics.add("fec_dispatch_cnt")
        for v in self.ingest.submit_sets(args):
            self._retire(ctx, v)

    def _retire(self, ctx, verdict):
        full, ok = self.ingest.split_verdict(verdict)
        metas = self._pending.popleft()
        for r, (key, resolver) in enumerate(metas):
            if not bool(ok[r]):
                # a surviving shred inconsistent with the re-derived
                # encoding: the set is corrupt, drop it (ERR_CORRUPT)
                ctx.metrics.add("fec_fail_cnt")
                continue
            ctx.metrics.add("fec_recovered_cnt")
            self._publish(ctx, key, resolver.data_regions(full[r]))

    def on_frag(self, ctx, iidx, meta, payload):
        try:
            s = self._sl.parse(payload)
        except self._sl.ShredParseError:
            ctx.metrics.add("shred_parse_fail_cnt")
            return
        ctx.metrics.add("shred_rx_cnt")
        key = (s.slot, s.fec_set_idx)
        if key in self._queued:
            return                       # set already recovering/complete
        fr = self._sets.get(key)
        if fr is None:
            fr = self._sets[key] = self._sl.FecResolver()
            while len(self._sets) > self.max_open:
                self._sets.popitem(last=False)
        if not fr.add(s) or not fr.ready():
            return
        self._queued[key] = None
        while len(self._queued) > self.max_open:
            self._queued.popitem(last=False)
        self._sets.pop(key, None)
        args = fr.recover_args()
        if args is None:
            # all-data completion: regions read straight off the shreds
            self._publish(ctx, key, fr.data_regions())
            return
        shreds, k, set_sz = args
        if (set_sz != self.sz or k > self.k_max
                or len(shreds) > self.n_max):
            # geometry outside the compiled engine: host per-set fallback
            # (counted, never silent — cfg should match the deployment)
            ctx.metrics.add("fec_host_fallback_cnt")
            try:
                full = self._sl.reedsol.recover(shreds, k, set_sz,
                                                device=False)
            except ValueError:
                ctx.metrics.add("fec_fail_cnt")
                return
            self._publish(ctx, key, fr.data_regions(full))
            return
        self._queue.append((key, fr, args))
        if self._q_t0 is None:
            self._q_t0 = time.monotonic_ns()
        if len(self._queue) >= self.batch_sets:
            self._dispatch(ctx)

    def after_credit(self, ctx):
        for v in self.ingest.poll():     # non-blocking verdict harvest
            self._retire(ctx, v)
        if (self._q_t0 is not None
                and time.monotonic_ns() - self._q_t0
                >= self.flush_age_us * 1000):
            self._dispatch(ctx)
        ctx.metrics.set("recover_pending", len(self._pending))

    def fini(self, ctx):
        try:
            self._dispatch(ctx)
            for v in self.ingest.drain():
                self._retire(ctx, v)
        except Exception:
            pass  # downstream rings may already be gone


def _ed25519_verify_one(sig: bytes, msg: bytes, pub: bytes) -> bool:
    from ..ops.ed25519 import verify_one
    return verify_one(sig, msg, pub)


def _ed25519_verify_host(sig: bytes, msg: bytes, pub: bytes) -> bool:
    """Host python-int verify for control-plane rates: same acceptance
    rules as verify_one, no device round trip (load-bearing on tunneled
    devices where a sync fetch costs ~100 ms)."""
    from ..ops.ed25519 import verify_one_host
    return verify_one_host(sig, msg, pub)


class ReplayTile:
    """Follower-side fork-aware replay + consensus tile (ref:
    src/disco/tvu/fd_tvu.c over src/choreo — replay competing forks into
    fork banks, count replayed votes into ghost, vote per TowerBFT, root
    when the tower roots).  The state machine is flamenco.replay.ForkReplay;
    this tile feeds it shreds and exports its decisions.

    Votes are signed through the keyguard when the `vote_sign`/`sign_vote`
    link pair is wired; signed vote txns are published to every other out
    link (toward gossip / the local TPU ingest).

    cfg: genesis_path, poh_start (hex), vote_account (hex, enables
    voting), identity_pub (hex; with keyguard) | key_path.
    metrics: replay_slot (highest replayed), ghost_head, root_slot,
    dead_slot_cnt, vote_cnt, txn_replay_cnt."""

    def init(self, ctx):
        from ..ballet.shred import ShredParseError
        from ..choreo.voter import Voter
        from ..flamenco.blockstore import Blockstore
        from ..flamenco.genesis import Genesis
        from ..flamenco.replay import ForkReplay
        from ..flamenco.runtime import Runtime
        from . import keyguard
        self._perr = ShredParseError
        self._kg = keyguard
        self.store = Blockstore(ctx.cfg.get("max_slots", 1024))
        self.rt = Runtime(Genesis.read(ctx.cfg["genesis_path"]))
        poh = ctx.cfg.get("poh_start")
        poh = bytes.fromhex(poh) if poh else bytes(32)
        if "vote_sign" in ctx.tile.out_links:
            self.kgc = keyguard.KeyguardClient(ctx, "vote_sign", "sign_vote")
            identity = bytes.fromhex(ctx.cfg["identity_pub"])
            self._local_sign = None
        else:
            self.kgc = None
            if ctx.cfg.get("key_path"):
                from ..ops import ed25519 as ed
                seed, identity = keyguard.keypair_read(ctx.cfg["key_path"])
                self._local_sign = lambda m: ed.sign(seed, m)
            else:
                identity = bytes(32)
                self._local_sign = None
        vote_acct = ctx.cfg.get("vote_account")
        self.voter = Voter(
            vote_account=bytes.fromhex(vote_acct) if vote_acct else bytes(32),
            node_pubkey=identity)
        self.fr = ForkReplay(self.rt, self.store, self.voter, poh)
        self._vote_outs = [i for i, ln in enumerate(ctx.tile.out_links)
                          if ln != "vote_sign"]

    def on_frag(self, ctx, iidx, meta, payload):
        try:
            completed = self.store.insert_shred(payload)
        except self._perr:
            return
        if completed:
            # only a completed FEC set can complete a slot: keeps the
            # O(n)-over-store drain scan off the per-shred hot path
            self._drain(ctx)

    def _sign_and_publish_vote(self, ctx, msg: bytes):
        from ..ballet import txn as txn_lib
        if self.kgc is not None:
            sig = self.kgc.sign(self._kg.ROLE_VOTER, msg)
        elif self._local_sign is not None:
            sig = self._local_sign(msg)
        else:
            return
        payload = txn_lib.assemble([sig], msg)
        for out in self._vote_outs:
            ctx.publish(payload, sig=int.from_bytes(sig[:8], "little"),
                        out=out)

    def _drain(self, ctx):
        events = self.fr.drain()
        if not events:
            return
        for res, decision in events:
            if not res.ok:
                ctx.metrics.add("dead_slot_cnt")
                continue
            ctx.metrics.add("txn_replay_cnt", res.txn_cnt)
            if decision is not None and decision.slot is not None:
                ctx.metrics.add("vote_cnt")
                if decision.txn_message is not None:
                    self._sign_and_publish_vote(ctx, decision.txn_message)
        ctx.metrics.set("replay_slot",
                        max(self.fr.replayed, default=self.rt.root_slot))
        ctx.metrics.set("ghost_head", self.fr.head)
        ctx.metrics.set("root_slot", self.rt.root_slot)


class GossipTile:
    """Cluster gossip tile (ref: src/app/fdctl/run/tiles/fd_gossip.c over
    src/flamenco/gossip): runs a GossipNode over its own UDP socket,
    bootstrapping from cfg `entrypoints` ([["ip", port], ...]).

    Signing is keyguard-routed when the `gossip_sign`/`sign_gossip` link
    pair is wired (cfg `identity_pub` hex; the tile then holds NO private
    key material — the reference's key-isolation contract,
    src/disco/keyguard/fd_keyguard.h:4-23).  Fallback for link-less
    topologies: in-tile signing from cfg key_path.

    cfg: identity_pub | key_path, gossip_port (0 = ephemeral, exported in
    `bound_port`), tpu_port, repair_port, entrypoints."""

    def init(self, ctx):
        from ..flamenco import gossip as gossip_mod
        from ..waltz.udpsock import UdpSock
        from . import keyguard
        self._g = gossip_mod
        if "gossip_sign" in ctx.tile.out_links:
            kgc = keyguard.KeyguardClient(ctx, "gossip_sign", "sign_gossip")
            sign_fn = lambda m: kgc.sign(keyguard.ROLE_GOSSIP, m)  # noqa: E731
            pub = bytes.fromhex(ctx.cfg["identity_pub"])
        else:
            from ..ops import ed25519 as ed
            seed, pub = keyguard.keypair_read(ctx.cfg["key_path"])
            sign_fn = lambda m: ed.sign(seed, m)  # noqa: E731
        self.sock = UdpSock(bind_port=ctx.cfg.get("gossip_port", 0))
        ctx.metrics.set("bound_port", self.sock.port)
        contact = gossip_mod.contact_info_body(
            ctx.cfg.get("advertise_ip", "127.0.0.1"), self.sock.port,
            ctx.cfg.get("tpu_port", 0), ctx.cfg.get("repair_port", 0))
        _ed25519_verify_one(bytes(64), b"warm", bytes(32))  # pre-RUN warmup
        self.node = gossip_mod.GossipNode(
            pub, sign_fn, _ed25519_verify_one, contact)
        self.entrypoints = [tuple(e) for e in ctx.cfg.get("entrypoints", [])]

    def house(self, ctx):
        from ..waltz.aio import Pkt
        outs = self.node.tick()
        # bootstrap: push our contact at the entrypoints until peers appear
        if not outs and self.entrypoints:
            push = self._g.encode_push(self.node.crds.values())
            outs = [(push, ep) for ep in self.entrypoints]
        if outs:
            self.sock.send_burst([Pkt(p, a) for p, a in outs])
        ctx.metrics.set("peer_cnt", len(self.node.crds.peers()))

    def after_credit(self, ctx):
        from ..waltz.aio import Pkt
        for pkt in self.sock.recv_burst():
            ctx.metrics.add("rx_pkt_cnt")
            replies = self.node.handle(pkt.payload, pkt.addr)
            if replies:
                self.sock.send_burst([Pkt(p, a) for p, a in replies])

    def fini(self, ctx):
        self.sock.close()


class RepairTile:
    """Shred repair tile (ref: src/app/fdctl/run/tiles/fd_repair.c): serves
    window-index requests from the local blockstore view AND runs the
    request side (RepairPlanner: gap detection, retry pacing,
    stake-weighted peer rotation) against configured peers.

    Request signing is keyguard-routed when the `repair_sign`/`sign_repair`
    link pair is wired (cfg `identity_pub` hex; no private key in-tile);
    fallback: in-tile signing from cfg key_path.  Repaired shreds are
    published to every out link except the sign request link (the store
    fan-in).

    cfg: identity_pub | key_path, repair_port (0 = ephemeral ->
    `bound_port`), peers ([[pubhex, ip, port, stake], ...]),
    plan_interval_s (default 0.05), leader_stakes ({pubhex: stake}) +
    slots_per_epoch — when given, repaired shreds must carry the slot
    leader's signature over their merkle root before they are stored or
    republished (repair peers are untrusted; without the schedule the
    tile accepts structurally-valid shreds only, flagged in metrics)."""

    def init(self, ctx):
        from ..ballet import shred as shred_lib
        from ..ballet.shred import ShredParseError
        from ..flamenco import repair as repair_mod
        from ..flamenco.blockstore import Blockstore
        from ..waltz.udpsock import UdpSock
        from . import keyguard
        self._sl = shred_lib
        self._perr = ShredParseError
        self._rm = repair_mod
        if "repair_sign" in ctx.tile.out_links:
            kgc = keyguard.KeyguardClient(ctx, "repair_sign", "sign_repair")
            sign_fn = lambda m: kgc.sign(keyguard.ROLE_REPAIR, m)  # noqa: E731
            pub = bytes.fromhex(ctx.cfg["identity_pub"])
        else:
            from ..ops import ed25519 as ed
            seed, pub = keyguard.keypair_read(ctx.cfg["key_path"])
            sign_fn = lambda m: ed.sign(seed, m)  # noqa: E731
        self._leaders = None
        if ctx.cfg.get("leader_stakes"):
            from ..flamenco.leaders import leader_schedule
            stakes = {bytes.fromhex(k): v
                      for k, v in ctx.cfg["leader_stakes"].items()}
            spe = ctx.cfg.get("slots_per_epoch", 432_000)
            sched = {}

            def leaders(slot):
                ep = slot // spe
                if ep not in sched:
                    sched[ep] = leader_schedule(ep, stakes, spe)
                return sched[ep][slot % spe]

            self._leaders = leaders
        # leader-signature gate on the blockstore's FEC resolvers too
        # (ADVICE r4): _response_shred_ok already screens repair traffic,
        # but the store-level root_check means even a shred slipping in
        # through another path cannot pin a bogus first-member root
        # repair-path crypto runs on the HOST verifier (python ints,
        # ~ms/item): these are control-plane rates, and on a tunneled
        # device every ops.verify_one call pays a ~100 ms synchronous
        # round trip — per request/shred (code-review r5)
        root_check = None
        if self._leaders is not None:
            def root_check(slot, root, sig):
                try:
                    leader = self._leaders(slot)
                except Exception:
                    return False
                return _ed25519_verify_host(sig, root, leader)
        self.store = Blockstore(ctx.cfg.get("max_slots", 1024),
                                root_check=root_check)
        self.sock = UdpSock(bind_port=ctx.cfg.get("repair_port", 0))
        ctx.metrics.set("bound_port", self.sock.port)
        self.server = repair_mod.RepairServer(
            _ed25519_verify_host,
            self.store.shred_raw, self.store.highest_shred,
            parent_of=self.store.parent_slot)
        self.client = repair_mod.RepairClient(sign_fn, pub)
        self.planner = repair_mod.RepairPlanner(self.client)
        self.peers = [(bytes.fromhex(p), (ip, port), stake)
                      for p, ip, port, stake in ctx.cfg.get("peers", ())]
        self._fanout = [i for i, ln in enumerate(ctx.tile.out_links)
                        if ln != "repair_sign"]
        self.plan_interval_s = ctx.cfg.get("plan_interval_s", 0.05)
        self._last_plan = 0.0

    def on_frag(self, ctx, iidx, meta, payload):
        """Shreds from the local store fan-in: track them so the planner
        stops re-requesting.  NOT pre_verified — upstream validation is
        config-dependent (a net-ins-only shred tile without turbine
        forwards unchecked), so the store's door gate runs here; it costs
        one HOST ed25519 verify per shred (~ms), not a device RTT."""
        try:
            sh = self._sl.parse(payload)
            self.store.insert_shred(bytes(payload), parsed=sh)
        except self._perr:
            return
        self.planner.on_shred(sh.slot, sh.idx)

    def _response_shred_ok(self, sh) -> bool:
        """Repair peers are untrusted: with a leader schedule configured,
        a response shred must carry the slot leader's signature over its
        merkle root (same check the turbine ingress runs)."""
        if self._leaders is None:
            return True
        root = sh.merkle_root()
        if root is None:
            return False
        try:
            leader = self._leaders(sh.slot)
        except Exception:
            return False
        return _ed25519_verify_host(sh.signature, root, leader)

    def _repair_wants(self) -> list[int]:
        """Slots worth repairing: known but incomplete (replay drives this
        list in the full validator; blockstore gaps are the local proxy)."""
        return [s for s in sorted(self.store.slots)
                if not self.store.slot_complete(s)][:64]

    def house(self, ctx):
        if not self.peers:
            return
        now = time.monotonic()
        if now - self._last_plan < self.plan_interval_s:
            return
        self._last_plan = now
        from ..waltz.aio import Pkt
        reqs = self.planner.plan(self.store, self._repair_wants(),
                                 self.peers)
        if reqs:
            self.sock.send_burst(
                [Pkt(req.serialize(), peer[1]) for req, peer in reqs])
            ctx.metrics.add("req_tx_cnt", len(reqs))

    def after_credit(self, ctx):
        from ..waltz.aio import Pkt
        for pkt in self.sock.recv_burst():
            # explicit wire discriminator byte (ADVICE r3: length-based
            # discrimination misparsed 113-byte responses as requests)
            if pkt.payload[:1] == bytes([self._rm.MSG_REQUEST]):
                ctx.metrics.add("req_cnt")
                resp = self.server.handle(pkt.payload)
                if resp is not None:
                    self.sock.send_burst([Pkt(resp, pkt.addr)])
                    ctx.metrics.add("served_cnt")
                continue
            raw = self.client.handle_response(bytes(pkt.payload))
            if raw is None:
                continue
            try:
                sh = self._sl.parse(raw)
            except self._perr:
                continue
            if not self._response_shred_ok(sh):
                ctx.metrics.add("resp_sig_fail_cnt")
                continue
            ctx.metrics.add("repaired_cnt")
            self.planner.on_shred(sh.slot, sh.idx)
            try:
                # pre_verified: _response_shred_ok above IS the leader-
                # signature gate (it also guards the republish below) —
                # re-running it inside the store would double the
                # repair path's crypto cost (code-review r5)
                self.store.insert_shred(raw, parsed=sh, pre_verified=True)
            except self._perr:
                continue
            for out in self._fanout:
                ctx.publish(raw, sig=sh.slot, out=out)

    def fini(self, ctx):
        self.sock.close()


class SinkTile:
    """Counts and drops (the fd_blackhole tile).

    cfg capture_path (optional): append every frag to that file as
    `u64 sig | u32 len | payload` — the offline re-verification surface
    the leader conformance/chaos harnesses read entry and microblock
    streams back from.  Capture forces the per-frag path (burst delivery
    is disabled) so file order is exactly publish order."""

    def init(self, ctx):
        self._cap = None
        path = ctx.cfg.get("capture_path") or ""
        if path:
            self._cap = open(path, "ab", buffering=0)
            self.on_burst = None       # per-frag so sigs ride along

    def on_frag(self, ctx, iidx, meta, payload):
        ctx.metrics.add("frag_cnt")
        if self._cap is not None:
            b = bytes(payload)
            self._cap.write(int(meta["sig"]).to_bytes(8, "little")
                            + len(b).to_bytes(4, "little") + b)

    def on_burst(self, ctx, iidx, metas, buf, offs, kept):
        ctx.metrics.add("frag_cnt", kept)

    def fini(self, ctx):
        if self._cap is not None:
            self._cap.close()


class MetricTile:
    """Prometheus exporter over HTTP (ref: run/tiles/fd_metric.c:135-263),
    snapshotting every tile's shared-memory metrics block."""

    def init(self, ctx):
        # same path-aware handler (/metrics + /healthz) the supervisor's
        # TopoRun(metrics_port=...) endpoint serves — one implementation
        from .run import MetricsHttpServer
        self.server = MetricsHttpServer(
            ctx.topo, port=ctx.cfg.get("port", 7999))

    def fini(self, ctx):
        self.server.close()


class NetmuxTile:
    """Frag fan-in multiplexer: N input links -> one output link, payload
    and app sig forwarded unchanged (ref:
    src/app/fdctl/run/tiles/fd_netmux.c — there it muxes net/quic/shred
    traffic onto one wire so consumers join a single mcache; same
    topology contract here)."""

    # traffic accounting rides the mux-layer counters (in_frag_cnt /
    # out_frag_cnt — disco/mux.py), matching the reference where netmux
    # has no tile-specific metrics section

    def on_frag(self, ctx, iidx, meta, payload):
        ctx.publish(payload, sig=int(meta["sig"]))

    def on_burst(self, ctx, iidx, metas, buf, offs, kept):
        ctx.publish_burst(
            buf, offs[:kept],
            (offs[1:kept + 1] - offs[:kept]).astype(np.int32),
            metas["sig"].astype(np.uint64))


class BlackholeTile:
    """Filters every frag BEFORE the payload copy (ref:
    src/app/fdctl/run/tiles/fd_blackhole.c before_frag sets opt_filter):
    the consumer-side packet sink used to terminate links whose traffic a
    topology variant doesn't consume.  Unlike SinkTile it never touches
    the dcache — pure metadata-rate drop."""

    def before_frag(self, ctx, iidx, seq, sig) -> bool:
        return True  # filter: payload never read; the mux counts the
        # drop in the standard in_filt_cnt slot


TILES: dict[str, type] = {
    "net": NetTile,
    "netmux": NetmuxTile,
    "blackhole": BlackholeTile,
    "quic": QuicTile,
    "quic_server": QuicServerTile,
    "source": SourceTile,
    "verify": VerifyTile,
    "dedup": DedupTile,
    "pack": PackTile,
    "bank": BankTile,
    "sign": SignTile,
    "poh": PohTile,
    "leader_pack": LeaderPackTile,
    "leader_merge": LeaderMergeTile,
    "poh_dev": PohDevTile,
    "shred": ShredTile,
    "shred_recover": ShredRecoverTile,
    "store": StoreTile,
    "gossip": GossipTile,
    "repair": RepairTile,
    "replay": ReplayTile,
    "sink": SinkTile,
    "metric": MetricTile,
}
