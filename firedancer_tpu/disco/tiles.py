"""Production tile implementations + registry (ref: the fd_topo_run_tile_t
vtables in src/app/fdctl/run/tiles/ and the TILES[] registry in
src/app/fdctl/main.c:33-48).

A tile is a class with any subset of the mux callbacks (disco/mux.py).  The
registry maps kind -> class; fd_topo_run looks tiles up by TileSpec.kind.

The data plane mirrors the reference's frankendancer flow (SURVEY.md §1):

    source/net -> verify -> dedup -> pack -> bank -> sink

with the TPU twist in the verify tile: txn signatures from many frags are
coalesced into one fixed-shape device batch, flushed on size or age
(wiredancer's async insertion point, SURVEY.md §3.2), instead of the
reference's synchronous per-frag batch-of-<=16 verify.
"""

import time

import numpy as np

from ..ballet import txn as txn_lib
from ..tango.tcache import TCache
from .pipeline import VerifyPipeline


class SourceTile:
    """Synthetic signed-txn generator (the fddev benchg analogue,
    src/app/fddev/tiles/fd_benchg.c): publishes `count` distinct valid
    txns then idles (count=0 -> unbounded).

    Two modes: standalone (default) signs with fresh keys against a random
    blockhash — enough for the verify path; executable=True generates REAL
    system transfers from cfg `seeds` (hex, funded in genesis) against
    cfg `blockhash`, so a downstream bank tile can execute them."""

    def init(self, ctx):
        from ..ops import ed25519 as ed
        cfg = ctx.cfg
        self.count = cfg.get("count", 0)
        self.executable = cfg.get("executable", False)
        self.pool = []
        rng = np.random.default_rng(cfg.get("seed", 42))
        if self.executable:
            from ..flamenco.system_program import ix_transfer
            from ..flamenco.types import SYSTEM_PROGRAM_ID
            self._ix_transfer = ix_transfer
            self._system_id = SYSTEM_PROGRAM_ID
            seeds = [bytes.fromhex(s) for s in cfg["seeds"]]
            self.blockhash = bytes.fromhex(cfg["blockhash"])
        else:
            seeds = [rng.bytes(32) for _ in range(cfg.get("keys", 4))]
            self.blockhash = rng.bytes(32)
        for seed in seeds:
            pub, _, _ = ed.keypair_from_seed(seed)
            self.pool.append((seed, pub))
        self.program = rng.bytes(32)
        self.sent = 0
        self._ed = ed
        self._rng = rng

    def _make_txn(self, i: int) -> bytes:
        seed, pub = self.pool[i % len(self.pool)]
        if self.executable:
            # nonzero prefix: dest must never collide with the all-zeros
            # system program id (duplicate account addresses in one txn)
            dest = b"\xd5" + bytes(15) + i.to_bytes(16, "little")
            msg = txn_lib.build_unsigned(
                [pub], self.blockhash,
                [(2, bytes([0, 1]), self._ix_transfer(1000 + i))],
                extra_accounts=[dest, self._system_id],
                readonly_unsigned_cnt=1)
        else:
            data = i.to_bytes(8, "little")  # distinct payload per i
            msg = txn_lib.build_unsigned(
                [pub], self.blockhash,
                [(1, bytes([0]), data)], extra_accounts=[self.program])
        sig = self._ed.sign(seed, msg)
        return txn_lib.assemble([sig], msg)

    def after_credit(self, ctx):
        if self.count and self.sent >= self.count:
            return
        payload = self._make_txn(self.sent)
        sig64 = int.from_bytes(payload[1:9], "little")
        ctx.publish(payload, sig=sig64)
        self.sent += 1
        ctx.metrics.add("txn_gen_cnt")


class VerifyTile:
    """The verify tile (ref: src/app/fdctl/run/tiles/fd_verify.c).

    Round-robin data parallel: instance r of n keeps frags with
    seq % n == r (fd_verify.c:36-47).  Parse -> tcache pre-dedup ->
    fixed-shape device batch verify -> publish passing txns downstream with
    sig = low 64 bits of the first signature (the dedup tile's key).
    """

    def init(self, ctx):
        from ..ops import ed25519 as ed
        from ..utils import xla_cache
        import jax
        import jax.numpy as jnp
        xla_cache.enable()
        cfg = ctx.cfg
        self.rr_cnt = cfg.get("round_robin_cnt", 1)
        self.rr_idx = cfg.get("round_robin_idx", 0)
        batch = cfg.get("batch", 64)
        maxlen = cfg.get("msg_maxlen", 256)
        self.flush_age_ns = cfg.get("flush_age_ns", 2_000_000)
        fn = jax.jit(ed.verify_batch)
        # warmup compile before signaling RUN: the verify graph can take
        # minutes to build cold, and the run loop must never stall that long
        # (the supervisor would flag a stale heartbeat)
        fn(jnp.zeros((batch, maxlen), jnp.uint8),
           jnp.zeros((batch,), jnp.int32),
           jnp.zeros((batch, 64), jnp.uint8),
           jnp.zeros((batch, 32), jnp.uint8)).block_until_ready()
        self.pipe = VerifyPipeline(
            fn, batch, maxlen,
            tcache_depth=cfg.get("tcache_depth", 1 << 16))
        self._last_submit_ns = 0

    def before_frag(self, ctx, iidx, seq, sig) -> bool:
        return (seq % self.rr_cnt) != self.rr_idx

    def _forward(self, ctx, passed):
        for payload, parsed in passed:
            tag = int.from_bytes(parsed.signatures(payload)[0][:8], "little")
            ctx.publish(payload, sig=tag)

    def on_frag(self, ctx, iidx, meta, payload):
        passed = self.pipe.submit(payload)
        self._last_submit_ns = time.monotonic_ns()
        self._forward(ctx, passed)
        self._sync_metrics(ctx)

    def after_credit(self, ctx):
        # age-based flush: bound batch latency when inflow stalls
        # (BASELINE p99 < 2ms requires closing partial batches)
        if (self.pipe._pending
                and time.monotonic_ns() - self._last_submit_ns
                > self.flush_age_ns):
            self._forward(ctx, self.pipe.flush())
            self._sync_metrics(ctx)

    def _sync_metrics(self, ctx):
        s = self.pipe.metrics
        ctx.metrics.set("txn_in_cnt", s.txns_in)
        ctx.metrics.set("parse_fail_cnt", s.parse_fail)
        ctx.metrics.set("dedup_drop_cnt", s.dedup_drop)
        ctx.metrics.set("too_long_cnt", s.too_long_drop)
        ctx.metrics.set("verify_fail_cnt", s.verify_fail)
        ctx.metrics.set("verify_pass_cnt", s.verify_pass)
        ctx.metrics.set("batch_cnt", s.batches)

    def fini(self, ctx):
        try:
            self._forward(ctx, self.pipe.flush())
            self._sync_metrics(ctx)
        except Exception:
            pass


class NetTile:
    """Packet ingress (ref: src/app/fdctl/run/tiles/fd_net.c): drains UDP
    socket bursts and steers by destination port to out links.

    cfg ports: {port: out_link_name}; port 0 = ephemeral, with the kernel's
    chosen port for the FIRST socket exported in the `bound_port` metrics
    slot once the tile is RUN (how tests discover where to send)."""

    def init(self, ctx):
        from ..waltz.udpsock import UdpSock
        self.socks = []
        for port, link in sorted(ctx.cfg["ports"].items()):
            s = UdpSock(bind_port=port)
            self.socks.append((s, ctx.out_index(link)))
        ctx.metrics.set("bound_port", self.socks[0][0].port)

    def after_credit(self, ctx):
        for s, out in self.socks:
            for pkt in s.recv_burst():
                ctx.publish(pkt.payload, sig=0, out=out)
                ctx.metrics.add("rx_pkt_cnt")

    def fini(self, ctx):
        for s, _ in self.socks:
            s.close()


class QuicTile:
    """TPU ingest tile (ref: src/app/fdctl/run/tiles/fd_quic.c).  Consumes
    net frags and publishes whole txns into the verify link via TpuReasm.
    UDP legacy mode today (one datagram = one txn, fd_quic.c:155-165); the
    QUIC stream path plugs into the same reasm."""

    def init(self, ctx):
        from .tpu_reasm import TpuReasm

        def _pub(txn_bytes: bytes):
            sig64 = (int.from_bytes(txn_bytes[1:9], "little")
                     if len(txn_bytes) >= 9 else 0)
            ctx.publish(txn_bytes, sig=sig64)
            ctx.metrics.add("reasm_pub_cnt")

        self.reasm = TpuReasm(ctx.cfg.get("reasm_depth", 64), _pub)

    def on_frag(self, ctx, iidx, meta, payload):
        if not self.reasm.publish_datagram(payload):
            ctx.metrics.add("reasm_drop_cnt")


class DedupTile:
    """Cross-verify-tile dedup on the signature tag
    (ref: src/app/fdctl/run/tiles/fd_dedup.c, tango tcache)."""

    def init(self, ctx):
        self.tcache = TCache(ctx.cfg.get("tcache_depth", 1 << 20))

    def on_frag(self, ctx, iidx, meta, payload):
        tag = int(meta["sig"])
        if self.tcache.insert(tag):
            ctx.metrics.add("dup_drop_cnt")
            return
        ctx.metrics.add("uniq_cnt")
        ctx.publish(payload, sig=tag)


class PackTile:
    """Block-packing scheduler tile (ref: src/app/fdctl/run/tiles/fd_pack.c
    over src/ballet/pack/fd_pack.c): inserts verified txns into the
    fee-priority scheduler and emits conflict-free microblocks round-robin
    to bank out-links (out link i = bank lane i)."""

    def init(self, ctx):
        from ..ballet.pack import Pack
        nbank = max(1, len(ctx.tile.out_links))
        self.pack = Pack(bank_tile_cnt=nbank,
                         max_txn_per_microblock=ctx.cfg.get("max_txn", 31))

    def on_frag(self, ctx, iidx, meta, payload):
        try:
            parsed = txn_lib.parse(payload)
        except txn_lib.TxnParseError:
            return
        if self.pack.insert(payload, parsed):
            ctx.metrics.add("txn_insert_cnt")
        self._drain(ctx)

    def after_credit(self, ctx):
        self._drain(ctx)

    def _drain(self, ctx):
        progressed = True
        while progressed and self.pack.pending:
            progressed = False
            for bank in range(self.pack.bank_cnt):
                mb = self.pack.schedule(bank)
                if mb is None:
                    continue
                for payload in mb.payloads:
                    ctx.publish(payload, sig=mb.bank, out=bank)
                ctx.metrics.add("microblock_cnt")
                # bank tiles are synchronous sinks for now: release at once
                self.pack.done(bank)
                progressed = True


class BankTile:
    """Executing bank tile (ref: src/app/fdctl/run/tiles/fd_bank.c — there a
    thin FFI shim into the Agave runtime; here the real thing: the flamenco
    Runtime executes microblock txns against a funk fork, freezes the slot
    after `slot_txn_max` txns or `slot_ns`, and rolls to the next slot).

    cfg: genesis_path (required), slot_txn_max, slot_ns."""

    def init(self, ctx):
        import hashlib
        from ..flamenco.genesis import Genesis
        from ..flamenco.runtime import Runtime
        self.rt = Runtime(Genesis.read(ctx.cfg["genesis_path"]))
        if ctx.cfg.get("pin_genesis_blockhash", True):
            # sources sign against the genesis hash and run in other
            # processes with no blockhash feedback link yet; without the
            # pin, every txn fails recency after max_age (300) slot rolls
            self.rt.blockhash_queue.pin(self.rt.root_hash)
        self.slot_txn_max = ctx.cfg.get("slot_txn_max", 1024)
        self.slot_ns = ctx.cfg.get("slot_ns", 400_000_000)
        self._hashlib = hashlib
        self._slot = 1
        self._bank = self.rt.new_bank(1)
        self._slot_t0 = time.monotonic_ns()
        self._poh = self.rt.root_hash

    def on_frag(self, ctx, iidx, meta, payload):
        res = self._bank.execute_txn(payload)
        if res.ok:
            ctx.metrics.add("txn_exec_cnt")
        else:
            ctx.metrics.add("txn_fail_cnt")
        if self._bank.txn_cnt >= self.slot_txn_max:
            self._roll(ctx)

    def house(self, ctx):
        if (self._bank.txn_cnt
                and time.monotonic_ns() - self._slot_t0 > self.slot_ns):
            self._roll(ctx)

    def _roll(self, ctx):
        """Freeze + root the slot, open the next (single-fork leader mode;
        fork choice arrives with the choreo layer)."""
        self._poh = self._hashlib.sha256(self._poh).digest()
        self._bank.freeze(self._poh)
        self.rt.publish(self._slot)
        self._slot += 1
        self._bank = self.rt.new_bank(self._slot)
        self._slot_t0 = time.monotonic_ns()
        ctx.metrics.add("slot_cnt")

    def fini(self, ctx):
        if self._bank.txn_cnt:
            self._roll(ctx)


class SinkTile:
    """Counts and drops (the fd_blackhole tile)."""

    def on_frag(self, ctx, iidx, meta, payload):
        ctx.metrics.add("frag_cnt")


class MetricTile:
    """Prometheus exporter over HTTP (ref: run/tiles/fd_metric.c:135-263),
    snapshotting every tile's shared-memory metrics block."""

    def init(self, ctx):
        import http.server
        import threading
        from . import metrics as metrics_mod

        topo = ctx.topo
        blocks = topo.metrics

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = metrics_mod.prometheus_render(blocks).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        port = ctx.cfg.get("port", 7999)
        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port), H)
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()

    def fini(self, ctx):
        self.httpd.shutdown()


TILES: dict[str, type] = {
    "net": NetTile,
    "quic": QuicTile,
    "source": SourceTile,
    "verify": VerifyTile,
    "dedup": DedupTile,
    "pack": PackTile,
    "bank": BankTile,
    "sink": SinkTile,
    "metric": MetricTile,
}
