"""Multi-chip collectives for the verify pipeline (ref: SURVEY.md §5
"distributed communication backend" — the reference's cross-host story is
the Solana protocol itself; ours adds the ICI tier the reference never
had: XLA collectives over a chip mesh).

Two collective patterns:

  * ring_point_fold — an all-reduce whose element is a curve POINT and
    whose op is group addition: partials rotate around the ICI ring via
    ppermute while every chip accumulates, n-1 steps (the ring-collective
    shape ring-attention uses, applied to EC aggregation).
  * shard_rlc_verify — the v5e-8 "data-parallel MSM" (BASELINE.json
    config #5): each chip runs the random-linear-combination batch-verify
    MSM over its shard of signatures; per-chip partial points ring-fold to
    the total, the scalar combination psums (limb-wise, then one mod-L
    reduce), and every chip checks the single group equation.

Both run on any jax mesh — the 8-virtual-CPU-device test mesh compiles
the identical SPMD program a v5e-8 slice executes over ICI.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.5 re-exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import f25519 as fe
from firedancer_tpu.ops import scalar25519 as sc
from firedancer_tpu.ops import sha512 as sh


def _ring_fold_local(p: cv.Point, axis: str, n: int) -> cv.Point:
    """All-reduce point addition inside shard_map: rotate a carry copy of
    the original partial around the ring, adding at each stop.  n is the
    static axis size (jax < 0.6 has no lax.axis_size; the mesh knows)."""
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(_, state):
        acc, carry = state
        carry = cv.Point(*(jax.lax.ppermute(t, axis, perm) for t in carry))
        return (cv.add(acc, carry), carry)

    acc, _ = jax.lax.fori_loop(0, n - 1, body, (p, p))
    return acc


def ring_point_fold(mesh: Mesh, axis: str = "dp"):
    """Jitted fn: (22,)-limbed per-device Points (stacked on a leading
    device axis, n × (22,)) -> the group sum, replicated to every device."""

    def local(X, Y, Z, T):
        p = cv.Point(X[0], Y[0], Z[0], T[0])  # this device's partial
        s = _ring_fold_local(p, axis, mesh.shape[axis])
        return tuple(t[None] for t in s)

    shard = _shard_map(
        local, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis), P(axis)),
    )
    return jax.jit(shard)


def shard_rlc_verify(mesh: Mesh, m: int = 2, axis: str = "dp"):
    """Multi-chip RLC batch verification (data-parallel MSM).

    Returns fn(msgs, msg_len, sigs, pubkeys, z_bytes) -> (all_ok scalar,
    prechecks (batch,)): True iff EVERY signature in the global batch
    passes (w.h.p. over the host-supplied 128-bit z randomness).  The
    check is Σ_i z_i s_i · B  ==  Σ_i [z_i]R_i + Σ_i [z_i k_i]A_i with
    both sides assembled across the mesh: chips compute shard-local MSM
    partials, the points ring-fold over ICI, the scalar c psums limb-wise
    (8 devices × 12-bit limbs stays far inside int32), and each chip
    evaluates the final equation on the replicated totals."""

    def local(msgs, msg_len, sigs, pubkeys, z_bytes):
        r_bytes = sigs[:, :32]
        s_bytes = sigs[:, 32:]
        ok_s = sc.is_canonical(s_bytes)
        ok_a, a_pt = cv.decompress(pubkeys)
        ok_r, r_pt = cv.decompress(r_bytes)
        ok_a &= ~cv.is_small_order_affine(a_pt)
        ok_r &= ~cv.is_small_order_affine(r_pt)
        pre = ok_s & ok_a & ok_r

        pre_img = jnp.concatenate([r_bytes, pubkeys, msgs], axis=1)
        k_limbs = sc.reduce_512(
            sh.sha512(pre_img, msg_len.astype(jnp.int32) + 64))
        z_limbs = sc.bytes_to_limbs(z_bytes, 11)
        s_limbs = sc.bytes_to_limbs(s_bytes, 22)
        w_limbs = sc.mul_mod_l(k_limbs, z_limbs)
        c_local = sc.sum_mod_l(sc.mul_mod_l(s_limbs, z_limbs), axis=0)

        w_windows = sc.limbs_to_windows(w_limbs)
        z_windows = sc.limbs_to_windows(
            jnp.concatenate([z_limbs, jnp.zeros_like(z_limbs[:11])], axis=0))

        # shard-local MSM partials: Q_local = -Σ[w]A - Σ[z]R
        acc_a = cv.msm(w_windows, cv.neg(a_pt), m=m, nwin=64)
        acc_r = cv.msm(z_windows[:32], cv.neg(r_pt), m=m, nwin=32)
        q_local = cv.add(acc_a, acc_r)

        # fold partial points around the ICI ring
        q = _ring_fold_local(q_local, axis, mesh.shape[axis])

        # c = Σ c_local mod L: limb-wise psum then one canonical reduce
        c_sum = jax.lax.psum(c_local, axis)
        pad = jnp.zeros((2, *c_sum.shape[1:]), dtype=c_sum.dtype)
        c = sc._cond_sub_l(jnp.concatenate([c_sum, pad], axis=0), times=8)

        base = cv.scalar_mul_base(sc.limbs_to_windows(c)[:, None])
        q = cv.add(q, cv.Point(*(t[:, 0] for t in base)))
        is_id = fe.is_zero(q.X) & fe.eq(q.Y, q.Z)
        all_pre = jax.lax.psum(
            jnp.sum((~pre).astype(jnp.uint32)), axis) == 0
        # the verdict is value-replicated (every chip folded the same
        # totals) but rides ppermute, which shard_map cannot statically
        # prove replicated — emit one copy per device instead
        return (all_pre & is_id)[None], pre

    shard = _shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis, None), P(axis, None),
                  P(axis, None)),
        out_specs=(P(axis), P(axis)),
    )

    fn = jax.jit(shard)
    n = mesh.shape[axis]

    def run(*args):
        batch = args[2].shape[0]
        # serving-path guard (SigVerifier routes rlc mode through here
        # when its mesh is active): a clean error beats shard_map's
        # shape-mismatch traceback, and the per-shard MSM needs its
        # local lanes divisible by the combination width m
        if batch % n or (batch // n) % m:
            raise ValueError(
                f"rlc batch {batch} must split {n} ways into "
                f"m={m}-divisible shards")
        per_dev, pre = fn(*args)
        return per_dev.all(), pre

    return run
