"""Device mesh construction and the data-parallel verify shard.

The reference scales the verify stage by round-robin sharding frags across N
verify tile processes (ref: src/app/fdctl/run/tiles/fd_verify.c:36-47,
round_robin_cnt/idx from the topology).  The TPU-native equivalent is a
1-D 'dp' mesh with the batch axis sharded across chips: each chip verifies
its shard independently (embarrassingly parallel, no cross-chip reduction on
the hot path — matching the reference, where verify tiles never talk to each
other), with a psum only for aggregate metrics (pass counts), riding ICI.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 re-exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

from firedancer_tpu.ops import ed25519 as ed


def make_mesh(n_devices: int | None = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def shard_verify_step(mesh: Mesh):
    """Build the jitted multi-chip verify step.

    Returns fn(msgs, msg_len, sigs, pubkeys) -> (ok_bits, pass_count) with
    batch sharded over 'dp'; pass_count is psum'd across the mesh (the
    monitoring aggregate, ref fd_metrics counters)."""

    def local_step(msgs, msg_len, sigs, pubkeys):
        ok = ed.verify_batch(msgs, msg_len, sigs, pubkeys)
        passes = jax.lax.psum(jnp.sum(ok.astype(jnp.uint32)), "dp")
        return ok, passes

    shard = _shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P("dp", None), P("dp", None)),
        out_specs=(P("dp"), P()),
    )
    return jax.jit(shard)


def shard_batch(mesh: Mesh, *arrays):
    """Place host arrays with the batch axis sharded over the mesh."""
    out = []
    for a in arrays:
        spec = P("dp", *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)
