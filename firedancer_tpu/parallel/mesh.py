"""Device mesh construction and the data-parallel verify shard.

The reference scales the verify stage by round-robin sharding frags across N
verify tile processes (ref: src/app/fdctl/run/tiles/fd_verify.c:36-47,
round_robin_cnt/idx from the topology).  The TPU-native equivalent is a
1-D 'dp' mesh with the batch axis sharded across chips: each chip verifies
its shard independently (embarrassingly parallel, no cross-chip reduction on
the hot path — matching the reference, where verify tiles never talk to each
other), with a psum only for aggregate metrics (pass counts), riding ICI.
"""

import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the sharded packed-blob step donates its input buffer (steady-state
# dispatch reuses the uploaded blob's pages for outputs/intermediates);
# backends that cannot alias (jax CPU) warn per-execution instead of
# failing — silence exactly that warning, donation is best-effort there
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

try:  # jax >= 0.5 re-exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

from firedancer_tpu.ops import ed25519 as ed


def make_mesh(n_devices: int | None = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def shard_verify_step(mesh: Mesh, mode: str = "strict"):
    """Build the jitted multi-chip verify step.

    Returns fn(msgs, msg_len, sigs, pubkeys) -> (ok_bits, pass_count) with
    batch sharded over 'dp'; pass_count is psum'd across the mesh (the
    monitoring aggregate, ref fd_metrics counters).  `mode` picks the
    per-lane graph: strict (ed.verify_batch) or antipa (the round-9
    halved-scalar chain) — lane parallelism is identical either way."""
    batch_fn = (ed.verify_batch_antipa if mode == "antipa"
                else ed.verify_batch)

    def local_step(msgs, msg_len, sigs, pubkeys):
        ok = batch_fn(msgs, msg_len, sigs, pubkeys)
        passes = jax.lax.psum(jnp.sum(ok.astype(jnp.uint32)), "dp")
        return ok, passes

    shard = _shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P("dp", None), P("dp", None)),
        out_specs=(P("dp"), P()),
    )
    return jax.jit(shard)


def shard_batch(mesh: Mesh, *arrays):
    """Place host arrays with the batch axis sharded over the mesh."""
    out = []
    for a in arrays:
        spec = P("dp", *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


def blob_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """The packed-blob placement: rows (lanes) sharded over the mesh,
    columns (the msgs|sig|pub|len row layout) replicated per shard.  One
    host `device_put` against this sharding splits the contiguous blob
    into per-device row slices — the multi-chip ingest upload shape."""
    return NamedSharding(mesh, P(axis, None))


def pad_rows(arr: np.ndarray, n: int) -> np.ndarray:
    """Pad a host batch's leading (lane) axis to a multiple of the shard
    count with zero rows.  Zero lanes are additionally masked on device
    by shard_verify_blob's true_rows so a padded dispatch can never
    surface a pass bit for a lane nobody submitted."""
    rem = (-arr.shape[0]) % n
    if not rem:
        return arr
    return np.concatenate(
        [arr, np.zeros((rem,) + arr.shape[1:], dtype=arr.dtype)])


def shard_verify_blob(mesh: Mesh, maxlen: int, ml: int | None = None,
                      true_rows: int | None = None, axis: str = "dp",
                      donate: bool = True, mode: str = "strict"):
    """Build the jitted multi-chip PACKED verify step — the serving-path
    twin of shard_verify_step over the single-blob row layout
    (ops.ed25519.verify_blob): fn(blob sharded P(dp, None)) -> ok bits
    sharded P(dp).

    Each chip verifies its row shard independently (the reference's
    round-robin verify tiles, fd_verify.c:36-47 — no cross-chip traffic
    on the hot path).  `true_rows` statically masks trailing padding
    lanes (a global batch not divisible by the mesh is padded host-side
    by pad_rows; the mask guarantees those lanes read False).  The blob
    argument is DONATED: steady-state dispatch reuses the uploaded
    buffer's device memory for the step's intermediates instead of
    allocating per call."""
    ml = maxlen if ml is None else ml
    n = mesh.shape[axis]
    blob_fn = (ed.verify_blob_antipa if mode == "antipa"
               else ed.verify_blob)

    def local(blob):
        ok = blob_fn(blob, maxlen=maxlen, ml=ml)
        if true_rows is not None:
            rows = blob.shape[0]  # per-shard rows (global // n)
            lane0 = jax.lax.axis_index(axis).astype(jnp.int32) * rows
            ok &= (lane0 + jnp.arange(rows, dtype=jnp.int32)) < true_rows
        return ok

    shard = _shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None),), out_specs=P(axis))
    return jax.jit(shard, donate_argnums=(0,) if donate else ())
