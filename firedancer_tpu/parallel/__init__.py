"""Multi-chip scale-out (the reference's parallelism inventory, SURVEY.md
§2.11, re-expressed as jax device meshes + shard_map collectives)."""
