// One-pass host-path kernel for the packed verify hot loop.
//
// Role: after round 8 removed every payload copy from ingest, the packed
// submit/harvest path still spent ~3.6 us/txn in Python/NumPy glue: the
// strided 8B tag gather + dedup query + mask arithmetic on submit
// (disco/pipeline.py submit_packed_rows) and the verdict masking +
// conditional tag insert + per-txn wire tobytes() loop on harvest
// (_finish_rows).  These two entry points fuse each side into a single C
// call per FRAG, reusing the tcache exported by txnparse.cpp (same .so,
// resolved at link) so the dedup window is shared with every other path.
//
// Submit: strided tag gather straight off the dcache row view + one
// fd_tcache_query_batch (QUERY only — tags are inserted at harvest iff
// the txn verifies, the FD_TCACHE_INSERT-at-publish contract).
//
// Harvest: verdict masking (ok & !dup & live), conditional
// fd_tcache_insert_batch_dedup over the passing tags, and wire
// reconstruction (0x01 | sig[64] | msg[len], equal-length and ragged rows
// alike via per-row memcpy) into a caller-provided arena with an offsets
// table.  The arena is sized by the caller; if the passing wires do not
// fit, the call returns -(needed bytes) WITHOUT touching the tcache so
// the caller can grow the arena and retry with identical semantics.
//
// C ABI (ctypes): flat arrays only.  Row layout (disco/dcache.py packed
// rows): msg[ml] | sig[64] | pub[32] | len_le32[4]; dedup tag = low 64
// bits of the signature = row[ml:ml+8] LE; tag 0 marks a dead lane.

#include <cstdint>
#include <cstring>

#define API extern "C" __attribute__((visibility("default")))

// txnparse.cpp exports (same shared library)
extern "C" void fd_tcache_query_batch(void *h, const uint64_t *tags, int n,
                                      uint8_t *hit);
extern "C" void fd_tcache_insert_batch_dedup(void *h, const uint64_t *tags,
                                             int n, uint8_t *dup);

namespace {

constexpr int kSigSz = 64;
constexpr int kLenOff = kSigSz + 32;  // len_le32 sits after sig|pub
constexpr int kMaxBatch = 1 << 16;    // passing-set scratch bound per frag

inline uint64_t row_tag(const uint8_t *row, int ml) {
  uint64_t t;
  std::memcpy(&t, row + ml, 8);  // low 64 bits of sig, LE host
  return t;
}

inline int row_len(const uint8_t *row, int ml) {
  int32_t l;
  std::memcpy(&l, row + ml + kLenOff, 4);
  // defensive clamp: a torn/garbage row must not drive memcpy off the lane
  if (l < 0) return 0;
  if (l > ml) return ml;
  return (int)l;
}

}  // namespace

// Submit side: gather the dedup tag of every lane (strided — `rows` is a
// dcache view whose row pitch is the bucket stride, not ml+100) and run
// one batched tcache QUERY.  tag_out[i] = lane tag (0 = dead lane),
// dup_out[i] = 1 iff the tag is already in the dedup window.  Returns the
// number of dup lanes.  tcache may be null (dedup off): dup_out zeroed.
API int64_t fd_hostpath_submit_rows(const uint8_t *rows, int64_t row_stride,
                                    int n, int ml, void *tcache,
                                    uint64_t *tag_out, uint8_t *dup_out) {
  if (n <= 0) return 0;
  for (int i = 0; i < n; i++)
    tag_out[i] = row_tag(rows + (int64_t)i * row_stride, ml);
  if (!tcache) {
    std::memset(dup_out, 0, (size_t)n);
    return 0;
  }
  fd_tcache_query_batch(tcache, tag_out, n, dup_out);
  int64_t ndup = 0;
  for (int i = 0; i < n; i++) ndup += dup_out[i];
  return ndup;
}

// Harvest side: one pass over the verdict.  Inputs are the submit-time
// tag/dup arrays plus the device verdict ok[i] (1 = signature valid).
//
//   live    = tag != 0
//   passing = ok & !dup & live           (candidates for publish)
//   vfail   = live & !dup & !ok          (counted, never published)
//
// Passing tags are inserted via fd_tcache_insert_batch_dedup (dup2[i]=1
// iff already present, including earlier indices of the same batch —
// those are dropped as harvest-time dups).  Survivor wires are written
// back-to-back into `arena`:  arena[offs[j] .. offs[j+1]] =
// 0x01 | sig[64] | msg[len_j], with offs having k+1 entries and
// keep_tag[j] the survivor's tag.  counts = {verify_fail, dup2_drops,
// passing}.  Returns k (survivor count), or -(needed bytes) if arena_cap
// is too small — in that case NOTHING was inserted into the tcache and
// the call can be retried verbatim with a larger arena.
API int64_t fd_hostpath_finish_rows(const uint8_t *rows, int64_t row_stride,
                                    int n, int ml, const uint8_t *ok,
                                    const uint64_t *tag, const uint8_t *dup,
                                    void *tcache, uint8_t *arena,
                                    int64_t arena_cap, int64_t *offs,
                                    uint64_t *keep_tag, int64_t *counts) {
  counts[0] = counts[1] = counts[2] = 0;
  if (n <= 0 || n > kMaxBatch) {
    offs[0] = 0;
    return n <= 0 ? 0 : -1;
  }

  static thread_local int pass_idx[kMaxBatch];
  static thread_local uint64_t pass_tag[kMaxBatch];
  static thread_local uint8_t dup2[kMaxBatch];

  int np = 0;
  int64_t vfail = 0, need = 0;
  for (int i = 0; i < n; i++) {
    if (!tag[i] || dup[i]) continue;  // dead lane or submit-time dup
    const uint8_t *row = rows + (int64_t)i * row_stride;
    if (!ok[i]) {
      vfail++;
      continue;
    }
    pass_idx[np] = i;
    pass_tag[np] = tag[i];
    np++;
    need += 1 + kSigSz + row_len(row, ml);
  }
  counts[0] = vfail;
  counts[2] = np;
  if (need > arena_cap) return -need;  // tcache untouched: retry-safe

  if (tcache && np)
    fd_tcache_insert_batch_dedup(tcache, pass_tag, np, dup2);
  else
    std::memset(dup2, 0, (size_t)np);

  int64_t k = 0, o = 0;
  offs[0] = 0;
  for (int j = 0; j < np; j++) {
    if (dup2[j]) continue;  // harvest-time dup (raced within the window)
    const uint8_t *row = rows + (int64_t)pass_idx[j] * row_stride;
    int len = row_len(row, ml);
    arena[o] = 0x01;
    std::memcpy(arena + o + 1, row + ml, kSigSz);
    std::memcpy(arena + o + 1 + kSigSz, row, (size_t)len);
    o += 1 + kSigSz + len;
    keep_tag[k] = pass_tag[j];
    offs[++k] = o;
  }
  counts[1] = np - k;
  return k;
}
