// Native pack scheduler hot loop (ballet/pack.py fast path).
//
// Role: the round-14 leader lane spent ~28.8 us/txn in Pack.schedule()'s
// Python heapq + per-txn frozenset algebra.  This file is the reference
// fd_pack shape reduced to a flat-C state machine: a fixed-capacity slot
// pool, a binary max-heap ordered by (priority desc, seq asc) — the exact
// total order of the Python (-prio, seq) heapq tuples — account locks as
// 256-bit bloom bitsets (two splitmix64-derived bits per account, so the
// conflict check is four word ANDs per side), and an open-addressed
// u64-key table for the consensus per-account write budget.
//
// Bit-identity contract with the Python fallback (tests enforce it):
//  * priority is computed host-side (arbitrary-precision reward math) and
//    passed in saturated to u64; C never re-derives it.
//  * fd_pack_acct_key == ballet.pack.acct_key for every 32-byte address.
//  * the schedule loop applies the same checks in the same order with the
//    same break/continue distinctions (block-cost overflow STOPS the
//    microblock; vote/data/conflict/budget failures only defer that txn).
//
// C ABI (ctypes): opaque handle + flat scalars; chosen txns are returned
// as slot indices the Python side maps back to held payloads.

#include <cstdint>
#include <cstdlib>
#include <cstring>

#define API extern "C" __attribute__((visibility("default")))

namespace {

// consensus limits — keep in lockstep with ballet/pack.py
constexpr uint64_t MAX_COST_PER_BLOCK = 48000000ull;
constexpr uint64_t MAX_VOTE_COST_PER_BLOCK = 36000000ull;
constexpr uint64_t MAX_WRITE_COST_PER_ACCT = 12000000ull;
constexpr uint64_t MAX_DATA_PER_BLOCK =
    ((32ull * 1024ull - 17ull) / 31ull) * 25871ull + 48ull;

constexpr int MAX_BANKS = 64;

struct Slot {
  uint64_t cost;
  uint64_t prio;
  uint64_t seq;
  uint64_t wmask[4];
  uint64_t rmask[4];
  uint64_t *wkeys;  // unique writable account keys (malloc'd per insert)
  int32_t n_wkeys;
  int32_t payload_len;
  uint8_t is_vote;
  uint8_t used;
};

struct Pack {
  int bank_cnt;
  int64_t pool_cap;   // hard bound
  int64_t alloc_cap;  // currently allocated slots (doubles on demand)
  Slot *slots;
  int64_t *freelist;  // stack of RELEASED slots only
  int64_t free_cnt;
  int64_t next_fresh;  // high-water mark: slots >= this were never used
  int64_t *heap;  // slot indices, max-heap by (prio desc, seq asc)
  int64_t heap_cnt;
  int64_t *skipped;  // scratch for deferred pops
  uint64_t bank_w[MAX_BANKS][4];
  uint64_t bank_r[MAX_BANKS][4];
  uint64_t gw[4];   // cached union of in-flight writable masks
  uint64_t grw[4];  // cached union of in-flight writable|readonly masks
  uint64_t block_cost, block_vote, block_data;
  // open-addressed per-account write cost table (cleared per block)
  uint64_t *tk;
  uint64_t *tv;
  uint8_t *tu;
  int64_t tcap, tcnt;
};

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

inline uint64_t acct_key(uint8_t const *a) {
  // distinct odd multipliers per limb: a plain xor-fold cancels on
  // repeated limb patterns (e.g. a byte repeated 32 times)
  uint64_t l[4];
  std::memcpy(l, a, 32);
  return splitmix64((l[0] * 0x9E3779B97F4A7C15ull)
                    ^ (l[1] * 0xC2B2AE3D27D4EB4Full)
                    ^ (l[2] * 0x165667B19E3779F9ull)
                    ^ (l[3] * 0x27D4EB2F165667C5ull));
}

inline void mask_set(uint64_t m[4], uint64_t key) {
  unsigned b0 = (unsigned)(key & 255u);
  unsigned b1 = (unsigned)((key >> 8) & 255u);
  m[b0 >> 6] |= 1ull << (b0 & 63u);
  m[b1 >> 6] |= 1ull << (b1 & 63u);
}

inline int mask_intersects(uint64_t const a[4], uint64_t const b[4]) {
  return ((a[0] & b[0]) | (a[1] & b[1]) | (a[2] & b[2]) | (a[3] & b[3]))
         != 0ull;
}

inline void mask_or(uint64_t d[4], uint64_t const s[4]) {
  d[0] |= s[0]; d[1] |= s[1]; d[2] |= s[2]; d[3] |= s[3];
}

// heap order: "less" == should pop first
inline int heap_before(Pack *p, int64_t a, int64_t b) {
  Slot const &sa = p->slots[a], &sb = p->slots[b];
  if (sa.prio != sb.prio) return sa.prio > sb.prio;
  return sa.seq < sb.seq;
}

void heap_push(Pack *p, int64_t idx) {
  int64_t i = p->heap_cnt++;
  p->heap[i] = idx;
  while (i > 0) {
    int64_t par = (i - 1) >> 1;
    if (!heap_before(p, p->heap[i], p->heap[par])) break;
    int64_t t = p->heap[i]; p->heap[i] = p->heap[par]; p->heap[par] = t;
    i = par;
  }
}

int64_t heap_pop(Pack *p) {
  int64_t top = p->heap[0];
  int64_t n = --p->heap_cnt;
  if (n > 0) {
    p->heap[0] = p->heap[n];
    int64_t i = 0;
    for (;;) {
      int64_t l = 2 * i + 1, r = l + 1, best = i;
      if (l < n && heap_before(p, p->heap[l], p->heap[best])) best = l;
      if (r < n && heap_before(p, p->heap[r], p->heap[best])) best = r;
      if (best == i) break;
      int64_t t = p->heap[i]; p->heap[i] = p->heap[best];
      p->heap[best] = t;
      i = best;
    }
  }
  return top;
}

// per-account write-cost table -------------------------------------------
uint64_t tbl_get(Pack *p, uint64_t key) {
  int64_t mask = p->tcap - 1;
  int64_t i = (int64_t)(key & (uint64_t)mask);
  while (p->tu[i]) {
    if (p->tk[i] == key) return p->tv[i];
    i = (i + 1) & mask;
  }
  return 0;
}

void tbl_grow(Pack *p);

void tbl_add(Pack *p, uint64_t key, uint64_t add) {
  if (4 * (p->tcnt + 1) >= 3 * p->tcap) tbl_grow(p);
  int64_t mask = p->tcap - 1;
  int64_t i = (int64_t)(key & (uint64_t)mask);
  while (p->tu[i]) {
    if (p->tk[i] == key) { p->tv[i] += add; return; }
    i = (i + 1) & mask;
  }
  p->tu[i] = 1; p->tk[i] = key; p->tv[i] = add; p->tcnt++;
}

void tbl_grow(Pack *p) {
  int64_t ncap = p->tcap * 2;
  uint64_t *nk = (uint64_t *)std::calloc((size_t)ncap, 8);
  uint64_t *nv = (uint64_t *)std::calloc((size_t)ncap, 8);
  uint8_t *nu = (uint8_t *)std::calloc((size_t)ncap, 1);
  int64_t nmask = ncap - 1;
  for (int64_t i = 0; i < p->tcap; i++) {
    if (!p->tu[i]) continue;
    int64_t j = (int64_t)(p->tk[i] & (uint64_t)nmask);
    while (nu[j]) j = (j + 1) & nmask;
    nu[j] = 1; nk[j] = p->tk[i]; nv[j] = p->tv[i];
  }
  std::free(p->tk); std::free(p->tv); std::free(p->tu);
  p->tk = nk; p->tv = nv; p->tu = nu; p->tcap = ncap;
}

void slot_release(Pack *p, int64_t idx) {
  Slot &s = p->slots[idx];
  std::free(s.wkeys);
  s.wkeys = nullptr;
  s.n_wkeys = 0;
  s.used = 0;
  p->freelist[p->free_cnt++] = idx;
}

}  // namespace

API void *fd_pack_new(int bank_cnt, long long pool_cap) {
  if (bank_cnt < 1 || bank_cnt > MAX_BANKS || pool_cap < 1) return nullptr;
  Pack *p = (Pack *)std::calloc(1, sizeof(Pack));
  if (!p) return nullptr;
  p->bank_cnt = bank_cnt;
  p->pool_cap = pool_cap;
  // start small and double on demand: construction stays O(1 KB) even
  // with a 64K hard cap (a fresh Pack per bench rep / tile respawn must
  // not pay megabytes of calloc)
  p->alloc_cap = pool_cap < 1024 ? pool_cap : 1024;
  p->slots = (Slot *)std::calloc((size_t)p->alloc_cap, sizeof(Slot));
  p->freelist = (int64_t *)std::malloc((size_t)p->alloc_cap * 8);
  p->heap = (int64_t *)std::malloc((size_t)p->alloc_cap * 8);
  p->skipped = (int64_t *)std::malloc((size_t)p->alloc_cap * 8);
  p->tcap = 1024;
  p->tk = (uint64_t *)std::calloc((size_t)p->tcap, 8);
  p->tv = (uint64_t *)std::calloc((size_t)p->tcap, 8);
  p->tu = (uint8_t *)std::calloc((size_t)p->tcap, 1);
  if (!p->slots || !p->freelist || !p->heap || !p->skipped || !p->tk ||
      !p->tv || !p->tu) {
    std::free(p->slots); std::free(p->freelist); std::free(p->heap);
    std::free(p->skipped); std::free(p->tk); std::free(p->tv);
    std::free(p->tu); std::free(p);
    return nullptr;
  }
  // slots are handed out lazily (released ones first, then fresh off the
  // high-water mark) so construction and teardown never touch the whole
  // pool — slot idx never affects schedule order (the heap orders by
  // prio/seq), so allocation order is free
  return p;
}

API void fd_pack_delete(void *h) {
  if (!h) return;
  Pack *p = (Pack *)h;
  for (int64_t i = 0; i < p->next_fresh; i++)
    if (p->slots[i].used) std::free(p->slots[i].wkeys);
  std::free(p->slots); std::free(p->freelist); std::free(p->heap);
  std::free(p->skipped); std::free(p->tk); std::free(p->tv);
  std::free(p->tu); std::free(p);
}

API unsigned long long fd_pack_acct_key(const unsigned char *addr) {
  return acct_key(addr);
}

// args: one packed little-endian blob (struct "<IIIIIIIQQQ", 52 bytes):
// acct_addr_off, n_acct, sig_cnt, ro_signed, ro_unsigned, is_vote,
// payload_len, cost, prio, seq.  One blob instead of 12 scalars keeps
// the ctypes marshalling cost at ~3 conversions per insert.
API long long fd_pack_insert(void *h, const unsigned char *payload,
                             const unsigned char *args) {
  uint32_t w[7];
  uint64_t q[3];
  std::memcpy(w, args, 28);
  std::memcpy(q, args + 28, 24);
  int acct_addr_off = (int)w[0], n_acct = (int)w[1], sig_cnt = (int)w[2];
  int ro_signed = (int)w[3], ro_unsigned = (int)w[4];
  int is_vote = (int)w[5], payload_len = (int)w[6];
  uint64_t cost = q[0], prio = q[1], seq = q[2];
  Pack *p = (Pack *)h;
  int64_t idx;
  if (p->free_cnt > 0) {
    idx = p->freelist[--p->free_cnt];
  } else if (p->next_fresh < p->alloc_cap) {
    idx = p->next_fresh++;
  } else if (p->alloc_cap < p->pool_cap) {
    int64_t ncap = p->alloc_cap * 2;
    if (ncap > p->pool_cap) ncap = p->pool_cap;
    Slot *ns = (Slot *)std::realloc(p->slots, (size_t)ncap * sizeof(Slot));
    if (!ns) return -1;
    p->slots = ns;
    int64_t *nf = (int64_t *)std::realloc(p->freelist, (size_t)ncap * 8);
    if (!nf) return -1;
    p->freelist = nf;
    int64_t *nh = (int64_t *)std::realloc(p->heap, (size_t)ncap * 8);
    if (!nh) return -1;
    p->heap = nh;
    int64_t *nk = (int64_t *)std::realloc(p->skipped, (size_t)ncap * 8);
    if (!nk) return -1;
    p->skipped = nk;
    p->alloc_cap = ncap;
    idx = p->next_fresh++;
  } else {
    return -1;
  }
  Slot &s = p->slots[idx];
  std::memset(s.wmask, 0, 32);
  std::memset(s.rmask, 0, 32);
  s.cost = cost;
  s.prio = prio;
  s.seq = seq;
  s.payload_len = payload_len;
  s.is_vote = (uint8_t)(is_vote != 0);
  s.used = 1;
  s.wkeys = n_acct > 0 ? (uint64_t *)std::malloc((size_t)n_acct * 8)
                       : nullptr;
  s.n_wkeys = 0;
  // fd_txn.h account ordering: writability from four header counts
  int w_signed_end = sig_cnt - ro_signed;
  int w_unsigned_end = n_acct - ro_unsigned;
  for (int i = 0; i < n_acct; i++) {
    uint64_t k = acct_key(payload + acct_addr_off + 32 * i);
    int writable =
        (i < sig_cnt) ? (i < w_signed_end) : (i < w_unsigned_end);
    if (writable) {
      mask_set(s.wmask, k);
      int dup = 0;
      for (int j = 0; j < s.n_wkeys; j++)
        if (s.wkeys[j] == k) { dup = 1; break; }
      if (!dup) s.wkeys[s.n_wkeys++] = k;
    } else {
      mask_set(s.rmask, k);
    }
  }
  heap_push(p, idx);
  return idx;
}

API long long fd_pack_pending(void *h) { return ((Pack *)h)->heap_cnt; }

API void fd_pack_clear_pending(void *h) {
  Pack *p = (Pack *)h;
  for (int64_t i = 0; i < p->heap_cnt; i++) slot_release(p, p->heap[i]);
  p->heap_cnt = 0;
}

API long long fd_pack_schedule(void *h, int bank, int max_txn,
                               long long *out_idx, long long *delayed_out) {
  Pack *p = (Pack *)h;
  uint64_t w_busy[4], rw_busy[4];
  std::memcpy(w_busy, p->gw, 32);
  std::memcpy(rw_busy, p->grw, 32);
  int64_t n_chosen = 0, n_skipped = 0, delayed = 0;
  uint64_t mb_cost = 0, mb_vote = 0, mb_data = 0;
  while (p->heap_cnt > 0 && n_chosen < max_txn) {
    int64_t idx = heap_pop(p);
    Slot &s = p->slots[idx];
    uint64_t c = s.cost;
    if (p->block_cost + mb_cost + c > MAX_COST_PER_BLOCK) {
      p->skipped[n_skipped++] = idx;
      break;
    }
    if (s.is_vote &&
        p->block_vote + mb_vote + c > MAX_VOTE_COST_PER_BLOCK) {
      p->skipped[n_skipped++] = idx;
      continue;
    }
    if (p->block_data + mb_data + (uint64_t)s.payload_len
        > MAX_DATA_PER_BLOCK) {
      p->skipped[n_skipped++] = idx;
      continue;
    }
    if (mask_intersects(s.wmask, rw_busy) ||
        mask_intersects(s.rmask, w_busy)) {
      delayed++;
      p->skipped[n_skipped++] = idx;
      continue;
    }
    int over = 0;
    for (int j = 0; j < s.n_wkeys; j++)
      if (tbl_get(p, s.wkeys[j]) + c > MAX_WRITE_COST_PER_ACCT) {
        over = 1;
        break;
      }
    if (over) {
      p->skipped[n_skipped++] = idx;
      continue;
    }
    // accept: intra-microblock conflicts are excluded immediately
    out_idx[n_chosen++] = idx;
    mb_cost += c;
    if (s.is_vote) mb_vote += c;
    mb_data += (uint64_t)s.payload_len;
    mask_or(w_busy, s.wmask);
    mask_or(rw_busy, s.wmask);
    mask_or(rw_busy, s.rmask);
  }
  for (int64_t i = 0; i < n_skipped; i++) heap_push(p, p->skipped[i]);
  *delayed_out = delayed;
  if (n_chosen == 0) return 0;
  for (int64_t i = 0; i < n_chosen; i++) {
    Slot &s = p->slots[out_idx[i]];
    mask_or(p->bank_w[bank], s.wmask);
    mask_or(p->bank_r[bank], s.rmask);
    for (int j = 0; j < s.n_wkeys; j++) tbl_add(p, s.wkeys[j], s.cost);
  }
  mask_or(p->gw, p->bank_w[bank]);
  mask_or(p->grw, p->bank_w[bank]);
  mask_or(p->grw, p->bank_r[bank]);
  p->block_cost += mb_cost;
  p->block_vote += mb_vote;
  p->block_data += mb_data;
  // release the chosen slots (wkeys already folded into the budget
  // table); out_idx keeps the indices for the Python _slots map
  for (int64_t i = 0; i < n_chosen; i++) slot_release(p, out_idx[i]);
  return n_chosen;
}

API void fd_pack_done(void *h, int bank) {
  Pack *p = (Pack *)h;
  std::memset(p->bank_w[bank], 0, 32);
  std::memset(p->bank_r[bank], 0, 32);
  // bloom bits are shared, so refold the surviving banks (O(banks) words)
  std::memset(p->gw, 0, 32);
  std::memset(p->grw, 0, 32);
  for (int b = 0; b < p->bank_cnt; b++) {
    mask_or(p->gw, p->bank_w[b]);
    mask_or(p->grw, p->bank_w[b]);
    mask_or(p->grw, p->bank_r[b]);
  }
}

API void fd_pack_end_block(void *h) {
  Pack *p = (Pack *)h;
  p->block_cost = 0;
  p->block_vote = 0;
  p->block_data = 0;
  std::memset(p->tu, 0, (size_t)p->tcap);
  p->tcnt = 0;
}
