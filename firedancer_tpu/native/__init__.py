"""Native (C++) runtime components, built on demand.

The reference's performance-native layers (tango rings, util shmem) are C;
ours are C++ compiled here into a single shared library loaded via ctypes.
Build is lazy and cached: the .so is rebuilt iff any source is newer.
"""

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["tango.cpp", "pkteng.cpp", "txnparse.cpp", "hostpath.cpp",
            "packsched.cpp", "aescrypt.cpp"]
_SO = os.path.join(_DIR, "_fdtpu_native.so")

_lock = threading.Lock()
_lib = None


def _stale() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    return any(
        os.path.getmtime(os.path.join(_DIR, s)) > so_mtime for s in _SOURCES
    )


def build() -> str:
    """Compile the native library if needed; returns the .so path."""
    with _lock:
        if _stale():
            cmd = [
                "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                "-fvisibility=hidden", "-o", _SO + ".tmp",
            ] + [os.path.join(_DIR, s) for s in _SOURCES]
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(_SO + ".tmp", _SO)
    return _SO


def lib() -> ctypes.CDLL:
    """The loaded native library (builds on first use)."""
    global _lib
    if _lib is None:
        path = build()
        with _lock:
            if _lib is None:
                _lib = _bind(ctypes.CDLL(path))
    return _lib


def _bind(L: ctypes.CDLL) -> ctypes.CDLL:
    u64, u32, i32 = ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int
    p = ctypes.c_void_p
    sig = {
        "fd_mcache_align": (u64, []),
        "fd_mcache_footprint": (u64, [u64]),
        "fd_mcache_new": (i32, [p, u64, u64]),
        "fd_mcache_depth": (u64, [p]),
        "fd_mcache_seq0": (u64, [p]),
        "fd_mcache_seq_query": (u64, [p]),
        "fd_mcache_publish": (u64, [p, u64, u32, u32, u32, u32, u32]),
        "fd_mcache_query": (i32, [p, u64, p]),
        "fd_mcache_consume_burst": (i32, [p, u64, u64, p, ctypes.POINTER(u64)]),
        "fd_fseq_footprint": (u64, []),
        "fd_fseq_new": (None, [p, u64]),
        "fd_fseq_update": (None, [p, u64]),
        "fd_fseq_query": (u64, [p]),
        "fd_fseq_diag_add": (None, [p, u64, u64]),
        "fd_fseq_diag_query": (u64, [p, u64]),
        "fd_cnc_footprint": (u64, []),
        "fd_cnc_new": (None, [p]),
        "fd_cnc_signal": (None, [p, u64]),
        "fd_cnc_signal_query": (u64, [p]),
        "fd_cnc_heartbeat": (None, [p, u64]),
        "fd_cnc_heartbeat_query": (u64, [p]),
        "fd_dcache_chunk_sz": (u64, []),
        "fd_dcache_req_data_sz": (u64, [u64, u64, u64]),
        "fd_dcache_compact_next": (u64, [u64, u64, u64, u64]),
        "fd_pkteng_open": (i32, [ctypes.c_char_p, i32, i32]),
        "fd_pkteng_port": (i32, [i32]),
        "fd_pkteng_rx_burst": (i32, [i32, p, i32, i32, p, p, p]),
        "fd_pkteng_tx_burst": (i32, [i32, p, i32, i32, p, p, p]),
        "fd_pkteng_close": (None, [i32]),
        "fd_xring_open": (ctypes.c_longlong,
                          [ctypes.c_char_p, i32, i32, i32]),
        "fd_xring_poll": (i32, [ctypes.c_longlong, i32]),
        "fd_xring_rx_burst": (i32, [ctypes.c_longlong, p, i32, i32,
                                    p, p, p, i32]),
        "fd_xring_close": (None, [ctypes.c_longlong]),
        "fd_ring_rx_burst": (i32, [p, p, u64, u64, u64, i32, i32,
                                   p, p, ctypes.c_int64, p, p, p, p]),
        "fd_ring_tx_burst": (u64, [p, p, u64, u64, u64, p, p, p, p,
                                   i32, u32, u32, p]),
        "fd_tcache_new": (p, [u64]),
        "fd_tcache_delete": (None, [p]),
        "fd_tcache_query": (i32, [p, u64]),
        "fd_tcache_insert": (None, [p, u64]),
        "fd_tcache_insert_batch": (None, [p, p, i32]),
        "fd_tcache_insert_batch_dedup": (None, [p, p, i32, p]),
        "fd_tcache_query_batch": (None, [p, p, i32, p]),
        "fd_hostpath_submit_rows": (ctypes.c_int64,
                                    [p, ctypes.c_int64, i32, i32, p, p, p]),
        "fd_hostpath_finish_rows": (ctypes.c_int64,
                                    [p, ctypes.c_int64, i32, i32, p, p, p,
                                     p, p, ctypes.c_int64, p, p, p]),
        "fd_txn_parse_batch": (i32, [p, p, i32, p, i32, i32, i32,
                                     p, p, p, p, p, p, p, p, p]),
        "fd_txn_parse_batch_packed": (i32, [p, p, i32, p, i32, i32, i32,
                                            p, ctypes.c_int64, p,
                                            p, p, p, p, p]),
        "fd_pack_new": (p, [i32, ctypes.c_longlong]),
        "fd_pack_delete": (None, [p]),
        "fd_pack_acct_key": (u64, [ctypes.c_char_p]),
        "fd_pack_insert": (ctypes.c_longlong,
                           [p, ctypes.c_char_p, ctypes.c_char_p]),
        "fd_pack_pending": (ctypes.c_longlong, [p]),
        "fd_pack_clear_pending": (None, [p]),
        "fd_pack_schedule": (ctypes.c_longlong,
                             [p, i32, i32, ctypes.POINTER(ctypes.c_longlong),
                              ctypes.POINTER(ctypes.c_longlong)]),
        "fd_pack_done": (None, [p, i32]),
        "fd_pack_end_block": (None, [p]),
        "fd_aescrypt_key_new": (ctypes.c_int64, [p, p, p]),
        "fd_aescrypt_key_free": (None, [ctypes.c_int64]),
        "fd_aescrypt_key_cnt": (ctypes.c_int64, []),
        "fd_aescrypt_decrypt_burst": (i32, [p, p, p, p, p, p, p, i32,
                                            p, p, p, p]),
        "fd_aescrypt_encrypt_burst": (i32, [p, p, p, p, p, i32, p]),
        "fd_xsk_fill": (i32, [p, ctypes.c_uint64, ctypes.c_uint64,
                              ctypes.c_uint64, ctypes.c_uint32, p, i32]),
        "fd_xsk_rx_burst": (i32, [p, ctypes.c_uint64, ctypes.c_uint64,
                                  ctypes.c_uint64, ctypes.c_uint32,
                                  p, ctypes.c_uint64, ctypes.c_uint64,
                                  ctypes.c_uint64, ctypes.c_uint32,
                                  p, ctypes.c_uint64, p, ctypes.c_int64,
                                  p, p, p, p, i32]),
    }
    for name, (res, args) in sig.items():
        fn = getattr(L, name)
        fn.restype = res
        fn.argtypes = args
    return L
