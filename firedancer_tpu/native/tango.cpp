// tango: lock-free single-producer broadcast rings, flow control, and
// command-and-control — the native IPC fabric of the framework.
//
// Re-imagines the reference's tango layer (src/tango/fd_tango_base.h:4-113,
// src/tango/mcache/fd_mcache.h, src/tango/fseq/fd_fseq.c,
// src/tango/cnc/fd_cnc.c) for a host feeding a TPU: same contracts —
// gapless 64-bit seqs, per-entry seqlock metas, overrun-by-regression
// detection, consumer-published fseq credits, heartbeat cnc — but built as a
// position-independent C++ library operating on caller-provided memory
// (anonymous or named shared memory mapped by the Python host layer), so the
// same code runs in-process, cross-process, and under tests.
//
// Concurrency model (per-entry seqlock, matching fd_frag_meta_t semantics,
// fd_tango_base.h:152-171):
//   producer: write all fields of line (seq & depth-1) with the seq word
//             stored LAST, release order.  The old occupant's seq differs
//             from the new one (it is seq - depth), so a concurrent reader
//             can never observe a half-written meta with a matching seq.
//   consumer: load seq word (acquire); if != want -> not-yet (lt) or
//             overrun (gt).  Copy meta, then re-load seq word; if changed,
//             the producer lapped us mid-copy -> overrun.
//
// Exported with C linkage for ctypes binding (firedancer_tpu/tango/ring.py).

#include <atomic>
#include <cstdint>
#include <cstring>

typedef uint64_t ulong_t;
typedef uint32_t uint_t;

#define FD_EXPORT extern "C" __attribute__((visibility("default")))

// ---------------------------------------------------------------------------
// frag meta: 32 bytes, cacheline-pair friendly (fd_tango_base.h:152-171)

struct alignas(32) frag_meta {
  std::atomic<ulong_t> seq;  // version word: entry valid iff seq == want
  ulong_t sig;               // app signature (dedup key / filter w/o payload)
  uint_t chunk;              // dcache chunk index of payload
  uint16_t sz;               // payload size in bytes
  uint16_t ctl;              // SOM/EOM/ERR + origin id (fd_tango_base.h:76-99)
  uint_t tsorig;             // compressed origin timestamp
  uint_t tspub;              // compressed publish timestamp
};
static_assert(sizeof(frag_meta) == 32, "frag_meta must be 32 bytes");

// mcache memory layout: [ header (128B) | frag_meta[depth] ]
struct alignas(64) mcache_hdr {
  ulong_t magic;
  ulong_t depth;      // power of two
  ulong_t seq0;       // initial sequence number
  std::atomic<ulong_t> seq;  // producer cursor: next seq to publish
  uint8_t pad[96];
};
static_assert(sizeof(mcache_hdr) == 128, "mcache_hdr must be 128 bytes");

static const ulong_t MCACHE_MAGIC = 0xfd7a6f0c0c0ffee1UL;

static inline frag_meta* mcache_ring(void* mem) {
  return reinterpret_cast<frag_meta*>(static_cast<uint8_t*>(mem) + sizeof(mcache_hdr));
}

FD_EXPORT ulong_t fd_mcache_align(void) { return 64; }

FD_EXPORT ulong_t fd_mcache_footprint(ulong_t depth) {
  // power of two, >= 2 (the seq-1 invalidation word must not alias a
  // want-seq on the same line, which needs depth >= 2)
  if (depth < 2 || (depth & (depth - 1))) return 0;
  return sizeof(mcache_hdr) + depth * sizeof(frag_meta);
}

FD_EXPORT int fd_mcache_new(void* mem, ulong_t depth, ulong_t seq0) {
  if (!fd_mcache_footprint(depth)) return -1;
  mcache_hdr* h = static_cast<mcache_hdr*>(mem);
  std::memset(mem, 0, fd_mcache_footprint(depth));
  h->depth = depth;
  h->seq0 = seq0;
  h->seq.store(seq0, std::memory_order_relaxed);
  frag_meta* ring = mcache_ring(mem);
  // Seed entries so no line ever matches a pre-publish want: entry i holds
  // seq0 + i - depth (i.e. "one lap ago"), mirroring fd_mcache_new's init.
  for (ulong_t i = 0; i < depth; i++)
    ring[i].seq.store(seq0 + i - depth, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  h->magic = MCACHE_MAGIC;
  return 0;
}

FD_EXPORT ulong_t fd_mcache_depth(void* mem) {
  mcache_hdr* h = static_cast<mcache_hdr*>(mem);
  return h->magic == MCACHE_MAGIC ? h->depth : 0;
}

FD_EXPORT ulong_t fd_mcache_seq0(void* mem) {
  return static_cast<mcache_hdr*>(mem)->seq0;
}

// producer cursor (next seq to be published), for lazy consumer resync
FD_EXPORT ulong_t fd_mcache_seq_query(void* mem) {
  return static_cast<mcache_hdr*>(mem)->seq.load(std::memory_order_acquire);
}

// Publish one frag at the producer cursor; returns the seq it got.
// Single producer only (the reference's contract too).
FD_EXPORT ulong_t fd_mcache_publish(void* mem, ulong_t sig, uint_t chunk,
                                    uint_t sz, uint_t ctl, uint_t tsorig,
                                    uint_t tspub) {
  mcache_hdr* h = static_cast<mcache_hdr*>(mem);
  ulong_t seq = h->seq.load(std::memory_order_relaxed);
  frag_meta* m = mcache_ring(mem) + (seq & (h->depth - 1));
  // Invalidate the line first so a reader that matched the OLD seq and is
  // mid-copy re-reads a changed version word (seqlock write begin).  The
  // fence is the store-store barrier keeping the data writes below from
  // hoisting above the invalidation (the reference's FD_COMPILER_MFENCE at
  // this spot; compiler barrier on x86-TSO, dmb st on weaker hw).
  m->seq.store(seq - 1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  m->sig = sig;
  m->chunk = chunk;
  m->sz = static_cast<uint16_t>(sz);
  m->ctl = static_cast<uint16_t>(ctl);
  m->tsorig = tsorig;
  m->tspub = tspub;
  m->seq.store(seq, std::memory_order_release);  // seqlock write end
  h->seq.store(seq + 1, std::memory_order_release);
  return seq;
}

// Consumer poll for `want`.  out must hold 32 bytes.
// Returns 0 = got it, -1 = not yet published, 1 = overrun (caller must
// resync via fd_mcache_seq_query and count the loss).
FD_EXPORT int fd_mcache_query(void* mem, ulong_t want, void* out) {
  mcache_hdr* h = static_cast<mcache_hdr*>(mem);
  frag_meta* m = mcache_ring(mem) + (want & (h->depth - 1));
  ulong_t s0 = m->seq.load(std::memory_order_acquire);
  if (s0 != want) {
    // signed distance handles wraparound the way the reference does
    return (static_cast<int64_t>(s0 - want) < 0) ? -1 : 1;
  }
  std::memcpy(out, m, sizeof(frag_meta));
  std::atomic_thread_fence(std::memory_order_acquire);
  ulong_t s1 = m->seq.load(std::memory_order_relaxed);
  return (s1 == want) ? 0 : 1;  // changed mid-copy -> lapped -> overrun
}

// Batch consume: copy metas for [want, want+max) into out (32B stride)
// until not-yet/overrun.  Writes number consumed to *n_out; returns the
// status of the FIRST non-consumed slot (0 if max consumed, -1 not yet,
// 1 overrun).  This is the Python host's amortization lever: one ctypes
// call drains a burst.
FD_EXPORT int fd_mcache_consume_burst(void* mem, ulong_t want, ulong_t max,
                                      void* out, ulong_t* n_out) {
  uint8_t* dst = static_cast<uint8_t*>(out);
  ulong_t n = 0;
  int rc = 0;
  while (n < max) {
    rc = fd_mcache_query(mem, want + n, dst + 32 * n);
    if (rc) break;
    n++;
  }
  *n_out = n;
  return n == max ? 0 : rc;
}

// ---------------------------------------------------------------------------
// fseq: consumer -> producer flow control cacheline (src/tango/fseq/fd_fseq.c)
// layout: [ seq | 7 diag ulongs ] in one 64-byte line.

struct alignas(64) fseq_line {
  std::atomic<ulong_t> seq;
  std::atomic<ulong_t> diag[7];
};
static_assert(sizeof(fseq_line) == 64, "fseq must be one cacheline");

// diag indices (mirrors FD_FSEQ_DIAG_* in src/disco/mux/fd_mux.c usage)
//   0 pub_cnt, 1 pub_sz, 2 filt_cnt, 3 filt_sz, 4 ovrnp_cnt, 5 ovrnr_cnt,
//   6 slow_cnt

FD_EXPORT ulong_t fd_fseq_footprint(void) { return sizeof(fseq_line); }

FD_EXPORT void fd_fseq_new(void* mem, ulong_t seq0) {
  fseq_line* f = static_cast<fseq_line*>(mem);
  f->seq.store(seq0, std::memory_order_relaxed);
  for (int i = 0; i < 7; i++) f->diag[i].store(0, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
}

FD_EXPORT void fd_fseq_update(void* mem, ulong_t seq) {
  static_cast<fseq_line*>(mem)->seq.store(seq, std::memory_order_release);
}

FD_EXPORT ulong_t fd_fseq_query(void* mem) {
  return static_cast<fseq_line*>(mem)->seq.load(std::memory_order_acquire);
}

FD_EXPORT void fd_fseq_diag_add(void* mem, ulong_t idx, ulong_t delta) {
  static_cast<fseq_line*>(mem)->diag[idx & 7].fetch_add(
      delta, std::memory_order_relaxed);
}

FD_EXPORT ulong_t fd_fseq_diag_query(void* mem, ulong_t idx) {
  return static_cast<fseq_line*>(mem)->diag[idx & 7].load(
      std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// cnc: command-and-control + heartbeat (src/tango/cnc/fd_cnc.c).
// layout: [ signal | heartbeat | 6 app ulongs ] in one line.
// signals mirror fd_cnc FD_CNC_SIGNAL_*: 0 RUN, 1 BOOT, 2 FAIL, 3 HALT
// (app-defined above 3).

struct alignas(64) cnc_line {
  std::atomic<ulong_t> signal;
  std::atomic<ulong_t> heartbeat;
  std::atomic<ulong_t> app[6];
};
static_assert(sizeof(cnc_line) == 64, "cnc must be one cacheline");

FD_EXPORT ulong_t fd_cnc_footprint(void) { return sizeof(cnc_line); }

FD_EXPORT void fd_cnc_new(void* mem) {
  cnc_line* c = static_cast<cnc_line*>(mem);
  c->signal.store(1 /* BOOT */, std::memory_order_relaxed);
  c->heartbeat.store(0, std::memory_order_relaxed);
  for (int i = 0; i < 6; i++) c->app[i].store(0, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
}

FD_EXPORT void fd_cnc_signal(void* mem, ulong_t sig) {
  static_cast<cnc_line*>(mem)->signal.store(sig, std::memory_order_release);
}

FD_EXPORT ulong_t fd_cnc_signal_query(void* mem) {
  return static_cast<cnc_line*>(mem)->signal.load(std::memory_order_acquire);
}

FD_EXPORT void fd_cnc_heartbeat(void* mem, ulong_t now) {
  static_cast<cnc_line*>(mem)->heartbeat.store(now, std::memory_order_release);
}

FD_EXPORT ulong_t fd_cnc_heartbeat_query(void* mem) {
  return static_cast<cnc_line*>(mem)->heartbeat.load(std::memory_order_acquire);
}

// ---------------------------------------------------------------------------
// dcache helpers (src/tango/dcache/fd_dcache.c): payload region addressed by
// chunk index; compact ring allocation a la fd_dcache_compact_next.

static const ulong_t CHUNK_LG_SZ = 6;  // 64B chunks (FD_CHUNK_LG_SZ)

FD_EXPORT ulong_t fd_dcache_chunk_sz(void) { return 1UL << CHUNK_LG_SZ; }

// footprint for a compact ring holding bursts of mtu-sized frags at `depth`
// outstanding (mirrors fd_dcache_req_data_sz, fd_dcache.h)
FD_EXPORT ulong_t fd_dcache_req_data_sz(ulong_t mtu, ulong_t depth,
                                        ulong_t burst) {
  ulong_t chunk = 1UL << CHUNK_LG_SZ;
  ulong_t mtu_chunks = (mtu + chunk - 1) >> CHUNK_LG_SZ;
  return (depth + burst + 1) * mtu_chunks * chunk;
}

// next chunk index for a compact ring write of sz bytes
FD_EXPORT ulong_t fd_dcache_compact_next(ulong_t chunk, ulong_t sz,
                                         ulong_t chunk0, ulong_t wmark) {
  ulong_t chunks = ((sz + (1UL << CHUNK_LG_SZ) - 1) >> CHUNK_LG_SZ);
  ulong_t next = chunk + chunks;
  return next > wmark ? chunk0 : next;
}

// ---------------------------------------------------------------------------
// Burst data plane (round 4): one C call per burst for the rx
// (consume + seqlock-validated payload copy + round-robin filter) and tx
// (dcache write + publish) sides.  This is what lets a Python tile process
// move hundreds of thousands of frags/s: the per-frag work never crosses
// the ctypes boundary.  Contracts identical to the per-frag calls above.

// Consume up to `max` frags starting at `want`.  Frags whose
// seq % rr_cnt != rr_idx are filtered (counted, not copied) — the verify
// tile's round-robin sharding (ref fd_verify.c:36-47) applied at the ring.
// Payloads of kept frags are copied from the dcache data area with seqlock
// re-validation; metas land in metas_out (32B stride, kept frags only),
// payload bytes concatenate into buf with offs_out[i] the start of kept
// frag i (offs_out[n_kept] = total).  Stops at not-yet, overrun, buf
// full, or max.
// Returns the status of the first unconsumed slot (0 burst full/buf full,
// -1 caught up, 1 overrun at that slot — caller resyncs).  *consumed_out =
// frags consumed (kept + filtered), *kept_out = kept, *filt_out = filtered.
FD_EXPORT int fd_ring_rx_burst(void* mc, const uint8_t* dc_data,
                               ulong_t chunk_sz, ulong_t want, ulong_t max,
                               int rr_cnt, int rr_idx, void* metas_out,
                               uint8_t* buf, int64_t buf_cap,
                               int64_t* offs_out, ulong_t* consumed_out,
                               ulong_t* kept_out, ulong_t* filt_out) {
  mcache_hdr* h = static_cast<mcache_hdr*>(mc);
  frag_meta* ring = mcache_ring(mc);
  ulong_t consumed = 0, kept = 0, filt = 0;
  int64_t used = 0;
  int rc = 0;
  offs_out[0] = 0;
  while (consumed < max) {
    ulong_t seq = want + consumed;
    frag_meta* m = ring + (seq & (h->depth - 1));
    ulong_t s0 = m->seq.load(std::memory_order_acquire);
    if (s0 != seq) {
      rc = (static_cast<int64_t>(s0 - seq) < 0) ? -1 : 1;
      break;
    }
    if (rr_cnt > 1 && (int)(seq % (ulong_t)rr_cnt) != rr_idx) {
      consumed++;
      filt++;
      continue;
    }
    frag_meta tmp;
    std::memcpy(&tmp, m, sizeof tmp);
    int64_t sz = tmp.sz;
    if (used + sz > buf_cap) {
      if (used == 0 && sz > buf_cap) {
        // frag wider than the whole rx buffer (buggy/hostile in-process
        // producer): consuming zero frags forever would wedge this input
        // permanently — drop it, count it as filtered (ADVICE r4).  But
        // first re-validate the seqlock: a producer lapping us mid-read
        // can tear sz, and that case must surface as an overrun/resync,
        // not a silent filtered skip (code-review r5)
        std::atomic_thread_fence(std::memory_order_acquire);
        if (m->seq.load(std::memory_order_relaxed) != seq) {
          rc = 1;
          break;
        }
        consumed++;
        filt++;
        continue;
      }
      rc = 0;  // buf full: stop cleanly
      break;
    }
    if (sz) std::memcpy(buf + used, dc_data + (ulong_t)tmp.chunk * chunk_sz,
                        (size_t)sz);
    std::atomic_thread_fence(std::memory_order_acquire);
    if (m->seq.load(std::memory_order_relaxed) != seq) {
      rc = 1;  // producer lapped us mid-copy
      break;
    }
    std::memcpy(static_cast<uint8_t*>(metas_out) + 32 * kept, &tmp,
                sizeof tmp);
    used += sz;
    kept++;
    offs_out[kept] = used;
    consumed++;
  }
  *consumed_out = consumed;
  *kept_out = kept;
  *filt_out = filt;
  return consumed == max ? 0 : rc;
}

// Publish n frags from a flat buffer: payload i = buf[starts[i],
// starts[i]+lens[i]), app sig sigs[i], ctl SOM|EOM (origin 0).
// Writes payloads into the dcache compact ring starting at *chunk_io
// (updated on return).  The CALLER must hold >= n credits — this function
// does no flow control.  Returns the last seq published.
FD_EXPORT ulong_t fd_ring_tx_burst(void* mc, uint8_t* dc_data,
                                   ulong_t chunk_sz, ulong_t chunk0,
                                   ulong_t wmark, const uint8_t* buf,
                                   const int64_t* starts,
                                   const int32_t* lens,
                                   const ulong_t* sigs, int n, uint_t tsorig,
                                   uint_t tspub, ulong_t* chunk_io) {
  ulong_t chunk = *chunk_io;
  ulong_t seq = 0;
  for (int i = 0; i < n; i++) {
    int64_t sz = lens[i];
    if (sz) std::memcpy(dc_data + chunk * chunk_sz, buf + starts[i],
                        (size_t)sz);
    // ctl = origin<<3 | SOM<<2 | EOM<<1 | ERR (fd_tango_base.h:76-99)
    seq = fd_mcache_publish(mc, sigs[i], (uint_t)chunk, (uint_t)sz,
                            0x6 /* SOM|EOM */, tsorig, tspub);
    // compact-ring advance (fd_dcache_compact_next)
    ulong_t chunks = ((ulong_t)sz + chunk_sz - 1) / chunk_sz;
    ulong_t next = chunk + chunks;
    chunk = next > wmark ? chunk0 : next;
  }
  *chunk_io = chunk;
  return seq;
}
