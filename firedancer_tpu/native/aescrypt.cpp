// Native QUIC packet-protection burst engine (waltz/quic.py fast path).
//
// Role: the reference runs QUIC packet protection in AES-NI C
// (src/waltz/quic/crypto/fd_quic_crypto_suites.c); our rx loop already
// moves packets in recvmmsg bursts but paid table-driven pure-Python
// AES-128-GCM + AES-ECB header protection per packet.  This file is the
// round-16 burst engine: one call takes a whole rx burst (buffer views +
// key-slot handles from a grow-only key registry), removes HP masks,
// decodes packet numbers, AEAD-decrypts in place in the rx buffers, and
// returns per-packet verdict/offset tables; a mirror call AEAD-encrypts +
// HP-masks a tx burst in place.
//
// Bit-identity contract with the Python fallback (tests enforce it):
//  * AES is the encrypt-direction T-table construction of ballet/aes.py;
//    GHASH is the GCM bit-reflected convention (both are mathematically
//    pinned, so "identical" is automatic once correct — RFC 9001 A vectors
//    pin both backends).
//  * decrypt mirrors waltz/quic.py::_unprotect exactly: the 16-byte HP
//    sample at pn_off+4 is clamped by the BUFFER length (not `end`); a
//    short sample or a tag mismatch fails the packet with ZERO buffer
//    mutation; success unmasks the first byte + pn bytes and decrypts the
//    payload in place.
//  * encrypt mirrors _build_packet: pn_len is always 4, AAD is
//    buf[0:pn_off+4], CTR from counter 2, tag at buf[pn_off+4+pt_len],
//    then the HP mask from the post-encrypt sample.
//  * packet-number reconstruction is RFC 9000 A.3 (== quic._decode_pn).
//
// C ABI (ctypes): flat parallel arrays, one entry per packet; buffers are
// passed as an array of raw addresses so Python hands over bytearrays
// without copying.

#include <cstdint>
#include <cstring>
#include <vector>

#define API extern "C" __attribute__((visibility("default")))

namespace {

// ------------------------------------------------------------------ AES-128

uint8_t SBOX[256];
uint32_t T0[256], T1[256], T2[256], T3[256];

uint8_t xtime(uint8_t a) {
  return (uint8_t)((a << 1) ^ ((a & 0x80) ? 0x1B : 0));
}

void build_aes_tables() {
  // GF(2^8) exp/log via generator 3 (poly 0x11B) — same derivation as
  // ballet/aes.py, no magic tables
  uint8_t exp[255], log[256];
  int p = 1;
  for (int i = 0; i < 255; i++) {
    exp[i] = (uint8_t)p;
    log[p] = (uint8_t)i;
    p ^= (p << 1) ^ ((p & 0x80) ? 0x11B : 0);
    p &= 0xFF;
  }
  for (int x = 0; x < 256; x++) {
    uint8_t inv = x ? exp[(255 - log[x]) % 255] : 0;
    uint8_t b = inv, s = 0x63;
    for (int i = 0; i < 4; i++) {
      b = (uint8_t)((b << 1) | (b >> 7));
      s ^= b;
    }
    SBOX[x] = (uint8_t)(s ^ inv);
  }
  for (int x = 0; x < 256; x++) {
    uint32_t s = SBOX[x];
    uint32_t t = ((uint32_t)xtime((uint8_t)s) << 24) | (s << 16) | (s << 8) |
                 (xtime((uint8_t)s) ^ s);
    T0[x] = t;
    T1[x] = (t >> 8) | (t << 24);
    T2[x] = (t >> 16) | (t << 16);
    T3[x] = (t >> 24) | (t << 8);
  }
}

const uint8_t RCON[10] = {0x01, 0x02, 0x04, 0x08, 0x10,
                          0x20, 0x40, 0x80, 0x1B, 0x36};

// expand a 16-byte key into 44 big-endian round words (AES-128 only: QUIC
// v1 packet protection and header protection keys are always 16 bytes)
void key_expand128(const uint8_t *key, uint32_t *w) {
  for (int i = 0; i < 4; i++)
    w[i] = ((uint32_t)key[4 * i] << 24) | ((uint32_t)key[4 * i + 1] << 16) |
           ((uint32_t)key[4 * i + 2] << 8) | key[4 * i + 3];
  for (int i = 4; i < 44; i++) {
    uint32_t t = w[i - 1];
    if (i % 4 == 0) {
      t = (t << 8) | (t >> 24);  // RotWord
      t = ((uint32_t)SBOX[(t >> 24) & 0xFF] << 24) |
          ((uint32_t)SBOX[(t >> 16) & 0xFF] << 16) |
          ((uint32_t)SBOX[(t >> 8) & 0xFF] << 8) | SBOX[t & 0xFF];
      t ^= (uint32_t)RCON[i / 4 - 1] << 24;
    }
    w[i] = w[i - 4] ^ t;
  }
}

void aes_encrypt_block(const uint32_t *rk, const uint8_t *in, uint8_t *out) {
  uint32_t s0 = (((uint32_t)in[0] << 24) | ((uint32_t)in[1] << 16) |
                 ((uint32_t)in[2] << 8) | in[3]) ^ rk[0];
  uint32_t s1 = (((uint32_t)in[4] << 24) | ((uint32_t)in[5] << 16) |
                 ((uint32_t)in[6] << 8) | in[7]) ^ rk[1];
  uint32_t s2 = (((uint32_t)in[8] << 24) | ((uint32_t)in[9] << 16) |
                 ((uint32_t)in[10] << 8) | in[11]) ^ rk[2];
  uint32_t s3 = (((uint32_t)in[12] << 24) | ((uint32_t)in[13] << 16) |
                 ((uint32_t)in[14] << 8) | in[15]) ^ rk[3];
  for (int r = 1; r < 10; r++) {
    uint32_t t0 = T0[(s0 >> 24) & 0xFF] ^ T1[(s1 >> 16) & 0xFF] ^
                  T2[(s2 >> 8) & 0xFF] ^ T3[s3 & 0xFF] ^ rk[4 * r];
    uint32_t t1 = T0[(s1 >> 24) & 0xFF] ^ T1[(s2 >> 16) & 0xFF] ^
                  T2[(s3 >> 8) & 0xFF] ^ T3[s0 & 0xFF] ^ rk[4 * r + 1];
    uint32_t t2 = T0[(s2 >> 24) & 0xFF] ^ T1[(s3 >> 16) & 0xFF] ^
                  T2[(s0 >> 8) & 0xFF] ^ T3[s1 & 0xFF] ^ rk[4 * r + 2];
    uint32_t t3 = T0[(s3 >> 24) & 0xFF] ^ T1[(s0 >> 16) & 0xFF] ^
                  T2[(s1 >> 8) & 0xFF] ^ T3[s2 & 0xFF] ^ rk[4 * r + 3];
    s0 = t0; s1 = t1; s2 = t2; s3 = t3;
  }
  uint32_t src[4] = {s0, s1, s2, s3};
  for (int c = 0; c < 4; c++) {
    out[4 * c + 0] = SBOX[(src[c] >> 24) & 0xFF];
    out[4 * c + 1] = SBOX[(src[(c + 1) & 3] >> 16) & 0xFF];
    out[4 * c + 2] = SBOX[(src[(c + 2) & 3] >> 8) & 0xFF];
    out[4 * c + 3] = SBOX[src[(c + 3) & 3] & 0xFF];
  }
  for (int c = 0; c < 4; c++) {
    uint32_t kb = rk[40 + c];
    out[4 * c + 0] ^= (kb >> 24) & 0xFF;
    out[4 * c + 1] ^= (kb >> 16) & 0xFF;
    out[4 * c + 2] ^= (kb >> 8) & 0xFF;
    out[4 * c + 3] ^= kb & 0xFF;
  }
}

// ------------------------------------------------------------------- GHASH
// GF(2^128), GCM bit-reflected convention; byte-table Horner like
// ballet/aes.py::_Ghash (256-entry H-multiple table per key + a shared
// key-independent x^8 reduction table).

struct u128 {
  uint64_t hi, lo;
};

inline u128 x128(u128 a, u128 b) { return {a.hi ^ b.hi, a.lo ^ b.lo}; }

inline u128 shr8(u128 v) {
  return {v.hi >> 8, (v.lo >> 8) | (v.hi << 56)};
}

u128 GHASH_RED[256];  // reduction of Z*x^8: the shifted-out low byte

u128 gmul_bit(u128 x, u128 y) {
  u128 z = {0, 0};
  u128 v = x;
  for (int i = 127; i >= 0; i--) {
    uint64_t bit = (i >= 64) ? (y.hi >> (i - 64)) & 1 : (y.lo >> i) & 1;
    if (bit) z = x128(z, v);
    uint64_t carry = v.lo & 1;
    v.lo = (v.lo >> 1) | (v.hi << 63);
    v.hi >>= 1;
    if (carry) v.hi ^= 0xE100000000000000ull;  // GCM R, top byte 0xE1
  }
  return z;
}

void build_ghash_red() {
  for (int b = 0; b < 256; b++) {
    u128 v = {0, (uint64_t)b};
    for (int i = 0; i < 8; i++) {
      uint64_t carry = v.lo & 1;
      v.lo = (v.lo >> 1) | (v.hi << 63);
      v.hi >>= 1;
      if (carry) v.hi ^= 0xE100000000000000ull;
    }
    GHASH_RED[b] = v;
  }
}

// table[b] = (b in the TOP byte position) * H; linear in b, so build the
// 8 single-bit entries bitwise and XOR-combine the other 248
void build_ghash_table(u128 h, u128 *table) {
  table[0] = {0, 0};
  for (int i = 0; i < 8; i++) {
    u128 x = {(uint64_t)(1u << i) << 56, 0};
    table[1u << i] = gmul_bit(x, h);
  }
  for (int b = 1; b < 256; b++)
    if (b & (b - 1))
      table[b] = x128(table[b & (b - 1)], table[b & -b]);
}

struct Ghash {
  const u128 *table;
  u128 acc;

  void update_block(const uint8_t *blk) {
    uint64_t bhi = 0, blo = 0;
    for (int i = 0; i < 8; i++) bhi = (bhi << 8) | blk[i];
    for (int i = 8; i < 16; i++) blo = (blo << 8) | blk[i];
    u128 z = {acc.hi ^ bhi, acc.lo ^ blo};
    // z * H, byte-at-a-time from the LOW byte upward (Horner)
    u128 a = {0, 0};
    for (int i = 0; i < 16; i++) {
      uint8_t byte = (uint8_t)(z.lo & 0xFF);
      z = shr8(z);
      if (i) {
        uint8_t low = (uint8_t)(a.lo & 0xFF);
        a = x128(shr8(a), GHASH_RED[low]);
      }
      if (byte) a = x128(a, table[byte]);
    }
    acc = a;
  }

  void update(const uint8_t *data, int64_t len) {
    int64_t full = len & ~15ll;
    for (int64_t i = 0; i < full; i += 16) update_block(data + i);
    if (len & 15) {
      uint8_t pad[16] = {0};
      memcpy(pad, data + full, (size_t)(len & 15));
      update_block(pad);
    }
  }
};

// ------------------------------------------------------------- key registry
// Grow-only chunked slab: slot handles stay stable forever (chunks are
// never reallocated), freed slots recycle through a free list.

struct KeySlot {
  uint32_t rk[44];     // AEAD round keys
  uint32_t hp_rk[44];  // header-protection round keys
  uint8_t iv[12];
  u128 ghash_tab[256];
  uint8_t used;
};

constexpr int kChunk = 256;
std::vector<KeySlot *> g_chunks;
std::vector<int64_t> g_free;
int64_t g_next = 0;
bool g_init = false;

KeySlot *slot_ptr(int64_t slot) {
  if (slot < 0 || slot >= g_next) return nullptr;
  KeySlot *k = &g_chunks[(size_t)(slot / kChunk)][slot % kChunk];
  return k->used ? k : nullptr;
}

// --------------------------------------------------------------- GCM pieces

void make_nonce(const uint8_t *iv, int64_t pn, uint8_t *nonce) {
  memcpy(nonce, iv, 12);
  for (int i = 0; i < 8; i++) nonce[11 - i] ^= (uint8_t)((pn >> (8 * i)) & 0xFF);
}

// tag = GHASH(aad, ct) ^ EK(nonce || 1)
void gcm_tag(const KeySlot *k, const uint8_t *nonce, const uint8_t *aad,
             int64_t aad_len, const uint8_t *ct, int64_t ct_len,
             uint8_t *tag) {
  Ghash g{k->ghash_tab, {0, 0}};
  g.update(aad, aad_len);
  g.update(ct, ct_len);
  uint8_t lens[16];
  uint64_t ab = (uint64_t)aad_len * 8, cb = (uint64_t)ct_len * 8;
  for (int i = 0; i < 8; i++) {
    lens[i] = (uint8_t)(ab >> (8 * (7 - i)));
    lens[8 + i] = (uint8_t)(cb >> (8 * (7 - i)));
  }
  g.update_block(lens);
  uint8_t y0[16], ek[16];
  memcpy(y0, nonce, 12);
  y0[12] = 0; y0[13] = 0; y0[14] = 0; y0[15] = 1;
  aes_encrypt_block(k->rk, y0, ek);
  for (int i = 0; i < 8; i++) {
    tag[i] = (uint8_t)((g.acc.hi >> (8 * (7 - i))) & 0xFF) ^ ek[i];
    tag[8 + i] = (uint8_t)((g.acc.lo >> (8 * (7 - i))) & 0xFF) ^ ek[8 + i];
  }
}

// CTR keystream XOR in place, counter starting at 2 (GCM payload counter)
void gcm_ctr_xor(const KeySlot *k, const uint8_t *nonce, uint8_t *data,
                 int64_t len) {
  uint8_t blk[16], ks[16];
  memcpy(blk, nonce, 12);
  uint32_t ctr = 2;
  for (int64_t off = 0; off < len; off += 16, ctr++) {
    blk[12] = (uint8_t)(ctr >> 24);
    blk[13] = (uint8_t)(ctr >> 16);
    blk[14] = (uint8_t)(ctr >> 8);
    blk[15] = (uint8_t)ctr;
    aes_encrypt_block(k->rk, blk, ks);
    int64_t n = len - off < 16 ? len - off : 16;
    for (int64_t i = 0; i < n; i++) data[off + i] ^= ks[i];
  }
}

// RFC 9000 A.3 packet-number reconstruction (== quic._decode_pn)
int64_t decode_pn(uint64_t truncated, int pn_len, int64_t expected) {
  int64_t win = 1ll << (pn_len * 8);
  int64_t half = win >> 1;
  int64_t candidate = (expected & ~(win - 1)) | (int64_t)truncated;
  if (candidate <= expected - half && candidate + win < (1ll << 62))
    return candidate + win;
  if (candidate > expected + half && candidate >= win)
    return candidate - win;
  return candidate;
}

void ensure_init() {
  if (!g_init) {
    build_aes_tables();
    build_ghash_red();
    g_init = true;
  }
}

thread_local std::vector<uint8_t> g_aad;

}  // namespace

// ------------------------------------------------------------------ C ABI

// Register one direction's packet-protection keys; returns a stable slot
// handle (or -1 on alloc failure).  aead_key/hp_key are 16 bytes, iv 12.
API int64_t fd_aescrypt_key_new(const uint8_t *aead_key, const uint8_t *iv,
                                const uint8_t *hp_key) {
  ensure_init();
  int64_t slot;
  if (!g_free.empty()) {
    slot = g_free.back();
    g_free.pop_back();
  } else {
    if (g_next % kChunk == 0) {
      KeySlot *c = new (std::nothrow) KeySlot[kChunk];
      if (!c) return -1;
      g_chunks.push_back(c);
    }
    slot = g_next++;
  }
  KeySlot *k = &g_chunks[(size_t)(slot / kChunk)][slot % kChunk];
  key_expand128(aead_key, k->rk);
  key_expand128(hp_key, k->hp_rk);
  memcpy(k->iv, iv, 12);
  uint8_t z[16] = {0}, hb[16];
  aes_encrypt_block(k->rk, z, hb);
  uint64_t hhi = 0, hlo = 0;
  for (int i = 0; i < 8; i++) hhi = (hhi << 8) | hb[i];
  for (int i = 8; i < 16; i++) hlo = (hlo << 8) | hb[i];
  build_ghash_table({hhi, hlo}, k->ghash_tab);
  k->used = 1;
  return slot;
}

API void fd_aescrypt_key_free(int64_t slot) {
  KeySlot *k = slot_ptr(slot);
  if (k) {
    k->used = 0;
    g_free.push_back(slot);
  }
}

API int64_t fd_aescrypt_key_cnt(void) {
  return g_next - (int64_t)g_free.size();
}

// Burst unprotect: per packet i, remove the HP mask, decode the packet
// number, and AEAD-decrypt in place.  Mirrors quic._unprotect: on failure
// (short sample, bad slot, tag mismatch) the buffer is untouched and
// ok[i]=0; on success buf[start] and the pn bytes are unmasked in place,
// the payload is plaintext at [pt_off, pt_off+pt_len), and ok[i]=1.
API int fd_aescrypt_decrypt_burst(
    const uint64_t *bufs, const int64_t *buf_len, const int64_t *start,
    const int64_t *pn_off, const int64_t *end, const int64_t *slots,
    const int64_t *expected, int n, int64_t *pn_out, int64_t *pt_off,
    int64_t *pt_len, uint8_t *ok) {
  ensure_init();
  for (int i = 0; i < n; i++) {
    ok[i] = 0;
    pn_out[i] = -1;
    pt_off[i] = 0;
    pt_len[i] = 0;
    const KeySlot *k = slot_ptr(slots[i]);
    uint8_t *buf = (uint8_t *)(uintptr_t)bufs[i];
    if (!k || !buf) continue;
    int64_t blen = buf_len[i], st = start[i], po = pn_off[i];
    int64_t en = end[i] < blen ? end[i] : blen;
    if (st < 0 || po < st + 1 || en < po) continue;
    // HP sample: buf[pn_off+4 : pn_off+20], clamped by the BUFFER length
    // exactly like the Python slice (not by `end`)
    if (po + 20 > blen) continue;  // sample short
    uint8_t mask[16];
    aes_encrypt_block(k->hp_rk, buf + po + 4, mask);
    uint8_t first =
        buf[st] ^ (mask[0] & ((buf[st] & 0x80) ? 0x0F : 0x1F));
    int pn_len = (first & 0x03) + 1;
    uint8_t pnb[4];
    uint64_t trunc = 0;
    for (int j = 0; j < pn_len; j++) {
      pnb[j] = buf[po + j] ^ mask[1 + j];
      trunc = (trunc << 8) | pnb[j];
    }
    int64_t pn = decode_pn(trunc, pn_len, expected[i]);
    int64_t ct_off = po + pn_len, ct_all = en - ct_off;
    if (ct_all < 16) continue;  // no room for the tag
    int64_t clen = ct_all - 16;
    // AAD = first | buf[start+1 : pn_off] | pn_bytes (unmasked header)
    int64_t aad_len = (po - st) + pn_len;
    if ((int64_t)g_aad.size() < aad_len) g_aad.resize((size_t)aad_len);
    uint8_t *aad = g_aad.data();
    aad[0] = first;
    memcpy(aad + 1, buf + st + 1, (size_t)(po - st - 1));
    memcpy(aad + (po - st), pnb, (size_t)pn_len);
    uint8_t nonce[12], want[16];
    make_nonce(k->iv, pn, nonce);
    gcm_tag(k, nonce, aad, aad_len, buf + ct_off, clen, want);
    uint8_t diff = 0;
    for (int j = 0; j < 16; j++) diff |= want[j] ^ buf[ct_off + clen + j];
    if (diff) continue;  // tag mismatch: buffer untouched
    buf[st] = first;
    memcpy(buf + po, pnb, (size_t)pn_len);
    gcm_ctr_xor(k, nonce, buf + ct_off, clen);
    pn_out[i] = pn;
    pt_off[i] = ct_off;
    pt_len[i] = clen;
    ok[i] = 1;
  }
  return 0;
}

// Burst protect: per packet i the buffer holds header | pn(4) | plaintext
// with 16 spare tag bytes after; pn_off is the header length.  Mirrors
// quic._build_packet: AAD = buf[0 : pn_off+4], CTR-encrypt the payload in
// place, write the tag, then HP-mask the first byte + 4 pn bytes from the
// post-encrypt sample at pn_off+4.
API int fd_aescrypt_encrypt_burst(const uint64_t *bufs, const int64_t *pn_off,
                                  const int64_t *pn, const int64_t *pt_len,
                                  const int64_t *slots, int n, uint8_t *ok) {
  ensure_init();
  for (int i = 0; i < n; i++) {
    ok[i] = 0;
    const KeySlot *k = slot_ptr(slots[i]);
    uint8_t *buf = (uint8_t *)(uintptr_t)bufs[i];
    if (!k || !buf) continue;
    int64_t po = pn_off[i], plen = pt_len[i];
    if (po < 1 || plen < 4) continue;  // tx payloads are padded to >= 4
    uint8_t nonce[12];
    make_nonce(k->iv, pn[i], nonce);
    uint8_t *pt = buf + po + 4;
    gcm_ctr_xor(k, nonce, pt, plen);
    gcm_tag(k, nonce, buf, po + 4, pt, plen, pt + plen);
    uint8_t mask[16];
    aes_encrypt_block(k->hp_rk, buf + po + 4, mask);
    buf[0] ^= mask[0] & ((buf[0] & 0x80) ? 0x0F : 0x1F);
    for (int j = 0; j < 4; j++) buf[po + j] ^= mask[1 + j];
    ok[i] = 1;
  }
  return 0;
}
