// Burst UDP packet engine (ref: src/waltz/xdp/fd_xsk.c role — the
// reference's kernel-bypass AF_XDP ring; portable equivalent here is
// recvmmsg/sendmmsg batched syscalls: one kernel crossing per burst
// instead of per packet, behind the same burst-aio contract as
// waltz/udpsock.py).
//
// C ABI (ctypes): flat arrays, one packet per fixed-size mtu slot.

#include <arpa/inet.h>
#include <cerrno>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#define API extern "C" __attribute__((visibility("default")))

namespace {
constexpr int kMaxBurst = 1024;
}

API int fd_pkteng_open(const char *bind_ip, int port, int rcvbuf) {
  int fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  if (rcvbuf > 0)
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf, sizeof rcvbuf);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, bind_ip, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (bind(fd, reinterpret_cast<sockaddr *>(&addr), sizeof addr) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  return fd;
}

API int fd_pkteng_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof addr;
  if (getsockname(fd, reinterpret_cast<sockaddr *>(&addr), &len) != 0)
    return -errno;
  return ntohs(addr.sin_port);
}

// Receive up to max_pkts datagrams in ONE recvmmsg syscall.
// buf: max_pkts slots of mtu bytes; lens/ips/ports: per-packet out arrays
// (ips/ports in host byte order). Returns packet count (0 if dry) or -errno.
API int fd_pkteng_rx_burst(int fd, unsigned char *buf, int mtu, int max_pkts,
                           unsigned int *lens, unsigned int *ips,
                           unsigned short *ports) {
  if (max_pkts > kMaxBurst) max_pkts = kMaxBurst;
  mmsghdr msgs[kMaxBurst];
  iovec iovs[kMaxBurst];
  sockaddr_in addrs[kMaxBurst];
  memset(msgs, 0, sizeof(mmsghdr) * static_cast<size_t>(max_pkts));
  for (int i = 0; i < max_pkts; i++) {
    iovs[i].iov_base = buf + static_cast<size_t>(i) * mtu;
    iovs[i].iov_len = static_cast<size_t>(mtu);
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
    msgs[i].msg_hdr.msg_name = &addrs[i];
    msgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  int n = recvmmsg(fd, msgs, static_cast<unsigned>(max_pkts), 0, nullptr);
  if (n < 0) {
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    return -errno;
  }
  int out = 0;
  for (int i = 0; i < n; i++) {
    // A datagram larger than the slot is truncated by the kernel and
    // flagged MSG_TRUNC per-message; passing it up as a complete packet
    // would hand parsers a silently-corrupted payload. Drop it (the slot
    // size is the wire MTU, so only over-MTU garbage lands here).
    if (msgs[i].msg_hdr.msg_flags & MSG_TRUNC) continue;
    if (out != i)
      memmove(buf + static_cast<size_t>(out) * mtu,
              buf + static_cast<size_t>(i) * mtu, msgs[i].msg_len);
    lens[out] = msgs[i].msg_len;
    ips[out] = ntohl(addrs[i].sin_addr.s_addr);
    ports[out] = ntohs(addrs[i].sin_port);
    out++;
  }
  return out;
}

// Send n_pkts datagrams in ONE sendmmsg syscall (best effort: returns the
// count the kernel accepted, which may be < n_pkts on backpressure).
API int fd_pkteng_tx_burst(int fd, const unsigned char *buf, int mtu,
                           int n_pkts, const unsigned int *lens,
                           const unsigned int *ips,
                           const unsigned short *ports) {
  if (n_pkts > kMaxBurst) n_pkts = kMaxBurst;
  mmsghdr msgs[kMaxBurst];
  iovec iovs[kMaxBurst];
  sockaddr_in addrs[kMaxBurst];
  memset(msgs, 0, sizeof(mmsghdr) * static_cast<size_t>(n_pkts));
  for (int i = 0; i < n_pkts; i++) {
    iovs[i].iov_base =
        const_cast<unsigned char *>(buf + static_cast<size_t>(i) * mtu);
    iovs[i].iov_len = lens[i];
    addrs[i].sin_family = AF_INET;
    addrs[i].sin_addr.s_addr = htonl(ips[i]);
    addrs[i].sin_port = htons(ports[i]);
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
    msgs[i].msg_hdr.msg_name = &addrs[i];
    msgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  int n = sendmmsg(fd, msgs, static_cast<unsigned>(n_pkts), 0);
  if (n < 0) {
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    return -errno;
  }
  return n;
}

API void fd_pkteng_close(int fd) { close(fd); }
