// Burst UDP packet engine (ref: src/waltz/xdp/fd_xsk.c role — the
// reference's kernel-bypass AF_XDP ring; portable equivalent here is
// recvmmsg/sendmmsg batched syscalls: one kernel crossing per burst
// instead of per packet, behind the same burst-aio contract as
// waltz/udpsock.py).
//
// C ABI (ctypes): flat arrays, one packet per fixed-size mtu slot.

#include <arpa/inet.h>
#include <cerrno>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#define API extern "C" __attribute__((visibility("default")))

namespace {
constexpr int kMaxBurst = 1024;
}

API int fd_pkteng_open(const char *bind_ip, int port, int rcvbuf) {
  int fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  if (rcvbuf > 0)
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf, sizeof rcvbuf);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, bind_ip, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (bind(fd, reinterpret_cast<sockaddr *>(&addr), sizeof addr) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  return fd;
}

API int fd_pkteng_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof addr;
  if (getsockname(fd, reinterpret_cast<sockaddr *>(&addr), &len) != 0)
    return -errno;
  return ntohs(addr.sin_port);
}

// Receive up to max_pkts datagrams in ONE recvmmsg syscall.
// buf: max_pkts slots of mtu bytes; lens/ips/ports: per-packet out arrays
// (ips/ports in host byte order). Returns packet count (0 if dry) or -errno.
API int fd_pkteng_rx_burst(int fd, unsigned char *buf, int mtu, int max_pkts,
                           unsigned int *lens, unsigned int *ips,
                           unsigned short *ports) {
  if (max_pkts > kMaxBurst) max_pkts = kMaxBurst;
  mmsghdr msgs[kMaxBurst];
  iovec iovs[kMaxBurst];
  sockaddr_in addrs[kMaxBurst];
  memset(msgs, 0, sizeof(mmsghdr) * static_cast<size_t>(max_pkts));
  for (int i = 0; i < max_pkts; i++) {
    iovs[i].iov_base = buf + static_cast<size_t>(i) * mtu;
    iovs[i].iov_len = static_cast<size_t>(mtu);
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
    msgs[i].msg_hdr.msg_name = &addrs[i];
    msgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  int n = recvmmsg(fd, msgs, static_cast<unsigned>(max_pkts), 0, nullptr);
  if (n < 0) {
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    return -errno;
  }
  int out = 0;
  for (int i = 0; i < n; i++) {
    // A datagram larger than the slot is truncated by the kernel and
    // flagged MSG_TRUNC per-message; passing it up as a complete packet
    // would hand parsers a silently-corrupted payload. Drop it (the slot
    // size is the wire MTU, so only over-MTU garbage lands here).
    if (msgs[i].msg_hdr.msg_flags & MSG_TRUNC) continue;
    if (out != i)
      memmove(buf + static_cast<size_t>(out) * mtu,
              buf + static_cast<size_t>(i) * mtu, msgs[i].msg_len);
    lens[out] = msgs[i].msg_len;
    ips[out] = ntohl(addrs[i].sin_addr.s_addr);
    ports[out] = ntohs(addrs[i].sin_port);
    out++;
  }
  return out;
}

// Send n_pkts datagrams in ONE sendmmsg syscall (best effort: returns the
// count the kernel accepted, which may be < n_pkts on backpressure).
API int fd_pkteng_tx_burst(int fd, const unsigned char *buf, int mtu,
                           int n_pkts, const unsigned int *lens,
                           const unsigned int *ips,
                           const unsigned short *ports) {
  if (n_pkts > kMaxBurst) n_pkts = kMaxBurst;
  mmsghdr msgs[kMaxBurst];
  iovec iovs[kMaxBurst];
  sockaddr_in addrs[kMaxBurst];
  memset(msgs, 0, sizeof(mmsghdr) * static_cast<size_t>(n_pkts));
  for (int i = 0; i < n_pkts; i++) {
    iovs[i].iov_base =
        const_cast<unsigned char *>(buf + static_cast<size_t>(i) * mtu);
    iovs[i].iov_len = lens[i];
    addrs[i].sin_family = AF_INET;
    addrs[i].sin_addr.s_addr = htonl(ips[i]);
    addrs[i].sin_port = htons(ports[i]);
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
    msgs[i].msg_hdr.msg_name = &addrs[i];
    msgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  int n = sendmmsg(fd, msgs, static_cast<unsigned>(n_pkts), 0);
  if (n < 0) {
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    return -errno;
  }
  return n;
}

API void fd_pkteng_close(int fd) { close(fd); }

// ---------------------------------------------------------------------------
// AF_PACKET TPACKET_V3 mmap'd RX ring — the kernel-bypass ingest tier
// (ref: src/waltz/xdp/fd_xsk.c AF_XDP rings; TPACKET_V3 is the portable
// cousin that works in unprivileged-NIC environments: the kernel DMA-fills
// mmap'd blocks and user space consumes them with ZERO per-packet syscalls,
// one block hand-back per ~hundreds of packets).  Full AF_XDP needs a
// driver-bound queue + BPF redirect (fd_xdp_redirect_prog role) which this
// container's virtual NIC cannot provide; the ring keeps the same
// burst-aio contract so an XDP backend can slot in behind it unchanged.

#include <linux/if_ether.h>
#include <linux/if_packet.h>
#include <linux/ip.h>
#include <linux/udp.h>
#include <net/if.h>
#include <poll.h>
#include <sys/mman.h>

namespace {

struct XRing {
  int fd;
  unsigned char *map;
  unsigned block_sz;
  unsigned block_cnt;
  unsigned cur;
};

}  // namespace

// Open an RX ring on `ifname`.  Returns an opaque handle (>0) or -errno.
API long long fd_xring_open(const char *ifname, int block_sz, int block_cnt,
                            int frame_sz) {
  int fd = socket(AF_PACKET, SOCK_RAW, htons(ETH_P_ALL));
  if (fd < 0) return -errno;
  int ver = TPACKET_V3;
  if (setsockopt(fd, SOL_PACKET, PACKET_VERSION, &ver, sizeof ver) != 0) {
    int e = errno; close(fd); return -e;
  }
  tpacket_req3 req{};
  req.tp_block_size = static_cast<unsigned>(block_sz);
  req.tp_block_nr = static_cast<unsigned>(block_cnt);
  req.tp_frame_size = static_cast<unsigned>(frame_sz);
  req.tp_frame_nr = req.tp_block_size / req.tp_frame_size * req.tp_block_nr;
  req.tp_retire_blk_tov = 10;  // ms: hand back partial blocks promptly
  if (setsockopt(fd, SOL_PACKET, PACKET_RX_RING, &req, sizeof req) != 0) {
    int e = errno; close(fd); return -e;
  }
  size_t map_sz = static_cast<size_t>(block_sz) * block_cnt;
  void *map = mmap(nullptr, map_sz, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_LOCKED, fd, 0);
  if (map == MAP_FAILED) {
    map = mmap(nullptr, map_sz, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (map == MAP_FAILED) { int e = errno; close(fd); return -e; }
  }
  sockaddr_ll sll{};
  sll.sll_family = AF_PACKET;
  sll.sll_protocol = htons(ETH_P_ALL);
  sll.sll_ifindex = static_cast<int>(if_nametoindex(ifname));
  if (sll.sll_ifindex == 0 || bind(fd, reinterpret_cast<sockaddr *>(&sll),
                                   sizeof sll) != 0) {
    int e = errno ? errno : ENODEV;
    munmap(map, map_sz); close(fd);
    return -e;
  }
  auto *r = new XRing{fd, static_cast<unsigned char *>(map),
                      static_cast<unsigned>(block_sz),
                      static_cast<unsigned>(block_cnt), 0};
  return reinterpret_cast<long long>(r);
}

API int fd_xring_poll(long long handle, int timeout_ms) {
  auto *r = reinterpret_cast<XRing *>(handle);
  pollfd p{r->fd, POLLIN | POLLERR, 0};
  return poll(&p, 1, timeout_ms);
}

// Drain ready blocks: extract IPv4/UDP payloads addressed to udp_port
// (0 = any), skipping the loopback OUTGOING duplicates.  Same out-array
// contract as fd_pkteng_rx_burst.  Returns packets extracted.
API int fd_xring_rx_burst(long long handle, unsigned char *buf, int mtu,
                          int max_pkts, unsigned int *lens,
                          unsigned int *ips, unsigned short *ports,
                          int udp_port) {
  auto *r = reinterpret_cast<XRing *>(handle);
  int out = 0;
  for (unsigned scanned = 0; scanned < r->block_cnt && out < max_pkts;
       scanned++) {
    auto *bd = reinterpret_cast<tpacket_block_desc *>(
        r->map + static_cast<size_t>(r->cur) * r->block_sz);
    if (!(bd->hdr.bh1.block_status & TP_STATUS_USER)) break;
    // blocks are consumed whole-or-not-at-all: releasing a block after a
    // mid-block capacity stop would hand its unread packets back to the
    // kernel and lose them.  (A lone over-capacity block when out==0 is
    // still taken, clamped — the caller's burst should exceed a block's
    // frame count.)
    if (out > 0
        && out + static_cast<int>(bd->hdr.bh1.num_pkts) > max_pkts)
      break;
    auto *hdr = reinterpret_cast<tpacket3_hdr *>(
        reinterpret_cast<unsigned char *>(bd)
        + bd->hdr.bh1.offset_to_first_pkt);
    for (unsigned i = 0; i < bd->hdr.bh1.num_pkts; i++) {
      auto *sll = reinterpret_cast<sockaddr_ll *>(
          reinterpret_cast<unsigned char *>(hdr)
          + TPACKET_ALIGN(sizeof(tpacket3_hdr)));
      const unsigned char *frame =
          reinterpret_cast<unsigned char *>(hdr) + hdr->tp_mac;
      unsigned snap = hdr->tp_snaplen;
      if (out < max_pkts && sll->sll_pkttype != PACKET_OUTGOING
          && snap >= sizeof(ethhdr) + sizeof(iphdr) + sizeof(udphdr)) {
        auto *eth = reinterpret_cast<const ethhdr *>(frame);
        if (eth->h_proto == htons(ETH_P_IP)) {
          auto *ip = reinterpret_cast<const iphdr *>(frame + sizeof(ethhdr));
          unsigned ihl = static_cast<unsigned>(ip->ihl) * 4u;
          // skip fragmented datagrams entirely (MF set or nonzero
          // offset): a non-first fragment has no UDP header, and a first
          // fragment's payload is incomplete
          bool fragmented = (ip->frag_off & htons(0x3FFF)) != 0;
          if (ip->version == 4 && ip->protocol == IPPROTO_UDP && !fragmented
              && snap >= sizeof(ethhdr) + ihl + sizeof(udphdr)) {
            auto *udp = reinterpret_cast<const udphdr *>(
                frame + sizeof(ethhdr) + ihl);
            unsigned udplen = ntohs(udp->len);
            unsigned avail = snap - sizeof(ethhdr) - ihl;
            if ((udp_port == 0 || ntohs(udp->dest) == udp_port)
                && udplen >= sizeof(udphdr) && udplen <= avail) {
              unsigned plen = udplen - sizeof(udphdr);
              if (plen <= static_cast<unsigned>(mtu)) {
                memcpy(buf + static_cast<size_t>(out) * mtu,
                       reinterpret_cast<const unsigned char *>(udp)
                           + sizeof(udphdr),
                       plen);
                lens[out] = plen;
                ips[out] = ntohl(ip->saddr);
                ports[out] = ntohs(udp->source);
                out++;
              }
            }
          }
        }
      }
      if (hdr->tp_next_offset == 0) break;
      hdr = reinterpret_cast<tpacket3_hdr *>(
          reinterpret_cast<unsigned char *>(hdr) + hdr->tp_next_offset);
    }
    // hand the block back to the kernel and advance
    bd->hdr.bh1.block_status = TP_STATUS_KERNEL;
    r->cur = (r->cur + 1) % r->block_cnt;
  }
  return out;
}

API void fd_xring_close(long long handle) {
  auto *r = reinterpret_cast<XRing *>(handle);
  munmap(r->map, static_cast<size_t>(r->block_sz) * r->block_cnt);
  close(r->fd);
  delete r;
}

// ---------------------------------------------------------------------------
// AF_XDP XSK rings (round 5; ref: src/waltz/xdp/fd_xsk.c rx/fill ring
// consume + fd_xsk_aio_recv).  Python owns the one-time setup (socket,
// umem, setsockopt ring sizes, mmaps, bind — waltz/xsk.py); these
// functions are the per-burst hot path over the mmap'd rings: consume RX
// descriptors with acquire/release ordering, parse eth/ipv4/udp in place,
// copy UDP payloads into the burst (buf, offs) contract, and recycle
// every frame into the fill ring — zero syscalls per packet.

struct XskRing {
  uint32_t *prod;   // kernel-producer / user-producer index (ring role)
  uint32_t *cons;
  uint8_t  *desc;
  uint32_t  size;   // entries (power of two)
};

static inline XskRing xskr(uint8_t *map, uint64_t prod_off, uint64_t cons_off,
                           uint64_t desc_off, uint32_t size) {
  return XskRing{(uint32_t *)(map + prod_off), (uint32_t *)(map + cons_off),
                 map + desc_off, size};
}

// Post n umem frame addrs into the fill ring; returns how many fit.
API int fd_xsk_fill(uint8_t *fq_map, uint64_t prod_off, uint64_t cons_off,
                    uint64_t desc_off, uint32_t size,
                    const uint64_t *addrs, int n) {
  XskRing fq = xskr(fq_map, prod_off, cons_off, desc_off, size);
  uint32_t prod = *fq.prod;                       // we are the producer
  uint32_t cons = __atomic_load_n(fq.cons, __ATOMIC_ACQUIRE);
  uint32_t free_slots = fq.size - (prod - cons);
  int cnt = n < (int)free_slots ? n : (int)free_slots;
  uint64_t *ring = (uint64_t *)fq.desc;
  for (int i = 0; i < cnt; i++)
    ring[(prod + i) & (fq.size - 1)] = addrs[i];
  __atomic_store_n(fq.prod, prod + cnt, __ATOMIC_RELEASE);
  return cnt;
}

// Consume up to max RX frames: UDP payloads land in buf with offs[i]
// boundaries (offs[n] = total), src ip/port per packet; every consumed
// frame address recycles straight into the fill ring.  Non-UDP frames
// (the redirect program only steers UDP, but be defensive) are dropped
// and still recycled.  Returns packets kept.
API int fd_xsk_rx_burst(uint8_t *rx_map, uint64_t rx_prod_off,
                        uint64_t rx_cons_off, uint64_t rx_desc_off,
                        uint32_t rx_size, uint8_t *fq_map,
                        uint64_t fq_prod_off, uint64_t fq_cons_off,
                        uint64_t fq_desc_off, uint32_t fq_size,
                        uint8_t *umem, uint64_t frame_sz, uint8_t *buf,
                        int64_t buf_cap, int64_t *offs, uint32_t *srcip,
                        uint16_t *srcport, uint16_t *dstport, int max) {
  XskRing rx = xskr(rx_map, rx_prod_off, rx_cons_off, rx_desc_off, rx_size);
  uint32_t prod = __atomic_load_n(rx.prod, __ATOMIC_ACQUIRE);
  uint32_t cons = *rx.cons;                       // we are the consumer
  uint32_t avail = prod - cons;
  if ((int)avail > max) avail = (uint32_t)max;
  if (avail > 256) avail = 256;  // recycle[] bound; callers loop for more

  uint64_t recycle[256];
  uint32_t nrec = 0;
  uint32_t processed = 0;
  int kept = 0;
  int64_t used = 0;
  offs[0] = 0;
  struct Desc { uint64_t addr; uint32_t len; uint32_t options; };
  Desc *ring = (Desc *)rx.desc;
  for (uint32_t i = 0; i < avail; i++) {
    Desc d = ring[(cons + i) & (rx.size - 1)];
    const uint8_t *p = umem + d.addr;
    uint32_t len = d.len;
    // eth(14) + ipv4 + udp(8); malformed/non-UDP frames are consumed
    // and recycled (drop), a frame that doesn't fit buf is NOT consumed
    // (retried next call) — consumed==recycled always, no frame leaks
    uint32_t ihl = (uint32_t)(p[14] & 0x0F) * 4;
    const uint8_t *udp = p + 14 + ihl;
    bool is_udp =
        len >= 14 + 20 + 8 && p[12] == 0x08 && p[13] == 0x00 &&
        ihl >= 20 && 14 + ihl + 8 <= len && p[14 + 9] == 17;
    uint32_t ulen = is_udp ? (((uint32_t)udp[4] << 8) | udp[5]) : 0;
    bool ok = is_udp && ulen >= 8 && 14 + ihl + ulen <= len;
    uint32_t paylen = ok ? ulen - 8 : 0;
    if (ok && used + paylen > buf_cap) break;  // not consumed; next call
    recycle[nrec++] = d.addr & ~(frame_sz - 1);
    processed++;
    if (!ok) continue;
    std::memcpy(buf + used, udp + 8, paylen);
    std::memcpy(&srcip[kept], p + 14 + 12, 4);         // src addr (BE)
    srcport[kept] = (uint16_t)(((uint32_t)udp[0] << 8) | udp[1]);
    dstport[kept] = (uint16_t)(((uint32_t)udp[2] << 8) | udp[3]);
    used += paylen;
    kept++;
    offs[kept] = used;
  }
  __atomic_store_n(rx.cons, cons + processed, __ATOMIC_RELEASE);
  if (nrec)
    fd_xsk_fill(fq_map, fq_prod_off, fq_cons_off, fq_desc_off, fq_size,
                recycle, (int)nrec);
  return kept;
}
