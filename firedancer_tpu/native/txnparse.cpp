// Batch transaction parser + dedup tcache (the verify tile's host data
// plane, in native code).
//
// Role: the per-txn host cost of the Python tile path (parse -> tcache
// query -> bucket fill) measured ~110 us/txn single-threaded — 3.6x the
// reference's whole verify tile budget (src/wiredancer/README.md:103:
// 30 Kps/core).  This module does the same work as a single C call per
// BURST: parse every payload with fd_txn_parse's validation rules
// (ref src/ballet/txn/fd_txn_parse.c:80-236), query/insert a tcache on
// the first-signature tag (ref src/tango/tcache/fd_tcache.h query/insert
// macros), and scatter message/signature/pubkey bytes straight into the
// verify bucket's numpy arrays.
//
// Validation is rule-identical to ballet/txn.py::parse (which is itself
// rule-identical to the reference); tests/test_txn.py diffs the two
// parsers over the corpus + fuzz inputs.
//
// C ABI (ctypes): flat arrays only.

#include <cstdint>
#include <cstdlib>
#include <cstring>

#define API extern "C" __attribute__((visibility("default")))

namespace {

// wire limits (ref src/ballet/txn/fd_txn.h:35-108)
constexpr int kSigSz = 64;
constexpr int kPubSz = 32;
constexpr int kBlockhashSz = 32;
constexpr int kSigMax = 127;
constexpr int kAcctMax = 128;
constexpr int kAddrLutMax = 127;
constexpr int kInstrMax = 64;
constexpr int kMtu = 1232;

// error codes (txn_err out array)
enum {
  kOk = 0,
  kErrParse = 1,    // any fd_txn_parse rule violation
  kErrTooLong = 2,  // message exceeds this bucket's maxlen (reroute)
  kErrDup = 3,      // tcache hit on first-sig tag
  kErrSigCap = 4,   // more sig lanes than one batch holds
};

struct Cursor {
  const uint8_t *p;
  int n;
  int i = 0;
  bool fail = false;

  bool need(int k) {
    if (k > n - i) fail = true;
    return !fail;
  }
  uint8_t u8() {
    if (!need(1)) return 0;
    return p[i++];
  }
  // compact-u16 varint (ref src/ballet/txn/fd_compact_u16.h): 1-3 bytes,
  // canonical encoding required (no overlong forms)
  int cu16() {
    if (!need(1)) return -1;
    uint32_t b0 = p[i++];
    if (!(b0 & 0x80)) return (int)b0;
    if (!need(1)) return -1;
    uint32_t b1 = p[i++];
    if (!(b1 & 0x80)) {
      if (b1 == 0) { fail = true; return -1; }  // overlong
      return (int)((b0 & 0x7F) | (b1 << 7));
    }
    if (!need(1)) return -1;
    uint32_t b2 = p[i++];
    if (b2 > 3 || b2 == 0) { fail = true; return -1; }  // >16 bits / overlong
    return (int)((b0 & 0x7F) | ((b1 & 0x7F) << 7) | (b2 << 14));
  }
};

// ------------------------------------------------------------------ tcache
// Open-addressed map + eviction ring, the fd_tcache contract: remembers
// the most recent `depth` distinct tags; query hits iff the tag is in the
// window.  Insert evicts the oldest ring entry from the map.

struct Tcache {
  uint64_t depth;
  uint64_t map_cnt;   // power of two, > 2*depth
  uint64_t ring_head; // next ring slot to overwrite
  uint64_t used;      // ring entries filled so far (< depth while warming)
  uint64_t *ring;     // (depth,)
  uint64_t *map;      // (map_cnt,) 0 = empty (tag 0 is mapped to 1)
};

inline uint64_t tag_hash(uint64_t t) {
  // fmix64 (splitmix finalizer) — same avalanche role as fd_tcache's
  // multiplicative hash
  t ^= t >> 33;
  t *= 0xFF51AFD7ED558CCDULL;
  t ^= t >> 33;
  t *= 0xC4CEB9FE1A85EC53ULL;
  t ^= t >> 33;
  return t;
}

// tag 0 is the null tag: never cached, never a hit (same contract as
// tango/tcache.py — callers with a real zero tag must remap it themselves)
bool tc_query(const Tcache *tc, uint64_t tag) {
  if (!tag) return false;
  uint64_t mask = tc->map_cnt - 1;
  uint64_t s = tag_hash(tag) & mask;
  while (tc->map[s]) {
    if (tc->map[s] == tag) return true;
    s = (s + 1) & mask;
  }
  return false;
}

void tc_map_remove(Tcache *tc, uint64_t tag) {
  // Robin-hood-free deletion with backward-shift (keeps probe chains
  // intact without tombstones)
  uint64_t mask = tc->map_cnt - 1;
  uint64_t s = tag_hash(tag) & mask;
  while (tc->map[s] && tc->map[s] != tag) s = (s + 1) & mask;
  if (!tc->map[s]) return;
  uint64_t hole = s;
  uint64_t j = s;
  for (;;) {
    j = (j + 1) & mask;
    uint64_t t = tc->map[j];
    if (!t) break;
    uint64_t home = tag_hash(t) & mask;
    // can t move into the hole?  yes iff hole is cyclically within
    // [home, j)
    uint64_t d_hole = (hole - home) & mask;
    uint64_t d_j = (j - home) & mask;
    if (d_hole <= d_j) {
      tc->map[hole] = t;
      hole = j;
    }
  }
  tc->map[hole] = 0;
}

void tc_insert(Tcache *tc, uint64_t tag) {
  if (!tag) return;
  if (tc_query(tc, tag)) return;
  if (tc->used == tc->depth) {
    tc_map_remove(tc, tc->ring[tc->ring_head]);
  } else {
    tc->used++;
  }
  tc->ring[tc->ring_head] = tag;
  tc->ring_head = (tc->ring_head + 1) % tc->depth;
  uint64_t mask = tc->map_cnt - 1;
  uint64_t s = tag_hash(tag) & mask;
  while (tc->map[s]) s = (s + 1) & mask;
  tc->map[s] = tag;
}

}  // namespace

API void *fd_tcache_new(uint64_t depth) {
  uint64_t map_cnt = 1;
  while (map_cnt < 4 * depth) map_cnt <<= 1;
  Tcache *tc = new Tcache();
  tc->depth = depth;
  tc->map_cnt = map_cnt;
  tc->ring_head = 0;
  tc->used = 0;
  tc->ring = (uint64_t *)calloc(depth, 8);
  tc->map = (uint64_t *)calloc(map_cnt, 8);
  // Pre-fault both regions NOW: calloc maps lazily, so without this every
  // first-touch slot in the (randomly probed) map costs a page fault IN
  // THE HOT PATH — ~2 us each, dominating query/insert until the whole
  // map has been walked (measured ~3 us/txn of fault cost on a cold
  // depth 2^21 tcache).  Same move as the reference's pre-touched
  // workspace pages (fd_wksp): pay the commit at creation, keep the
  // steady state fault-free.  volatile stores, one per 4 KiB page —
  // a plain memset(0) after calloc is dead-store-eliminated (calloc
  // already guarantees zeros) and faults nothing.
  constexpr uint64_t kPerPage = 4096 / 8;
  volatile uint64_t *vr = tc->ring;
  for (uint64_t i = 0; i < depth; i += kPerPage) vr[i] = 0;
  volatile uint64_t *vm = tc->map;
  for (uint64_t i = 0; i < map_cnt; i += kPerPage) vm[i] = 0;
  return tc;
}

API void fd_tcache_delete(void *h) {
  Tcache *tc = (Tcache *)h;
  free(tc->ring);
  free(tc->map);
  delete tc;
}

API int fd_tcache_query(void *h, uint64_t tag) {
  return tc_query((Tcache *)h, tag) ? 1 : 0;
}

API void fd_tcache_insert(void *h, uint64_t tag) {
  tc_insert((Tcache *)h, tag);
}

API void fd_tcache_insert_batch(void *h, const uint64_t *tags, int n) {
  Tcache *tc = (Tcache *)h;
  for (int i = 0; i < n; i++) tc_insert(tc, tags[i]);
}

// Batched FD_TCACHE_INSERT: dup[i] = 1 iff tags[i] was already present
// (including an earlier index of this same batch); non-dups are inserted.
API void fd_tcache_insert_batch_dedup(void *h, const uint64_t *tags, int n,
                                      uint8_t *dup) {
  Tcache *tc = (Tcache *)h;
  for (int i = 0; i < n; i++) {
    dup[i] = tc_query(tc, tags[i]) ? 1 : 0;
    if (!dup[i]) tc_insert(tc, tags[i]);
  }
}

// Batched QUERY (no insert): hit[i] = 1 iff tags[i] is in the window.
// The packed-wire verify path pre-filters rows with this before device
// dispatch; tags are inserted only after verify passes (same rationale as
// the query-only tcache in fd_txn_parse_batch).
API void fd_tcache_query_batch(void *h, const uint64_t *tags, int n,
                               uint8_t *hit) {
  Tcache *tc = (Tcache *)h;
  for (int i = 0; i < n; i++) hit[i] = tc_query(tc, tags[i]) ? 1 : 0;
}

// -------------------------------------------------------------- batch parse

// Parse + dedup + bucket-fill a burst of serialized txns.
//
//   buf/offs:   concatenated payloads; payload i = buf[offs[i], offs[i+1])
//   n:          number of payloads
//   tcache:     optional dedup window (nullptr = no dedup); QUERY-only —
//               tags are inserted by the harvest path after verify passes
//               (inserting pre-verify would let a mangled copy poison the
//               window and block the valid retransmission)
//   maxlen:     bucket message width; longer messages get kErrTooLong
//   cap/lane0:  bucket lane capacity and first free lane
//   msgs/lens/sigs/pubs: the bucket arrays ((cap,maxlen) u8, (cap,) i32,
//               (cap,64) u8, (cap,32) u8) — one lane PER SIGNATURE,
//               message replicated across a txn's lanes
//   txn_lane0/txn_nsig/txn_tag/txn_err: per-txn outputs; nsig=0 for
//               dropped txns (err says why)
//
// Returns the number of txns CONSUMED: parsing stops (without consuming)
// at the first txn whose sig lanes don't fit the remaining capacity, so
// the caller flushes the bucket and re-enters with the tail.
// Strided core: msgs/sigs/pubs rows land at their pointer + lane*stride,
// so the bucket can be ONE packed (cap, maxlen+100) row-interleaved
// buffer (msgs | sigs | pubs | lens-le32 per row) — the DMA-blob shape
// the device dispatch uploads with a single transfer.  lens_bytes
// (nullable, stride msgs_stride) mirrors each lane's msg_len as 4 LE
// bytes into the packed row; the contiguous int32 lens array stays for
// host-side bookkeeping either way.
static int parse_batch_impl(
    const uint8_t *buf, const int64_t *offs, int n, void *tcache, int maxlen,
    int cap, int lane0, uint8_t *msgs, int64_t msgs_stride, int32_t *lens,
    uint8_t *sigs, int64_t sigs_stride, uint8_t *pubs, int64_t pubs_stride,
    uint8_t *lens_bytes, int32_t *txn_lane0, int32_t *txn_nsig,
    uint64_t *txn_tag, int32_t *txn_err, int32_t *lanes_used_out) {
  Tcache *tc = (Tcache *)tcache;
  int lane = lane0;
  int t = 0;
  for (; t < n; t++) {
    txn_lane0[t] = -1;
    txn_nsig[t] = 0;
    txn_tag[t] = 0;
    const uint8_t *p = buf + offs[t];
    int sz = (int)(offs[t + 1] - offs[t]);
    if (sz > kMtu) { txn_err[t] = kErrParse; continue; }
    Cursor c{p, sz};

    int sig_cnt = c.u8();
    if (c.fail || sig_cnt < 1 || sig_cnt > kSigMax) {
      txn_err[t] = kErrParse; continue;
    }
    if (!c.need(kSigSz * sig_cnt)) { txn_err[t] = kErrParse; continue; }
    int sig_off = c.i;
    c.i += kSigSz * sig_cnt;

    int msg_off = c.i;
    int b0 = c.u8();
    if (c.fail) { txn_err[t] = kErrParse; continue; }
    if (b0 & 0x80) {
      if ((b0 & 0x7F) != 0) { txn_err[t] = kErrParse; continue; }  // != v0
      int hdr_sig = c.u8();
      if (c.fail || hdr_sig != sig_cnt) { txn_err[t] = kErrParse; continue; }
    } else {
      if (b0 != sig_cnt) { txn_err[t] = kErrParse; continue; }
    }
    bool is_v0 = (b0 & 0x80) != 0;

    int ro_signed = c.u8();
    if (c.fail || ro_signed >= sig_cnt) { txn_err[t] = kErrParse; continue; }
    int ro_unsigned = c.u8();
    if (c.fail) { txn_err[t] = kErrParse; continue; }

    int acct_cnt = c.cu16();
    if (c.fail || acct_cnt < sig_cnt || acct_cnt > kAcctMax ||
        sig_cnt + ro_unsigned > acct_cnt) {
      txn_err[t] = kErrParse; continue;
    }
    if (!c.need(kPubSz * acct_cnt)) { txn_err[t] = kErrParse; continue; }
    int acct_off = c.i;
    c.i += kPubSz * acct_cnt;
    if (!c.need(kBlockhashSz)) { txn_err[t] = kErrParse; continue; }
    c.i += kBlockhashSz;

    int instr_cnt = c.cu16();
    if (c.fail || instr_cnt > kInstrMax) { txn_err[t] = kErrParse; continue; }
    if (!c.need(3 * instr_cnt)) { txn_err[t] = kErrParse; continue; }
    if (acct_cnt <= (instr_cnt ? 1 : 0)) { txn_err[t] = kErrParse; continue; }

    int max_acct = 0;
    bool bad = false;
    for (int k = 0; k < instr_cnt && !bad; k++) {
      int prog = c.u8();
      int nacc = c.cu16();
      if (c.fail || !c.need(nacc)) { bad = true; break; }
      for (int a = 0; a < nacc; a++)
        if (p[c.i + a] > max_acct) max_acct = p[c.i + a];
      c.i += nacc;
      int dsz = c.cu16();
      if (c.fail || !c.need(dsz)) { bad = true; break; }
      c.i += dsz;
      if (prog <= 0 || prog >= acct_cnt) { bad = true; break; }
    }
    if (bad || c.fail) { txn_err[t] = kErrParse; continue; }

    int adtl = 0;
    if (is_v0) {
      int lut_cnt = c.cu16();
      if (c.fail || lut_cnt > kAddrLutMax || !c.need(34 * lut_cnt)) {
        txn_err[t] = kErrParse; continue;
      }
      for (int k = 0; k < lut_cnt && !bad; k++) {
        if (!c.need(kPubSz)) { bad = true; break; }
        c.i += kPubSz;
        int wr = c.cu16();
        if (c.fail || !c.need(wr)) { bad = true; break; }
        c.i += wr;
        int ro = c.cu16();
        if (c.fail || !c.need(ro)) { bad = true; break; }
        c.i += ro;
        if (wr > kAcctMax - acct_cnt || ro > kAcctMax - acct_cnt ||
            wr + ro < 1) { bad = true; break; }
        adtl += wr + ro;
      }
      if (bad || c.fail) { txn_err[t] = kErrParse; continue; }
    }
    if (c.i != sz || acct_cnt + adtl > kAcctMax ||
        max_acct >= acct_cnt + adtl) {
      txn_err[t] = kErrParse; continue;
    }

    // ---- rules passed; route + dedup + fill
    int msg_len = sz - msg_off;
    if (msg_len > maxlen) { txn_err[t] = kErrTooLong; continue; }
    if (sig_cnt > cap) { txn_err[t] = kErrSigCap; continue; }
    uint64_t tag;
    memcpy(&tag, p + sig_off, 8);
    txn_tag[t] = tag;
    if (tc && tc_query(tc, tag)) { txn_err[t] = kErrDup; continue; }
    if (lane + sig_cnt > cap) break;  // bucket full: caller flushes

    txn_err[t] = kOk;
    txn_lane0[t] = lane;
    txn_nsig[t] = sig_cnt;
    for (int s = 0; s < sig_cnt; s++, lane++) {
      memcpy(msgs + (int64_t)lane * msgs_stride, p + msg_off, msg_len);
      if (msg_len < maxlen)
        memset(msgs + (int64_t)lane * msgs_stride + msg_len, 0,
               maxlen - msg_len);
      lens[lane] = msg_len;
      if (lens_bytes) {
        int32_t ml32 = msg_len;
        memcpy(lens_bytes + (int64_t)lane * msgs_stride, &ml32, 4);
      }
      memcpy(sigs + (int64_t)lane * sigs_stride, p + sig_off + s * kSigSz,
             kSigSz);
      memcpy(pubs + (int64_t)lane * pubs_stride, p + acct_off + s * kPubSz,
             kPubSz);
    }
  }
  *lanes_used_out = lane - lane0;
  return t;
}

API int fd_txn_parse_batch(
    const uint8_t *buf, const int64_t *offs, int n, void *tcache, int maxlen,
    int cap, int lane0, uint8_t *msgs, int32_t *lens, uint8_t *sigs,
    uint8_t *pubs, int32_t *txn_lane0, int32_t *txn_nsig, uint64_t *txn_tag,
    int32_t *txn_err, int32_t *lanes_used_out) {
  return parse_batch_impl(buf, offs, n, tcache, maxlen, cap, lane0, msgs,
                          maxlen, lens, sigs, kSigSz, pubs, kPubSz, nullptr,
                          txn_lane0, txn_nsig, txn_tag, txn_err,
                          lanes_used_out);
}

// Packed-bucket form: one (cap, row_stride) row-interleaved buffer with
// msgs at +0, sigs at +maxlen, pubs at +maxlen+64, lens-le32 at
// +maxlen+96 (row_stride >= maxlen + 100).
API int fd_txn_parse_batch_packed(
    const uint8_t *buf, const int64_t *offs, int n, void *tcache, int maxlen,
    int cap, int lane0, uint8_t *bucket, int64_t row_stride, int32_t *lens,
    int32_t *txn_lane0, int32_t *txn_nsig, uint64_t *txn_tag,
    int32_t *txn_err, int32_t *lanes_used_out) {
  return parse_batch_impl(buf, offs, n, tcache, maxlen, cap, lane0, bucket,
                          row_stride, lens, bucket + maxlen, row_stride,
                          bucket + maxlen + 64, row_stride,
                          bucket + maxlen + 96, txn_lane0, txn_nsig, txn_tag,
                          txn_err, lanes_used_out);
}
