"""firedancer-tpu: a TPU-native rebuild of the Firedancer validator's capabilities.

Layer map (mirrors the reference's layering, SURVEY.md §1, rebuilt TPU-first):

  utils/    — logging, config, histograms, rng           (ref: src/util)
  ops/      — batched device crypto math in JAX/Pallas   (ref: src/ballet)
  ballet/   — host-side protocol codecs (txn parse, ...) (ref: src/ballet)
  tango/    — lock-free shm ring fabric (C++ + ctypes)   (ref: src/tango)
  disco/    — tile runtime: topology, mux loop, tiles    (ref: src/disco)
  models/   — flagship pipelines (the batch sig-verifier)
  parallel/ — device mesh / shard_map scale-out          (ref: round-robin tiles)
"""

__version__ = "0.1.0"
