"""BLAKE3 — the account-hash function, TPU-first.

Reference role: src/ballet/blake3/ (vendored C/asm BLAKE3 with SSE/AVX
dispatch) — Solana hashes every modified account with BLAKE3
(src/flamenco/runtime/fd_hashes.c), so epoch/slot account-delta hashing is
a wide, batchable workload: thousands of small messages per slot.

TPU mapping:
  * `blake3_batch` — device path: a batch of variable-length messages up to
    one 1024-byte chunk each (the overwhelming majority of accounts).  The
    16-block chunk walk is a lax.scan over vmapped compressions; all 32-bit
    word math rides the VPU int32 lanes, batch on the 128-wide axis.
  * `blake3` — host golden/tree path (numpy): full multi-chunk binary tree
    for arbitrarily long inputs (left subtree = largest power-of-two number
    of chunks < total, per the BLAKE3 spec).  Device-side multi-chunk tree
    reduction is future work (vmap over chunks + log-depth parent folds).

Correctness oracle: the official BLAKE3 test vectors
(github.com/BLAKE3-team/BLAKE3/test_vectors) in tests/test_blake3.py.
"""

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32

IV = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)

_PERM = np.array([2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8])

CHUNK_START = 1
CHUNK_END = 2
PARENT = 4
ROOT = 8

CHUNK_LEN = 1024
BLOCK_LEN = 64

# schedule[r] = word indices for round r (apply _PERM r times)
_SCHEDULE = np.zeros((7, 16), dtype=np.int32)
_SCHEDULE[0] = np.arange(16)
for _r in range(1, 7):
    _SCHEDULE[_r] = _SCHEDULE[_r - 1][_PERM]

# G applications per round: (a, b, c, d, mx_slot, my_slot)
_G_COLS = [(0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15)]
_G_DIAG = [(0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14)]


# --------------------------------------------------------------------------
# host (numpy) implementation — golden model + multi-chunk tree

def _rotr32(x, n):
    return ((x >> np.uint32(n)) | (x << np.uint32(32 - n))) & np.uint32(0xFFFFFFFF)


def _compress_words_np(st, block_words):
    """Run the 7 rounds over a prepared 16-word state; returns final state."""

    def g(a, b, c, d, mx, my):
        with np.errstate(over="ignore"):
            st[a] = st[a] + st[b] + mx
            st[d] = _rotr32(st[d] ^ st[a], 16)
            st[c] = st[c] + st[d]
            st[b] = _rotr32(st[b] ^ st[c], 12)
            st[a] = st[a] + st[b] + my
            st[d] = _rotr32(st[d] ^ st[a], 8)
            st[c] = st[c] + st[d]
            st[b] = _rotr32(st[b] ^ st[c], 7)

    for r in range(7):
        m = block_words[_SCHEDULE[r]]
        for i, (a, b, c, d) in enumerate(_G_COLS):
            g(a, b, c, d, m[2 * i], m[2 * i + 1])
        for i, (a, b, c, d) in enumerate(_G_DIAG):
            g(a, b, c, d, m[8 + 2 * i], m[8 + 2 * i + 1])
    return st


def _compress_np(cv, block_words, counter, block_len, flags):
    st = np.zeros(16, dtype=np.uint32)
    st[0:8] = cv
    st[8:12] = IV[0:4]
    st[12] = counter & 0xFFFFFFFF
    st[13] = (counter >> 32) & 0xFFFFFFFF
    st[14] = block_len
    st[15] = flags
    full = _compress_words_np(st, block_words)
    return full[0:8] ^ full[8:16]


def _compress_xof_np(cv, block_words, counter, block_len, flags):
    """Full 64-byte output form of the compression (for extended output)."""
    st = np.zeros(16, dtype=np.uint32)
    st[0:8] = cv
    st[8:12] = IV[0:4]
    st[12] = counter & 0xFFFFFFFF
    st[13] = (counter >> 32) & 0xFFFFFFFF
    st[14] = block_len
    st[15] = flags
    full = _compress_words_np(st, block_words)
    lo = full[0:8] ^ full[8:16]
    hi = full[8:16] ^ cv
    return np.concatenate([lo, hi])


def _chunk_blocks(chunk: bytes):
    """Yield (words, block_len, flags_sans_root) for each block of a chunk."""
    n_blocks = max(1, (len(chunk) + BLOCK_LEN - 1) // BLOCK_LEN)
    for i in range(n_blocks):
        blk = chunk[i * BLOCK_LEN : (i + 1) * BLOCK_LEN]
        blen = len(blk)
        words = np.frombuffer(blk + b"\0" * (BLOCK_LEN - blen), dtype="<u4")
        flags = (CHUNK_START if i == 0 else 0) | (
            CHUNK_END if i == n_blocks - 1 else 0
        )
        yield words, blen, flags


def _chunk_cv_np(chunk: bytes, counter: int) -> np.ndarray:
    cv = IV.copy()
    for words, blen, flags in _chunk_blocks(chunk):
        cv = _compress_np(cv, words, counter, blen, flags)
    return cv


def _tree_cv_np(data: bytes, chunk0: int) -> np.ndarray:
    """Chaining value of a non-root subtree."""
    n_chunks = max(1, (len(data) + CHUNK_LEN - 1) // CHUNK_LEN)
    if n_chunks == 1:
        return _chunk_cv_np(data, chunk0)
    # left subtree: largest power of two strictly less than n_chunks
    left_chunks = 1 << ((n_chunks - 1).bit_length() - 1)
    lcv = _tree_cv_np(data[: left_chunks * CHUNK_LEN], chunk0)
    rcv = _tree_cv_np(data[left_chunks * CHUNK_LEN :], chunk0 + left_chunks)
    block = np.concatenate([lcv, rcv])
    return _compress_np(IV.copy(), block, 0, BLOCK_LEN, PARENT)


def _root_node_np(data: bytes):
    """The root output node (cv_in, block_words, block_len, flags_sans_root):
    the deferred final compression, re-runnable with an output counter for
    extended (XOF) output."""
    n_chunks = max(1, (len(data) + CHUNK_LEN - 1) // CHUNK_LEN)
    if n_chunks == 1:
        cv = IV.copy()
        blocks = list(_chunk_blocks(data))
        for words, blen, flags in blocks[:-1]:
            cv = _compress_np(cv, words, 0, blen, flags)
        words, blen, flags = blocks[-1]
        return cv, words, blen, flags
    left_chunks = 1 << ((n_chunks - 1).bit_length() - 1)
    lcv = _tree_cv_np(data[: left_chunks * CHUNK_LEN], 0)
    rcv = _tree_cv_np(data[left_chunks * CHUNK_LEN :], left_chunks)
    return IV.copy(), np.concatenate([lcv, rcv]), BLOCK_LEN, PARENT


def blake3(data: bytes, out_len: int = 32) -> bytes:
    """Host BLAKE3 of arbitrary-length data with extended (XOF) output.

    out_len=32 is the plain hash; larger requests re-run the root
    compression with an incrementing output-block counter (64 bytes per
    block) — needed by lthash (2048-byte digests, ballet/lthash.py)."""
    cv, words, blen, flags = _root_node_np(data)
    out = b""
    t = 0
    while len(out) < out_len:
        blk = _compress_xof_np(cv, words, t, blen, flags | ROOT)
        out += blk.astype("<u4").tobytes()
        t += 1
    return out[:out_len]


# --------------------------------------------------------------------------
# device (JAX) implementation — batch of single-chunk messages

def _compress_jax(cv, m, counter_lo, counter_hi, block_len, flags):
    """Batched compression: cv (B,8), m (B,16), rest (B,) u32 (the 64-bit
    chunk counter rides as two u32 words — jax x64 stays off)."""
    B = cv.shape[0]
    iv = jnp.broadcast_to(jnp.asarray(IV[0:4], dtype=_U32), (B, 4))
    st = jnp.concatenate(
        [
            cv,
            iv,
            counter_lo.astype(_U32)[:, None],
            counter_hi.astype(_U32)[:, None],
            block_len.astype(_U32)[:, None],
            flags.astype(_U32)[:, None],
        ],
        axis=1,
    )

    def rotr(x, n):
        return (x >> _U32(n)) | (x << _U32(32 - n))

    def g(st, a, b, c, d, mx, my):
        sa, sb, sc, sd = st[:, a], st[:, b], st[:, c], st[:, d]
        sa = sa + sb + mx
        sd = rotr(sd ^ sa, 16)
        sc = sc + sd
        sb = rotr(sb ^ sc, 12)
        sa = sa + sb + my
        sd = rotr(sd ^ sa, 8)
        sc = sc + sd
        sb = rotr(sb ^ sc, 7)
        return st.at[:, a].set(sa).at[:, b].set(sb).at[:, c].set(sc).at[:, d].set(sd)

    sched = jnp.asarray(_SCHEDULE)

    def round_body(r, st):
        mm = m[:, sched[r]]
        for i, (a, b, c, d) in enumerate(_G_COLS):
            st = g(st, a, b, c, d, mm[:, 2 * i], mm[:, 2 * i + 1])
        for i, (a, b, c, d) in enumerate(_G_DIAG):
            st = g(st, a, b, c, d, mm[:, 8 + 2 * i], mm[:, 8 + 2 * i + 1])
        return st

    st = jax.lax.fori_loop(0, 7, round_body, st)
    return st[:, 0:8] ^ st[:, 8:16]


def blake3_batch(msgs: jax.Array, lens: jax.Array) -> jax.Array:
    """BLAKE3-256 of a batch of single-chunk messages.

    msgs: (B, P) uint8, P <= 1024 and a multiple of 64, zero-padded.
    lens: (B,) int32 true lengths (0 <= len <= P).
    Returns (B, 32) uint8 digests.  Jit/vmap/pjit friendly; the batch axis
    shards cleanly for multi-chip account hashing (data parallel, no
    cross-item communication).
    """
    B, P = msgs.shape
    assert P % BLOCK_LEN == 0 and P <= CHUNK_LEN
    n_slots = P // BLOCK_LEN
    # view as little-endian u32 words: (B, n_slots, 16)
    w = (
        msgs.reshape(B, n_slots, 16, 4).astype(_U32)
        * jnp.asarray([1, 1 << 8, 1 << 16, 1 << 24], dtype=_U32)
    ).sum(axis=3, dtype=_U32)

    lens = lens.astype(jnp.int32)
    n_blocks = jnp.maximum(1, (lens + BLOCK_LEN - 1) // BLOCK_LEN)
    last = n_blocks - 1
    zero = jnp.zeros((B,), dtype=_U32)  # single-chunk: counter is 0

    def body(cv, i):
        active = i < n_blocks
        blen = jnp.clip(lens - i * BLOCK_LEN, 0, BLOCK_LEN)
        flags = (
            jnp.where(i == 0, CHUNK_START, 0)
            | jnp.where(i == last, CHUNK_END | ROOT, 0)
        ).astype(_U32)
        out = _compress_jax(cv, w[:, i], zero, zero, blen.astype(_U32), flags)
        cv = jnp.where(active[:, None], out[:, 0:8], cv)
        return cv, None

    cv0 = jnp.broadcast_to(jnp.asarray(IV, dtype=_U32), (B, 8))
    cv, _ = jax.lax.scan(body, cv0, jnp.arange(n_slots, dtype=jnp.int32))
    # serialize little-endian
    out = jnp.stack(
        [(cv >> _U32(8 * k)) & _U32(0xFF) for k in range(4)], axis=2
    )  # (B, 8, 4)
    return out.reshape(B, 32).astype(jnp.uint8)
