"""Batched ed25519 signature verification on TPU.

The TPU analogue of fd_ed25519_verify / fd_ed25519_verify_batch_single_msg
(reference: src/ballet/ed25519/fd_ed25519_user.c:135-311), with two
deliberate interface upgrades for the batched pipeline:

  * per-item pass/fail BITS instead of the reference's fail-fast batch
    return (the verify tile needs per-txn outcomes; SURVEY.md §7.3)
  * batch width is the array's leading axis (thousands), not MAX=16

Acceptance rules are consensus-identical to the reference (and to Agave's
dalek 2.x + verify_strict usage):

  1. S canonical: 0 <= S < L, else reject          (fd_ed25519_user.c:158-161)
  2. A', R decompress per RFC; non-canonical y accepted
  3. A' or R of small order (<= 8): reject          (fd_ed25519_user.c:200-206)
  4. k = SHA-512(R || A || M) reduced mod L
  5. accept iff [S]B + [k](-A') == R (projective eq, no cofactor mul)
"""

import hashlib
import os
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import curve25519 as cv
from . import f25519 as fe
from . import scalar25519 as sc
from . import sha512 as sh

L = sc.L
P = fe.P

_PALLAS_BLK = 128  # best-measured block for the signed/T-skip kernel
# (tools/exp_r3_dsm.py: blk=128 beats 256 by ~25% — the smaller live set
# pipelines better through VMEM)


def _pallas_ok(batch: int) -> bool:
    """Use the Pallas dsm kernel when lowering to a real TPU and the batch
    tiles evenly.  CPU (tests, dryrun_multichip) keeps the XLA path —
    Mosaic has no CPU backend and interpret mode is orders slower."""
    if os.environ.get("FDTPU_NO_PALLAS"):
        return False
    if batch % 128:
        return False
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except RuntimeError:
        return False


def _decompress_checked(b, use_pallas: bool, blk: int):
    """(ok, point): decompress + small-order rejection on the selected
    backend (shared by the strict and rlc paths)."""
    if use_pallas:
        from . import curve_pallas as cpal

        ok, small, pt = cpal.decompress(b, blk=blk)
        return ok & ~small, pt
    ok, pt = cv.decompress(b)
    return ok & ~cv.is_small_order_affine(pt), pt


def _sha512_k(pre, lens, batch: int, use_pallas: bool):
    """k = SHA-512 digest on the selected backend (the Pallas kernel needs
    batch % (8*128) == 0 for its sublane packing)."""
    if use_pallas and batch % (8 * 128) == 0:
        from . import sha512_pallas as shp

        return shp.sha512(pre, lens)
    return sh.sha512(pre, lens)


def _compressed_r_check(qx, qy, qz, r_bytes, ok_y=None, parsed_r=None):
    """Accept iff Q == the point R's bytes encode, with fd_ed25519's
    R-side semantics, WITHOUT decompressing R (round 4: the R sqrt chain
    was ~27 ms of the 92 ms strict budget at 32k).

    Equivalences to the reference's decompress-then-compare, case by case:
      * non-canonical y (>= p): accepted — comparison is mod p
        (fe.eq canonicalizes), matching frombytes
      * R not on the curve (u/v non-residue): NO curve point has that y,
        and Q is a curve point, so the y compare fails — same reject
      * x = 0 with sign bit set: sgn(0) = 0 != 1 — same reject
      * small-order R: the 8-torsion points have exactly 5 distinct y
        values {0, 1, -1, +-y8}; y membership (mod p) == smallness, since
        y determines x up to sign and both signs stay in the subgroup
      * otherwise: curve points are equal iff same y and same x-parity
        (x != 0 ensured above: x and p-x differ in parity for odd p)
    Verified bit-exact against the real Wycheproof/CCTV/malleability
    corpora (tests/test_ed25519_real_corpora.py).

    The affine conversion uses ONE tree-shaped batch inversion (~3 muls
    per lane + one pow chain amortized over the batch).  When the
    projective y-compare already ran in-kernel (the Pallas tail), pass
    ok_y and qy=None; otherwise qy is compared here.  parsed_r reuses a
    caller's (y_r, sign_r, small) triple instead of re-deriving it
    (ADVICE r4: the Pallas path parsed R twice)."""
    y_r, sign_r, small = (parsed_r if parsed_r is not None
                          else _parse_r_bytes(r_bytes))
    z_ok = ~fe.is_zero(qz)
    one = jnp.zeros_like(qz).at[0].set(1)
    zi = fe.batch_inv(jnp.where(z_ok[None, :], qz, one))
    x_aff = fe.mul(qx, zi)
    if ok_y is None:
        ok_y = fe.eq(fe.mul(qy, zi), y_r)
    return (z_ok & ~small & ok_y & (fe.sgn(x_aff) == sign_r))


def _parse_r_bytes(r_bytes):
    """R's encoded y (canonical limbs), sign bit, and the 8-torsion
    y-membership smallness bit — one canonicalization pass."""
    yc = fe.canonical(fe.from_bytes(r_bytes))   # sign bit masked, mod p
    sign_r = (r_bytes[:, 31] >> 7).astype(jnp.uint32)
    small = jnp.all(yc == 0, axis=0)
    for v in (1, fe.P - 1, cv._ORDER8_Y0 % fe.P, cv._ORDER8_Y1 % fe.P):
        limbs = fe.const(v, yc.ndim)
        small = small | jnp.all(yc == limbs.astype(yc.dtype), axis=0)
    return yc, sign_r, small


def verify_batch(msgs, msg_len, sigs, pubkeys):
    """Verify a batch of detached ed25519 signatures.

    Args:
      msgs:    uint8 (batch, maxlen) — messages, zero-padded
      msg_len: int32 (batch,)        — true message lengths
      sigs:    uint8 (batch, 64)     — R || S
      pubkeys: uint8 (batch, 32)

    Returns: bool (batch,) pass/fail bits.
    """
    r_bytes = sigs[:, :32]
    s_bytes = sigs[:, 32:]
    batch, maxlen = msgs.shape

    use_pallas = _pallas_ok(batch)
    blk = _PALLAS_BLK

    if use_pallas and not os.environ.get("FDTPU_NO_FUSED"):
        from . import curve_pallas as cpal

        # FUSED tail (round 5): decompress(A) + reduce/recode + dsm +
        # y-compare in ONE kernel — A's planes and the scalar windows
        # never round-trip HBM between stages, one launch instead of
        # three.  ok already folds ok_a/small_a/ok_s/ok_y; the XLA tail
        # adds z!=0, small-order R and the x-parity bit.
        pre = jnp.concatenate([r_bytes, pubkeys, msgs], axis=1)
        k_digest = _sha512_k(
            pre, msg_len.astype(jnp.int32) + 64, batch, use_pallas)
        parsed_r = _parse_r_bytes(r_bytes)
        ok_k, qx, qz = cpal.verify_tail_fused(
            pubkeys, s_bytes, k_digest, parsed_r[0], blk=blk)
        return _compressed_r_check(qx, None, qz, r_bytes, ok_y=ok_k,
                                   parsed_r=parsed_r)

    ok_a, a_pt = _decompress_checked(pubkeys, use_pallas, blk)

    # k = SHA-512(R || A || M) mod L
    pre = jnp.concatenate([r_bytes, pubkeys, msgs], axis=1)
    k_digest = _sha512_k(
        pre, msg_len.astype(jnp.int32) + 64, batch, use_pallas)

    if use_pallas:
        from . import curve_pallas as cpal

        # split-kernel path (FDTPU_NO_FUSED: the round-4 layout, kept for
        # A/B measurement): one VMEM-resident pass does S-canonicity +
        # digest mod L + signed window recode for both scalars
        ok_s, wins = cpal.reduce_recode(s_bytes, k_digest, blk=blk)
        parsed_r = _parse_r_bytes(r_bytes)
        ok_y, qx, qz = cpal.dsm_tail_q(wins, a_pt, parsed_r[0], blk=blk)
        ok_eq = _compressed_r_check(qx, None, qz, r_bytes, ok_y=ok_y,
                                    parsed_r=parsed_r)
    else:
        ok_s = sc.is_canonical(s_bytes)
        k_limbs = sc.reduce_512(k_digest)
        s_windows = cv.scalar_windows(s_bytes)
        k_windows = sc.limbs_to_windows(k_limbs)
        q = cv.double_scalar_mul_base(s_windows, k_windows, cv.neg(a_pt))
        ok_eq = _compressed_r_check(q.X, q.Y, q.Z, r_bytes)

    return ok_s & ok_a & ok_eq


def verify_batch_rlc(msgs, msg_len, sigs, pubkeys, z_bytes, m: int = 8):
    """Random-linear-combination batch verification (one bit for the whole
    batch) — the high-throughput path.

    Checks  [Σ z_i s_i]B == Σ [z_i]R_i + Σ [z_i k_i]A_i  with host-supplied
    random 128-bit z_i, via one lane-parallel MSM (cv.msm).  If every
    per-sig equation holds the combined one does; a forged sig survives only
    if the z draw lands in a ~2^-125 bad set (the standard batch-verify
    soundness argument, as in ed25519-dalek's verify_batch).

    Consensus semantics: the check is COFACTORLESS, exactly like the per-sig
    path (no [8] multiply), so a batch containing only honestly-valid sigs
    passes; any batch this rejects must be re-checked per-sig to get exact
    consensus-identical bits (SigVerifier does that fallback).  A True from
    here implies every sig passes fd_ed25519_verify semantics (w.h.p.).

    Args are as verify_batch plus z_bytes: uint8 (batch, 16) — fresh
    unpredictable randomness per call (host CSPRNG).

    Returns (all_ok: bool scalar, prechecks: bool (batch,)).
    """
    r_bytes = sigs[:, :32]
    s_bytes = sigs[:, 32:]
    batch = msgs.shape[0]

    use_pallas = _pallas_ok(batch) and batch % (m * 128) == 0
    blk = _PALLAS_BLK
    ok_a, a_pt = _decompress_checked(pubkeys, use_pallas, blk)
    ok_r, r_pt = _decompress_checked(r_bytes, use_pallas, blk)

    # k_i = SHA-512(R||A||M) mod L;  w_i = z_i * k_i;  c = Σ z_i * s_i
    pre_img = jnp.concatenate([r_bytes, pubkeys, msgs], axis=1)
    digest = _sha512_k(pre_img, msg_len.astype(jnp.int32) + 64, batch,
                       use_pallas)

    # scalar chain stays XLA on BOTH backends: the Pallas transcription
    # (cpal.rlc_recode) measured SLOWER at 32k (106 vs 60 ms) — its
    # per-(1,blk)-row list ops waste 7/8 of each VPU tile, while XLA
    # vectorizes the same chain across the full batch (r4 finding,
    # docs/perf_ceiling.md)
    ok_s = sc.is_canonical(s_bytes)
    k_limbs = sc.reduce_512(digest)
    z_limbs = sc.bytes_to_limbs(z_bytes, 11)          # 128-bit -> 11 limbs
    s_limbs = sc.bytes_to_limbs(s_bytes, 22)
    w_limbs = sc.mul_mod_l(k_limbs, z_limbs)           # (22, batch)
    c_limbs = sc.sum_mod_l(sc.mul_mod_l(s_limbs, z_limbs), axis=0)
    w_windows = sc.limbs_to_windows(w_limbs)           # (64, batch)
    z_windows = sc.limbs_to_windows(
        jnp.concatenate([z_limbs, jnp.zeros_like(z_limbs[:11])],
                        axis=0))[:32]
    if use_pallas:
        from . import curve_pallas as cpal

        # round-6 select-redesign lever (signed digits + packed 16-bit
        # limb planes); default stays legacy pending the on-chip A/B
        # verdict (docs/perf_ceiling.md round 6, tools/exp_r6_rlc_select)
        sel = os.environ.get("FDTPU_RLC_SELECT", "legacy")
        acc_a = cpal.msm(w_windows, cv.neg(a_pt), m=m, nwin=64, select=sel)
        acc_r = cpal.msm(z_windows, cv.neg(r_pt), m=m, nwin=32, select=sel)
    else:
        acc_a = cv.msm(w_windows, cv.neg(a_pt), m=m, nwin=64)
        acc_r = cv.msm(z_windows, cv.neg(r_pt), m=m, nwin=32)

    pre = ok_s & ok_a & ok_r
    # Q = [c]B - Σ[w_i]A_i - Σ[z_i]R_i ; all sigs valid => Q == identity
    base = cv.scalar_mul_base(sc.limbs_to_windows(c_limbs)[:, None])
    q = cv.add(cv.add(acc_a, acc_r),
               cv.Point(*(t[:, 0] for t in base)))
    is_id = fe.is_zero(q.X) & fe.eq(q.Y, q.Z)
    return jnp.all(pre) & is_id, pre


def _halve_scalar_host(k: int) -> tuple[int, int]:
    """Antipa-style rational decomposition of a mod-L scalar (host
    python-int half-gcd): returns (u, v) with  u == k*v (mod L),
    0 <= u < 2^127, 0 < |v| <= ~2^126.  The extended Euclidean chain on
    (L, k) stopped at the first remainder below sqrt(L); the invariant
    r_i == k*t_i (mod L) holds at every step."""
    r0, r1 = sc.L, k % sc.L
    t0, t1 = 0, 1
    while r1 >= (1 << 127):
        q = r0 // r1
        r0, r1 = r1, r0 - q * r1
        t0, t1 = t1, t0 - q * t1
    return r1, t1


def _divstep_halve_host(k: int) -> tuple[int, int]:
    """Host transcription of sc.halve_scalar: the SAME (u, v) pair the
    device divstep emits, step for step (tests/test_scalar_divstep.py
    pins the equivalence).  The euclid pair from _halve_scalar_host is
    equally valid for honest signatures, but antipa acceptance of a
    torsion-defective forgery depends on v's 2-adic valuation — so the
    degraded-mode CPU fallback must reproduce THIS pair, not euclid's,
    to stay bit-identical to the active device graph."""
    n1 = sc.DIVSTEP_ITERS
    f, g = sc.L, (pow(2, n1, sc.L) * (k % sc.L)) % sc.L
    bf, bg, delta = 0, 1, 1
    for _ in range(n1):
        if delta > 0 and g & 1:
            delta, f, g, bf, bg = 1 - delta, g, (g - f) >> 1, 2 * bg, bg - bf
        else:
            b = g & 1
            delta, f, g, bf, bg = (1 + delta, f, (g + b * f) >> 1,
                                   2 * bf, bg + b * bf)

    def nrm(a, b):
        return max(abs(a), abs(b))

    F, G = (f, bf), (g, bg)
    for _ in range(sc.LAGRANGE_ITERS):
        if nrm(*F) < nrm(*G):
            F, G = G, F
        t = min(max(0, nrm(*F).bit_length() - nrm(*G).bit_length()), 31)
        sG = (G[0] << t, G[1] << t)
        Pc = (F[0] - sG[0], F[1] - sG[1])
        Mc = (F[0] + sG[0], F[1] + sG[1])
        C = Pc if nrm(*Pc) <= nrm(*Mc) else Mc
        if nrm(*C) < nrm(*F):
            F = C
    u, v = F if nrm(*F) <= nrm(*G) else G
    if u < 0:
        u, v = -u, -v
    return u, v


def _int_windows(vals, nwin: int) -> np.ndarray:
    """Python ints -> uint32 (nwin, batch) 4-bit windows, low first."""
    out = np.zeros((nwin, len(vals)), np.uint32)
    for b, v in enumerate(vals):
        for i in range(nwin):
            out[i, b] = (v >> (4 * i)) & 0xF
    return out


def verify_batch_antipa(msgs, msg_len, sigs, pubkeys):
    """Strict per-sig verify via Antipa halved scalars, fully device
    resident (round 9; flag-selectable via [verify] mode = antipa).

    k = H(R,A,M) mod L is decomposed ON DEVICE as k == u/v (mod L) with
    u, |v| < 2^128 by sc.halve_scalar (a fixed 250-iteration
    Bernstein-Yang divstep plus a 24-round branchless binary-Lagrange
    polish — no host round-trip, zero per-signature host work).  The
    check  [S]B - [k]A - R == 0  times v becomes
    [vS mod L]B + [u](-A) + [|v|](R~) == identity   (R~ = -R if v > 0
    else R) — the variable chain runs 32 windows (128 doubles) instead
    of 64 (256), at the cost of decompressing R (eliminated in round 4
    for the strict path) and a second var table.

    Semantics vs verify_batch: multiplying the equation by v is
    TORSION-LAX — a forged sig whose defect is an 8-torsion point of
    order dividing v passes here but fails strict (cofactorless
    semantics are already lax there, but the bits are not guaranteed
    identical on adversarial torsion cases; the enumerated cases live
    in tests/test_ed25519_antipa.py).  Honest-signature and
    corrupted-signature bits match verify_batch."""
    r_bytes = sigs[:, :32]
    s_bytes = sigs[:, 32:]
    batch = int(msgs.shape[0])

    ok_a, a_pt = cv.decompress(pubkeys)
    ok_a = ok_a & ~cv.is_small_order_affine(a_pt)
    ok_r, r_pt = cv.decompress(r_bytes)          # the Antipa payback cost
    _, _, small_r = _parse_r_bytes(r_bytes)
    ok_s = sc.is_canonical(s_bytes)

    pre = jnp.concatenate([r_bytes, pubkeys, msgs], axis=1)
    k_limbs = sc.reduce_512(
        _sha512_k(pre, msg_len.astype(jnp.int32) + 64, batch, False))

    # in-kernel halving: u == v*k (mod L), u and |v| inside 32 windows
    u_limbs, av_limbs, v_pos = sc.halve_scalar(k_limbs)
    s_limbs = sc.bytes_to_limbs(s_bytes, 22)
    c_limbs = sc.mul_mod_l(s_limbs, av_limbs)    # |v|*S mod L
    c_limbs = jnp.where(v_pos[None, :], c_limbs, sc.neg_mod_l(c_limbs))
    u_wins = sc.limbs_to_windows(u_limbs)[:32]
    av_wins = sc.limbs_to_windows(av_limbs)[:32]
    c_wins = sc.limbs_to_windows(c_limbs)

    r_neg = cv.neg(r_pt)
    r_eff = cv.Point(*(jnp.where(v_pos[None, :], n, p)
                       for n, p in zip(r_neg, r_pt)))
    chain = cv.double_scalar_mul_halved(
        u_wins, av_wins, cv.neg(a_pt), r_eff, nwin=32)
    base = cv.scalar_mul_base(c_wins)
    q = cv.add(chain, base)
    return ok_s & ok_a & ok_r & ~small_r & cv.is_identity(q)


# Packed-blob row layout — THE single definition (the native parser's
# fd_txn_parse_batch_packed, the pipeline's packed buckets, SigVerifier's
# packed dispatch and the AOT store all build against this):
# one uint8 row per lane = msgs[0:ml] | sig 64 | pubkey 32 | msg_len
# le-int32 4, row width ml + PACKED_EXTRA.
PACKED_EXTRA = 100


def verify_blob(blob, maxlen: int, ml: int | None = None):
    """verify_batch over a packed row-interleaved blob (ml = packed
    message width; messages re-pad to maxlen on device when trimmed)."""
    ml = maxlen if ml is None else ml
    b = blob.shape[0]
    m = blob[:, :ml]
    if ml < maxlen:
        m = jnp.pad(m, ((0, 0), (0, maxlen - ml)))
    s = blob[:, ml:ml + 64]
    p = blob[:, ml + 64:ml + 96]
    ln = jax.lax.bitcast_convert_type(
        blob[:, ml + 96:ml + 100], jnp.int32).reshape(b)
    return verify_batch(m, ln, s, p)


def verify_blob_antipa(blob, maxlen: int, ml: int | None = None):
    """verify_batch_antipa over the same packed row layout as
    verify_blob — the antipa-mode packed dispatch / AOT graph."""
    ml = maxlen if ml is None else ml
    b = blob.shape[0]
    m = blob[:, :ml]
    if ml < maxlen:
        m = jnp.pad(m, ((0, 0), (0, maxlen - ml)))
    s = blob[:, ml:ml + 64]
    p = blob[:, ml + 64:ml + 96]
    ln = jax.lax.bitcast_convert_type(
        blob[:, ml + 96:ml + 100], jnp.int32).reshape(b)
    return verify_batch_antipa(m, ln, s, p)


def verify_batch_single_msg(msg, sigs, pubkeys):
    """All signatures over one shared message (the reference's batch shape,
    fd_ed25519_user.c:231: a Solana txn's sigs all cover the same payload)."""
    batch = sigs.shape[0]
    msgs = jnp.broadcast_to(msg[None, :], (batch, msg.shape[0]))
    lens = jnp.full((batch,), msg.shape[0], dtype=jnp.int32)
    return verify_batch(msgs, lens, sigs, pubkeys)


_VERIFY_ONE = None
_VERIFY_ONE_MAXLEN = 1280  # covers every signed control-plane payload:
                           # crds values (41 + body <= 1232), repair
                           # requests (49), vote txn messages (<= 1232)


def verify_one(sig: bytes, msg: bytes, pub: bytes) -> bool:
    """Single-item verify for control-plane protocols (gossip crds values,
    repair requests, precompile instructions): one shared jitted
    (1, 1280) verifier compiled lazily per process (the persistent xla
    cache makes later processes instant)."""
    global _VERIFY_ONE
    if len(msg) > _VERIFY_ONE_MAXLEN or len(sig) != 64 or len(pub) != 32:
        return False
    first_call = _VERIFY_ONE is None
    if first_call:
        from ..utils import xla_cache
        xla_cache.enable()
        _VERIFY_ONE = jax.jit(verify_batch)
        t0 = time.perf_counter_ns()
    out = _VERIFY_ONE(
        jnp.asarray(np.frombuffer(
            msg.ljust(_VERIFY_ONE_MAXLEN, b"\0"), np.uint8)[None, :]),
        jnp.asarray(np.array([len(msg)], dtype=np.int32)),
        jnp.asarray(np.frombuffer(sig, np.uint8)[None, :]),
        jnp.asarray(np.frombuffer(pub, np.uint8)[None, :]))
    res = bool(np.asarray(out)[0])
    if first_call:
        # the first dispatch pays the jit trace+compile (or xla-cache
        # load); surface it in the shared compile-event registry
        from ..disco import trace as _trace
        _trace.record_compile(("verify_one", 1, _VERIFY_ONE_MAXLEN),
                              time.perf_counter_ns() - t0)
    return res


# ------------------------------------------------------------------ host side
# Key generation and signing are control-plane operations (the validator signs
# through the isolated sign tile, one item at a time — ref src/disco/keyguard);
# python-int host code is the right tool, device batching buys nothing.


def keypair_from_seed(seed: bytes):
    """seed (32B) -> (public_key bytes, secret scalar int, prefix bytes).
    (ref fd_ed25519_public_from_private)"""
    assert len(seed) == 32
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    pub = _scalar_mul_base_host(a)
    return _compress_host(pub), a, h[32:]


def sign(seed: bytes, msg: bytes) -> bytes:
    """Single-item host signer (ref fd_ed25519_sign)."""
    pub, a, prefix = keypair_from_seed(seed)
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = _compress_host(_scalar_mul_base_host(r))
    k = int.from_bytes(hashlib.sha512(R + pub + msg).digest(), "little") % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def _decompress_host(b: bytes):
    """Host point decompress; returns extended coords or None (ref
    fd_ed25519_point_frombytes semantics: non-canonical y accepted)."""
    enc = int.from_bytes(b, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    y %= P
    u = (y * y - 1) % P
    v = (cv.D * y * y + 1) % P
    x = u * pow(v, 3, P) * pow(u * pow(v, 7, P), (P - 5) // 8, P)
    # candidate root of x^2 = u/v; fix up by sqrt(-1) if needed
    if (v * x * x - u) % P != 0:
        x = x * pow(2, (P - 1) // 4, P) % P
        if (v * x * x - u) % P != 0:
            return None
    x %= P
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y, 1, x * y % P)


def _is_small_order_host(p) -> bool:
    q = p
    for _ in range(3):
        q = _pt_add_host(q, q)  # [8]P
    X, Y, Z, _ = q
    return X % P == 0  # identity or the order-2 point


def verify_one_host(sig: bytes, msg: bytes, pub: bytes) -> bool:
    """Single-item host verify (python ints) for control-plane checks where
    spinning up the jitted verifier isn't worth it (x509 self-signatures,
    TLS CertificateVerify).  Same acceptance rules — and same (sig, msg,
    pub) argument order — as verify_one."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    a = _decompress_host(pub)
    r = _decompress_host(sig[:32])
    if a is None or r is None:
        return False
    if _is_small_order_host(a) or _is_small_order_host(r):
        return False
    k = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % L
    neg_a = (P - a[0], a[1], a[2], P - a[3])
    q = _pt_add_host(_scalar_mul_base_host(s), _scalar_mul_host(k, neg_a))
    # q == r in projective coords (r has Z=1)
    Xq, Yq, Zq, _ = q
    Xr, Yr, _, _ = r
    return (Xq - Xr * Zq) % P == 0 and (Yq - Yr * Zq) % P == 0


def verify_one_host_antipa(sig: bytes, msg: bytes, pub: bytes) -> bool:
    """Host twin of the verify_batch_antipa device graph, bit for bit:
    same prechecks as verify_one_host, then the halved equation
    [vS mod L]B + [u](-A) + [|v|](R~) == identity with (u, v) from the
    divstep host model — including its torsion laxity.  This is the
    degraded-mode fallback for antipa-mode verifiers (GuardedVerifier's
    contract is fidelity to the ACTIVE device graph, not to strict)."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    a = _decompress_host(pub)
    r = _decompress_host(sig[:32])
    if a is None or r is None:
        return False
    if _is_small_order_host(a) or _is_small_order_host(r):
        return False
    k = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % L
    u, v = _divstep_halve_host(k)
    c = (v * s) % L
    neg_a = (P - a[0], a[1], a[2], P - a[3])
    r_eff = r if v < 0 else (P - r[0], r[1], r[2], P - r[3])
    q = _pt_add_host(
        _scalar_mul_base_host(c),
        _pt_add_host(_scalar_mul_host(u, neg_a),
                     _scalar_mul_host(abs(v), r_eff)))
    X, Y, Z, _ = q
    return X % P == 0 and (Y - Z) % P == 0


def _scalar_mul_host(s: int, p):
    q = (0, 1, 1, 0)
    while s > 0:
        if s & 1:
            q = _pt_add_host(q, p)
        p = _pt_add_host(p, p)
        s >>= 1
    return q


def _pt_add_host(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    Bv = (Y1 + X1) * (Y2 + X2) % P
    Cc = 2 * T1 * T2 * cv.D % P
    Dd = 2 * Z1 * Z2 % P
    E, F, G, H = (Bv - A) % P, (Dd - Cc) % P, (Dd + Cc) % P, (Bv + A) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def _scalar_mul_base_host(s: int):
    q = (0, 1, 1, 0)
    p = (cv.BASE_X, cv.BASE_Y, 1, cv.BASE_X * cv.BASE_Y % P)
    while s > 0:
        if s & 1:
            q = _pt_add_host(q, p)
        p = _pt_add_host(p, p)
        s >>= 1
    return q


def _compress_host(p) -> bytes:
    X, Y, Z, _ = p
    zi = pow(Z, P - 2, P)
    x, y = X * zi % P, Y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")
