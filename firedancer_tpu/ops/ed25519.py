"""Batched ed25519 signature verification on TPU.

The TPU analogue of fd_ed25519_verify / fd_ed25519_verify_batch_single_msg
(reference: src/ballet/ed25519/fd_ed25519_user.c:135-311), with two
deliberate interface upgrades for the batched pipeline:

  * per-item pass/fail BITS instead of the reference's fail-fast batch
    return (the verify tile needs per-txn outcomes; SURVEY.md §7.3)
  * batch width is the array's leading axis (thousands), not MAX=16

Acceptance rules are consensus-identical to the reference (and to Agave's
dalek 2.x + verify_strict usage):

  1. S canonical: 0 <= S < L, else reject          (fd_ed25519_user.c:158-161)
  2. A', R decompress per RFC; non-canonical y accepted
  3. A' or R of small order (<= 8): reject          (fd_ed25519_user.c:200-206)
  4. k = SHA-512(R || A || M) reduced mod L
  5. accept iff [S]B + [k](-A') == R (projective eq, no cofactor mul)
"""

import hashlib
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import curve25519 as cv
from . import f25519 as fe
from . import scalar25519 as sc
from . import sha512 as sh

L = sc.L
P = fe.P


def verify_batch(msgs, msg_len, sigs, pubkeys):
    """Verify a batch of detached ed25519 signatures.

    Args:
      msgs:    uint8 (batch, maxlen) — messages, zero-padded
      msg_len: int32 (batch,)        — true message lengths
      sigs:    uint8 (batch, 64)     — R || S
      pubkeys: uint8 (batch, 32)

    Returns: bool (batch,) pass/fail bits.
    """
    r_bytes = sigs[:, :32]
    s_bytes = sigs[:, 32:]

    ok_s = sc.is_canonical(s_bytes)

    ok_a, a_pt = cv.decompress(pubkeys)
    ok_r, r_pt = cv.decompress(r_bytes)
    ok_a &= ~cv.is_small_order_affine(a_pt)
    ok_r &= ~cv.is_small_order_affine(r_pt)

    # k = SHA-512(R || A || M) mod L
    batch, maxlen = msgs.shape
    pre = jnp.concatenate([r_bytes, pubkeys, msgs], axis=1)
    k_digest = sh.sha512(pre, msg_len.astype(jnp.int32) + 64)
    k_limbs = sc.reduce_512(k_digest)

    s_windows = cv.scalar_windows(s_bytes)
    k_windows = sc.limbs_to_windows(k_limbs)

    r_cmp = cv.double_scalar_mul_base(s_windows, k_windows, cv.neg(a_pt))
    ok_eq = cv.eq_z1(r_cmp, r_pt)

    return ok_s & ok_a & ok_r & ok_eq


def verify_batch_single_msg(msg, sigs, pubkeys):
    """All signatures over one shared message (the reference's batch shape,
    fd_ed25519_user.c:231: a Solana txn's sigs all cover the same payload)."""
    batch = sigs.shape[0]
    msgs = jnp.broadcast_to(msg[None, :], (batch, msg.shape[0]))
    lens = jnp.full((batch,), msg.shape[0], dtype=jnp.int32)
    return verify_batch(msgs, lens, sigs, pubkeys)


# ------------------------------------------------------------------ host side
# Key generation and signing are control-plane operations (the validator signs
# through the isolated sign tile, one item at a time — ref src/disco/keyguard);
# python-int host code is the right tool, device batching buys nothing.


def keypair_from_seed(seed: bytes):
    """seed (32B) -> (public_key bytes, secret scalar int, prefix bytes).
    (ref fd_ed25519_public_from_private)"""
    assert len(seed) == 32
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    pub = _scalar_mul_base_host(a)
    return _compress_host(pub), a, h[32:]


def sign(seed: bytes, msg: bytes) -> bytes:
    """Single-item host signer (ref fd_ed25519_sign)."""
    pub, a, prefix = keypair_from_seed(seed)
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = _compress_host(_scalar_mul_base_host(r))
    k = int.from_bytes(hashlib.sha512(R + pub + msg).digest(), "little") % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def _pt_add_host(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    Bv = (Y1 + X1) * (Y2 + X2) % P
    Cc = 2 * T1 * T2 * cv.D % P
    Dd = 2 * Z1 * Z2 % P
    E, F, G, H = (Bv - A) % P, (Dd - Cc) % P, (Dd + Cc) % P, (Bv + A) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def _scalar_mul_base_host(s: int):
    q = (0, 1, 1, 0)
    p = (cv.BASE_X, cv.BASE_Y, 1, cv.BASE_X * cv.BASE_Y % P)
    while s > 0:
        if s & 1:
            q = _pt_add_host(q, p)
        p = _pt_add_host(p, p)
        s >>= 1
    return q


def _compress_host(p) -> bytes:
    X, Y, Z, _ = p
    zi = pow(Z, P - 2, P)
    x, y = X * zi % P, Y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")
