"""X25519 ECDH (RFC 7748), host-side.

Reference role: src/ballet/ed25519/fd_x25519.c — the TLS 1.3 / QUIC
handshake key exchange.  One exchange per connection setup, strictly
control-plane: a python-int Montgomery ladder is the right tool (the
device batch story belongs to sigverify, not ECDH).

Constant-time is NOT claimed here (CPython big-int math isn't);
the reference's ladder is.  The validator's long-term identity key never
touches this path — X25519 keys are ephemeral per handshake.
"""

P = 2**255 - 19
_A24 = 121665


def _decode_u(u: bytes) -> int:
    if len(u) != 32:
        raise ValueError("u must be 32 bytes")
    # RFC 7748: mask the top bit of the final byte
    return int.from_bytes(u[:31] + bytes([u[31] & 0x7F]), "little")


def _decode_scalar(k: bytes) -> int:
    if len(k) != 32:
        raise ValueError("scalar must be 32 bytes")
    v = int.from_bytes(k, "little")
    v &= ~7
    v &= (1 << 254) - 1
    v |= 1 << 254
    return v


def x25519(scalar: bytes, u: bytes) -> bytes:
    """RFC 7748 X25519(k, u) -> 32-byte shared point."""
    k = _decode_scalar(scalar)
    x1 = _decode_u(u)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (k >> t) & 1
        if swap ^ kt:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        A = (x2 + z2) % P
        AA = A * A % P
        B = (x2 - z2) % P
        BB = B * B % P
        E = (AA - BB) % P
        C = (x3 + z3) % P
        D = (x3 - z3) % P
        DA = D * A % P
        CB = C * B % P
        x3 = (DA + CB) % P
        x3 = x3 * x3 % P
        z3 = (DA - CB) % P
        z3 = x1 * z3 * z3 % P
        x2 = AA * BB % P
        z2 = E * (AA + _A24 * E) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, P - 2, P) % P
    return out.to_bytes(32, "little")


BASE_POINT = (9).to_bytes(32, "little")


def public_key(secret: bytes) -> bytes:
    return x25519(secret, BASE_POINT)


def shared_secret(secret: bytes, peer_public: bytes) -> bytes:
    """DH shared secret; raises on the all-zero output (low-order peer
    point), per RFC 7748 §6.1 MUST-check for TLS."""
    out = x25519(secret, peer_public)
    if out == b"\0" * 32:
        raise ValueError("low-order public key (zero shared secret)")
    return out
