"""ristretto255 group (RFC 9496), host-side.

Reference role: src/ballet/ed25519/fd_ristretto255.c — backs the
sol_curve25519 ristretto syscalls (point validate/add/sub/mul) used by
confidential-transfer style programs.  Syscalls execute one point op at a
time inside the VM, so this is python-int host math on the edwards curve
(batched device variants would ride ops/curve25519 if a workload appears).

Encodings/decodings follow RFC 9496 §4.3 exactly; invalid encodings
(non-canonical field elements, negative x, t*x negative, y=0 cases) are
rejected as the syscalls require.
"""

P = 2**255 - 19
D = -121665 * pow(121666, P - 2, P) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# group order (same L as ed25519)
L = 2**252 + 27742317777372353535851937790883648493

INVSQRT_A_MINUS_D = None  # filled below
SQRT_AD_MINUS_ONE = None

_A = P - 1  # a = -1


def _is_neg(x: int) -> bool:
    return bool(x & 1)


def _sqrt_ratio_m1(u: int, v: int):
    """(was_square, sqrt(u/v) or sqrt(i*u/v)), RFC 9496 §4.2."""
    u %= P
    v %= P
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    correct = check == u
    flipped = check == (-u) % P
    flipped_i = check == (-u) % P * SQRT_M1 % P
    if flipped or flipped_i:
        r = r * SQRT_M1 % P
    was_square = correct or flipped
    if _is_neg(r):
        r = (-r) % P
    return was_square, r


def _compute_consts():
    global INVSQRT_A_MINUS_D, SQRT_AD_MINUS_ONE
    a_minus_d = (_A - D) % P
    _, inv_sqrt = _sqrt_ratio_m1(1, a_minus_d)
    INVSQRT_A_MINUS_D = inv_sqrt
    ad_minus_one = (_A * D - 1) % P
    _, s = _sqrt_ratio_m1(ad_minus_one % P, 1)
    SQRT_AD_MINUS_ONE = s


_compute_consts()


class Point:
    """Edwards point (extended coords) representing a ristretto element."""

    __slots__ = ("X", "Y", "Z", "T")

    def __init__(self, X, Y, Z, T):
        self.X, self.Y, self.Z, self.T = X % P, Y % P, Z % P, T % P

    @classmethod
    def identity(cls):
        return cls(0, 1, 1, 0)

    def __add__(self, q):
        X1, Y1, Z1, T1 = self.X, self.Y, self.Z, self.T
        X2, Y2, Z2, T2 = q.X, q.Y, q.Z, q.T
        A = (Y1 - X1) * (Y2 - X2) % P
        B = (Y1 + X1) * (Y2 + X2) % P
        C = 2 * T1 * T2 * D % P
        Dv = 2 * Z1 * Z2 % P
        E, F, G, H = (B - A) % P, (Dv - C) % P, (Dv + C) % P, (B + A) % P
        return Point(E * F, G * H, F * G, E * H)

    def __neg__(self):
        return Point((-self.X) % P, self.Y, self.Z, (-self.T) % P)

    def __sub__(self, q):
        return self + (-q)

    def mul(self, n: int) -> "Point":
        n %= L
        q = Point.identity()
        p = self
        while n:
            if n & 1:
                q = q + p
            p = p + p
            n >>= 1
        return q

    # RFC 9496 §4.3.2 encoding
    def encode(self) -> bytes:
        X, Y, Z, T = self.X, self.Y, self.Z, self.T
        u1 = (Z + Y) * (Z - Y) % P
        u2 = X * Y % P
        _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
        den1 = invsqrt * u1 % P
        den2 = invsqrt * u2 % P
        z_inv = den1 * den2 % P * T % P
        ix0 = X * SQRT_M1 % P
        iy0 = Y * SQRT_M1 % P
        enchanted = den1 * INVSQRT_A_MINUS_D % P
        rotate = _is_neg(T * z_inv % P)
        if rotate:
            X, Y = iy0, ix0
            den_inv = enchanted
        else:
            den_inv = den2
        if _is_neg(X * z_inv % P):
            Y = (-Y) % P
        s = (Z - Y) * den_inv % P
        if _is_neg(s):
            s = (-s) % P
        return s.to_bytes(32, "little")

    def __eq__(self, other) -> bool:
        # ristretto equality: X1*Y2 == Y1*X2 or Y1*Y2 == -a*X1*X2 (a=-1)
        return (
            self.X * other.Y % P == self.Y * other.X % P
            or self.Y * other.Y % P == self.X * other.X % P
        )


def decode(b: bytes):
    """Decode 32 bytes to a Point; returns None if invalid (RFC 9496 §4.3.1)."""
    if len(b) != 32:
        return None
    s = int.from_bytes(b, "little")
    if s >= P:  # non-canonical
        return None
    if _is_neg(s):
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(D * u1 % P) * u1 % P - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    if not was_square:
        return None
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = 2 * s * den_x % P
    if _is_neg(x):
        x = (-x) % P
    y = u1 * den_y % P
    t = x * y % P
    if _is_neg(t) or y == 0:
        return None
    return Point(x, y, 1, t)


# generator: the edwards base point
BASE = Point(
    15112221349535400772501151409588531511454012693041857206046113283949847762202,
    46316835694926478169428394003475163141307993866256225615783033603165251855960,
    1,
    0,
)
BASE = Point(BASE.X, BASE.Y, 1, BASE.X * BASE.Y % P)


def from_uniform_bytes(b: bytes) -> Point:
    """One-way map from 64 uniform bytes (RFC 9496 §4.3.4) — the hash-to-
    group used by sol_curve syscalls' HashToCurve."""
    if len(b) != 64:
        raise ValueError("need 64 bytes")
    p1 = _elligator(int.from_bytes(b[:32], "little") & ((1 << 255) - 1))
    p2 = _elligator(int.from_bytes(b[32:], "little") & ((1 << 255) - 1))
    return p1 + p2


def _elligator(r0: int) -> Point:
    """MAP function of RFC 9496 §4.3.4."""
    r = SQRT_M1 * r0 % P * r0 % P
    one_minus_d_sq = (1 - D * D) % P
    u = (r + 1) * one_minus_d_sq % P
    c = (-1) % P
    d_minus_one_sq = (D - 1) * (D - 1) % P
    v = (c - r * D) % P * ((r + D) % P) % P
    was_square, s = _sqrt_ratio_m1(u, v)
    s_prime = s * r0 % P
    if not _is_neg(s_prime):
        s_prime = (-s_prime) % P
    if not was_square:
        s = s_prime
        c = r
    n = c * ((r - 1) % P) % P * d_minus_one_sq % P
    n = (n - v) % P
    w0 = 2 * s * v % P
    w1 = n * SQRT_AD_MINUS_ONE % P
    ss = s * s % P
    w2 = (1 - ss) % P
    w3 = (1 + ss) % P
    return Point(w0 * w3, w2 * w1, w1 * w3, w0 * w2)
