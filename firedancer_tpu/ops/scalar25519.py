"""Batched arithmetic mod L = 2^252 + 27742...493 (the ed25519 group order).

Plays the role of fd_curve25519_scalar.c (reference:
src/ballet/ed25519/fd_curve25519_scalar.c: scalar_validate, scalar_reduce).

Reduction strategy (TPU-friendly, branch-free): with L = 2^252 + C
(C ~ 2^124.7), fold x = hi*2^252 + lo  ->  lo - C*hi using SIGNED int32
limbs (radix 2^12), which shrinks the value by ~127 bits per fold; three
folds take a 512-bit digest below 2^252 + 2^135, then add 2L and
conditionally subtract L.  Signed carry passes use arithmetic shifts
(x >> 12) and masks (x & 0xFFF), both exact for two's-complement int32.
"""

import jax
import jax.numpy as jnp
import numpy as np

B = 12
MASK = (1 << B) - 1
L = 2**252 + 27742317777372353535851937790883648493
C = L - 2**252  # 27742...493, 125 bits -> 11 limbs

_I32 = jnp.int32
_C_NLIMB = 11

_C_LIMBS = np.array([(C >> (B * i)) & MASK for i in range(_C_NLIMB)], dtype=np.int64)
assert sum(int(c) << (B * i) for i, c in enumerate(_C_LIMBS)) == C
_L_LIMBS = np.array([(L >> (B * i)) & MASK for i in range(22)], dtype=np.int64)
_L2_LIMBS = np.array([(2 * L >> (B * i)) & MASK for i in range(22)], dtype=np.int64)


def bytes_to_limbs(b, nlimb: int):
    """uint8 (..., nbytes) -> int32 limbs (nlimb, ...), little-endian."""
    x = b.astype(_I32)
    nbytes = b.shape[-1]
    ngroups = (nlimb + 1) // 2
    need = 3 * ngroups + 1
    xs = [x[..., i] for i in range(nbytes)] + [
        jnp.zeros_like(x[..., 0]) for _ in range(max(0, need - nbytes))
    ]
    limbs = []
    for t in range(ngroups):
        limbs.append(xs[3 * t] | ((xs[3 * t + 1] & 0xF) << 8))
        limbs.append((xs[3 * t + 1] >> 4) | (xs[3 * t + 2] << 4))
    return jnp.stack(limbs[:nlimb], axis=0)


def _carry_signed(x, passes: int):
    """Parallel signed carry passes on (n, ...) int32 limbs; the caller must
    provide zero-padded headroom limbs at the top so no carry is dropped."""
    for _ in range(passes):
        lo = x & MASK
        hi = jnp.right_shift(x, B)  # arithmetic shift on int32
        x = lo + jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    return x


def _fold_once(x):
    """x (n>=22 limbs, signed) -> lo(21) - C*hi, with 2 headroom limbs.

    Row-list accumulation (no .at[].add): the scatter-add lowering both
    bloats eager dispatch and has crashed this jaxlib's CPU compiler;
    plain per-row adds sidestep the primitive entirely."""
    n = x.shape[0]
    m = n - 21
    out_len = max(21, m + _C_NLIMB) + 2
    z = jnp.zeros_like(x[0])
    rows = [x[i] if i < 21 else z for i in range(out_len)]
    for i in range(_C_NLIMB):
        c = jnp.int32(int(_C_LIMBS[i]))
        for j in range(m):
            rows[i + j] = rows[i + j] - c * x[21 + j]
    return jnp.stack(rows, axis=0)


def reduce_512(digest_bytes):
    """SHA-512 digest (interpreted little-endian) mod L.

    digest_bytes: uint8 (..., 64) -> int32 limbs (22, ...) canonical in [0, L).
    (ref fd_curve25519_scalar_reduce)"""
    x = bytes_to_limbs(digest_bytes, 44)  # 528 bits, top limbs zero
    # three folds: 516 -> ~390 -> ~263 -> 252+eps bits (each shrinks ~127)
    for _ in range(3):
        x = _fold_once(x)
        x = _carry_signed(x, 2)
    # make positive: add 2L (value > -2^181), then canonical subtract
    l2 = jnp.asarray(_L2_LIMBS.astype(np.int32)).reshape((22,) + (1,) * (x.ndim - 1))
    x = jnp.concatenate([x[:22] + l2, x[22:]], axis=0)
    x = _carry_signed(x, 3)
    return _cond_sub_l(x, times=4)


def _cond_sub_l(x, times: int):
    """Repeated conditional subtract of L.  x: (n>=22, ...) signed limbs of a
    nonneg value < 2^264; returns canonical-carry (22, ...) limbs."""
    n = x.shape[0]
    # serial-exact carry so limbs are canonical 12-bit (top limbs drain to 0)
    rows = [x[i] for i in range(n)]
    for i in range(n - 1):
        rows[i + 1] = rows[i + 1] + jnp.right_shift(rows[i], B)
        rows[i] = rows[i] & MASK
    x = jnp.stack(rows[:22], axis=0)
    for _ in range(times):
        rows = [x[i] for i in range(22)]
        borrow = jnp.zeros_like(rows[0])
        diff = []
        for i in range(22):
            t = rows[i] + jnp.int32(1 << B) - jnp.int32(int(_L_LIMBS[i])) - borrow
            diff.append(t & MASK)
            borrow = 1 - jnp.right_shift(t, B)
        ge = borrow == 0
        x = jnp.stack([jnp.where(ge, d, r) for d, r in zip(diff, rows)], axis=0)
    return x


def is_canonical(scalar_bytes):
    """Batch check s < L (ref fd_curve25519_scalar_validate).
    scalar_bytes: uint8 (..., 32) -> bool (...,)."""
    x = bytes_to_limbs(scalar_bytes, 22)
    borrow = jnp.zeros_like(x[0])
    for i in range(22):
        t = x[i] + jnp.int32(1 << B) - jnp.int32(int(_L_LIMBS[i])) - borrow
        borrow = 1 - jnp.right_shift(t, B)
    return borrow == 1  # final borrow -> s < L


def mul_mod_l(a, b, b_nlimb: int | None = None):
    """Batched product mod L.  a: (22, ...) canonical 12-bit limbs,
    b: (nb, ...) canonical limbs (nb <= 22).  Returns canonical (22, ...).

    Column bound: a 22xnb convolution column accumulates <= 22 products of
    two 12-bit limbs: 22 * (2^12-1)^2 < 2^29 — exact in int32."""
    nb = b.shape[0] if b_nlimb is None else b_nlimb
    a = a.astype(_I32)
    b = b.astype(_I32)
    z = jnp.zeros_like(a[0])
    rows = [z] * (22 + nb)
    for i in range(nb):
        t = b[i] * a
        for j in range(22):
            rows[i + j] = rows[i + j] + t[j]
    out = jnp.stack(rows, axis=0)
    # normalize then fold 2^252*hi -> -C*hi until below ~2^253
    out = _carry_signed(out, 3)
    x = out
    while x.shape[0] > 23:
        x = _fold_once(x)
        x = _carry_signed(x, 2)
    x = _fold_once(x)
    x = _carry_signed(x, 2)
    l2 = jnp.asarray(_L2_LIMBS.astype(np.int32)).reshape(
        (22,) + (1,) * (x.ndim - 1))
    x = jnp.concatenate([x[:22] + l2, x[22:]], axis=0)
    x = _carry_signed(x, 3)
    return _cond_sub_l(x, times=4)


def sum_mod_l(limbs, axis: int):
    """Sum canonical (22, ..., n, ...) limb vectors over `axis` (a batch
    axis, counted in the trailing batch dims), mod L.

    Tree-halving partial sums keep every limb < 2^31: each halving at most
    doubles limb magnitude, and a carry pass every 17 halvings would suffice
    — we carry every 8 for margin."""
    x = limbs.astype(_I32)
    ax = axis + 1  # account for the leading limb axis
    steps = 0
    while x.shape[ax] > 1:
        n = x.shape[ax]
        half = n // 2
        lo = jax.lax.slice_in_dim(x, 0, half, axis=ax)
        hi = jax.lax.slice_in_dim(x, half, 2 * half, axis=ax)
        s = lo + hi
        if n % 2:
            s = jnp.concatenate(
                [s, jax.lax.slice_in_dim(x, 2 * half, n, axis=ax)], axis=ax)
        x = s
        steps += 1
        if steps % 8 == 0:
            x = _carry_signed(x, 2)
    x = jnp.squeeze(x, axis=ax)
    # value < 2^(12+8)*22ish; normalize + fold the top bits, then canonical
    pad = jnp.zeros((2, *x.shape[1:]), dtype=_I32)
    x = jnp.concatenate([x, pad], axis=0)
    x = _carry_signed(x, 3)
    x = _fold_once(x)
    x = _carry_signed(x, 2)
    l2 = jnp.asarray(_L2_LIMBS.astype(np.int32)).reshape(
        (22,) + (1,) * (x.ndim - 1))
    x = jnp.concatenate([x[:22] + l2, x[22:]], axis=0)
    x = _carry_signed(x, 3)
    return _cond_sub_l(x, times=4)


# --------------------------------------------------------------- divstep
# Antipa halving (ROADMAP item 4): decompose a mod-L scalar k as
# k == u/v (mod L) with u, |v| < 2^128, entirely on device, so the
# halved double-scalar chain (cv.double_scalar_mul_halved, 128 doubles
# instead of 256) needs no host half-gcd round-trip.
#
# Two fixed-shape phases, both branchless (jnp.where selects only):
#
#   1. DIVSTEP_ITERS iterations of Bernstein-Yang divstep (CHES 2019)
#      on (f, g) = (L, 2^DIVSTEP_ITERS * k mod L), tracking only the
#      k-coefficients (bf, bg) of each row.  Each step halves g, so
#      after exactly i steps  f == bf * k * 2^(DIVSTEP_ITERS - i)
#      (mod L): the 2^N premultiply makes the pair UNTWISTED precisely
#      at i == DIVSTEP_ITERS — which is why the iteration count is
#      fixed rather than early-exited.  At that point both lattice
#      vectors (f, bf), (g, bg) sit near the 2^126 balance point, with
#      an empirical spread up to ~2^143 (the divstep hull wobbles
#      ~±14 bits around sqrt(L) at any fixed cut).
#
#   2. LAGRANGE_ITERS rounds of binary Lagrange reduction on those two
#      vectors: conditionally swap so F is the sup-norm-larger one,
#      then try F <- F ± 2^t G with t = blen(F) - blen(G) (capped 31),
#      keeping the candidate only when it strictly shrinks ||F||.
#      Monotone by construction; converges to a Gauss-reduced pair
#      whose shorter vector is within a factor ~2 of the lattice
#      minimum (<= (4L/3)^(1/2) ~ 2^126.1 by Minkowski, det = L).
#      Measured worst case over 10^5 random + structured-adversarial
#      scalars: 128 bits after 16 rounds (tests/test_scalar_divstep.py
#      re-runs a corpus sweep) — exactly the 32-window budget.
#
# Values ride the existing signed int32 limb planes.  Phase 1 needs NO
# carry passes on f/g: the shift-right-1 identity
#     (x/2)_i = (l_i >> 1) + ((l_{i+1} & 1) << 11)
# is exact for any redundant signed limbs (only limb 0's parity is the
# value's parity; higher limbs contribute even terms), and limb drift
# is +2^11/iter -> < 2^20 after 250 iters, far inside int32.  The
# coefficient planes double each step, so they get one parallel signed
# carry pass per iteration.

DIVSTEP_ITERS = 250
LAGRANGE_ITERS = 24  # converged at 16 on the measured corpora; +8 margin

_PRE_LIMBS = np.array(
    [(pow(2, DIVSTEP_ITERS, L) >> (B * i)) & MASK for i in range(22)],
    dtype=np.int64)


def _canon_signed(x):
    """Serial-exact carry: (n, ...) signed limbs -> limbs 0..n-2 in
    [0, 2^B), top limb signed (two's-complement-style mixed radix).
    Value-preserving, so it is safe on frozen/selected lanes."""
    n = x.shape[0]
    rows = [x[i] for i in range(n)]
    for i in range(n - 1):
        rows[i + 1] = rows[i + 1] + jnp.right_shift(rows[i], B)
        rows[i] = rows[i] & MASK
    return jnp.stack(rows, axis=0)


def _shr1(x):
    """Exact value/2 of an EVEN-valued redundant signed limb plane."""
    lo = jnp.right_shift(x, 1)
    odd = x & 1
    up = jnp.concatenate([odd[1:], jnp.zeros_like(odd[:1])], axis=0)
    return lo + (up << (B - 1))


def _abs_cs(x):
    """Canonical-signed plane -> (|x| canonical, negative flag)."""
    neg = x[x.shape[0] - 1] < 0
    nx = _canon_signed(-x)
    return jnp.where(neg[None], nx, x), neg


def _lt_nn(a, b):
    """a < b for nonneg canonical planes (borrow chain sign)."""
    return _canon_signed(a - b)[a.shape[0] - 1] < 0


def _blen_nn(a):
    """Bit length of a nonneg canonical plane (top limb may hold a few
    extra bits after shifts; compares cover 14)."""
    out = jnp.zeros_like(a[0])
    for i in range(a.shape[0]):
        bl = jnp.zeros_like(a[0])
        for s in range(14):
            bl = bl + (a[i] > ((1 << s) - 1)).astype(_I32)
        out = jnp.where(a[i] > 0, B * i + bl, out)
    return out


def _shl_cs(x, t):
    """Canonical-signed plane times 2^t, t int32 (...,) in [0, 31].
    Limb-rolls cover multiples of 12 (the dropped top limb is provably
    zero: the caller only shifts the sup-norm-smaller vector up to the
    larger one's bit length, and both stay <= L); the residual shift is
    a plain per-limb multiply, leaving redundant limbs < 2^24."""
    for _ in range(2):
        c = t >= B
        top = x[-2] + (x[-1] << B)  # keeps the signed top limb's value
        rolled = jnp.concatenate(
            [jnp.zeros_like(x[:1]), x[:-2], top[None]], axis=0)
        x = jnp.where(c[None], rolled, x)
        t = t - jnp.where(c, B, 0)
    for s in (8, 4, 2, 1):
        c = (t & s) != 0
        x = jnp.where(c[None], x << s, x)
    return x


def _pairmax_nn(a, b):
    return jnp.where(_lt_nn(a, b)[None], b, a)


def _carry_keep_top(x):
    """One signed carry pass that leaves the top limb UNSPLIT (it absorbs
    the carry from below instead of shedding one upward) — unlike
    _carry_signed, no headroom limbs are needed, so a negative value's
    sign can never be truncated off the top."""
    lo = jnp.concatenate([x[:-1] & MASK, x[-1:]], axis=0)
    hi = jnp.right_shift(x, B)
    return lo + jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)


def _divstep_body(_, st):
    f, g, bf, bg, delta = st
    odd = (g[0] & 1).astype(_I32)
    swap = (delta > 0) & (odd == 1)
    sw = swap[None]
    delta = jnp.where(swap, 1 - delta, 1 + delta)
    f_n = jnp.where(sw, g, f)
    g_n = _shr1(jnp.where(sw, g - f, g + odd[None] * f))
    bf_n = _carry_keep_top(jnp.where(sw, 2 * bg, 2 * bf))
    bg_n = _carry_keep_top(jnp.where(sw, bg - bf, bg + odd[None] * bf))
    return f_n, g_n, bf_n, bg_n, delta


def _lagrange_body(_, st):
    f, bf, g, bg = st
    nf = _pairmax_nn(*(_abs_cs(p)[0] for p in (f, bf)))
    ng = _pairmax_nn(*(_abs_cs(p)[0] for p in (g, bg)))
    swap = _lt_nn(nf, ng)[None]
    f, g = jnp.where(swap, g, f), jnp.where(swap, f, g)
    bf, bg = jnp.where(swap, bg, bf), jnp.where(swap, bf, bg)
    nf, ng = jnp.where(swap, ng, nf), jnp.where(swap, nf, ng)
    t = jnp.clip(_blen_nn(nf) - _blen_nn(ng), 0, 31)
    sg, sbg = _shl_cs(g, t), _shl_cs(bg, t)
    p, pb = _canon_signed(f - sg), _canon_signed(bf - sbg)
    m, mb = _canon_signed(f + sg), _canon_signed(bf + sbg)
    np_ = _pairmax_nn(*(_abs_cs(q)[0] for q in (p, pb)))
    nm = _pairmax_nn(*(_abs_cs(q)[0] for q in (m, mb)))
    use_m = _lt_nn(nm, np_)[None]
    c, cb = jnp.where(use_m, m, p), jnp.where(use_m, mb, pb)
    nc = jnp.where(use_m, nm, np_)
    better = _lt_nn(nc, nf)[None]
    return (jnp.where(better, c, f), jnp.where(better, cb, bf), g, bg)


def halve_scalar(k_limbs):
    """Batched constant-time Antipa halving:  k -> (u, v) with
    u == v * k (mod L) and u, |v| < 2^128 (empirical worst 2^128
    inclusive-exclusive; see module comment for the certification).

    k_limbs: (22, ...) canonical limbs of k in [0, L).
    Returns (u_limbs, vabs_limbs, v_nonneg):
      u_limbs:    (22, ...) canonical limbs of u, 0 <= u < 2^128
      vabs_limbs: (22, ...) canonical limbs of |v|, 0 < |v| < 2^128
                  (except k = 0, which yields exactly (u, v) = (0, 1))
      v_nonneg:   bool (...,) — sign of v after normalizing u >= 0
    """
    batch_shape = k_limbs.shape[1:]
    pre = jnp.asarray(_PRE_LIMBS.astype(np.int32)).reshape(
        (22,) + (1,) * len(batch_shape))
    g0 = mul_mod_l(k_limbs.astype(_I32), pre)
    f0 = jnp.broadcast_to(
        jnp.asarray(_L_LIMBS.astype(np.int32)).reshape(
            (22,) + (1,) * len(batch_shape)),
        g0.shape).astype(_I32)
    z = jnp.zeros_like(g0)
    one = z.at[0].set(1)
    delta = jnp.ones(batch_shape, dtype=_I32)

    f, g, bf, bg, _ = jax.lax.fori_loop(
        0, DIVSTEP_ITERS, _divstep_body, (f0, g0, z, one, delta))

    # untwisted at exactly DIVSTEP_ITERS:  f == bf*k, g == bg*k (mod L)
    f, g = _canon_signed(f), _canon_signed(g)
    bf, bg = _canon_signed(bf), _canon_signed(bg)
    f, bf, g, bg = jax.lax.fori_loop(
        0, LAGRANGE_ITERS, _lagrange_body, (f, bf, g, bg))

    # shorter of the two vectors, then normalize to u >= 0
    nf = _pairmax_nn(*(_abs_cs(p)[0] for p in (f, bf)))
    ng = _pairmax_nn(*(_abs_cs(p)[0] for p in (g, bg)))
    take_g = _lt_nn(ng, nf)[None]
    u = jnp.where(take_g, g, f)
    v = jnp.where(take_g, bg, bf)
    au, u_neg = _abs_cs(u)
    v = jnp.where(u_neg[None], _canon_signed(-v), v)
    av, v_neg = _abs_cs(v)
    return au, av, ~v_neg


def neg_mod_l(x):
    """(L - x) mod L for canonical (22, ...) limbs of x in [0, L)."""
    l2 = jnp.asarray(_L2_LIMBS.astype(np.int32)).reshape(
        (22,) + (1,) * (x.ndim - 1))
    y = l2 - x.astype(_I32)
    pad = jnp.zeros((2, *y.shape[1:]), dtype=_I32)
    return _cond_sub_l(jnp.concatenate([y, pad], axis=0), times=2)


def limbs_to_windows(limbs):
    """(22, ...) 12-bit limbs -> (64, ...) 4-bit windows (3 nibbles/limb)."""
    out = []
    for j in range(64):
        out.append((limbs[j // 3] >> (4 * (j % 3))) & 0xF)
    return jnp.stack(out, axis=0).astype(jnp.uint32)


def to_int(limbs) -> int:
    """Host helper: single (22,) limb vector -> python int."""
    return sum(int(v) << (B * i) for i, v in enumerate(np.asarray(limbs))) % L
