"""Batched arithmetic mod L = 2^252 + 27742...493 (the ed25519 group order).

Plays the role of fd_curve25519_scalar.c (reference:
src/ballet/ed25519/fd_curve25519_scalar.c: scalar_validate, scalar_reduce).

Reduction strategy (TPU-friendly, branch-free): with L = 2^252 + C
(C ~ 2^124.7), fold x = hi*2^252 + lo  ->  lo - C*hi using SIGNED int32
limbs (radix 2^12), which shrinks the value by ~127 bits per fold; three
folds take a 512-bit digest below 2^252 + 2^135, then add 2L and
conditionally subtract L.  Signed carry passes use arithmetic shifts
(x >> 12) and masks (x & 0xFFF), both exact for two's-complement int32.
"""

import jax
import jax.numpy as jnp
import numpy as np

B = 12
MASK = (1 << B) - 1
L = 2**252 + 27742317777372353535851937790883648493
C = L - 2**252  # 27742...493, 125 bits -> 11 limbs

_I32 = jnp.int32
_C_NLIMB = 11

_C_LIMBS = np.array([(C >> (B * i)) & MASK for i in range(_C_NLIMB)], dtype=np.int64)
assert sum(int(c) << (B * i) for i, c in enumerate(_C_LIMBS)) == C
_L_LIMBS = np.array([(L >> (B * i)) & MASK for i in range(22)], dtype=np.int64)
_L2_LIMBS = np.array([(2 * L >> (B * i)) & MASK for i in range(22)], dtype=np.int64)


def bytes_to_limbs(b, nlimb: int):
    """uint8 (..., nbytes) -> int32 limbs (nlimb, ...), little-endian."""
    x = b.astype(_I32)
    nbytes = b.shape[-1]
    ngroups = (nlimb + 1) // 2
    need = 3 * ngroups + 1
    xs = [x[..., i] for i in range(nbytes)] + [
        jnp.zeros_like(x[..., 0]) for _ in range(max(0, need - nbytes))
    ]
    limbs = []
    for t in range(ngroups):
        limbs.append(xs[3 * t] | ((xs[3 * t + 1] & 0xF) << 8))
        limbs.append((xs[3 * t + 1] >> 4) | (xs[3 * t + 2] << 4))
    return jnp.stack(limbs[:nlimb], axis=0)


def _carry_signed(x, passes: int):
    """Parallel signed carry passes on (n, ...) int32 limbs; the caller must
    provide zero-padded headroom limbs at the top so no carry is dropped."""
    for _ in range(passes):
        lo = x & MASK
        hi = jnp.right_shift(x, B)  # arithmetic shift on int32
        x = lo + jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    return x


def _fold_once(x):
    """x (n>=22 limbs, signed) -> lo(21) - C*hi, with 2 headroom limbs.

    Row-list accumulation (no .at[].add): the scatter-add lowering both
    bloats eager dispatch and has crashed this jaxlib's CPU compiler;
    plain per-row adds sidestep the primitive entirely."""
    n = x.shape[0]
    m = n - 21
    out_len = max(21, m + _C_NLIMB) + 2
    z = jnp.zeros_like(x[0])
    rows = [x[i] if i < 21 else z for i in range(out_len)]
    for i in range(_C_NLIMB):
        c = jnp.int32(int(_C_LIMBS[i]))
        for j in range(m):
            rows[i + j] = rows[i + j] - c * x[21 + j]
    return jnp.stack(rows, axis=0)


def reduce_512(digest_bytes):
    """SHA-512 digest (interpreted little-endian) mod L.

    digest_bytes: uint8 (..., 64) -> int32 limbs (22, ...) canonical in [0, L).
    (ref fd_curve25519_scalar_reduce)"""
    x = bytes_to_limbs(digest_bytes, 44)  # 528 bits, top limbs zero
    # three folds: 516 -> ~390 -> ~263 -> 252+eps bits (each shrinks ~127)
    for _ in range(3):
        x = _fold_once(x)
        x = _carry_signed(x, 2)
    # make positive: add 2L (value > -2^181), then canonical subtract
    l2 = jnp.asarray(_L2_LIMBS.astype(np.int32)).reshape((22,) + (1,) * (x.ndim - 1))
    x = jnp.concatenate([x[:22] + l2, x[22:]], axis=0)
    x = _carry_signed(x, 3)
    return _cond_sub_l(x, times=4)


def _cond_sub_l(x, times: int):
    """Repeated conditional subtract of L.  x: (n>=22, ...) signed limbs of a
    nonneg value < 2^264; returns canonical-carry (22, ...) limbs."""
    n = x.shape[0]
    # serial-exact carry so limbs are canonical 12-bit (top limbs drain to 0)
    rows = [x[i] for i in range(n)]
    for i in range(n - 1):
        rows[i + 1] = rows[i + 1] + jnp.right_shift(rows[i], B)
        rows[i] = rows[i] & MASK
    x = jnp.stack(rows[:22], axis=0)
    for _ in range(times):
        rows = [x[i] for i in range(22)]
        borrow = jnp.zeros_like(rows[0])
        diff = []
        for i in range(22):
            t = rows[i] + jnp.int32(1 << B) - jnp.int32(int(_L_LIMBS[i])) - borrow
            diff.append(t & MASK)
            borrow = 1 - jnp.right_shift(t, B)
        ge = borrow == 0
        x = jnp.stack([jnp.where(ge, d, r) for d, r in zip(diff, rows)], axis=0)
    return x


def is_canonical(scalar_bytes):
    """Batch check s < L (ref fd_curve25519_scalar_validate).
    scalar_bytes: uint8 (..., 32) -> bool (...,)."""
    x = bytes_to_limbs(scalar_bytes, 22)
    borrow = jnp.zeros_like(x[0])
    for i in range(22):
        t = x[i] + jnp.int32(1 << B) - jnp.int32(int(_L_LIMBS[i])) - borrow
        borrow = 1 - jnp.right_shift(t, B)
    return borrow == 1  # final borrow -> s < L


def mul_mod_l(a, b, b_nlimb: int | None = None):
    """Batched product mod L.  a: (22, ...) canonical 12-bit limbs,
    b: (nb, ...) canonical limbs (nb <= 22).  Returns canonical (22, ...).

    Column bound: a 22xnb convolution column accumulates <= 22 products of
    two 12-bit limbs: 22 * (2^12-1)^2 < 2^29 — exact in int32."""
    nb = b.shape[0] if b_nlimb is None else b_nlimb
    a = a.astype(_I32)
    b = b.astype(_I32)
    z = jnp.zeros_like(a[0])
    rows = [z] * (22 + nb)
    for i in range(nb):
        t = b[i] * a
        for j in range(22):
            rows[i + j] = rows[i + j] + t[j]
    out = jnp.stack(rows, axis=0)
    # normalize then fold 2^252*hi -> -C*hi until below ~2^253
    out = _carry_signed(out, 3)
    x = out
    while x.shape[0] > 23:
        x = _fold_once(x)
        x = _carry_signed(x, 2)
    x = _fold_once(x)
    x = _carry_signed(x, 2)
    l2 = jnp.asarray(_L2_LIMBS.astype(np.int32)).reshape(
        (22,) + (1,) * (x.ndim - 1))
    x = jnp.concatenate([x[:22] + l2, x[22:]], axis=0)
    x = _carry_signed(x, 3)
    return _cond_sub_l(x, times=4)


def sum_mod_l(limbs, axis: int):
    """Sum canonical (22, ..., n, ...) limb vectors over `axis` (a batch
    axis, counted in the trailing batch dims), mod L.

    Tree-halving partial sums keep every limb < 2^31: each halving at most
    doubles limb magnitude, and a carry pass every 17 halvings would suffice
    — we carry every 8 for margin."""
    x = limbs.astype(_I32)
    ax = axis + 1  # account for the leading limb axis
    steps = 0
    while x.shape[ax] > 1:
        n = x.shape[ax]
        half = n // 2
        lo = jax.lax.slice_in_dim(x, 0, half, axis=ax)
        hi = jax.lax.slice_in_dim(x, half, 2 * half, axis=ax)
        s = lo + hi
        if n % 2:
            s = jnp.concatenate(
                [s, jax.lax.slice_in_dim(x, 2 * half, n, axis=ax)], axis=ax)
        x = s
        steps += 1
        if steps % 8 == 0:
            x = _carry_signed(x, 2)
    x = jnp.squeeze(x, axis=ax)
    # value < 2^(12+8)*22ish; normalize + fold the top bits, then canonical
    pad = jnp.zeros((2, *x.shape[1:]), dtype=_I32)
    x = jnp.concatenate([x, pad], axis=0)
    x = _carry_signed(x, 3)
    x = _fold_once(x)
    x = _carry_signed(x, 2)
    l2 = jnp.asarray(_L2_LIMBS.astype(np.int32)).reshape(
        (22,) + (1,) * (x.ndim - 1))
    x = jnp.concatenate([x[:22] + l2, x[22:]], axis=0)
    x = _carry_signed(x, 3)
    return _cond_sub_l(x, times=4)


def limbs_to_windows(limbs):
    """(22, ...) 12-bit limbs -> (64, ...) 4-bit windows (3 nibbles/limb)."""
    out = []
    for j in range(64):
        out.append((limbs[j // 3] >> (4 * (j % 3))) & 0xF)
    return jnp.stack(out, axis=0).astype(jnp.uint32)


def to_int(limbs) -> int:
    """Host helper: single (22,) limb vector -> python int."""
    return sum(int(v) << (B * i) for i, v in enumerate(np.asarray(limbs))) % L
