"""Batched SHA-512 over variable-length messages, TPU-first.

The reference's batch SHA-512 parallelizes across AVX lanes with a fixed
batch width (reference: src/ballet/sha512/fd_sha512.h:266-361, widths 4/8);
here the batch axis is the array's leading dim and the width is whatever the
caller shapes (thousands, not 8).

TPU has no 64-bit integer units, so each 64-bit word is an (hi, lo) uint32
pair; rotations/shifts/adds are pair ops on (batch,)-shaped vectors.
Variable message lengths inside the fixed-shape batch are handled by
device-side padding + per-block active masks (the reference streams bytes per
message, src/ballet/sha512/fd_sha512.c — a TPU batch must pad to a static
block count instead, SURVEY.md §7 "hard parts").
"""

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32


def _iroot(n: int, k: int) -> int:
    """floor(n^(1/k)) by Newton iteration on python ints."""
    if n == 0:
        return 0
    x = 1 << ((n.bit_length() + k - 1) // k)
    while True:
        y = ((k - 1) * x + n // x ** (k - 1)) // k
        if y >= x:
            return x
        x = y


def _primes(n: int):
    out, c = [], 2
    while len(out) < n:
        if all(c % q for q in out):
            out.append(c)
        c += 1
    return out


# H0 = frac(sqrt(p)) and K = frac(cbrt(p)) over the first 8 / 80 primes
_H0 = [_iroot(p << 128, 2) & ((1 << 64) - 1) for p in _primes(8)]
_K = [_iroot(p << 192, 3) & ((1 << 64) - 1) for p in _primes(80)]
_K_HI = np.array([k >> 32 for k in _K], dtype=np.uint32)
_K_LO = np.array([k & 0xFFFFFFFF for k in _K], dtype=np.uint32)


def _add2(a, b):
    """64-bit add of (hi, lo) pairs."""
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(_U32)
    return (a[0] + b[0] + carry, lo)


def _addk(*xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = _add2(acc, x)
    return acc


def _rotr(a, r: int):
    hi, lo = a
    if r == 0:
        return a
    if r < 32:
        return ((hi >> r) | (lo << (32 - r)), (lo >> r) | (hi << (32 - r)))
    if r == 32:
        return (lo, hi)
    r -= 32
    return ((lo >> r) | (hi << (32 - r)), (hi >> r) | (lo << (32 - r)))


def _shr(a, r: int):
    hi, lo = a
    if r < 32:
        return (hi >> r, (lo >> r) | (hi << (32 - r)))
    return (jnp.zeros_like(hi), hi >> (r - 32))


def _xor2(a, b):
    return (a[0] ^ b[0], a[1] ^ b[1])


def _xor3(a, b, c):
    return (a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1])


def _compress_block(state, blk):
    """One SHA-512 compression.  state: list of 8 (hi, lo) pairs; blk: uint8
    (batch, 128).

    Both the message-schedule expansion and the 80 rounds are lax.scan loops
    rather than unrolled graphs: an unrolled compression is ~4k ops of serial
    dependency chain, which XLA compiles pathologically slowly; scans keep the
    traced graph one-round-sized and are the idiomatic TPU control flow."""
    b = blk.reshape(blk.shape[0], 16, 8).astype(_U32)
    hi = (b[:, :, 0] << 24) | (b[:, :, 1] << 16) | (b[:, :, 2] << 8) | b[:, :, 3]
    lo = (b[:, :, 4] << 24) | (b[:, :, 5] << 16) | (b[:, :, 6] << 8) | b[:, :, 7]
    w16 = jnp.stack([hi.T, lo.T], axis=1)  # (16, 2, batch)

    def sched_step(win, _):
        w15 = (win[1, 0], win[1, 1])
        w2 = (win[14, 0], win[14, 1])
        s0 = _xor3(_rotr(w15, 1), _rotr(w15, 8), _shr(w15, 7))
        s1 = _xor3(_rotr(w2, 19), _rotr(w2, 61), _shr(w2, 6))
        nw = jnp.stack(_addk((win[0, 0], win[0, 1]), s0, (win[9, 0], win[9, 1]), s1))
        return jnp.concatenate([win[1:], nw[None]], axis=0), nw

    _, w_rest = jax.lax.scan(sched_step, w16, None, length=64)
    ws = jnp.concatenate([w16, w_rest], axis=0)  # (80, 2, batch)

    k_pairs = jnp.stack([jnp.asarray(_K_HI), jnp.asarray(_K_LO)], axis=1)  # (80, 2)

    def round_step(st, inp):
        w_t, kt = inp  # (2, batch), (2,)
        a, b_, c, d, e, f, g, h = [(st[i, 0], st[i, 1]) for i in range(8)]
        S1 = _xor3(_rotr(e, 14), _rotr(e, 18), _rotr(e, 41))
        ch = ((e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1]))
        kb = (jnp.broadcast_to(kt[0], e[0].shape), jnp.broadcast_to(kt[1], e[1].shape))
        t1 = _addk(h, S1, ch, kb, (w_t[0], w_t[1]))
        S0 = _xor3(_rotr(a, 28), _rotr(a, 34), _rotr(a, 39))
        maj = (
            (a[0] & b_[0]) ^ (a[0] & c[0]) ^ (b_[0] & c[0]),
            (a[1] & b_[1]) ^ (a[1] & c[1]) ^ (b_[1] & c[1]),
        )
        t2 = _add2(S0, maj)
        h, g, f, e, d, c, b_, a = g, f, e, _add2(d, t1), c, b_, a, _add2(t1, t2)
        out = jnp.stack([jnp.stack(x) for x in (a, b_, c, d, e, f, g, h)])
        return out, None

    st0 = jnp.stack([jnp.stack(p) for p in state])  # (8, 2, batch)
    stf, _ = jax.lax.scan(round_step, st0, (ws, k_pairs))

    new = [(stf[i, 0], stf[i, 1]) for i in range(8)]
    return [_add2(s, n) for s, n in zip(state, new)]


def pad_messages(msgs, lengths, max_blocks: int):
    """Device-side SHA-512 padding.

    msgs: uint8 (batch, maxlen); lengths: int32 (batch,).  Returns
    (padded (batch, max_blocks*128) uint8, nblocks (batch,) int32)."""
    batch, maxlen = msgs.shape
    total = max_blocks * 128
    lengths = lengths.astype(jnp.int32)
    nblocks = (lengths + 17 + 127) // 128
    j = jnp.arange(total, dtype=jnp.int32)[None, :]  # (1, total)
    ln = lengths[:, None]
    src = jnp.pad(msgs, ((0, 0), (0, total - maxlen)))
    body = jnp.where(j < ln, src, 0)
    body = jnp.where(j == ln, jnp.uint8(0x80), body)
    # 128-bit big-endian length field in the last 16 bytes of block nblocks-1;
    # message bit length < 2^32 in practice, so only the low 4 bytes matter
    end = nblocks[:, None] * 128
    fpos = j - (end - 16)  # 0..15 inside the field
    bitlen = (lengths.astype(jnp.uint32) * 8)[:, None]
    shift = (15 - fpos) * 8
    lbyte = jnp.where(
        (fpos >= 0) & (fpos < 16) & (shift < 32),
        (bitlen >> jnp.clip(shift, 0, 31)) & 0xFF,
        0,
    ).astype(jnp.uint8)
    padded = jnp.where((fpos >= 0) & (fpos < 16), lbyte, body)
    return padded, nblocks


def sha512(msgs, lengths, max_blocks: int | None = None):
    """Batched SHA-512.  msgs: uint8 (batch, maxlen); lengths: (batch,).
    Returns digests uint8 (batch, 64)."""
    batch, maxlen = msgs.shape
    if max_blocks is None:
        max_blocks = (maxlen + 17 + 127) // 128
    padded, nblocks = pad_messages(msgs, lengths, max_blocks)
    blocks = padded.reshape(batch, max_blocks, 128).transpose(1, 0, 2)  # (nb, B, 128)

    # vz: a varying zero derived from the input so the scan carry inherits the
    # input's manual-mesh axes under shard_map (a constant-only carry trips
    # jax's varying-manual-axes check against the scanned blocks)
    vz = (blocks[0, :, 0] * 0).astype(_U32)
    state0 = []
    for hv in _H0:
        state0.append(
            (
                jnp.full((batch,), hv >> 32, dtype=_U32) + vz,
                jnp.full((batch,), hv & 0xFFFFFFFF, dtype=_U32) + vz,
            )
        )

    def step(state, inp):
        blk, blk_idx = inp
        active = blk_idx < nblocks  # (batch,)
        new = _compress_block(state, blk)
        merged = [
            (jnp.where(active, n[0], s[0]), jnp.where(active, n[1], s[1]))
            for s, n in zip(state, new)
        ]
        return merged, None

    idxs = jnp.arange(max_blocks, dtype=jnp.int32)
    state, _ = jax.lax.scan(step, state0, (blocks, idxs))

    out = []
    for hi, lo in state:
        for word, sh in ((hi, (24, 16, 8, 0)), (lo, (24, 16, 8, 0))):
            for s in sh:
                out.append(((word >> s) & 0xFF).astype(jnp.uint8))
    return jnp.stack(out, axis=-1)  # (batch, 64)
