"""Pallas TPU kernel for the ed25519 verify hot loop.

Why this exists: the XLA-compiled double-scalar-multiply is bounded by
HBM round-trips between fusion islands — measured 21.7 ns/double/lane vs
1-5 ns for the same arithmetic inside one Pallas kernel whose limb planes
stay resident in VMEM (tools/exp_pallas_dbl.py, v5e).

Two design points differ from the XLA path (ops/f25519.py, curve25519.py):

1. **Shared-chain (Shamir/Straus) double-scalar-mul** instead of
   var-half + fixed-base comb: 64 windows of (4 doubles + two table
   adds).  The comb exists to avoid doublings for the base half, but in
   a shared chain the base half rides the variable half's doublings for
   free — and (decisively, for Mosaic) the only static table it needs is
   [0..15]B, expressible as scalar-literal vector constants.  Mosaic
   rejects captured array constants and cannot relayout dynamic
   window-indexed slices of a table input into limb-plane form, so the
   comb's 64 distinct window tables are unlowerable.

2. **Sublane-packed field geometry.** The XLA path's per-column
   convolution builds (1, batch) rows; on Mosaic every such row pads to
   a full (8, 128) tile — 8x the VMEM and 8x the ALU waste, which blew
   the 16 MB scoped-VMEM budget and spilled (measured 30 K/s).  Here a
   field element is (22, blk) with limbs on SUBLANES, and the 22x22
   limb convolution is 22 shifted whole-array multiply-accumulates into
   a (44, blk) column space: every op is a dense multi-tile vector op.
   Radix/magnitude discipline is identical to f25519.py (12-bit limbs,
   lazy adds < 8212, u32-exact 44-column accumulation < 2^32); the
   reduction is _reduce_wide/weak_reduce transcribed to this geometry.

Reference semantic contract: fd_ed25519_double_scalar_mul_base
(src/ballet/ed25519/fd_curve25519.c:123-160).

Grid is over the batch; each block owns `blk` lanes end-to-end, so the
only HBM traffic is the kernel's inputs/outputs.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve25519 as cv
from . import f25519 as fe

NWIN = 64
NL = fe.NLIMB          # 22
MASK = fe.MASK
B12 = fe.B             # 12 bits/limb
F264 = fe.FOLD264


def _constw(v: int):
    """Kernel-safe (22, 1) field constant (scalar literals; see
    fe._limb_const)."""
    return fe._limb_const(fe._to_limbs_py(v % fe.P), 2)


# ------------------------------------------------- field ops, (22, blk) geom


def _wr(x, passes=2):
    """weak_reduce on (22, blk): parallel shifted-carry passes + >=2^255
    fold.  Same magnitude contract as fe.weak_reduce."""
    for _ in range(passes):
        lo = x & MASK
        hi = x >> B12
        x = jnp.concatenate(
            [lo[:1] + hi[NL - 1 :] * F264, lo[1:] + hi[: NL - 1]], axis=0)
    t = x[NL - 1 :] >> 3
    x0 = x[:1] + t * 19
    c0 = x0 >> B12
    return jnp.concatenate(
        [x0 & MASK, x[1:2] + c0, x[2 : NL - 1], x[NL - 1 :] & 7], axis=0)


def _reduce44(c):
    """(44, blk) column accumulator -> NORMAL (22, blk).

    Two in-space carry passes bring every column <= ~4184, then the
    2^264 fold is DECOMPOSED: e_i = c_hi_i * 19 (<= 79496) splits into
    its 2^9-shifted limb contributions lo_i = (e_i << 9) & MASK (limb i)
    and hi_i = e_i >> 3 (limb i+1); the >=2^255 fold runs on the top
    limb first and ONE parallel carry pass finishes.  Bounds: r_i <=
    4184 + 4095 + 9937 = 18216; after top-fold limb0 <= 61479; the final
    pass leaves every limb <= ~4110 (NORMAL).  This replaces the 3-pass
    weak_reduce tail (the naive fold's limb-21-carry-times-9728 blowup
    is what forced 3 passes); measured as part of the round-3 lever set
    (tools/exp_r3_dsm.py)."""
    for _ in range(2):
        lo = c & MASK
        hi = c >> B12
        c = jnp.concatenate([lo[:1], lo[1:] + hi[:-1]], axis=0)
    d, ch = c[:NL], c[NL:]
    e = ch * 19                                     # <= 79496 (17 bits)
    lo = (e << 9) & MASK                            # contribution to limb i
    hi = e >> 3                                     # to limb i+1
    # c[43] is structurally zero so hi[21] (-> limb 22) carries nothing
    r = d + lo + jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    t = r[NL - 1 :] >> 3
    r = jnp.concatenate([r[:1] + t * 19, r[1 : NL - 1], r[NL - 1 :] & 7],
                        axis=0)
    lo = r & MASK
    hi = r >> B12
    return jnp.concatenate(
        [lo[:1] + hi[NL - 1 :] * F264, lo[1:] + hi[: NL - 1]], axis=0)


def _cat(parts):
    parts = [p for p in parts if p.shape[0]]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def _mulw(a, b):
    """Field mul: 22 shifted whole-array MACs accumulated into TWO
    (22, blk) planes (columns 0..21 / 22..43) — each MAC row lands as two
    22-row adds instead of one concat-to-44-row add, the shape Mosaic
    schedules best of the measured ladder variants (tools/exp_r3_dsm.py).

    Exactness: inputs LAZY (limbs <= 8212 after one unreduced add), each
    product <= 8212^2 = 6.75e7, 22 accumulated terms <= 1.49e9 < 2^32."""
    z = jnp.zeros_like(a)
    acc_lo = jnp.zeros_like(a)
    acc_hi = jnp.zeros_like(a)
    for i in range(NL):
        t = b * a[i : i + 1]                      # (22, blk) broadcast mul
        if i == 0:
            acc_lo = acc_lo + t
        else:
            acc_lo = acc_lo + _cat([z[:i], t[: NL - i]])
            acc_hi = acc_hi + _cat([t[NL - i :], z[: NL - i]])
    return _reduce44(jnp.concatenate([acc_lo, acc_hi], axis=0))


def _sqrw(a):
    """Field square: the cross-term doubling trick (c_k = 2*sum_{i<k-i}
    a_i a_{k-i} + [k even] a_{k/2}^2) on the same split accumulator.

    Magnitudes: per-column cross-term count <= 11; 11 * 6.75e7 = 7.4e8
    < 2^31, doubled = 1.49e9, + diagonal 6.75e7 < 2^32 exact."""
    z = jnp.zeros_like(a)
    acc_lo = jnp.zeros_like(a)
    acc_hi = jnp.zeros_like(a)
    for i in range(NL - 1):
        t = a[i + 1 :] * a[i : i + 1]   # rows i+1..21 -> cols 2i+1..i+21
        lo = 2 * i + 1
        ln = NL - 1 - i
        n_lo = max(0, min(ln, NL - lo))
        if n_lo:
            acc_lo = acc_lo + _cat([z[:lo], t[:n_lo], z[: NL - lo - n_lo]])
        if ln - n_lo:
            start = max(lo, NL) - NL
            acc_hi = acc_hi + _cat(
                [z[:start], t[n_lo:], z[: NL - start - (ln - n_lo)]])
    acc = jnp.concatenate([acc_lo, acc_hi], axis=0)
    acc = acc + acc                                # double cross terms
    diag = a * a                                   # a_i^2 at column 2i
    de = jnp.stack([diag, jnp.zeros_like(diag)], axis=1).reshape(
        2 * NL, *diag.shape[1:])
    return _reduce44(acc + de)


def _addw(a, b):
    return _wr(a + b, passes=1)


def _subw(a, b, bias):
    return _wr(a + bias - b, passes=1)


# --------------------------------------------------- point ops, (22, blk)
# Formulas are cv.double / cv.add / cv.add_niels / cv.add_affine_niels /
# cv.to_niels restated in this geometry (dbl-2008-hwcd, add-2008-hwcd-3).


class _Pt(NamedTuple):
    X: jnp.ndarray
    Y: jnp.ndarray
    Z: jnp.ndarray
    T: jnp.ndarray


def _doublew(p: _Pt, bias, want_t: bool = True) -> _Pt:
    """dbl-2008-hwcd.  The INPUT T is never read, so inside a 4-double
    run only the last double (whose output feeds a table add) needs to
    produce T — want_t=False skips that mul (256 windows x 3 skipped
    muls; measured ~27%% off the chain, tools/exp_r3_dsm.py)."""
    XX = _sqrw(p.X)
    YY = _sqrw(p.Y)
    ZZ = _sqrw(p.Z)
    ZZ2 = _addw(ZZ, ZZ)
    XpY2 = _sqrw(p.X + p.Y)                        # lazy add, mul-safe
    Yp = _addw(YY, XX)
    Ym = _subw(YY, XX, bias)
    Ec = _subw(XpY2, Yp, bias)
    Tc = _subw(ZZ2, Ym, bias)
    return _Pt(_mulw(Ec, Tc), _mulw(Yp, Ym), _mulw(Ym, Tc),
               _mulw(Ec, Yp) if want_t else p.T)


def _addfull(p: _Pt, q: _Pt, bias, d2) -> _Pt:
    A = _mulw(_subw(p.Y, p.X, bias), _subw(q.Y, q.X, bias))
    Bv = _mulw(p.Y + p.X, q.Y + q.X)               # lazy adds
    C = _mulw(_mulw(p.T, q.T), d2)
    ZZ = _mulw(p.Z, q.Z)
    Dv = _addw(ZZ, ZZ)
    E = _subw(Bv, A, bias)
    F = _subw(Dv, C, bias)
    G = _addw(Dv, C)
    H = _addw(Bv, A)
    return _Pt(_mulw(E, F), _mulw(G, H), _mulw(F, G), _mulw(E, H))


class _Niels(NamedTuple):
    Ym: jnp.ndarray
    Yp: jnp.ndarray
    Z: jnp.ndarray
    T2d: jnp.ndarray


def _to_nielsw(p: _Pt, bias, d2) -> _Niels:
    return _Niels(_subw(p.Y, p.X, bias), _addw(p.Y, p.X), p.Z,
                  _mulw(p.T, d2))


def _add_nielsw(p: _Pt, q: _Niels, bias) -> _Pt:
    A = _mulw(_subw(p.Y, p.X, bias), q.Ym)
    Bv = _mulw(p.Y + p.X, q.Yp)
    C = _mulw(p.T, q.T2d)
    ZZ = _mulw(p.Z, q.Z)
    Dv = _addw(ZZ, ZZ)
    E = _subw(Bv, A, bias)
    F = _subw(Dv, C, bias)
    G = _addw(Dv, C)
    H = _addw(Bv, A)
    return _Pt(_mulw(E, F), _mulw(G, H), _mulw(F, G), _mulw(E, H))


def _add_affine_nielsw(p: _Pt, ym, yp, t2d, bias, want_t: bool = True) -> _Pt:
    """want_t=False: the affine add that CLOSES a window feeds the next
    window's first double, which ignores T — skip its mul."""
    A = _mulw(_subw(p.Y, p.X, bias), ym)
    Bv = _mulw(p.Y + p.X, yp)
    C = _mulw(p.T, t2d)
    Dv = _addw(p.Z, p.Z)
    E = _subw(Bv, A, bias)
    F = _subw(Dv, C, bias)
    G = _addw(Dv, C)
    H = _addw(Bv, A)
    return _Pt(_mulw(E, F), _mulw(G, H), _mulw(F, G),
               _mulw(E, H) if want_t else p.T)


# --------------------------------------------------------------- kernel


def _ones_k(blk):
    return jnp.concatenate(
        [jnp.full((1, blk), 1, jnp.int32),
         jnp.zeros((NL - 1, blk), jnp.int32)], axis=0)


def _identity_k(blk):
    z = jnp.zeros((NL, blk), jnp.int32)
    one = _ones_k(blk)
    return _Pt(z, one, one, z)


def _select_list(entries, idx, nbits=4):
    """entries: list of 2^nbits pytrees of (22, blk) planes; idx: (1, blk)
    u32.  Binary where-tree; (1, blk) masks broadcast over sublanes."""
    bits = [((idx >> k) & 1).astype(bool) for k in range(nbits)]
    cur = list(entries)
    for k in range(nbits):
        m = bits[k]
        cur = [
            jax.tree_util.tree_map(
                lambda hi, lo: jnp.where(m, hi, lo), cur[2 * i + 1], cur[2 * i]
            )
            for i in range(len(cur) // 2)
        ]
    return cur[0]


def _base_digit_table():
    """[i]B for i in 0..15 as affine-Niels scalar-literal constants
    (window 0 of the fixed-base tables — the only static table the
    shared-chain form needs)."""
    t = cv._BASE_TABS
    return [
        (fe._limb_const(t["Ym"][0, i], 2),
         fe._limb_const(t["Yp"][0, i], 2),
         fe._limb_const(t["T2d"][0, i], 2))
        for i in range(16)
    ]


# ------------------------------------------------------- signed windows
# 4-bit digits recoded to [-8, 8]: the variable table shrinks to
# [0..8]A (7 builder adds instead of 14), selects go 15-where -> 8-where
# + a cheap conditional negate, and kernel VMEM falls ~40% (larger blk
# headroom).  Negation of a Niels entry is (Ym,Yp) swap + T2d negate.


def signed_windows(w):
    """(64, *batch) u32 digits 0..15 -> (mag 0..8, sgn 0/1), value-
    preserving (sum mag*(-1)^sgn * 16^i == sum w_i 16^i).  Jittable
    low-to-high carry ripple; both ed25519 scalars are < L < 2^253 so
    the top window (<= 1) never overflows with the incoming carry."""
    def step(carry, wi):
        d = wi + carry
        over = d > 8
        mag = jnp.where(over, 16 - d, d)
        carry = over.astype(w.dtype)
        return carry, (mag, over.astype(w.dtype))
    _, (mags, sgns) = jax.lax.scan(
        step, jnp.zeros_like(w[0]), w)
    return mags, sgns


def signed_windows_ext(w):
    """signed_windows with the carry-out appended as an EXTRA top window
    (nwin -> nwin+1): value-preserving for scalars of ANY width relative
    to the window count.  Needed by the MSM p16 path — the RLC z scalars
    are full 128-bit values over nwin=32, so the in-place top window can
    overflow to 16 under the recode carry (unlike the < 2^253 ed25519
    scalars signed_windows was written for)."""
    def step(carry, wi):
        d = wi + carry
        over = d > 8
        mag = jnp.where(over, 16 - d, d)
        carry = over.astype(w.dtype)
        return carry, (mag, over.astype(w.dtype))
    carry, (mags, sgns) = jax.lax.scan(
        step, jnp.zeros_like(w[0]), w)
    mags = jnp.concatenate([mags, carry[None]], axis=0)
    sgns = jnp.concatenate([sgns, jnp.zeros_like(carry)[None]], axis=0)
    return mags, sgns


def _sel_signed_niels(tab9, mag, sgn, bias):
    """tab9: [0..8] Niels entries; mag (1, blk) in 0..8, sgn (1, blk)."""
    e8 = _select_list(tab9[:8], mag, nbits=3)
    is8 = mag == 8
    pick = jax.tree_util.tree_map(
        lambda a, b: jnp.where(is8, a, b), tab9[8], e8)
    neg = sgn == 1
    return _Niels(
        jnp.where(neg, pick.Yp, pick.Ym),
        jnp.where(neg, pick.Ym, pick.Yp),
        pick.Z,
        jnp.where(neg, _wr(bias - pick.T2d, passes=1), pick.T2d))


def _base_digit_table_signed():
    """[0..8]B affine-Niels constants plus precomputed NEGATED T2d (sign
    application is then three wheres, no in-kernel negation)."""
    t = cv._BASE_TABS
    one = fe._to_limbs_py(1)
    zero = fe._to_limbs_py(0)
    out = []
    for i in range(9):
        if i == 0:
            ym = yp = one
            t2 = nt2 = zero
        else:
            ym, yp, t2 = (t["Ym"][0, i], t["Yp"][0, i], t["T2d"][0, i])
            nt2 = fe._to_limbs_py(
                (fe.P - fe._from_limbs_py(t["T2d"][0, i])) % fe.P)
        out.append(tuple(fe._limb_const(v, 2) for v in (ym, yp, t2, nt2)))
    return out


def _sel_signed_base(tab9, mag, sgn):
    e8 = _select_list(tab9[:8], mag, nbits=3)
    is8 = mag == 8
    ym, yp, t2, nt2 = (jnp.where(is8, a, b) for a, b in zip(tab9[8], e8))
    neg = sgn == 1
    return (jnp.where(neg, yp, ym), jnp.where(neg, ym, yp),
            jnp.where(neg, nt2, t2))


def _dsm_chain(sm_ref, ss_ref, km_ref, ks_ref, a: _Pt, blk: int) -> _Pt:
    """Shared-chain [s]B + [k]A accumulation over SIGNED windows (kernel
    body helper).  s/k mag+sign refs are (64, blk) u32."""
    bias = fe._limb_const(fe._BIAS_PY, 2)           # (22, 1)
    d2 = _constw(cv.D2)

    # per-lane variable-point Niels table: [0]A .. [8]A
    pts = [_identity_k(blk), a]
    for _ in range(7):
        pts.append(_addfull(pts[-1], a, bias, d2))
    tab_a = [_to_nielsw(p, bias, d2) for p in pts]
    tab_b = _base_digit_table_signed()

    def body(i, acc):
        w = NWIN - 1 - i
        for j in range(4):
            acc = _doublew(acc, bias, want_t=(j == 3))
        km = km_ref[pl.ds(w, 1), :]                  # (1, blk)
        ks = ks_ref[pl.ds(w, 1), :]
        acc = _add_nielsw(acc, _sel_signed_niels(tab_a, km, ks, bias), bias)
        sm = sm_ref[pl.ds(w, 1), :]
        ss = ss_ref[pl.ds(w, 1), :]
        ym, yp, t2d = _sel_signed_base(tab_b, sm, ss)
        return _add_affine_nielsw(acc, ym, yp, t2d, bias, want_t=False)

    return jax.lax.fori_loop(0, NWIN, body, _identity_k(blk))


def _dsm_kernel(blk: int):
    """out = [s]B + [k]A for one block of `blk` lanes, shared-chain."""

    def kernel(sm_ref, ss_ref, km_ref, ks_ref,
               ax_ref, ay_ref, az_ref, at_ref,
               xo_ref, yo_ref, zo_ref, to_ref):
        a = _Pt(ax_ref[...], ay_ref[...], az_ref[...], at_ref[...])
        acc = _dsm_chain(sm_ref, ss_ref, km_ref, ks_ref, a, blk)
        # the T-skip chain leaves the final T stale; one identity-add
        # rescales to (4XZ, 4YZ, 4Z^2, 4XY) — same point, valid T
        bias = fe._limb_const(fe._BIAS_PY, 2)
        one = _ones_k(blk)
        acc = _add_nielsw(acc, _Niels(one, one, one, _identity_k(blk).X),
                          bias)
        xo_ref[...] = acc.X
        yo_ref[...] = acc.Y
        zo_ref[...] = acc.Z
        to_ref[...] = acc.T

    return kernel


def _dsm_tail_q_kernel(blk: int):
    """Q = [s]B + [k](-A) for one block — the compressed-R verify
    (round 4): the y-compare against R's encoded y runs IN-KERNEL
    (one mul + canon), only Q's X/Z planes leave VMEM for the XLA-side
    x-parity check (batch inversion).  Eliminates the R decompress sqrt
    chain (~half of the 53.6 ms decompress stage at 32k)."""

    def kernel(sm_ref, ss_ref, km_ref, ks_ref,
               ax_ref, ay_ref, az_ref, at_ref, yr_ref,
               oky_ref, xo_ref, zo_ref):
        bias = fe._limb_const(fe._BIAS_PY, 2)
        neg_a = _Pt(
            _wr(bias - ax_ref[...], passes=1), ay_ref[...], az_ref[...],
            _wr(bias - at_ref[...], passes=1))
        acc = _dsm_chain(sm_ref, ss_ref, km_ref, ks_ref, neg_a, blk)
        ok_y = _canon_is_zero(
            _subw(acc.Y, _mulw(yr_ref[...], acc.Z), bias))
        oky_ref[...] = ok_y.astype(jnp.uint32)
        xo_ref[...] = acc.X
        zo_ref[...] = acc.Z

    return kernel


def dsm_tail_q(wins, a: cv.Point, y_r, blk: int = 128,
               interpret: bool = False):
    """Q = [s]B + [k](-A) with precomputed signed windows; returns
    (ok_y bool (batch,), X, Z planes) where ok_y is the projective
    y-compare Y == y_r * Z."""
    sm, ss, km, ks = wins
    batch = sm.shape[1]
    assert batch % blk == 0, (batch, blk)
    win_spec = pl.BlockSpec((NWIN, blk), lambda i: (0, i))
    pt_spec = pl.BlockSpec((NL, blk), lambda i: (0, i))
    bit_spec = pl.BlockSpec((1, blk), lambda i: (0, i))
    i32 = jnp.int32
    oky, x, z = pl.pallas_call(
        _dsm_tail_q_kernel(blk),
        out_shape=[jax.ShapeDtypeStruct((1, batch), jnp.uint32)]
        + [jax.ShapeDtypeStruct((NL, batch), jnp.int32)] * 2,
        grid=(batch // blk,),
        in_specs=[win_spec] * 4 + [pt_spec] * 5,
        out_specs=[bit_spec] + [pt_spec] * 2,
        interpret=interpret,
    )(sm, ss, km, ks, a.X.astype(i32), a.Y.astype(i32), a.Z.astype(i32),
      a.T.astype(i32), y_r.astype(i32))
    return oky[0] == 1, x.astype(jnp.uint32), z.astype(jnp.uint32)


def double_scalar_mul_base(s_windows, k_windows, a: cv.Point,
                           blk: int = 128, interpret: bool = False):
    """Drop-in Pallas replacement for cv.double_scalar_mul_base.

    s_windows, k_windows: uint32 (64, batch) unsigned digits; a: Point of
    (22, batch) planes.  batch must be a multiple of `blk`.
    """
    batch = s_windows.shape[1]
    assert batch % blk == 0, (batch, blk)
    sm, ss = signed_windows(s_windows)
    km, ks = signed_windows(k_windows)
    win_spec = pl.BlockSpec((NWIN, blk), lambda i: (0, i))
    pt_spec = pl.BlockSpec((NL, blk), lambda i: (0, i))
    i32 = jnp.int32
    outs = pl.pallas_call(
        _dsm_kernel(blk),
        out_shape=[jax.ShapeDtypeStruct((NL, batch), jnp.int32)] * 4,
        grid=(batch // blk,),
        in_specs=[win_spec] * 4 + [pt_spec] * 4,
        out_specs=[pt_spec] * 4,
        interpret=interpret,
    )(sm, ss, km, ks, a.X.astype(i32), a.Y.astype(i32), a.Z.astype(i32),
      a.T.astype(i32))
    return cv.Point(*(t.astype(jnp.uint32) for t in outs))


# --------------------------------------------------------- sqrt_ratio kernel


def _serial_carry(d):
    """Two exact serial carry passes + >=2^255 fold: representation unique
    up to {value, value+p} with value < p + 2^12 (fe.canonical's phase 1)."""
    for _ in range(2):
        rows = [d[i : i + 1] for i in range(NL)]
        for i in range(NL - 1):
            rows[i + 1] = rows[i + 1] + (rows[i] >> B12)
            rows[i] = rows[i] & MASK
        t = rows[NL - 1] >> 3
        rows[NL - 1] = rows[NL - 1] & 7
        rows[0] = rows[0] + t * 19
        d = jnp.concatenate(rows, axis=0)
    return d


def _canon_is_zero(d):
    """(22, blk) NORMAL-form -> (1, blk) bool: value ≡ 0 mod p (after the
    serial passes zero is represented as exactly 0 or p)."""
    d = _serial_carry(d)
    p_limbs = fe._limb_const(fe._to_limbs_py(fe.P), 2)
    is0 = jnp.min((d == 0).astype(jnp.int32), axis=0, keepdims=True)
    isp = jnp.min((d == p_limbs).astype(jnp.int32), axis=0, keepdims=True)
    return (is0 | isp) == 1


def _canon(d):
    """Full canonical form (fe.canonical in (22, blk) geometry): serial
    carries then two conditional subtracts of p."""
    d = _serial_carry(d)
    p_rows = [int(v) for v in fe._to_limbs_py(fe.P)]
    for _ in range(2):
        rows = [d[i : i + 1] for i in range(NL)]
        borrow = jnp.zeros_like(rows[0])
        diff = []
        for i in range(NL):
            t = rows[i] + jnp.int32(1 << B12) - jnp.int32(p_rows[i]) - borrow
            diff.append(t & MASK)
            borrow = 1 - (t >> B12)
        ge = borrow == 0
        d = jnp.concatenate(
            [jnp.where(ge, dd, rr) for dd, rr in zip(diff, rows)], axis=0)
    return d


def _eq_const(d_canon, val: int):
    """(22, blk) canonical == python constant -> (1, blk) bool."""
    c = fe._limb_const(fe._to_limbs_py(val), 2)
    return jnp.min((d_canon == c).astype(jnp.int32), axis=0,
                   keepdims=True) == 1


def _sqrt_uv(u, v, bias):
    """x = sqrt(u/v) candidate + ok/flip masks — RFC 8032 5.1.3 recipe
    (semantic contract: fe.sqrt_ratio / ref fd_f25519_sqrt_ratio).  The
    pow chain exploits (p-5)/8 = 2^252 - 3 whose 4-bit digits are
    F,F,...,F,D: every window multiplies by t^15 except the last (t^13) —
    no dynamic table selection at all."""
    v2 = _sqrw(v)
    v3 = _mulw(v2, v)
    v7 = _mulw(_sqrw(v2), v3)
    t0 = _mulw(u, v7)

    t2 = _sqrw(t0)
    t4 = _sqrw(t2)
    t8 = _sqrw(t4)
    t12 = _mulw(t8, t4)
    t13 = _mulw(t12, t0)
    t15 = _mulw(t13, t2)

    def body(i, r):
        for _ in range(4):
            r = _sqrw(r)
        return _mulw(r, t15)

    r = jax.lax.fori_loop(0, 61, body, t15)      # 62 leading F windows
    for _ in range(4):
        r = _sqrw(r)
    r = _mulw(r, t13)                             # trailing D window

    x = _mulw(_mulw(u, v3), r)
    vxx = _mulw(_sqrw(x), v)
    good = _canon_is_zero(_subw(vxx, u, bias))
    flipped = _canon_is_zero(_wr(vxx + u, passes=1))
    x = jnp.where(flipped, _mulw(x, _constw(fe.SQRT_M1)), x)
    return good | flipped, x


def _decompress_kernel(blk: int):
    """Full batch point decompression + small-order test in one kernel
    (semantic contract: fd_ed25519_point_frombytes,
    src/ballet/ed25519/fd_curve25519.c:26-63, plus
    fd_ed25519_affine_is_small_order).  Inputs are y limbs + sign bits
    (byte unpack stays in XLA); outputs ok/small masks, x, t=x*y."""

    def kernel(y_ref, sg_ref, ok_ref, sm_ref, x_ref, t_ref):
        bias = fe._limb_const(fe._BIAS_PY, 2)
        y = y_ref[...]
        sign = sg_ref[...]
        one = _ones_k(blk)
        yy = _sqrw(y)
        u = _subw(yy, one, bias)
        v = _addw(_mulw(yy, _constw(cv.D)), one)
        ok, x = _sqrt_uv(u, v, bias)

        xc = _canon(x)
        flip = (xc[:1] & 1) != sign
        x = jnp.where(flip, _wr(bias - x, passes=1), x)

        # small-order: x == 0 | y canonical in {0, order8_y0, order8_y1}
        yc = _canon(y)
        small = (
            _canon_is_zero(x)
            | _eq_const(yc, 0)
            | _eq_const(yc, cv._ORDER8_Y0 % fe.P)
            | _eq_const(yc, cv._ORDER8_Y1 % fe.P)
        )

        ok_ref[...] = ok.astype(jnp.uint32)
        sm_ref[...] = small.astype(jnp.uint32)
        x_ref[...] = x
        t_ref[...] = _mulw(x, y)

    return kernel


def decompress(b, blk: int = 256, interpret: bool = False):
    """Pallas replacement for cv.decompress + is_small_order_affine.

    b: uint8 (batch, 32).  Returns (ok (batch,), small (batch,), Point)."""
    batch = b.shape[0]
    assert batch % blk == 0, (batch, blk)
    y = fe.from_bytes(b)
    sign = (b[:, 31] >> 7).astype(jnp.uint32)[None, :]
    pt_spec = pl.BlockSpec((NL, blk), lambda i: (0, i))
    bit_spec = pl.BlockSpec((1, blk), lambda i: (0, i))
    ok, small, x, t = pl.pallas_call(
        _decompress_kernel(blk),
        out_shape=[jax.ShapeDtypeStruct((1, batch), jnp.uint32),
                   jax.ShapeDtypeStruct((1, batch), jnp.uint32),
                   jax.ShapeDtypeStruct((NL, batch), jnp.int32),
                   jax.ShapeDtypeStruct((NL, batch), jnp.int32)],
        grid=(batch // blk,),
        in_specs=[pt_spec, bit_spec],
        out_specs=[bit_spec, bit_spec, pt_spec, pt_spec],
        interpret=interpret,
    )(y.astype(jnp.int32), sign.astype(jnp.int32))
    x = x.astype(jnp.uint32)
    t = t.astype(jnp.uint32)
    one = fe.ones((batch,))
    return ok[0] == 1, small[0] == 1, cv.Point(x, y, one, t)


# ------------------------------------------- scalar reduce/recode kernel


def _rows(x):
    return [x[i : i + 1] for i in range(x.shape[0])]


def _b2l_rows(byte_rows, nlimb):
    """Little-endian byte rows -> 12-bit limb rows (scalar25519
    bytes_to_limbs transcribed to row ops)."""
    ngroups = (nlimb + 1) // 2
    need = 3 * ngroups + 1
    z = jnp.zeros_like(byte_rows[0])
    xs = list(byte_rows) + [z] * max(0, need - len(byte_rows))
    limbs = []
    for t in range(ngroups):
        limbs.append(xs[3 * t] | ((xs[3 * t + 1] & 0xF) << 8))
        limbs.append((xs[3 * t + 1] >> 4) | (xs[3 * t + 2] << 4))
    return limbs[:nlimb]


_SC_B = 12
_SC_MASK = (1 << _SC_B) - 1
_SC_L = 2**252 + 27742317777372353535851937790883648493
_SC_C = _SC_L - 2**252
_SC_C_LIMBS = [(_SC_C >> (_SC_B * i)) & _SC_MASK for i in range(11)]
_SC_L_LIMBS = [(_SC_L >> (_SC_B * i)) & _SC_MASK for i in range(22)]
_SC_L2_LIMBS = [((2 * _SC_L) >> (_SC_B * i)) & _SC_MASK for i in range(22)]


def _sc_carry_rows(rows, passes):
    for _ in range(passes):
        lo = [r & _SC_MASK for r in rows]
        hi = [r >> _SC_B for r in rows]          # arithmetic (int32)
        rows = [lo[0]] + [lo[i] + hi[i - 1] for i in range(1, len(rows))]
    return rows


def _sc_fold_rows(rows):
    """scalar25519._fold_once on row lists: lo(21) - C*hi with 2 headroom
    limbs (concat-ladder instead of at[].add — Mosaic has no DUS)."""
    n = len(rows)
    hi = rows[21:]
    m = n - 21
    out_len = max(21, m + 11) + 2
    z = jnp.zeros_like(rows[0])
    out = rows[:21] + [z] * (out_len - 21)
    for i in range(11):
        c = jnp.int32(_SC_C_LIMBS[i])
        for j, h in enumerate(hi):
            out[i + j] = out[i + j] - c * h
    return out


def _sc_cond_sub_rows(rows, times):
    n = len(rows)
    for i in range(n - 1):
        rows[i + 1] = rows[i + 1] + (rows[i] >> _SC_B)
        rows[i] = rows[i] & _SC_MASK
    rows = rows[:22]
    for _ in range(times):
        borrow = jnp.zeros_like(rows[0])
        diff = []
        for i in range(22):
            t = (rows[i] + jnp.int32(1 << _SC_B)
                 - jnp.int32(_SC_L_LIMBS[i]) - borrow)
            diff.append(t & _SC_MASK)
            borrow = 1 - (t >> _SC_B)
        ge = borrow == 0
        rows = [jnp.where(ge, d, r) for d, r in zip(diff, rows)]
    return rows


def _limbs_to_signed_windows(limb_rows):
    """22x12-bit limb rows -> 64 signed 4-bit window rows (mag, sgn).
    Window w covers bits [4w, 4w+4): limb w*4//12, shift (w%3)*4.  The
    recode ripples a carry low->high (same contract as signed_windows);
    the top window of an L-reduced scalar is <= 1 so it never overflows."""
    mags, sgns = [], []
    carry = jnp.zeros_like(limb_rows[0])
    for w in range(64):
        j, sh = divmod(w, 3)
        d = ((limb_rows[j] >> (4 * sh)) & 0xF) + carry
        over = d > 8
        mags.append(jnp.where(over, 16 - d, d).astype(jnp.uint32))
        sgns.append(over.astype(jnp.uint32))
        carry = over.astype(d.dtype)
    return mags, sgns


def _reduce_recode_kernel(blk: int):
    """s bytes + SHA-512 digest -> canonicity bit + signed windows for
    BOTH scalars, in one VMEM-resident pass.  Replaces the XLA chain
    (is_canonical, reduce_512, limbs_to_windows, scalar_windows, signed
    recode) whose ~200 serial (1, batch) row ops cost more at batch 32k
    than the whole dsm kernel (measured: reduce_512+windows ~90 ms vs
    dsm ~34 ms)."""

    def kernel(sb_ref, db_ref, oks_ref, sm_ref, ss_ref, km_ref, ks_ref):
        sb = [r.astype(jnp.int32) for r in _rows(sb_ref[...])]
        db = [r.astype(jnp.int32) for r in _rows(db_ref[...])]

        # ---- k = digest mod L (scalar25519.reduce_512 transcription)
        x = _b2l_rows(db, 44)
        for _ in range(3):
            x = _sc_fold_rows(x)
            x = _sc_carry_rows(x, 2)
        x = [x[i] + jnp.int32(_SC_L2_LIMBS[i]) if i < 22 else x[i]
             for i in range(len(x))]
        x = _sc_carry_rows(x, 3)
        k_limbs = _sc_cond_sub_rows(x, 4)
        km, ks = _limbs_to_signed_windows(k_limbs)

        # ---- s: canonicity (s < L) + windows
        s_limbs = _b2l_rows(sb, 22)
        borrow = jnp.zeros_like(s_limbs[0])
        for i in range(22):
            t = (s_limbs[i] + jnp.int32(1 << _SC_B)
                 - jnp.int32(_SC_L_LIMBS[i]) - borrow)
            borrow = 1 - (t >> _SC_B)
        ok_s = borrow == 1                       # borrow out -> s < L
        sm, ss = _limbs_to_signed_windows(s_limbs)

        oks_ref[...] = ok_s.astype(jnp.uint32)
        sm_ref[...] = jnp.concatenate(sm, axis=0)
        ss_ref[...] = jnp.concatenate(ss, axis=0)
        km_ref[...] = jnp.concatenate(km, axis=0)
        ks_ref[...] = jnp.concatenate(ks, axis=0)

    return kernel


def reduce_recode(s_bytes, digest, blk: int = 128, interpret: bool = False):
    """s_bytes: uint8 (batch, 32); digest: uint8 (batch, 64).
    Returns (ok_s bool (batch,), (smag, ssgn, kmag, ksgn) each uint32
    (64, batch)) — kernel-ready signed windows for dsm_tail_q."""
    batch = s_bytes.shape[0]
    assert batch % blk == 0, (batch, blk)
    sb = s_bytes.T.astype(jnp.uint32)
    db = digest.T.astype(jnp.uint32)
    in_specs = [pl.BlockSpec((32, blk), lambda i: (0, i)),
                pl.BlockSpec((64, blk), lambda i: (0, i))]
    bit_spec = pl.BlockSpec((1, blk), lambda i: (0, i))
    win_spec = pl.BlockSpec((NWIN, blk), lambda i: (0, i))
    ok, sm, ss, km, ks = pl.pallas_call(
        _reduce_recode_kernel(blk),
        out_shape=[jax.ShapeDtypeStruct((1, batch), jnp.uint32)]
        + [jax.ShapeDtypeStruct((NWIN, batch), jnp.uint32)] * 4,
        grid=(batch // blk,),
        in_specs=in_specs,
        out_specs=[bit_spec] + [win_spec] * 4,
        interpret=interpret,
    )(sb, db)
    return ok[0] == 1, (sm, ss, km, ks)


def _sc_mul_rows(a22, b11):
    """Row-list transcription of scalar25519.mul_mod_l for a 22x11 limb
    product (the RLC path's z*k and z*s): convolution (<= 11 products of
    two 12-bit limbs per column < 2^28, exact in int32), then the same
    normalize/fold/canonicalize ladder as the XLA reference."""
    z = jnp.zeros_like(a22[0])
    rows = [z] * (22 + 11)
    for i in range(11):
        c = b11[i]
        for j in range(22):
            rows[i + j] = rows[i + j] + c * a22[j]
    rows = _sc_carry_rows(rows, 3)
    while len(rows) > 23:
        rows = _sc_carry_rows(_sc_fold_rows(rows), 2)
    rows = _sc_carry_rows(_sc_fold_rows(rows), 2)
    rows = [rows[i] + jnp.int32(_SC_L2_LIMBS[i]) if i < 22 else rows[i]
            for i in range(len(rows))]
    rows = _sc_carry_rows(rows, 3)
    return _sc_cond_sub_rows(rows, 4)


def _limbs_to_u4_windows(limb_rows, nwin):
    """22x12-bit limb rows -> nwin unsigned 4-bit window rows (the MSM
    kernel's [0..15] table digits)."""
    return [((limb_rows[j // 3] >> (4 * (j % 3))) & 0xF).astype(jnp.uint32)
            for j in range(nwin)]


def _rlc_recode_kernel(blk: int):
    """RLC batch-verify scalar chain in ONE VMEM-resident pass:
    s canonicity, k = digest mod L, w = z*k mod L, zs = z*s mod L, and
    unsigned 4-bit windows of w (64) and z (32).

    MEASURED NEGATIVE RESULT (r4, kept for the record + parity test):
    106 ms at 32k vs the XLA chain's 60 ms.  The 22x11 mod-L convolutions
    here run as ~500 per-(1,blk)-row ops — 1/8 VPU tile utilization —
    while XLA vectorizes the identical chain across the full batch.
    verify_batch_rlc therefore keeps its scalars in XLA; a future rewrite
    would need _mulw-style whole-(22,blk)-array accumulation to pay off
    (docs/perf_ceiling.md round-4 addendum)."""

    def kernel(sb_ref, db_ref, zb_ref, oks_ref, ww_ref, zw_ref, zs_ref):
        sb = [r.astype(jnp.int32) for r in _rows(sb_ref[...])]
        db = [r.astype(jnp.int32) for r in _rows(db_ref[...])]
        zb = [r.astype(jnp.int32) for r in _rows(zb_ref[...])]

        # ---- k = digest mod L (reduce_512 transcription)
        x = _b2l_rows(db, 44)
        for _ in range(3):
            x = _sc_fold_rows(x)
            x = _sc_carry_rows(x, 2)
        x = [x[i] + jnp.int32(_SC_L2_LIMBS[i]) if i < 22 else x[i]
             for i in range(len(x))]
        x = _sc_carry_rows(x, 3)
        k_limbs = _sc_cond_sub_rows(x, 4)

        # ---- s canonicity (s < L)
        s_limbs = _b2l_rows(sb, 22)
        borrow = jnp.zeros_like(s_limbs[0])
        for i in range(22):
            t = (s_limbs[i] + jnp.int32(1 << _SC_B)
                 - jnp.int32(_SC_L_LIMBS[i]) - borrow)
            borrow = 1 - (t >> _SC_B)
        ok_s = borrow == 1

        # ---- z (128-bit host randomness) -> 11 limbs
        z_limbs = _b2l_rows(zb, 11)

        # ---- w = z*k, zs = z*s (both mod L, canonical limbs)
        w_limbs = _sc_mul_rows(k_limbs, z_limbs)
        zs_limbs = _sc_mul_rows(s_limbs, z_limbs)

        oks_ref[...] = ok_s.astype(jnp.uint32)
        ww_ref[...] = jnp.concatenate(
            _limbs_to_u4_windows(w_limbs, 64), axis=0)
        zw_ref[...] = jnp.concatenate(
            _limbs_to_u4_windows(z_limbs + [jnp.zeros_like(z_limbs[0])] * 11,
                                 32), axis=0)
        zs_ref[...] = jnp.concatenate(zs_limbs, axis=0)

    return kernel


def rlc_recode(s_bytes, digest, z_bytes, blk: int = 128,
               interpret: bool = False):
    """s_bytes: uint8 (batch, 32); digest: uint8 (batch, 64); z_bytes:
    uint8 (batch, 16).  Returns (ok_s bool (batch,), w_wins u32
    (64, batch), z_wins u32 (32, batch), zs_limbs i32 (22, batch))
    — MSM-ready unsigned windows plus per-lane z*s products for the
    XLA-side sum_mod_l reduction."""
    batch = s_bytes.shape[0]
    assert batch % blk == 0, (batch, blk)
    sb = s_bytes.T.astype(jnp.uint32)
    db = digest.T.astype(jnp.uint32)
    zb = z_bytes.T.astype(jnp.uint32)
    in_specs = [pl.BlockSpec((32, blk), lambda i: (0, i)),
                pl.BlockSpec((64, blk), lambda i: (0, i)),
                pl.BlockSpec((16, blk), lambda i: (0, i))]
    bit_spec = pl.BlockSpec((1, blk), lambda i: (0, i))
    ok, ww, zw, zs = pl.pallas_call(
        _rlc_recode_kernel(blk),
        out_shape=[jax.ShapeDtypeStruct((1, batch), jnp.uint32),
                   jax.ShapeDtypeStruct((64, batch), jnp.uint32),
                   jax.ShapeDtypeStruct((32, batch), jnp.uint32),
                   jax.ShapeDtypeStruct((22, batch), jnp.int32)],
        grid=(batch // blk,),
        in_specs=in_specs,
        out_specs=[bit_spec,
                   pl.BlockSpec((64, blk), lambda i: (0, i)),
                   pl.BlockSpec((32, blk), lambda i: (0, i)),
                   pl.BlockSpec((22, blk), lambda i: (0, i))],
        interpret=interpret,
    )(sb, db, zb)
    return ok[0] == 1, ww, zw, zs


# --------------------------------------------------- fused verify tail
# Round-5 structural lever (VERDICT r4 #1): ONE kernel does A-decompress,
# scalar reduce/recode and the dsm tail — the three hot kernels fused so
# A's planes and both scalars' windows never leave VMEM between stages
# (previously: 3 kernel launches with (22, batch) x4 + (64, batch) x4
# HBM round-trips between them, plus a separate negate pass over A).


def _fused_tail_kernel(blk: int):
    """pubkey y/sign + s bytes + SHA digest + R's y -> one combined ok bit
    (A decompresses & not small-order & s canonical & projective y match)
    plus Q's X/Z planes for the XLA-side x-parity tail.

    Body = _decompress_kernel + _reduce_recode_kernel + _dsm_tail_q_kernel
    compositions; windows stage through VMEM scratch refs because the dsm
    chain's window loop indexes a Ref via pl.ds (dynamic sublane slices of
    in-register arrays don't lower)."""

    def kernel(ay_ref, asg_ref, sb_ref, db_ref, yr_ref,
               ok_ref, xo_ref, zo_ref,
               sm_ref, ss_ref, km_ref, ks_ref):
        bias = fe._limb_const(fe._BIAS_PY, 2)
        one = _ones_k(blk)

        # ---- A decompress + small-order test (fd_ed25519_point_frombytes
        # + affine_is_small_order semantics, as _decompress_kernel)
        y = ay_ref[...]
        sign = asg_ref[...]
        yy = _sqrw(y)
        u = _subw(yy, one, bias)
        v = _addw(_mulw(yy, _constw(cv.D)), one)
        ok_a, x = _sqrt_uv(u, v, bias)
        xc = _canon(x)
        flip = (xc[:1] & 1) != sign
        x = jnp.where(flip, _wr(bias - x, passes=1), x)
        yc = _canon(y)
        small = (
            _canon_is_zero(x)
            | _eq_const(yc, 0)
            | _eq_const(yc, cv._ORDER8_Y0 % fe.P)
            | _eq_const(yc, cv._ORDER8_Y1 % fe.P)
        )
        # the chain computes [s]B + [k](-A): negate A in place (one mul
        # for T, where the split path paid a separate negate pass)
        neg_x = _wr(bias - x, passes=1)
        neg_a = _Pt(neg_x, y, one, _mulw(neg_x, y))

        # ---- s canonicity + signed windows for BOTH scalars (the
        # _reduce_recode_kernel body), staged into the scratch refs
        sb = [r.astype(jnp.int32) for r in _rows(sb_ref[...])]
        db = [r.astype(jnp.int32) for r in _rows(db_ref[...])]
        xr = _b2l_rows(db, 44)
        for _ in range(3):
            xr = _sc_fold_rows(xr)
            xr = _sc_carry_rows(xr, 2)
        xr = [xr[i] + jnp.int32(_SC_L2_LIMBS[i]) if i < 22 else xr[i]
              for i in range(len(xr))]
        xr = _sc_carry_rows(xr, 3)
        k_limbs = _sc_cond_sub_rows(xr, 4)
        km, ks = _limbs_to_signed_windows(k_limbs)

        s_limbs = _b2l_rows(sb, 22)
        borrow = jnp.zeros_like(s_limbs[0])
        for i in range(22):
            t = (s_limbs[i] + jnp.int32(1 << _SC_B)
                 - jnp.int32(_SC_L_LIMBS[i]) - borrow)
            borrow = 1 - (t >> _SC_B)
        ok_s = borrow == 1
        sm, ss = _limbs_to_signed_windows(s_limbs)

        sm_ref[...] = jnp.concatenate(sm, axis=0)
        ss_ref[...] = jnp.concatenate(ss, axis=0)
        km_ref[...] = jnp.concatenate(km, axis=0)
        ks_ref[...] = jnp.concatenate(ks, axis=0)

        # ---- shared-chain dsm + in-kernel projective y-compare
        acc = _dsm_chain(sm_ref, ss_ref, km_ref, ks_ref, neg_a, blk)
        ok_y = _canon_is_zero(
            _subw(acc.Y, _mulw(yr_ref[...], acc.Z), bias))

        ok_ref[...] = (ok_a & ~small & ok_s & ok_y).astype(jnp.uint32)
        xo_ref[...] = acc.X
        zo_ref[...] = acc.Z

    return kernel


def verify_tail_fused(pubkeys, s_bytes, digest, y_r, blk: int = 128,
                      interpret: bool = False):
    """Fused strict-verify tail: returns (ok bool (batch,), X, Z) where ok
    already folds A-decompress/small-order, S-canonicity and the
    projective y-compare; callers finish with the XLA x-parity check
    (ed25519._compressed_r_check with ok_y=ok)."""
    batch = pubkeys.shape[0]
    assert batch % blk == 0, (batch, blk)
    y = fe.from_bytes(pubkeys)
    sign = (pubkeys[:, 31] >> 7).astype(jnp.uint32)[None, :]
    sb = s_bytes.T.astype(jnp.uint32)
    db = digest.T.astype(jnp.uint32)
    pt_spec = pl.BlockSpec((NL, blk), lambda i: (0, i))
    bit_spec = pl.BlockSpec((1, blk), lambda i: (0, i))
    ok, x, z = pl.pallas_call(
        _fused_tail_kernel(blk),
        out_shape=[jax.ShapeDtypeStruct((1, batch), jnp.uint32)]
        + [jax.ShapeDtypeStruct((NL, batch), jnp.int32)] * 2,
        grid=(batch // blk,),
        in_specs=[pt_spec, bit_spec,
                  pl.BlockSpec((32, blk), lambda i: (0, i)),
                  pl.BlockSpec((64, blk), lambda i: (0, i)),
                  pt_spec],
        out_specs=[bit_spec] + [pt_spec] * 2,
        scratch_shapes=[pltpu.VMEM((NWIN, blk), jnp.uint32)] * 4,
        interpret=interpret,
    )(y.astype(jnp.int32), sign.astype(jnp.int32), sb, db,
      y_r.astype(jnp.int32))
    return ok[0] == 1, x.astype(jnp.uint32), z.astype(jnp.uint32)


# ------------------------------------------------------------- MSM kernel


def _msm_kernel(m: int, nwin: int, blk: int):
    """Lane-parallel Straus MSM (semantic contract: cv.msm): each lane
    accumulates its m points inside ONE shared 4-bit-window chain, so the
    4 doublings per window are paid once per lane, not once per point —
    per-point cost falls to nwin*4/m doublings + nwin adds.  This is the
    op-count win that makes RLC batch verification pay once the chain
    runs at Pallas (VMEM-resident) speed; under XLA the same structure
    lost to strict (round-1 finding, now obsolete — see
    docs/perf_ceiling.md).

    wins_ref: (nwin*m, blk) u32, row w*m+j = window w of point j's
    scalar.  Point planes: (m*22, blk), rows [22j, 22j+22) = point j.
    """

    def kernel(wins_ref, x_ref, y_ref, z_ref, t_ref,
               xo_ref, yo_ref, zo_ref, to_ref):
        bias = fe._limb_const(fe._BIAS_PY, 2)
        d2 = _constw(cv.D2)

        tabs = []
        for j in range(m):
            pj = _Pt(
                x_ref[22 * j : 22 * j + 22, :],
                y_ref[22 * j : 22 * j + 22, :],
                z_ref[22 * j : 22 * j + 22, :],
                t_ref[22 * j : 22 * j + 22, :])
            pts = [_identity_k(blk), pj]
            for _ in range(14):
                pts.append(_addfull(pts[-1], pj, bias, d2))
            tabs.append([_to_nielsw(p, bias, d2) for p in pts])

        def body(i, acc):
            w = nwin - 1 - i
            acc = jax.lax.fori_loop(
                0, 4, lambda _, q: _doublew(q, bias), acc)
            for j in range(m):
                wv = wins_ref[pl.ds(w * m + j, 1), :]
                acc = _add_nielsw(acc, _select_list(tabs[j], wv), bias)
            return acc

        acc = jax.lax.fori_loop(0, nwin, body, _identity_k(blk))
        xo_ref[...] = acc.X
        yo_ref[...] = acc.Y
        zo_ref[...] = acc.Z
        to_ref[...] = acc.T

    return kernel


# --------------------------------------------- select-redesigned MSM (r6)
# The r4 fused-chain profile pinned ~45% of kernel time on table selects
# (15-where binary trees over 4 planes x (22, blk) per add).  Lever
# measured here (docs/perf_ceiling.md round-5/6): shrink the data volume
# a select moves, not the add count.


def _pack16(x):
    """(22, blk) 12-bit limbs -> (11, blk): limb i | limb i+11 << 16.
    Safe for NORMAL/LAZY magnitudes (every limb < 2^14 << 2^16); the
    packed word stays positive in int32 so arithmetic >> unpacks
    exactly."""
    return x[:11] | (x[11:] << 16)


def _unpack16(p):
    return jnp.concatenate([p & 0xFFFF, (p >> 16) & 0xFFFF], axis=0)


def _sel_signed_p16(tab9, mag, sgn):
    """Two's-complement digit select over packed planes.  tab9: 9 entries
    of (pYm, pYp, pZ, pT2d, pNT2d) packed (11, blk) planes for digits
    0..8; mag (1, blk) 0..8, sgn (1, blk) 0/1.  3-bit where-tree over
    [0..8) + an is8 pick + three sign wheres, ALL on half-height packed
    planes; unpack only the four planes the add consumes."""
    e8 = _select_list(tab9[:8], mag, nbits=3)
    is8 = mag == 8
    ym, yp, z, t2, nt2 = (jnp.where(is8, a, b)
                          for a, b in zip(tab9[8], e8))
    neg = sgn == 1
    return _Niels(
        _unpack16(jnp.where(neg, yp, ym)),
        _unpack16(jnp.where(neg, ym, yp)),
        _unpack16(z),
        _unpack16(jnp.where(neg, nt2, t2)))


def _msm_kernel_p16(m: int, nwin: int, blk: int):
    """Straus MSM with the redesigned table select (semantic contract:
    bit-identical to _msm_kernel).  Three changes:

      * signed digits [-8..8] (signed_windows_ext): 9-entry tables need
        7 builder _addfulls per point instead of 14, and the select tree
        is 3 levels + is8 + sign instead of 4 levels over 16 entries
      * packed 16-bit limb planes: two 12-bit limbs per int32, so every
        where in the tree moves (11, blk) instead of (22, blk) — half
        the select data volume; unpack happens once, after the pick
      * negated T2d precomputed per table entry: applying the digit sign
        costs three wheres, no in-select field negation

    `nwin` here COUNTS the recode carry-out window (callers pass the
    unsigned window count + 1).  mag/sgn refs: (nwin*m, blk) u32, row
    w*m+j = window w of point j, same row convention as _msm_kernel.
    """

    def kernel(mag_ref, sgn_ref, x_ref, y_ref, z_ref, t_ref,
               xo_ref, yo_ref, zo_ref, to_ref):
        bias = fe._limb_const(fe._BIAS_PY, 2)
        d2 = _constw(cv.D2)

        tabs = []
        for j in range(m):
            pj = _Pt(
                x_ref[22 * j : 22 * j + 22, :],
                y_ref[22 * j : 22 * j + 22, :],
                z_ref[22 * j : 22 * j + 22, :],
                t_ref[22 * j : 22 * j + 22, :])
            pts = [_identity_k(blk), pj]
            for _ in range(7):
                pts.append(_addfull(pts[-1], pj, bias, d2))
            ent = []
            for p in pts:
                nl = _to_nielsw(p, bias, d2)
                nt2 = _wr(bias - nl.T2d, passes=1)
                ent.append(tuple(_pack16(v) for v in
                                 (nl.Ym, nl.Yp, nl.Z, nl.T2d, nt2)))
            tabs.append(ent)

        def body(i, acc):
            w = nwin - 1 - i
            acc = jax.lax.fori_loop(
                0, 4, lambda _, q: _doublew(q, bias), acc)
            for j in range(m):
                mg = mag_ref[pl.ds(w * m + j, 1), :]
                sg = sgn_ref[pl.ds(w * m + j, 1), :]
                acc = _add_nielsw(acc, _sel_signed_p16(tabs[j], mg, sg),
                                  bias)
            return acc

        acc = jax.lax.fori_loop(0, nwin, body, _identity_k(blk))
        xo_ref[...] = acc.X
        yo_ref[...] = acc.Y
        zo_ref[...] = acc.Z
        to_ref[...] = acc.T

    return kernel


def msm(windows, points: cv.Point, m: int = 8, nwin: int = 64,
        blk: int = 128, interpret: bool = False,
        select: str = "legacy") -> cv.Point:
    """Pallas replacement for cv.msm: Σ_i [s_i]P_i over a flat batch of n
    points.  windows: uint32 (nwin, n) low-window-first; points: (22, n)
    planes; n % (m*blk) == 0.  Returns one unbatched Point.

    select: "legacy" (unsigned 16-entry tables, 4-level where-tree) or
    "p16" (signed digits + packed 16-bit limb planes, _msm_kernel_p16) —
    same verdict bits either way (tests/test_curve_pallas.py).

    Layout note: cv.msm reshapes n -> (lanes, m) with the batch LAST; we
    keep the same (m, lanes) split so results are bit-identical: lane l
    accumulates points [j*lanes + l for j in range(m)].
    """
    n = windows.shape[1]
    assert n % m == 0, (n, m)
    lanes = n // m
    assert lanes % blk == 0, (lanes, blk)

    pl_planes = [p.reshape(m * NL, lanes) for p in
                 (points.X.reshape(NL, m, lanes).transpose(1, 0, 2),
                  points.Y.reshape(NL, m, lanes).transpose(1, 0, 2),
                  points.Z.reshape(NL, m, lanes).transpose(1, 0, 2),
                  points.T.reshape(NL, m, lanes).transpose(1, 0, 2))]
    pts_spec = pl.BlockSpec((m * NL, blk), lambda i: (0, i))
    out_spec = pl.BlockSpec((NL, blk), lambda i: (0, i))

    def rows(a, nw):
        # (nw, n) -> rows w*m+j over (lanes,): point j of lane l is flat
        # index j*lanes + l (cv.msm's reshape(m, lanes) convention)
        return a.reshape(nw, m, lanes).reshape(nw * m, lanes)

    if select == "p16":
        mags, sgns = signed_windows_ext(windows)     # (nwin+1, n)
        nw2 = nwin + 1
        win_spec = pl.BlockSpec((nw2 * m, blk), lambda i: (0, i))
        outs = pl.pallas_call(
            _msm_kernel_p16(m, nw2, blk),
            out_shape=[jax.ShapeDtypeStruct((NL, lanes), jnp.int32)] * 4,
            grid=(lanes // blk,),
            in_specs=[win_spec] * 2 + [pts_spec] * 4,
            out_specs=[out_spec] * 4,
            interpret=interpret,
        )(rows(mags, nw2), rows(sgns, nw2),
          *(t.astype(jnp.int32) for t in pl_planes))
    else:
        assert select == "legacy", select
        win_spec = pl.BlockSpec((nwin * m, blk), lambda i: (0, i))
        outs = pl.pallas_call(
            _msm_kernel(m, nwin, blk),
            out_shape=[jax.ShapeDtypeStruct((NL, lanes), jnp.int32)] * 4,
            grid=(lanes // blk,),
            in_specs=[win_spec] + [pts_spec] * 4,
            out_specs=[out_spec] * 4,
            interpret=interpret,
        )(rows(windows, nwin), *(t.astype(jnp.int32) for t in pl_planes))
    acc = cv.Point(*(t.astype(jnp.uint32) for t in outs))

    # tree-fold the lanes to one point (XLA; log2(lanes) adds on
    # shrinking arrays)
    while lanes > 1:
        half = lanes // 2
        lo = cv.Point(*(t[:, :half] for t in acc))
        hi = cv.Point(*(t[:, half : 2 * half] for t in acc))
        s = cv.add(lo, hi)
        if lanes % 2:
            s = cv.Point(*(
                jnp.concatenate([ts, ta[:, 2 * half :]], axis=1)
                for ts, ta in zip(s, acc)))
            lanes = half + 1
        else:
            lanes = half
        acc = s
    return cv.Point(*(t[:, 0] for t in acc))
