"""Pallas TPU kernel for the ed25519 verify hot loop.

Why this exists: the XLA-compiled double-scalar-multiply is bounded by
HBM round-trips between fusion islands — measured 21.7 ns/double/lane vs
1-5 ns for the same arithmetic inside one Pallas kernel whose limb planes
stay resident in VMEM (tools/exp_pallas_dbl.py, v5e).

Design: [S]B + [k]A' (reference semantic contract:
fd_ed25519_double_scalar_mul_base, src/ballet/ed25519/fd_curve25519.c:
123-160) as ONE kernel using the shared-doubling-chain (Shamir/Straus)
form: 64 windows of (4 doubles + two table adds), NOT the XLA path's
var-half + fixed-base comb split.  The comb exists to avoid doublings for
the base half — but with a shared chain the base half rides the variable
half's doublings for free, and (decisively, for Mosaic) its 16-entry
[0..15]B table is a static constant expressible as scalar-literal vector
constants: Mosaic rejects captured array constants and cannot relayout a
dynamic (window-indexed) slice of a table input into limb-plane form, so
the comb's 64 distinct window tables are unlowerable, while Shamir needs
only window 0.

The per-lane A' table (16 Niels entries) is built in VMEM from the input
point.  Grid is over the batch; each block owns `blk` lanes end-to-end,
so the only HBM traffic is the kernel's inputs/outputs.  The arithmetic
is the ordinary f25519/curve25519 code — written to lower through both
XLA and Mosaic (concatenate-built carries, no scatter, scalar-literal
constants) — so this file is orchestration, not new math.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve25519 as cv
from . import f25519 as fe

NWIN = 64


def _ones_k(blk):
    """fe.ones without .at[] scatter (kernel-safe)."""
    return jnp.concatenate(
        [jnp.full((1, 1, blk), 1, jnp.uint32),
         jnp.zeros((fe.NLIMB - 1, 1, blk), jnp.uint32)], axis=0)


def _identity_k(blk):
    z = jnp.zeros((fe.NLIMB, 1, blk), jnp.uint32)
    one = _ones_k(blk)
    return cv.Point(z, one, one, z)


def _select_list(entries, idx, nbits=4):
    """entries: list of 2^nbits pytrees of (22,1,blk) planes; idx: (1,blk)
    u32.  Binary where-tree, list-based so no stacked (16,22,blk)
    intermediate materializes."""
    bits = [((idx >> k) & 1).astype(bool) for k in range(nbits)]
    cur = list(entries)
    for k in range(nbits):
        m = bits[k]
        cur = [
            jax.tree_util.tree_map(
                lambda hi, lo: jnp.where(m, hi, lo), cur[2 * i + 1], cur[2 * i]
            )
            for i in range(len(cur) // 2)
        ]
    return cur[0]


def _base_digit_table():
    """[i]B for i in 0..15 as affine-Niels scalar-literal constants
    (window 0 of the fixed-base tables; the only static table Shamir
    needs)."""
    t = cv._BASE_TABS
    return [
        (fe._limb_const(t["Ym"][0, i], 3),
         fe._limb_const(t["Yp"][0, i], 3),
         fe._limb_const(t["T2d"][0, i], 3))
        for i in range(16)
    ]


def _dsm_kernel(blk: int):
    """out = [s]B + [k]A for one block of `blk` lanes, shared-chain."""

    def kernel(sw_ref, kw_ref, ax_ref, ay_ref, az_ref, at_ref,
               xo_ref, yo_ref, zo_ref, to_ref):
        a = cv.Point(
            ax_ref[...][:, None, :], ay_ref[...][:, None, :],
            az_ref[...][:, None, :], at_ref[...][:, None, :])

        # per-lane variable-point Niels table: [0]A .. [15]A
        pts = [_identity_k(blk), a]
        for _ in range(14):
            pts.append(cv.add(pts[-1], a))
        tab_a = [cv.to_niels(p) for p in pts]
        tab_b = _base_digit_table()

        def body(i, acc):
            w = NWIN - 1 - i
            acc = jax.lax.fori_loop(0, 4, lambda _, q: cv.double(q), acc)
            kw = kw_ref[pl.ds(w, 1), :]              # (1, blk)
            acc = cv.add_niels(acc, _select_list(tab_a, kw))
            sw = sw_ref[pl.ds(w, 1), :]
            ym, yp, t2d = _select_list(tab_b, sw)
            return cv.add_affine_niels(acc, ym, yp, t2d)

        acc = jax.lax.fori_loop(0, NWIN, body, _identity_k(blk))
        xo_ref[...] = acc.X[:, 0, :]
        yo_ref[...] = acc.Y[:, 0, :]
        zo_ref[...] = acc.Z[:, 0, :]
        to_ref[...] = acc.T[:, 0, :]

    return kernel


def double_scalar_mul_base(s_windows, k_windows, a: cv.Point,
                           blk: int = 256, interpret: bool = False):
    """Drop-in Pallas replacement for cv.double_scalar_mul_base.

    s_windows, k_windows: uint32 (64, batch); a: Point of (22, batch)
    planes.  batch must be a multiple of `blk`.
    """
    batch = s_windows.shape[1]
    assert batch % blk == 0, (batch, blk)
    win_spec = pl.BlockSpec((NWIN, blk), lambda i: (0, i))
    pt_spec = pl.BlockSpec((fe.NLIMB, blk), lambda i: (0, i))
    outs = pl.pallas_call(
        _dsm_kernel(blk),
        out_shape=[jax.ShapeDtypeStruct((fe.NLIMB, batch), jnp.uint32)] * 4,
        grid=(batch // blk,),
        in_specs=[win_spec, win_spec] + [pt_spec] * 4,
        out_specs=[pt_spec] * 4,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(s_windows, k_windows, a.X, a.Y, a.Z, a.T)
    return cv.Point(*outs)
