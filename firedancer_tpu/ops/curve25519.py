"""Batched ed25519 curve arithmetic (twisted Edwards, a=-1) on TPU.

Points are NamedTuples of four (22, *batch) limb planes in extended
homogeneous coordinates (X:Y:Z:T), x=X/Z, y=Y/Z, T=XY/Z — the same
representation as the reference's fd_ed25519_point_t (reference:
src/ballet/ed25519/ref/fd_curve25519.h), batched across the trailing axes.

The scalar multiply is NOT a port of the reference's wNAF loop
(src/ballet/ed25519/ref/fd_curve25519.c:123-160): signed digits would need
per-element branches.  Instead we use fixed 4-bit windows with table
selection via one-hot masked accumulation — constant control flow, identical
work for every batch element, which is exactly what the VPU wants (and is
constant-time as a side effect, like the reference's _const_time variants).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import f25519 as fe

P = fe.P
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = fe.SQRT_M1

# order-8 subgroup y coordinates (ref fd_curve25519.h:82-113 small-order table)
_ORDER8_Y0 = int.from_bytes(
    bytes.fromhex("26e8958fc2b227b045c3f489f2ef98f0d5dfac05d3c63339b13802886d53fc05"),
    "little",
) & ((1 << 255) - 1)
_ORDER8_Y1 = int.from_bytes(
    bytes.fromhex("c7176a703d4dd84fba3c0b760d10670f2a2053fa2c39ccc64ec7fd7792ac037a"),
    "little",
) & ((1 << 255) - 1)


class Point(NamedTuple):
    """Extended (X:Y:Z:T) point; each field is a (22, *batch) limb plane."""

    X: jnp.ndarray
    Y: jnp.ndarray
    Z: jnp.ndarray
    T: jnp.ndarray


def identity(batch_shape) -> Point:
    return Point(
        fe.zeros(batch_shape), fe.ones(batch_shape), fe.ones(batch_shape), fe.zeros(batch_shape)
    )


def _identity_like(ref) -> Point:
    """Identity point whose limbs inherit `ref`'s varying manual-mesh axes
    (loop carries must match the loop body's vma under shard_map; a purely
    constant identity carry trips jax's check against varying inputs)."""
    vz = (ref[0] * 0).astype(jnp.uint32)
    return Point(*(f + vz for f in identity(ref.shape[1:])))


def point_const(x: int, y: int, ndim: int) -> Point:
    return Point(
        fe.const(x, ndim), fe.const(y, ndim), fe.const(1, ndim), fe.const(x * y % P, ndim)
    )


# base point
_BASE_Y = 4 * pow(5, P - 2, P) % P
_u, _v = (_BASE_Y * _BASE_Y - 1) % P, (D * _BASE_Y * _BASE_Y + 1) % P
_BASE_X = (_u * pow(_v, 3, P) % P) * pow(_u * pow(_v, 7, P) % P, (P - 5) // 8, P) % P
if (_v * _BASE_X * _BASE_X - _u) % P != 0:
    _BASE_X = _BASE_X * SQRT_M1 % P
if _BASE_X & 1:
    _BASE_X = (-_BASE_X) % P
BASE_X, BASE_Y = _BASE_X, _BASE_Y


def add(p: Point, q: Point) -> Point:
    """Unified addition (add-2008-hwcd-3 for a=-1); complete on the curve,
    identity-safe — the property that makes a branch-free batch loop legal."""
    A = fe.mul(fe.sub(p.Y, p.X), fe.sub(q.Y, q.X))
    Bv = fe.mul(fe.add(p.Y, p.X), fe.add(q.Y, q.X))
    C = fe.mul(fe.mul(p.T, q.T), fe.const(D2, p.T.ndim))
    ZZ = fe.mul(p.Z, q.Z)
    Dv = fe.add(ZZ, ZZ)
    E = fe.sub(Bv, A)
    F = fe.sub(Dv, C)
    G = fe.add(Dv, C)
    H = fe.add(Bv, A)
    return Point(fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def double(p: Point) -> Point:
    """Dedicated doubling (dbl-2008-hwcd, 4M+4S) — the hot op: the 256
    doublings dominate the double-scalar multiply."""
    XX = fe.sqr(p.X)
    YY = fe.sqr(p.Y)
    ZZ2 = fe.add(fe.sqr(p.Z), fe.sqr(p.Z))
    XpY2 = fe.sqr(fe.add_nr(p.X, p.Y))
    Yp = fe.add(YY, XX)       # Y² + X²
    Ym = fe.sub(YY, XX)       # Y² - X²
    Ec = fe.sub(XpY2, Yp)     # 2XY
    Tc = fe.sub(ZZ2, Ym)
    return Point(fe.mul(Ec, Tc), fe.mul(Yp, Ym), fe.mul(Ym, Tc), fe.mul(Ec, Yp))


def neg(p: Point) -> Point:
    return Point(fe.neg(p.X), p.Y, p.Z, fe.neg(p.T))


def is_identity(p: Point):
    """Lane mask: projective point == the group identity (0 : 1 : 1).
    The verify chains' final equality check (X == 0 and Y == Z covers
    every projective representative of the neutral element)."""
    return fe.is_zero(p.X) & fe.eq(p.Y, p.Z)


class Niels(NamedTuple):
    """Precomputed-point form (Y-X, Y+X, Z, 2dT): the reference's
    fd_ed25519_point precomputed tables play the same game (ref
    avx512/fd_r43x6_ge.c precomputation; dalek's ProjectiveNielsPoint).
    Folding the (Y±X) sums and the 2d·T constant multiply into the table
    turns the 9-mul unified add into an 8-mul add (7 when Z==1)."""

    Ym: jnp.ndarray
    Yp: jnp.ndarray
    Z: jnp.ndarray
    T2d: jnp.ndarray


def to_niels(p: Point) -> Niels:
    return Niels(fe.sub(p.Y, p.X), fe.add(p.Y, p.X), p.Z,
                 fe.mul(p.T, fe.const(D2, p.T.ndim)))


def add_niels(p: Point, q: Niels) -> Point:
    """p + q with q in precomputed form: 8 field muls."""
    A = fe.mul(fe.sub(p.Y, p.X), q.Ym)
    Bv = fe.mul(fe.add(p.Y, p.X), q.Yp)
    C = fe.mul(p.T, q.T2d)
    ZZ = fe.mul(p.Z, q.Z)
    Dv = fe.add(ZZ, ZZ)
    E = fe.sub(Bv, A)
    F = fe.sub(Dv, C)
    G = fe.add(Dv, C)
    H = fe.add(Bv, A)
    return Point(fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def add_affine_niels(p: Point, ym, yp, t2d) -> Point:
    """p + q with q affine (Z==1) precomputed: 7 field muls."""
    A = fe.mul(fe.sub(p.Y, p.X), ym)
    Bv = fe.mul(fe.add(p.Y, p.X), yp)
    C = fe.mul(p.T, t2d)
    Dv = fe.add(p.Z, p.Z)
    E = fe.sub(Bv, A)
    F = fe.sub(Dv, C)
    G = fe.add(Dv, C)
    H = fe.add(Bv, A)
    return Point(fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def select(mask, p: Point, q: Point) -> Point:
    """Per-batch-element select: mask ? p : q  (mask: bool (*batch,))."""
    return Point(*(jnp.where(mask, a, b) for a, b in zip(p, q)))


def eq(p: Point, q: Point):
    """Projective equality: X1·Z2 == X2·Z1 and Y1·Z2 == Y2·Z1."""
    return fe.eq(fe.mul(p.X, q.Z), fe.mul(q.X, p.Z)) & fe.eq(
        fe.mul(p.Y, q.Z), fe.mul(q.Y, p.Z)
    )


def eq_z1(p: Point, q: Point):
    """Equality against an affine (Z==1) point, saving two muls
    (ref fd_ed25519_point_eq_z1)."""
    return fe.eq(p.X, fe.mul(q.X, p.Z)) & fe.eq(p.Y, fe.mul(q.Y, p.Z))


def is_small_order_affine(p: Point):
    """Order <= 8 test for affine (Z==1) points: X==0 or Y==0 or Y is an
    order-8 y (ref fd_ed25519_affine_is_small_order, fd_curve25519.h:82-113)."""
    yc = fe.canonical(p.Y)
    y0 = fe.const(_ORDER8_Y0, p.Y.ndim)
    y1 = fe.const(_ORDER8_Y1, p.Y.ndim)
    return (
        fe.is_zero(p.X)
        | jnp.all(yc == 0, axis=0)
        | jnp.all(yc == y0, axis=0)
        | jnp.all(yc == y1, axis=0)
    )


def decompress(b):
    """Batch point decompression.  b: uint8 (*batch, 32).

    Returns (ok, Point) — semantics of fd_ed25519_point_frombytes
    (src/ballet/ed25519/fd_curve25519.c:26-63): non-canonical y accepted,
    x==0-with-sign-set accepted (rejected later as small order).  For ok=False
    lanes the point limbs are unspecified but arithmetic-safe."""
    y = fe.from_bytes(b)
    sign = (b[..., 31] >> 7).astype(jnp.uint32)
    yy = fe.sqr(y)
    u = fe.sub(yy, fe.ones(yy.shape[1:]))
    v = fe.add(fe.mul(yy, fe.const(D, yy.ndim)), fe.ones(yy.shape[1:]))
    ok, x = fe.sqrt_ratio(u, v)
    flip = fe.sgn(x) != sign
    x = jnp.where(flip, fe.neg(x), x)
    t = fe.mul(x, y)
    one = fe.ones(y.shape[1:])
    return ok, Point(x, y, one, t)


def compress(p: Point):
    """Serialize to 32 bytes (*batch, 32); costs one field inversion
    (ref fd_ed25519_point_tobytes)."""
    zi = fe.inv(p.Z)
    x = fe.mul(p.X, zi)
    y = fe.mul(p.Y, zi)
    by = fe.to_bytes(y)
    sign = (fe.sgn(x) << 7).astype(jnp.uint8)
    return by.at[..., 31].add(sign)


# ------------------------------------------------------- scalar multiplication


def _table_select_var(tables, idx):
    """Select tables[idx[b]] per batch element via a 4-level binary
    where-tree over the index bits: 15 selects per plane vs the one-hot
    masked accumulate's 16 mul + 15 add (gathers would scalarize on TPU;
    selects are lane-regular single-op)."""
    n = tables[0].shape[0]
    assert n == 16
    cls = type(tables)
    bits = [((idx >> k) & 1).astype(bool) for k in range(4)]

    def sel(t):
        cur = [t[i] for i in range(n)]
        for k in range(4):
            m = bits[k]
            cur = [jnp.where(m, cur[2 * i + 1], cur[2 * i])
                   for i in range(len(cur) // 2)]
        return cur[0]

    return cls(*(sel(t) for t in tables))


import functools


@functools.partial(jax.jit, static_argnames=("n",))
def _build_var_table(p: Point, n: int = 16) -> Point:
    """[0]P, [1]P, ..., [n-1]P with a leading table axis.

    Built under lax.scan so the add traces ONCE: unrolled, the 14 chained
    adds alone put ~45k multiplies in the graph and dominated the XLA
    path's trace/compile/load time (measured 20.8 MB StableHLO for a
    1-lane verify; scan brings it to a fraction).  The jit wrapper is
    load-bearing: inlined under an outer jit, but EAGER callers (tests,
    host tools) compile the whole build as one cached graph — this
    jaxlib's CPU backend segfaults compiling the scan primitive
    per-op in eager dispatch."""
    def step(carry, _):
        return add(carry, p), carry
    _, tab = jax.lax.scan(step, _identity_like(p.X), None, length=n)
    return tab


@functools.partial(jax.jit, static_argnames=("n",))
def _build_var_niels_table(p: Point, n: int = 16) -> Niels:
    """Precomputed window table in Niels form: each of the 64 window adds
    then saves one mul.  Scanned + jitted — see _build_var_table."""
    def step(carry, _):
        return add(carry, p), to_niels(carry)
    _, ne = jax.lax.scan(step, _identity_like(p.X), None, length=n)
    return ne


def _base_window_tables(num_windows: int = 64, width_bits: int = 4):
    """Precomputed python-int tables T[w][i] = [i * 16^w]B for the fixed-base
    comb: eliminates doublings for the base-point half of the double-scalar
    multiply.  Returns numpy arrays (num_windows, 16, 22) per coordinate."""
    # python-int affine arithmetic (host-side, runs once at import)
    def padd(a, b):
        x1, y1, z1, t1 = a
        x2, y2, z2, t2 = b
        A = (y1 - x1) * (y2 - x2) % P
        Bv = (y1 + x1) * (y2 + x2) % P
        C = 2 * t1 * t2 * D % P
        Dv = 2 * z1 * z2 % P
        E, F, G, H = (Bv - A) % P, (Dv - C) % P, (Dv + C) % P, (Bv + A) % P
        return (E * F % P, G * H % P, F * G % P, E * H % P)

    def paff(a):
        x, y, z, t = a
        zi = pow(z, P - 2, P)
        return (x * zi % P, y * zi % P, 1, x * zi * y * zi % P)

    nent = 1 << width_bits
    base = (BASE_X, BASE_Y, 1, BASE_X * BASE_Y % P)
    # affine-niels entries (y-x, y+x, 2dxy): each comb add is then 7 muls
    tabs = {f: np.zeros((num_windows, nent, fe.NLIMB), dtype=np.uint32)
            for f in ("Ym", "Yp", "T2d")}
    cur = base
    for w in range(num_windows):
        acc = (0, 1, 1, 0)
        for i in range(nent):
            x, y, z, t = paff(acc) if i else acc
            tabs["Ym"][w, i] = fe._to_limbs_py((y - x) % P)
            tabs["Yp"][w, i] = fe._to_limbs_py((y + x) % P)
            tabs["T2d"][w, i] = fe._to_limbs_py(t * D2 % P)
            acc = padd(acc, cur)
        # advance cur by 16x: cur = [16^(w+1)]B
        for _ in range(width_bits):
            cur = padd(cur, cur)
        cur = paff(cur)
    return tabs


_BASE_TABS = _base_window_tables()


def scalar_windows(scalar_bytes):
    """Split little-endian 32-byte scalars into 64 4-bit windows.
    scalar_bytes: uint8 (*batch, 32) -> uint32 (64, *batch)."""
    x = scalar_bytes.astype(jnp.uint32)
    lo = x & 0xF
    hi = x >> 4
    inter = jnp.stack([lo, hi], axis=-1).reshape(*x.shape[:-1], 64)
    return jnp.moveaxis(inter, -1, 0)


def double_scalar_mul_base(s_windows, k_windows, a: Point) -> Point:
    """[s]B + [k]A with 4-bit windows, the analogue of
    fd_ed25519_double_scalar_mul_base (src/ballet/ed25519/fd_curve25519.c:123-160).

    The base-point half uses a fixed-base comb over affine-niels constant
    tables (7-mul adds, no doublings); the variable half uses a per-element
    16-entry niels table (8-mul adds) built with 14 adds.  Loop runs high
    window -> low with 4 doublings per window.
    """
    a_tab = _build_var_niels_table(a)

    # base comb tables as one stacked constant: (64, 16, 22) per plane
    base_tabs = {f: jnp.asarray(_BASE_TABS[f]) for f in ("Ym", "Yp", "T2d")}

    def body(i, acc: Point):
        w = 63 - i
        for _ in range(4):
            acc = double(acc)
        kw = k_windows[w]
        acc = add_niels(acc, _table_select_var(a_tab, kw))
        return acc

    acc = jax.lax.fori_loop(0, 64, body, _identity_like(a.X))

    # fixed-base comb half: sum over windows of T[w][s_w] — no doublings;
    # folded in after the variable half (order irrelevant, group is abelian).
    def comb_body(w, acc: Point):
        oh = _onehot(s_windows[w], 16)
        ym, yp, t2d = (
            jnp.tensordot(base_tabs[f][w].T, oh, axes=([1], [0]))
            .astype(jnp.uint32)
            for f in ("Ym", "Yp", "T2d")
        )
        return add_affine_niels(acc, ym, yp, t2d)

    acc2 = jax.lax.fori_loop(0, 64, comb_body, acc)
    return acc2


def _onehot(idx, n):
    return (
        jnp.arange(n, dtype=jnp.uint32).reshape((n,) + (1,) * idx.ndim) == idx
    ).astype(jnp.uint32)


def double_scalar_mul_halved(u_windows, v_windows, p: Point, q: Point,
                             nwin: int = 32) -> Point:
    """[u]P + [v]Q over `nwin` shared 4-bit windows — the Antipa
    halved-scalar chain (round-6 go/no-go, docs/perf_ceiling.md).  With
    both scalars < 2^(4*nwin) (the half-gcd guarantees < ~2^127), the
    chain pays 4*nwin doubles + 2*nwin table adds instead of the
    full-width 256 doubles; two var-point Niels tables (2 x 14 builder
    adds) replace the one table + base comb of double_scalar_mul_base."""
    p_tab = _build_var_niels_table(p)
    q_tab = _build_var_niels_table(q)

    def body(i, acc: Point):
        w = nwin - 1 - i
        for _ in range(4):
            acc = double(acc)
        acc = add_niels(acc, _table_select_var(p_tab, u_windows[w]))
        acc = add_niels(acc, _table_select_var(q_tab, v_windows[w]))
        return acc

    return jax.lax.fori_loop(0, nwin, body, _identity_like(p.X))


def scalar_mul(s_windows, p: Point) -> Point:
    """[s]P, variable point, 4-bit windows over a niels table."""
    tab = _build_var_niels_table(p)

    def body(i, acc: Point):
        w = 63 - i
        for _ in range(4):
            acc = double(acc)
        return add_niels(acc, _table_select_var(tab, s_windows[w]))

    return jax.lax.fori_loop(0, 64, body, _identity_like(p.X))


def msm(windows, points: Point, m: int = 8, nwin: int = 64) -> Point:
    """Multi-scalar multiply  Σ_i [s_i]P_i  over a flat batch of n points.

    Lane-parallel Straus: the batch is reshaped to (lanes, m); each lane
    accumulates its m points inside ONE shared 4-bit-window double-and-add
    loop (the 4 doublings per window are paid once per lane, not once per
    point), then lanes are tree-folded.  Per-point cost falls from
    256 dbl + 78 add (per-sig path) to 256/m dbl + 78 add — the win that
    makes random-linear-combination batch verification pay (wiredancer gets
    the same effect from its credit-chained pipeline; here it's lane math).

    windows: uint32 (nwin, n) 4-bit digits, low window first; only the low
    `nwin` windows are consumed (use nwin=32 for 128-bit scalars).
    points:  Point with flat (22, n) planes.  n must be divisible by m.
    Returns a single unbatched Point (trailing batch shape ()).
    """
    n = windows.shape[1]
    assert n % m == 0, (n, m)
    lanes = n // m
    # batch layout (m, lanes) with lanes LAST: every op inside the loop runs
    # on (22, lanes) tiles with the big axis on the TPU's 128-wide lane
    # dimension (m last would leave the VPU 1-m/128 idle)
    tabs = _build_var_niels_table(points)  # (16, 22, n)
    tabs = Niels(*(t.reshape(16, fe.NLIMB, m, lanes) for t in tabs))
    wins = windows.reshape(nwin, m, lanes)

    def body(i, acc: Point):
        w = nwin - 1 - i
        for _ in range(4):
            acc = double(acc)
        for j in range(m):
            sel = _table_select_var(
                Niels(*(t[:, :, j, :] for t in tabs)), wins[w, j, :])
            acc = add_niels(acc, sel)
        return acc

    # identity carry inherits the points' varying-mesh-axes so the loop
    # is legal under shard_map (see _identity_like)
    acc = jax.lax.fori_loop(
        0, nwin, body, _identity_like(tabs.Ym[0][:, 0, :]))

    # tree-fold the lanes to one point
    while lanes > 1:
        half = lanes // 2
        lo = Point(*(t[:, :half] for t in acc))
        hi = Point(*(t[:, half : 2 * half] for t in acc))
        s = add(lo, hi)
        if lanes % 2:  # carry the odd lane into the next round
            s = Point(*(
                jnp.concatenate([ts, ta[:, 2 * half :]], axis=1)
                for ts, ta in zip(s, acc)))
            lanes = half + 1
        else:
            lanes = half
        acc = s
    return Point(*(t[:, 0] for t in acc))


def scalar_mul_base(s_windows) -> Point:
    """[s]B via the fixed-base comb only (affine-niels tables)."""
    base_tabs = {f: jnp.asarray(_BASE_TABS[f]) for f in ("Ym", "Yp", "T2d")}

    def comb_body(w, acc: Point):
        oh = _onehot(s_windows[w], 16)
        ym, yp, t2d = (
            jnp.tensordot(base_tabs[f][w].T, oh, axes=([1], [0]))
            .astype(jnp.uint32)
            for f in ("Ym", "Yp", "T2d")
        )
        return add_affine_niels(acc, ym, yp, t2d)

    return jax.lax.fori_loop(0, 64, comb_body, _identity_like(s_windows))
