"""GF(2^255-19) field arithmetic, batched, TPU-first.

Design
------
A field element is a planar array of NLIMB=22 radix-2^12 limbs, dtype uint32,
shape ``(22, *batch)`` — the limb axis FIRST so the (large) batch axis maps to
the TPU's 128-wide lane dimension and every op below is a pure elementwise /
shifted-add vector op over the batch.

This plays the role of the reference's field element types: the portable
10x25.5-bit fd_f25519 (reference: src/ballet/ed25519/ref/fd_f25519.h) and the
AVX-512 radix-2^43x6 fd_r43x6 (src/ballet/ed25519/avx512/fd_r43x6.h:8-56).
The radix is chosen by the same range-analysis discipline that file documents,
redone for TPU uint32 vector lanes:

  * products of two 12(+lazy)-bit limbs fit in uint32
  * a 43-column schoolbook product column accumulates <= 22 terms:
    22 * (2^13.2)^2 < 2^32, so whole-product accumulation stays exact in
    uint32 with one level of lazy ("_nr") addition allowed on mul inputs
  * carry propagation is done with PARALLEL shifted-add passes (2-3 passes)
    instead of a serial 22-step chain — a carry-save normalization that keeps
    the VPU busy across the whole (22, B) tile

Magnitude invariants (audited in tests/test_f25519.py):

  NORMAL   limbs <= ~4106, top limb <= ~31; value < 2^255 + eps.
           Produced by every reducing op (add/sub/mul/sqr/neg/weak_reduce).
  LAZY     one add_nr of two NORMALs: limbs <= ~8212.  Valid mul/sqr input
           (the Karatsuba middle product stays uint32-exact up to here —
           see _conv); add_nr MUST NOT be nested twice before a mul.

Functions are shape-polymorphic over trailing batch dims and jit-safe.
"""

import jax
import jax.numpy as jnp
import numpy as np

B = 12                     # bits per limb
NLIMB = 22                 # 22 * 12 = 264 bits
MASK = (1 << B) - 1
P = 2**255 - 19
FOLD264 = 19 * 512         # 2^264 mod p  (2^264 = 2^9 * 2^255 ≡ 19 * 2^9)

_U32 = jnp.uint32


def _to_limbs_py(v: int) -> np.ndarray:
    assert 0 <= v < 1 << (B * NLIMB)
    return np.array([(v >> (B * i)) & MASK for i in range(NLIMB)], dtype=np.uint32)


def _from_limbs_py(l) -> int:
    return sum(int(x) << (B * i) for i, x in enumerate(np.asarray(l, dtype=np.uint64)))


# Subtraction bias: limbs of 4*p rebalanced (each limb 0..20 borrows 3 units
# from the limb above) so that every limb exceeds any LAZY subtrahend limb.
# bias ≡ 0 (mod p), so a + bias - b ≡ a - b with no per-limb underflow.
_w = _to_limbs_py(4 * P).astype(np.int64)
_BIAS_PY = np.concatenate([_w[:1] + 3 * 4096, _w[1:21] + 3 * 4096 - 3, _w[21:] - 3])
assert _from_limbs_py(_BIAS_PY) == 4 * P
assert _BIAS_PY[:21].min() >= 12288 and _BIAS_PY[21] >= 28
_BIAS_PY = _BIAS_PY.astype(np.uint32)


def _limb_const(limbs, ndim: int) -> jnp.ndarray:
    """(22, 1, ...) constant built from per-limb SCALAR literals — the
    Pallas-kernel-safe constructor: scalars are legal jaxpr literals inside
    kernels, while captured array constants are rejected by Mosaic.  ONLY
    for kernel bodies: in plain XLA graphs the 22 stacked broadcasts bloat
    the program (measured: multi-minute CPU compiles) — use const()/
    _bias() there, which emit one array constant."""
    one = (1,) * (ndim - 1)
    # int32, not uint32: TPU VPU int32 multiply measured 22% faster than
    # uint32 (tools/exp_r5_f32mul.py: 83.8 vs 102.6 ns/MAC/block) and every
    # kernel intermediate fits 2^31 (max accumulation 1.56e9) — the whole
    # Pallas field layer runs int32 (round 5)
    return jnp.stack(
        [jnp.full(one, int(v), dtype=jnp.int32) for v in limbs], axis=0)


def const(v: int, ndim: int = 1) -> jnp.ndarray:
    """Field constant as (22, 1, 1, ...) broadcastable against ndim-dim limbs."""
    c = _to_limbs_py(v % P)
    return jnp.asarray(c.reshape((NLIMB,) + (1,) * (ndim - 1)), dtype=_U32)


def _bias(ndim: int) -> jnp.ndarray:
    return jnp.asarray(
        _BIAS_PY.reshape((NLIMB,) + (1,) * (ndim - 1)), dtype=_U32)


def zeros(batch_shape) -> jnp.ndarray:
    return jnp.zeros((NLIMB, *batch_shape), dtype=_U32)


def ones(batch_shape) -> jnp.ndarray:
    return jnp.zeros((NLIMB, *batch_shape), dtype=_U32).at[0].set(1)


# ------------------------------------------------------------------ carries


def _shift_up(x):
    """Shift limbs one position up (toward higher significance); drop top."""
    return jnp.concatenate([jnp.zeros_like(x[:1]), x[:-1]], axis=0)


def weak_reduce(x, passes: int = 2):
    """Carry-normalize a (22, ...) accumulator to NORMAL form.

    Parallel shifted-add passes; the carry out of limb 21 wraps to limb 0
    with weight 2^264 mod p, then bits >= 255 are folded (*19) and a final
    single-limb mini-pass bounds limb 0.  `passes` must be sized to the input
    magnitude: 2 suffices for limbs < 2^20, 3 for limbs < 2^27.

    Built with concatenation (never .at[] scatter) so the same code lowers
    both through XLA and inside Pallas TPU kernels.
    """
    for _ in range(passes):
        lo = x & MASK
        hi = x >> B
        x = jnp.concatenate(
            [(lo[0] + hi[NLIMB - 1] * FOLD264)[None], lo[1:] + hi[:-1]],
            axis=0,
        )
    # fold bits >= 255 (limb 21 holds bits 252..263; keep its low 3 bits)
    t = x[NLIMB - 1] >> 3
    x0 = x[0] + t * 19
    c0 = x0 >> B
    return jnp.concatenate(
        [
            (x0 & MASK)[None],
            (x[1] + c0)[None],
            x[2 : NLIMB - 1],
            (x[NLIMB - 1] & 7)[None],
        ],
        axis=0,
    )


# ------------------------------------------------------------------ add/sub


def add_nr(a, b):
    """Lazy add, no carry ("nr" naming from ref fd_f25519_add_nr).

    Output is LAZY: valid as a mul/sqr input but must not be nested."""
    return a + b


def add(a, b):
    return weak_reduce(a + b, passes=1)


def sub(a, b):
    """a - b via the 4p bias; inputs may be LAZY."""
    return weak_reduce(a + _bias(a.ndim) - b, passes=1)


def neg(a):
    return weak_reduce(_bias(a.ndim) - a, passes=1)


# ------------------------------------------------------------------ mul


def _conv_rows(ar, br):
    """Schoolbook convolution of two equal-length row lists -> column list
    (len 2n-1).  Emitted as explicit per-column sums (producer/consumer
    chains XLA fuses into one kernel) rather than a chain of
    dynamic-update-slice accumulations."""
    n = len(ar)
    cols = []
    for k in range(2 * n - 1):
        lo = max(0, k - n + 1)
        hi = min(k, n - 1)
        c = ar[lo] * br[k - lo]
        for i in range(lo + 1, hi + 1):
            c = c + ar[i] * br[k - i]
        cols.append(c)
    return cols


def _conv(a, b):
    """22x22 limb convolution -> (44, ...) columns via one Karatsuba split:
    3 x (11x11) sub-convolutions = 363 lane-muls vs schoolbook's 484.

    Exactness (worst case LAZY inputs, limbs <= ~8212 after one add_nr):
      * p0/p1 columns <= 11 * 8212^2           = 7.42e8 < 2^30
      * (a0+a1) limbs <= 16424, so m columns   <= 11 * 16424^2
                                               = 2.97e9 < 2^32
      * mid = m - p0 - p1 is >= 0 per column (all product terms are
        non-negative and m's column set is a superset), so u32-exact
      * combined columns equal the schoolbook columns exactly,
        <= 22 * 8212^2 = 1.48e9 < 2^32       -- u32-exact
    A second nested add_nr (limbs ~16k) would push m past 2^32 — hence
    the module invariant that add_nr is never nested before a mul."""
    ar = [a[i] for i in range(NLIMB)]
    br = [b[i] for i in range(NLIMB)]
    h = NLIMB // 2
    p0 = _conv_rows(ar[:h], br[:h])                      # 21 cols
    p1 = _conv_rows(ar[h:], br[h:])
    sa = [x + y for x, y in zip(ar[:h], ar[h:])]
    sb = [x + y for x, y in zip(br[:h], br[h:])]
    m = _conv_rows(sa, sb)
    mid = [mm - x - y for mm, x, y in zip(m, p0, p1)]
    zero = jnp.zeros_like(p0[0])
    cols = []
    for k in range(2 * NLIMB - 1):
        c = p0[k] if k < 2 * h - 1 else None
        if h <= k < h + 2 * h - 1:
            t = mid[k - h]
            c = t if c is None else c + t
        if 2 * h <= k:
            t = p1[k - 2 * h] if k - 2 * h < 2 * h - 1 else None
            if t is not None:
                c = t if c is None else c + t
        cols.append(zero if c is None else c)
    cols.append(zero)  # column 43 is structurally zero
    return jnp.stack(cols, axis=0)


def _reduce_wide(c):
    """Reduce a (44, ...) column accumulator to NORMAL (22, ...) form."""
    # two in-array carry passes (no wrap: limb 43 has headroom by construction)
    for _ in range(2):
        lo = c & MASK
        hi = c >> B
        c = jnp.concatenate([lo[:1], lo[1:] + hi[:-1]], axis=0)
    # fold limbs 22..43 into 0..21: 2^(12(22+i)) ≡ FOLD264 * 2^(12 i)
    r = c[:NLIMB] + c[NLIMB:] * FOLD264
    return weak_reduce(r, passes=3)


def mul(a, b):
    return _reduce_wide(_conv(a, b))


def _conv_sqr_rows(ar):
    """Squaring convolution over a row list: c_k = 2·Σ_{i<k-i} a_i a_{k-i}
    (+ a_{k/2}² for even k) — ~half the limb products of the general conv
    (the classic squaring shortcut; ref fd_f25519_sqr does the same in its
    backends)."""
    n = len(ar)
    cols = []
    for k in range(2 * n - 1):
        lo = max(0, k - n + 1)
        terms = []
        i = lo
        while i < k - i:
            terms.append(ar[i] * ar[k - i])
            i += 1
        c = None
        if terms:
            c = terms[0]
            for t in terms[1:]:
                c = c + t
            c = c + c  # cross terms count twice
        if k % 2 == 0:
            sq = ar[k // 2] * ar[k // 2]
            c = sq if c is None else c + sq
        cols.append(c)
    return cols


def _conv_sqr(a):
    """Karatsuba squaring: 3 x 11-limb squaring sub-convs (~198 lane-muls
    vs 286 schoolbook-squared, 484 general).  mid = (a0+a1)^2 - a0^2 - a1^2
    = 2·a0·a1 >= 0 per column; magnitude analysis as in _conv (LAZY-safe)."""
    ar = [a[i] for i in range(NLIMB)]
    h = NLIMB // 2
    p0 = _conv_sqr_rows(ar[:h])
    p1 = _conv_sqr_rows(ar[h:])
    m = _conv_sqr_rows([x + y for x, y in zip(ar[:h], ar[h:])])
    mid = [mm - x - y for mm, x, y in zip(m, p0, p1)]
    zero = jnp.zeros_like(p0[0])
    cols = []
    for k in range(2 * NLIMB - 1):
        c = p0[k] if k < 2 * h - 1 else None
        if h <= k < h + 2 * h - 1:
            t = mid[k - h]
            c = t if c is None else c + t
        if 2 * h <= k and k - 2 * h < 2 * h - 1:
            t = p1[k - 2 * h]
            c = t if c is None else c + t
        cols.append(zero if c is None else c)
    cols.append(zero)
    return jnp.stack(cols, axis=0)


def sqr(a):
    return _reduce_wide(_conv_sqr(a))


def mul_small(a, c: int):
    """Multiply by a small python constant (c < 2^15)."""
    assert 0 < c < 1 << 15
    return weak_reduce(a * jnp.uint32(c), passes=3)


def mul_const(a, v: int):
    """Multiply by a field constant given as a python int."""
    return mul(a, const(v, a.ndim))


# ------------------------------------------------------------------ canonical


def canonical(x):
    """Fully reduce to the canonical representative in [0, p)."""
    for _ in range(2):
        # serial exact carry
        rows = [x[i] for i in range(NLIMB)]
        for i in range(NLIMB - 1):
            rows[i + 1] = rows[i + 1] + (rows[i] >> B)
            rows[i] = rows[i] & MASK
        # fold bits >= 255
        t = rows[NLIMB - 1] >> 3
        rows[NLIMB - 1] = rows[NLIMB - 1] & 7
        rows[0] = rows[0] + t * 19
        x = jnp.stack(rows, axis=0)
    # conditional subtract p (value < p + 2^12 here, so once is enough; do twice
    # for margin)
    p_limbs = _to_limbs_py(P)
    for _ in range(2):
        rows = [x[i] for i in range(NLIMB)]
        borrow = jnp.zeros_like(rows[0])
        diff = []
        for i in range(NLIMB):
            t = rows[i] + jnp.uint32(1 << B) - jnp.uint32(int(p_limbs[i])) - borrow
            diff.append(t & MASK)
            borrow = 1 - (t >> B)
        ge = borrow == 0  # no final borrow -> x >= p
        x = jnp.stack(
            [jnp.where(ge, d, r) for d, r in zip(diff, rows)], axis=0
        )
    return x


def eq(a, b):
    """Batch equality -> bool (*batch)."""
    return jnp.all(canonical(a) == canonical(b), axis=0)


def is_zero(a):
    return jnp.all(canonical(a) == 0, axis=0)


def sgn(a):
    """Low bit of the canonical representative (ref fd_f25519_sgn)."""
    return canonical(a)[0] & 1


# ------------------------------------------------------------------ pow


import functools as _functools


@_functools.partial(jax.jit, static_argnames=("e",))
def pow_const(a, e: int):
    """a^e for a fixed public exponent: 4-bit fixed windows over a
    16-entry power table.

    Per window the loop pays 4 sqr + 1 mul + a (16, 22)-row table select —
    versus bitwise square-and-multiply's 4 sqr + 4 mul + 4 full-width
    selects; for the 252-bit sqrt/inversion exponents that trades ~175
    field muls per chain for 14 table-build muls.  (The reference uses
    unrolled addition chains, ref/fd_f25519.c pow22523; on TPU the compact
    constant-trip loop compiles fast and keeps the graph small.)"""
    assert e > 0
    digits = []
    v = e
    while v:
        digits.append(v & 0xF)
        v >>= 4
    digits = digits[::-1]  # MSB window first; leading window is nonzero
    ndig = len(digits)
    dig_arr = jnp.asarray(np.array(digits, dtype=np.uint32))

    # table[i] = a^i for i in 0..15 (a^0 = 1), built under lax.scan so the
    # mul traces once (unrolled, 14 muls add ~20k ops to every pow chain's
    # graph — trace/compile/load time, see _build_var_table's note).
    # The carry derives from `a` (zeros_like, not ones()) so it inherits
    # a's varying mesh axes and the scan stays legal under shard_map.
    def _tab_step(carry, _):
        return mul(carry, a), carry
    one = jnp.zeros_like(a).at[0].set(1)
    _, tab = jax.lax.scan(_tab_step, one, None, length=16)

    def _sel(idx):
        # (16, 1, <1 per batch dim>) against tab (16, 22, *batch)
        onehot = (
            jnp.arange(16, dtype=_U32).reshape((16,) + (1,) * a.ndim) == idx
        ).astype(_U32)
        return jnp.sum(tab * onehot, axis=0).astype(_U32)

    def body(i, r):
        for _ in range(4):
            r = sqr(r)
        return mul(r, _sel(dig_arr[i]))

    r = _sel(dig_arr[0])
    return jax.lax.fori_loop(1, ndig, body, r)


def inv(a):
    return pow_const(a, P - 2)


SQRT_M1 = pow(2, (P - 1) // 4, P)


def sqrt_ratio(u, v):
    """(ok, x) with x = sqrt(u/v) when u/v is square (RFC 8032 5.1.3 recipe;
    ref fd_f25519_sqrt_ratio under src/ballet/ed25519).  For non-square
    ratios ok=False and x is unspecified (callers must mask)."""
    v2 = sqr(v)
    v3 = mul(v2, v)
    v7 = mul(mul(v2, v2), v3)
    t = pow_const(mul(u, v7), (P - 5) // 8)
    x = mul(mul(u, v3), t)
    vxx = mul(sqr(x), v)
    good = eq(vxx, u)
    flipped = eq(vxx, neg(u))
    x_alt = mul(x, const(SQRT_M1, x.ndim))
    x = jnp.where(flipped, x_alt, x)
    return good | flipped, x


# ------------------------------------------------------------------ ser/de


def from_bytes(b):
    """Little-endian 32 bytes -> limbs.  b: uint8 (..., 32) -> (22, ...).

    Bit 255 (the point-compression sign bit) is masked off; values >= p are
    NOT rejected (non-canonical encodings are accepted, matching
    fd_f25519_frombytes / dalek 2.x semantics)."""
    x = b.astype(_U32)
    top = x[..., 31] & 0x7F
    xs = [x[..., i] for i in range(31)] + [top, jnp.zeros_like(top)]  # 33 bytes
    limbs = []
    for t in range(11):
        limbs.append(xs[3 * t] | ((xs[3 * t + 1] & 0xF) << 8))
        limbs.append((xs[3 * t + 1] >> 4) | (xs[3 * t + 2] << 4))
    return jnp.stack(limbs, axis=0)


def to_bytes(a):
    """Canonical little-endian serialization -> uint8 (..., 32)."""
    l = canonical(a)
    bs = []
    for t in range(11):
        e, o = l[2 * t], l[2 * t + 1]
        bs.append(e & 0xFF)
        bs.append((e >> 8) | ((o & 0xF) << 4))
        bs.append(o >> 4)
    return jnp.stack(bs[:32], axis=-1).astype(jnp.uint8)


# ------------------------------------------------------------------ helpers


def max_limb(a) -> int:
    """Debug/audit helper: the largest limb magnitude (host int)."""
    return int(jnp.max(a))


def to_int(a) -> int:
    """Host-side: convert a single (22,) element to a python int."""
    return _from_limbs_py(np.asarray(a)) % P


def batch_inv(a, stop: int = 128):
    """Montgomery batch inversion over the batch axis, tree-shaped for
    SIMD: pair-products up (whole-array muls on halving sizes), ONE
    pow-chain inversion at the stop width, pair-unwinds down.  Total
    field-mul work ~= 3 muls per lane + one 250-sqr chain amortized over
    the whole batch — versus one chain per lane.

    stop: tree leaf width for the pow chain.  Do NOT reduce to 1: the
    chain's ~250 serial muls vectorize across `stop` lanes, and running
    them on a (22, 1) array measured ~30 ms of pure small-op overhead at
    32k (the r4 regression that made compressed-R verify slower than the
    decompress it replaced).

    a: (22, n) limbs, all nonzero (callers guard zero lanes and mask
    their results).  Returns (22, n) with out[i] = a[i]^-1."""
    n = a.shape[-1]
    if n <= stop:
        return inv(a)
    levels = []
    cur = a
    while cur.shape[-1] > stop:
        if cur.shape[-1] % 2:
            # pad with 1 (inv(1) = 1) BEFORE storing: every stored level
            # is even and its parent is exactly half its width
            pad = jnp.zeros_like(cur[:, :1]).at[0].set(1)
            cur = jnp.concatenate([cur, pad], axis=-1)
        levels.append(cur)
        cur = mul(cur[:, 0::2], cur[:, 1::2])
    down = inv(cur)
    # unwind: parent p = l*r  =>  inv(l) = inv(p)*r, inv(r) = inv(p)*l.
    # A padded parent level carries one extra inverse (of the pad) —
    # truncate down to this level's true pair count first.
    for lvl in levels[::-1]:
        left, right = lvl[:, 0::2], lvl[:, 1::2]
        down = down[:, : lvl.shape[-1] // 2]
        inv_left = mul(down, right)
        inv_right = mul(down, left)
        down = jnp.stack([inv_left, inv_right], axis=-1).reshape(
            lvl.shape[0], lvl.shape[-1])
    return down[:, :n]
