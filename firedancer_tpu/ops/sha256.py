"""Batched SHA-256 over variable-length messages, TPU-first.

Reference role: src/ballet/sha256/ (streaming + batch API, SHA-NI/AVX
backends).  Used by PoH (src/ballet/poh/), shred merkle trees
(src/ballet/bmtree/), and gossip/repair message signing.

Unlike SHA-512 (64-bit words emulated as uint32 pairs on TPU), SHA-256's
32-bit words map directly onto the VPU's native int32 lanes, so this is the
cheaper hash on TPU — one reason PoH/merkle work stays on sha256.  Batch
axis is the leading dim; variable lengths are handled by device-side padding
+ per-block active masks, same scheme as ops/sha512.py.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .sha512 import _iroot, _primes

_U32 = jnp.uint32


# H0 = frac(sqrt(p)), K = frac(cbrt(p)) to 32 bits over the first 8/64 primes
_H0 = np.array(
    [_iroot(p << 64, 2) & 0xFFFFFFFF for p in _primes(8)], dtype=np.uint32
)
_K = np.array(
    [_iroot(p << 96, 3) & 0xFFFFFFFF for p in _primes(64)], dtype=np.uint32
)


def _rotr(x, r: int):
    return (x >> r) | (x << (32 - r))


def _compress_block(state, blk):
    """One SHA-256 compression.  state: uint32 (8, batch); blk: uint8
    (batch, 64).  Schedule + 64 rounds as lax.scan (one-round-sized graph,
    same rationale as sha512._compress_block)."""
    b = blk.reshape(blk.shape[0], 16, 4).astype(_U32)
    w16 = ((b[:, :, 0] << 24) | (b[:, :, 1] << 16) | (b[:, :, 2] << 8) | b[:, :, 3]).T
    # w16: (16, batch)

    def sched_step(win, _):
        w15, w2 = win[1], win[14]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        nw = win[0] + s0 + win[9] + s1
        return jnp.concatenate([win[1:], nw[None]], axis=0), nw

    _, w_rest = jax.lax.scan(sched_step, w16, None, length=48)
    ws = jnp.concatenate([w16, w_rest], axis=0)  # (64, batch)

    def round_step(st, inp):
        w_t, kt = inp
        a, b_, c, d, e, f, g, h = st
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + kt + w_t
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b_) ^ (a & c) ^ (b_ & c)
        t2 = S0 + maj
        return jnp.stack([t1 + t2, a, b_, c, d + t1, e, f, g]), None

    stf, _ = jax.lax.scan(round_step, state, (ws, jnp.asarray(_K)))
    return state + stf


def pad_messages(msgs, lengths, max_blocks: int):
    """Device-side SHA-256 padding.  msgs: uint8 (batch, maxlen); lengths:
    int32 (batch,).  Returns (padded (batch, max_blocks*64), nblocks)."""
    batch, maxlen = msgs.shape
    total = max_blocks * 64
    lengths = lengths.astype(jnp.int32)
    nblocks = (lengths + 9 + 63) // 64
    j = jnp.arange(total, dtype=jnp.int32)[None, :]
    ln = lengths[:, None]
    src = jnp.pad(msgs, ((0, 0), (0, total - maxlen)))
    body = jnp.where(j < ln, src, 0)
    body = jnp.where(j == ln, jnp.uint8(0x80), body)
    # 64-bit big-endian bit length in the last 8 bytes of the final block;
    # message bit length < 2^32 in practice so only the low 4 bytes matter
    end = nblocks[:, None] * 64
    fpos = j - (end - 8)
    bitlen = (lengths.astype(jnp.uint32) * 8)[:, None]
    shift = (7 - fpos) * 8
    lbyte = jnp.where(
        (fpos >= 0) & (fpos < 8) & (shift < 32),
        (bitlen >> jnp.clip(shift, 0, 31)) & 0xFF,
        0,
    ).astype(jnp.uint8)
    return jnp.where((fpos >= 0) & (fpos < 8), lbyte, body), nblocks


def sha256(msgs, lengths, max_blocks: int | None = None):
    """Batched SHA-256.  msgs: uint8 (batch, maxlen); lengths: (batch,).
    Returns digests uint8 (batch, 32)."""
    batch, maxlen = msgs.shape
    if max_blocks is None:
        max_blocks = (maxlen + 9 + 63) // 64
    padded, nblocks = pad_messages(msgs, lengths, max_blocks)
    blocks = padded.reshape(batch, max_blocks, 64).transpose(1, 0, 2)

    vz = (blocks[0, :, 0] * 0).astype(_U32)
    state0 = jnp.asarray(_H0)[:, None] + vz[None, :]  # (8, batch)

    def step(state, inp):
        blk, blk_idx = inp
        active = blk_idx < nblocks  # (batch,)
        new = _compress_block(state, blk)
        return jnp.where(active[None, :], new, state), None

    idxs = jnp.arange(max_blocks, dtype=jnp.int32)
    state, _ = jax.lax.scan(step, state0, (blocks, idxs))
    return state_to_bytes(state)


def state_to_bytes(state):
    """uint32 (8, batch) big-endian → uint8 (batch, 32)."""
    out = []
    for i in range(8):
        for s in (24, 16, 8, 0):
            out.append(((state[i] >> s) & 0xFF).astype(jnp.uint8))
    return jnp.stack(out, axis=-1)


def sha256_fixed64(msgs64):
    """SHA-256 of exactly-64-byte messages (the merkle interior-node and PoH
    mixin shape): two blocks, second is constant padding — no length logic.
    msgs64: uint8 (batch, 64) → uint8 (batch, 32)."""
    batch = msgs64.shape[0]
    vz = (msgs64[:, 0] * 0).astype(_U32)
    state = jnp.asarray(_H0)[:, None] + vz[None, :]
    state = _compress_block(state, msgs64)
    pad = np.zeros((64,), dtype=np.uint8)
    pad[0] = 0x80
    pad[62] = 0x02  # bitlen 512 = 0x200 big-endian in last 8 bytes
    blk2 = jnp.broadcast_to(jnp.asarray(pad), (batch, 64))
    state = _compress_block(state, blk2)
    return state_to_bytes(state)


def sha256_fixed32(msgs32):
    """SHA-256 of exactly-32-byte messages (PoH tick: hash of prev hash):
    single block with constant padding.  (batch, 32) → (batch, 32)."""
    batch = msgs32.shape[0]
    pad = np.zeros((32,), dtype=np.uint8)
    pad[0] = 0x80
    pad[30] = 0x01  # bitlen 256 = 0x100
    blk = jnp.concatenate(
        [msgs32, jnp.broadcast_to(jnp.asarray(pad), (batch, 32))], axis=1
    )
    vz = (msgs32[:, 0] * 0).astype(_U32)
    state = jnp.asarray(_H0)[:, None] + vz[None, :]
    return state_to_bytes(_compress_block(state, blk))
