"""Batched SHA-256 over variable-length messages, TPU-first.

Reference role: src/ballet/sha256/ (streaming + batch API, SHA-NI/AVX
backends).  Used by PoH (src/ballet/poh/), shred merkle trees
(src/ballet/bmtree/), and gossip/repair message signing.

Unlike SHA-512 (64-bit words emulated as uint32 pairs on TPU), SHA-256's
32-bit words map directly onto the VPU's native int32 lanes, so this is the
cheaper hash on TPU — one reason PoH/merkle work stays on sha256.  Batch
axis is the leading dim; variable lengths are handled by device-side padding
+ per-block active masks, same scheme as ops/sha512.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .sha512 import _iroot, _primes

_U32 = jnp.uint32


# H0 = frac(sqrt(p)), K = frac(cbrt(p)) to 32 bits over the first 8/64 primes
_H0 = np.array(
    [_iroot(p << 64, 2) & 0xFFFFFFFF for p in _primes(8)], dtype=np.uint32
)
_K = np.array(
    [_iroot(p << 96, 3) & 0xFFFFFFFF for p in _primes(64)], dtype=np.uint32
)


@functools.lru_cache(maxsize=None)
def _k_dev():
    """Round constants as ONE device-resident array.  Hoisted out of the
    traced functions (round 14): `jnp.asarray(_K)` inside a traced body
    re-embedded a fresh 256-byte constant into every trace; every
    compiled sha256 graph now closes over the same buffer.  Creation is
    forced eager (ensure_compile_time_eval) so a first call from inside
    a scan/jit trace can never cache a tracer."""
    with jax.ensure_compile_time_eval():
        return jnp.asarray(_K)


@functools.lru_cache(maxsize=None)
def _h0_dev():
    with jax.ensure_compile_time_eval():
        return jnp.asarray(_H0)


def _rotr(x, r: int):
    return (x >> r) | (x << (32 - r))


def _words16(blk):
    """Unpack a 64-byte block into the initial 16-word schedule window:
    uint8 (batch, 64) -> uint32 (16, batch), big-endian words."""
    b = blk.reshape(blk.shape[0], 16, 4).astype(_U32)
    return ((b[:, :, 0] << 24) | (b[:, :, 1] << 16)
            | (b[:, :, 2] << 8) | b[:, :, 3]).T


def _compress_w16(state, w16):
    """SHA-256 compression from a pre-built 16-word schedule window.
    state: uint32 (8, batch); w16: uint32 (16, batch).  Schedule + 64
    rounds as lax.scan (one-round-sized graph, same rationale as
    sha512._compress_block)."""

    def sched_step(win, _):
        w15, w2 = win[1], win[14]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        nw = win[0] + s0 + win[9] + s1
        return jnp.concatenate([win[1:], nw[None]], axis=0), nw

    _, w_rest = jax.lax.scan(sched_step, w16, None, length=48)
    ws = jnp.concatenate([w16, w_rest], axis=0)  # (64, batch)

    def round_step(st, inp):
        w_t, kt = inp
        a, b_, c, d, e, f, g, h = st
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + kt + w_t
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b_) ^ (a & c) ^ (b_ & c)
        t2 = S0 + maj
        return jnp.stack([t1 + t2, a, b_, c, d + t1, e, f, g]), None

    stf, _ = jax.lax.scan(round_step, state, (ws, _k_dev()))
    return state + stf


def _compress_block(state, blk):
    """One SHA-256 compression.  state: uint32 (8, batch); blk: uint8
    (batch, 64)."""
    return _compress_w16(state, _words16(blk))


# -- constant-block fast path (round 14) ------------------------------------
# The fixed-shape hashes below (PoH tick = 32-byte message, PoH mixin /
# merkle interior = 64-byte message) end in STATIC padding: the pad block
# of sha256_fixed64 is fully constant, and the back half of
# sha256_fixed32's single block is constant.  The message schedule of a
# constant block never changes, so it is computed ONCE on host (numpy)
# with the round constants folded in — the traced graph then runs 64
# rounds against a precomputed (64,) w+K table, skipping the 48-step
# schedule scan entirely.


def _np_schedule(w16: np.ndarray) -> np.ndarray:
    """Host message schedule of one constant block: (16,) -> (64,) u32."""

    def rotr(x, r):
        return ((x >> r) | (x << (32 - r))) & 0xFFFFFFFF

    w = [int(x) for x in w16]
    for i in range(16, 64):
        s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3)
        s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10)
        w.append((w[i - 16] + s0 + w[i - 7] + s1) & 0xFFFFFFFF)
    return np.array(w, dtype=np.uint32)


def _block_words_np(blk: np.ndarray) -> np.ndarray:
    """(64,) u8 block -> (16,) u32 big-endian words, host-side."""
    return blk.reshape(16, 4).astype(np.uint32) @ np.array(
        [1 << 24, 1 << 16, 1 << 8, 1], dtype=np.uint32)


def _pad_block64() -> np.ndarray:
    """The constant second block of a 64-byte message."""
    pad = np.zeros((64,), dtype=np.uint8)
    pad[0] = 0x80
    pad[62] = 0x02  # bitlen 512 = 0x200 big-endian in last 8 bytes
    return pad


# full schedule+K of sha256_fixed64's constant pad block, and the constant
# tail words (8..15) of sha256_fixed32's single block (32-byte pad half:
# 0x80 then bitlen 256 = 0x100)
_PAD64_WK = (_np_schedule(_block_words_np(_pad_block64()))
             .astype(np.uint64) + _K.astype(np.uint64)) \
    .astype(np.uint32)
_PAD32_TAILW = np.array(
    [0x80000000, 0, 0, 0, 0, 0, 0, 0x100], dtype=np.uint32)


@functools.lru_cache(maxsize=None)
def _pad64_wk_dev():
    with jax.ensure_compile_time_eval():
        return jnp.asarray(_PAD64_WK)


@functools.lru_cache(maxsize=None)
def _pad32_tailw_dev():
    with jax.ensure_compile_time_eval():
        return jnp.asarray(_PAD32_TAILW)


def _compress_const_block(state, wk):
    """Compression of a block whose CONTENT is static: `wk` is the
    precomputed (64,) schedule-plus-round-constant table, so the
    schedule scan disappears and each round adds one scalar."""

    def round_step(st, wkt):
        a, b_, c, d, e, f, g, h = st
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + wkt
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b_) ^ (a & c) ^ (b_ & c)
        t2 = S0 + maj
        return jnp.stack([t1 + t2, a, b_, c, d + t1, e, f, g]), None

    stf, _ = jax.lax.scan(round_step, state, wk)
    return state + stf


def pad_messages(msgs, lengths, max_blocks: int):
    """Device-side SHA-256 padding.  msgs: uint8 (batch, maxlen); lengths:
    int32 (batch,).  Returns (padded (batch, max_blocks*64), nblocks)."""
    batch, maxlen = msgs.shape
    total = max_blocks * 64
    lengths = lengths.astype(jnp.int32)
    nblocks = (lengths + 9 + 63) // 64
    j = jnp.arange(total, dtype=jnp.int32)[None, :]
    ln = lengths[:, None]
    src = jnp.pad(msgs, ((0, 0), (0, total - maxlen)))
    body = jnp.where(j < ln, src, 0)
    body = jnp.where(j == ln, jnp.uint8(0x80), body)
    # 64-bit big-endian bit length in the last 8 bytes of the final block;
    # message bit length < 2^32 in practice so only the low 4 bytes matter
    end = nblocks[:, None] * 64
    fpos = j - (end - 8)
    bitlen = (lengths.astype(jnp.uint32) * 8)[:, None]
    shift = (7 - fpos) * 8
    lbyte = jnp.where(
        (fpos >= 0) & (fpos < 8) & (shift < 32),
        (bitlen >> jnp.clip(shift, 0, 31)) & 0xFF,
        0,
    ).astype(jnp.uint8)
    return jnp.where((fpos >= 0) & (fpos < 8), lbyte, body), nblocks


def sha256(msgs, lengths, max_blocks: int | None = None):
    """Batched SHA-256.  msgs: uint8 (batch, maxlen); lengths: (batch,).
    Returns digests uint8 (batch, 32)."""
    batch, maxlen = msgs.shape
    if max_blocks is None:
        max_blocks = (maxlen + 9 + 63) // 64
    padded, nblocks = pad_messages(msgs, lengths, max_blocks)
    blocks = padded.reshape(batch, max_blocks, 64).transpose(1, 0, 2)

    vz = (blocks[0, :, 0] * 0).astype(_U32)
    state0 = _h0_dev()[:, None] + vz[None, :]  # (8, batch)

    def step(state, inp):
        blk, blk_idx = inp
        active = blk_idx < nblocks  # (batch,)
        new = _compress_block(state, blk)
        return jnp.where(active[None, :], new, state), None

    idxs = jnp.arange(max_blocks, dtype=jnp.int32)
    state, _ = jax.lax.scan(step, state0, (blocks, idxs))
    return state_to_bytes(state)


def state_to_bytes(state):
    """uint32 (8, batch) big-endian → uint8 (batch, 32)."""
    out = []
    for i in range(8):
        for s in (24, 16, 8, 0):
            out.append(((state[i] >> s) & 0xFF).astype(jnp.uint8))
    return jnp.stack(out, axis=-1)


def sha256_fixed64(msgs64):
    """SHA-256 of exactly-64-byte messages (the merkle interior-node and PoH
    mixin shape): two blocks, second fully constant — its schedule+K table
    is precomputed on host (_PAD64_WK), so the pad block costs 64 rounds
    with no schedule scan.  msgs64: uint8 (batch, 64) → uint8 (batch, 32)."""
    vz = (msgs64[:, 0] * 0).astype(_U32)
    state = _h0_dev()[:, None] + vz[None, :]
    state = _compress_block(state, msgs64)
    state = _compress_const_block(state, _pad64_wk_dev())
    return state_to_bytes(state)


def sha256_fixed32(msgs32):
    """SHA-256 of exactly-32-byte messages (PoH tick: hash of prev hash):
    single block whose back half is constant padding — the schedule
    window concatenates 8 unpacked message words with the precomputed
    constant tail (_PAD32_TAILW) instead of unpacking a built 64-byte
    block.  (batch, 32) → (batch, 32)."""
    batch = msgs32.shape[0]
    b = msgs32.reshape(batch, 8, 4).astype(_U32)
    w_msg = ((b[:, :, 0] << 24) | (b[:, :, 1] << 16)
             | (b[:, :, 2] << 8) | b[:, :, 3]).T  # (8, batch)
    tail = jnp.broadcast_to(_pad32_tailw_dev()[:, None], (8, batch))
    w16 = jnp.concatenate([w_msg, tail], axis=0)
    vz = (msgs32[:, 0] * 0).astype(_U32)
    state = _h0_dev()[:, None] + vz[None, :]
    return state_to_bytes(_compress_w16(state, w16))
