"""Device math ops (the reference's `ballet` layer, rebuilt for TPU).

All ops are batched, jit-friendly, and layout-planar: field elements are
arrays of radix-2^12 limbs with the limb axis FIRST so the batch axis rides
the TPU's 128-wide lane dimension.
"""
