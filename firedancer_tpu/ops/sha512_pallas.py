"""Pallas TPU SHA-512: fully-unrolled compression in VMEM.

The XLA path (ops/sha512.py) keeps the graph small with lax.scan — but on
device that is 160 sequential scan iterations per digest batch, and the
per-iteration launch/carry overhead dominates: measured 476 ns/lane at
batch 4096 where the raw ALU work is ~10 ns/lane.  Inside one Pallas
kernel the 80 rounds x nb blocks unroll completely (static python loop),
the schedule ring lives in vector registers, and the only HBM traffic is
the packed message words in and the digest state out.

Geometry: batch maps to (8 sublanes) x (blk lanes) — message words are
(8, blk) full tiles, so every 64-bit pair op is a dense 2-op vector op.
The 64-bit pair arithmetic helpers are reused from ops/sha512.py
(shape-polymorphic).  Reference contract: src/ballet/sha512/fd_sha512.c
(fd_sha512_core), batched like the AVX path fd_sha512_batch (widths 4/8 —
here 8 x blk).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .sha512 import _H0, _K, _add2, _addk, _rotr, _shr, _xor3, pad_messages

SUB = 8  # batch elements per sublane group


def _compress_unrolled(state, w):
    """One unrolled SHA-512 compression.  state: list of 8 (hi, lo) pairs;
    w: list of 16 (hi, lo) pairs ((8, blk) arrays).  Returns new state."""
    w = list(w)
    for t in range(16, 80):
        w15 = w[t - 15]
        w2 = w[t - 2]
        s0 = _xor3(_rotr(w15, 1), _rotr(w15, 8), _shr(w15, 7))
        s1 = _xor3(_rotr(w2, 19), _rotr(w2, 61), _shr(w2, 6))
        w.append(_addk(w[t - 16], s0, w[t - 7], s1))

    a, b, c, d, e, f, g, h = state
    for t in range(80):
        kt = (jnp.uint32(_K[t] >> 32), jnp.uint32(_K[t] & 0xFFFFFFFF))
        S1 = _xor3(_rotr(e, 14), _rotr(e, 18), _rotr(e, 41))
        ch = ((e[0] & f[0]) ^ (~e[0] & g[0]),
              (e[1] & f[1]) ^ (~e[1] & g[1]))
        t1 = _addk(h, S1, ch, kt, w[t])
        S0 = _xor3(_rotr(a, 28), _rotr(a, 34), _rotr(a, 39))
        maj = ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
               (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))
        t2 = _add2(S0, maj)
        h, g, f, e, d, c, b, a = g, f, e, _add2(d, t1), c, b, a, _add2(t1, t2)
    return [_add2(s, n) for s, n in
            zip(state, (a, b, c, d, e, f, g, h))]


def _sha_kernel(nb: int, blk: int):
    """words_ref: (nb*32*SUB, blk) — per block, 16 words x (hi row group,
    lo row group) x SUB sublanes.  nbl_ref: (SUB, blk) block counts.
    out_ref: (16*SUB, blk) final state words (hi, lo interleaved)."""

    def kernel(words_ref, nbl_ref, out_ref):
        nbl = nbl_ref[...]
        state = [
            (jnp.full((SUB, blk), hv >> 32, jnp.uint32),
             jnp.full((SUB, blk), hv & 0xFFFFFFFF, jnp.uint32))
            for hv in _H0
        ]
        for bi in range(nb):
            base = bi * 32 * SUB
            w = [
                (words_ref[base + (2 * t) * SUB : base + (2 * t + 1) * SUB, :],
                 words_ref[base + (2 * t + 1) * SUB
                           : base + (2 * t + 2) * SUB, :])
                for t in range(16)
            ]
            new = _compress_unrolled(state, w)
            active = nbl > bi
            state = [
                (jnp.where(active, n[0], s[0]), jnp.where(active, n[1], s[1]))
                for s, n in zip(state, new)
            ]
        out = []
        for hi, lo in state:
            out.append(hi)
            out.append(lo)
        out_ref[...] = jnp.concatenate(out, axis=0)

    return kernel


def sha512(msgs, lengths, max_blocks: int | None = None, blk: int = 512):
    """Batched SHA-512 via the Pallas kernel.  Same contract as
    ops.sha512.sha512: msgs uint8 (batch, maxlen), lengths (batch,) ->
    digests uint8 (batch, 64).  batch must be divisible by 8*128."""
    batch, maxlen = msgs.shape
    if max_blocks is None:
        max_blocks = (maxlen + 17 + 127) // 128
    nb = max_blocks
    lanes = batch // SUB
    assert batch % (SUB * 128) == 0, batch
    while lanes % blk:          # largest power-of-two block dividing lanes
        blk //= 2
    assert blk >= 128, (batch, blk)

    padded, nblocks = pad_messages(msgs, lengths, nb)
    # big-endian byte quads -> u32 words, laid out (nb, 16 words, hi/lo,
    # SUB, lanes) then flattened to rows
    b = padded.reshape(batch, nb, 16, 2, 4).astype(jnp.uint32)
    wrds = (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    # (batch, nb, 16, 2) -> (nb, 16, 2, batch) -> rows (nb*16*2*SUB, lanes)
    wrds = wrds.transpose(1, 2, 3, 0).reshape(nb * 32, SUB, lanes)
    wrds = wrds.reshape(nb * 32 * SUB, lanes)
    nbl = nblocks.astype(jnp.int32).reshape(SUB, lanes)

    w_spec = pl.BlockSpec((nb * 32 * SUB, blk), lambda i: (0, i))
    n_spec = pl.BlockSpec((SUB, blk), lambda i: (0, i))
    o_spec = pl.BlockSpec((16 * SUB, blk), lambda i: (0, i))
    out = pl.pallas_call(
        _sha_kernel(nb, blk),
        out_shape=jax.ShapeDtypeStruct((16 * SUB, lanes), jnp.uint32),
        grid=(lanes // blk,),
        in_specs=[w_spec, n_spec],
        out_specs=o_spec,
    )(wrds, nbl)

    # rows (16 words x SUB, lanes) -> (batch, 64) big-endian bytes; batch
    # index was split sub-major (batch = sub * lanes + lane) on the way in
    words = out.reshape(16, SUB, lanes).transpose(1, 2, 0).reshape(batch, 16)
    sh = jnp.array([24, 16, 8, 0], dtype=jnp.uint32)
    by = (words[:, :, None] >> sh[None, None, :]) & 0xFF
    return by.reshape(batch, 64).astype(jnp.uint8)
