"""fdtpudbg — debug-attach helper (the fddbg role, src/app/fddbg/main.c).

The reference's fddbg exists to get a debugger onto privileged validator
processes (a gdb capability wrapper for IDE F5 attach).  The tile
runtime here is sandboxed Python processes, so the analogue offers:

    ps <topo>            list a running topology's tile processes
    stack <topo> [tile]  non-disruptive stack dump: SIGUSR1 triggers the
                         faulthandler hook every tile registers at boot
                         (disco/run.py), printing all threads to the
                         tile's stderr — works on wedged tiles too
    gdb <pid>            exec gdb -p PID for the native layer (tango C++
                         shm, zstd, pkteng).  Like fddbg, raises
                         ambient capabilities first when possible so a
                         sandboxed target remains attachable.

Tile discovery matches process cmdlines against the topology name the
same way `fdtpuctl monitor` finds its workspace.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def _tile_procs(topo: str) -> list[tuple[int, str]]:
    """[(pid, shm-map-entry)] of processes mapping the topology's
    workspace shm (tiles join the wksp by name, so /proc/PID/maps shows
    /dev/shm/<wksp> — the same discovery `fdctl monitor` does through
    the shmem path)."""
    me = os.getpid()
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        try:
            with open(f"/proc/{pid}/maps") as f:
                maps = f.read()
        except OSError:
            continue
        for line in maps.splitlines():
            if "/dev/shm/" in line and topo in line:
                out.append((int(pid), line.rsplit(" ", 1)[-1]))
                break
    return out


def cmd_ps(args) -> int:
    procs = _tile_procs(args.topo)
    if not procs:
        print(f"no processes matching topology {args.topo!r}",
              file=sys.stderr)
        return 1
    for pid, cmd in procs:
        print(f"{pid:8d}  {cmd[:120]}")
    return 0


def cmd_stack(args) -> int:
    procs = _tile_procs(args.topo)
    if args.tile:
        procs = [(p, c) for p, c in procs if args.tile in c]
    if not procs:
        print("no matching tile processes", file=sys.stderr)
        return 1
    for pid, cmd in procs:
        try:
            os.kill(pid, signal.SIGUSR1)
            print(f"stack dump requested: pid {pid} "
                  f"(output on that process's stderr)")
        except ProcessLookupError:
            print(f"pid {pid} gone", file=sys.stderr)
    return 0


def cmd_gdb(args) -> int:
    # the fddbg trick, minus the VS-code contortions: raise ambient caps
    # when we hold them so gdb survives into a sandboxed target; plain
    # exec otherwise (works as root / same-user)
    try:
        import ctypes
        PR_CAP_AMBIENT, PR_CAP_AMBIENT_RAISE = 47, 2
        libc = ctypes.CDLL(None, use_errno=True)
        for cap in range(41):
            libc.prctl(PR_CAP_AMBIENT, PR_CAP_AMBIENT_RAISE, cap, 0, 0)
    except Exception:
        pass
    os.execvp("gdb", ["gdb", "-p", str(args.pid)] + (args.gdb_args or []))
    return 127  # unreachable


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="fdtpudbg", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("ps", help="list tile processes of a topology")
    sp.add_argument("topo")
    sp = sub.add_parser("stack", help="non-disruptive stack dump")
    sp.add_argument("topo")
    sp.add_argument("tile", nargs="?")
    sp = sub.add_parser("gdb", help="attach gdb to a native-layer pid")
    sp.add_argument("pid", type=int)
    sp.add_argument("gdb_args", nargs="*")
    args = p.parse_args(argv)
    return {"ps": cmd_ps, "stack": cmd_stack, "gdb": cmd_gdb}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
