"""fdtpuctl — the production CLI (ref: src/app/fdctl — main1.c:10-17 action
table: run, configure, monitor, keys, ready, mem, version).

    fdtpuctl [--config file.toml] run          boot + supervise the topology
    fdtpuctl [--config ...]       topo         print the materialized graph
    fdtpuctl [--config ...]       monitor      periodic metrics snapshot
    fdtpuctl [--config ...]       trace        span rings -> Chrome trace JSON
    fdtpuctl [--config ...]       autotune     autotuner decision history
    fdtpuctl keys new <path> | keys pubkey <path>
    fdtpuctl configure                          preflight environment checks
    fdtpuctl drain                              graceful quiesce + shutdown
    fdtpuctl fleet top|rolling_restart          multi-host control plane
    fdtpuctl ready                              block until every tile is RUN
    fdtpuctl mem                                shared-memory budget report
    fdtpuctl version
"""

import argparse
import json
import os
import sys
import time


def _supervisor_pidfile(app: str) -> str:
    """Where `fdtpuctl run` records its pid so `fdtpuctl drain` can ask
    THE SUPERVISOR to quiesce (the process that owns the children and
    the respawn machinery) instead of driving the cnc lines blind."""
    import tempfile
    return os.path.join(tempfile.gettempdir(), f"fdtpu_{app}.pid")


# pidfile older than this with no way to cross-check process identity is
# presumed stale (a supervisor that ran for a week would have refreshed
# nothing — but a recycled pid that LOOKS alive is the real hazard)
_PIDFILE_STALE_AGE_S = 7 * 24 * 3600.0


def _proc_start_time(pid: int) -> float | None:
    """Wall-clock start time of `pid`, from /proc/<pid>/stat field 22
    (starttime, clock ticks since boot) + /proc/stat btime.  None when
    /proc isn't available (non-Linux) or unparseable."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # comm may contain spaces/parens — fields count from after ')'
        fields = stat[stat.rindex(")") + 2:].split()
        start_ticks = int(fields[19])        # field 22, 0-indexed past comm
        with open("/proc/stat", "rb") as f:
            for line in f.read().decode().splitlines():
                if line.startswith("btime "):
                    btime = float(line.split()[1])
                    break
            else:
                return None
        hz = os.sysconf(os.sysconf_names["SC_CLK_TCK"])
        return btime + start_ticks / float(hz)
    except (OSError, ValueError, IndexError, KeyError):
        return None


def _live_supervisor_pid(pidfile: str) -> int:
    """Read a supervisor pidfile and return the pid ONLY if the process
    is alive AND demonstrably the one that wrote the file.  A pid
    recycled by an unrelated process must never be signaled: the
    process's start time (from /proc) has to predate the pidfile's
    mtime (+slack for clock granularity).  Where /proc can't answer,
    an old pidfile is presumed stale.  Returns 0 for no/stale/dead —
    callers fall through to driving the cnc lines directly."""
    try:
        st = os.stat(pidfile)
        with open(pidfile) as f:
            pid = int(f.read().strip())
        os.kill(pid, 0)
    except (OSError, ValueError):
        return 0
    started = _proc_start_time(pid)
    if started is not None:
        if started > st.st_mtime + 2.0:
            return 0                      # pid recycled after the file
    elif time.time() - st.st_mtime > _PIDFILE_STALE_AGE_S:
        return 0                          # no /proc; too old to trust
    return pid


def cmd_run(cfg, args):
    from ..disco.run import SupervisionPolicy, TopoRun
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    print(f"booting topology {spec.app!r}: "
          f"{len(spec.tiles)} tiles, {len(spec.links)} links", flush=True)
    # [observability] http_port: 0 disables the supervisor-side scrape
    # endpoint (a metric-kind tile can still serve one), N binds it fixed
    obs = cfg.get("observability", {})
    http_port = obs.get("http_port", 0)
    policy = SupervisionPolicy.from_cfg(cfg)
    pidfile = _supervisor_pidfile(spec.app)
    try:
        with open(pidfile, "w") as f:
            f.write(str(os.getpid()))
    except OSError:
        pidfile = ""
    try:
        with TopoRun(spec,
                     metrics_port=http_port if http_port else None,
                     policy=policy,
                     flight_dir=str(obs.get("flight_dir", "") or ""),
                     slo_target_ms=float(obs.get("slo_target_ms", 2.0)),
                     config=cfg) as run:
            if run.metrics_port:
                print("metrics: "
                      f"http://127.0.0.1:{run.metrics_port}/metrics",
                      flush=True)
            run.wait_ready(timeout=args.boot_timeout)
            print("all tiles RUN", flush=True)
            try:
                run.supervise()
            except KeyboardInterrupt:
                # with [supervision] drain_timeout_s set, SIGINT never
                # lands here (the drain handler absorbs the first one)
                print("halting", flush=True)
    finally:
        if pidfile:
            try:
                os.unlink(pidfile)
            except OSError:
                pass
    return 0


def cmd_drain(cfg, args):
    """Gracefully quiesce a running topology (drain protocol, ref: the
    cnc lifecycle PAPER.md describes — here BOOT→RUN→DRAIN→DRAINED→HALT):
    every tile drains in dependency order (source→net→quic→verify→dedup),
    so the topology exits with every accepted txn verdicted.

    Preferred path: SIGTERM to the `fdtpuctl run` supervisor (pidfile) —
    it owns the children, drains in order bounded by drain_timeout_s,
    and degrades to the plain halt on a wedged tile.  Without a live
    supervisor (e.g. a TopoRun embedded in a test), the cnc lines are
    driven directly."""
    import signal as signal_mod
    from ..disco import topo as topo_mod
    from ..disco.run import dependency_order
    from ..tango.ring import Cnc
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    sup = cfg.get("supervision") or {}
    timeout = args.timeout or float(sup.get("drain_timeout_s", 0) or 10.0)

    pidfile = _supervisor_pidfile(spec.app)
    pid = _live_supervisor_pid(pidfile)
    if pid:
        os.kill(pid, signal_mod.SIGTERM)
        print(f"drain requested from supervisor (pid {pid})", flush=True)
        budget = timeout * (len(spec.tiles) + 1) + 10.0
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            try:
                os.kill(pid, 0)
            except OSError:
                print("topology drained and halted")
                return 0
            time.sleep(0.1)
        print(f"supervisor still up after {budget:.0f}s", file=sys.stderr)
        return 1

    jt = topo_mod.join(spec)
    try:
        ok = True
        for name in dependency_order(spec):
            cnc = jt.cnc[name]
            if cnc.signal_query() != Cnc.SIGNAL_RUN:
                print(f"  {name}: not running, skipped")
                continue
            cnc.signal(Cnc.SIGNAL_DRAIN)
            deadline = time.monotonic() + timeout
            while (time.monotonic() < deadline
                   and cnc.signal_query() != Cnc.SIGNAL_DRAINED):
                time.sleep(0.005)
            drained = cnc.signal_query() == Cnc.SIGNAL_DRAINED
            print(f"  {name}: {'drained' if drained else 'DRAIN TIMEOUT'}",
                  flush=True)
            if not drained:
                ok = False
                break
        for cnc in jt.cnc.values():
            cnc.signal(Cnc.SIGNAL_HALT)
        print("topology halted" + ("" if ok else " (degraded: timeout)"))
        return 0 if ok else 1
    finally:
        jt.close()


def _fleet_workdir(args) -> str:
    wd = args.workdir or os.environ.get("FDTPU_FLEET_DIR", "")
    if not wd:
        print("fleet: no workdir (--workdir or FDTPU_FLEET_DIR)",
              file=sys.stderr)
    return wd


def _fleet_scrape(port) -> tuple[str, dict]:
    """One host's (healthz state, parsed /metrics) — ('unreachable', {})
    when the host is gone."""
    import urllib.error
    import urllib.request
    if not port:
        return "unreachable", {}
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=2.0
        ).read().decode()
        state = body.split()[0] if body else "unknown"
    except urllib.error.HTTPError as e:
        # 503 still carries the state word in the body
        body = e.read().decode(errors="replace")
        state = body.split()[0] if body else "unhealthy"
    except Exception:
        return "unreachable", {}
    metrics = {}
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2.0
        ).read().decode()
        for line in body.splitlines():
            if not line or line.startswith("#"):
                continue
            try:
                key, val = line.rsplit(None, 1)
                metrics[key] = float(val)
            except ValueError:
                continue
    except Exception:
        pass
    return state, metrics


_FLEET_STATE_RANK = {"ok": 0, "shedding": 1, "degraded": 2,
                     "draining": 3, "unknown": 4, "unreachable": 4,
                     "unhealthy": 5, "lost": 6}


def cmd_fleet(cfg, args):
    """Fleet control plane over the supervisor's state/command files:
    `fleet top` aggregates every host's /healthz + /metrics (verdict
    counters, dedup attribution, autotune decisions) under one rollup;
    `fleet rolling_restart` asks the live fleet supervisor for a
    zero-loss one-host-at-a-time upgrade."""
    wd = _fleet_workdir(args)
    if not wd:
        return 2
    state_path = os.path.join(wd, "fleet_state.json")

    def read_state():
        try:
            with open(state_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    if args.action == "top":
        shown = 0
        while True:
            st = read_state()
            if st is None:
                print(f"fleet: no state at {state_path} (fleet not "
                      "running?)", file=sys.stderr)
                return 1
            worst, rows = "ok", []
            agg = {"captured": 0, "dup_drop": 0, "uniq": 0, "foreign": 0,
                   "preload": 0, "adopt_pub": 0, "manifest_corrupt": 0,
                   "autotune": 0}
            for i in sorted(st["hosts"], key=int):
                h = st["hosts"][i]
                if h["state"] == "lost":
                    hs, m = "lost", {}
                else:
                    hs, m = _fleet_scrape(h.get("metrics_port"))
                sink = "-"
                for key, val in m.items():
                    if "fdtpu_frag_cnt" in key and 'tile="sink"' in key:
                        sink = int(val)
                    elif "fdtpu_dup_drop_cnt" in key:
                        agg["dup_drop"] += int(val)
                    elif "fdtpu_uniq_cnt" in key:
                        agg["uniq"] += int(val)
                    elif "fdtpu_shard_foreign_cnt" in key:
                        agg["foreign"] += int(val)
                    elif "fdtpu_preload_cnt" in key:
                        agg["preload"] += int(val)
                    elif "fdtpu_adopt_pub_cnt" in key:
                        agg["adopt_pub"] += int(val)
                    elif key.startswith("fdtpu_manifest_corrupt_cnt"):
                        agg["manifest_corrupt"] += int(val)
                    elif key.startswith("fdtpu_autotune_decision"):
                        agg["autotune"] += int(val)
                agg["captured"] += int(h.get("captured", 0))
                if _FLEET_STATE_RANK.get(hs, 4) > \
                        _FLEET_STATE_RANK.get(worst, 0):
                    worst = hs
                rows.append(f"  h{i:<3} state={hs:<11} "
                            f"gen={h['boot_gen']} "
                            f"captured={h.get('captured', 0):<7} "
                            f"sink={sink}")
            lost = ",".join(f"h{i}" for i in st.get("lost", [])) or "-"
            print(f"FLEET state={worst} live="
                  f"{st['n'] - len(st.get('lost', []))}/{st['n']} "
                  f"lost={lost} captured={agg['captured']} "
                  f"dup_drop={agg['dup_drop']} uniq={agg['uniq']} "
                  f"foreign={agg['foreign']} preload={agg['preload']} "
                  f"adopt_pub={agg['adopt_pub']} "
                  f"manifest_corrupt={agg['manifest_corrupt']} "
                  f"autotune={agg['autotune']}")
            for r in rows:
                print(r)
            for d, a in (st.get("adopting") or {}).items():
                ms = (st.get("failover_ms") or {}).get(d, "?")
                print(f"  failover h{d} -> h{a} ({ms} ms)")
            shown += 1
            if args.count and shown >= args.count:
                return 0
            if not args.count and shown >= 1 and not args.follow:
                return 0
            time.sleep(args.interval)

    if args.action == "rolling_restart":
        st = read_state()
        if st is None:
            print(f"fleet: no state at {state_path}", file=sys.stderr)
            return 1
        ack_path = os.path.join(wd, "fleet_cmd_ack.json")
        seq = 0
        try:
            with open(ack_path) as f:
                seq = int(json.load(f).get("seq", 0))
        except (OSError, ValueError, TypeError):
            pass
        try:
            with open(os.path.join(wd, "fleet_cmd.json")) as f:
                seq = max(seq, int(json.load(f).get("seq", 0)))
        except (OSError, ValueError, TypeError):
            pass
        seq += 1
        cmd_path = os.path.join(wd, "fleet_cmd.json")
        with open(cmd_path + ".tmp", "w") as f:
            json.dump({"seq": seq, "cmd": "rolling_restart",
                       "timeout_s": args.timeout}, f)
        os.replace(cmd_path + ".tmp", cmd_path)
        print(f"rolling restart requested (seq={seq}); waiting", flush=True)
        deadline = time.monotonic() + args.timeout * st["n"] + 30.0
        while time.monotonic() < deadline:
            try:
                with open(ack_path) as f:
                    ack = json.load(f)
                if int(ack.get("seq", 0)) >= seq:
                    ok = bool(ack.get("ok"))
                    print("fleet rolling restart "
                          + ("complete (graceful)" if ok
                             else "complete (degraded)"))
                    return 0 if ok else 1
            except (OSError, ValueError, TypeError):
                pass
            time.sleep(0.5)
        print("fleet rolling restart not acknowledged", file=sys.stderr)
        return 1
    return 2


def cmd_topo(cfg, args):
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    print(f"app: {spec.app}  workspace: {spec.wksp_mb} MiB")
    print("links:")
    for l in spec.links:
        print(f"  {l.name:24s} depth={l.depth:<6d} mtu={l.mtu}")
    print("tiles:")
    for t in spec.tiles:
        ins = ",".join(i.link for i in t.in_links) or "-"
        outs = ",".join(t.out_links) or "-"
        print(f"  {t.name:12s} kind={t.kind:8s} in=[{ins}] out=[{outs}]")
    return 0


def cmd_monitor(cfg, args):
    """Read-only metrics snapshots of a running topology (ref:
    src/app/fdctl/monitor/monitor.c — joins workspaces read-only).

    Default mode prints one JSON object per sample; --follow renders the
    live in-place dashboard (monitor.c:49-160's terminal table): per tile
    the cnc status + heartbeat age, per in-link the consumer's catch-up
    rate vs the producer plus backlog and the overrun/slow diag rates,
    and each tile's busiest counters as per-second rates."""
    from ..disco import topo as topo_mod
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    jt = topo_mod.join(spec)
    try:
        if getattr(args, "follow", False):
            return _monitor_follow(spec, jt, args)
        for _ in range(args.count) if args.count else iter(int, 1):
            out = {}
            for name, blk in jt.metrics.items():
                snap = blk.snapshot()
                out[name] = {k: v for k, v in snap.items() if v}
            print(json.dumps(out), flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        jt.close()
    return 0


def _monitor_follow(spec, jt, args):
    """In-place refreshing dashboard over the shared-memory topology."""
    from ..tango.ring import Cnc, FSeq
    sig_name = {Cnc.SIGNAL_RUN: "run", Cnc.SIGNAL_BOOT: "boot",
                Cnc.SIGNAL_FAIL: "FAIL", Cnc.SIGNAL_HALT: "halt",
                Cnc.SIGNAL_DRAIN: "drain", Cnc.SIGNAL_DRAINED: "drained"}

    def sample():
        now = time.monotonic_ns()
        s = {"t": now, "tiles": {}, "links": {}}
        for t in spec.tiles:
            cnc = jt.cnc[t.name]
            hb = cnc.heartbeat_query()
            s["tiles"][t.name] = {
                "sig": sig_name.get(cnc.signal_query(), "?"),
                "hb_ms": (now - hb) / 1e6 if hb else -1.0,
                "m": {k: v for k, v in jt.metrics[t.name].snapshot().items()
                      if isinstance(v, (int, float)) and v},
            }
            for il in t.in_links:
                fs = jt.fseq[(t.name, il.link)]
                s["links"][(t.name, il.link)] = {
                    "seq": fs.query(),
                    "prod": jt.links[il.link].mcache.seq_query(),
                    "ovrnp": fs.diag(FSeq.DIAG_OVRNP_CNT),
                    "ovrnr": fs.diag(FSeq.DIAG_OVRNR_CNT),
                    "slow": fs.diag(FSeq.DIAG_SLOW_CNT),
                    "filt": fs.diag(FSeq.DIAG_FILT_CNT),
                }
        return s

    def render(prev, cur):
        dt = max((cur["t"] - prev["t"]) / 1e9, 1e-9)
        lines = [f"fdtpu monitor — {spec.app}  "
                 f"(interval {dt:.2f}s, ctrl-c to exit)", ""]
        lines.append(f"{'TILE':<14}{'STAT':<6}{'HB(ms)':>8}  busiest rates")
        for name, tv in cur["tiles"].items():
            pm = prev["tiles"][name]["m"]
            rates = sorted(
                ((k, (v - pm.get(k, 0)) / dt) for k, v in tv["m"].items()
                 if isinstance(v, int)),
                key=lambda kv: -abs(kv[1]))[:3]
            rstr = "  ".join(f"{k}={r:,.0f}/s" for k, r in rates if r)
            hb = f"{tv['hb_ms']:.0f}" if tv["hb_ms"] >= 0 else "-"
            lines.append(f"{name:<14}{tv['sig']:<6}{hb:>8}  {rstr}")
        lines.append("")
        lines.append(f"{'LINK (consumer)':<30}{'rate/s':>12}{'backlog':>9}"
                     f"{'ovrnp/s':>9}{'ovrnr/s':>9}{'slow/s':>9}")
        for key, lv in cur["links"].items():
            pv = prev["links"][key]
            tile, link = key
            lines.append(
                f"{link + ' -> ' + tile:<30}"
                f"{(lv['seq'] - pv['seq']) / dt:>12,.0f}"
                f"{max(0, lv['prod'] - lv['seq']):>9,}"
                f"{(lv['ovrnp'] - pv['ovrnp']) / dt:>9,.0f}"
                f"{(lv['ovrnr'] - pv['ovrnr']) / dt:>9,.0f}"
                f"{(lv['slow'] - pv['slow']) / dt:>9,.0f}")
        return lines

    import sys
    prev = sample()
    print("\x1b[2J", end="")                       # clear once
    n = 0
    try:
        while not args.count or n < args.count:
            time.sleep(args.interval)
            cur = sample()
            out = render(prev, cur)
            sys.stdout.write("\x1b[H")             # home, repaint in place
            for ln in out:
                sys.stdout.write(ln + "\x1b[K\n")  # clear line tails
            sys.stdout.write("\x1b[J")             # clear below
            sys.stdout.flush()
            prev = cur
            n += 1
    except KeyboardInterrupt:
        pass
    finally:
        jt.close()
    return 0


def cmd_trace(cfg, args):
    """Drain every tile's shm span ring of a running topology for
    --duration seconds, write Chrome trace_event JSON (load the file in
    Perfetto or chrome://tracing) and print the p50/p99-per-hop table
    (ref: fd_monitor's tsorig/tspub rendering, as a span timeline)."""
    import numpy as np
    from ..disco import topo as topo_mod
    from ..disco import trace as trace_mod
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    jt = topo_mod.join(spec)
    chunks = {name: [] for name in jt.trace}
    cursors = dict.fromkeys(jt.trace, 0)
    try:
        deadline = time.monotonic() + args.duration
        while True:
            for name, ring in jt.trace.items():
                cursors[name], recs = ring.snapshot(since=cursors[name])
                if len(recs):
                    chunks[name].append(recs)
            if time.monotonic() >= deadline:
                break
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
    except KeyboardInterrupt:
        pass
    finally:
        jt.close()
    spans = {
        name: (np.concatenate(c) if c
               else np.empty(0, dtype=trace_mod.TRACE_REC_DTYPE))
        for name, c in chunks.items()}
    if getattr(args, "lane", ""):
        # verify-tile spans carry the lane tag in iidx's high bit
        # (trace.LANE_LAT); --lane lat keeps only low-latency-lane spans,
        # --lane bulk keeps everything else (stage spans are lane-less
        # and count as bulk)
        want = args.lane == "lat"
        spans = {
            name: recs[(recs["iidx"] & trace_mod.LANE_LAT != 0) == want]
            for name, recs in spans.items()}
    total = sum(len(v) for v in spans.values())
    if args.out:
        trace_mod.write_chrome_trace(args.out, spans)
        print(f"wrote {total} spans -> {args.out}", flush=True)
    print(trace_mod.hop_table(spans), flush=True)
    return 0


def cmd_top(cfg, args):
    """Live bottleneck attribution: per-tile regime split (busy/backp/
    house/idle from the mux's loop accounting), per-link lag + slow-
    consumer stall rates, and one "bottleneck: <link> (<reason>)"
    verdict line (ref: fd_monitor's fctl diag columns + the human
    squinting at them, monitor.c:49-160 — the squint is now code)."""
    import sys
    from ..disco import attrib
    from ..disco import topo as topo_mod
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    jt = topo_mod.join(spec)
    try:
        prev = attrib.link_sample(jt)
        print("\x1b[2J", end="")                    # clear once
        n = 0
        while not args.count or n < args.count:
            time.sleep(args.interval)
            cur = attrib.link_sample(jt)
            sys.stdout.write("\x1b[H")              # home, repaint
            for ln in attrib.render_top(spec, prev, cur):
                sys.stdout.write(ln + "\x1b[K\n")   # clear line tails
            sys.stdout.write("\x1b[J")              # clear below
            sys.stdout.flush()
            prev = cur
            n += 1
    except KeyboardInterrupt:
        pass
    finally:
        jt.close()
    return 0


def cmd_slo(cfg, args):
    """Stage-budget SLO table: drain the span rings for --duration
    seconds, fold them into the named stage pipeline and grade each
    stage's p99 against its share of the e2e latency target, plus the
    window burn rate + trend (disco/slo.py)."""
    import numpy as np
    from ..disco import slo as slo_mod
    from ..disco import topo as topo_mod
    from ..disco import trace as trace_mod
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    target = args.target if args.target else float(
        cfg.get("observability", {}).get("slo_target_ms",
                                         slo_mod.DEFAULT_TARGET_MS))
    jt = topo_mod.join(spec)
    chunks = {name: [] for name in jt.trace}
    cursors = dict.fromkeys(jt.trace, 0)
    kind_of = {t.name: t.kind for t in spec.tiles}
    try:
        deadline = time.monotonic() + args.duration
        while True:
            for name, ring in jt.trace.items():
                cursors[name], recs = ring.snapshot(since=cursors[name])
                if len(recs):
                    chunks[name].append(recs)
            if time.monotonic() >= deadline:
                break
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
    except KeyboardInterrupt:
        pass
    finally:
        jt.close()
    spans = {
        name: (np.concatenate(c) if c
               else np.empty(0, dtype=trace_mod.TRACE_REC_DTYPE))
        for name, c in chunks.items()}
    stats = slo_mod.stage_stats(spans, kind_of, target)
    burn = slo_mod.burn(spans, kind_of, target)
    print(slo_mod.render_table(stats, burn, target), flush=True)
    return 0 if all(r["ok"] for r in stats) else 1


def cmd_postmortem(cfg, args):
    """Render a flight-recorder bundle written by the supervisor on tile
    crash/degrade/respawn/SIGUSR2 (disco/flightrec.py): tile table, hop
    table, stage budgets, and the bottleneck verdict at time of death."""
    from ..disco import flightrec
    print(flightrec.render_bundle(args.bundle), flush=True)
    return 0


def cmd_autotune(cfg, args):
    """Render the closed-loop tuner's decision history: either the live
    autotune.jsonl mirror under [observability] flight_dir (default) or
    the autotune.json of a specific flight bundle (--bundle).  Each line
    is one control-period decision — inputs, rule, old -> new, outcome
    (applied / clamped / reverted / kept) — see disco/autotune.py."""
    from ..disco import autotune as autotune_mod
    if getattr(args, "bundle", ""):
        from ..disco import flightrec
        decisions = flightrec.load_bundle(args.bundle).get("autotune", [])
    else:
        fdir = str(
            cfg.get("observability", {}).get("flight_dir", "") or "")
        if not fdir:
            print("no [observability] flight_dir configured and no "
                  "--bundle given; the decision log lives in one of them",
                  file=sys.stderr)
            return 1
        decisions = autotune_mod.load_decisions(
            os.path.join(fdir, autotune_mod.LOG_NAME))
    print(autotune_mod.render_decisions(decisions, limit=args.limit),
          flush=True)
    return 0


def cmd_keys(cfg, args):
    from ..disco import keyguard
    from ..ops import ed25519 as ed
    if args.action == "new":
        seed = os.urandom(32)
        pub, _, _ = ed.keypair_from_seed(seed)
        keyguard.keypair_write(args.path, seed, pub)
        print(pub.hex())
        return 0
    if args.action == "pubkey":
        _, pub = keyguard.keypair_read(args.path)
        print(pub.hex())
        return 0
    raise SystemExit(f"unknown keys action {args.action}")


def cmd_configure(cfg, args):
    """Environment preflight (ref: fdctl configure stages, main.c:5-17 —
    hugetlbfs/sysctl/xdp there; shm + device visibility here)."""
    import multiprocessing.shared_memory as shm
    ok = True
    try:
        s = shm.SharedMemory(create=True, size=1 << 20, name="fdtpu_cfgtest")
        s.close()
        s.unlink()
        print("shm: ok")
    except Exception as e:  # pragma: no cover
        ok = False
        print(f"shm: FAIL ({e})")
    try:
        import jax
        devs = jax.devices()
        print(f"devices: {[str(d) for d in devs]}")
    except Exception as e:  # pragma: no cover
        ok = False
        print(f"devices: FAIL ({e})")
    # XDP/eBPF kernel-bypass tier (ref: fdctl configure xdp): probe-only —
    # unavailability is NOT a failure, the AF_PACKET engine is the
    # container-friendly fallback (waltz/pkteng)
    try:
        from ..waltz import ebpf
        k = ebpf.KernelXdp()
        fd = k.map_create(ebpf.KernelXdp.BPF_MAP_TYPE_HASH, 8, 4, 16)
        import os as _os
        _os.close(fd)
        print("xdp: ebpf available (redirect program loadable)")
    except Exception as e:
        print(f"xdp: unavailable ({e}); net tiles use AF_PACKET fallback")
    # AF_XDP XSK rings (the full kernel-bypass data plane): umem + ring
    # setup + bind on loopback proves the socket tier end to end
    try:
        from ..waltz.xsk import XskSock
        xs = XskSock("lo", frames=64)
        xs.recv_burst()
        xs.close()
        print("xsk: AF_XDP rings available (net tile backend \"xsk\")")
    except Exception as e:
        print(f"xsk: unavailable ({e}); TPACKET_V3/AF_PACKET tier in use")
    return 0 if ok else 1


def cmd_ready(cfg, args):
    """Block until every tile of the running topology signals RUN (ref:
    `fdctl ready` — polls each tile's cnc, main1.c action table)."""
    from ..disco import topo as topo_mod
    from ..tango.ring import Cnc
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    jt = topo_mod.join(spec)
    try:
        deadline = time.monotonic() + args.timeout
        for name, cnc in jt.cnc.items():
            while cnc.signal_query() != Cnc.SIGNAL_RUN:
                if time.monotonic() > deadline:
                    print(f"NOT READY: {name}")
                    return 1
                time.sleep(0.05)
        print("ready")
        return 0
    finally:
        jt.close()


def cmd_mem(cfg, args):
    """Print the topology's shared-memory budget per object (ref:
    `fdctl mem` — workspace/link footprints before boot).  Mirrors the
    actual join() layout: mcache + dcache(burst) per link, cnc + metrics
    per tile, one fseq per (tile, in-link) subscription."""
    from .. import native
    from ..disco import metrics as metrics_mod
    from ..disco import trace as trace_mod
    from ..tango import ring as ring_mod
    from . import config as config_mod
    spec = config_mod.build_topology(cfg)
    L = native.lib()
    total = 0
    print(f"{'object':30s} {'bytes':>12s}")
    for l in spec.links:
        mc = ring_mod.MCache.footprint(l.depth)
        dc = (ring_mod.Dcache.footprint(l.mtu, l.depth, l.burst)
              if l.mtu else 0)
        total += mc + dc
        print(f"link {l.name:24s} {mc + dc:12d}  "
              f"(mcache {mc}, dcache {dc}, depth {l.depth}, mtu {l.mtu})")
    cnc_fp = L.fd_cnc_footprint()
    fseq_fp = L.fd_fseq_footprint()
    met_fp = metrics_mod.footprint()
    trc_fp = trace_mod.footprint()
    for t in spec.tiles:
        fseqs = fseq_fp * len(t.in_links)
        tile_total = cnc_fp + met_fp + trc_fp + fseqs
        total += tile_total
        print(f"tile {t.name:24s} {tile_total:12d}  "
              f"(cnc {cnc_fp}, metrics {met_fp}, trace {trc_fp}, "
              f"fseq {fseq_fp}x{len(t.in_links)})")
    print(f"{'TOTAL':30s} {total:12d}  "
          f"(workspace budget {spec.wksp_mb} MiB)")
    return 0


def cmd_version(cfg, args):
    from importlib.metadata import version
    try:
        print(version("firedancer-tpu"))
    except Exception:
        print("0.1.0 (source tree)")
    return 0


def cmd_ledger(cfg, args):
    """Offline ledger ingest + replay + bank-hash conformance (ref:
    src/app/ledger/main.c, contrib/ledger-tests)."""
    from ..flamenco import genesis as gen_mod
    from ..flamenco.ledger import replay_ledger
    from ..flamenco.runtime import Runtime

    g = gen_mod.Genesis.read(args.genesis)
    rt = Runtime(g)
    report = replay_ledger(rt, args.shredcap, capture_path=args.capture,
                           expected_capture_path=args.expected)
    for r in report.results:
        print(json.dumps({
            "slot": r.slot, "ok": r.ok, "err": r.err,
            "bank_hash": r.bank_hash.hex() if r.bank_hash else None,
            "txns": r.txn_cnt, "failed": r.txn_fail_cnt}))
    summary = {
        "shreds": report.shreds, "slots": report.slots_complete,
        "slots_ok": report.slots_ok, "conformant": report.ok,
    }
    if report.first_divergence:
        summary["first_divergence"] = report.first_divergence
    print(json.dumps(summary))
    return 0 if report.ok else 1


def main(argv=None):
    p = argparse.ArgumentParser(prog="fdtpuctl", description=__doc__)
    p.add_argument("--config", help="TOML config overlaying the defaults")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("run")
    sp.add_argument("--boot-timeout", type=float, default=600.0)
    sub.add_parser("topo")
    sp = sub.add_parser("monitor")
    sp.add_argument("--interval", type=float, default=1.0)
    sp.add_argument("--count", type=int, default=0, help="0 = forever")
    sp.add_argument("--follow", action="store_true",
                    help="live in-place dashboard (fdctl monitor style)")
    sp = sub.add_parser(
        "trace", help="drain span rings -> Chrome trace JSON + hop table")
    sp.add_argument("--duration", type=float, default=2.0,
                    help="seconds to collect spans for")
    sp.add_argument("--out", default="",
                    help="write Chrome trace_event JSON here")
    sp.add_argument("--lane", default="", choices=["", "bulk", "lat"],
                    help="keep only one dispatch lane's spans (verify "
                         "tiles tag device/coalesce spans per lane)")
    sp = sub.add_parser(
        "top", help="live bottleneck attribution (per-tile regimes, "
                    "per-link lag/stalls, verdict line)")
    sp.add_argument("--interval", type=float, default=1.0)
    sp.add_argument("--count", type=int, default=0, help="0 = forever")
    sp = sub.add_parser(
        "slo", help="stage-budget table vs the e2e latency target")
    sp.add_argument("--duration", type=float, default=2.0,
                    help="seconds to collect spans for")
    sp.add_argument("--target", type=float, default=0.0,
                    help="e2e p99 target in ms (0 = config "
                         "[observability] slo_target_ms)")
    sp = sub.add_parser(
        "postmortem", help="render a flight-recorder crash bundle")
    sp.add_argument("bundle", help="bundle directory under flight_dir")
    sp = sub.add_parser(
        "autotune", help="render the autotuner's decision history")
    sp.add_argument("--bundle", default="",
                    help="read a flight bundle's autotune.json instead "
                         "of the live flight_dir jsonl mirror")
    sp.add_argument("--limit", type=int, default=50,
                    help="decisions rendered (newest last)")
    sp = sub.add_parser("keys")
    sp.add_argument("action", choices=["new", "pubkey"])
    sp.add_argument("path")
    sub.add_parser("configure")
    sp = sub.add_parser(
        "drain", help="graceful quiesce: drain every tile in dependency "
                      "order, exit with all accepted txns verdicted")
    sp.add_argument("--timeout", type=float, default=0.0,
                    help="per-tile drain budget in seconds (0 = config "
                         "[supervision] drain_timeout_s, else 10)")
    sp = sub.add_parser(
        "fleet", help="fleet control plane: aggregate host health/"
                      "metrics, drive a fleet-wide zero-loss upgrade")
    sp.add_argument("action", choices=["top", "rolling_restart"])
    sp.add_argument("--workdir", default="",
                    help="fleet workdir (default $FDTPU_FLEET_DIR)")
    sp.add_argument("--interval", type=float, default=1.0)
    sp.add_argument("--count", type=int, default=0,
                    help="top refreshes (0 = once, unless --follow)")
    sp.add_argument("--follow", action="store_true",
                    help="keep refreshing top until interrupted")
    sp.add_argument("--timeout", type=float, default=120.0,
                    help="per-host budget for rolling_restart")
    sp = sub.add_parser("ready")
    sp.add_argument("--timeout", type=float, default=60.0)
    sub.add_parser("mem")
    sub.add_parser("version")
    sp = sub.add_parser(
        "ledger", help="offline ledger conformance (app/ledger analogue)")
    sp.add_argument("action", choices=["replay"])
    sp.add_argument("genesis", help="genesis file (Genesis.write)")
    sp.add_argument("shredcap", help="shredcap archive to ingest + replay")
    sp.add_argument("--capture", help="write a solcap capture here")
    sp.add_argument("--expected", help="diff against this capture")
    args = p.parse_args(argv)

    from . import config as config_mod
    cfg = config_mod.load(args.config)
    return {
        "run": cmd_run, "topo": cmd_topo, "monitor": cmd_monitor,
        "trace": cmd_trace, "top": cmd_top, "slo": cmd_slo,
        "postmortem": cmd_postmortem, "autotune": cmd_autotune,
        "keys": cmd_keys, "drain": cmd_drain, "fleet": cmd_fleet,
        "configure": cmd_configure, "ready": cmd_ready, "mem": cmd_mem,
        "version": cmd_version, "ledger": cmd_ledger,
    }[args.cmd](cfg, args)


if __name__ == "__main__":
    sys.exit(main())
