"""Layered TOML config -> topology materialization (ref: src/app/fdctl/
config.c:818-870 config_parse — compiled-in defaults <- --config file <-
env overrides; topo selection topos.c:6-12).

The compiled-in defaults live in DEFAULT_TOML below (the reference ships
src/app/fdctl/config/default.toml); a user file overlays it key-by-key;
FDTPU_* environment variables overlay scalars last (FDTPU_LAYOUT_VERIFY_
TILE_COUNT=4 sets [layout] verify_tile_count).
"""

import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is API-identical
    import tomli as tomllib

from ..disco.topo import InLink, TopoBuilder, TopoSpec

DEFAULT_TOML = """
name = "fdtpu"
topology = "fdtpu"          # fdtpu | verify-bench | leader-bench

[layout]
verify_tile_count = 1
bank_tile_count = 1
affinity = ""               # "" = no pinning | "auto" | "0,2,3" cpu list
                            # (tiles take cpus in topology order, wrapping)

[net]
listen_port = 9001
pps_per_source = 0          # >0: per-source-IP packet token bucket at the
                            # net tile (sheds -> rate_drop_cnt + shedding)
pps_burst = 0               # bucket depth (0 = 2x pps_per_source)

[quic]                      # DoS front-door knobs (threaded to the quic
                            # tiles / QuicConfig; see docs/guide.md)
max_conns = 4096            # global conn table cap (idle-LRU evict on full)
max_conns_per_peer = 32     # conns one source IP may hold (0 = unlimited)
retry = 0                   # 1: ALWAYS require stateless Retry tokens
retry_half_open_threshold = 64  # half-open conns before Retry turns
                            # mandatory for tokenless Initials (0 = off)
lru_evict_idle = 1.0        # idle secs before a conn is LRU-evictable
conn_txn_rate = 0.0         # per-conn completed-txn/s token bucket (0 = off)
conn_txn_burst = 32
conn_reasm_budget = 19712   # partial-stream bytes buffered per conn (16 MTU)
reasm_conn_budget = 0       # TpuReasm slot-bytes per conn (0 = off)
idle_timeout = 10.0
packed_publish = 0          # 1: stamp reassembled txns as packed dcache
                            # rows (zero-copy wire->device; 0 = legacy
                            # per-txn publish, bit-identical verdicts)
crypto_native = -1          # burst packet protection (aescrypt.cpp):
                            # -1 = auto (C engine if the .so builds, else
                            # the bit-identical NumPy fallback), 0 = force
                            # Python, 1 = require native.
                            # Env: FDTPU_QUIC_CRYPTO_NATIVE
initial_key_cache = 1024    # per-dcid Initial key-schedule LRU cap (a
                            # random-dcid flood holds at most this many
                            # expanded schedules; 0 = no caching)

[verify]
mode = "strict"             # strict | antipa (round 9: halved-scalar chain
                            # with in-kernel divstep — 128 doubles vs 256;
                            # default-off pending the driver A/B, and
                            # torsion-LAX on adversarial 8-torsion defects,
                            # see docs/guide.md).  Env: FDTPU_VERIFY_MODE

[ingest]
native_hostpath = 1         # 1: round-11 one-pass C submit/harvest kernel
                            # (hostpath.cpp) on packed dcache row views; 0 =
                            # NumPy fallback, bit-identical verdicts.
                            # Env: FDTPU_INGEST_NATIVE_HOSTPATH
egress_packed = 0           # 1: verify tiles publish ONE packed arena frag
                            # per harvest (u32 offs[k+1] | wires) instead of
                            # k per-txn frags; the dedup tile unpacks it.
                            # Requires a packed ingest topology
                            # ([quic] packed_publish or [development]
                            # packed_wire).  0 = legacy per-txn egress.

[tiles.verify]
batch = 64
msg_maxlen = 256
flush_age_ns = 2000000
tcache_depth = 65536
dp_shards = 1               # >1: shard each batch P("dp") over a device mesh

[latency]
enabled = 0                 # 1: dual-lane dispatch in verify tiles (frags
                            # with the sig priority bit take the small lane)
deadline_us = 2000          # close the low-latency batch when its oldest
                            # txn reaches this age, regardless of fill
shapes = [16, 64, 256]      # small-lane batch ladder, pre-warmed at boot
max_inflight = 2            # lat-lane inflight budget before spilling
spill_age_factor = 4.0      # spill when open-queue age > factor * deadline

[tiles.dedup]
tcache_depth = 1048576

[tiles.pack]
max_txn_per_microblock = 31

[tiles.bank]
slot_txn_max = 1024
slot_ns = 400000000

[tiles.poh]
hashes_per_tick = 64
ticks_per_slot = 64

[leader]                    # leader lane: pack -> device PoH (round 14;
                            # leader-bench topology + the fdtpu leader
                            # tiles; see docs/guide.md "[leader] lane")
hashes_per_tick = 16
ticks_per_slot = 8
spec_spans = 3              # concurrent engine span lanes: 1 chain lane +
                            # (spec_spans - 1) emitted-entry re-check lanes
poh_spec_ticks = 4          # PoH speculation depth: ticks pre-hashed per
                            # window dispatch (a mixin splices from the
                            # saved insertion point and invalidates the
                            # rest of the window)
mb_per_tick = 8             # mixin steps per tick (capped at
                            # hashes_per_tick - 1; excess microblocks defer)
pack_shards = 1             # leader_pack tiles, partitioned by fee-payer
                            # writable account; > 1 adds a leader_merge
                            # tile enforcing the global block budgets
native_pack = -1            # pack schedule hot loop: -1 = auto (native if
                            # the .so builds, else the bit-identical
                            # Python fallback), 0 = force Python, 1 =
                            # require native
mixin_txn_max = 32          # mixin merkle-tree pad width (txns/microblock)
max_txn_per_microblock = 31
max_pending = 4096          # pack heap cap (0 = unbounded; simple votes
                            # bypass — the reserved vote lane)
block_us = 400000           # end_block cadence (block budget reset)
unroll = 8                  # inner sha256 scan unroll factor (XLA fusion)
capture_path = ""           # sink capture file (sig|len|payload per frag)
                            # for offline chain re-verification; "" = off

[tiles.shred]
shred_version = 1
fec_data_cnt = 32
sig_batch = 32              # turbine-ingress batched leader-sig admission:
                            # shreds per merkle-walk + sigverify dispatch
sig_flush_age_us = 2000     # partial-batch deadline (age-or-size flush)
sig_backend = "device"      # "device" = batched graphs; "host" = per-shred
                            # python-int verify (control-plane rates)

[tiles.shred_recover]
fec_data_cnt = 32           # k_max: data shreds per set the engine packs
fec_code_cnt = 32           # parity bound; n_max = data + code
batch_sets = 8              # FEC sets per fused recover dispatch
flush_age_us = 5000         # partial-batch deadline for queued sets
nbuf = 2                    # rotating recover blobs (>= 2 to overlap)

[tiles.metric]
prometheus_port = 0         # 0 = disabled

[observability]
http_port = 0               # 0 = no supervisor /metrics + /healthz endpoint
flight_dir = ""             # "" = flight recorder off; else postmortem
                            # bundle dir (crash/degrade/respawn/SIGUSR2)
flight_max_bundles = 16     # oldest-bundle rotation bound on flight_dir
                            # (a crash loop can't fill the disk); evictions
                            # counted in fdtpu_flightrec_evict_cnt
slo_target_ms = 2.0         # e2e p99 latency target the stage budgets
                            # and /healthz slo field grade against

[autotune]                  # closed-loop tuner (disco/autotune.py): turns
                            # attribution verdicts + SLO burn into bounded
                            # knob moves.  WARNING: with enabled = 1 the
                            # loop owns its knob surface — hand-edits to
                            # [latency]/[tiles.verify]/rate knobs only set
                            # the BASELINE it relaxes back toward.
enabled = 0                 # default-off: zero overhead, bit-identical
                            # behavior (same invariant as faultinject)
period_s = 2.0              # control period (one sense + at most one move)
burn_hi = 0.35              # act when SLO burn rate >= this (hysteresis hi)
burn_lo = 0.10              # healthy below this (hysteresis lo)
cooldown_periods = 3        # periods a fired rule stays ineligible
relax_after = 10            # healthy periods before stepping a displaced
                            # knob back toward its boot baseline
quarantine_periods = 64     # rule lockout after a do-no-harm revert
respawn_after = 0           # >0: last resort — this many consecutive
                            # burn_hi periods respawns verify with the
                            # dispatch-ahead window at its hi clamp
poison = ""                 # test hook: invert the named rule's step
                            # direction (the chaos gate proves do-no-harm
                            # catches and reverts it)

[autotune.bounds]           # optional per-knob [lo, hi] or [lo, hi, step]
                            # overrides of disco/autotune.py KNOB_SPECS
                            # (knob names are globally unique, e.g.
                            # deadline_us = [500, 10000, 0.25])

[supervision]
restart_policy = "fail_fast"  # fail_fast (ref run.c:279) | respawn
max_restarts = 5              # per-tile respawn budget
backoff_initial_s = 0.25      # exponential backoff: initial delay,
backoff_max_s = 8.0           # cap, and +/- jitter fraction (jitter is
backoff_jitter = 0.2          # deterministic per (tile, attempt))
boot_grace_s = 300.0          # no staleness checks while a tile boots
heartbeat_stale_s = 60.0      # default heartbeat staleness -> tile failed
device_fail_threshold = 3     # consecutive dispatch failures -> CPU fallback
device_retry = 1              # bounded retries per device dispatch
device_deadline_s = 30.0      # verdict materialization deadline
device_reprobe_s = 5.0        # degraded-mode device re-probe interval
drain_timeout_s = 0.0         # >0: graceful drain budget (rolling restarts,
                              # SIGTERM/SIGINT topology drain).  A tile that
                              # is not DRAINED within the budget falls back
                              # to crash-respawn semantics + flight bundle.
                              # 0 (default): drain never engages — behavior
                              # bit-identical to a world without it.
drain_manifest_dir = ""       # where draining tiles persist their cursor
                              # manifests ("" = skip; $FDTPU_DRAIN_DIR also
                              # works per-process)

[supervision.heartbeat_stale] # per tile KIND overrides (seconds)
verify = 120.0                # uncached device dispatches stall longer

[consensus]
identity_path = ""
genesis_path = ""

[fleet]                     # multi-host fleet layer (disco/fleet.py).
hosts = 1                   # 1 = single-host mode: fleet layer fully inert
vnodes = 64                 # ring points per host (waltz SteerRing)
shard_bits = 4              # tcache shards = 2^bits (sig-prefix sharding)
digest_period_s = 0.5       # sig-digest gossip publish cadence per host
digest_chunk = 512          # max tags per gossip digest chunk
failover_timeout_s = 15.0   # host silent past this -> declared lost
gossip_port = 0             # control-ring UDP base port (0 = ephemeral)
host_boot_timeout_s = 120.0 # per-host topology wait_ready bound

[development]
source_count = 0            # >0: synthetic txn source instead of net ingest
source_burst_n = 0          # >0: numpy burst firehose (txns/loop; see SourceTile)
packed_wire = 0             # 1: dcache frags ARE device-blob rows (zero-copy
                            # wire->device path, verify-bench topology only)
burst_splits = 2            # packed frags emitted per source loop (round-robin
                            # deal across verify tiles)
lat_every = 0               # >0: tag every Nth synthetic txn latency-class
                            # (sets the sig priority bit; see [latency])
bench_seed = 42
"""


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _env_overlay(cfg: dict, environ=os.environ) -> dict:
    """FDTPU_SECTION_KEY=value overrides; ints parsed when they look like
    ints (the reference parses env as the final layer, config.c)."""
    for name, val in environ.items():
        if not name.startswith("FDTPU_"):
            continue
        path = name[6:].lower().split("_", 1)
        cur = cfg
        # walk into the deepest section that matches; remaining underscore
        # words form the key (sections never contain underscores)
        if len(path) == 1:
            key = path[0]
        else:
            sect, key = path
            if sect in cur and isinstance(cur[sect], dict):
                cur = cur[sect]
                # tiles.verify style: one more level
                head = key.split("_", 1)
                if (len(head) == 2 and head[0] in cur
                        and isinstance(cur[head[0]], dict)):
                    cur = cur[head[0]]
                    key = head[1]
            else:
                key = name[6:].lower()
        try:
            cur[key] = int(val)
        except ValueError:
            cur[key] = val
    return cfg


# Sections where an unknown key is an ERROR, not a silent no-op: these
# all carry tuning knobs, and a typo'd knob (deadline_uss) that no-ops is
# the worst possible failure mode for an autotuned topology.  The valid
# key set IS the DEFAULT_TOML schema; listed sub-tables are exempt
# (heartbeat_stale keys are tile kinds, bounds keys are knob names —
# the latter validated against the autotune KNOB_SPECS registry).
_STRICT_SECTIONS = ("latency", "verify", "supervision", "observability",
                    "autotune", "leader", "fleet")
_STRICT_SUBTABLES = {"supervision": ("heartbeat_stale",),
                     "autotune": ("bounds",)}


def _validate_strict(cfg: dict):
    import difflib
    schema = tomllib.loads(DEFAULT_TOML)
    for sect in _STRICT_SECTIONS:
        got = cfg.get(sect)
        if not isinstance(got, dict):
            continue
        valid = set(schema[sect]) | set(_STRICT_SUBTABLES.get(sect, ()))
        for key in got:
            if key in valid:
                continue
            near = difflib.get_close_matches(key, sorted(valid), n=1)
            hint = f" (did you mean {near[0]!r}?)" if near else ""
            raise ValueError(
                f"unknown key {key!r} in [{sect}]{hint}; valid keys: "
                + ", ".join(sorted(valid)))
    bounds = (cfg.get("autotune") or {}).get("bounds") or {}
    if bounds:
        from ..disco.autotune import KNOB_SPECS
        for knob, b in bounds.items():
            if knob not in KNOB_SPECS:
                near = difflib.get_close_matches(
                    knob, sorted(KNOB_SPECS), n=1)
                hint = f" (did you mean {near[0]!r}?)" if near else ""
                raise ValueError(
                    f"unknown knob {knob!r} in [autotune.bounds]{hint}")
            if (not isinstance(b, (list, tuple)) or len(b) not in (2, 3)
                    or not all(isinstance(x, (int, float)) for x in b)):
                raise ValueError(
                    f"[autotune.bounds] {knob} must be [lo, hi] or "
                    f"[lo, hi, step], got {b!r}")


def load(path: str | None = None, environ=os.environ) -> dict:
    cfg = tomllib.loads(DEFAULT_TOML)
    if path:
        with open(path, "rb") as f:
            cfg = _deep_merge(cfg, tomllib.load(f))
    cfg = _env_overlay(cfg, environ)
    _validate_strict(cfg)
    return cfg


def build_topology(cfg: dict) -> TopoSpec:
    """Materialize the configured topology (the fd_topo_frankendancer /
    fd_topo_firedancer analogues, src/app/fdctl/run/topos/)."""
    name = cfg.get("topology", "fdtpu")
    if name == "fdtpu":
        spec = _topo_fdtpu(cfg)
    elif name == "verify-bench":
        spec = _topo_verify_bench(cfg)
    elif name == "leader-bench":
        spec = _topo_leader_bench(cfg)
    else:
        raise ValueError(f"unknown topology {name!r}")
    from ..disco.topo import assign_affinity
    return assign_affinity(spec, str(cfg["layout"].get("affinity", "")))


def _topo_fdtpu(cfg: dict) -> TopoSpec:
    """The full single-host validator graph:

        net -> quic -> verify[v] -> dedup -> pack -> bank -> poh
           -> shred (keyguard-signed) -> store        (+ metric tile)

    verify tiles are round-robin data parallel (fd_verify.c:36-47); with
    [development] source_count > 0 a synthetic source replaces net+quic.
    """
    lay = cfg["layout"]
    nverify = int(lay["verify_tile_count"])
    t = cfg["tiles"]
    qcfg = dict(cfg.get("quic") or {})
    dev_count = int(cfg["development"]["source_count"])
    # [quic] packed_publish: the quic tile stamps reassembled txns as
    # packed device-blob rows (round-8 layout) — same link/vcfg shape as
    # the verify-bench packed_wire topology
    packed = bool(int(qcfg.get("packed_publish", 0))) and not dev_count
    b = TopoBuilder(cfg.get("name", "fdtpu"),
                    wksp_mb=128 if packed else 64)

    # degraded-mode thresholds + fault plans ride in the verify tile cfg
    # (the [supervision] respawn half is supervisor-side only); the
    # [verify] mode knob (strict|antipa, FDTPU_VERIFY_MODE) rides along
    # so every verify tile builds the same device graph
    vcfg = dict(t["verify"])
    vcfg["mode"] = str(cfg.get("verify", {}).get("mode", "strict"))
    ing = dict(cfg.get("ingest") or {})
    vcfg["native_hostpath"] = int(ing.get("native_hostpath", 1))
    # packed arena egress rides the packed ingest path only: one frag per
    # harvest, so the verify_dedup link must fit a whole arena (k wires of
    # up to 65+ml bytes each plus the u32 offsets table)
    egress_packed = bool(int(ing.get("egress_packed", 0))) and packed
    if egress_packed:
        vcfg["egress_packed"] = 1
    if dev_count:
        b.link("quic_verify", depth=256, mtu=1280)
        b.tile("source", "source", outs=["quic_verify"], count=dev_count,
               seed=int(cfg["development"]["bench_seed"]),
               burst_n=int(cfg["development"].get("source_burst_n", 0)),
               lat_every=int(cfg["development"].get("lat_every", 0)))
    else:
        b.link("net_quic", depth=256, mtu=2048)
        if packed:
            from ..tango.ring import PACKED_ROW_EXTRA, packed_row_ml
            batch = int(vcfg.get("batch", 64))
            ml = packed_row_ml(int(vcfg.get("msg_maxlen", 256)))
            vcfg["packed_wire"] = 1
            vcfg["buckets"] = [[batch, ml]]
            qcfg.update(packed_rows=batch, packed_ml=ml)
            b.link("quic_verify", depth=16,
                   mtu=batch * (ml + PACKED_ROW_EXTRA))
        else:
            b.link("quic_verify", depth=256, mtu=1280)
        pps = {"pps_per_source": int(cfg["net"].get("pps_per_source", 0)),
               "pps_burst": int(cfg["net"].get("pps_burst", 0))}
        nnet = int(lay.get("net_tile_count", 1))
        if nnet > 1:
            # N net tiles fan into one netmux (ref fd_netmux.c's role:
            # consumers join ONE mcache no matter how many ingress tiles).
            # Kernel-socket backends can't share a port, so tile i binds
            # listen_port+i; the XDP tier round-robins one port instead.
            for i in range(nnet):
                b.link(f"net_mux:{i}", depth=256, mtu=2048)
                b.tile(f"net:{i}", "net", outs=[f"net_mux:{i}"],
                       ports={int(cfg["net"]["listen_port"]) + i:
                              f"net_mux:{i}"}, **pps)
            b.tile("netmux", "netmux",
                   ins=[f"net_mux:{i}" for i in range(nnet)],
                   outs=["net_quic"])
        else:
            b.tile("net", "net", outs=["net_quic"],
                   ports={int(cfg["net"]["listen_port"]): "net_quic"},
                   **pps)
        b.tile("quic", "quic", ins=["net_quic"], outs=["quic_verify"],
               **qcfg)

    vcfg.setdefault("supervision", dict(cfg.get("supervision") or {}))
    vcfg.setdefault("latency", dict(cfg.get("latency") or {}))
    if egress_packed:
        from ..tango.ring import packed_row_ml
        batch = int(vcfg.get("batch", 64))
        ml = packed_row_ml(int(vcfg.get("msg_maxlen", 256)))
        vd_depth, vd_mtu = 16, batch * (65 + ml) + 4 * (batch + 1)
    else:
        vd_depth, vd_mtu = 256, 1280
    for v in range(nverify):
        b.link(f"verify_dedup:{v}", depth=vd_depth, mtu=vd_mtu)
        b.tile(f"verify:{v}", "verify", ins=["quic_verify"],
               outs=[f"verify_dedup:{v}"],
               round_robin_cnt=nverify, round_robin_idx=v,
               **vcfg)
    b.link("dedup_pack", depth=256, mtu=1280)
    b.tile("dedup", "dedup",
           ins=[f"verify_dedup:{v}" for v in range(nverify)],
           outs=["dedup_pack"], packed_egress=int(egress_packed),
           **t["dedup"])
    b.link("pack_bank", depth=256, mtu=1280)
    b.tile("pack", "pack", ins=["dedup_pack"], outs=["pack_bank"],
           max_txn=t["pack"]["max_txn_per_microblock"])

    gpath = cfg["consensus"]["genesis_path"]
    kpath = cfg["consensus"]["identity_path"]
    if gpath:
        b.link("bank_poh", depth=256, mtu=1280)
        b.link("poh_shred", depth=256, mtu=2048)
        b.link("shred_sign", depth=16, mtu=128)
        b.link("sign_shred", depth=16, mtu=128)
        b.link("shred_store", depth=512, mtu=1280)
        b.tile("bank", "bank", ins=["pack_bank"], outs=["bank_poh"],
               genesis_path=gpath, **t["bank"])
        b.tile("poh", "poh", ins=["bank_poh"], outs=["poh_shred"],
               **t["poh"])
        b.tile("shred", "shred", ins=["poh_shred"],
               outs=["shred_sign", "shred_store"], **t["shred"])
        b.tile("sign", "sign", ins=["shred_sign"], outs=["sign_shred"],
               key_path=kpath)
        b.tile("store", "store", ins=["shred_store"])
    else:
        # ingest-only slice (Frankendancer-without-Agave shape): count txns
        # (sink) or drop at metadata rate without reading payloads
        # (blackhole, ref fd_blackhole.c)
        b.tile("sink", cfg["development"].get("sink_kind", "sink"),
               ins=["pack_bank"])
    if int(t["metric"]["prometheus_port"]):
        b.tile("metric", "metric", ins=(),
               port=int(t["metric"]["prometheus_port"]))
    return b.build()


def _topo_verify_bench(cfg: dict) -> TopoSpec:
    """source -> verify[v] -> dedup -> sink: the synthetic sigverify load
    harness (the verify_synth_load.c / `fddev bench` analogue)."""
    lay = cfg["layout"]
    nverify = int(lay["verify_tile_count"])
    t = cfg["tiles"]
    dev = cfg["development"]
    vcfg = dict(t["verify"])
    vcfg["mode"] = str(cfg.get("verify", {}).get("mode", "strict"))
    packed = int(dev.get("packed_wire", 0))
    ing = dict(cfg.get("ingest") or {})
    vcfg["native_hostpath"] = int(ing.get("native_hostpath", 1))
    egress_packed = bool(int(ing.get("egress_packed", 0))) and bool(packed)
    if egress_packed:
        vcfg["egress_packed"] = 1
    b = TopoBuilder(cfg.get("name", "fdtpu") + "-bench",
                    wksp_mb=128 if packed else 64)
    if packed:
        # zero-copy wire->device: the src_verify dcache chunk layout IS
        # the PackedIngest device-blob layout.  One frag = one packed
        # burst of `batch` rows at a chunk-aligned stride; meta.sz
        # carries the row count (u16 can't hold the byte size).  Small
        # depth — frags are few and huge, and the reader pins them until
        # verdicts land (mux credits_held).
        from ..tango.ring import PACKED_ROW_EXTRA, packed_row_ml
        batch = int(vcfg.get("batch", 64))
        ml = packed_row_ml(int(vcfg.get("msg_maxlen", 256)))
        stride = ml + PACKED_ROW_EXTRA
        vcfg["packed_wire"] = 1
        vcfg["buckets"] = [[batch, ml]]
        b.link("src_verify", depth=16, mtu=batch * stride)
        b.tile("source", "source", outs=["src_verify"],
               count=int(dev["source_count"]),
               seed=int(dev["bench_seed"]),
               packed_rows=batch, packed_ml=ml,
               burst_splits=int(dev.get("burst_splits", 2)))
    else:
        b.link("src_verify", depth=4096, mtu=1280)
        # source_extra: fleet harness passthrough (adopt_streams,
        # rate_ns, ... — disco/fleet.py host topologies)
        b.tile("source", "source", outs=["src_verify"],
               count=int(dev["source_count"]),
               seed=int(dev["bench_seed"]),
               burst_n=int(dev.get("source_burst_n", 0)),
               lat_every=int(dev.get("lat_every", 0)),
               **dict(dev.get("source_extra") or {}))
    vcfg.setdefault("supervision", dict(cfg.get("supervision") or {}))
    vcfg.setdefault("latency", dict(cfg.get("latency") or {}))
    if egress_packed:
        vd_depth = 16
        vd_mtu = int(vcfg["buckets"][0][0]) * (65 + int(vcfg["buckets"][0][1])) \
            + 4 * (int(vcfg["buckets"][0][0]) + 1)
    else:
        vd_depth, vd_mtu = 256, 1280
    for v in range(nverify):
        b.link(f"verify_dedup:{v}", depth=vd_depth, mtu=vd_mtu)
        b.tile(f"verify:{v}", "verify", ins=["src_verify"],
               outs=[f"verify_dedup:{v}"],
               round_robin_cnt=nverify, round_robin_idx=v, **vcfg)
    b.link("dedup_sink", depth=256, mtu=1280)
    b.tile("dedup", "dedup",
           ins=[f"verify_dedup:{v}" for v in range(nverify)],
           outs=["dedup_sink"], packed_egress=int(egress_packed),
           **t["dedup"])
    b.tile("sink", "sink", ins=["dedup_sink"],
           **dict(t.get("sink") or {}))
    if int(t["metric"]["prometheus_port"]):
        b.tile("metric", "metric", ins=(),
               port=int(t["metric"]["prometheus_port"]))
    return b.build()


def _topo_leader_bench(cfg: dict) -> TopoSpec:
    """source -> verify[v] -> leader_pack -> poh_dev -> sink: the leader
    write-side harness (round 14) — verified txns feed the fee-priority
    pack scheduler, whose microblocks mix into the device PoH chain; the
    sink collects serialized entries (a test/chaos harness re-verifies
    them through ballet.poh.verify_entries)."""
    lay = cfg["layout"]
    nverify = int(lay["verify_tile_count"])
    t = cfg["tiles"]
    dev = cfg["development"]
    ld = dict(cfg.get("leader") or {})
    vcfg = dict(t["verify"])
    vcfg["mode"] = str(cfg.get("verify", {}).get("mode", "strict"))
    packed = int(dev.get("packed_wire", 0))
    ing = dict(cfg.get("ingest") or {})
    vcfg["native_hostpath"] = int(ing.get("native_hostpath", 1))
    egress_packed = bool(int(ing.get("egress_packed", 0))) and bool(packed)
    if egress_packed:
        vcfg["egress_packed"] = 1
    b = TopoBuilder(cfg.get("name", "fdtpu") + "-leader",
                    wksp_mb=128 if packed else 64)
    if packed:
        from ..tango.ring import PACKED_ROW_EXTRA, packed_row_ml
        batch = int(vcfg.get("batch", 64))
        ml = packed_row_ml(int(vcfg.get("msg_maxlen", 256)))
        stride = ml + PACKED_ROW_EXTRA
        vcfg["packed_wire"] = 1
        vcfg["buckets"] = [[batch, ml]]
        b.link("src_verify", depth=16, mtu=batch * stride)
        b.tile("source", "source", outs=["src_verify"],
               count=int(dev["source_count"]),
               seed=int(dev["bench_seed"]),
               packed_rows=batch, packed_ml=ml,
               burst_splits=int(dev.get("burst_splits", 2)))
    else:
        b.link("src_verify", depth=4096, mtu=1280)
        b.tile("source", "source", outs=["src_verify"],
               count=int(dev["source_count"]),
               seed=int(dev["bench_seed"]),
               burst_n=int(dev.get("source_burst_n", 0)),
               lat_every=int(dev.get("lat_every", 0)))
    vcfg.setdefault("supervision", dict(cfg.get("supervision") or {}))
    vcfg.setdefault("latency", dict(cfg.get("latency") or {}))
    if egress_packed:
        vd_depth = 16
        vd_mtu = int(vcfg["buckets"][0][0]) * (65 + int(vcfg["buckets"][0][1])) \
            + 4 * (int(vcfg["buckets"][0][0]) + 1)
    else:
        vd_depth, vd_mtu = 256, 1280
    for v in range(nverify):
        b.link(f"verify_pack:{v}", depth=vd_depth, mtu=vd_mtu)
        b.tile(f"verify:{v}", "verify", ins=["src_verify"],
               outs=[f"verify_pack:{v}"],
               round_robin_cnt=nverify, round_robin_idx=v, **vcfg)
    mtxn = int(ld.get("max_txn_per_microblock", 31))
    mb_mtu = 4 + mtxn * (4 + 1280)          # serialize_txn_batch wire
    b.link("pack_poh", depth=256, mtu=mb_mtu)
    shards = max(1, int(ld.get("pack_shards", 1)))
    pack_kw = dict(packed_egress=int(egress_packed), max_txn=mtxn,
                   max_pending=int(ld.get("max_pending", 4096)),
                   block_us=int(ld.get("block_us", 400_000)),
                   native_pack=int(ld.get("native_pack", -1)))
    if shards == 1:
        b.tile("leader_pack", "leader_pack",
               ins=[f"verify_pack:{v}" for v in range(nverify)],
               outs=["pack_poh"], **pack_kw)
    else:
        # sharded pack: every shard sees every verified txn and keeps
        # only its fee-payer partition; leader_merge interleaves the
        # per-shard microblocks and re-enforces the GLOBAL block budgets
        # (a txn payload caps writable accounts at ~38, 16 B per merge
        # item — size the shard->merge links for the worst case)
        merge_mtu = mb_mtu + 24 + 40 * mtxn * 16  # MERGE_HDR + items
        for s in range(shards):
            b.link(f"pack_merge:{s}", depth=64, mtu=merge_mtu)
            b.tile(f"leader_pack:{s}", "leader_pack",
                   ins=[f"verify_pack:{v}" for v in range(nverify)],
                   outs=[f"pack_merge:{s}"],
                   shard_cnt=shards, shard_idx=s, **pack_kw)
        b.tile("leader_merge", "leader_merge",
               ins=[f"pack_merge:{s}" for s in range(shards)],
               outs=["pack_poh"],
               block_us=int(ld.get("block_us", 400_000)))
    mixin_max = int(ld.get("mixin_txn_max", 32))
    entry_mtu = 48 + mixin_max * (4 + 1280)  # Entry.serialize wire
    b.link("poh_sink", depth=512, mtu=entry_mtu)
    b.tile("poh_dev", "poh_dev", ins=["pack_poh"], outs=["poh_sink"],
           hashes_per_tick=int(ld.get("hashes_per_tick", 16)),
           ticks_per_slot=int(ld.get("ticks_per_slot", 8)),
           spec_spans=int(ld.get("spec_spans", 3)),
           spec_ticks=int(ld.get("poh_spec_ticks", 4)),
           mb_per_tick=int(ld.get("mb_per_tick", 8)),
           mixin_txn_max=mixin_max,
           unroll=int(ld.get("unroll", 8)))
    b.tile("sink", "sink", ins=["poh_sink"],
           capture_path=str(ld.get("capture_path", "")))
    if int(t["metric"]["prometheus_port"]):
        b.tile("metric", "metric", ins=(),
               port=int(t["metric"]["prometheus_port"]))
    return b.build()
