"""fdtpudev — the dev CLI (ref: src/app/fddev — main1.c:90-98: dev, bench,
txn; dev.c zero-to-running single-node cluster).

    fdtpudev dev   [--dir D]      keygen + genesis + full validator topology
    fdtpudev bench [--count N]    synthetic sigverify TPS through the graph
    fdtpudev flame [--count N]    per-tile cProfile of the bench topology
    fdtpudev txn   --port P       sign + send one transfer to a running node
"""

import argparse
import json
import os
import sys
import time


def _ensure_cluster_files(d: str):
    """Create identity key + genesis under `d` if missing (the fddev
    configure stages keys + genesis, src/app/fddev/configure/)."""
    from ..disco import keyguard
    from ..flamenco import genesis as gen_mod
    from ..flamenco.types import Account
    from ..ops import ed25519 as ed
    os.makedirs(d, exist_ok=True)
    kpath = os.path.join(d, "identity.json")
    gpath = os.path.join(d, "genesis.bin")
    fpath = os.path.join(d, "faucet.json")
    if not os.path.exists(kpath):
        seed = os.urandom(32)
        keyguard.keypair_write(kpath, seed, ed.keypair_from_seed(seed)[0])
    if not os.path.exists(fpath):
        seed = os.urandom(32)
        keyguard.keypair_write(fpath, seed, ed.keypair_from_seed(seed)[0])
    if not os.path.exists(gpath):
        _, id_pub = keyguard.keypair_read(kpath)
        fseed, faucet_pub = keyguard.keypair_read(fpath)
        g = gen_mod.create(faucet_pub,
                           faucet_lamports=500_000_000_000_000,
                           creation_time=int(time.time()))
        # fund the identity so it can vote/pay fees later
        g.accounts[id_pub] = Account(lamports=1_000_000_000_000)
        g.write(gpath)
    return kpath, gpath, fpath


def cmd_dev(args):
    from . import config as config_mod, fdtpuctl
    kpath, gpath, fpath = _ensure_cluster_files(args.dir)
    cfg = config_mod.load(args.config)
    cfg["consensus"]["identity_path"] = kpath
    cfg["consensus"]["genesis_path"] = gpath
    print(f"cluster dir: {args.dir}", flush=True)
    ns = argparse.Namespace(boot_timeout=600.0)
    return fdtpuctl.cmd_run(cfg, ns)


def _run_bench_topology(config_path, count: int, batch: int | None = None):
    """Boot the verify-bench graph and run until `count` txns pass dedup;
    returns elapsed seconds (shared by `bench` and `flame`)."""
    from ..disco.run import TopoRun
    from . import config as config_mod
    cfg = config_mod.load(config_path)
    cfg["topology"] = "verify-bench"
    cfg["development"]["source_count"] = count
    if batch is not None:
        cfg["tiles"]["verify"]["batch"] = batch
    spec = config_mod.build_topology(cfg)
    with TopoRun(spec) as run:
        run.wait_ready(timeout=600)
        t0 = time.monotonic()
        done = 0
        while done < count:
            time.sleep(0.2)
            done = run.metrics("dedup")["uniq_cnt"]
            if run.poll() is not None:
                raise RuntimeError("a tile died mid-bench")
        return time.monotonic() - t0


def cmd_bench(args):
    """Self-contained TPS firehose (ref: fddev bench, bench.c:62-110):
    verify-bench topology, run until `count` txns pass dedup, report TPS.
    --quic drives the REAL QUIC server tile at saturating load instead
    (the benchg/benchs shape: live QUIC conns over loopback)."""
    if getattr(args, "quic", False):
        return _quic_firehose(args.count)
    dt = _run_bench_topology(args.config, args.count, args.batch)
    print(json.dumps({
        "txns": args.count,
        "seconds": round(dt, 3),
        "tps": round(args.count / dt, 1),
    }))
    return 0


def _quic_firehose(count: int) -> int:
    """Saturating-TPS QUIC ingest (VERDICT r4 missing #7; ref: fddev
    bench's benchg->QUIC->benchs loop, src/app/fddev/bench.c:62-110):
    boot the quic_server tile topology, open a live QUIC connection over
    loopback, and push txn streams as fast as the stream quota allows
    until `count` txns land at the sink.  Reports the QUIC-layer TPS —
    the full handshake/AEAD/stream machinery is in the path."""
    from ..disco.run import TopoRun
    from ..disco.topo import TopoBuilder
    from ..waltz.quic import QuicConfig, QuicEndpoint
    from ..waltz.udpsock import UdpSock

    spec = (
        TopoBuilder(f"quicfire{os.getpid()}", wksp_mb=32)
        .link("quic_sink", depth=2048, mtu=1280)
        .tile("quic_server", "quic_server", outs=["quic_sink"], port=0)
        .tile("sink", "sink", ins=["quic_sink"])
        .build()
    )
    payload = b"Q" + os.urandom(8) + bytes(150)  # txn-sized stream body
    with TopoRun(spec) as run:
        run.wait_ready(timeout=120)
        port = run.metrics("quic_server")["bound_port"]
        csock = UdpSock(bind_ip="127.0.0.1", burst=256, mutable=True)
        try:
            cl = QuicEndpoint(
                QuicConfig(identity_seed=os.urandom(32)), csock.aio())
            conn = cl.connect(("127.0.0.1", int(port)),
                              now=time.monotonic())
            sent = 0
            t0 = None
            loop_start = time.monotonic()
            deadline = loop_start + max(120, count / 50)
            # NOTE on pacing (measured, round 5): bounding the send
            # queue per iteration STARVES on conn-level flow control
            # (the queue stops draining when MAX_DATA credit is spent,
            # blocking new submissions: 21 TPS).  Unbounded queueing +
            # PTO recovery of any sockbuf-dropped tail measured 409 TPS
            # with all streams delivered — the saturating shape.
            while time.monotonic() < deadline:
                now = time.monotonic()
                pkts = csock.recv_burst()
                if pkts:
                    cl.rx(pkts, now)
                if conn.handshake_done:
                    if t0 is None:
                        t0 = time.monotonic()
                    while sent < count:
                        tx = bytearray(payload)
                        tx[1:9] = sent.to_bytes(8, "little")
                        if conn.send_txn(bytes(tx)) is None:
                            break              # stream quota: drain first
                        sent += 1
                cl.service(now)
                done = run.metrics("sink")["frag_cnt"]
                if done >= count:
                    break
            dt = time.monotonic() - (t0 if t0 is not None else loop_start)
            done = run.metrics("sink")["frag_cnt"]
            print(json.dumps({
                "mode": "quic-firehose",
                "txns": int(done),
                "seconds": round(dt, 3),
                "tps": round(done / dt, 1) if dt > 0 else 0.0,
                "quic_streams_rx": int(
                    run.metrics("quic_server").get("reasm_pub_cnt", 0)),
            }))
            return 0 if done >= count else 1
        finally:
            csock.close()


def cmd_flame(args):
    """Per-tile profiling (ref: fddev flame, src/app/fddev/flame.c:31-60 —
    there a perf-record wrapper per tile; here cProfile inside each tile
    process via FDTPU_PROFILE_DIR): run the bench topology for a bounded
    txn count, then print each tile's hottest functions."""
    import pstats

    prof_dir = args.out
    os.makedirs(prof_dir, exist_ok=True)
    for stale in os.listdir(prof_dir):  # never report a previous run's data
        if stale.endswith(".pstats"):
            os.unlink(os.path.join(prof_dir, stale))
    os.environ["FDTPU_PROFILE_DIR"] = prof_dir
    try:
        _run_bench_topology(args.config, args.count)
    finally:
        del os.environ["FDTPU_PROFILE_DIR"]
    for f in sorted(os.listdir(prof_dir)):
        if not f.endswith(".pstats"):
            continue
        print(f"\n=== {f[:-7]} ===")
        st = pstats.Stats(os.path.join(prof_dir, f))
        st.sort_stats("cumulative").print_stats(args.top)
    return 0


def cmd_txn(args):
    """Build, sign and send one transfer txn over UDP to a node's TPU port
    (ref: fddev txn + the minimal rpc_client)."""
    import socket
    from ..ballet import txn as txn_lib
    from ..disco import keyguard
    from ..flamenco.system_program import ix_transfer
    from ..flamenco.types import SYSTEM_PROGRAM_ID
    from ..ops import ed25519 as ed
    seed, pub = keyguard.keypair_read(args.key)
    dest = bytes.fromhex(args.dest)
    blockhash = bytes.fromhex(args.blockhash)
    msg = txn_lib.build_unsigned(
        [pub], blockhash,
        [(2, bytes([0, 1]), ix_transfer(args.lamports))],
        extra_accounts=[dest, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    payload = txn_lib.assemble([ed.sign(seed, msg)], msg)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.sendto(payload, ("127.0.0.1", args.port))
    s.close()
    print(f"sent {len(payload)}B txn to 127.0.0.1:{args.port}")
    return 0


def cmd_run_test_vectors(args):
    """Replay a test-vectors corpus — a directory or tar of `.fix`
    proto3 fixtures (instr/ + elf_loader/, the firedancer-io/
    test-vectors layout; ref contrib/test/run_test_vectors.sh)."""
    from ..flamenco import test_vectors as tv
    results = tv.run_path(args.path)
    failed = [r for r in results if not r.passed]
    for r in failed[:args.show]:
        print(f"FAIL {r.name}: {r.detail}")
    print(f"Total test cases: {len(results)}")
    print(f"Total passed: {len(results) - len(failed)}")
    print(f"Total failed: {len(failed)}")
    return 1 if failed else 0


def main(argv=None):
    p = argparse.ArgumentParser(prog="fdtpudev", description=__doc__)
    p.add_argument("--config", help="TOML config overlaying the defaults")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("dev")
    sp.add_argument("--dir", default=os.path.expanduser("~/.fdtpu"))
    sp = sub.add_parser("bench")
    sp.add_argument("--count", type=int, default=4096)
    sp.add_argument("--batch", type=int, default=64)
    sp.add_argument("--quic", action="store_true",
                    help="drive the QUIC server tile at saturating load "
                         "(the fddev benchg/benchs analogue)")
    sp = sub.add_parser("flame")
    sp.add_argument("--count", type=int, default=512)
    sp.add_argument("--out", default="/tmp/fdtpu_flame")
    sp.add_argument("--top", type=int, default=12)
    sp = sub.add_parser("txn")
    sp.add_argument("--key", required=True)
    sp.add_argument("--dest", required=True, help="hex pubkey")
    sp.add_argument("--blockhash", required=True, help="hex")
    sp.add_argument("--lamports", type=int, default=1000)
    sp.add_argument("--port", type=int, default=9001)
    sp = sub.add_parser("run-test-vectors")
    sp.add_argument("path", help=".fix corpus: directory or tar")
    sp.add_argument("--show", type=int, default=10)
    args = p.parse_args(argv)
    return {"dev": cmd_dev, "bench": cmd_bench, "flame": cmd_flame,
            "txn": cmd_txn,
            "run-test-vectors": cmd_run_test_vectors}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
