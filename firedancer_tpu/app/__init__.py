"""Binaries (ref: src/app — fdctl the production CLI, fddev the dev CLI).

`fdtpuctl` (app.fdtpuctl) runs/monitors a validator topology from layered
TOML config; `fdtpudev` (app.fdtpudev) adds zero-to-running dev workflows
(keygen + genesis + single-node cluster + bench load).
"""
