"""Protocol math & codecs (the reference's ballet layer, src/ballet/).

Device-batched crypto lives in firedancer_tpu.ops; this package holds the
host-side protocol codecs (txn parsing, compact-u16, shreds, pack) that feed
fixed-shape batches to the device.
"""
