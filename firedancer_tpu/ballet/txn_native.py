"""ctypes binding for the native batch txn parser (native/txnparse.cpp).

One C call parses a burst of serialized txns with fd_txn_parse's rules
(ref src/ballet/txn/fd_txn_parse.c:80-236), dedups on the first-signature
tag against a native tcache, and scatters msg/sig/pubkey bytes directly
into the verify bucket's numpy arrays — the host data plane of the verify
tile without per-txn Python.

Rule-parity with ballet/txn.py::parse is asserted by tests/test_txn.py
(same corpus, same fuzz inputs, identical accept/reject bits).
"""

import ctypes
from dataclasses import dataclass

import numpy as np

# error codes (native/txnparse.cpp)
OK = 0
ERR_PARSE = 1
ERR_TOO_LONG = 2
ERR_DUP = 3
ERR_SIG_CAP = 4


@dataclass
class BurstResult:
    consumed: int          # payloads processed (stop = bucket filled)
    lanes_used: int        # signature lanes written
    lane0: np.ndarray      # (consumed,) int32: first lane or -1
    nsig: np.ndarray       # (consumed,) int32: lanes used by txn (0=dropped)
    tag: np.ndarray        # (consumed,) uint64 dedup tags
    err: np.ndarray        # (consumed,) int32 error codes


def _buf_ptr(buf) -> ctypes.c_void_p:
    """Zero-copy base pointer for bytes / bytearray / memoryview / ndarray
    payload buffers (a memoryview over a shm dcache parses in place)."""
    if isinstance(buf, (bytearray, memoryview)):
        buf = np.frombuffer(buf, dtype=np.uint8)
    if isinstance(buf, np.ndarray):
        return buf.ctypes.data_as(ctypes.c_void_p)
    return ctypes.cast(ctypes.c_char_p(buf), ctypes.c_void_p)


def pack_payloads(payloads) -> tuple[bytes, np.ndarray]:
    """list[bytes] -> (flat buffer, int64 offsets (n+1)) for parse_packed."""
    offs = np.zeros(len(payloads) + 1, dtype=np.int64)
    np.cumsum([len(p) for p in payloads], out=offs[1:])
    return b"".join(payloads), offs


def parse_burst(payloads, msgs: np.ndarray, lens: np.ndarray,
                sigs: np.ndarray, pubs: np.ndarray, lane0: int,
                tcache_handle=None) -> BurstResult:
    """Convenience form of parse_packed for a list[bytes]."""
    buf, offs = pack_payloads(payloads)
    return parse_packed(buf, offs, msgs, lens, sigs, pubs, lane0,
                        tcache_handle)


def parse_packed(buf, offs: np.ndarray, msgs: np.ndarray, lens: np.ndarray,
                 sigs: np.ndarray, pubs: np.ndarray, lane0: int,
                 tcache_handle=None) -> BurstResult:
    """Parse txns packed in a flat buffer into the bucket arrays starting
    at lane `lane0`.  Payload i = buf[offs[i]:offs[i+1]] (offsets are
    ABSOLUTE into buf, so a caller resuming mid-burst passes offs[idx:]
    without re-packing).  Stops early when the bucket runs out of lanes —
    the caller flushes and re-enters.

    buf: bytes or a uint8 numpy array (e.g. the ring rx scratch buffer —
    zero-copy from fd_ring_rx_burst's output).
    tcache_handle: NativeTCache.handle for inline QUERY-only dedup (tags
    are inserted by the harvest path after verify passes)."""
    from .. import native
    L = native.lib()

    n = len(offs) - 1
    t_lane0 = np.empty(n, dtype=np.int32)
    t_nsig = np.empty(n, dtype=np.int32)
    t_tag = np.empty(n, dtype=np.uint64)
    t_err = np.empty(n, dtype=np.int32)
    lanes_used = np.zeros(1, dtype=np.int32)

    vp = ctypes.c_void_p
    buf_p = _buf_ptr(buf)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    consumed = L.fd_txn_parse_batch(
        buf_p, offs.ctypes.data_as(vp), n,
        tcache_handle if tcache_handle is not None else None,
        msgs.shape[1], msgs.shape[0], lane0,
        msgs.ctypes.data_as(vp), lens.ctypes.data_as(vp),
        sigs.ctypes.data_as(vp), pubs.ctypes.data_as(vp),
        t_lane0.ctypes.data_as(vp), t_nsig.ctypes.data_as(vp),
        t_tag.ctypes.data_as(vp), t_err.ctypes.data_as(vp),
        lanes_used.ctypes.data_as(vp))
    return BurstResult(consumed, int(lanes_used[0]), t_lane0[:consumed],
                       t_nsig[:consumed], t_tag[:consumed], t_err[:consumed])


def parse_packed_bucket(buf, offs: np.ndarray, bucket: np.ndarray,
                        maxlen: int, lens: np.ndarray, lane0: int,
                        tcache_handle=None) -> BurstResult:
    """parse_packed into a ROW-INTERLEAVED bucket: one (cap, stride)
    uint8 array with msgs at +0, sigs at +maxlen, pubs at +maxlen+64 and
    little-endian int32 msg_len at +maxlen+96 (stride >= maxlen+100) —
    the single-transfer DMA-blob shape the device dispatch uploads whole
    (wiredancer's packed txn push, wd_f1.h:85-113).  `lens` is the
    contiguous int32 side array for host bookkeeping; the C fill writes
    both."""
    from .. import native
    L = native.lib()

    assert bucket.dtype == np.uint8 and bucket.ndim == 2
    assert bucket.shape[1] >= maxlen + 100
    assert bucket.flags.c_contiguous

    n = len(offs) - 1
    t_lane0 = np.empty(n, dtype=np.int32)
    t_nsig = np.empty(n, dtype=np.int32)
    t_tag = np.empty(n, dtype=np.uint64)
    t_err = np.empty(n, dtype=np.int32)
    lanes_used = np.zeros(1, dtype=np.int32)

    vp = ctypes.c_void_p
    buf_p = _buf_ptr(buf)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    consumed = L.fd_txn_parse_batch_packed(
        buf_p, offs.ctypes.data_as(vp), n,
        tcache_handle if tcache_handle is not None else None,
        maxlen, bucket.shape[0], lane0,
        bucket.ctypes.data_as(vp), bucket.shape[1],
        lens.ctypes.data_as(vp),
        t_lane0.ctypes.data_as(vp), t_nsig.ctypes.data_as(vp),
        t_tag.ctypes.data_as(vp), t_err.ctypes.data_as(vp),
        lanes_used.ctypes.data_as(vp))
    return BurstResult(consumed, int(lanes_used[0]), t_lane0[:consumed],
                       t_nsig[:consumed], t_tag[:consumed], t_err[:consumed])
