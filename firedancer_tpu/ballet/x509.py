"""Minimal X.509: self-signed Ed25519 certs for QUIC-TLS, DER encode/parse.

Reference role: src/ballet/x509/ — the reference generates a mock
self-signed Ed25519 certificate (QUIC-TLS requires *a* certificate even
though Solana peers authenticate by raw Ed25519 pubkey) and extracts the
subject public key when parsing a peer's cert.  We implement exactly that
surface: `cert_create` emits a deterministic DER cert over a node pubkey,
`cert_pubkey` pulls the Ed25519 subjectPublicKey back out of any cert that
uses the id-Ed25519 algorithm, and `cert_verify_self_signed` checks the
self-signature.  DER is hand-rolled (a few tag/len helpers) — no ASN.1
library exists in this image and the subset needed is tiny.
"""

from __future__ import annotations

_OID_ED25519 = bytes.fromhex("2b6570")  # 1.3.101.112
_OID_COMMON_NAME = bytes.fromhex("550403")  # 2.5.4.3


def _der(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    ln = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([tag, 0x80 | len(ln)]) + ln + content


def _seq(*parts: bytes) -> bytes:
    return _der(0x30, b"".join(parts))


def _int(v: int) -> bytes:
    b = v.to_bytes((max(v.bit_length(), 1) + 7) // 8, "big")
    if b[0] & 0x80:
        b = b"\0" + b
    return _der(0x02, b)


def _bitstring(b: bytes) -> bytes:
    return _der(0x03, b"\0" + b)


def _alg_ed25519() -> bytes:
    return _seq(_der(0x06, _OID_ED25519))


def _name(cn: str) -> bytes:
    rdn = _der(
        0x31,
        _seq(_der(0x06, _OID_COMMON_NAME), _der(0x0C, cn.encode())),
    )
    return _seq(rdn)


def _utctime(s: str) -> bytes:
    return _der(0x17, s.encode())


def spki_ed25519(pubkey: bytes) -> bytes:
    """SubjectPublicKeyInfo for an Ed25519 key (RFC 8410 §4)."""
    return _seq(_alg_ed25519(), _bitstring(pubkey))


def cert_create(seed: bytes, pubkey: bytes, cn: str = "firedancer-tpu") -> bytes:
    """Deterministic self-signed v3 cert binding `pubkey`, signed by `seed`.

    Mirrors the reference's mock cert generator: fixed validity window,
    serial derived from the pubkey, issuer == subject.
    """
    from firedancer_tpu.ops.ed25519 import sign

    name = _name(cn)
    tbs = _seq(
        _der(0xA0, _int(2)),  # [0] version v3
        _int(int.from_bytes(pubkey[:8], "big") | 1),  # serial (positive)
        _alg_ed25519(),
        name,  # issuer
        _seq(_utctime("200101000000Z"), _utctime("400101000000Z")),
        name,  # subject
        spki_ed25519(pubkey),
    )
    sig = sign(seed, tbs)
    return _seq(tbs, _alg_ed25519(), _bitstring(sig))


class DerReader:
    """Cursor over a DER buffer; raises ValueError on malformed input."""

    def __init__(self, buf: bytes, pos: int = 0, end: int | None = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def read_tlv(self) -> tuple[int, "DerReader"]:
        if self.pos + 2 > self.end:
            raise ValueError("DER: truncated TLV")
        tag = self.buf[self.pos]
        ln = self.buf[self.pos + 1]
        p = self.pos + 2
        if ln & 0x80:
            nlen = ln & 0x7F
            if nlen == 0 or nlen > 4 or p + nlen > self.end:
                raise ValueError("DER: bad length")
            ln = int.from_bytes(self.buf[p : p + nlen], "big")
            p += nlen
        if p + ln > self.end:
            raise ValueError("DER: length overruns buffer")
        inner = DerReader(self.buf, p, p + ln)
        self.pos = p + ln
        return tag, inner

    def bytes(self) -> bytes:
        return self.buf[self.pos : self.end]

    def raw_span(self) -> tuple[int, int]:
        return self.pos, self.end


def cert_pubkey(der: bytes) -> bytes:
    """Extract the Ed25519 subjectPublicKey from a DER certificate.

    Walks Certificate → tbsCertificate → subjectPublicKeyInfo, skipping
    optional/contextual fields; raises ValueError if the SPKI algorithm is
    not id-Ed25519 (the only identity algorithm Solana's TLS profile allows).
    """
    tag, cert = DerReader(der).read_tlv()
    if tag != 0x30:
        raise ValueError("x509: not a SEQUENCE")
    tbs_tag, tbs = cert.read_tlv()
    if tbs_tag != 0x30:
        raise ValueError("x509: bad tbsCertificate")
    # version [0] optional
    first_tag, first = tbs.read_tlv()
    if first_tag != 0xA0:
        pass  # v1 cert: `first` was the serial; already consumed
    else:
        tbs.read_tlv()  # serial
    tbs.read_tlv()  # signature algorithm
    tbs.read_tlv()  # issuer
    tbs.read_tlv()  # validity
    tbs.read_tlv()  # subject
    spki_tag, spki = tbs.read_tlv()
    if spki_tag != 0x30:
        raise ValueError("x509: bad SPKI")
    alg_tag, alg = spki.read_tlv()
    oid_tag, oid = alg.read_tlv()
    if oid_tag != 0x06 or oid.bytes() != _OID_ED25519:
        raise ValueError("x509: subject key is not Ed25519")
    bs_tag, bs = spki.read_tlv()
    if bs_tag != 0x03:
        raise ValueError("x509: bad subjectPublicKey")
    body = bs.bytes()
    if len(body) != 33 or body[0] != 0:
        raise ValueError("x509: bad Ed25519 key length")
    return body[1:]


def cert_verify_self_signed(der: bytes) -> bool:
    """Check the cert's Ed25519 self-signature over tbsCertificate."""
    from firedancer_tpu.ops.ed25519 import verify_one_host

    try:
        pub = cert_pubkey(der)
        tag, cert = DerReader(der).read_tlv()
        start = cert.pos
        tbs_tag, tbs_inner = cert.read_tlv()
        tbs_raw = der[start : cert.pos]
        cert.read_tlv()  # signatureAlgorithm
        bs_tag, bs = cert.read_tlv()
        body = bs.bytes()
        if bs_tag != 0x03 or len(body) != 65 or body[0] != 0:
            return False
        sig = body[1:]
    except ValueError:
        return False
    return bool(verify_one_host(sig, tbs_raw, pub))
