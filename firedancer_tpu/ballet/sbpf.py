"""sBPF ELF loader (ref: src/ballet/sbpf/fd_sbpf_loader.c + src/ballet/elf/).

Loads an on-chain program: ELF64 little-endian, machine EM_BPF, extracting
.text, resolving the entrypoint, and applying the two SBF relocation types:

  R_BPF_64_64       (1): patch a lddw pair's imm fields with a 64-bit vaddr
  R_BPF_64_RELATIVE (8): rebase a 64-bit value by MM_PROGRAM
plus call-imm resolution: `call -1` sites referencing symbols become either
syscall ids (murmur3 of the name) or bpf-to-bpf target pcs.

Also ships a tiny assembler (`asm`) — the test-vector generator role the
reference fills with its in-tree python tooling (wiredancer py/ models,
reedsol generators)."""

import struct
from dataclasses import dataclass, field

from .murmur3 import murmur3_32

EM_BPF = 247
MM_PROGRAM = 0x1_0000_0000

R_BPF_64_64 = 1
R_BPF_64_RELATIVE = 8
R_BPF_64_32 = 10


class SbpfLoaderError(ValueError):
    pass


@dataclass
class SbpfProgram:
    text: bytes          # instruction stream (pc 0 = first insn of .text)
    entry_pc: int
    rodata: bytes        # full loaded image mapped at MM_PROGRAM
    text_off: int        # byte offset of .text within rodata
    calldests: set = field(default_factory=set)


def load(elf: bytes) -> SbpfProgram:
    if elf[:4] != b"\x7fELF":
        raise SbpfLoaderError("not an ELF")
    if elf[4] != 2 or elf[5] != 1:
        raise SbpfLoaderError("need ELF64 little-endian")
    (e_type, e_machine, _, e_entry, _, e_shoff, _, _, _, _,
     e_shentsize, e_shnum, e_shstrndx) = struct.unpack_from(
        "<HHIQQQIHHHHHH", elf, 16)
    if e_machine != EM_BPF:
        raise SbpfLoaderError(f"not a BPF ELF (machine {e_machine})")

    shdrs = []
    for i in range(e_shnum):
        off = e_shoff + i * e_shentsize
        (name, stype, flags, addr, offset, size, link, info, align,
         entsize) = struct.unpack_from("<IIQQQQIIQQ", elf, off)
        shdrs.append(dict(name=name, type=stype, flags=flags, addr=addr,
                          offset=offset, size=size, link=link, info=info,
                          entsize=entsize))
    shstr = shdrs[e_shstrndx]
    strtab_raw = elf[shstr["offset"]:shstr["offset"] + shstr["size"]]

    def sec_name(sh):
        end = strtab_raw.find(b"\0", sh["name"])
        return strtab_raw[sh["name"]:end].decode()

    by_name = {sec_name(sh): sh for sh in shdrs}
    text_sh = by_name.get(".text")
    if text_sh is None:
        raise SbpfLoaderError("no .text section")

    # the loaded image: sections laid out at their file offsets (SBF links
    # with file offset == vaddr, fd_sbpf_loader.c keeps the full image ro)
    image = bytearray(elf)

    # symbol table
    symbols = {}   # index -> (name, value, shndx)
    sym_sh = by_name.get(".symtab") or by_name.get(".dynsym")
    if sym_sh is not None:
        symstr_sh = shdrs[sym_sh["link"]]
        symstr = elf[symstr_sh["offset"]:symstr_sh["offset"] + symstr_sh["size"]]
        n = sym_sh["size"] // 24
        for i in range(n):
            noff, info, other, shndx, value, size = struct.unpack_from(
                "<IBBHQQ", elf, sym_sh["offset"] + 24 * i)
            end = symstr.find(b"\0", noff)
            symbols[i] = (symstr[noff:end].decode(), value, shndx)

    calldests = set()
    text_lo = text_sh["offset"]
    text_hi = text_lo + text_sh["size"]

    # relocations (.rel.dyn / .rel.text — SBF uses REL, not RELA)
    for sh in shdrs:
        if sh["type"] != 9:  # SHT_REL
            continue
        n = sh["size"] // 16
        for i in range(n):
            r_offset, r_info = struct.unpack_from(
                "<QQ", elf, sh["offset"] + 16 * i)
            r_type = r_info & 0xFFFFFFFF
            r_sym = r_info >> 32
            if r_type == R_BPF_64_64:
                # lddw at r_offset: imm lo at +4, imm hi at +12
                sname, sval, shndx = symbols.get(r_sym, ("", 0, 0))
                addr = struct.unpack_from("<I", image, r_offset + 4)[0] + sval
                if addr < MM_PROGRAM:
                    addr += MM_PROGRAM
                struct.pack_into("<I", image, r_offset + 4,
                                 addr & 0xFFFFFFFF)
                struct.pack_into("<I", image, r_offset + 12,
                                 (addr >> 32) & 0xFFFFFFFF)
            elif r_type == R_BPF_64_RELATIVE:
                if text_lo <= r_offset < text_hi:
                    addr = struct.unpack_from("<I", image, r_offset + 4)[0]
                    if addr < MM_PROGRAM:
                        addr += MM_PROGRAM
                    struct.pack_into("<I", image, r_offset + 4,
                                     addr & 0xFFFFFFFF)
                else:
                    addr = struct.unpack_from("<Q", image, r_offset)[0]
                    if addr < MM_PROGRAM:
                        addr += MM_PROGRAM
                    struct.pack_into("<Q", image, r_offset, addr)
            elif r_type == R_BPF_64_32:
                # call site: resolve symbol -> syscall hash or target pc
                sname, sval, shndx = symbols.get(r_sym, ("", 0, 0))
                if shndx == 0:          # undefined -> syscall by name hash
                    key = murmur3_32(sname.encode(), 0)
                else:                   # defined -> bpf-to-bpf target pc
                    key = (sval - text_sh["offset"]) // 8 \
                        if sval >= text_sh["offset"] else sval // 8
                    calldests.add(key)
                struct.pack_into("<I", image, r_offset + 4, key)

    text = bytes(image[text_lo:text_hi])
    # entrypoint: symbol named "entrypoint", else e_entry, else pc 0
    entry_pc = 0
    for name, value, shndx in symbols.values():
        if name == "entrypoint":
            entry_pc = (value - text_sh["addr"]) // 8
            break
    else:
        if e_entry:
            entry_pc = (e_entry - text_sh["addr"]) // 8
    if not (0 <= entry_pc < len(text) // 8):
        raise SbpfLoaderError(f"entrypoint pc {entry_pc} out of range")
    return SbpfProgram(text=text, entry_pc=entry_pc, rodata=bytes(image),
                       text_off=text_lo, calldests=calldests)


# -- mini assembler ---------------------------------------------------------

_ALU_OPS = {"add": 0x0, "sub": 0x1, "mul": 0x2, "div": 0x3, "or": 0x4,
            "and": 0x5, "lsh": 0x6, "rsh": 0x7, "neg": 0x8, "mod": 0x9,
            "xor": 0xA, "mov": 0xB, "arsh": 0xC}
_JMP_OPS = {"ja": 0x0, "jeq": 0x1, "jgt": 0x2, "jge": 0x3, "jset": 0x4,
            "jne": 0x5, "jsgt": 0x6, "jsge": 0x7, "jlt": 0xA, "jle": 0xB,
            "jslt": 0xC, "jsle": 0xD}
_MEM_SZ = {"b": 0x10, "h": 0x08, "w": 0x00, "dw": 0x18}


def ins(op, dst=0, src=0, off=0, imm=0) -> bytes:
    imm &= 0xFFFFFFFF  # accept unsigned (syscall hashes) and signed alike
    return struct.pack("<BBHI", op, (src << 4) | dst, off & 0xFFFF, imm)


def asm(src: str) -> bytes:
    """Assemble newline-separated sBPF mnemonics (registers rN, numbers
    decimal or 0x hex; labels 'name:' with jump targets '=name').
    Covers the subset the tests exercise."""
    lines = [l.split(";")[0].strip() for l in src.strip().splitlines()]
    lines = [l for l in lines if l]
    # first pass: label -> pc (lddw counts as 2 slots)
    labels, pc = {}, 0
    body = []
    for l in lines:
        if l.endswith(":"):
            labels[l[:-1]] = pc
            continue
        body.append((pc, l))
        pc += 2 if l.split()[0] == "lddw" else 1

    def val(tok, cur_pc):
        if tok.startswith("="):
            return labels[tok[1:]] - cur_pc - 1
        return int(tok, 0)

    out = bytearray()
    for cur_pc, l in body:
        parts = l.replace(",", " ").split()
        m = parts[0]
        if m == "exit":
            out += ins(0x95)
        elif m == "call":
            # VM semantics: call imm is an ABSOLUTE target pc (the loader
            # resolves symbols to absolute pcs), unlike relative jumps
            tgt = labels[parts[1][1:]] if parts[1].startswith("=") \
                else int(parts[1], 0)
            out += ins(0x85, imm=tgt)
        elif m == "syscall":
            out += ins(0x85, imm=murmur3_32(parts[1].encode(), 0))
        elif m == "callx":
            out += ins(0x8D, imm=int(parts[1][1:]))
        elif m == "lddw":
            v = val(parts[2], cur_pc) & 0xFFFFFFFFFFFFFFFF
            dst = int(parts[1][1:])
            out += ins(0x18, dst=dst, imm=v & 0xFFFFFFFF)
            out += ins(0x00, imm=(v >> 32) & 0xFFFFFFFF)
        elif m in ("ldxb", "ldxh", "ldxw", "ldxdw"):
            sz = _MEM_SZ[m[3:]]
            dst = int(parts[1][1:])
            inner = l[l.index("[") + 1:l.index("]")]
            reg, _, disp = inner.partition("+")
            out += ins(0x61 | sz, dst=dst, src=int(reg.strip()[1:]),
                       off=int(disp or 0, 0))
        elif m in ("stxb", "stxh", "stxw", "stxdw"):
            sz = _MEM_SZ[m[3:]]
            inner = l[l.index("[") + 1:l.index("]")]
            reg, _, disp = inner.partition("+")
            out += ins(0x63 | sz, dst=int(reg.strip()[1:]),
                       src=int(parts[-1][1:]), off=int(disp or 0, 0))
        elif m in ("stb", "sth", "stw", "stdw"):
            sz = _MEM_SZ[m[2:]]
            inner = l[l.index("[") + 1:l.index("]")]
            reg, _, disp = inner.partition("+")
            out += ins(0x62 | sz, dst=int(reg.strip()[1:]),
                       off=int(disp or 0, 0), imm=int(parts[-1], 0))
        elif m in ("le", "be"):
            dst = int(parts[1][1:])
            out += ins(0xD4 | (0x08 if m == "be" else 0),
                       dst=dst, imm=int(parts[2]))
        elif m.rstrip("32") in _ALU_OPS:
            is32 = m.endswith("32")
            base = 0x04 if is32 else 0x07
            opc = _ALU_OPS[m.rstrip("32")] << 4
            dst = int(parts[1][1:])
            if opc == 0x80:  # neg
                out += ins(base | opc, dst=dst)
            elif parts[2].startswith("r"):
                out += ins(base | opc | 0x08, dst=dst, src=int(parts[2][1:]))
            else:
                out += ins(base | opc, dst=dst, imm=int(parts[2], 0))
        elif m in _JMP_OPS:
            opc = _JMP_OPS[m] << 4
            if m == "ja":
                out += ins(0x05, off=val(parts[1], cur_pc))
            else:
                dst = int(parts[1][1:])
                tgt = val(parts[3], cur_pc)
                if parts[2].startswith("r"):
                    out += ins(0x05 | opc | 0x08, dst=dst,
                               src=int(parts[2][1:]), off=tgt)
                else:
                    out += ins(0x05 | opc, dst=dst, off=tgt,
                               imm=int(parts[2], 0))
        else:
            raise ValueError(f"cannot assemble: {l}")
    return bytes(out)


# ------------------------------------------------------------- disassembler
# (role of the reference's vm disassembler, src/flamenco/vm/fd_vm_disasm.c)

_ALU_NAMES = {v: k for k, v in _ALU_OPS.items()}
_JMP_NAMES = {v: k for k, v in _JMP_OPS.items()}
_SZ_NAMES = {0x10: "b", 0x08: "h", 0x00: "w", 0x18: "dw"}


def disasm_one(op: int, dst: int, src: int, off: int, imm: int,
               imm_hi: int | None = None) -> str:
    """One instruction -> mnemonic text (asm()'s syntax, so round-trips)."""
    cls = op & 0x07
    if op == 0x95:
        return "exit"
    if op == 0x85:
        return f"call {imm & 0xFFFFFFFF:#x}"
    if op == 0x8D:
        return f"callx r{imm}"
    if op == 0x18:
        v = (imm & 0xFFFFFFFF) | (((imm_hi or 0) & 0xFFFFFFFF) << 32)
        return f"lddw r{dst}, {v:#x}"
    if op & 0xF7 == 0xD4:
        return f"{'be' if op & 0x08 else 'le'} r{dst} {imm}"
    if cls in (0x07, 0x04):  # ALU64 / ALU32
        name = _ALU_NAMES.get(op >> 4)
        if name is None:
            return f".byte {op:#04x}"
        sfx = "" if cls == 0x07 else "32"
        if name == "neg":
            return f"{name}{sfx} r{dst}"
        rhs = f"r{src}" if op & 0x08 else f"{imm}"
        return f"{name}{sfx} r{dst}, {rhs}"
    if cls == 0x05:
        name = _JMP_NAMES.get(op >> 4)
        if name is None:
            return f".byte {op:#04x}"
        if name == "ja":
            return f"ja {off}"
        rhs = f"r{src}" if op & 0x08 else f"{imm}"
        return f"{name} r{dst}, {rhs}, {off}"
    if cls in (0x00, 0x01):  # LDX
        sz = _SZ_NAMES.get(op & 0x18, "?")
        return f"ldx{sz} r{dst}, [r{src}+{off}]"
    if cls in (0x02, 0x03):  # ST / STX
        sz = _SZ_NAMES.get(op & 0x18, "?")
        if op & 0x01:  # stx
            return f"stx{sz} [r{dst}+{off}], r{src}"
        return f"st{sz} [r{dst}+{off}], {imm}"
    return f".byte {op:#04x}"


def disasm(code: bytes) -> list[str]:
    """Disassemble a text segment; one entry per 8-byte slot (lddw's
    second slot renders as a continuation comment)."""
    out = []
    i = 0
    n = len(code) // 8
    while i < n:
        op, regs, off, imm = struct.unpack_from("<BBhi", code, i * 8)
        dst, src = regs & 0xF, regs >> 4
        if op == 0x18 and i + 1 < n:
            (imm2,) = struct.unpack_from("<i", code, (i + 1) * 8 + 4)
            out.append(disasm_one(op, dst, src, off, imm, imm2))
            out.append("; lddw cont")
            i += 2
            continue
        out.append(disasm_one(op, dst, src, off, imm))
        i += 1
    return out


def mini_elf(text: bytes, entry_sym_value: int = 0) -> bytes:
    """Hand-rolled minimal BPF ELF64 (.text + .symtab('entrypoint') +
    .strtab + .shstrtab): the fixture/test program container (also used
    by the test-vectors ELF corpus generator)."""
    ehsize, shentsize = 64, 64
    shstrtab = b"\0.text\0.symtab\0.strtab\0.shstrtab\0"
    strtab = b"\0entrypoint\0"
    # symtab: null sym + entrypoint(value=entry_sym_value, shndx=1)
    symtab = bytes(24) + struct.pack("<IBBHQQ", 1, 0x12, 0, 1,
                                     entry_sym_value, 0)
    off = ehsize + 5 * shentsize
    text_off = off
    sym_off = text_off + len(text)
    str_off = sym_off + len(symtab)
    shstr_off = str_off + len(strtab)

    def shdr(name, stype, offset, size, link=0, entsize=0, addr=0):
        return struct.pack("<IIQQQQIIQQ", name, stype, 0, addr, offset,
                           size, link, 0, 8, entsize)

    shdrs = (shdr(0, 0, 0, 0)
             + shdr(1, 1, text_off, len(text))                  # .text
             + shdr(7, 2, sym_off, len(symtab), link=3, entsize=24)
             + shdr(15, 3, str_off, len(strtab))                # .strtab
             + shdr(23, 3, shstr_off, len(shstrtab)))           # .shstrtab
    ehdr = (b"\x7fELF\x02\x01\x01" + bytes(9)
            + struct.pack("<HHIQQQIHHHHHH", 3, 247, 1, 0, 0, ehsize, 0,
                          ehsize, 0, 0, shentsize, 5, 4))
    return ehdr + shdrs + text + symtab + strtab + shstrtab
