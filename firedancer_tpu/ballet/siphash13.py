"""SipHash-1-3 keyed hash, host-side (numpy-vectorizable core).

Reference role: src/ballet/siphash13/ — keyed flow steering (e.g. picking a
verify tile for a QUIC connection) where an unkeyed hash would let an
attacker aim all load at one shard.  SipHash-1-3 = 1 compression round per
word, 3 finalization rounds (the reduced-round variant the reference and
Rust's std hasher use).
"""

import numpy as np

_M = np.uint64(0xFFFFFFFFFFFFFFFF)


def _rotl(x, b):
    b = np.uint64(b)
    return ((x << b) | (x >> (np.uint64(64) - b))) & _M


def _round(v0, v1, v2, v3):
    v0 = (v0 + v1) & _M
    v1 = _rotl(v1, 13)
    v1 ^= v0
    v0 = _rotl(v0, 32)
    v2 = (v2 + v3) & _M
    v3 = _rotl(v3, 16)
    v3 ^= v2
    v0 = (v0 + v3) & _M
    v3 = _rotl(v3, 21)
    v3 ^= v0
    v2 = (v2 + v1) & _M
    v1 = _rotl(v1, 17)
    v1 ^= v2
    v2 = _rotl(v2, 32)
    return v0, v1, v2, v3


def siphash13(k0: int, k1: int, data: bytes) -> int:
    """64-bit SipHash-1-3 of `data` under key (k0, k1)."""
    with np.errstate(over="ignore"):
        k0 = np.uint64(k0)
        k1 = np.uint64(k1)
        v0 = k0 ^ np.uint64(0x736F6D6570736575)
        v1 = k1 ^ np.uint64(0x646F72616E646F6D)
        v2 = k0 ^ np.uint64(0x6C7967656E657261)
        v3 = k1 ^ np.uint64(0x7465646279746573)

        n = len(data)
        tail_len = n & 7
        # last word encodes length in the top byte (SipHash spec)
        tail = data[n - tail_len :] + b"\0" * (7 - tail_len) + bytes([n & 0xFF])
        words = np.frombuffer(data[: n - tail_len] + tail, dtype="<u8")

        for m in words:
            v3 ^= m
            v0, v1, v2, v3 = _round(v0, v1, v2, v3)  # c = 1 round
            v0 ^= m
        v2 ^= np.uint64(0xFF)
        for _ in range(3):  # d = 3 rounds
            v0, v1, v2, v3 = _round(v0, v1, v2, v3)
        return int(v0 ^ v1 ^ v2 ^ v3)
