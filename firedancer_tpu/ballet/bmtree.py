"""Binary merkle trees (20- and 32-byte nodes), TPU-first.

Reference role: src/ballet/bmtree/ — merkle commitments over shred FEC sets
(20-byte truncated nodes) and general 32-byte trees.  Domain separation
follows the Solana protocol: leaf hash = sha256(0x00 || data), interior
hash = sha256(0x01 || left || right), odd nodes promoted by hashing with
themselves.

TPU shape: each tree level is one batched sha256 over all sibling pairs at
that level (the whole level is a single fixed-shape device call), rather
than the reference's incremental leaf-append state machine — on TPU the
natural unit is "commit a whole FEC set at once".
"""

import jax.numpy as jnp
import numpy as np

from firedancer_tpu.ops.sha256 import sha256

LEAF_PREFIX = 0x00
INTERIOR_PREFIX = 0x01

# Long domain-separation prefixes used by the Solana shred merkle tree
# (fd_bmtree.c:141-142); the 1-byte short prefixes above are the generic
# 32-byte-tree form.
LEAF_PREFIX_LONG = b"\x00SOLANA_MERKLE_SHREDS_LEAF"
NODE_PREFIX_LONG = b"\x01SOLANA_MERKLE_SHREDS_NODE"


def hash_leaves(data, lengths, node_sz: int = 32):
    """Leaf hashes: sha256(0x00 || data[i][:len]) truncated to node_sz.

    data: uint8 (n, maxlen); lengths: int32 (n,) → uint8 (n, node_sz)."""
    n, maxlen = data.shape
    pre = jnp.concatenate(
        [jnp.full((n, 1), LEAF_PREFIX, dtype=jnp.uint8), data], axis=1
    )
    return sha256(pre, lengths.astype(jnp.int32) + 1)[:, :node_sz]


def _hash_level(nodes, node_sz: int):
    """One tree level: pair up nodes (odd count: last pairs with itself) and
    hash each pair.  nodes: uint8 (n, node_sz) → (ceil(n/2), node_sz)."""
    n = nodes.shape[0]
    if n % 2:
        nodes = jnp.concatenate([nodes, nodes[-1:]], axis=0)
    left = nodes[0::2]
    right = nodes[1::2]
    m = left.shape[0]
    buf = jnp.concatenate(
        [jnp.full((m, 1), INTERIOR_PREFIX, dtype=jnp.uint8), left, right], axis=1
    )
    lens = jnp.full((m,), 1 + 2 * node_sz, dtype=jnp.int32)
    return sha256(buf, lens)[:, :node_sz]


def root_from_leaves(leaf_hashes, node_sz: int = 32):
    """Reduce leaf hashes to the root.  leaf_hashes: uint8 (n, node_sz).
    Level count is static (derived from n at trace time)."""
    nodes = leaf_hashes
    while nodes.shape[0] > 1:
        nodes = _hash_level(nodes, node_sz)
    return nodes[0]


def commit(data, lengths, node_sz: int = 32):
    """Full tree: leaves → root in one jittable call."""
    return root_from_leaves(hash_leaves(data, lengths, node_sz), node_sz)


# ---------------------------------------------------------------------------
# Batched proof walk (shred trees): B inclusion proofs -> B untruncated
# roots, one batched sha256 per level.  The walk is the device twin of
# shred.walk_merkle_root: leaf = sha256(LEAF_PREFIX_LONG || data), each
# level truncates the running node to 20 bytes, pairs it with the sibling
# by the index bit, and rehashes under NODE_PREFIX_LONG; the ROOT is the
# final full 32-byte digest.  Ragged depths ride one static-max-depth
# graph via a where-mask (shape family: (B, maxlen, D) — steady-state
# bursts reuse one compile).

MERKLE_NODE_SZ = 20


def batch_walk_roots(leaf_data, lengths, indices, proofs, depths):
    """leaf_data u8 (B, maxlen); lengths i32 (B,); indices i32 (B,) = leaf
    tree index; proofs u8 (B, D, 20); depths i32 (B,) <= D (static max).
    Returns u8 (B, 32) roots.  Jit-safe; call under jax.jit for the
    production path."""
    B = leaf_data.shape[0]
    D = proofs.shape[1]
    leaf_pre = jnp.tile(
        jnp.frombuffer(LEAF_PREFIX_LONG, dtype=np.uint8)[None, :], (B, 1))
    node_pre = jnp.tile(
        jnp.frombuffer(NODE_PREFIX_LONG, dtype=np.uint8)[None, :], (B, 1))
    npre = len(NODE_PREFIX_LONG)
    h = sha256(
        jnp.concatenate([leaf_pre, leaf_data.astype(jnp.uint8)], axis=1),
        lengths.astype(jnp.int32) + len(LEAF_PREFIX_LONG))
    idx = indices.astype(jnp.int32)
    for lvl in range(D):
        t = h[:, :MERKLE_NODE_SZ]
        p = proofs[:, lvl, :].astype(jnp.uint8)
        right_child = ((idx >> lvl) & 1).astype(bool)[:, None]
        left = jnp.where(right_child, p, t)
        right = jnp.where(right_child, t, p)
        buf = jnp.concatenate([node_pre, left, right], axis=1)
        h2 = sha256(buf, jnp.full((B,), npre + 2 * MERKLE_NODE_SZ,
                                  dtype=jnp.int32))
        h = jnp.where((depths > lvl)[:, None], h2, h)
    return h


_batch_walk_roots_jit = None


def batch_walk_roots_jit():
    """Lazily-jitted batch_walk_roots (module import stays graph-free)."""
    global _batch_walk_roots_jit
    if _batch_walk_roots_jit is None:
        import jax

        _batch_walk_roots_jit = jax.jit(batch_walk_roots)
    return _batch_walk_roots_jit


def np_batch_walk_roots(leaf_datas, indices, proofs) -> list[bytes]:
    """Host golden twin of batch_walk_roots (ragged lists, hashlib)."""
    out = []
    for leaf, idx, proof in zip(leaf_datas, indices, proofs):
        h = _np_sha256(LEAF_PREFIX_LONG + bytes(leaf))
        for p in proof:
            t = h[:MERKLE_NODE_SZ]
            pair = (bytes(p) + t) if idx & 1 else (t + bytes(p))
            h = _np_sha256(NODE_PREFIX_LONG + pair)
            idx >>= 1
        out.append(h)
    return out


# ---------------------------------------------------------------------------
# Host-side (numpy) proof plumbing — control plane, mirrors the device tree.


def _np_sha256(b: bytes) -> bytes:
    import hashlib

    return hashlib.sha256(b).digest()


def np_tree(
    leaves: list[bytes],
    node_sz: int = 32,
    leaf_prefix: bytes = bytes([LEAF_PREFIX]),
    node_prefix: bytes = bytes([INTERIOR_PREFIX]),
) -> list[list[bytes]]:
    """All levels bottom-up; leaves are raw data (prefixed + hashed here).
    Pass LEAF_PREFIX_LONG/NODE_PREFIX_LONG + node_sz=20 for shred trees."""
    level = [_np_sha256(leaf_prefix + d)[:node_sz] for d in leaves]
    levels = [level]
    while len(level) > 1:
        if len(level) % 2:
            level = level + [level[-1]]
        level = [
            _np_sha256(node_prefix + level[i] + level[i + 1])[:node_sz]
            for i in range(0, len(level), 2)
        ]
        levels.append(level)
    return levels


def np_proof(levels: list[list[bytes]], idx: int) -> list[bytes]:
    """Inclusion proof (sibling path) for leaf idx."""
    proof = []
    for level in levels[:-1]:
        sib = idx ^ 1
        if sib >= len(level):
            sib = idx  # odd promotion: sibling is self
        proof.append(level[sib])
        idx //= 2
    return proof


def np_verify_proof(
    leaf_data: bytes,
    idx: int,
    proof: list[bytes],
    root: bytes,
    node_sz: int = 32,
    leaf_prefix: bytes = bytes([LEAF_PREFIX]),
    node_prefix: bytes = bytes([INTERIOR_PREFIX]),
) -> bool:
    node = _np_sha256(leaf_prefix + leaf_data)[:node_sz]
    for sib in proof:
        pair = (node + sib) if idx % 2 == 0 else (sib + node)
        node = _np_sha256(node_prefix + pair)[:node_sz]
        idx //= 2
    return node == root
