"""alt_bn128 (BN254) G1 group ops + compression (ref: src/ballet/bn254/ —
the reference ships stubs backing the alt_bn128 syscalls; we implement the
G1 arithmetic the add/mul syscalls need directly and gate the pairing the
same way the reference gates its unimplemented surface).

Curve: y^2 = x^3 + 3 over Fp, p the BN254 base field prime.  Serialization
is the syscall ABI's: 64-byte big-endian (x ‖ y) points, zero bytes = the
identity.
"""

from __future__ import annotations

P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
N = 21888242871839275222246405745257275088548364400416034343698204186575808495617
_B = 3


class Bn254Error(ValueError):
    pass


def _add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def _mul(k: int, pt):
    acc = None
    while k:
        if k & 1:
            acc = _add(acc, pt)
        pt = _add(pt, pt)
        k >>= 1
    return acc


def decode_g1(b: bytes):
    """64-byte BE (x ‖ y) -> point; all-zero = identity; validates
    curve membership (the syscall MUST reject off-curve inputs)."""
    if len(b) != 64:
        raise Bn254Error("bn254: G1 point must be 64 bytes")
    x = int.from_bytes(b[:32], "big")
    y = int.from_bytes(b[32:], "big")
    if x == 0 and y == 0:
        return None
    if x >= P or y >= P:
        raise Bn254Error("bn254: coordinate out of field")
    if (y * y - x * x * x - _B) % P:
        raise Bn254Error("bn254: point not on curve")
    return x, y


def encode_g1(pt) -> bytes:
    if pt is None:
        return bytes(64)
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def g1_add(a: bytes, b: bytes) -> bytes:
    """The alt_bn128_addition syscall (sol_alt_bn128_group_op ADD)."""
    return encode_g1(_add(decode_g1(a), decode_g1(b)))


def g1_scalar_mul(a: bytes, scalar: bytes) -> bytes:
    """The alt_bn128_multiplication syscall: 32-byte BE scalar."""
    if len(scalar) != 32:
        raise Bn254Error("bn254: scalar must be 32 bytes")
    k = int.from_bytes(scalar, "big") % N
    return encode_g1(_mul(k, decode_g1(a)))


def pairing_check(pairs: bytes) -> bool:
    """The alt_bn128_pairing syscall surface.  G2/pairing arithmetic is not
    implemented (the reference's bn254 is likewise a stub layer,
    src/ballet/bn254/); callers get a typed gate, not silent wrong math."""
    raise Bn254Error(
        "bn254 pairing not implemented in this build (reference parity: "
        "src/ballet/bn254 is a stub layer)")
