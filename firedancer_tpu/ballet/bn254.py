"""alt_bn128 (BN254) G1/G2 group ops, compression, and the optimal ate
pairing — the full surface behind Solana's alt_bn128 syscalls.

Parity target: src/ballet/bn254/fd_bn254.{h,cxx} (the reference wraps
libff; fd_bn254_g1_check/compress/decompress, g2 variants, g1_add,
g1_mult, fd_bn254_pairing).  This build implements the curve and pairing
arithmetic from scratch:

  * Fp12 is the single polynomial extension Fp[w]/(w^12 - 18 w^6 + 82);
    u := w^6 - 9 then satisfies u^2 = -1, so Fp2 = Fp[u] embeds as
    a0 + a1*(w^6 - 9).  One generic dense-polynomial arithmetic layer
    (mul / xgcd-inverse) covers the whole tower — no 2-3-2 ladder.
  * G2 points (over Fp2, curve y^2 = x^3 + 3/(9+u)) are "untwisted" into
    E(Fp12) coordinates (x*w^2, y*w^3); the Miller loop then runs on one
    generic affine line function over Fp12.
  * Optimal ate: loop count 6t+2, two frobenius correction lines, final
    exponentiation split into the easy part (p^6-1)(p^2+1) and a plain
    square-and-multiply of the hard exponent (p^4 - p^2 + 1)/r.

Serialization is the syscall ABI's: big-endian 32-byte field elements;
G1 = x ‖ y (64 B), G2 = x.c1 ‖ x.c0 ‖ y.c1 ‖ y.c0 (128 B, imaginary limb
first — fd_bn254_Fq2_sol_to_libff reads c1 then c0); all-zero = identity.
Compressed form: X only, top bit of byte 0 flags Y parity (the reference's
bit-7 "Y is odd" flag, fd_bn254_g1_compress).
"""

from __future__ import annotations

# ---------------------------------------------------------------- params

P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
N = 21888242871839275222246405745257275088548364400416034343698204186575808495617
_B = 3

# BN parameter t: p = 36t^4 + 36t^3 + 24t^2 + 6t + 1
_T = 4965661367192848881
ATE_LOOP = 6 * _T + 2
assert P == 36 * _T**4 + 36 * _T**3 + 24 * _T**2 + 6 * _T + 1
assert N == 36 * _T**4 + 36 * _T**3 + 18 * _T**2 + 6 * _T + 1


class Bn254Error(ValueError):
    pass


# ---------------------------------------------------------------- Fp2
# (a0, a1) = a0 + a1*u with u^2 = -1; only needed for G2 decode/checks and
# compression sqrt — the pairing itself runs in Fp12.


def _f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def _f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def _f2_mul(a, b):
    return (
        (a[0] * b[0] - a[1] * b[1]) % P,
        (a[0] * b[1] + a[1] * b[0]) % P,
    )


def _f2_sqr(a):
    return _f2_mul(a, a)


def _f2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def _f2_inv(a):
    d = pow(a[0] * a[0] + a[1] * a[1], P - 2, P)
    return (a[0] * d % P, (-a[1]) * d % P)


def _f2_pow(a, e: int):
    r = (1, 0)
    while e:
        if e & 1:
            r = _f2_mul(r, a)
        a = _f2_sqr(a)
        e >>= 1
    return r


_XI = (9, 1)  # u + 9, the sextic non-residue
_B2 = _f2_mul((_B, 0), _f2_inv(_XI))  # twist coefficient b' = 3/(9+u)


def _f2_sqrt(a):
    """Square root in Fp2 (p ≡ 3 mod 4): candidate a^((q+7)/8)-style via
    the norm trick.  Returns None if a is not a square."""
    if a == (0, 0):
        return (0, 0)
    # Algorithm 9 of Adj–Rodríguez-Henríquez (complex method): with
    # q = p^2, compute a1 = a^((p-3)/4), x0 = a1^2 * a, alpha = x0 norm part
    a1 = _f2_pow(a, (P - 3) // 4)
    alpha = _f2_mul(_f2_sqr(a1), a)
    x0 = _f2_mul(a1, a)
    if alpha == (P - 1 % P, 0):
        x = _f2_mul((0, 1), x0)  # u * x0
    else:
        b = _f2_pow(_f2_add(alpha, (1, 0)), (P - 1) // 2)
        x = _f2_mul(b, x0)
    return x if _f2_sqr(x) == a else None


# ---------------------------------------------------------------- Fp12
# Dense degree-<12 polynomials in w over Fp, modulo w^12 - 18 w^6 + 82.
# Reduction: w^12 ≡ 18 w^6 - 82.

_DEG = 12
_MOD_MID = 18  # w^12 = 18*w^6 - 82
_MOD_LO = -82


def _f12(c0: int = 0) -> list:
    v = [0] * _DEG
    v[0] = c0 % P
    return v


_F12_ONE = _f12(1)


def _f12_add(a, b):
    return [(x + y) % P for x, y in zip(a, b)]


def _f12_sub(a, b):
    return [(x - y) % P for x, y in zip(a, b)]


def _f12_neg(a):
    return [(-x) % P for x in a]


def _f12_scale(a, k: int):
    return [x * k % P for x in a]


def _f12_mul(a, b):
    # dense 12x12 convolution then two-step reduction by w^12 = 18w^6 - 82
    c = [0] * (2 * _DEG - 1)
    for i, ai in enumerate(a):
        if not ai:
            continue
        for j, bj in enumerate(b):
            c[i + j] += ai * bj
    for k in range(2 * _DEG - 2, _DEG - 1, -1):
        t = c[k]
        if t:
            c[k - 6] += t * _MOD_MID
            c[k - 12] += t * _MOD_LO
    return [x % P for x in c[:_DEG]]


def _f12_sqr(a):
    return _f12_mul(a, a)


def _f12_pow(a, e: int):
    r = _F12_ONE[:]
    while e:
        if e & 1:
            r = _f12_mul(r, a)
        a = _f12_sqr(a)
        e >>= 1
    return r


def _poly_divmod(num, den):
    """Polynomial division over Fp (dense int-list coeffs, little-endian)."""
    num = num[:]
    deg_d = len(den) - 1
    while deg_d >= 0 and den[deg_d] == 0:
        deg_d -= 1
    inv_lead = pow(den[deg_d], P - 2, P)
    q = [0] * max(1, len(num) - deg_d)
    for k in range(len(num) - deg_d - 1, -1, -1):
        c = num[k + deg_d] * inv_lead % P
        if c:
            q[k] = c
            for i in range(deg_d + 1):
                num[k + i] = (num[k + i] - c * den[i]) % P
    return q, num[:deg_d] if deg_d > 0 else [0]


def _f12_inv(a):
    """Inverse via extended Euclid on polynomials mod (w^12 - 18w^6 + 82)."""
    modp = [0] * (_DEG + 1)
    modp[0] = 82 % P
    modp[6] = (-18) % P
    modp[12] = 1
    # xgcd(a, modp)
    r0, r1 = a[:] + [0], modp
    s0, s1 = [1], [0]
    while True:
        deg1 = len(r1) - 1
        while deg1 >= 0 and r1[deg1] == 0:
            deg1 -= 1
        if deg1 < 0:
            raise Bn254Error("bn254: non-invertible Fp12 element")
        if deg1 == 0:
            c = pow(r1[0], P - 2, P)
            out = [x * c % P for x in s1]
            out += [0] * (_DEG - len(out))
            return out[:_DEG]
        q, rem = _poly_divmod(r0, r1[: deg1 + 1])
        # s_new = s0 - q*s1
        qs = [0] * (len(q) + len(s1) - 1)
        for i, qi in enumerate(q):
            if not qi:
                continue
            for j, sj in enumerate(s1):
                qs[i + j] = (qs[i + j] + qi * sj) % P
        s_new = [
            ((s0[i] if i < len(s0) else 0) - (qs[i] if i < len(qs) else 0)) % P
            for i in range(max(len(s0), len(qs), 1))
        ]
        r0, r1 = r1, rem
        s0, s1 = s1, s_new


def _f2_to_f12(a):
    """Embed a0 + a1*u with u = w^6 - 9: a0 - 9*a1 + a1*w^6."""
    v = _f12((a[0] - 9 * a[1]) % P)
    v[6] = a[1] % P
    return v


# w^2 and w^3 as Fp12 elements (for the twist map)
_W2 = _f12()
_W2[2] = 1
_W3 = _f12()
_W3[3] = 1


# ------------------------------------------------------- generic curve ops
# Affine points are (x, y) tuples of field elements; None = infinity.
# Field ops are passed in so the same code serves Fp (ints) and Fp12.


class _Ops:
    __slots__ = ("add", "sub", "mul", "sqr", "inv", "neg", "scale")

    def __init__(self, add, sub, mul, sqr, inv, neg, scale):
        self.add, self.sub, self.mul = add, sub, mul
        self.sqr, self.inv, self.neg, self.scale = sqr, inv, neg, scale


_OPS12 = _Ops(
    _f12_add, _f12_sub, _f12_mul, _f12_sqr, _f12_inv, _f12_neg, _f12_scale
)


def _pt_double(ops, pt):
    x, y = pt
    lam = ops.mul(ops.scale(ops.sqr(x), 3), ops.inv(ops.scale(y, 2)))
    x3 = ops.sub(ops.sqr(lam), ops.scale(x, 2))
    y3 = ops.sub(ops.mul(lam, ops.sub(x, x3)), y)
    return (x3, y3)


def _pt_add(ops, p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if ops.add(y1, y2) == ops.scale(y1, 0):
            return None
        return _pt_double(ops, p1)
    lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    x3 = ops.sub(ops.sub(ops.sqr(lam), x1), x2)
    y3 = ops.sub(ops.mul(lam, ops.sub(x1, x3)), y1)
    return (x3, y3)


# ---------------------------------------------------------------- G1


def _add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def _mul(k: int, pt):
    acc = None
    while k:
        if k & 1:
            acc = _add(acc, pt)
        pt = _add(pt, pt)
        k >>= 1
    return acc


def decode_g1(b: bytes):
    """64-byte BE (x ‖ y) -> point; all-zero = identity; validates
    curve membership (the syscall MUST reject off-curve inputs)."""
    if len(b) != 64:
        raise Bn254Error("bn254: G1 point must be 64 bytes")
    x = int.from_bytes(b[:32], "big")
    y = int.from_bytes(b[32:], "big")
    if x == 0 and y == 0:
        return None
    if x >= P or y >= P:
        raise Bn254Error("bn254: coordinate out of field")
    if (y * y - x * x * x - _B) % P:
        raise Bn254Error("bn254: point not on curve")
    return x, y


def encode_g1(pt) -> bytes:
    if pt is None:
        return bytes(64)
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def g1_add(a: bytes, b: bytes) -> bytes:
    """The alt_bn128_addition syscall (sol_alt_bn128_group_op ADD)."""
    return encode_g1(_add(decode_g1(a), decode_g1(b)))


def g1_scalar_mul(a: bytes, scalar: bytes) -> bytes:
    """The alt_bn128_multiplication syscall: 32-byte BE scalar."""
    if len(scalar) != 32:
        raise Bn254Error("bn254: scalar must be 32 bytes")
    k = int.from_bytes(scalar, "big") % N
    return encode_g1(_mul(k, decode_g1(a)))


# ---------------------------------------------------------------- G1/G2 compression


def g1_compress(b: bytes) -> bytes:
    """64-byte point -> 32-byte X with bit 7 of byte 0 = Y parity
    (ref fd_bn254_g1_compress flag semantics)."""
    pt = decode_g1(b)
    if pt is None:
        return bytes(32)
    out = bytearray(pt[0].to_bytes(32, "big"))
    if pt[1] & 1:
        out[0] |= 0x80
    return bytes(out)


def g1_decompress(b: bytes) -> bytes:
    if len(b) != 32:
        raise Bn254Error("bn254: compressed G1 must be 32 bytes")
    if b == bytes(32):
        return bytes(64)
    odd = bool(b[0] & 0x80)
    # only the parity flag (bit 7) is masked off; any residual bit that
    # pushes x past p (p < 2^254, so bit 254 always does) must reject
    x = int.from_bytes(bytes([b[0] & 0x7F]) + b[1:], "big")
    if x >= P:
        raise Bn254Error("bn254: coordinate out of field")
    rhs = (x * x * x + _B) % P
    y = pow(rhs, (P + 1) // 4, P)
    if y * y % P != rhs:
        raise Bn254Error("bn254: X not on curve")
    if (y & 1) != odd:
        y = P - y
    return encode_g1((x, y))


def decode_g2(b: bytes):
    """128-byte BE (x.c1 ‖ x.c0 ‖ y.c1 ‖ y.c0) -> ((x0,x1),(y0,y1)) in Fp2;
    all-zero = identity.  The imaginary limb comes FIRST on the wire
    (ref fd_bn254_Fq2_sol_to_libff reads c1 then c0)."""
    if len(b) != 128:
        raise Bn254Error("bn254: G2 point must be 128 bytes")
    x1 = int.from_bytes(b[0:32], "big")
    x0 = int.from_bytes(b[32:64], "big")
    y1 = int.from_bytes(b[64:96], "big")
    y0 = int.from_bytes(b[96:128], "big")
    if x0 == x1 == y0 == y1 == 0:
        return None
    for v in (x0, x1, y0, y1):
        if v >= P:
            raise Bn254Error("bn254: coordinate out of field")
    x, y = (x0, x1), (y0, y1)
    if _f2_sub(_f2_sqr(y), _f2_add(_f2_mul(_f2_sqr(x), x), _B2)) != (0, 0):
        raise Bn254Error("bn254: point not on twist curve")
    return x, y


def encode_g2(pt) -> bytes:
    if pt is None:
        return bytes(128)
    (x0, x1), (y0, y1) = pt
    return (
        x1.to_bytes(32, "big") + x0.to_bytes(32, "big")
        + y1.to_bytes(32, "big") + y0.to_bytes(32, "big")
    )


def g2_compress(b: bytes) -> bytes:
    """128-byte G2 -> 64-byte X, bit 7 of byte 0 = parity of y.c0
    (the reference flags byte FD_BN254_FIELD_FOOTPRINT*3-1, i.e. y.c1's
    low byte in wire order = y.c0... the low bit of the third limb; we flag
    the canonical y.c0 parity and decompress symmetrically)."""
    pt = decode_g2(b)
    if pt is None:
        return bytes(64)
    (x0, x1), (y0, y1) = pt
    out = bytearray(x1.to_bytes(32, "big") + x0.to_bytes(32, "big"))
    if y0 & 1:
        out[0] |= 0x80
    return bytes(out)


def g2_decompress(b: bytes) -> bytes:
    if len(b) != 64:
        raise Bn254Error("bn254: compressed G2 must be 64 bytes")
    if b == bytes(64):
        return bytes(128)
    odd = bool(b[0] & 0x80)
    x1 = int.from_bytes(bytes([b[0] & 0x7F]) + b[1:32], "big")
    x0 = int.from_bytes(b[32:64], "big")
    if x0 >= P or x1 >= P:
        raise Bn254Error("bn254: coordinate out of field")
    x = (x0, x1)
    rhs = _f2_add(_f2_mul(_f2_sqr(x), x), _B2)
    y = _f2_sqrt(rhs)
    if y is None:
        raise Bn254Error("bn254: X not on twist curve")
    if (y[0] & 1) != odd:
        y = _f2_neg(y)
    return encode_g2((x, y))


def g2_subgroup_check(pt) -> bool:
    """[N]Q == O on the twist (jacobian over Fp2, no inversions)."""
    if pt is None:
        return True
    X, Y, Z = pt[0], pt[1], (1, 0)

    def jdouble(X, Y, Z):
        A = _f2_sqr(X)
        Bv = _f2_sqr(Y)
        C = _f2_sqr(Bv)
        D = _f2_mul(_f2_sub(_f2_sqr(_f2_add(X, Bv)), _f2_add(A, C)), (2, 0))
        E = _f2_mul(A, (3, 0))
        F = _f2_sqr(E)
        X3 = _f2_sub(F, _f2_mul(D, (2, 0)))
        Y3 = _f2_sub(_f2_mul(E, _f2_sub(D, X3)), _f2_mul(C, (8, 0)))
        Z3 = _f2_mul(_f2_mul(Y, Z), (2, 0))
        return X3, Y3, Z3

    def jadd(X1, Y1, Z1, X2, Y2):
        # mixed addition, (X2, Y2) affine; Z1 != 0
        Z1Z1 = _f2_sqr(Z1)
        U2 = _f2_mul(X2, Z1Z1)
        S2 = _f2_mul(_f2_mul(Y2, Z1), Z1Z1)
        H = _f2_sub(U2, X1)
        R = _f2_sub(S2, Y1)
        if H == (0, 0):
            if R == (0, 0):
                return jdouble(X1, Y1, Z1)
            return None  # infinity
        HH = _f2_sqr(H)
        HHH = _f2_mul(H, HH)
        V = _f2_mul(X1, HH)
        X3 = _f2_sub(_f2_sub(_f2_sqr(R), HHH), _f2_mul(V, (2, 0)))
        Y3 = _f2_sub(_f2_mul(R, _f2_sub(V, X3)), _f2_mul(Y1, HHH))
        Z3 = _f2_mul(Z1, H)
        return X3, Y3, Z3

    acc = None  # infinity
    for bit in bin(N)[2:]:
        if acc is not None:
            acc = jdouble(*acc)
            if acc[2] == (0, 0):  # doubling an order-2 point
                acc = None
        if bit == "1":
            if acc is None:
                acc = (pt[0], pt[1], (1, 0))
            else:
                acc = jadd(*acc, pt[0], pt[1])  # None when sum is infinity
    return acc is None or acc[2] == (0, 0)


# ---------------------------------------------------------------- pairing


def _twist(pt):
    """G2 (Fp2 affine) -> E(Fp12) affine: (x*w^2, y*w^3) after embedding.

    For the M-type untwist used with our xi = 9+u and w^6 = u+9... the
    correct map for alt_bn128's D-twist is (x/w^2, y/w^3); since
    w^6 = u + 9 here, multiplying by w^2/w^3 lands the SAME subgroup with
    coordinates in Fp12 — validated by the trace equation in tests
    (bilinearity + non-degeneracy), matching py_ecc's construction."""
    x = _f12_mul(_f2_to_f12(pt[0]), _W2)
    y = _f12_mul(_f2_to_f12(pt[1]), _W3)
    return (x, y)


def _line(ops, p1, p2, t):
    """Evaluate the line through p1,p2 (affine, Fp12) at point t."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
        return ops.sub(ops.sub(yt, y1), ops.mul(lam, ops.sub(xt, x1)))
    if y1 == y2:
        lam = ops.mul(ops.scale(ops.sqr(x1), 3), ops.inv(ops.scale(y1, 2)))
        return ops.sub(ops.sub(yt, y1), ops.mul(lam, ops.sub(xt, x1)))
    return ops.sub(xt, x1)


def _pt_frob(pt, k: int = 1):
    """Apply the p^k-power Frobenius to an E(Fp12) affine point:
    coordinate-wise a -> a^(p^k) done coefficient-wise in the w basis."""
    return (_f12_frob(pt[0], k), _f12_frob(pt[1], k))


def _f12_frob(a, k: int = 1):
    """a^(p^k) for a in Fp12: Fp coefficients are Frobenius-fixed, and
    (c * w^i)^(p^k) = c * w^(i*p^k) reduced — use precomputed w^(p^k)
    as an Fp12 element and index powers."""
    wpk = _WFROB[k % 12]
    out = _f12(a[0])
    cur = _F12_ONE[:]
    for i in range(1, _DEG):
        cur = _f12_mul(cur, wpk)
        if a[i]:
            out = _f12_add(out, _f12_scale(cur, a[i]))
    return out


def _compute_wfrob():
    """_WFROB[k] = w^(p^k) as an Fp12 element.  Only k=1 costs a full
    254-bit exponentiation; each further power is one coefficient-wise
    Frobenius application (w^(p^k) = (w^(p^(k-1)))^p), keeping module
    import to a single _f12_pow."""
    tabs = [None] * 12
    w = _f12()
    w[1] = 1
    tabs[0] = w
    tabs[1] = _f12_pow(w, P)

    def frob1(a):  # a^p using tabs[1] (local: _f12_frob needs _WFROB)
        out = _f12(a[0])
        cur = _F12_ONE[:]
        for i in range(1, _DEG):
            cur = _f12_mul(cur, tabs[1])
            if a[i]:
                out = _f12_add(out, _f12_scale(cur, a[i]))
        return out

    for k in range(2, 12):
        tabs[k] = frob1(tabs[k - 1])
    return tabs


_WFROB = _compute_wfrob()


def _miller(q, p, loop: int = ATE_LOOP):
    """Miller loop for the optimal ate pairing: f_{6t+2,Q}(P) with the two
    frobenius correction lines."""
    ops = _OPS12
    t = q
    f = _F12_ONE[:]
    for bit in bin(loop)[3:]:
        f = _f12_mul(_f12_sqr(f), _line(ops, t, t, p))
        t = _pt_add(ops, t, t)
        if bit == "1":
            f = _f12_mul(f, _line(ops, t, q, p))
            t = _pt_add(ops, t, q)
    q1 = _pt_frob(q, 1)
    nq2 = _pt_frob(q, 2)
    nq2 = (nq2[0], _f12_neg(nq2[1]))
    f = _f12_mul(f, _line(ops, t, q1, p))
    t = _pt_add(ops, t, q1)
    f = _f12_mul(f, _line(ops, t, nq2, p))
    return f


_HARD_EXP = (P**4 - P**2 + 1) // N


def _final_exp(f):
    """f^((p^12-1)/r): easy part via conjugate/inverse + frobenius, then a
    plain pow of the hard exponent (p^4-p^2+1)/r."""
    # f^(p^6 - 1): p^6 conjugation is w^i -> (-1)^i w^i since w^(p^6) = -w
    conj = [c if i % 2 == 0 else (-c) % P for i, c in enumerate(f)]
    f1 = _f12_mul(conj, _f12_inv(f))
    # f1^(p^2 + 1)
    f2 = _f12_mul(_f12_frob(f1, 2), f1)
    return _f12_pow(f2, _HARD_EXP)


def pairing(g1_pt, g2_pt):
    """e(P, Q) as an Fp12 element; identity inputs give 1."""
    if g1_pt is None or g2_pt is None:
        return _F12_ONE[:]
    p12 = (_f12(g1_pt[0]), _f12(g1_pt[1]))
    q12 = _twist(g2_pt)
    return _final_exp(_miller(q12, p12))


def pairing_check(pairs: bytes) -> bool:
    """The alt_bn128_pairing syscall: input is n * 192 bytes of
    (G1 ‖ G2) pairs; returns prod e(P_i, Q_i) == 1.  Validates curve
    membership and the G2 subgroup (r-torsion), like the ark-backed
    upstream syscall; ref surface fd_bn254_pairing (fd_bn254.cxx:183-201,
    fixed 2 pairs — this generalizes to n)."""
    if len(pairs) % 192:
        raise Bn254Error("bn254: pairing input must be n*192 bytes")
    miller_acc = _F12_ONE[:]
    nontrivial = False
    for off in range(0, len(pairs), 192):
        g1 = decode_g1(pairs[off : off + 64])
        g2 = decode_g2(pairs[off + 64 : off + 192])
        if g2 is not None and not g2_subgroup_check(g2):
            raise Bn254Error("bn254: G2 point not in r-torsion subgroup")
        if g1 is None or g2 is None:
            continue
        p12 = (_f12(g1[0]), _f12(g1[1]))
        q12 = _twist(g2)
        miller_acc = _f12_mul(miller_acc, _miller(q12, p12))
        nontrivial = True
    if not nontrivial:
        return True
    return _final_exp(miller_acc) == _F12_ONE


# generators (standard alt_bn128 parameters)
G1_GEN = (1, 2)
G2_GEN = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)


def g2_add(p1, p2):
    """Affine G2 addition over Fp2 (host-side helper for tests/tools)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if _f2_add(y1, y2) == (0, 0):
            return None
        lam = _f2_mul(
            _f2_mul((3, 0), _f2_sqr(x1)), _f2_inv(_f2_mul((2, 0), y1)))
    else:
        lam = _f2_mul(_f2_sub(y2, y1), _f2_inv(_f2_sub(x2, x1)))
    x3 = _f2_sub(_f2_sub(_f2_sqr(lam), x1), x2)
    y3 = _f2_sub(_f2_mul(lam, _f2_sub(x1, x3)), y1)
    return x3, y3


def g2_scalar_mul(k: int, pt):
    acc = None
    while k:
        if k & 1:
            acc = g2_add(acc, pt)
        pt = g2_add(pt, pt)
        k >>= 1
    return acc
