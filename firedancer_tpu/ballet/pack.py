"""Block-packing scheduler: fee-prioritized txn selection with account-
conflict-free microblock emission.

Reference role: src/ballet/pack/ (fd_pack.c, fd_pack_cost.h,
fd_pack_bitset.h) — between dedup and the bank tiles, pack holds verified
transactions in a fee-priority order and emits "microblocks" to bank
lanes such that no two concurrently-executing microblocks touch the same
account in a conflicting way, while staying inside the consensus-critical
block limits (fd_pack.h:17-52).

Host-side by design: scheduling is branchy, latency-critical, small-N
work — exactly what should NOT go to the device (the device is busy with
sigverify batches).  Round 15 reproduces the reference's fd_pack_bitset
trick: every account hashes to a 64-bit key (splitmix64 over an xor-fold
of the address) that sets TWO bits of a 256-bit bloom bitset, so the
conflict check `(writable & rw_busy) | (readonly & w_busy)` is a few word
ANDs instead of Python set unions.  A bitset false positive can only
DEFER a txn (it reschedules next call), never falsely admit a conflicting
pair — the conservative direction, consensus-safe.  Busy bitsets are
maintained incrementally across schedule()/done() instead of rebuilt from
`set().union(*inflight)` per call.

The hot loop has two interchangeable bodies: a C implementation
(native/packsched.cpp — fixed-capacity pool + binary heap + open-addressed
per-account write-cost table, ctypes-bound like the PR-11 host path) and a
bit-identical Python fallback used when the .so is absent or
FDTPU_PACK_NATIVE=0.  Both order by the same saturated-u64 priority and
apply the same checks in the same order, so the emitted microblock stream
is identical byte for byte.
"""

import ctypes
import os
import struct
from dataclasses import dataclass
import heapq
from typing import Optional

from . import txn as txn_lib
from .base58 import decode as b58decode

# ---- consensus-critical limits (fd_pack.h:19-23) --------------------------
MAX_COST_PER_BLOCK = 48_000_000
MAX_VOTE_COST_PER_BLOCK = 36_000_000
MAX_WRITE_COST_PER_ACCT = 12_000_000
FEE_PER_SIGNATURE = 5_000  # lamports
MAX_DATA_PER_BLOCK = ((32 * 1024 - 17) // 31) * 25_871 + 48

MAX_BANK_TILES = 62  # FD_PACK_MAX_BANK_TILES

# ---- cost model constants (fd_pack_cost.h:74-76) --------------------------
COST_PER_SIGNATURE = 720
COST_PER_WRITABLE_ACCT = 300
INV_COST_PER_INSTR_DATA_BYTE = 4

# built-in program execution costs per instruction (fd_pack_cost.h:55-66,
# mirroring solana block_cost_limits.rs)
_BUILTIN_COSTS = {
    "Stake11111111111111111111111111111111111111": 750,
    "Config1111111111111111111111111111111111111": 450,
    "Vote111111111111111111111111111111111111111": 2_100,
    "11111111111111111111111111111111": 150,
    "ComputeBudget111111111111111111111111111111": 150,
    "AddressLookupTab1e1111111111111111111111111": 750,
    "BPFLoaderUpgradeab1e11111111111111111111111": 2_370,
    "BPFLoader1111111111111111111111111111111111": 1_140,
    "BPFLoader2111111111111111111111111111111111": 570,
    "LoaderV411111111111111111111111111111111111": 2_000,
    "KeccakSecp256k11111111111111111111111111111": 720,
    "Ed25519SigVerify111111111111111111111111111": 720,
}
BUILTIN_COSTS = {b58decode(k, 32): v for k, v in _BUILTIN_COSTS.items()}

VOTE_PROG_ID = b58decode("Vote111111111111111111111111111111111111111", 32)
COMPUTE_BUDGET_PROG_ID = b58decode(
    "ComputeBudget111111111111111111111111111111", 32
)

# non-builtin (BPF) instruction default CU allotment, overridable by a
# SetComputeUnitLimit compute-budget instruction
DEFAULT_INSTR_COMPUTE_UNITS = 200_000
MAX_COMPUTE_UNIT_LIMIT = 1_400_000

_M64 = (1 << 64) - 1


# ---- account keys + bloom bitsets (fd_pack_bitset.h analogue) -------------
def acct_key(addr: bytes) -> int:
    """64-bit account key: fold the four u64 limbs of the 32-byte address
    with distinct odd multipliers (a plain xor-fold cancels on repeated
    limb patterns), then the splitmix64 finalizer.  Implemented
    identically in native/packsched.cpp (fd_pack_acct_key) — the shard
    steering, budget table, and bitset bits all derive from this one
    function, so native and Python schedules stay bit-identical."""
    x = ((int.from_bytes(addr[0:8], "little") * 0x9E3779B97F4A7C15)
         ^ (int.from_bytes(addr[8:16], "little") * 0xC2B2AE3D27D4EB4F)
         ^ (int.from_bytes(addr[16:24], "little") * 0x165667B19E3779F9)
         ^ (int.from_bytes(addr[24:32], "little") * 0x27D4EB2F165667C5)) \
        & _M64
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def acct_mask(key: int) -> int:
    """Two bits of a 256-bit bloom bitset per account key."""
    return (1 << (key & 255)) | (1 << ((key >> 8) & 255))


# ---- native fast path (packsched.cpp) -------------------------------------
_NATIVE_ENV = "FDTPU_PACK_NATIVE"
_native_cache = [False, None]  # [probed, lib-or-None]


def _native_lib():
    if not _native_cache[0]:
        _native_cache[0] = True
        try:
            from .. import native as native_mod
            _native_cache[1] = native_mod.lib()
        except Exception:
            _native_cache[1] = None
    return _native_cache[1]


def _resolve_native(native):
    """native arg: None = auto (env overrides, then try-build), False =
    force the Python fallback, True = require the C path."""
    if native is False:
        return None
    env = os.environ.get(_NATIVE_ENV)
    if native is None and env is not None and env == "0":
        return None
    L = _native_lib()
    if native is True and L is None:
        raise RuntimeError("native pack scheduler unavailable "
                           "(packsched.cpp failed to build)")
    return L


# native insert arg blob: acct_addr_off, n_acct, sig_cnt, ro_signed,
# ro_unsigned, is_vote, payload_len, cost, prio, seq (packsched.cpp
# fd_pack_insert reads the same layout)
_INS_ARGS = struct.Struct("<IIIIIIIQQQ")


@dataclass(slots=True)
class TxnCost:
    total: int
    is_simple_vote: bool
    cu_price_micro_lamports: int  # from SetComputeUnitPrice
    requested_cu: Optional[int]


def _parse_compute_budget(parsed: txn_lib.Txn, payload: bytes):
    """Extract (cu_limit or None, cu_price) from compute-budget instructions
    (fd_compute_budget_program.h discriminants: 1 heap, 2 SetComputeUnitLimit
    u32, 3 SetComputeUnitPrice u64)."""
    accts = parsed.account_addrs(payload)
    cu_limit = None
    cu_price = 0
    for ins in parsed.instrs:
        if ins.program_id >= len(accts):
            continue
        if accts[ins.program_id] != COMPUTE_BUDGET_PROG_ID:
            continue
        data = payload[ins.data_off : ins.data_off + ins.data_sz]
        if len(data) >= 5 and data[0] == 2:
            cu_limit = min(
                int.from_bytes(data[1:5], "little"), MAX_COMPUTE_UNIT_LIMIT
            )
        elif len(data) >= 9 and data[0] == 3:
            cu_price = int.from_bytes(data[1:9], "little")
    return cu_limit, cu_price


def compute_cost(parsed: txn_lib.Txn, payload: bytes, accts=None) -> TxnCost:
    """The consensus cost model: signatures + write locks + instr data +
    per-instruction execution costs (fd_pack_cost.h compute_cost).

    One pass: program ids are fetched as direct payload slices (only the
    1-2 instruction programs, never the full account list) and the
    compute-budget scan folds into the same instruction walk instead of
    re-deriving the accounts per helper.  Callers that already hold the
    account list may pass it via `accts`."""
    n_accts = parsed.acct_addr_cnt
    ao = parsed.acct_addr_off
    sig_cnt = parsed.signature_cnt
    cost = sig_cnt * COST_PER_SIGNATURE
    # writability is pure index arithmetic (fd_txn.h account ordering):
    # [0, sig_cnt - ro_signed) writable-signed, [sig_cnt, cnt - ro_unsigned)
    # writable-unsigned
    writable_cnt = (
        sig_cnt - parsed.readonly_signed_cnt
        + max(parsed.acct_addr_cnt - sig_cnt - parsed.readonly_unsigned_cnt, 0)
        + parsed.addr_table_adtl_writable_cnt
    )
    cost += writable_cnt * COST_PER_WRITABLE_ACCT

    data_bytes = 0
    cu_limit = None
    cu_price = 0
    exec_cost = 0
    bpf_instr_cnt = 0
    for ins in parsed.instrs:
        data_bytes += ins.data_sz
        pid = ins.program_id
        if pid < n_accts:
            if accts is not None:
                prog = accts[pid]
            else:
                prog = payload[ao + pid * 32 : ao + pid * 32 + 32]
        else:
            prog = None
        builtin = BUILTIN_COSTS.get(prog)
        if builtin is None:
            bpf_instr_cnt += 1
            continue
        exec_cost += builtin
        if prog == COMPUTE_BUDGET_PROG_ID:
            data = payload[ins.data_off : ins.data_off + ins.data_sz]
            if len(data) >= 5 and data[0] == 2:
                cu_limit = min(
                    int.from_bytes(data[1:5], "little"),
                    MAX_COMPUTE_UNIT_LIMIT)
            elif len(data) >= 9 and data[0] == 3:
                cu_price = int.from_bytes(data[1:9], "little")
    cost += data_bytes // INV_COST_PER_INSTR_DATA_BYTE
    if bpf_instr_cnt:
        exec_cost += (
            cu_limit
            if cu_limit is not None
            else min(
                bpf_instr_cnt * DEFAULT_INSTR_COMPUTE_UNITS, MAX_COMPUTE_UNIT_LIMIT
            )
        )

    is_simple_vote = False
    if sig_cnt == 1 and len(parsed.instrs) == 1:
        pid = parsed.instrs[0].program_id
        if pid < n_accts:
            pb = (accts[pid] if accts is not None
                  else payload[ao + pid * 32 : ao + pid * 32 + 32])
            is_simple_vote = pb == VOTE_PROG_ID
    return TxnCost(cost + exec_cost, is_simple_vote, cu_price, cu_limit)


def reward(parsed: txn_lib.Txn, cost: TxnCost) -> int:
    """Validator reward in lamports: base fee share + priority fee."""
    base = parsed.signature_cnt * FEE_PER_SIGNATURE
    cu = cost.requested_cu if cost.requested_cu is not None else cost.total
    priority = (cost.cu_price_micro_lamports * cu) // 1_000_000
    return base + priority


@dataclass(slots=True)
class _Held:
    payload: bytes
    parsed: txn_lib.Txn
    cost: TxnCost
    rew: int
    seq: int        # FIFO tiebreak
    wkeys: tuple    # unique writable account keys (fallback path; () native)
    wmask: int      # 256-bit writable bloom bitset (fallback path)
    rmask: int      # 256-bit readonly bloom bitset (fallback path)


def writable_key_costs(h: _Held) -> dict:
    """Per-account write cost contributions of one held txn: unique
    writable account key -> cost.total.  Derived from the parsed payload
    (not the scheduler state) so it works on both the native and the
    fallback path — the sharded merge wire rides on this."""
    parsed = h.parsed
    o = parsed.acct_addr_off
    payload = h.payload
    out = {}
    for i in range(parsed.acct_addr_cnt):
        if parsed.is_writable(i):
            k = acct_key(payload[o + i * 32 : o + (i + 1) * 32])
            out[k] = h.cost.total
    return out


@dataclass
class Microblock:
    bank: int
    txns: list  # list[_Held]

    @property
    def payloads(self) -> list[bytes]:
        return [h.payload for h in self.txns]


class MergeBudget:
    """Global block budgets enforced at the shard-merge point.

    Each sharded leader_pack tile runs its own Pack with the FULL block
    budget (shard-local admission is only a pre-filter); the merge tile
    owns the consensus-critical global accounting and admits per-shard
    microblocks against it atomically (check everything, then commit).
    Keyed by the same u64 acct_key the scheduler uses, carried on the
    merge wire so the merge never re-parses txns.

    Convergence invariant the drain path relies on: any microblock a
    shard emits fits a FRESH budget (per-txn oversize is dropped at
    insert, and no two txns in one microblock write the same account),
    so resetting via end_block always unblocks a stalled head."""

    def __init__(self):
        self.block_cost = 0
        self.block_vote_cost = 0
        self.block_data = 0
        self.acct_write_cost: dict = {}

    def try_admit(self, cost: int, vote_cost: int, data: int,
                  items) -> bool:
        """items: iterable of (acct_key u64, write cost).  All-or-nothing:
        returns False without mutating anything if any budget would
        overflow."""
        if self.block_cost + cost > MAX_COST_PER_BLOCK:
            return False
        if vote_cost and (self.block_vote_cost + vote_cost
                          > MAX_VOTE_COST_PER_BLOCK):
            return False
        if self.block_data + data > MAX_DATA_PER_BLOCK:
            return False
        awc = self.acct_write_cost
        for k, c in items:
            if awc.get(k, 0) + c > MAX_WRITE_COST_PER_ACCT:
                return False
        self.block_cost += cost
        self.block_vote_cost += vote_cost
        self.block_data += data
        for k, c in items:
            awc[k] = awc.get(k, 0) + c
        return True

    def end_block(self):
        self.block_cost = 0
        self.block_vote_cost = 0
        self.block_data = 0
        self.acct_write_cost.clear()


class Pack:
    """The pack scheduler state machine.

    insert() verified txns; schedule() emits a conflict-free microblock for
    a free bank lane; done() releases a lane's account locks;
    end_block() resets block-level accounting for the next slot.

    native: None = auto (FDTPU_PACK_NATIVE env overrides, then try the C
    path, silently falling back), False = Python fallback, True = require
    the C path.  Both paths emit bit-identical microblock streams.
    """

    def __init__(self, bank_tile_cnt: int, max_txn_per_microblock: int = 31,
                 max_pending: int = 0, native=None):
        if not (1 <= bank_tile_cnt <= MAX_BANK_TILES):
            raise ValueError("bad bank tile count")
        self.bank_cnt = bank_tile_cnt
        self.max_txn_per_microblock = max_txn_per_microblock
        # heap admission cap (0 = unbounded).  Simple votes bypass the cap
        # — the reference reserves a vote lane so consensus traffic is
        # never crowded out by a fee-paying flood (fd_pack extra txn
        # handling); a full heap sheds the lowest-value REGULAR txns.
        self.max_pending = int(max_pending)
        # hard pool bound (native slot arrays are fixed-capacity; the
        # fallback honors the same bound so the paths shed identically —
        # votes bypass max_pending but not the pool)
        self.pool_cap = (max(1024, 2 * self.max_pending)
                         if self.max_pending else 65536)
        self._seq = 0
        self._pending = 0
        self._busy = [False] * bank_tile_cnt
        # block accounting (mirrored on the native path per committed
        # microblock except acct_write_cost, which lives in the C table)
        self.block_cost = 0
        self.block_vote_cost = 0
        self.block_data = 0
        self.acct_write_cost: dict = {}
        self.metrics = {
            "inserted": 0,
            "vote_inserted": 0,
            "scheduled": 0,
            "microblocks": 0,
            "dropped_oversize": 0,
            "dropped_heap_full": 0,
            "delayed_conflict": 0,
        }

        self._L = _resolve_native(native)
        self._c = None
        if self._L is not None:
            self._c = self._L.fd_pack_new(bank_tile_cnt, self.pool_cap)
            if not self._c:
                raise MemoryError("fd_pack_new failed")
            self._slots: dict = {}  # native slot idx -> _Held
            self._out = (ctypes.c_longlong
                         * max(1, max_txn_per_microblock))()
        else:
            self._heap: list = []  # (-priority, seq, _Held)
            # incremental busy bitsets: per-bank write/read masks plus the
            # cached unions schedule() starts from (satellite: no more
            # set().union(*inflight) per call)
            self._bank_w = [0] * bank_tile_cnt
            self._bank_r = [0] * bank_tile_cnt
            self._gw = 0    # union of in-flight writable masks
            self._grw = 0   # union of in-flight writable+readonly masks

    @property
    def native(self) -> bool:
        return self._c is not None

    def __del__(self):
        c, L = getattr(self, "_c", None), getattr(self, "_L", None)
        if c and L is not None:
            try:
                L.fd_pack_delete(c)
            except Exception:
                pass
            self._c = None

    # ------------------------------------------------------------- ingest
    def insert(self, payload: bytes, parsed: txn_lib.Txn) -> bool:
        cost = compute_cost(parsed, payload)
        if cost.total > MAX_COST_PER_BLOCK:
            self.metrics["dropped_oversize"] += 1
            return False
        if (
            self.max_pending
            and self._pending >= self.max_pending
            and not cost.is_simple_vote
        ):
            self.metrics["dropped_heap_full"] += 1
            return False
        if self._pending >= self.pool_cap:
            self.metrics["dropped_heap_full"] += 1
            return False
        rew = reward(parsed, cost)
        # priority = reward per cost unit, scaled to keep integer math;
        # saturated to u64 so native and fallback order identically
        prio = (rew << 20) // max(cost.total, 1)
        if prio > _M64:
            prio = _M64
        if self._c is not None:
            idx = self._L.fd_pack_insert(
                self._c, payload,
                _INS_ARGS.pack(
                    parsed.acct_addr_off, parsed.acct_addr_cnt,
                    parsed.signature_cnt, parsed.readonly_signed_cnt,
                    parsed.readonly_unsigned_cnt, cost.is_simple_vote,
                    len(payload), cost.total, prio, self._seq))
            if idx < 0:
                self.metrics["dropped_heap_full"] += 1
                return False
            self._slots[idx] = _Held(payload, parsed, cost, rew, self._seq,
                                     (), 0, 0)
        else:
            wmask = rmask = 0
            wseen: dict = {}
            o = parsed.acct_addr_off
            for i in range(parsed.acct_addr_cnt):
                k = acct_key(payload[o + i * 32 : o + (i + 1) * 32])
                m = (1 << (k & 255)) | (1 << ((k >> 8) & 255))
                if parsed.is_writable(i):
                    wmask |= m
                    wseen[k] = None
                else:
                    rmask |= m
            h = _Held(payload, parsed, cost, rew, self._seq,
                      tuple(wseen), wmask, rmask)
            heapq.heappush(self._heap, (-prio, self._seq, h))
        self._seq += 1
        self._pending += 1
        self.metrics["inserted"] += 1
        if cost.is_simple_vote:
            self.metrics["vote_inserted"] += 1
        return True

    @property
    def pending(self) -> int:
        return self._pending

    def clear_pending(self) -> int:
        """Drop every held txn (drain-protocol shed); returns the count."""
        n = self._pending
        if self._c is not None:
            self._L.fd_pack_clear_pending(self._c)
            self._slots.clear()
        else:
            self._heap.clear()
        self._pending = 0
        return n

    # ---------------------------------------------------------- schedule
    def schedule(self, bank: int) -> Optional[Microblock]:
        """Emit a microblock for idle bank lane `bank` (None if nothing
        schedulable).  Locks the lane until done(bank)."""
        if self._busy[bank]:
            raise ValueError(f"bank {bank} still executing")
        if self._c is not None:
            chosen = self._schedule_native(bank)
        else:
            chosen = self._schedule_py(bank)
        if not chosen:
            return None
        self._busy[bank] = True
        self._pending -= len(chosen)
        for h in chosen:
            self.block_cost += h.cost.total
            if h.cost.is_simple_vote:
                self.block_vote_cost += h.cost.total
            self.block_data += len(h.payload)
        self.metrics["scheduled"] += len(chosen)
        self.metrics["microblocks"] += 1
        return Microblock(bank, chosen)

    def _schedule_native(self, bank: int):
        delayed = ctypes.c_longlong(0)
        n = self._L.fd_pack_schedule(
            self._c, bank, self.max_txn_per_microblock, self._out,
            ctypes.byref(delayed))
        self.metrics["delayed_conflict"] += delayed.value
        return [self._slots.pop(self._out[i]) for i in range(n)]

    def _schedule_py(self, bank: int):
        # start from the incrementally-maintained busy unions: my writes
        # vs their reads+writes, my reads vs their writes
        w_busy = self._gw
        rw_busy = self._grw
        chosen: list[_Held] = []
        skipped = []
        # per-class accumulators for the microblock being built: the block
        # caps must count txns already CHOSEN this call, not just committed
        # blocks, or one wide microblock sails past every limit
        mb_cost = 0
        mb_vote_cost = 0
        mb_data = 0
        heap = self._heap
        awc = self.acct_write_cost
        while heap and len(chosen) < self.max_txn_per_microblock:
            item = heapq.heappop(heap)
            h = item[2]
            c = h.cost.total
            if self.block_cost + mb_cost + c > MAX_COST_PER_BLOCK:
                skipped.append(item)
                break
            if h.cost.is_simple_vote and (
                self.block_vote_cost + mb_vote_cost + c
                > MAX_VOTE_COST_PER_BLOCK
            ):
                skipped.append(item)
                continue
            if self.block_data + mb_data + len(h.payload) \
                    > MAX_DATA_PER_BLOCK:
                skipped.append(item)
                continue
            if (h.wmask & rw_busy) or (h.rmask & w_busy):
                self.metrics["delayed_conflict"] += 1
                skipped.append(item)
                continue
            if any(awc.get(k, 0) + c > MAX_WRITE_COST_PER_ACCT
                   for k in h.wkeys):
                skipped.append(item)
                continue
            # accept.  Consensus requires txns within one entry/microblock
            # to be mutually non-conflicting (they may replay in parallel),
            # so chosen txns' accounts join the busy bitsets immediately.
            chosen.append(h)
            mb_cost += c
            if h.cost.is_simple_vote:
                mb_vote_cost += c
            mb_data += len(h.payload)
            w_busy |= h.wmask
            rw_busy |= h.wmask | h.rmask
        for item in skipped:
            heapq.heappush(heap, item)
        if not chosen:
            return chosen
        bw = self._bank_w[bank]
        br = self._bank_r[bank]
        for h in chosen:
            bw |= h.wmask
            br |= h.rmask
            for k in h.wkeys:
                awc[k] = awc.get(k, 0) + h.cost.total
        self._bank_w[bank] = bw
        self._bank_r[bank] = br
        self._gw |= bw
        self._grw |= bw | br
        return chosen

    def done(self, bank: int):
        """Bank lane finished executing its microblock; release locks."""
        if self._c is not None:
            self._L.fd_pack_done(self._c, bank)
        else:
            self._bank_w[bank] = 0
            self._bank_r[bank] = 0
            # shared bits can't be subtracted out of a bloom union: fold
            # the surviving banks' masks (bank_cnt <= 62 int ORs, still
            # O(banks) not O(inflight accounts))
            gw = 0
            grw = 0
            for w, r in zip(self._bank_w, self._bank_r):
                gw |= w
                grw |= w | r
            self._gw = gw
            self._grw = grw
        self._busy[bank] = False

    def end_block(self):
        """Slot boundary: reset block-level accounting (leftover pending
        txns carry to the next block, as the reference's pack does)."""
        if any(self._busy):
            raise ValueError("end_block with banks still executing")
        self.block_cost = 0
        self.block_vote_cost = 0
        self.block_data = 0
        self.acct_write_cost.clear()
        if self._c is not None:
            self._L.fd_pack_end_block(self._c)
