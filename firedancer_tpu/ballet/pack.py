"""Block-packing scheduler: fee-prioritized txn selection with account-
conflict-free microblock emission.

Reference role: src/ballet/pack/ (fd_pack.c, fd_pack_cost.h,
fd_pack_bitset.h) — between dedup and the bank tiles, pack holds verified
transactions in a fee-priority order and emits "microblocks" to bank
lanes such that no two concurrently-executing microblocks touch the same
account in a conflicting way, while staying inside the consensus-critical
block limits (fd_pack.h:17-52).

Host-side by design: scheduling is branchy, latency-critical, small-N
work — exactly what should NOT go to the device (the device is busy with
sigverify batches).  The reference's treap + account bitsets become a
lazy-deletion heap + hash sets here; same contract, idiomatic host code.
"""

from dataclasses import dataclass, field
import heapq
from typing import Optional

from . import txn as txn_lib
from .base58 import decode as b58decode

# ---- consensus-critical limits (fd_pack.h:19-23) --------------------------
MAX_COST_PER_BLOCK = 48_000_000
MAX_VOTE_COST_PER_BLOCK = 36_000_000
MAX_WRITE_COST_PER_ACCT = 12_000_000
FEE_PER_SIGNATURE = 5_000  # lamports
MAX_DATA_PER_BLOCK = ((32 * 1024 - 17) // 31) * 25_871 + 48

MAX_BANK_TILES = 62  # FD_PACK_MAX_BANK_TILES

# ---- cost model constants (fd_pack_cost.h:74-76) --------------------------
COST_PER_SIGNATURE = 720
COST_PER_WRITABLE_ACCT = 300
INV_COST_PER_INSTR_DATA_BYTE = 4

# built-in program execution costs per instruction (fd_pack_cost.h:55-66,
# mirroring solana block_cost_limits.rs)
_BUILTIN_COSTS = {
    "Stake11111111111111111111111111111111111111": 750,
    "Config1111111111111111111111111111111111111": 450,
    "Vote111111111111111111111111111111111111111": 2_100,
    "11111111111111111111111111111111": 150,
    "ComputeBudget111111111111111111111111111111": 150,
    "AddressLookupTab1e1111111111111111111111111": 750,
    "BPFLoaderUpgradeab1e11111111111111111111111": 2_370,
    "BPFLoader1111111111111111111111111111111111": 1_140,
    "BPFLoader2111111111111111111111111111111111": 570,
    "LoaderV411111111111111111111111111111111111": 2_000,
    "KeccakSecp256k11111111111111111111111111111": 720,
    "Ed25519SigVerify111111111111111111111111111": 720,
}
BUILTIN_COSTS = {b58decode(k, 32): v for k, v in _BUILTIN_COSTS.items()}

VOTE_PROG_ID = b58decode("Vote111111111111111111111111111111111111111", 32)
COMPUTE_BUDGET_PROG_ID = b58decode(
    "ComputeBudget111111111111111111111111111111", 32
)

# non-builtin (BPF) instruction default CU allotment, overridable by a
# SetComputeUnitLimit compute-budget instruction
DEFAULT_INSTR_COMPUTE_UNITS = 200_000
MAX_COMPUTE_UNIT_LIMIT = 1_400_000


@dataclass
class TxnCost:
    total: int
    is_simple_vote: bool
    cu_price_micro_lamports: int  # from SetComputeUnitPrice
    requested_cu: Optional[int]


def _parse_compute_budget(parsed: txn_lib.Txn, payload: bytes):
    """Extract (cu_limit or None, cu_price) from compute-budget instructions
    (fd_compute_budget_program.h discriminants: 1 heap, 2 SetComputeUnitLimit
    u32, 3 SetComputeUnitPrice u64)."""
    accts = parsed.account_addrs(payload)
    cu_limit = None
    cu_price = 0
    for ins in parsed.instrs:
        if ins.program_id >= len(accts):
            continue
        if accts[ins.program_id] != COMPUTE_BUDGET_PROG_ID:
            continue
        data = payload[ins.data_off : ins.data_off + ins.data_sz]
        if len(data) >= 5 and data[0] == 2:
            cu_limit = min(
                int.from_bytes(data[1:5], "little"), MAX_COMPUTE_UNIT_LIMIT
            )
        elif len(data) >= 9 and data[0] == 3:
            cu_price = int.from_bytes(data[1:9], "little")
    return cu_limit, cu_price


def compute_cost(parsed: txn_lib.Txn, payload: bytes) -> TxnCost:
    """The consensus cost model: signatures + write locks + instr data +
    per-instruction execution costs (fd_pack_cost.h compute_cost)."""
    accts = parsed.account_addrs(payload)
    cost = parsed.signature_cnt * COST_PER_SIGNATURE
    writable_cnt = sum(
        1 for i in range(parsed.acct_addr_cnt) if parsed.is_writable(i)
    ) + parsed.addr_table_adtl_writable_cnt
    cost += writable_cnt * COST_PER_WRITABLE_ACCT

    data_bytes = sum(ins.data_sz for ins in parsed.instrs)
    cost += data_bytes // INV_COST_PER_INSTR_DATA_BYTE

    cu_limit, cu_price = _parse_compute_budget(parsed, payload)
    exec_cost = 0
    bpf_instr_cnt = 0
    for ins in parsed.instrs:
        prog = accts[ins.program_id] if ins.program_id < len(accts) else None
        builtin = BUILTIN_COSTS.get(prog)
        if builtin is not None:
            exec_cost += builtin
        else:
            bpf_instr_cnt += 1
    if bpf_instr_cnt:
        exec_cost += (
            cu_limit
            if cu_limit is not None
            else min(
                bpf_instr_cnt * DEFAULT_INSTR_COMPUTE_UNITS, MAX_COMPUTE_UNIT_LIMIT
            )
        )

    is_simple_vote = (
        parsed.signature_cnt == 1
        and len(parsed.instrs) == 1
        and parsed.instrs[0].program_id < len(accts)
        and accts[parsed.instrs[0].program_id] == VOTE_PROG_ID
    )
    return TxnCost(cost + exec_cost, is_simple_vote, cu_price, cu_limit)


def reward(parsed: txn_lib.Txn, cost: TxnCost) -> int:
    """Validator reward in lamports: base fee share + priority fee."""
    base = parsed.signature_cnt * FEE_PER_SIGNATURE
    cu = cost.requested_cu if cost.requested_cu is not None else cost.total
    priority = (cost.cu_price_micro_lamports * cu) // 1_000_000
    return base + priority


@dataclass
class _Held:
    payload: bytes
    parsed: txn_lib.Txn
    cost: TxnCost
    rew: int
    writable: frozenset
    readonly: frozenset
    seq: int  # FIFO tiebreak


@dataclass
class Microblock:
    bank: int
    txns: list  # list[_Held]

    @property
    def payloads(self) -> list[bytes]:
        return [h.payload for h in self.txns]


class Pack:
    """The pack scheduler state machine.

    insert() verified txns; schedule() emits a conflict-free microblock for
    a free bank lane; done() releases a lane's account locks;
    end_block() resets block-level accounting for the next slot.
    """

    def __init__(self, bank_tile_cnt: int, max_txn_per_microblock: int = 31,
                 max_pending: int = 0):
        if not (1 <= bank_tile_cnt <= MAX_BANK_TILES):
            raise ValueError("bad bank tile count")
        self.bank_cnt = bank_tile_cnt
        self.max_txn_per_microblock = max_txn_per_microblock
        # heap admission cap (0 = unbounded).  Simple votes bypass the cap
        # — the reference reserves a vote lane so consensus traffic is
        # never crowded out by a fee-paying flood (fd_pack extra txn
        # handling); a full heap sheds the lowest-value REGULAR txns.
        self.max_pending = int(max_pending)
        self._heap: list = []  # (-priority, seq, _Held)
        self._seq = 0
        # in-flight account locks per bank lane
        self._inflight_w: list[set] = [set() for _ in range(bank_tile_cnt)]
        self._inflight_r: list[set] = [set() for _ in range(bank_tile_cnt)]
        self._busy = [False] * bank_tile_cnt
        # block accounting
        self.block_cost = 0
        self.block_vote_cost = 0
        self.block_data = 0
        self.acct_write_cost: dict = {}
        self.metrics = {
            "inserted": 0,
            "vote_inserted": 0,
            "scheduled": 0,
            "microblocks": 0,
            "dropped_oversize": 0,
            "dropped_heap_full": 0,
            "delayed_conflict": 0,
        }

    # ------------------------------------------------------------- ingest
    def insert(self, payload: bytes, parsed: txn_lib.Txn) -> bool:
        cost = compute_cost(parsed, payload)
        if cost.total > MAX_COST_PER_BLOCK:
            self.metrics["dropped_oversize"] += 1
            return False
        if (
            self.max_pending
            and len(self._heap) >= self.max_pending
            and not cost.is_simple_vote
        ):
            self.metrics["dropped_heap_full"] += 1
            return False
        writable = frozenset(
            a
            for i, a in enumerate(parsed.account_addrs(payload))
            if parsed.is_writable(i)
        )
        readonly = frozenset(
            a
            for i, a in enumerate(parsed.account_addrs(payload))
            if not parsed.is_writable(i)
        )
        rew = reward(parsed, cost)
        h = _Held(payload, parsed, cost, rew, writable, readonly, self._seq)
        # priority = reward per cost unit, scaled to keep integer math
        prio = (rew << 20) // max(cost.total, 1)
        heapq.heappush(self._heap, (-prio, self._seq, h))
        self._seq += 1
        self.metrics["inserted"] += 1
        if cost.is_simple_vote:
            self.metrics["vote_inserted"] += 1
        return True

    @property
    def pending(self) -> int:
        return len(self._heap)

    # ---------------------------------------------------------- schedule
    def _conflicts(self, h: _Held, w_busy: set, rw_busy: set) -> bool:
        # my writes vs their reads+writes; my reads vs their writes
        return bool(h.writable & rw_busy) or bool(h.readonly & w_busy)

    def schedule(self, bank: int) -> Optional[Microblock]:
        """Emit a microblock for idle bank lane `bank` (None if nothing
        schedulable).  Locks the lane until done(bank)."""
        if self._busy[bank]:
            raise ValueError(f"bank {bank} still executing")
        w_busy = set().union(*self._inflight_w) if self.bank_cnt else set()
        rw_busy = w_busy | set().union(*self._inflight_r)

        chosen: list[_Held] = []
        skipped = []
        # per-class accumulators for the microblock being built: the block
        # caps must count txns already CHOSEN this call, not just committed
        # blocks, or one wide microblock sails past every limit
        mb_cost = 0
        mb_vote_cost = 0
        mb_data = 0
        while self._heap and len(chosen) < self.max_txn_per_microblock:
            negp, seq, h = heapq.heappop(self._heap)
            c = h.cost.total
            if self.block_cost + mb_cost + c > MAX_COST_PER_BLOCK:
                skipped.append((negp, seq, h))
                break
            if h.cost.is_simple_vote and (
                self.block_vote_cost + mb_vote_cost + c
                > MAX_VOTE_COST_PER_BLOCK
            ):
                skipped.append((negp, seq, h))
                continue
            if self.block_data + mb_data + len(h.payload) \
                    > MAX_DATA_PER_BLOCK:
                skipped.append((negp, seq, h))
                continue
            if self._conflicts(h, w_busy, rw_busy):
                self.metrics["delayed_conflict"] += 1
                skipped.append((negp, seq, h))
                continue
            if any(
                self.acct_write_cost.get(a, 0) + c > MAX_WRITE_COST_PER_ACCT
                for a in h.writable
            ):
                skipped.append((negp, seq, h))
                continue
            # accept.  Consensus requires txns within one entry/microblock
            # to be mutually non-conflicting (they may replay in parallel),
            # so chosen txns' accounts join the busy sets immediately.
            chosen.append(h)
            mb_cost += c
            if h.cost.is_simple_vote:
                mb_vote_cost += c
            mb_data += len(h.payload)
            w_busy |= h.writable
            rw_busy |= h.writable | h.readonly
        for item in skipped:
            heapq.heappush(self._heap, item)
        if not chosen:
            return None

        self._busy[bank] = True
        for h in chosen:
            self._inflight_w[bank] |= h.writable
            self._inflight_r[bank] |= h.readonly
            self.block_cost += h.cost.total
            if h.cost.is_simple_vote:
                self.block_vote_cost += h.cost.total
            self.block_data += len(h.payload)
            for a in h.writable:
                self.acct_write_cost[a] = (
                    self.acct_write_cost.get(a, 0) + h.cost.total
                )
        self.metrics["scheduled"] += len(chosen)
        self.metrics["microblocks"] += 1
        return Microblock(bank, chosen)

    def done(self, bank: int):
        """Bank lane finished executing its microblock; release locks."""
        self._inflight_w[bank].clear()
        self._inflight_r[bank].clear()
        self._busy[bank] = False

    def end_block(self):
        """Slot boundary: reset block-level accounting (leftover pending
        txns carry to the next block, as the reference's pack does)."""
        if any(self._busy):
            raise ValueError("end_block with banks still executing")
        self.block_cost = 0
        self.block_vote_cost = 0
        self.block_data = 0
        self.acct_write_cost.clear()
