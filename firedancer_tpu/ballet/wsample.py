"""Weighted random sampling — leader schedules and turbine trees.

Reference role: src/ballet/wsample/ (fd_wsample.c) — stake-weighted
sampling driven by a ChaCha20Rng, used by the leader schedule
(src/flamenco/leaders/) and turbine shred destinations
(src/disco/shred/fd_shred_dest.c).  Supports sampling with and without
replacement ("remove" mode) and matches the draw discipline of Rust's
WeightedIndex bit-for-bit: one uniform draw in [0, total_weight) via the
Lemire multiply-high roll (ChaCha20Rng.roll_u64, MODE_MOD for leader
schedules / MODE_SHIFT for turbine — fd_chacha20rng.h:21-24), then a
search over cumulative weights.  Wire-exactness is fixture-tested against
the reference algorithm (tests/golden/wsample_ref.json).

The index is a Fenwick (binary indexed) tree so without-replacement
removal stays O(log n) — the same complexity story as the reference's
radix-9 left-sum tree (fd_wsample.c:14-96; ordering semantics identical,
only the search structure differs).
"""

from ..ballet.chacha20 import ChaCha20Rng


class WSample:
    def __init__(self, weights: list[int], mode: int = ChaCha20Rng.MODE_MOD):
        self.mode = mode
        if any(w < 0 for w in weights):
            raise ValueError("weights must be non-negative")
        self.n = len(weights)
        self._fen = [0] * (self.n + 1)
        self._w = [0] * self.n
        for i, w in enumerate(weights):
            if w:
                self._add(i, w)
        if self.total == 0:
            raise ValueError("total weight must be positive")

    # Fenwick primitives -------------------------------------------------
    def _add(self, i: int, delta: int):
        self._w[i] += delta
        i += 1
        while i <= self.n:
            self._fen[i] += delta
            i += i & (-i)

    @property
    def total(self) -> int:
        return self._fen_prefix(self.n)

    def _fen_prefix(self, i: int) -> int:
        s = 0
        while i > 0:
            s += self._fen[i]
            i -= i & (-i)
        return s

    def _find(self, x: int) -> int:
        """Smallest index i with prefix_sum(i+1) > x (x < total)."""
        pos = 0
        bit = 1 << (self.n.bit_length())
        while bit:
            nxt = pos + bit
            if nxt <= self.n and self._fen[nxt] <= x:
                x -= self._fen[nxt]
                pos = nxt
            bit >>= 1
        return pos  # 0-based index

    # sampling -----------------------------------------------------------
    def sample(self, rng: ChaCha20Rng) -> int:
        """One draw with replacement."""
        return self._find(rng.roll_u64(self.total, self.mode))

    def sample_and_remove(self, rng: ChaCha20Rng) -> int:
        """One draw without replacement (turbine tree construction)."""
        i = self._find(rng.roll_u64(self.total, self.mode))
        self._add(i, -self._w[i])
        return i

    def sample_many(self, rng: ChaCha20Rng, cnt: int) -> list[int]:
        return [self.sample(rng) for _ in range(cnt)]
