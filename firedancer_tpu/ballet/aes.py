"""AES-128/256 block cipher, AES-GCM AEAD, and AES-ECB header masks.

Reference role: src/ballet/aes/ — QUIC packet protection (AEAD over the
packet payload) and header protection (an AES-ECB mask over a ciphertext
sample), per RFC 9001.  The reference carries AES-NI and portable C
backends; this is host control/ingest-plane code (per-packet work bounded
by the network, never on the TPU hot path), so we implement it as
table-driven Python tuned for clarity: encryption-direction T-tables for
the block cipher and a byte-table GHASH.

Only the encrypt direction of the block cipher is implemented — GCM (CTR
mode) and header protection need nothing else, exactly the subset the
reference's QUIC stack uses (src/waltz/quic/crypto/fd_quic_crypto_suites.c).
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# S-box generation (no magic tables: derive from GF(2^8) inverse + affine map)

_SBOX = [0] * 256


def _build_sbox() -> None:
    # GF(2^8) exp/log via generator 3 (poly 0x11B)
    p = 1
    exp = [0] * 255
    log = [0] * 256
    for i in range(255):
        exp[i] = p
        log[p] = i
        p ^= (p << 1) ^ (0x11B if p & 0x80 else 0)
        p &= 0xFF
    for x in range(256):
        inv = 0 if x == 0 else exp[(255 - log[x]) % 255]
        b = inv
        s = 0x63
        for _ in range(4):
            b = ((b << 1) | (b >> 7)) & 0xFF
            s ^= b
        _SBOX[x] = s ^ inv


_build_sbox()


def _xtime(a: int) -> int:
    a <<= 1
    return (a ^ 0x11B) & 0xFF if a & 0x100 else a


# Encryption T-tables: T0[x] = [2s, s, s, 3s] packed big-endian (s = SBOX[x]);
# T1..T3 are byte rotations.
_T0 = []
for _x in range(256):
    _s = _SBOX[_x]
    _T0.append((_xtime(_s) << 24) | (_s << 16) | (_s << 8) | (_xtime(_s) ^ _s))
_T1 = [((t >> 8) | ((t & 0xFF) << 24)) & 0xFFFFFFFF for t in _T0]
_T2 = [((t >> 16) | ((t & 0xFFFF) << 16)) & 0xFFFFFFFF for t in _T0]
_T3 = [((t >> 24) | ((t & 0xFFFFFF) << 8)) & 0xFFFFFFFF for t in _T0]

_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36, 0x6C]


def aes_key_expand(key: bytes) -> list[int]:
    """Expand a 16- or 32-byte key into 4*(rounds+1) big-endian round words."""
    nk = len(key) // 4
    if nk not in (4, 8):
        raise ValueError("AES key must be 16 or 32 bytes")
    rounds = nk + 6
    w = [int.from_bytes(key[4 * i : 4 * i + 4], "big") for i in range(nk)]
    for i in range(nk, 4 * (rounds + 1)):
        t = w[i - 1]
        if i % nk == 0:
            t = ((t << 8) | (t >> 24)) & 0xFFFFFFFF  # RotWord
            t = (
                (_SBOX[(t >> 24) & 0xFF] << 24)
                | (_SBOX[(t >> 16) & 0xFF] << 16)
                | (_SBOX[(t >> 8) & 0xFF] << 8)
                | _SBOX[t & 0xFF]
            )
            t ^= _RCON[i // nk - 1] << 24
        elif nk == 8 and i % nk == 4:
            t = (
                (_SBOX[(t >> 24) & 0xFF] << 24)
                | (_SBOX[(t >> 16) & 0xFF] << 16)
                | (_SBOX[(t >> 8) & 0xFF] << 8)
                | _SBOX[t & 0xFF]
            )
        w.append(w[i - nk] ^ t)
    return w


def aes_encrypt_block(rk: list[int], block: bytes) -> bytes:
    """Encrypt one 16-byte block under expanded round keys `rk`."""
    rounds = len(rk) // 4 - 1
    s0 = int.from_bytes(block[0:4], "big") ^ rk[0]
    s1 = int.from_bytes(block[4:8], "big") ^ rk[1]
    s2 = int.from_bytes(block[8:12], "big") ^ rk[2]
    s3 = int.from_bytes(block[12:16], "big") ^ rk[3]
    for r in range(1, rounds):
        t0 = (
            _T0[(s0 >> 24) & 0xFF]
            ^ _T1[(s1 >> 16) & 0xFF]
            ^ _T2[(s2 >> 8) & 0xFF]
            ^ _T3[s3 & 0xFF]
            ^ rk[4 * r]
        )
        t1 = (
            _T0[(s1 >> 24) & 0xFF]
            ^ _T1[(s2 >> 16) & 0xFF]
            ^ _T2[(s3 >> 8) & 0xFF]
            ^ _T3[s0 & 0xFF]
            ^ rk[4 * r + 1]
        )
        t2 = (
            _T0[(s2 >> 24) & 0xFF]
            ^ _T1[(s3 >> 16) & 0xFF]
            ^ _T2[(s0 >> 8) & 0xFF]
            ^ _T3[s1 & 0xFF]
            ^ rk[4 * r + 2]
        )
        t3 = (
            _T0[(s3 >> 24) & 0xFF]
            ^ _T1[(s0 >> 16) & 0xFF]
            ^ _T2[(s1 >> 8) & 0xFF]
            ^ _T3[s2 & 0xFF]
            ^ rk[4 * r + 3]
        )
        s0, s1, s2, s3 = t0, t1, t2, t3
    # final round: SubBytes + ShiftRows + AddRoundKey (no MixColumns)
    out = bytearray(16)
    src = (s0, s1, s2, s3)
    for c in range(4):
        out[4 * c + 0] = _SBOX[(src[c] >> 24) & 0xFF]
        out[4 * c + 1] = _SBOX[(src[(c + 1) % 4] >> 16) & 0xFF]
        out[4 * c + 2] = _SBOX[(src[(c + 2) % 4] >> 8) & 0xFF]
        out[4 * c + 3] = _SBOX[src[(c + 3) % 4] & 0xFF]
    k = rk[4 * rounds : 4 * rounds + 4]
    for c in range(4):
        kb = k[c]
        out[4 * c + 0] ^= (kb >> 24) & 0xFF
        out[4 * c + 1] ^= (kb >> 16) & 0xFF
        out[4 * c + 2] ^= (kb >> 8) & 0xFF
        out[4 * c + 3] ^= kb & 0xFF
    return bytes(out)


# ---------------------------------------------------------------------------
# GHASH: GF(2^128) with the GCM bit-reflected convention, byte-table driven.

_GCM_R = 0xE1000000000000000000000000000000


def _gmul_bit(x: int, y: int) -> int:
    """Bitwise GF(2^128) multiply (GCM convention): z = x*y mod the GCM poly."""
    z = 0
    v = x
    for i in range(127, -1, -1):
        if (y >> i) & 1:
            z ^= v
        v = (v >> 1) ^ _GCM_R if v & 1 else v >> 1
    return z


# reduction of Z*x^8: the shifted-out low byte folds back in (R has no
# low bits so the fold never cascades within 8 shifts) — key-independent
_GHASH_RED = []
for _b in range(256):
    _v = _b
    for _ in range(8):
        _v = (_v >> 1) ^ _GCM_R if _v & 1 else _v >> 1
    _GHASH_RED.append(_v)
del _b, _v


class _Ghash:
    """GHASH accumulator keyed by H, with a 256-entry byte table.

    Processes a block via 16 table lookups using Horner on bytes: multiply
    the accumulator by x^8 per step (low-byte reduction table) and add the
    next byte's H-multiple.
    """

    def __init__(self, h: int) -> None:
        # table[b] = (polynomial with byte b in the TOP byte position) * H.
        # GF(2) multiplication is linear in b, so compute the 8 single-bit
        # entries with the bitwise multiply and XOR-combine the rest —
        # ~1k loop iterations instead of ~33k (matters on the QUIC packet
        # admission path, where a fresh key is derived per probe).
        table = [0] * 256
        for i in range(8):
            table[1 << i] = _gmul_bit((1 << i) << 120, h)
        for b in range(1, 256):
            if b & (b - 1):  # not a power of two
                table[b] = table[b & (b - 1)] ^ table[b & -b]
        self.table = table
        self.red = _GHASH_RED
        self.acc = 0

    def update_block(self, block16: bytes) -> None:
        z = self.acc ^ int.from_bytes(block16, "big")
        # z * H, byte-at-a-time from the LOW byte upward
        acc = 0
        for i in range(16):
            byte = z & 0xFF
            z >>= 8
            if i:
                # acc currently holds (lower bytes)*H shifted; multiply by x^8
                low = acc & 0xFF
                acc = (acc >> 8) ^ self.red[low]
            acc ^= self.table[byte] if byte else 0
        self.acc = acc

    def update(self, data: bytes) -> None:
        if len(data) % 16:
            data = data + b"\0" * (16 - len(data) % 16)
        for i in range(0, len(data), 16):
            self.update_block(data[i : i + 16])

    def digest(self) -> int:
        return self.acc


class AesGcm:
    """AES-GCM AEAD with 12-byte IVs (the only size QUIC/TLS use)."""

    TAG_SZ = 16

    def __init__(self, key: bytes) -> None:
        self.rk = aes_key_expand(key)
        self.h = int.from_bytes(aes_encrypt_block(self.rk, b"\0" * 16), "big")
        self._ghash_tmpl = _Ghash(self.h)

    def _ctr(self, iv: bytes, counter0: int, n: int) -> bytes:
        out = bytearray()
        for i in range(n):
            ctr_block = iv + ((counter0 + i) & 0xFFFFFFFF).to_bytes(4, "big")
            out += aes_encrypt_block(self.rk, ctr_block)
        return bytes(out)

    def _tag(self, iv: bytes, aad: bytes, ct: bytes) -> bytes:
        g = _Ghash.__new__(_Ghash)
        g.table = self._ghash_tmpl.table
        g.red = self._ghash_tmpl.red
        g.acc = 0
        g.update(aad)
        g.update(ct)
        g.update_block(
            (len(aad) * 8).to_bytes(8, "big") + (len(ct) * 8).to_bytes(8, "big")
        )
        ek_y0 = aes_encrypt_block(self.rk, iv + b"\0\0\0\1")
        return (g.digest() ^ int.from_bytes(ek_y0, "big")).to_bytes(16, "big")

    def encrypt(self, iv: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
        if len(iv) != 12:
            raise ValueError("GCM IV must be 12 bytes")
        n_blocks = (len(plaintext) + 15) // 16
        ks = self._ctr(iv, 2, n_blocks)
        ct = bytes(p ^ k for p, k in zip(plaintext, ks))
        return ct + self._tag(iv, aad, ct)

    def decrypt(self, iv: bytes, ciphertext: bytes, aad: bytes = b"") -> bytes | None:
        """Returns plaintext, or None on tag mismatch (constant-time compare)."""
        if len(ciphertext) < self.TAG_SZ:
            return None
        ct, tag = ciphertext[: -self.TAG_SZ], ciphertext[-self.TAG_SZ :]
        want = self._tag(iv, aad, ct)
        diff = 0
        for a, b in zip(want, tag):
            diff |= a ^ b
        if diff:
            return None
        n_blocks = (len(ct) + 15) // 16
        ks = self._ctr(iv, 2, n_blocks)
        return bytes(c ^ k for c, k in zip(ct, ks))


def aes_ecb_mask(key: bytes, sample: bytes) -> bytes:
    """QUIC header-protection mask: AES-ECB of a 16-byte ciphertext sample
    (RFC 9001 §5.4.3); the first 5 bytes mask the header."""
    return aes_encrypt_block(aes_key_expand(key), sample[:16])
