"""Murmur3-32 hash, host-side.

Reference role: src/ballet/murmur3/ — sBPF syscall id hashing
(murmur3_32(name, seed=0) names each syscall in the VM dispatch table).
"""


def murmur3_32(data: bytes, seed: int = 0) -> int:
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF

    def rotl32(x, r):
        return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF

    n_blocks = len(data) // 4
    for i in range(n_blocks):
        k = int.from_bytes(data[4 * i : 4 * i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = rotl32(k, 15)
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF

    tail = data[4 * n_blocks :]
    k = 0
    for i, b in enumerate(tail):
        k |= b << (8 * i)
    if tail:
        k = (k * c1) & 0xFFFFFFFF
        k = rotl32(k, 15)
        k = (k * c2) & 0xFFFFFFFF
        h ^= k

    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h
