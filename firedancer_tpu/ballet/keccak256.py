"""Keccak-256 (the pre-NIST padding variant Ethereum/Solana syscalls use).

Reference role: src/ballet/keccak256/ — backs the sol_keccak256 syscall.
Host-side numpy implementation of Keccak-f[1600]; the syscall path hashes
one message at a time, so there is no device batch to win here (if a model
ever needs batched keccak, the 25-lane uint64 state maps to the same
uint32-pair scheme ops/sha512 uses).
"""

import numpy as np

_ROUNDS = 24

# round constants via the LFSR definition
def _rc():
    out = []
    r = 1
    for _ in range(_ROUNDS):
        c = 0
        for j in range(7):
            if r & 1:
                c ^= 1 << ((1 << j) - 1)
            r = ((r << 1) ^ (0x71 if r & 0x80 else 0)) & 0xFF
        out.append(c)
    return np.array(out, dtype=np.uint64)


_RC = _rc()

_ROT = np.zeros((5, 5), dtype=np.uint64)
_x, _y, _r = 1, 0, 0
for _t in range(24):
    _r = (_r + _t + 1) % 64
    _ROT[_x, _y] = _r
    _x, _y = _y, (2 * _x + 3 * _y) % 5


def _rotl(v, r):
    r = np.uint64(r)
    if r == 0:
        return v
    return (v << r) | (v >> (np.uint64(64) - r))


def _keccak_f(a: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        for rnd in range(_ROUNDS):
            # theta (a is indexed [x][y])
            c = np.bitwise_xor.reduce(a, axis=1)
            d = np.roll(c, 1) ^ _rotl(np.roll(c, -1), 1)
            a = a ^ d[:, None]
            # rho + pi
            b = np.zeros_like(a)
            for x in range(5):
                for y in range(5):
                    b[y, (2 * x + 3 * y) % 5] = _rotl(a[x, y], int(_ROT[x, y]))
            # chi
            a = b ^ (~np.roll(b, -1, axis=0) & np.roll(b, -2, axis=0))
            # iota
            a[0, 0] ^= _RC[rnd]
    return a


def keccak256(data: bytes) -> bytes:
    rate = 136  # 1088-bit rate for 256-bit output
    # pad10*1 with the 0x01 domain byte (legacy Keccak, not SHA-3's 0x06)
    pad_len = rate - (len(data) % rate)
    padded = data + b"\x01" + b"\0" * (pad_len - 2) + b"\x80" if pad_len >= 2 else (
        data + b"\x81"
    )
    state = np.zeros((5, 5), dtype=np.uint64)
    for off in range(0, len(padded), rate):
        block = np.frombuffer(padded[off : off + rate], dtype="<u8")
        for i in range(rate // 8):
            x, y = i % 5, i // 5
            state[x, y] ^= block[i]
        state = _keccak_f(state)
    # squeeze 32 bytes
    out = b""
    for i in range(4):
        x, y = i % 5, i // 5
        out += int(state[x, y]).to_bytes(8, "little")
    return out
