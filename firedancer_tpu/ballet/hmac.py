"""HMAC-SHA256/512, host-side control plane.

Reference role: src/ballet/hmac/ — TLS key schedule (HKDF) and repair
message auth.  The data-plane hashes are our JAX kernels (ops/sha256,
ops/sha512); HMAC sits on the host control plane (key schedules are a few
hashes per connection), so it composes the stdlib primitives directly.
HKDF-Expand-Label is the TLS 1.3 form used by the QUIC key schedule
(src/waltz/quic/crypto/fd_quic_crypto_suites.c).
"""

import hashlib


def _hmac(hash_name: str, key: bytes, msg: bytes) -> bytes:
    h = hashlib.new(hash_name)
    block = h.block_size
    if len(key) > block:
        key = hashlib.new(hash_name, key).digest()
    key = key + b"\0" * (block - len(key))
    inner = hashlib.new(hash_name, bytes(k ^ 0x36 for k in key) + msg).digest()
    return hashlib.new(hash_name, bytes(k ^ 0x5C for k in key) + inner).digest()


def hmac_sha256(key: bytes, msg: bytes) -> bytes:
    return _hmac("sha256", key, msg)


def hmac_sha512(key: bytes, msg: bytes) -> bytes:
    return _hmac("sha512", key, msg)


def hkdf_extract(salt: bytes, ikm: bytes, hash_name: str = "sha256") -> bytes:
    if not salt:
        salt = b"\0" * hashlib.new(hash_name).digest_size
    return _hmac(hash_name, salt, ikm)


def hkdf_expand(prk: bytes, info: bytes, length: int, hash_name: str = "sha256") -> bytes:
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = _hmac(hash_name, prk, t + info + bytes([i]))
        out += t
        i += 1
    return out[:length]


def hkdf_expand_label(
    secret: bytes, label: str, context: bytes, length: int,
    hash_name: str = "sha256",
) -> bytes:
    """TLS 1.3 HKDF-Expand-Label (RFC 8446 §7.1; QUIC uses "tls13 " labels)."""
    full = b"tls13 " + label.encode()
    info = (
        length.to_bytes(2, "big")
        + bytes([len(full)])
        + full
        + bytes([len(context)])
        + context
    )
    return hkdf_expand(secret, info, length, hash_name)
