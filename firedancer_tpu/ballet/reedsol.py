"""Reed-Solomon erasure coding over GF(2^8) — shred FEC, TPU-first.

Reference role: src/ballet/reedsol/ (GFNI/AVX accelerated, using the
Lin-Al-Naffouri-Han-Chung FFT basis, fd_reedsol_private.h:160).  The CODE
itself — systematic RS interpolating the data shreds at field points
0..k-1 and evaluating parity at points k..n-1 over GF(2^8) mod 0x11D —
is construction-independent: Vandermonde systematization (used here, and
by the Rust reed-solomon-erasure crate Solana shreds interop with) and
the reference's FFT basis produce identical parity bytes.

TPU mapping: GF(2^8) multiplication by a constant is GF(2)-linear on the
8 bits, so the entire encode collapses to ONE binary matmul: unpack shred
bytes to bit-planes, multiply by the (8p x 8k) generator bit-matrix on the
MXU (int8 matmul), reduce mod 2, repack.  No gathers, no tables on the
device — the systolic array does all the work.  Recovery = the same with
an erasure-specific reconstruction matrix (built host-side per erasure
pattern, O(k^3) GF Gauss-Jordan, amortized over the whole FEC set).

Limits mirror the reference: <= 67 data and <= 67 parity shreds
(fd_reedsol.h:29-30).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

DATA_SHREDS_MAX = 67
PARITY_SHREDS_MAX = 67

_POLY = 0x11D  # x^8+x^4+x^3+x^2+1, the GF(2^8) modulus Solana's RS uses

# exp/log tables for generator 2 (primitive for 0x11D)
_EXP = np.zeros(512, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _POLY
_EXP[255:510] = _EXP[0:255]  # wraparound so exp[a+b] needs no mod


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[_LOG[a] + _LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(_EXP[255 - _LOG[a]])


def gf_pow(a: int, e: int) -> int:
    if e == 0:
        return 1
    if a == 0:
        return 0
    return int(_EXP[(_LOG[a] * e) % 255])


def _mat_mul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product (host, table-driven)."""
    out = np.zeros((A.shape[0], B.shape[1]), dtype=np.uint8)
    for i in range(A.shape[0]):
        for j in range(B.shape[1]):
            acc = 0
            for t in range(A.shape[1]):
                acc ^= gf_mul(int(A[i, t]), int(B[t, j]))
            out[i, j] = acc
    return out


def _mat_inv(M: np.ndarray) -> np.ndarray:
    """GF(2^8) Gauss-Jordan inverse; raises if singular."""
    n = M.shape[0]
    a = M.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = next((r for r in range(col, n) if a[r, col]), None)
        if piv is None:
            raise ValueError("singular matrix (not enough independent shreds)")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        s = gf_inv(int(a[col, col]))
        for j in range(n):
            a[col, j] = gf_mul(int(a[col, j]), s)
            inv[col, j] = gf_mul(int(inv[col, j]), s)
        for r in range(n):
            if r != col and a[r, col]:
                f = int(a[r, col])
                for j in range(n):
                    a[r, j] ^= gf_mul(f, int(a[col, j]))
                    inv[r, j] ^= gf_mul(f, int(inv[col, j]))
    return inv


@functools.lru_cache(maxsize=None)
def _systematic(k: int, n: int) -> bytes:
    """n x k systematic generator: row r = evaluations making codeword[r]
    the degree<k interpolation of data at points 0..k-1 evaluated at r.
    Top k rows are the identity.  Cached as bytes (hashable)."""
    V = np.zeros((n, k), dtype=np.uint8)
    for r in range(n):
        for c in range(k):
            V[r, c] = gf_pow(r, c)
    A = _mat_mul(V, _mat_inv(V[:k, :]))
    assert np.array_equal(A[:k], np.eye(k, dtype=np.uint8))
    return A.tobytes()


def generator_matrix(k: int, n: int) -> np.ndarray:
    return np.frombuffer(_systematic(k, n), dtype=np.uint8).reshape(n, k)


def _bitmatrix(M: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix (R, C) to its GF(2) bit-matrix (8R, 8C):
    out_bit[8r+j, 8c+i] = bit j of (M[r,c] * x^i).  Bit i = (byte>>i)&1."""
    R, C = M.shape
    out = np.zeros((8 * R, 8 * C), dtype=np.int8)
    for r in range(R):
        for c in range(C):
            m = int(M[r, c])
            if not m:
                continue
            for i in range(8):
                prod = gf_mul(m, 1 << i)
                for j in range(8):
                    out[8 * r + j, 8 * c + i] = (prod >> j) & 1
    return out


def _unpack_bits(shreds: jnp.ndarray) -> jnp.ndarray:
    """(k, sz) uint8 -> (8k, sz) int8 bit-planes (bit i of byte r at row 8r+i)."""
    k, sz = shreds.shape
    bits = jnp.stack(
        [(shreds >> jnp.uint8(i)) & jnp.uint8(1) for i in range(8)], axis=1
    )  # (k, 8, sz)
    return bits.reshape(8 * k, sz).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8p, sz) -> (p, sz) uint8."""
    p8, sz = bits.shape
    b = bits.reshape(p8 // 8, 8, sz).astype(jnp.uint8)
    weights = jnp.asarray([1 << i for i in range(8)], dtype=jnp.uint8)
    return (b * weights[None, :, None]).sum(axis=1, dtype=jnp.uint32).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def _encode_device(data_bits: jnp.ndarray, bitmat: jnp.ndarray) -> jnp.ndarray:
    """parity_bits = bitmat @ data_bits mod 2, on the MXU (int8 x int8 ->
    int32 accumulate; max inner dim 8*67=536 << int32 overflow)."""
    acc = jax.lax.dot_general(
        bitmat,
        data_bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (acc & 1).astype(jnp.int8)


def encode(data_shreds: np.ndarray, parity_cnt: int, device: bool = True) -> np.ndarray:
    """Encode parity shreds.  data_shreds: (k, sz) uint8.  Returns (p, sz).

    device=True runs the bit-plane matmul under jit (the production path);
    device=False is the host table-driven golden model.
    """
    k, sz = data_shreds.shape
    n = k + parity_cnt
    if k > DATA_SHREDS_MAX or parity_cnt > PARITY_SHREDS_MAX:
        raise ValueError("shred counts exceed protocol limits")
    P = generator_matrix(k, n)[k:, :]  # (p, k), the non-identity rows
    if not device:
        return _mat_mul(P, data_shreds.astype(np.uint8))
    bitmat = jnp.asarray(_bitmatrix(P))
    bits = _unpack_bits(jnp.asarray(data_shreds, dtype=jnp.uint8))
    return np.asarray(_pack_bits(_encode_device(bits, bitmat)))


def recover(
    shreds: list, k: int, sz: int, device: bool = True
) -> list:
    """Recover a full FEC set from any >= k surviving shreds.

    shreds: length-n list; entry i is the (sz,)-byte shred i or None if
    erased (indices [0,k) data, [k,n) parity).  Returns the complete list.
    Raises ValueError if fewer than k survive (ERR_PARTIAL analogue) or the
    surviving set is inconsistent (ERR_CORRUPT analogue).
    """
    n = len(shreds)
    have = [i for i, s in enumerate(shreds) if s is not None]
    if len(have) < k:
        raise ValueError(f"unrecoverable: only {len(have)} of {k} needed shreds")
    use = have[:k]
    A = generator_matrix(k, n)
    inv = _mat_inv(A[use, :])  # maps surviving codeword bytes -> data bytes
    S = np.stack([np.asarray(shreds[i], dtype=np.uint8) for i in use])  # (k, sz)

    if device:
        bits = _unpack_bits(jnp.asarray(S))
        data = np.asarray(_pack_bits(_encode_device(bits, jnp.asarray(_bitmatrix(inv)))))
    else:
        data = _mat_mul(inv, S)

    # re-derive every shred; check consistency of surviving ones we didn't use
    full = list(data)
    if n > k:
        par = encode(data, n - k, device=device)
        full += list(par)
    for i in have:
        if not np.array_equal(np.asarray(shreds[i], dtype=np.uint8), full[i]):
            raise ValueError(f"corrupt: shred {i} inconsistent with encoding")
    return [np.asarray(s, dtype=np.uint8) for s in full]
