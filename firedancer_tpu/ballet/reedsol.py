"""Reed-Solomon erasure coding over GF(2^8) — shred FEC, TPU-first.

Reference role: src/ballet/reedsol/ (GFNI/AVX accelerated, using the
Lin-Al-Naffouri-Han-Chung FFT basis, fd_reedsol_private.h:160).  The CODE
itself — systematic RS interpolating the data shreds at field points
0..k-1 and evaluating parity at points k..n-1 over GF(2^8) mod 0x11D —
is construction-independent: Vandermonde systematization (used here, and
by the Rust reed-solomon-erasure crate Solana shreds interop with) and
the reference's FFT basis produce identical parity bytes.

TPU mapping: GF(2^8) multiplication by a constant is GF(2)-linear on the
8 bits, so the entire encode collapses to ONE binary matmul: unpack shred
bytes to bit-planes, multiply by the (8p x 8k) generator bit-matrix on the
MXU (int8 matmul), reduce mod 2, repack.  No gathers, no tables on the
device — the systolic array does all the work.  Recovery = the same with
an erasure-specific reconstruction matrix (built host-side per erasure
pattern, O(k^3) GF Gauss-Jordan, amortized over the whole FEC set).

Limits mirror the reference: <= 67 data and <= 67 parity shreds
(fd_reedsol.h:29-30).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

DATA_SHREDS_MAX = 67
PARITY_SHREDS_MAX = 67

_POLY = 0x11D  # x^8+x^4+x^3+x^2+1, the GF(2^8) modulus Solana's RS uses

# exp/log tables for generator 2 (primitive for 0x11D)
_EXP = np.zeros(512, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _POLY
_EXP[255:510] = _EXP[0:255]  # wraparound so exp[a+b] needs no mod


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[_LOG[a] + _LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(_EXP[255 - _LOG[a]])


def gf_pow(a: int, e: int) -> int:
    if e == 0:
        return 1
    if a == 0:
        return 0
    return int(_EXP[(_LOG[a] * e) % 255])


def _mat_mul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product (host, table-driven)."""
    out = np.zeros((A.shape[0], B.shape[1]), dtype=np.uint8)
    for i in range(A.shape[0]):
        for j in range(B.shape[1]):
            acc = 0
            for t in range(A.shape[1]):
                acc ^= gf_mul(int(A[i, t]), int(B[t, j]))
            out[i, j] = acc
    return out


def _mat_inv(M: np.ndarray) -> np.ndarray:
    """GF(2^8) Gauss-Jordan inverse; raises if singular."""
    n = M.shape[0]
    a = M.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = next((r for r in range(col, n) if a[r, col]), None)
        if piv is None:
            raise ValueError("singular matrix (not enough independent shreds)")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        s = gf_inv(int(a[col, col]))
        for j in range(n):
            a[col, j] = gf_mul(int(a[col, j]), s)
            inv[col, j] = gf_mul(int(inv[col, j]), s)
        for r in range(n):
            if r != col and a[r, col]:
                f = int(a[r, col])
                for j in range(n):
                    a[r, j] ^= gf_mul(f, int(a[col, j]))
                    inv[r, j] ^= gf_mul(f, int(inv[col, j]))
    return inv


@functools.lru_cache(maxsize=None)
def _systematic(k: int, n: int) -> bytes:
    """n x k systematic generator: row r = evaluations making codeword[r]
    the degree<k interpolation of data at points 0..k-1 evaluated at r.
    Top k rows are the identity.  Cached as bytes (hashable)."""
    V = np.zeros((n, k), dtype=np.uint8)
    for r in range(n):
        for c in range(k):
            V[r, c] = gf_pow(r, c)
    A = _mat_mul(V, _mat_inv(V[:k, :]))
    assert np.array_equal(A[:k], np.eye(k, dtype=np.uint8))
    return A.tobytes()


def generator_matrix(k: int, n: int) -> np.ndarray:
    return np.frombuffer(_systematic(k, n), dtype=np.uint8).reshape(n, k)


def _bitmatrix(M: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix (R, C) to its GF(2) bit-matrix (8R, 8C):
    out_bit[8r+j, 8c+i] = bit j of (M[r,c] * x^i).  Bit i = (byte>>i)&1."""
    R, C = M.shape
    out = np.zeros((8 * R, 8 * C), dtype=np.int8)
    for r in range(R):
        for c in range(C):
            m = int(M[r, c])
            if not m:
                continue
            for i in range(8):
                prod = gf_mul(m, 1 << i)
                for j in range(8):
                    out[8 * r + j, 8 * c + i] = (prod >> j) & 1
    return out


def _unpack_bits(shreds: jnp.ndarray) -> jnp.ndarray:
    """(k, sz) uint8 -> (8k, sz) int8 bit-planes (bit i of byte r at row 8r+i)."""
    k, sz = shreds.shape
    bits = jnp.stack(
        [(shreds >> jnp.uint8(i)) & jnp.uint8(1) for i in range(8)], axis=1
    )  # (k, 8, sz)
    return bits.reshape(8 * k, sz).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8p, sz) -> (p, sz) uint8."""
    p8, sz = bits.shape
    b = bits.reshape(p8 // 8, 8, sz).astype(jnp.uint8)
    weights = jnp.asarray([1 << i for i in range(8)], dtype=jnp.uint8)
    return (b * weights[None, :, None]).sum(axis=1, dtype=jnp.uint32).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def _encode_device(data_bits: jnp.ndarray, bitmat: jnp.ndarray) -> jnp.ndarray:
    """parity_bits = bitmat @ data_bits mod 2, on the MXU (int8 x int8 ->
    int32 accumulate; max inner dim 8*67=536 << int32 overflow)."""
    acc = jax.lax.dot_general(
        bitmat,
        data_bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (acc & 1).astype(jnp.int8)


def encode(data_shreds: np.ndarray, parity_cnt: int, device: bool = True) -> np.ndarray:
    """Encode parity shreds.  data_shreds: (k, sz) uint8.  Returns (p, sz).

    device=True runs the bit-plane matmul under jit (the production path);
    device=False is the host table-driven golden model.
    """
    k, sz = data_shreds.shape
    n = k + parity_cnt
    if k > DATA_SHREDS_MAX or parity_cnt > PARITY_SHREDS_MAX:
        raise ValueError("shred counts exceed protocol limits")
    P = generator_matrix(k, n)[k:, :]  # (p, k), the non-identity rows
    if not device:
        return _mat_mul(P, data_shreds.astype(np.uint8))
    bitmat = jnp.asarray(_bitmatrix(P))
    bits = _unpack_bits(jnp.asarray(data_shreds, dtype=jnp.uint8))
    return np.asarray(_pack_bits(_encode_device(bits, bitmat)))


# ---------------------------------------------------------------------------
# Recovery: cached reconstruction matrices + fused single-dispatch recover.
#
# The combined (n, k) matrix R = A @ inv(A[use, :]) maps the k used
# surviving codeword bytes straight to the WHOLE codeword (data recover +
# parity re-derive in one matmul); rows of R at used survivor positions are
# the selection identity, so the consistency check reduces to comparing the
# re-derived codeword against every surviving shred.  R (and its GF(2)
# bit-matrix) is LRU-cached per (k, n, erasure-pattern) — the O(k^3)
# Gauss-Jordan amortizes across every FEC set sharing a pattern, which the
# module docstring always promised and the code now actually does.

_RECOVER_CACHE_MAX = 1024


@functools.lru_cache(maxsize=_RECOVER_CACHE_MAX)
def _recover_matrices(k: int, n: int, use: tuple) -> tuple:
    """(R bytes, R bit-matrix bytes) for surviving indices `use` (len k).

    Fast path: when the first k survivors are exactly 0..k-1 (no data
    erasures) the inner inverse is the identity — _mat_inv is skipped
    entirely and R is the systematic generator itself."""
    A = generator_matrix(k, n)
    if use == tuple(range(k)):
        R = A  # identity reconstruction: no data erasures
    else:
        R = _mat_mul(A, _mat_inv(A[list(use), :]))
    return R.tobytes(), _bitmatrix(R).tobytes()


def recover_cache_info():
    """Hit/miss accounting for the reconstruction-matrix LRU."""
    return _recover_matrices.cache_info()


def recover_cache_clear() -> None:
    _recover_matrices.cache_clear()


def _recover_bitmat(k: int, n: int, use: tuple) -> np.ndarray:
    _, bits = _recover_matrices(k, n, use)
    return np.frombuffer(bits, dtype=np.int8).reshape(8 * n, 8 * k)


def _recover_gfmat(k: int, n: int, use: tuple) -> np.ndarray:
    R, _ = _recover_matrices(k, n, use)
    return np.frombuffer(R, dtype=np.uint8).reshape(n, k)


def recover(
    shreds: list, k: int, sz: int, device: bool = True
) -> list:
    """Recover a full FEC set from any >= k surviving shreds.

    shreds: length-n list; entry i is the (sz,)-byte shred i or None if
    erased (indices [0,k) data, [k,n) parity).  Returns the complete list.
    Raises ValueError if fewer than k survive (ERR_PARTIAL analogue) or the
    surviving set is inconsistent (ERR_CORRUPT analogue).

    One fused dispatch: the combined cached matrix R recovers data AND
    re-derives parity in a single bit-plane matmul (the pre-round-13 path
    paid a second device dispatch re-encoding parity via encode()).  With
    no data erasures the reconstruction is the identity: survivors pass
    through and only the parity rows of R do work.
    """
    n = len(shreds)
    if k > DATA_SHREDS_MAX or n - k > PARITY_SHREDS_MAX:
        raise ValueError("shred counts exceed protocol limits")
    have = [i for i, s in enumerate(shreds) if s is not None]
    if len(have) < k:
        raise ValueError(f"unrecoverable: only {len(have)} of {k} needed shreds")
    use = tuple(have[:k])
    S = np.stack([np.asarray(shreds[i], dtype=np.uint8) for i in use])  # (k, sz)

    if use == tuple(range(k)) and not device:
        # all-data fast path (host): no recover matmul at all — data IS the
        # survivors; go straight to parity re-derive + consistency check
        full_arr = np.concatenate(
            [S, _mat_mul(generator_matrix(k, n)[k:, :], S)]
            if n > k else [S])
    elif device:
        bits = _unpack_bits(jnp.asarray(S))
        full_arr = np.asarray(_pack_bits(_encode_device(
            bits, jnp.asarray(_recover_bitmat(k, n, use)))))
    else:
        full_arr = _mat_mul(_recover_gfmat(k, n, use), S)

    full = [np.asarray(full_arr[i], dtype=np.uint8) for i in range(n)]
    for i in have:
        if not np.array_equal(np.asarray(shreds[i], dtype=np.uint8), full[i]):
            raise ValueError(f"corrupt: shred {i} inconsistent with encoding")
    return full


# ---------------------------------------------------------------------------
# Batched multi-set recovery: many FEC sets per device dispatch.
#
# Surviving shreds from B sets pad/stack into (B, K, S) against a stacked
# per-set reconstruction bit-matrix (B, 8N, 8K); one batched matmul
# re-derives every codeword, and the per-set consistency verdict (recovered
# == every surviving shred) is computed in the SAME dispatch.  Zero-padding
# is self-consistent: padded rows/columns of a GF(2)-linear map produce
# zeros, which compare equal against the zero-padded reference.


def _recover_batch_core(surv: jnp.ndarray, bitmat: jnp.ndarray,
                        ref: jnp.ndarray, have: jnp.ndarray):
    """surv (B, K, S) u8, bitmat (B, 8N, 8K) i8, ref (B, N, S) u8,
    have (B, N) bool -> (full (B, N, S) u8, ok (B,) bool).  One dispatch:
    data recover + parity re-derive + per-set consistency check."""
    B, K, S = surv.shape
    bits = jnp.stack(
        [(surv >> jnp.uint8(i)) & jnp.uint8(1) for i in range(8)], axis=2
    ).reshape(B, 8 * K, S).astype(jnp.int8)          # (B, 8K, S)
    acc = jax.lax.dot_general(
        bitmat, bits,
        (((2,), (1,)), ((0,), (0,))),                # batched (8N,8K)@(8K,S)
        preferred_element_type=jnp.int32)
    fb = (acc & 1).astype(jnp.uint8).reshape(B, -1, 8, S)
    weights = jnp.asarray([1 << i for i in range(8)], dtype=jnp.uint8)
    full = (fb * weights[None, None, :, None]).sum(
        axis=2, dtype=jnp.uint32).astype(jnp.uint8)  # (B, N, S)
    ok = jnp.all((full == ref) | ~have[:, :, None], axis=(1, 2))
    return full, ok


_recover_batch_device = jax.jit(_recover_batch_core)


# -- packed-blob form (dispatch-engine workload) ----------------------------
# Row layout for the rotation-buffer engine (models.verifier
# PackedDispatchEngine / disco.tiles.ShredRecoverIngest): one FEC set per
# row, surv[K*S] | ref[N*S] | have[N], all uint8; the per-set
# reconstruction bit-matrix rides in a sibling (B, 8N, 8K) array stamped
# by the same accumulator.  Verdict row = full[N*S] | ok[1] so the engine
# harvests ONE device array.


def recover_blob_row_bytes(k_max: int, n_max: int, sz: int) -> int:
    return (k_max + n_max) * sz + n_max


def recover_verdict_row_bytes(n_max: int, sz: int) -> int:
    return n_max * sz + 1


@functools.partial(jax.jit, static_argnames=("k_max", "n_max", "sz"))
def recover_blob(blob: jnp.ndarray, bitmat: jnp.ndarray,
                 k_max: int, n_max: int, sz: int) -> jnp.ndarray:
    """Packed-row batched recover: blob (B, recover_blob_row_bytes(...))
    u8 + bitmat (B, 8*n_max, 8*k_max) i8 -> (B, n_max*sz + 1) u8 verdict
    rows (recovered codeword bytes, then the ok flag)."""
    B = blob.shape[0]
    ks, ns = k_max * sz, n_max * sz
    surv = blob[:, :ks].reshape(B, k_max, sz)
    ref = blob[:, ks:ks + ns].reshape(B, n_max, sz)
    have = blob[:, ks + ns:].astype(bool)
    full, ok = _recover_batch_core(surv, bitmat, ref, have)
    return jnp.concatenate(
        [full.reshape(B, ns), ok[:, None].astype(jnp.uint8)], axis=1)


def _stack_recover_batch(sets: list):
    """Host-side pack: validate + stack B sets for the fused dispatch.

    Returns (surv, bitmat, ref, have, metas, errs) where metas[i] is
    (k, n, sz, have_idx) for packable sets and errs[i] is a ValueError for
    sets rejected before dispatch (too few survivors / over limits)."""
    B = len(sets)
    metas, errs = [None] * B, [None] * B
    K = N = S = 1
    packable = []
    for bi, (shreds, k, sz) in enumerate(sets):
        n = len(shreds)
        have = [i for i, s in enumerate(shreds) if s is not None]
        if k > DATA_SHREDS_MAX or n - k > PARITY_SHREDS_MAX:
            errs[bi] = ValueError("shred counts exceed protocol limits")
            continue
        if len(have) < k:
            errs[bi] = ValueError(
                f"unrecoverable: only {len(have)} of {k} needed shreds")
            continue
        metas[bi] = (k, n, sz, have)
        K, N, S = max(K, k), max(N, n), max(S, sz)
        packable.append(bi)
    surv = np.zeros((B, K, S), dtype=np.uint8)
    bitmat = np.zeros((B, 8 * N, 8 * K), dtype=np.int8)
    ref = np.zeros((B, N, S), dtype=np.uint8)
    have_m = np.zeros((B, N), dtype=bool)
    for bi in packable:
        shreds, k, sz = sets[bi]
        _, n, _, have = metas[bi]
        use = tuple(have[:k])
        for r, i in enumerate(use):
            surv[bi, r, :sz] = np.asarray(shreds[i], dtype=np.uint8)
        bm = _recover_bitmat(k, n, use)
        bitmat[bi, :8 * n, :8 * k] = bm
        for i in have:
            ref[bi, i, :sz] = np.asarray(shreds[i], dtype=np.uint8)
            have_m[bi, i] = True
    return surv, bitmat, ref, have_m, metas, errs


def _finish_recover_batch(full: np.ndarray, ok: np.ndarray,
                          metas: list, errs: list) -> list:
    """Per-set outcomes off a materialized batch verdict: the recovered
    full shred list, or the ValueError describing why the set failed
    (never raises per-set — an erasure storm must not sink the batch)."""
    out = []
    for bi, meta in enumerate(metas):
        if meta is None:
            out.append(errs[bi])
            continue
        k, n, sz, have = meta
        if not bool(ok[bi]):
            out.append(ValueError(
                "corrupt: a surviving shred is inconsistent with the "
                "re-derived encoding"))
            continue
        out.append([np.asarray(full[bi, i, :sz], dtype=np.uint8)
                    for i in range(n)])
    return out


def recover_batch(sets: list, device: bool = True) -> list:
    """Recover many FEC sets in ONE device dispatch.

    sets: list of (shreds, k, sz) triples with the recover() per-set
    contract.  Returns a list of per-set outcomes: the recovered full
    shred list on success, else the ValueError (ERR_PARTIAL/ERR_CORRUPT
    analogue) for that set — errors never propagate across sets.

    device=False runs the table-driven host golden model per set
    (bit-identity reference for the stacked device path)."""
    if not sets:
        return []
    if not device:
        out = []
        for shreds, k, sz in sets:
            try:
                out.append(recover(shreds, k, sz, device=False))
            except ValueError as e:
                out.append(e)
        return out
    surv, bitmat, ref, have_m, metas, errs = _stack_recover_batch(sets)
    full_d, ok_d = _recover_batch_device(
        jnp.asarray(surv), jnp.asarray(bitmat), jnp.asarray(ref),
        jnp.asarray(have_m))
    return _finish_recover_batch(np.asarray(full_d), np.asarray(ok_d),
                                 metas, errs)
