"""Solana transaction wire-format parser.

Validation rules are consensus-identical to the reference's fd_txn_parse
(src/ballet/txn/fd_txn_parse.c:80-236); the descriptor mirrors fd_txn_t
(src/ballet/txn/fd_txn.h:60-103): byte OFFSETS into the original payload
rather than copies, so signature/pubkey/message extraction for the verify
batch is zero-copy slicing.

This is control-plane host code (the reference's parser is also a scalar
loop per txn — there is no data parallelism inside one txn to map to the
device); the batch axis lives one level up, in the coalescer that packs many
parsed txns into fixed device shapes.
"""

from dataclasses import dataclass, field

from . import compact_u16 as cu16

# wire limits (fd_txn.h:35-108)
SIGNATURE_SZ = 64
PUBKEY_SZ = 32
ACCT_ADDR_SZ = 32
BLOCKHASH_SZ = 32
SIG_MAX = 127
ACTUAL_SIG_MAX = 12
ACCT_ADDR_MAX = 128
ADDR_TABLE_LOOKUP_MAX = 127
INSTR_MAX = 64
MTU = 1232
MIN_SERIALIZED_SZ = 134

VLEGACY = 0xFF
V0 = 0x00

_MIN_INSTR_SZ = 3
_MIN_ADDR_LUT_SZ = 34


class TxnParseError(ValueError):
    pass


@dataclass(frozen=True)
class Instr:
    """One instruction: offsets into the payload (fd_txn_instr_t)."""

    program_id: int
    acct_cnt: int
    data_sz: int
    acct_off: int
    data_off: int


@dataclass(frozen=True)
class AddrTableLookup:
    """One address-table lookup (fd_txn_acct_addr_lut_t)."""

    addr_off: int
    writable_cnt: int
    readonly_cnt: int
    writable_off: int
    readonly_off: int


@dataclass(frozen=True)
class Txn:
    """Parsed transaction descriptor (fd_txn_t, fd_txn.h:60-103)."""

    transaction_version: int
    signature_cnt: int
    signature_off: int
    message_off: int
    readonly_signed_cnt: int
    readonly_unsigned_cnt: int
    acct_addr_cnt: int
    acct_addr_off: int
    recent_blockhash_off: int
    addr_table_lookup_cnt: int
    addr_table_adtl_writable_cnt: int
    addr_table_adtl_cnt: int
    instrs: tuple[Instr, ...] = field(default_factory=tuple)
    addr_tables: tuple[AddrTableLookup, ...] = field(default_factory=tuple)

    # ------------------------------------------------- zero-copy extraction

    def signatures(self, payload: bytes) -> list[bytes]:
        o = self.signature_off
        return [
            payload[o + i * SIGNATURE_SZ : o + (i + 1) * SIGNATURE_SZ]
            for i in range(self.signature_cnt)
        ]

    def signer_pubkeys(self, payload: bytes) -> list[bytes]:
        """The first signature_cnt account addresses are the signers'
        pubkeys, in signature order (fd_txn.h account ordering)."""
        o = self.acct_addr_off
        return [
            payload[o + i * ACCT_ADDR_SZ : o + (i + 1) * ACCT_ADDR_SZ]
            for i in range(self.signature_cnt)
        ]

    def account_addrs(self, payload: bytes) -> list[bytes]:
        o = self.acct_addr_off
        return [
            payload[o + i * ACCT_ADDR_SZ : o + (i + 1) * ACCT_ADDR_SZ]
            for i in range(self.acct_addr_cnt)
        ]

    def message(self, payload: bytes) -> bytes:
        """The signed region: everything from message_off to the end."""
        return payload[self.message_off :]

    def recent_blockhash(self, payload: bytes) -> bytes:
        return payload[self.recent_blockhash_off : self.recent_blockhash_off + BLOCKHASH_SZ]

    def is_writable(self, idx: int) -> bool:
        """Static-account writability (message-level accounts only):
        writable-signed | writable-unsigned partition per fd_txn.h ordering."""
        if idx < self.signature_cnt:
            return idx < self.signature_cnt - self.readonly_signed_cnt
        return idx < self.acct_addr_cnt - self.readonly_unsigned_cnt


def fee_payer(payload: bytes):
    """The first static account address (the fee payer) without a full
    parse — just the fixed-offset header walk.  Returns None on any
    malformed header instead of raising: the sharded leader_pack tiles
    steer EVERY rx'd txn by fee payer before deciding whether to pay for
    a full parse, so a bad txn must cost O(1) on the non-owning shards
    (the owning shard's parse rejects it with the real error)."""
    try:
        nsig = payload[0]
        i = 1 + SIGNATURE_SZ * nsig
        # message header: 1 version byte + dup sig byte (v0) or the sig
        # count itself (legacy), then ro_signed + ro_unsigned
        i += 2 if payload[i] & 0x80 else 1
        i += 2
        cnt, used = cu16.decode(payload, i)
        i += used
        if cnt < 1 or i + ACCT_ADDR_SZ > len(payload):
            return None
        return payload[i : i + ACCT_ADDR_SZ]
    except (IndexError, ValueError):
        return None


def parse(payload: bytes, allow_zero_signatures: bool = False,
          partial: bool = False):
    """Parse + validate one serialized txn (fd_txn_parse semantics).

    Raises TxnParseError on any rule violation; trailing bytes are an error
    (the reference's !payload_sz_opt mode) unless partial=True, which
    returns (Txn, consumed) instead — the embedded-in-a-bincode-stream
    form gossip vote CRDS values use (the reference's payload_sz_opt
    mode, fd_txn_parse_core)."""
    n = len(payload)
    if n > MTU:
        raise TxnParseError(f"payload {n} > MTU {MTU}")
    i = 0

    def need(k: int):
        if k > n - i:
            raise TxnParseError(f"truncated at {i}, need {k}")

    def read_cu16() -> int:
        nonlocal i
        try:
            v, used = cu16.decode(payload, i)
        except ValueError as e:
            raise TxnParseError(str(e)) from e
        i += used
        return v

    need(1)
    signature_cnt = payload[i]
    i += 1
    if not allow_zero_signatures and not (1 <= signature_cnt <= SIG_MAX):
        raise TxnParseError(f"signature_cnt {signature_cnt}")
    need(SIGNATURE_SZ * signature_cnt)
    signature_off = i
    i += SIGNATURE_SZ * signature_cnt

    message_off = i
    need(1)
    header_b0 = payload[i]
    i += 1
    if header_b0 & 0x80:
        version = header_b0 & 0x7F
        if version != V0:
            raise TxnParseError(f"unknown txn version {version}")
        transaction_version = V0
        need(1)
        if payload[i] != signature_cnt:
            raise TxnParseError("header sig cnt != signature_cnt")
        i += 1
    else:
        transaction_version = VLEGACY
        if header_b0 != signature_cnt:
            raise TxnParseError("header sig cnt != signature_cnt")

    need(1)
    ro_signed_cnt = payload[i]
    i += 1
    if not allow_zero_signatures and not ro_signed_cnt < signature_cnt:
        raise TxnParseError("readonly_signed_cnt >= signature_cnt")
    need(1)
    ro_unsigned_cnt = payload[i]
    i += 1

    acct_addr_cnt = read_cu16()
    if not (signature_cnt <= acct_addr_cnt <= ACCT_ADDR_MAX):
        raise TxnParseError(f"acct_addr_cnt {acct_addr_cnt}")
    if signature_cnt + ro_unsigned_cnt > acct_addr_cnt:
        raise TxnParseError("signers + readonly unsigned > accounts")
    need(ACCT_ADDR_SZ * acct_addr_cnt)
    acct_addr_off = i
    i += ACCT_ADDR_SZ * acct_addr_cnt
    need(BLOCKHASH_SZ)
    recent_blockhash_off = i
    i += BLOCKHASH_SZ

    instr_cnt = read_cu16()
    if instr_cnt > INSTR_MAX:
        raise TxnParseError(f"instr_cnt {instr_cnt}")
    need(_MIN_INSTR_SZ * instr_cnt)
    # >0 instructions requires a non-fee-payer account for the program id
    if not allow_zero_signatures and not acct_addr_cnt > (1 if instr_cnt else 0):
        raise TxnParseError("no account available for program id")

    max_acct = 0
    instrs = []
    for _ in range(instr_cnt):
        need(_MIN_INSTR_SZ)
        program_id = payload[i]
        i += 1
        acct_cnt = read_cu16()
        need(acct_cnt)
        acct_off = i
        for k in range(acct_cnt):
            max_acct = max(max_acct, payload[i + k])
        i += acct_cnt
        data_sz = read_cu16()
        need(data_sz)
        data_off = i
        i += data_sz
        # program can't be the fee payer (acct 0) and can't come from a table
        if not allow_zero_signatures and not 0 < program_id < acct_addr_cnt:
            raise TxnParseError(f"program_id {program_id} out of range")
        instrs.append(Instr(program_id, acct_cnt, data_sz, acct_off, data_off))

    addr_tables = []
    adtl_writable = 0
    adtl = 0
    if transaction_version == V0:
        addr_table_cnt = read_cu16()
        if addr_table_cnt > ADDR_TABLE_LOOKUP_MAX:
            raise TxnParseError(f"addr_table_cnt {addr_table_cnt}")
        need(_MIN_ADDR_LUT_SZ * addr_table_cnt)
        for _ in range(addr_table_cnt):
            need(ACCT_ADDR_SZ)
            addr_off = i
            i += ACCT_ADDR_SZ
            writable_cnt = read_cu16()
            need(writable_cnt)
            writable_off = i
            i += writable_cnt
            readonly_cnt = read_cu16()
            need(readonly_cnt)
            readonly_off = i
            i += readonly_cnt
            if writable_cnt > ACCT_ADDR_MAX - acct_addr_cnt:
                raise TxnParseError("table writable_cnt too large")
            if readonly_cnt > ACCT_ADDR_MAX - acct_addr_cnt:
                raise TxnParseError("table readonly_cnt too large")
            if writable_cnt + readonly_cnt < 1:
                raise TxnParseError("empty address table lookup")
            addr_tables.append(
                AddrTableLookup(addr_off, writable_cnt, readonly_cnt, writable_off, readonly_off)
            )
            adtl_writable += writable_cnt
            adtl += writable_cnt + readonly_cnt

    if i != n and not partial:
        raise TxnParseError(f"{n - i} trailing bytes")
    if acct_addr_cnt + adtl > ACCT_ADDR_MAX:
        raise TxnParseError("total accounts > max")
    if not max_acct < acct_addr_cnt + adtl:
        raise TxnParseError(f"account index {max_acct} out of range")

    txn = Txn(
        transaction_version=transaction_version,
        signature_cnt=signature_cnt,
        signature_off=signature_off,
        message_off=message_off,
        readonly_signed_cnt=ro_signed_cnt,
        readonly_unsigned_cnt=ro_unsigned_cnt,
        acct_addr_cnt=acct_addr_cnt,
        acct_addr_off=acct_addr_off,
        recent_blockhash_off=recent_blockhash_off,
        addr_table_lookup_cnt=len(addr_tables),
        addr_table_adtl_writable_cnt=adtl_writable,
        addr_table_adtl_cnt=adtl,
        instrs=tuple(instrs),
        addr_tables=tuple(addr_tables),
    )
    return (txn, i) if partial else txn


# ---------------------------------------------------------------- generation
# Test/bench txn construction (the reference's fd_txn_generate,
# src/flamenco/txn/fd_txn_generate.c, serves the same role).


def build_unsigned(
    signer_pubkeys: list[bytes],
    recent_blockhash: bytes,
    instrs: list[tuple[int, bytes, bytes]],
    extra_accounts: list[bytes] | None = None,
    readonly_signed_cnt: int = 0,
    readonly_unsigned_cnt: int = 0,
    version: int = VLEGACY,
    lookups: list[tuple[bytes, bytes, bytes]] | None = None,
) -> bytes:
    """Serialize the MESSAGE (signed region) of a txn.

    instrs: list of (program_id_index, account_index_bytes, data).
    lookups (v0 only): list of (table_pubkey, writable_idx_bytes,
    readonly_idx_bytes) address-table lookups."""
    out = bytearray()
    nsig = len(signer_pubkeys)
    if version == V0:
        out.append(0x80)
        out.append(nsig)
    else:
        out.append(nsig)
    out.append(readonly_signed_cnt)
    out.append(readonly_unsigned_cnt)
    accounts = list(signer_pubkeys) + list(extra_accounts or [])
    out += cu16.encode(len(accounts))
    for a in accounts:
        assert len(a) == ACCT_ADDR_SZ
        out += a
    assert len(recent_blockhash) == BLOCKHASH_SZ
    out += recent_blockhash
    out += cu16.encode(len(instrs))
    for prog_idx, acct_idx, data in instrs:
        out.append(prog_idx)
        out += cu16.encode(len(acct_idx))
        out += acct_idx
        out += cu16.encode(len(data))
        out += data
    if version == V0:
        out += cu16.encode(len(lookups or []))
        for table_pk, wr_idx, ro_idx in lookups or []:
            assert len(table_pk) == ACCT_ADDR_SZ
            out += table_pk
            out += cu16.encode(len(wr_idx)) + wr_idx
            out += cu16.encode(len(ro_idx)) + ro_idx
    else:
        assert not lookups, "lookups require a v0 message"
    return bytes(out)


def assemble(signatures: list[bytes], message: bytes) -> bytes:
    """sig list + message -> serialized txn."""
    out = bytearray([len(signatures)])
    for s in signatures:
        assert len(s) == SIGNATURE_SZ
        out += s
    out += message
    return bytes(out)
