"""Shred wire format: parse/construct, shredder, and FEC recovery.

Reference role: src/ballet/shred/ (fd_shred.h wire layout),
src/disco/shred/fd_shredder.c (entry batch -> FEC sets: data shreds +
Reed-Solomon parity + merkle commitment + leader signature) and
fd_fec_resolver.c (incoming side: collect a partial FEC set, recover the
erasures, verify the merkle inclusion of every shred).

Merkle-variant shreds only (what mainnet emits today): the leader signs
the 20-byte-node merkle root committing to the whole FEC set, and every
shred carries its inclusion proof, so a receiver can authenticate any
single packet in isolation.  Layouts/constants follow fd_shred.h:10-232
exactly; domain prefixes for the tree are the long Solana prefixes
(fd_bmtree.c:141-142).

Device hooks: parity generation rides ballet/reedsol (MXU bit-plane
matmul); the per-level tree hashing rides ops/sha256 via ballet/bmtree
(batched; one device call per level when committing many sets at once).
Wire parse/construct is host work.
"""

from dataclasses import dataclass
from typing import Optional

import numpy as np

from . import bmtree, reedsol

MAX_SZ = 1228
MIN_SZ = 1203
DATA_HEADER_SZ = 0x58  # 88
CODE_HEADER_SZ = 0x59  # 89
SIGNATURE_SZ = 64
MERKLE_NODE_SZ = 20
MERKLE_ROOT_SZ = 32

TYPE_LEGACY_DATA = 0xA0
TYPE_LEGACY_CODE = 0x50
TYPE_MERKLE_DATA = 0x80
TYPE_MERKLE_CODE = 0x40
TYPE_MERKLE_DATA_CHAINED = 0x90
TYPE_MERKLE_CODE_CHAINED = 0x60
TYPE_MERKLE_DATA_CHAINED_RESIGNED = 0xB0
TYPE_MERKLE_CODE_CHAINED_RESIGNED = 0x70

TYPEMASK_DATA = TYPE_MERKLE_DATA
TYPEMASK_CODE = TYPE_MERKLE_CODE

FLAG_SLOT_COMPLETE = 0x80
FLAG_DATA_COMPLETE = 0x40
REF_TICK_MASK = 0x3F

MAX_PER_SLOT = 1 << 15


def shred_type(variant: int) -> int:
    return variant & 0xF0


def is_data(variant: int) -> bool:
    # all data types have the 0x80 bit set (0xA0/0x80/0x90/0xB0); no code
    # type does (0x50/0x40/0x60/0x70)
    return bool(shred_type(variant) & TYPEMASK_DATA)


def _merkle_cnt(variant: int) -> int:
    """Number of non-root proof nodes (low nibble, merkle variants)."""
    return variant & 0x0F


@dataclass
class Shred:
    """Parsed shred header (fd_shred_t) + the raw buffer."""

    raw: bytes
    signature: bytes
    variant: int
    slot: int
    idx: int
    version: int
    fec_set_idx: int
    # data shreds
    parent_off: int = 0
    flags: int = 0
    size: int = 0  # headers + payload
    # code shreds
    data_cnt: int = 0
    code_cnt: int = 0
    code_idx: int = 0

    @property
    def type(self) -> int:
        return shred_type(self.variant)

    @property
    def is_data(self) -> bool:
        return is_data(self.variant)

    @property
    def merkle_proof_len(self) -> int:
        return _merkle_cnt(self.variant) if self.type not in (
            TYPE_LEGACY_DATA,
            TYPE_LEGACY_CODE,
        ) else 0

    def payload(self) -> bytes:
        if self.is_data:
            return self.raw[DATA_HEADER_SZ : self.size]
        return self.raw[CODE_HEADER_SZ : CODE_HEADER_SZ + self._code_payload_sz()]

    def _code_payload_sz(self) -> int:
        return len(self.raw) - CODE_HEADER_SZ - self._trailer_sz()

    def _trailer_sz(self) -> int:
        """Wire trailer past the payload: [chained merkle root (32)]
        [proof nodes (20 each, NO root stored)] [retransmitter sig (64)]
        — the root is COMPUTED by walking the proof (fd_shred.h layout;
        round-4 fix: the r3 layout materialized the root in the trailer,
        which no real Agave shred does)."""
        t = self.type
        sz = 0
        if t in (TYPE_MERKLE_DATA_CHAINED, TYPE_MERKLE_CODE_CHAINED,
                 TYPE_MERKLE_DATA_CHAINED_RESIGNED, TYPE_MERKLE_CODE_CHAINED_RESIGNED):
            sz += MERKLE_ROOT_SZ
        if t not in (TYPE_LEGACY_DATA, TYPE_LEGACY_CODE):
            sz += MERKLE_NODE_SZ * self.merkle_proof_len
        if t in (TYPE_MERKLE_DATA_CHAINED_RESIGNED, TYPE_MERKLE_CODE_CHAINED_RESIGNED):
            sz += SIGNATURE_SZ
        return sz

    def _proof_off(self) -> int:
        end = len(self.raw)
        t = self.type
        if t in (TYPE_MERKLE_DATA_CHAINED_RESIGNED, TYPE_MERKLE_CODE_CHAINED_RESIGNED):
            end -= SIGNATURE_SZ
        return end - MERKLE_NODE_SZ * self.merkle_proof_len

    def proof_nodes(self) -> list[bytes]:
        """The stored inclusion proof (sibling path, leaf upward)."""
        t = self.type
        if t in (TYPE_LEGACY_DATA, TYPE_LEGACY_CODE):
            return []
        start = self._proof_off()
        return [
            self.raw[start + i * MERKLE_NODE_SZ : start + (i + 1) * MERKLE_NODE_SZ]
            for i in range(self.merkle_proof_len)
        ]

    def tree_index(self, data_cnt: int | None = None) -> int:
        """Leaf index in the FEC set's tree: data shreds sit at
        idx - fec_set_idx; parity at data_cnt + code_idx (the fec
        resolver's shred_idx recipe, fd_fec_resolver.c:352)."""
        if self.is_data:
            return self.idx - self.fec_set_idx
        return (self.data_cnt if data_cnt is None else data_cnt) + self.code_idx

    def merkle_root(self, data_cnt: int | None = None) -> bytes | None:
        """The 32-byte root the leader SIGNS, computed by hashing the leaf
        and walking the stored proof (interior children truncate to 20
        bytes; the root itself is the untruncated sha256 — validated
        against the real capture, tests/golden/demo-shreds.pcap)."""
        if self.type in (TYPE_LEGACY_DATA, TYPE_LEGACY_CODE):
            return None
        return walk_merkle_root(
            self.merkle_leaf_data(), self.tree_index(data_cnt),
            self.proof_nodes())

    def merkle_leaf_data(self) -> bytes:
        """The bytes the merkle leaf hash covers: everything after the
        signature up to the proof (chained roots are INSIDE the covered
        span; the retransmitter signature is not)."""
        return self.raw[SIGNATURE_SZ : self._proof_off()]


def walk_merkle_root(leaf_data: bytes, index: int,
                     proof: list[bytes]) -> bytes:
    """leaf bytes + tree index + sibling path -> 32-byte signed root."""
    import hashlib
    h = hashlib.sha256(bmtree.LEAF_PREFIX_LONG + leaf_data).digest()
    for p in proof:
        t = h[:MERKLE_NODE_SZ]
        pair = p + t if index & 1 else t + p
        h = hashlib.sha256(bmtree.NODE_PREFIX_LONG + pair).digest()
        index >>= 1
    return h


class ShredParseError(ValueError):
    pass


def parse(buf: bytes) -> Shred:
    """Parse + validate an untrusted shred (fd_shred_parse semantics)."""
    if len(buf) < CODE_HEADER_SZ:
        raise ShredParseError("too short")
    variant = buf[0x40]
    t = shred_type(variant)
    if t not in (
        TYPE_LEGACY_DATA, TYPE_LEGACY_CODE, TYPE_MERKLE_DATA, TYPE_MERKLE_CODE,
        TYPE_MERKLE_DATA_CHAINED, TYPE_MERKLE_CODE_CHAINED,
        TYPE_MERKLE_DATA_CHAINED_RESIGNED, TYPE_MERKLE_CODE_CHAINED_RESIGNED,
    ):
        raise ShredParseError(f"bad type {t:#x}")
    if t == TYPE_LEGACY_DATA and (variant & 0x0F) != 0x05:
        raise ShredParseError("bad legacy data variant")
    if t == TYPE_LEGACY_CODE and (variant & 0x0F) != 0x0A:
        raise ShredParseError("bad legacy code variant")

    s = Shred(
        raw=bytes(buf),
        signature=bytes(buf[:64]),
        variant=variant,
        slot=int.from_bytes(buf[0x41:0x49], "little"),
        idx=int.from_bytes(buf[0x49:0x4D], "little"),
        version=int.from_bytes(buf[0x4D:0x4F], "little"),
        fec_set_idx=int.from_bytes(buf[0x4F:0x53], "little"),
    )
    if s.idx >= MAX_PER_SLOT:
        raise ShredParseError("shred idx out of range")
    if s.is_data:
        s.parent_off = int.from_bytes(buf[0x53:0x55], "little")
        s.flags = buf[0x55]
        s.size = int.from_bytes(buf[0x56:0x58], "little")
        if not (DATA_HEADER_SZ <= s.size <= len(buf)):
            raise ShredParseError("bad data size field")
        if s.parent_off == 0 and s.slot != 0:
            raise ShredParseError("zero parent_off")
    else:
        s.data_cnt = int.from_bytes(buf[0x53:0x55], "little")
        s.code_cnt = int.from_bytes(buf[0x55:0x57], "little")
        s.code_idx = int.from_bytes(buf[0x57:0x59], "little")
        if s.data_cnt > MAX_PER_SLOT or s.code_cnt > MAX_PER_SLOT:
            raise ShredParseError("fec counts out of range")
        if s.code_idx >= max(s.code_cnt, 1):
            raise ShredParseError("code idx out of range")
    hdr_sz = DATA_HEADER_SZ if s.is_data else CODE_HEADER_SZ
    if len(buf) < hdr_sz + s._trailer_sz():
        raise ShredParseError("truncated merkle trailer")
    if s.is_data and s.type not in (TYPE_LEGACY_DATA,) \
            and s.idx < s.fec_set_idx:
        # merkle tree index is idx - fec_set_idx; a crafted inversion
        # would otherwise wrap the leaf position
        raise ShredParseError("data idx below fec_set_idx")
    return s


# ---------------------------------------------------------------------------
# shredder: entry batch -> signed FEC set(s)

def _proof_len_for(total_leaves: int) -> int:
    """Non-root proof node count = tree depth for `total_leaves` leaves."""
    n, d = 1, 0
    while n < total_leaves:
        n *= 2
        d += 1
    return d


@dataclass
class FecSet:
    data_shreds: list[bytes]
    code_shreds: list[bytes]
    merkle_root: bytes


def _le(v: int, n: int) -> bytes:
    return int(v).to_bytes(n, "little")


def make_fec_set(
    entry_batch: bytes,
    slot: int,
    parent_off: int,
    version: int,
    fec_set_idx: int,
    sign_fn,
    data_cnt: int = 32,
    code_cnt: int = 32,
    ref_tick: int = 0,
    slot_complete: bool = False,
) -> FecSet:
    """Shred one entry batch into a single signed merkle FEC set
    (fd_shredder semantics, fixed 32:32 geometry by default).

    fec_set_idx is the first data shred's slot-level index (the merkle
    convention: set id == first member's idx).  sign_fn(root32) -> 64-byte
    leader signature over the merkle root — the keyguard hook
    (src/disco/keyguard): the private key never enters this module.

    Wire geometry (round-4 parity with fd_shred.h / fd_fec_resolver.c:339
    — validated byte-for-byte against the real capture in
    tests/golden/demo-shreds.pcap): every data shred is 1203 bytes and
    every parity shred 1228; the reedsol-protected span is
    1139 - 20*proof_len bytes from offset 0x40, parity blocks land after
    the 0x59-byte code header, and the trailer stores ONLY the proof.
    """
    proof_len = _proof_len_for(data_cnt + code_cnt)
    protected = 1139 - MERKLE_NODE_SZ * proof_len     # [0x40, ...) span
    payload_cap = protected - (DATA_HEADER_SZ - SIGNATURE_SZ)
    if len(entry_batch) > payload_cap * data_cnt:
        raise ValueError("entry batch exceeds FEC set capacity")

    chunk = (len(entry_batch) + data_cnt - 1) // data_cnt if entry_batch else 0

    # --- data shreds (unsigned, no merkle trailer yet)
    data_bodies = []
    for i in range(data_cnt):
        piece = entry_batch[i * chunk : (i + 1) * chunk]
        flags = ref_tick & REF_TICK_MASK
        if i == data_cnt - 1:
            flags |= FLAG_DATA_COMPLETE
            if slot_complete:
                flags |= FLAG_SLOT_COMPLETE
        hdr = (
            b"\0" * SIGNATURE_SZ
            + bytes([TYPE_MERKLE_DATA | proof_len])
            + _le(slot, 8)
            + _le(fec_set_idx + i, 4)
            + _le(version, 2)
            + _le(fec_set_idx, 4)
            + _le(parent_off, 2)
            + bytes([flags])
            + _le(DATA_HEADER_SZ + len(piece), 2)
        )
        assert len(hdr) == DATA_HEADER_SZ
        body = hdr + piece + b"\0" * (payload_cap - len(piece))
        data_bodies.append(bytearray(body))

    # --- parity over the data shreds' post-signature bytes
    # (the erasure code covers byte range [0x40, end-of-payload))
    protected = np.stack(
        [
            np.frombuffer(bytes(b[SIGNATURE_SZ:]), dtype=np.uint8)
            for b in data_bodies
        ]
    )
    parity = reedsol.encode(protected, code_cnt)

    code_bodies = []
    for j in range(code_cnt):
        hdr = (
            b"\0" * SIGNATURE_SZ
            + bytes([TYPE_MERKLE_CODE | proof_len])
            + _le(slot, 8)
            + _le(fec_set_idx + j, 4)  # code shreds get their own idx space
            + _le(version, 2)
            + _le(fec_set_idx, 4)
            + _le(data_cnt, 2)
            + _le(code_cnt, 2)
            + _le(j, 2)
        )
        assert len(hdr) == CODE_HEADER_SZ
        code_bodies.append(bytearray(hdr + parity[j].tobytes()))

    # --- merkle tree over all leaves (data then code): the 32-byte SIGNED
    # root comes from untruncated sha256 at the top; interior levels pass
    # 20-byte truncated children (fd_bmtree hash_sz contract)
    leaves = [bytes(b[SIGNATURE_SZ:]) for b in data_bodies] + [
        bytes(b[SIGNATURE_SZ:]) for b in code_bodies
    ]
    levels = bmtree.np_tree(
        leaves,
        node_sz=MERKLE_NODE_SZ,
        leaf_prefix=bmtree.LEAF_PREFIX_LONG,
        node_prefix=bmtree.NODE_PREFIX_LONG,
    )
    proof0 = bmtree.np_proof(levels, 0)
    root = walk_merkle_root(leaves[0], 0, proof0)
    sig = sign_fn(root)
    if len(sig) != SIGNATURE_SZ:
        raise ValueError("sign_fn must return 64 bytes")

    out_data, out_code = [], []
    for i, b in enumerate(data_bodies + code_bodies):
        proof = bmtree.np_proof(levels, i)
        full = bytes(sig) + bytes(b[SIGNATURE_SZ:]) + b"".join(proof)
        (out_data if i < data_cnt else out_code).append(full)
    return FecSet(out_data, out_code, root)


# ---------------------------------------------------------------------------
# FEC resolver: incoming side

class FecResolver:
    """Collect shreds of one FEC set; recover erasures once >= data_cnt
    arrive; verify merkle inclusion of every shred against the signed root
    (fd_fec_resolver.c contract, minus the signature check which the
    caller does once per set against the leader key)."""

    def __init__(self, root_check=None):
        """root_check(root32, signature) -> bool: the leader-signature
        gate run on the FIRST member's computed root (fd_fec_resolver.c
        verifies the sig before admitting a set — without it a lone
        tampered shred is self-consistent, since the wire stores only the
        proof and ANY leaf walks to some root).  None = the caller
        signature-checks shreds before add() (the tile layer's shape)."""
        self.data: dict[int, Shred] = {}
        self.code: dict[int, Shred] = {}
        self.data_cnt: Optional[int] = None
        self.code_cnt: Optional[int] = None
        self.root: Optional[bytes] = None
        self.root_check = root_check
        # data_cnt pinned by a DATA_COMPLETE/SLOT_COMPLETE-flagged data
        # shred (last data idx in the set + 1) — lets a set complete from
        # data shreds alone, e.g. over repair, which serves data only
        self._implied_data_cnt: Optional[int] = None

    def add(self, s: Shred) -> bool:
        """Returns True if the shred was accepted (consistent + verified).

        Acceptance = the shred's COMPUTED root (leaf + proof walk,
        fd_bmtree_commitp_insert_with_proof's contract) matches every
        other member's — no root rides the wire, so agreement IS the
        inclusion proof."""
        if not s.merkle_proof_len and s.type in (TYPE_LEGACY_DATA,
                                                 TYPE_LEGACY_CODE):
            return False
        # a code shred's tree index comes from its OWN header counts; the
        # resolver's counts are committed only AFTER acceptance (a spoofed
        # first shred must not poison data_cnt and wreck every honest
        # member's computed root — one-packet set DoS)
        root = s.merkle_root()
        if root is None:
            return False
        if self.root is None:
            if self.root_check is not None and not self.root_check(
                    root, s.signature):
                return False
            self.root = root
        elif root != self.root:
            return False
        if not s.is_data and self.data_cnt is None:
            self.data_cnt = s.data_cnt
            self.code_cnt = s.code_cnt
        if s.is_data:
            self.data[self._leaf_index(s)] = s
            if s.flags & (FLAG_DATA_COMPLETE | FLAG_SLOT_COMPLETE):
                self._implied_data_cnt = (s.idx - s.fec_set_idx) + 1
        else:
            self.code[s.code_idx] = s
        return True

    def _leaf_index(self, s: Shred) -> int:
        if s.is_data:
            return s.idx - s.fec_set_idx  # data idx within set
        return (self.data_cnt or s.data_cnt) + s.code_idx

    @property
    def resolved_data_cnt(self) -> Optional[int]:
        """data_cnt of the set: code-shred header if seen (authoritative),
        else the DATA_COMPLETE-flag-implied count."""
        return self.data_cnt if self.data_cnt is not None else self._implied_data_cnt

    def ready(self) -> bool:
        if self.data_cnt is not None:
            return len(self.data) + len(self.code) >= self.data_cnt
        # no code shred seen: only a flag-pinned count with EVERY data
        # shred present can complete (no parity -> no erasure recovery).
        # Index CONTIGUITY is required, not just count: a crafted set can
        # flag idx 3 while holding idx 5 — count alone would pass ready()
        # and then recover() would hit a hole
        k = self._implied_data_cnt
        return (k is not None
                and all(i in self.data for i in range(k)))

    def recover_args(self):
        """The (shreds, k, sz) triple for reedsol.recover/recover_batch,
        or None when the set completes from data shreds alone (repair
        path: nothing to recover).  Raises if not ready().  This is the
        batching seam (round 13): a multi-set caller gathers one triple
        per ready resolver and recovers them all in ONE device dispatch
        via reedsol.recover_batch, then feeds each outcome back through
        data_regions()."""
        if not self.ready():
            raise ValueError("not enough shreds")
        k = self.resolved_data_cnt
        if not self.code:
            return None
        c = self.code_cnt
        some_code = next(iter(self.code.values()))
        sz = len(some_code.raw) - CODE_HEADER_SZ - some_code._trailer_sz()
        shreds: list[Optional[np.ndarray]] = [None] * (k + c)
        for i, s in self.data.items():
            body = s.raw[SIGNATURE_SZ : SIGNATURE_SZ + sz]
            shreds[i] = np.frombuffer(body, dtype=np.uint8)
        for j, s in self.code.items():
            body = s.raw[CODE_HEADER_SZ : CODE_HEADER_SZ + sz]
            shreds[k + j] = np.frombuffer(body, dtype=np.uint8)
        return shreds, k, sz

    def data_regions(self, full=None) -> list[bytes]:
        """Data shreds' protected regions from a recover outcome.  `full`
        is the recovered codeword list (reedsol.recover/recover_batch
        output for this set's recover_args triple); None means the
        all-data completion path (regions read straight off the stored
        shreds)."""
        k = self.resolved_data_cnt
        if full is not None:
            return [np.asarray(f).tobytes() for f in full[:k]]
        out = []
        for i in range(k):
            s = self.data[i]
            sz = len(s.raw) - SIGNATURE_SZ - s._trailer_sz()
            out.append(s.raw[SIGNATURE_SZ : SIGNATURE_SZ + sz])
        return out

    def recover(self) -> list[bytes]:
        """Returns the data shreds' protected regions (post-signature bytes,
        padding included) for all data shreds, recovering erasures."""
        args = self.recover_args()
        if args is None:
            return self.data_regions()
        return self.data_regions(reedsol.recover(*args))

    @staticmethod
    def assemble_payload(regions: list[bytes]) -> bytes:
        """Reassembled entry-batch bytes from data-shred protected
        regions (each = variant..headers..payload..pad)."""
        out = b""
        for region in regions:
            size = int.from_bytes(region[0x56 - 0x40 : 0x58 - 0x40], "little")
            out += region[DATA_HEADER_SZ - SIGNATURE_SZ : size - SIGNATURE_SZ]
        return out

    def payloads(self) -> bytes:
        """Reassembled entry-batch bytes from recovered data shreds."""
        return self.assemble_payload(self.recover())
