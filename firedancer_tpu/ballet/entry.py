"""PoH entry wire format (ref: the entry batches fd_poh/fd_shred exchange —
src/disco/poh/fd_poh_tile.c microblock mixin and the entry batch payload
fd_shredder consumes, src/disco/shred/fd_shredder.c).

A fresh chain defines its own compact LE layout (Agave bincode layout
compatibility is a non-goal this round; confined to this module):

    u64 num_hashes | hash[32] | u64 txn_cnt | txn_cnt * (u32 len | bytes)

An entry with txn_cnt==0 is a tick.  The PoH chain rule is the reference's
(fd_poh_append / mixin): hash advances num_hashes-1 times, then the final
step absorbs the mixin (the merkle root of the entry's txn signatures).
"""

import hashlib
import struct
from dataclasses import dataclass, field

import numpy as np

from . import bmtree


@dataclass
class Entry:
    num_hashes: int
    hash: bytes                    # chain state after this entry
    txns: list[bytes] = field(default_factory=list)

    @property
    def is_tick(self) -> bool:
        return not self.txns

    def serialize(self) -> bytes:
        out = bytearray(struct.pack("<Q", self.num_hashes))
        out += self.hash
        out += struct.pack("<Q", len(self.txns))
        for t in self.txns:
            out += struct.pack("<I", len(t)) + t
        return bytes(out)

    @classmethod
    def deserialize(cls, buf: bytes, off: int = 0) -> tuple["Entry", int]:
        (num_hashes,) = struct.unpack_from("<Q", buf, off)
        off += 8
        h = bytes(buf[off : off + 32])
        off += 32
        (n,) = struct.unpack_from("<Q", buf, off)
        off += 8
        txns = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", buf, off)
            off += 4
            txns.append(bytes(buf[off : off + ln]))
            off += ln
        return cls(num_hashes, h, txns), off


def serialize_batch(entries: list[Entry]) -> bytes:
    out = bytearray(struct.pack("<Q", len(entries)))
    for e in entries:
        out += e.serialize()
    return bytes(out)


def deserialize_batch(buf: bytes) -> list[Entry]:
    """Parse one or more concatenated serialize_batch blobs until the
    buffer is exhausted (a slot's data is one blob per FEC-set flush, so
    multi-FEC slots concatenate several counted batches).  Up to 7 bytes
    of trailing padding are tolerated; a truncated batch raises
    ValueError (never a bare struct.error — callers treat ValueError as
    a corrupt block, not a crash)."""
    off = 0
    out = []
    try:
        while off + 8 <= len(buf):
            (n,) = struct.unpack_from("<Q", buf, off)
            off += 8
            for _ in range(n):
                e, off = Entry.deserialize(buf, off)
                out.append(e)
    except (struct.error, IndexError) as e:
        raise ValueError(f"corrupt entry batch at {off}: {e}") from None
    return out


def serialize_txn_batch(txns: list[bytes]) -> bytes:
    """Standalone txn batch wire (the pack→PoH microblock frag payload):
    u32 cnt | cnt * (u32 len | bytes).  Same per-txn framing as
    Entry.serialize so the two never disagree on txn encoding."""
    out = bytearray(struct.pack("<I", len(txns)))
    for t in txns:
        out += struct.pack("<I", len(t)) + t
    return bytes(out)


def deserialize_txn_batch(buf: bytes, off: int = 0) -> tuple[list[bytes], int]:
    """Inverse of serialize_txn_batch.  Raises ValueError on truncation
    (callers treat that as a corrupt frag, not a crash)."""
    try:
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        txns = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", buf, off)
            off += 4
            if off + ln > len(buf):
                raise ValueError(f"txn batch overruns buffer at {off}")
            txns.append(bytes(buf[off : off + ln]))
            off += ln
    except struct.error as e:
        raise ValueError(f"corrupt txn batch at {off}: {e}") from None
    return txns, off


def txn_mixin(txns: list[bytes]) -> bytes:
    """The mixin absorbed into the PoH chain for a txn entry: the 32-byte
    merkle root of the txns' first signatures (Solana's entry hash rule)."""
    sigs = [t[1 : 1 + 64] for t in txns]
    return bmtree.np_tree(sigs)[-1][0]


def next_hash(prev: bytes, num_hashes: int, mixin: bytes | None) -> bytes:
    """Advance the PoH chain: num_hashes-1 plain appends, then one append
    absorbing `mixin` (or num_hashes plain appends for a tick)."""
    h = prev
    plain = num_hashes - (1 if mixin is not None else 0)
    for _ in range(plain):
        h = hashlib.sha256(h).digest()
    if mixin is not None:
        h = hashlib.sha256(h + mixin).digest()
    return h


# ---------------------------------------------------------------------------
# Device-batched mixins (round 14): the leader lane closes every tick with
# one mixin per microblock — B independent little merkle trees over the
# microblocks' txn signatures.  Each tree level for ALL trees is one
# batched sha256 call (leaf = sha256(0x00||sig64), interior =
# sha256(0x01||l||r), odd node duplicated — exactly np_tree's rule), with
# per-tree widths masked so ragged microblocks share one (B, W) graph.

_MIXIN_JITS: dict = {}


def _mixin_roots(sigs, widths):
    """sigs: uint8 (B, W, 64) first-signatures (W = pow2 pad, rows past
    widths[i] ignored); widths: int32 (B,) >= 1.  Returns uint8 (B, 32)
    merkle roots, bit-identical to txn_mixin per tree."""
    import jax.numpy as jnp

    from firedancer_tpu.ops.sha256 import sha256

    B, W, _ = sigs.shape
    pre = jnp.full((B, W, 1), bmtree.LEAF_PREFIX, dtype=jnp.uint8)
    buf = jnp.concatenate([pre, sigs.astype(jnp.uint8)], axis=2)
    lens = jnp.full((B * W,), 65, dtype=jnp.int32)
    nodes = sha256(buf.reshape(B * W, 65), lens).reshape(B, W, 32)
    w = widths.astype(jnp.int32)
    while W > 1:
        half = W // 2
        left = nodes[:, 0::2]
        right = nodes[:, 1::2]
        # odd promotion: a pair whose right index falls past the tree's
        # live width hashes the left node with itself
        use_self = (jnp.arange(half, dtype=jnp.int32) * 2 + 1)[None, :] \
            >= w[:, None]
        right = jnp.where(use_self[:, :, None], left, right)
        ipre = jnp.full((B, half, 1), bmtree.INTERIOR_PREFIX, dtype=jnp.uint8)
        ibuf = jnp.concatenate([ipre, left, right], axis=2)
        ilens = jnp.full((B * half,), 65, dtype=jnp.int32)
        hashed = sha256(ibuf.reshape(B * half, 65), ilens) \
            .reshape(B, half, 32)
        done = (w <= 1)  # tree already reduced: root rides in column 0
        nodes = jnp.where(done[:, None, None], nodes[:, :half], hashed)
        w = jnp.where(done, w, (w + 1) // 2)
        W = half
    return nodes[:, 0]


def _mixin_jit(B: int, W: int):
    key = (B, W)
    fn = _MIXIN_JITS.get(key)
    if fn is None:
        import jax

        fn = jax.jit(_mixin_roots)
        _MIXIN_JITS[key] = fn
    return fn


def _pow2_at_least(n: int) -> int:
    w = 1
    while w < n:
        w *= 2
    return w


def txn_mixins_device(txn_batches: list[list[bytes]], pad_batch: int = 0,
                      pad_width: int = 0):
    """Mixin hashes for a batch of microblocks in ONE device round-trip.

    txn_batches: list of non-empty txn lists (raw wire txns; the first
    signature t[1:65] is the merkle leaf, as txn_mixin).  pad_batch /
    pad_width pad the batch and leaf axes up so steady-state calls reuse
    one compiled shape regardless of how full each microblock is.
    Returns np.ndarray uint8 (len(txn_batches), 32)."""
    import jax.numpy as jnp

    B = len(txn_batches)
    if B == 0:
        return np.zeros((0, 32), dtype=np.uint8)
    widths = np.array([len(ts) for ts in txn_batches], dtype=np.int32)
    if (widths < 1).any():
        raise ValueError("empty microblock has no mixin (tick instead)")
    Bp = max(B, int(pad_batch))
    W = _pow2_at_least(max(int(widths.max()), int(pad_width), 1))
    sigs = np.zeros((Bp, W, 64), dtype=np.uint8)
    for i, ts in enumerate(txn_batches):
        for j, t in enumerate(ts):
            sigs[i, j] = np.frombuffer(bytes(t[1:65]), dtype=np.uint8)
    wp = np.ones((Bp,), dtype=np.int32)
    wp[:B] = widths
    out = _mixin_jit(Bp, W)(jnp.asarray(sigs), jnp.asarray(wp))
    return np.asarray(out)[:B]


def warm_txn_mixins(batch: int, max_width: int) -> int:
    """AOT-compile the mixin tree shapes reachable at (batch, width<=
    max_width) so the leader hot path never compiles; returns shape count."""
    n = 0
    w = 1
    while True:
        txn_mixins_device([[b"\x00" * 65] * w], pad_batch=batch)
        n += 1
        if w >= max_width:
            break
        w *= 2
    return n


def verify_chain(start: bytes, entries: list[Entry]) -> bool:
    """Host-side sequential chain check (the JAX-batched verifier over many
    entries is ballet.poh.verify_entries)."""
    h = start
    for e in entries:
        mix = None if e.is_tick else txn_mixin(e.txns)
        h = next_hash(h, e.num_hashes, mix)
        if h != e.hash:
            return False
    return True
