"""PoH entry wire format (ref: the entry batches fd_poh/fd_shred exchange —
src/disco/poh/fd_poh_tile.c microblock mixin and the entry batch payload
fd_shredder consumes, src/disco/shred/fd_shredder.c).

A fresh chain defines its own compact LE layout (Agave bincode layout
compatibility is a non-goal this round; confined to this module):

    u64 num_hashes | hash[32] | u64 txn_cnt | txn_cnt * (u32 len | bytes)

An entry with txn_cnt==0 is a tick.  The PoH chain rule is the reference's
(fd_poh_append / mixin): hash advances num_hashes-1 times, then the final
step absorbs the mixin (the merkle root of the entry's txn signatures).
"""

import hashlib
import struct
from dataclasses import dataclass, field

from . import bmtree


@dataclass
class Entry:
    num_hashes: int
    hash: bytes                    # chain state after this entry
    txns: list[bytes] = field(default_factory=list)

    @property
    def is_tick(self) -> bool:
        return not self.txns

    def serialize(self) -> bytes:
        out = bytearray(struct.pack("<Q", self.num_hashes))
        out += self.hash
        out += struct.pack("<Q", len(self.txns))
        for t in self.txns:
            out += struct.pack("<I", len(t)) + t
        return bytes(out)

    @classmethod
    def deserialize(cls, buf: bytes, off: int = 0) -> tuple["Entry", int]:
        (num_hashes,) = struct.unpack_from("<Q", buf, off)
        off += 8
        h = bytes(buf[off : off + 32])
        off += 32
        (n,) = struct.unpack_from("<Q", buf, off)
        off += 8
        txns = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", buf, off)
            off += 4
            txns.append(bytes(buf[off : off + ln]))
            off += ln
        return cls(num_hashes, h, txns), off


def serialize_batch(entries: list[Entry]) -> bytes:
    out = bytearray(struct.pack("<Q", len(entries)))
    for e in entries:
        out += e.serialize()
    return bytes(out)


def deserialize_batch(buf: bytes) -> list[Entry]:
    """Parse one or more concatenated serialize_batch blobs until the
    buffer is exhausted (a slot's data is one blob per FEC-set flush, so
    multi-FEC slots concatenate several counted batches).  Up to 7 bytes
    of trailing padding are tolerated; a truncated batch raises
    ValueError (never a bare struct.error — callers treat ValueError as
    a corrupt block, not a crash)."""
    off = 0
    out = []
    try:
        while off + 8 <= len(buf):
            (n,) = struct.unpack_from("<Q", buf, off)
            off += 8
            for _ in range(n):
                e, off = Entry.deserialize(buf, off)
                out.append(e)
    except (struct.error, IndexError) as e:
        raise ValueError(f"corrupt entry batch at {off}: {e}") from None
    return out


def txn_mixin(txns: list[bytes]) -> bytes:
    """The mixin absorbed into the PoH chain for a txn entry: the 32-byte
    merkle root of the txns' first signatures (Solana's entry hash rule)."""
    sigs = [t[1 : 1 + 64] for t in txns]
    return bmtree.np_tree(sigs)[-1][0]


def next_hash(prev: bytes, num_hashes: int, mixin: bytes | None) -> bytes:
    """Advance the PoH chain: num_hashes-1 plain appends, then one append
    absorbing `mixin` (or num_hashes plain appends for a tick)."""
    h = prev
    plain = num_hashes - (1 if mixin is not None else 0)
    for _ in range(plain):
        h = hashlib.sha256(h).digest()
    if mixin is not None:
        h = hashlib.sha256(h + mixin).digest()
    return h


def verify_chain(start: bytes, entries: list[Entry]) -> bool:
    """Host-side sequential chain check (the JAX-batched verifier over many
    entries is ballet.poh.verify_entries)."""
    h = start
    for e in entries:
        mix = None if e.is_tick else txn_mixin(e.txns)
        h = next_hash(h, e.num_hashes, mix)
        if h != e.hash:
            return False
    return True
