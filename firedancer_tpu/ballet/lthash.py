"""Lattice homomorphic hash (LtHash) — the accounts delta hash.

Reference role: src/ballet/lthash/ — Solana's incremental accounts hash:
each account hashes to a 2048-byte vector of 1024 u16 lanes (BLAKE3 XOF);
the bank maintains one running vector, adding vectors for new account
states and subtracting old ones (wrapping u16 adds — homomorphic, so
updates are order-independent and parallelizable).  The 32-byte identity
published on-chain is BLAKE3 of the running vector.

TPU mapping: add/sub over (batch, 1024) u16 is pure VPU elementwise work;
`mix_batch` folds thousands of per-account vectors in one reduction —
this is where a slot's account-delta hashing becomes a single device op.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.blake3 import blake3

LTHASH_LEN = 2048  # bytes
LANES = LTHASH_LEN // 2


def hash_account(data: bytes) -> np.ndarray:
    """LtHash vector of one input: BLAKE3 XOF to 2048 bytes as u16 lanes."""
    return np.frombuffer(blake3(data, out_len=LTHASH_LEN), dtype="<u2").copy()


def zero() -> np.ndarray:
    return np.zeros(LANES, dtype=np.uint16)


def add(state: np.ndarray, vec: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return state + vec


def sub(state: np.ndarray, vec: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return state - vec


def fini(state: np.ndarray) -> bytes:
    """32-byte identity of the running vector (published bank hash input)."""
    return blake3(state.astype("<u2").tobytes())


@jax.jit
def mix_batch(state: jax.Array, adds: jax.Array, subs: jax.Array) -> jax.Array:
    """Device fold: state (1024,) u16 + sum(adds) - sum(subs), wrapping.

    adds/subs: (N, 1024) uint16 — per-account LtHash vectors for the new
    and old states touched this slot.  One reduction, batch-shardable.
    """
    s = state.astype(jnp.uint16)
    s = s + jnp.sum(adds.astype(jnp.uint16), axis=0, dtype=jnp.uint16)
    s = s - jnp.sum(subs.astype(jnp.uint16), axis=0, dtype=jnp.uint16)
    return s
