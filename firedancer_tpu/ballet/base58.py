"""Base58 codec (Bitcoin alphabet), host-side.

Reference role: src/ballet/base58/ (fd_base58.h) — fixed-size fast paths for
32-byte (pubkeys/hashes) and 64-byte (signatures) values plus the general
codec.  The reference unrolls AVX big-number division; here the fixed-size
paths go through one python-int limb conversion (fast enough for the control
plane — the data plane never round-trips base58; it is a display/RPC format).
"""

_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(_ALPHABET)}

# maximum encoded lengths for the fixed-size fast paths (fd_base58.h:32,61)
ENCODED_32_MAX = 44
ENCODED_64_MAX = 88


def encode(data: bytes) -> str:
    """General base58 encode (leading zero bytes -> leading '1's)."""
    n_zeros = len(data) - len(data.lstrip(b"\0"))
    num = int.from_bytes(data, "big")
    out = []
    while num:
        num, rem = divmod(num, 58)
        out.append(_ALPHABET[rem])
    return "1" * n_zeros + "".join(reversed(out))


def decode(s: str, want_len: int | None = None) -> bytes:
    """General base58 decode; raises ValueError on bad chars or wrong len."""
    num = 0
    for c in s:
        try:
            num = num * 58 + _INDEX[c]
        except KeyError:
            raise ValueError(f"invalid base58 character {c!r}") from None
    n_zeros = len(s) - len(s.lstrip("1"))
    body = num.to_bytes((num.bit_length() + 7) // 8, "big") if num else b""
    out = b"\0" * n_zeros + body
    if want_len is not None and len(out) != want_len:
        raise ValueError(f"decoded length {len(out)} != {want_len}")
    return out


def encode_32(data: bytes) -> str:
    """Encode exactly 32 bytes (pubkey/hash; fd_base58_encode_32)."""
    if len(data) != 32:
        raise ValueError("encode_32 requires 32 bytes")
    return encode(data)


def decode_32(s: str) -> bytes:
    return decode(s, want_len=32)


def encode_64(data: bytes) -> str:
    """Encode exactly 64 bytes (signature; fd_base58_encode_64)."""
    if len(data) != 64:
        raise ValueError("encode_64 requires 64 bytes")
    return encode(data)


def decode_64(s: str) -> bytes:
    return decode(s, want_len=64)
