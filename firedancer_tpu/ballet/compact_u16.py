"""compact-u16: Solana's variable-length u16 wire encoding.

Semantics of the reference decoder/encoder (src/ballet/txn/fd_compact_u16.h):
1-3 bytes, 7 value bits per continuation byte, minimal-length encoding
required (a trailing zero continuation byte or a 3rd byte > 3 is illegal).
"""


def decode(buf: bytes, offset: int = 0) -> tuple[int, int]:
    """Decode one compact-u16 at `offset`.

    Returns (value, bytes_consumed).  Raises ValueError on truncation or a
    non-minimal/overflowing encoding (the reference's fd_cu16_dec_sz
    returning 0)."""
    n = len(buf)
    if offset >= n:
        raise ValueError("compact_u16: truncated")
    b0 = buf[offset]
    if b0 < 0x80:
        return b0, 1
    if offset + 1 >= n:
        raise ValueError("compact_u16: truncated")
    b1 = buf[offset + 1]
    if b1 < 0x80:
        if b1 == 0:
            raise ValueError("compact_u16: non-minimal encoding")
        return (b0 & 0x7F) | (b1 << 7), 2
    if offset + 2 >= n:
        raise ValueError("compact_u16: truncated")
    b2 = buf[offset + 2]
    if b2 == 0 or b2 > 3:
        raise ValueError("compact_u16: non-minimal or overflowing encoding")
    return (b0 & 0x7F) | ((b1 & 0x7F) << 7) | (b2 << 14), 3


def encode(val: int) -> bytes:
    """Minimal-length encoding of val in [0, 0xFFFF]."""
    if not 0 <= val <= 0xFFFF:
        raise ValueError(f"compact_u16: {val} out of range")
    if val < 0x80:
        return bytes([val])
    if val < 0x4000:
        return bytes([(val & 0x7F) | 0x80, val >> 7])
    return bytes([(val & 0x7F) | 0x80, ((val >> 7) & 0x7F) | 0x80, val >> 14])
