"""secp256k1 ECDSA verify/recover, host-side (ref: src/ballet/secp256k1/ —
there a wrapper over libsecp256k1 gated by config/extra/with-secp256k1.mk;
no such library ships in this image, so the curve math is implemented
directly.  Usage is the secp256k1 precompile program: a handful of
signatures per txn on the execution control plane, not the TPU hot path.)

Ethereum-compatible surface: recover(msg_hash, r, s, recid) -> uncompressed
pubkey, and eth_address(pub) = keccak256(pub)[12:] — what the Solana
secp256k1 program actually checks (signatures commit to an eth address,
not a raw pubkey).
"""

from __future__ import annotations

from .keccak256 import keccak256

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
_B = 7


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


def _add(p1, p2):
    """Affine point add; None is the identity."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def _mul(k: int, pt):
    acc = None
    while k:
        if k & 1:
            acc = _add(acc, pt)
        pt = _add(pt, pt)
        k >>= 1
    return acc


def _on_curve(pt) -> bool:
    if pt is None:
        return False
    x, y = pt
    return (y * y - x * x * x - _B) % P == 0


def pubkey_serialize(pt) -> bytes:
    """64-byte uncompressed (x ‖ y), no 0x04 prefix (eth convention)."""
    x, y = pt
    return x.to_bytes(32, "big") + y.to_bytes(32, "big")


def pubkey_parse(b: bytes):
    if len(b) == 65 and b[0] == 4:
        b = b[1:]
    if len(b) != 64:
        raise ValueError("secp256k1: bad pubkey length")
    pt = (int.from_bytes(b[:32], "big"), int.from_bytes(b[32:], "big"))
    if not _on_curve(pt):
        raise ValueError("secp256k1: point not on curve")
    return pt


def eth_address(pub) -> bytes:
    """keccak256(uncompressed pubkey)[12:] — 20 bytes."""
    return keccak256(pubkey_serialize(pub))[12:]


def sign(msg_hash: bytes, secret: int) -> tuple[int, int, int]:
    """Deterministic-nonce ECDSA (RFC 6979 simplified via keccak chain);
    returns (r, s, recid) with low-s normalization.  Test/keygen use —
    validators never hold secp keys."""
    z = int.from_bytes(msg_hash, "big") % N
    k = int.from_bytes(
        keccak256(secret.to_bytes(32, "big") + msg_hash), "big") % N
    while True:
        if k == 0:
            k = 1
        R = _mul(k, (_GX, _GY))
        r = R[0] % N
        s = _inv(k, N) * (z + r * secret) % N
        if r and s:
            break
        k = (k + 1) % N
    recid = (R[1] & 1) ^ (1 if R[0] >= N else 0)
    if s > N // 2:
        s = N - s
        recid ^= 1
    return r, s, recid


def verify(msg_hash: bytes, r: int, s: int, pub) -> bool:
    if not (0 < r < N and 0 < s < N) or not _on_curve(pub):
        return False
    z = int.from_bytes(msg_hash, "big") % N
    w = _inv(s, N)
    u1, u2 = z * w % N, r * w % N
    pt = _add(_mul(u1, (_GX, _GY)), _mul(u2, pub))
    return pt is not None and pt[0] % N == r


def recover(msg_hash: bytes, r: int, s: int, recid: int):
    """Recover the public key from a recoverable signature (the eth
    ecrecover / libsecp256k1 recover operation the Solana precompile and
    the secp256k1_recover syscall use).  Returns the point or None."""
    if not (0 < r < N and 0 < s < N) or recid not in (0, 1, 2, 3):
        return None
    x = r + (N if recid >= 2 else 0)
    if x >= P:
        return None
    y_sq = (x * x * x + _B) % P
    y = pow(y_sq, (P + 1) // 4, P)
    if y * y % P != y_sq:
        return None
    if (y & 1) != (recid & 1):
        y = P - y
    z = int.from_bytes(msg_hash, "big") % N
    rinv = _inv(r, N)
    # Q = r^-1 (s*R - z*G)
    q = _add(_mul(s * rinv % N, (x, y)),
             _mul((-z * rinv) % N, (_GX, _GY)))
    if q is None or not _on_curve(q):
        return None
    return q
