"""Proof-of-History hash chain, TPU-first.

Reference role: src/ballet/poh/ (fd_poh_append: iterated sha256;
fd_poh_mixin: hash(state || mixin)).

Generation is inherently serial (that is the point of PoH), so `append` is a
lax.scan over sha256 compressions of the running 32-byte state.  But
*verification* is embarrassingly parallel: a block's entries each declare
(start_hash, num_hashes, mixin) and every segment can be recomputed
independently — so `verify_entries` vmaps whole segments across the batch
axis, which is where a TPU beats a CPU core checking the chain serially.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from firedancer_tpu.ops.sha256 import sha256_fixed32, sha256_fixed64


def append(state, n):
    """Advance PoH chains by n iterated sha256 hashes.

    state: uint8 (batch, 32); n: static int.  Returns uint8 (batch, 32).
    Equivalent of fd_poh_append(state, n) over a batch of chains."""

    def step(st, _):
        return sha256_fixed32(st), None

    out, _ = jax.lax.scan(step, state, None, length=n)
    return out


def mixin(state, mix):
    """PoH mixin: state = sha256(state || mix).  Both uint8 (batch, 32)."""
    return sha256_fixed64(jnp.concatenate([state, mix], axis=1))


def verify_entries(start_hashes, num_hashes, mixins, has_mixin, max_hashes: int):
    """Verify a batch of PoH entry segments in parallel.

    Each entry i claims: starting from start_hashes[i], after num_hashes[i]
    sha256 appends (the last one a mixin of mixins[i] if has_mixin[i]), the
    chain reaches the next entry's start hash.  Returns the computed end
    hash per entry, uint8 (batch, 32); the caller compares against the
    declared next-start (entry_verify below does this for a whole slot).

    num_hashes is data-dependent, so the scan runs max_hashes steps with a
    per-lane active mask (standard fixed-shape TPU pattern; cf. the block
    masks in ops/sha512.sha512)."""
    n = num_hashes.astype(jnp.int32)

    # run num_hashes-1 plain appends...
    nm1 = jnp.maximum(n - 1, 0)

    def step_nm1(st, i):
        plain = sha256_fixed32(st)
        return jnp.where((i < nm1)[:, None], plain, st), None

    idxs = jnp.arange(max_hashes, dtype=jnp.int32)
    st, _ = jax.lax.scan(step_nm1, start_hashes, idxs)
    # ...then the final hash: either plain append or mixin
    final_plain = sha256_fixed32(st)
    final_mix = mixin(st, mixins)
    last = jnp.where(has_mixin[:, None], final_mix, final_plain)
    return jnp.where((n > 0)[:, None], last, start_hashes)


def entry_verify(start_hashes, num_hashes, mixins, has_mixin, end_hashes,
                 max_hashes: int):
    """Full slot check: recompute every segment in parallel and compare with
    the declared end hashes.  Returns bool (batch,)."""
    got = verify_entries(start_hashes, num_hashes, mixins, has_mixin, max_hashes)
    return jnp.all(got == end_hashes, axis=1)


# -- bucketed trip-count ladder (round 14) ----------------------------------
# verify_entries pays max_hashes masked scan steps for EVERY lane: a batch
# of 1-hash microblock entries checked with max_hashes=1024 runs 1024x the
# hash work it needs.  The ladder picks the smallest pre-warmed trip count
# that covers the batch's actual worst num_hashes — the same closest-fit
# shape discipline as the latency lane's _fit_rows (disco/pipeline.py) —
# and warm_verify_ladder compiles every rung BEFORE the hot path so
# steady-state compile_cnt stays flat.

DEFAULT_HASH_LADDER = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def fit_max_hashes(needed: int, max_hashes: int,
                   ladder=DEFAULT_HASH_LADDER) -> int:
    """Closest-fit trip count: the smallest ladder rung covering `needed`
    hashes, capped at max_hashes (rungs past the cap fall back to the
    exact max_hashes shape)."""
    needed = max(1, min(int(needed), int(max_hashes)))
    for s in ladder:
        if s > int(max_hashes):
            break
        if s >= needed:
            return int(s)
    return int(max_hashes)


@functools.lru_cache(maxsize=None)
def _verify_entries_jit(max_hashes: int):
    return jax.jit(functools.partial(verify_entries, max_hashes=max_hashes))


def verify_entries_fit(start_hashes, num_hashes, mixins, has_mixin,
                       max_hashes: int, ladder=DEFAULT_HASH_LADDER):
    """verify_entries at the closest-fit ladder rung >= the batch's actual
    worst num_hashes — short entries stop paying the worst-case trip
    count.  num_hashes must be concrete (host-side dispatch decision)."""
    nh = np.asarray(num_hashes)
    needed = int(nh.max()) if nh.size else 1
    rung = fit_max_hashes(needed, max_hashes, ladder)
    return _verify_entries_jit(rung)(start_hashes, num_hashes, mixins,
                                     has_mixin)


def entry_verify_fit(start_hashes, num_hashes, mixins, has_mixin, end_hashes,
                     max_hashes: int, ladder=DEFAULT_HASH_LADDER):
    """entry_verify riding the bucketed ladder."""
    got = verify_entries_fit(start_hashes, num_hashes, mixins, has_mixin,
                             max_hashes, ladder)
    return jnp.all(got == jnp.asarray(end_hashes), axis=1)


def warm_verify_ladder(batch: int, max_hashes: int,
                       ladder=DEFAULT_HASH_LADDER, heartbeat=None) -> int:
    """AOT warmup: compile every reachable rung at `batch` rows before the
    hot path (zero-input dispatches, results fetched so the compiles
    finish here, not on the first real batch).  `heartbeat` is poked
    between rungs (supervised tiles must not read as dead mid-warm).
    Returns the number of rungs compiled."""
    rungs = sorted({fit_max_hashes(s, max_hashes, ladder)
                    for s in (*ladder, max_hashes) if s <= max_hashes}
                   | {int(max_hashes)})
    z32 = jnp.zeros((batch, 32), jnp.uint8)
    zn = jnp.zeros((batch,), jnp.int32)
    zb = jnp.zeros((batch,), jnp.bool_)
    for r in rungs:
        np.asarray(_verify_entries_jit(r)(z32, zn, z32, zb))
        if heartbeat is not None:
            heartbeat()
    return len(rungs)
