"""Proof-of-History hash chain, TPU-first.

Reference role: src/ballet/poh/ (fd_poh_append: iterated sha256;
fd_poh_mixin: hash(state || mixin)).

Generation is inherently serial (that is the point of PoH), so `append` is a
lax.scan over sha256 compressions of the running 32-byte state.  But
*verification* is embarrassingly parallel: a block's entries each declare
(start_hash, num_hashes, mixin) and every segment can be recomputed
independently — so `verify_entries` vmaps whole segments across the batch
axis, which is where a TPU beats a CPU core checking the chain serially.
"""

import jax
import jax.numpy as jnp

from firedancer_tpu.ops.sha256 import sha256_fixed32, sha256_fixed64


def append(state, n):
    """Advance PoH chains by n iterated sha256 hashes.

    state: uint8 (batch, 32); n: static int.  Returns uint8 (batch, 32).
    Equivalent of fd_poh_append(state, n) over a batch of chains."""

    def step(st, _):
        return sha256_fixed32(st), None

    out, _ = jax.lax.scan(step, state, None, length=n)
    return out


def mixin(state, mix):
    """PoH mixin: state = sha256(state || mix).  Both uint8 (batch, 32)."""
    return sha256_fixed64(jnp.concatenate([state, mix], axis=1))


def verify_entries(start_hashes, num_hashes, mixins, has_mixin, max_hashes: int):
    """Verify a batch of PoH entry segments in parallel.

    Each entry i claims: starting from start_hashes[i], after num_hashes[i]
    sha256 appends (the last one a mixin of mixins[i] if has_mixin[i]), the
    chain reaches the next entry's start hash.  Returns the computed end
    hash per entry, uint8 (batch, 32); the caller compares against the
    declared next-start (entry_verify below does this for a whole slot).

    num_hashes is data-dependent, so the scan runs max_hashes steps with a
    per-lane active mask (standard fixed-shape TPU pattern; cf. the block
    masks in ops/sha512.sha512)."""
    n = num_hashes.astype(jnp.int32)

    # run num_hashes-1 plain appends...
    nm1 = jnp.maximum(n - 1, 0)

    def step_nm1(st, i):
        plain = sha256_fixed32(st)
        return jnp.where((i < nm1)[:, None], plain, st), None

    idxs = jnp.arange(max_hashes, dtype=jnp.int32)
    st, _ = jax.lax.scan(step_nm1, start_hashes, idxs)
    # ...then the final hash: either plain append or mixin
    final_plain = sha256_fixed32(st)
    final_mix = mixin(st, mixins)
    last = jnp.where(has_mixin[:, None], final_mix, final_plain)
    return jnp.where((n > 0)[:, None], last, start_hashes)


def entry_verify(start_hashes, num_hashes, mixins, has_mixin, end_hashes,
                 max_hashes: int):
    """Full slot check: recompute every segment in parallel and compare with
    the declared end hashes.  Returns bool (batch,)."""
    got = verify_entries(start_hashes, num_hashes, mixins, has_mixin, max_hashes)
    return jnp.all(got == end_hashes, axis=1)
